"""Pallas MXU histogram kernel tests (interpret mode on the CPU mesh;
compiled-path parity and speed were measured on the real chip: PERF_NOTES.md).

Parity oracle: the XLA scatter path (ops.histogram), itself verified
against the pure-Python reference oracle in test_ops.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from heatmap_tpu.ops import Window, bin_points_window, bin_rowcol_window
from heatmap_tpu.ops.pallas_kernels import (
    bin_points_window_pallas,
    bin_rowcol_window_pallas,
)

WINDOW = Window(zoom=10, row0=320, col0=256, height=64, width=128)


def _points(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(25.0, 55.0, n),  # some out-of-window
        rng.uniform(-95.0, -60.0, n),
        rng.exponential(1.5, n),
    )


def test_rowcol_parity_with_xla_scatter():
    rng = np.random.default_rng(1)
    row = rng.integers(300, 400, 5000)  # straddles the window rows
    col = rng.integers(230, 400, 5000)
    expected = bin_rowcol_window(
        jnp.asarray(row), jnp.asarray(col), WINDOW, dtype=jnp.float32
    )
    got = bin_rowcol_window_pallas(
        jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32), WINDOW,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    assert float(got.sum()) > 0


def test_weighted_parity():
    rng = np.random.default_rng(2)
    row = rng.integers(320, 384, 2000)
    col = rng.integers(256, 384, 2000)
    w = rng.exponential(1.0, 2000).astype(np.float32)
    expected = bin_rowcol_window(
        jnp.asarray(row), jnp.asarray(col), WINDOW,
        weights=jnp.asarray(w), dtype=jnp.float32,
    )
    got = bin_rowcol_window_pallas(
        jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32), WINDOW,
        weights=jnp.asarray(w), interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-6)


def test_valid_mask_and_padding():
    # 700 points (not a chunk multiple) with every other point masked.
    row = np.full(700, 330, np.int32)
    col = np.full(700, 300, np.int32)
    valid = (np.arange(700) % 2) == 0
    got = bin_rowcol_window_pallas(
        jnp.asarray(row), jnp.asarray(col), WINDOW,
        valid=jnp.asarray(valid), chunk=256, interpret=True,
    )
    assert float(got[10, 44]) == 350.0  # row 330-320, col 300-256
    assert float(got.sum()) == 350.0


def test_empty_input():
    got = bin_rowcol_window_pallas(
        jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32), WINDOW,
        interpret=True,
    )
    assert float(got.sum()) == 0.0


def test_fused_projection_parity():
    lat, lon, w = _points()
    expected = bin_points_window(
        jnp.asarray(lat), jnp.asarray(lon), WINDOW,
        weights=jnp.asarray(w, jnp.float32),
        proj_dtype=jnp.float64, dtype=jnp.float32,
    )
    got = bin_points_window_pallas(
        jnp.asarray(lat), jnp.asarray(lon), WINDOW,
        weights=jnp.asarray(w, jnp.float32),
        proj_dtype=jnp.float64, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=1e-6
    )


def test_bf16_and_f32_onehots_identical():
    rng = np.random.default_rng(4)
    row = rng.integers(300, 420, 4000)
    col = rng.integers(230, 400, 4000)
    args = (jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32), WINDOW)
    bf = bin_rowcol_window_pallas(*args, interpret=True,
                                  onehot_dtype=jnp.bfloat16)
    f32 = bin_rowcol_window_pallas(*args, interpret=True,
                                   onehot_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(bf), np.asarray(f32))


def test_weighted_rejects_bf16_onehots():
    import pytest

    with pytest.raises(ValueError):
        bin_rowcol_window_pallas(
            jnp.zeros(8, jnp.int32), jnp.zeros(8, jnp.int32), WINDOW,
            weights=jnp.ones(8, jnp.float32), interpret=True,
            onehot_dtype=jnp.bfloat16,
        )


def test_backend_selection_in_histogram():
    """bin_rowcol_window backend plumbing: auto falls back to xla off-TPU;
    explicit pallas matches (via interpret-free path only on TPU, so here
    just check auto==xla result on CPU)."""
    from heatmap_tpu.ops.histogram import _pick_backend

    assert _pick_backend("auto", WINDOW) == "xla"  # CPU test env
    assert _pick_backend("pallas", WINDOW) == "pallas"
    assert _pick_backend("xla", WINDOW) == "xla"
    rng = np.random.default_rng(5)
    row = jnp.asarray(rng.integers(300, 400, 1000), jnp.int32)
    col = jnp.asarray(rng.integers(230, 400, 1000), jnp.int32)
    a = bin_rowcol_window(row, col, WINDOW, backend="auto")
    b = bin_rowcol_window(row, col, WINDOW, backend="xla")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
