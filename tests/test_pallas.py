"""Pallas MXU histogram kernel tests (interpret mode on the CPU mesh;
compiled-path parity and speed were measured on the real chip: PERF_NOTES.md).

Parity oracle: the XLA scatter path (ops.histogram), itself verified
against the pure-Python reference oracle in test_ops.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from heatmap_tpu.ops import Window, bin_points_window, bin_rowcol_window
from heatmap_tpu.ops.pallas_kernels import (
    bin_points_window_pallas,
    bin_rowcol_window_pallas,
)

WINDOW = Window(zoom=10, row0=320, col0=256, height=64, width=128)


def _points(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(25.0, 55.0, n),  # some out-of-window
        rng.uniform(-95.0, -60.0, n),
        rng.exponential(1.5, n),
    )


def test_rowcol_parity_with_xla_scatter():
    rng = np.random.default_rng(1)
    row = rng.integers(300, 400, 5000)  # straddles the window rows
    col = rng.integers(230, 400, 5000)
    expected = bin_rowcol_window(
        jnp.asarray(row), jnp.asarray(col), WINDOW, dtype=jnp.float32
    )
    got = bin_rowcol_window_pallas(
        jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32), WINDOW,
        interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    assert float(got.sum()) > 0


def test_weighted_parity():
    rng = np.random.default_rng(2)
    row = rng.integers(320, 384, 2000)
    col = rng.integers(256, 384, 2000)
    w = rng.exponential(1.0, 2000).astype(np.float32)
    expected = bin_rowcol_window(
        jnp.asarray(row), jnp.asarray(col), WINDOW,
        weights=jnp.asarray(w), dtype=jnp.float32,
    )
    got = bin_rowcol_window_pallas(
        jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32), WINDOW,
        weights=jnp.asarray(w), interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-6)


def test_valid_mask_and_padding():
    # 700 points (not a chunk multiple) with every other point masked.
    row = np.full(700, 330, np.int32)
    col = np.full(700, 300, np.int32)
    valid = (np.arange(700) % 2) == 0
    got = bin_rowcol_window_pallas(
        jnp.asarray(row), jnp.asarray(col), WINDOW,
        valid=jnp.asarray(valid), chunk=256, interpret=True,
    )
    assert float(got[10, 44]) == 350.0  # row 330-320, col 300-256
    assert float(got.sum()) == 350.0


def test_empty_input():
    got = bin_rowcol_window_pallas(
        jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32), WINDOW,
        interpret=True,
    )
    assert float(got.sum()) == 0.0


def test_fused_projection_parity():
    lat, lon, w = _points()
    expected = bin_points_window(
        jnp.asarray(lat), jnp.asarray(lon), WINDOW,
        weights=jnp.asarray(w, jnp.float32),
        proj_dtype=jnp.float64, dtype=jnp.float32,
    )
    got = bin_points_window_pallas(
        jnp.asarray(lat), jnp.asarray(lon), WINDOW,
        weights=jnp.asarray(w, jnp.float32),
        proj_dtype=jnp.float64, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=1e-6
    )
