"""Multi-channel partitioned segment reduction (ops.sparse_partitioned),
interpret mode: bit-equal to ops.sparse.aggregate_sorted_keys on every
path — good-chunk matmuls, bounded bad tails, the full-scatter
fallback, and the multi-slab exactness combine."""

import numpy as np
import jax.numpy as jnp
import pytest

from heatmap_tpu.ops.sparse import aggregate_sorted_keys
from heatmap_tpu.ops.sparse_partitioned import (
    aggregate_sorted_keys_partitioned,
)

SENTINEL = np.iinfo(np.int64).max


def _diff(sorted_keys, capacity, **kw):
    sorted_keys = jnp.asarray(np.sort(np.asarray(sorted_keys)), jnp.int64)
    want_u, want_s, want_n = aggregate_sorted_keys(
        sorted_keys, jnp.ones(len(sorted_keys), jnp.int32), capacity,
        sentinel=SENTINEL,
    )
    got_u, got_s, got_n = aggregate_sorted_keys_partitioned(
        sorted_keys, capacity, interpret=True, **kw
    )
    assert int(got_n) == int(want_n)
    n = min(int(want_n), capacity)
    np.testing.assert_array_equal(np.asarray(got_u)[:n],
                                  np.asarray(want_u)[:n])
    np.testing.assert_array_equal(np.asarray(got_s)[:n],
                                  np.asarray(want_s)[:n])
    # Padding slots: sentinel keys, zero counts — both contracts.
    assert (np.asarray(got_u)[n:] == SENTINEL).all()
    assert (np.asarray(got_s)[n:] == 0).all()
    return int(want_n)


@pytest.mark.slow
def test_clustered_runs_good_chunks():
    """Long runs (few segments per chunk) take the matmul path."""
    rng = np.random.default_rng(0)
    keys = np.repeat(rng.choice(1 << 40, 40, replace=False),
                     rng.integers(100, 900, 40))
    assert _diff(keys, capacity=1 << 12) == 40


@pytest.mark.slow
def test_mostly_unique_keys():
    """Run length ~1: every chunk spans many segments, but segments are
    dense so chunks still land inside blocks."""
    rng = np.random.default_rng(1)
    keys = rng.choice(1 << 50, 30_000, replace=False)
    _diff(keys, capacity=30_000)


@pytest.mark.slow
def test_sentinel_padding_and_drop():
    rng = np.random.default_rng(2)
    keys = np.concatenate([
        rng.integers(0, 1 << 30, 5000),
        np.full(3000, SENTINEL),
    ])
    _diff(keys, capacity=8192)


@pytest.mark.slow
def test_multi_slab_combine_exact():
    """slab smaller than the stream: per-slab partials must combine to
    the global counts, including segments straddling slab boundaries
    and per-key fan-in far above one slab's contribution."""
    rng = np.random.default_rng(3)
    keys = np.repeat(rng.choice(1 << 35, 13, replace=False),
                     rng.integers(500, 4000, 13))
    n = _diff(keys, capacity=4096, slab=4096)
    assert n == 13


@pytest.mark.slow
def test_single_hot_key_fanin_beyond_slab():
    """One segment larger than several slabs: counts must stay exact
    (the f32-per-slab / f64-combine design point)."""
    keys = np.full(40_000, 123456789)
    got_u, got_s, got_n = aggregate_sorted_keys_partitioned(
        jnp.asarray(keys, jnp.int64), 64, slab=8192, interpret=True,
    )
    assert int(got_n) == 1
    assert int(got_s[0]) == 40_000
    assert int(got_u[0]) == 123456789


@pytest.mark.slow
def test_58_bit_keys_reconstruct():
    """Cascade-scale composite keys (58 bits) round-trip through the
    three 20-bit channels."""
    rng = np.random.default_rng(4)
    keys = rng.integers(1 << 57, 1 << 58, 3000, dtype=np.int64)
    _diff(keys, capacity=4096)


@pytest.mark.slow
def test_hostile_distribution_falls_back():
    """capacity-spanning sparse segments make most chunks straddle
    blocks -> the lax.cond scatter fallback must match too."""
    rng = np.random.default_rng(5)
    # Unique keys + big capacity: segments land far apart in cell space
    # relative to block_cells, so chunks straddle constantly with a
    # tiny block size.
    keys = rng.choice(1 << 45, 20_000, replace=False)
    _diff(keys, capacity=1 << 18, block_cells=1 << 12)


@pytest.mark.slow
def test_empty_and_tiny():
    _diff(np.empty(0, np.int64), capacity=64)
    _diff(np.asarray([7]), capacity=64)
    _diff(np.asarray([7, 7, 8]), capacity=64)


@pytest.mark.slow
def test_pyramid_partitioned_matches_scatter_pyramid():
    """The full count pyramid: kernel variant == scatter variant at
    every level, including invalid lanes and per-level capacities."""
    from heatmap_tpu.ops.pyramid import (
        pyramid_sparse_morton,
        pyramid_sparse_morton_partitioned,
    )

    rng = np.random.default_rng(7)
    n = 20_000
    # Clustered codes with repeats (collapsing pyramid) + invalid tail.
    codes = np.sort(rng.choice(1 << 26, 700, replace=False))[
        rng.integers(0, 700, n)
    ].astype(np.int64)
    valid = rng.random(n) < 0.9
    levels = 6
    want = pyramid_sparse_morton(
        jnp.asarray(codes), valid=jnp.asarray(valid), levels=levels,
        capacity=n,
    )
    got = pyramid_sparse_morton_partitioned(
        jnp.asarray(codes), valid=jnp.asarray(valid), levels=levels,
        capacity=n, interpret=True,
    )
    for lvl, ((wu, ws, wn), (gu, gs, gn)) in enumerate(zip(want, got)):
        m = int(wn)
        assert int(gn) == m, lvl
        np.testing.assert_array_equal(np.asarray(wu)[:m],
                                      np.asarray(gu)[:m])
        np.testing.assert_array_equal(np.asarray(ws)[:m],
                                      np.asarray(gs)[:m])
        # Padding normalized to the repo-wide int64-max sentinel at
        # EVERY level (the shifted per-level sentinel must not leak).
        assert (np.asarray(gu)[m:] == SENTINEL).all(), lvl


# -- bounded-integer weighted form (VERDICT r4 #7) --------------------------


def _diff_weighted(keys, weights, capacity, weight_bound, **kw):
    order = np.argsort(np.asarray(keys), kind="stable")
    sk = jnp.asarray(np.asarray(keys)[order], jnp.int64)
    sw = jnp.asarray(np.asarray(weights, np.float64)[order])
    want_u, want_s, want_n = aggregate_sorted_keys(
        sk, sw, capacity, sentinel=SENTINEL
    )
    got_u, got_s, got_n = aggregate_sorted_keys_partitioned(
        sk, capacity, interpret=True, sorted_weights=sw,
        weight_bound=weight_bound, **kw,
    )
    assert int(got_n) == int(want_n)
    n = min(int(want_n), capacity)
    np.testing.assert_array_equal(np.asarray(got_u)[:n],
                                  np.asarray(want_u)[:n])
    # Integer weights: exact f64 integers on both paths — bitwise.
    np.testing.assert_array_equal(np.asarray(got_s)[:n],
                                  np.asarray(want_s)[:n])
    assert (np.asarray(got_u)[n:] == SENTINEL).all()
    assert (np.asarray(got_s)[n:] == 0).all()


@pytest.mark.slow
def test_weighted_integer_bit_exact():
    """Clustered integer weights: bit-equal to the f64 scatter path."""
    rng = np.random.default_rng(11)
    keys = np.repeat(rng.choice(1 << 40, 40, replace=False),
                     rng.integers(100, 900, 40))
    w = rng.integers(0, 1000, keys.size)
    _diff_weighted(keys, w, capacity=1 << 12, weight_bound=1000)


@pytest.mark.slow
def test_weighted_zero_sum_segment_survives():
    """A segment whose weights all sum to zero must keep its key (the
    presence channel exists exactly for this)."""
    keys = np.asarray([5, 5, 9, 9, 9, 12], np.int64)
    w = np.asarray([0, 0, 3, 4, 0, 7], np.float64)
    _diff_weighted(keys, w, capacity=64, weight_bound=8)


@pytest.mark.slow
def test_weighted_slab_shrinks_and_fanin_exact():
    """Fan-in far past the shrunk slab: per-slab integer partials
    combine exactly in f64 (weight_bound scales the slab down; force a
    tiny slab to cross boundaries many times)."""
    keys = np.full(40_000, 987654321)
    w = np.full(40_000, 255.0)
    got_u, got_s, got_n = aggregate_sorted_keys_partitioned(
        jnp.asarray(keys, jnp.int64), 64, slab=8192, interpret=True,
        sorted_weights=jnp.asarray(w), weight_bound=255,
    )
    assert int(got_n) == 1
    assert float(got_s[0]) == 40_000 * 255.0
    assert int(got_u[0]) == 987654321


@pytest.mark.slow
@pytest.mark.parametrize("bad_w", [2.5, -1.0, 2000.0])
def test_weighted_contract_violation_is_loud(bad_w):
    """A fractional, negative, or over-bound weight poisons n_unique
    past capacity (the repo-wide overflow signal) — never a silently
    rounded sum."""
    keys = np.sort(np.random.default_rng(12).integers(0, 1000, 5000))
    w = np.ones(5000)
    w[1234] = bad_w
    _, _, got_n = aggregate_sorted_keys_partitioned(
        jnp.asarray(keys, jnp.int64), 2048, interpret=True,
        sorted_weights=jnp.asarray(w), weight_bound=1000,
    )
    assert int(got_n) > 2048


def test_weighted_requires_bound():
    with pytest.raises(ValueError, match="weight_bound"):
        aggregate_sorted_keys_partitioned(
            jnp.zeros(8, jnp.int64), 8, interpret=True,
            sorted_weights=jnp.ones(8),
        )


def test_weighted_bound_too_large_for_exactness_refused():
    """A bound whose exactness slab would fall below one chunk row per
    stream cannot be made exact by ANY slab size — it must raise, not
    silently floor the slab and round sums (review finding, round 5)."""
    with pytest.raises(ValueError, match="too large for the exactness"):
        aggregate_sorted_keys_partitioned(
            jnp.zeros(2048, jnp.int64), 64, interpret=True, chunk=1024,
            sorted_weights=jnp.ones(2048), weight_bound=20_000,
        )
    # The same bound is fine with a smaller chunk (budget restored).
    u, s, n = aggregate_sorted_keys_partitioned(
        jnp.zeros(2048, jnp.int64), 64, interpret=True, chunk=128,
        block_cells=1 << 14,
        sorted_weights=jnp.full(2048, 20_000.0), weight_bound=20_000,
    )
    assert int(n) == 1 and float(s[0]) == 2048 * 20_000.0


@pytest.mark.slow
def test_pyramid_partitioned_weighted_matches_scatter():
    """The weighted pyramid: kernel variant == scatter variant at every
    level (f64 integer sums, invalid lanes, zero weights mixed in)."""
    from heatmap_tpu.ops.pyramid import (
        pyramid_sparse_morton,
        pyramid_sparse_morton_partitioned,
    )

    rng = np.random.default_rng(13)
    n = 20_000
    codes = np.sort(rng.choice(1 << 26, 700, replace=False))[
        rng.integers(0, 700, n)
    ].astype(np.int64)
    valid = rng.random(n) < 0.9
    w = rng.integers(0, 50, n).astype(np.float64)
    levels = 6
    want = pyramid_sparse_morton(
        jnp.asarray(codes), weights=jnp.asarray(w),
        valid=jnp.asarray(valid), levels=levels, capacity=n,
        acc_dtype=jnp.float64,
    )
    got = pyramid_sparse_morton_partitioned(
        jnp.asarray(codes), valid=jnp.asarray(valid), levels=levels,
        capacity=n, interpret=True, weights=jnp.asarray(w),
        weight_bound=50,
    )
    for lvl, ((wu, ws, wn), (gu, gs, gn)) in enumerate(zip(want, got)):
        m = int(wn)
        assert int(gn) == m, lvl
        np.testing.assert_array_equal(np.asarray(wu)[:m],
                                      np.asarray(gu)[:m])
        np.testing.assert_array_equal(np.asarray(ws)[:m],
                                      np.asarray(gs)[:m])
        assert (np.asarray(gu)[m:] == SENTINEL).all(), lvl


def test_matches_cascade_shift_reaggregation():
    """The cascade use case: re-reduce a shifted (still sorted) unique
    stream, sentinels preserved — exactly pyramid_sparse_morton's
    per-level step."""
    rng = np.random.default_rng(6)
    base = np.sort(rng.choice(1 << 30, 10_000, replace=False))
    u0, s0, n0 = aggregate_sorted_keys(
        jnp.asarray(base, jnp.int64), jnp.ones(len(base), jnp.int32),
        len(base), sentinel=SENTINEL,
    )
    parents = jnp.where(u0 == SENTINEL, SENTINEL, u0 >> 2)
    want = aggregate_sorted_keys(parents, s0, len(base), sentinel=SENTINEL)
    got = aggregate_sorted_keys_partitioned(parents, len(base),
                                            interpret=True)
    # Counts path only matches when the previous sums are unit counts
    # re-aggregated; here s0 are counts of 1 so parent sums == segment
    # sizes — the partitioned variant counts elements, which only
    # coincides when every input element carries weight 1. Verify the
    # keys agree and counts equal the number of child uniques folded in.
    nw = int(want[2])
    np.testing.assert_array_equal(np.asarray(got[0])[:nw],
                                  np.asarray(want[0])[:nw])
    np.testing.assert_array_equal(np.asarray(got[1])[:nw],
                                  np.asarray(want[1])[:nw])


@pytest.mark.slow
def test_streams_variant_bit_equal():
    """streams>1 (per-sub-stream output slabs, summed) must be
    bit-identical to streams=1 and to the scatter contract — the
    cascade analog of the window kernel's streams=8 default."""
    rng = np.random.default_rng(11)
    n = 1 << 14
    keys = rng.choice(1 << 42, n // 16, replace=False)[
        rng.integers(0, n // 16, n)
    ].astype(np.int64)
    for streams in (2, 4):
        _diff(keys, n, slab=1 << 13, chunk=512, streams=streams)


def test_streams_with_sentinel_padding():
    rng = np.random.default_rng(12)
    n = 3000  # pads to whole slabs/chunks internally
    keys = np.concatenate([
        rng.choice(1 << 40, n - 500, replace=False).astype(np.int64),
        np.full(500, SENTINEL, np.int64),
    ])
    _diff(keys, n, slab=1 << 12, chunk=512, streams=4)


def test_streams_rejects_bad_slab():
    with pytest.raises(ValueError, match="streams"):
        aggregate_sorted_keys_partitioned(
            jnp.zeros(8, jnp.int64), 8, interpret=True,
            slab=1 << 12, chunk=512, streams=3,
        )
