"""Spark adapter algebra: partitioned cascade + merge == global cascade.

No Spark cluster needed — heatmap_partitions returns a plain iterator
closure, so the exact mapPartitions/reduceByKey dataflow is simulated
on lists (simulate_partitions). pyspark is only imported by
run_with_spark, which these tests don't touch.
"""

import json

import numpy as np
import pytest

from heatmap_tpu.pipeline import BatchJobConfig, run_batch
from heatmap_tpu.spark_adapter import (
    heatmap_partitions,
    merge_heatmaps,
    simulate_partitions,
)


def _rows(n, seed):
    rng = np.random.default_rng(seed)
    users = ["alice", "bob", "x-7", "rt-1", "rt-2"]
    return [
        {
            "latitude": float(rng.uniform(40, 50)),
            "longitude": float(rng.uniform(-130, -110)),
            "user_id": users[int(rng.integers(0, len(users)))],
            "source": "background" if rng.random() < 0.1 else "gps",
            "timestamp": int(rng.integers(0, 2**31)),
        }
        for _ in range(n)
    ]


CFG = dict(detail_zoom=12, min_detail_zoom=9)


@pytest.mark.slow
@pytest.mark.parametrize("amplify", [False, True])
def test_partitioned_equals_global(amplify):
    rows = _rows(1200, seed=1)
    cfg = BatchJobConfig(amplify_all=amplify, **CFG)
    global_blobs = run_batch(rows, cfg, as_json=True)
    # 4 uneven partitions, one empty.
    parts = [rows[:100], rows[100:700], [], rows[700:]]
    merged = simulate_partitions(parts, cfg)
    assert set(merged) == set(global_blobs)
    for k in global_blobs:
        assert json.loads(merged[k]) == pytest.approx(
            json.loads(global_blobs[k])
        )


def test_arrow_partitions_equal_row_partitions():
    """The mapInArrow body gives the same partials as the row-dict
    mapPartitions body, merged to the same global blobs."""
    import pyarrow as pa

    from heatmap_tpu.spark_adapter import heatmap_arrow_partitions

    rows = _rows(3000, seed=8)
    parts = [rows[:1300], rows[1300:]]
    want = simulate_partitions(parts, config=CFG)

    fn = heatmap_arrow_partitions(config=CFG)
    merged: dict = {}
    for part in parts:
        rb = pa.RecordBatch.from_pydict({
            k: [r[k] for r in part]
            for k in ("latitude", "longitude", "user_id", "source",
                      "timestamp")
        })
        # Two record batches per partition exercises the accumulate
        # path inside the runner.
        half = rb.num_rows // 2
        for out in fn(iter([rb.slice(0, half), rb.slice(half)])):
            for key, blob in zip(out.column("id").to_pylist(),
                                 out.column("heatmap").to_pylist()):
                merged[key] = (
                    merge_heatmaps(merged[key], blob)
                    if key in merged else blob
                )
    assert {k: json.loads(v) for k, v in merged.items()} == {
        k: json.loads(v) for k, v in want.items()
    }


def test_arrow_runner_empty_result_keeps_string_schema():
    """An all-invalid partition must emit string-typed (or no) batches,
    never null-typed columns that Spark's schema check rejects."""
    import pyarrow as pa

    from heatmap_tpu.spark_adapter import heatmap_arrow_partitions

    fn = heatmap_arrow_partitions(config=CFG)
    rb = pa.RecordBatch.from_pydict({
        "latitude": [89.9, 89.95],  # beyond the Mercator limit
        "longitude": [0.0, 1.0],
        "user_id": ["a", "b"],
        "source": ["gps", "gps"],
        "timestamp": [1, 2],
    })
    for out in fn(iter([rb])):
        assert out.schema.field("id").type == pa.string()
        assert out.schema.field("heatmap").type == pa.string()


def test_arrow_runner_is_picklable():
    import pickle

    from heatmap_tpu.spark_adapter import heatmap_arrow_partitions

    fn = heatmap_arrow_partitions(config=CFG)
    assert pickle.loads(pickle.dumps(fn)).cfg_kwargs == fn.cfg_kwargs


def test_merge_heatmaps_sums():
    a = json.dumps({"12_1_2": 2.0, "12_1_3": 1.0})
    b = json.dumps({"12_1_3": 4.0, "12_9_9": 1.0})
    assert json.loads(merge_heatmaps(a, b)) == {
        "12_1_2": 2.0, "12_1_3": 5.0, "12_9_9": 1.0
    }


def test_partition_closure_is_picklable():
    """Spark ships the closure to executors via pickle."""
    import pickle

    fn = heatmap_partitions(BatchJobConfig(**CFG))
    fn2 = pickle.loads(pickle.dumps(fn))
    rows = _rows(50, seed=3)
    assert dict(fn2(iter(rows))) == dict(
        heatmap_partitions(BatchJobConfig(**CFG))(iter(rows))
    )


def test_output_schema_matches_reference():
    """(id, heatmap-json) with id = user|timespan|coarseTile and the
    blob a detailTile->count dict (reference heatmap.py:156-157,
    §3.5 output record shape)."""
    blobs = simulate_partitions([_rows(200, seed=4)], BatchJobConfig(**CFG))
    assert blobs
    for key, val in blobs.items():
        user, timespan, tile = key.split("|")
        assert timespan == "alltime"
        z, r, c = tile.split("_")
        inner = json.loads(val)
        assert isinstance(inner, dict) and inner
        for dk, dv in inner.items():
            dz, _, _ = dk.split("_")
            assert int(dz) == int(z) + 5  # result_delta
            assert dv > 0
