"""CLI tests: flag parsing in-process, end-to-end runs via subprocess.

The subprocess runs use ``--backend cpu`` (the CLI's own platform
switch — the flag system under test) rather than the conftest's
config, since they are fresh interpreters.
"""

import json
import os
import subprocess
import sys

import pytest

from heatmap_tpu.cli import build_parser

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(*argv, timeout=240):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "heatmap_tpu", *argv],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )


class TestParser:
    def test_run_defaults_match_reference_constants(self):
        args = build_parser().parse_args(["run", "--input", "synthetic:10"])
        # reference heatmap.py:16-17: DETAIL_ZOOM_DELTA=5, MAX_ZOOM_LEVEL=16
        assert args.detail_zoom == 21
        assert args.min_detail_zoom == 5
        assert args.result_delta == 5
        assert args.timespans == "alltime"
        assert args.backend == "tpu"

    def test_backend_choices(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["run", "--input", "x", "--backend", "gpu"])

    def test_subcommand_required(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args([])

    def test_bad_timespan_rejected_before_ingest(self):
        from heatmap_tpu.cli import cmd_run

        args = build_parser().parse_args(
            ["run", "--input", "synthetic:10", "--timespans", "dayly"]
        )
        with pytest.raises(SystemExit, match="dayly"):
            cmd_run(args)

    def test_tiles_zoom_below_pixel_delta_rejected(self):
        from heatmap_tpu.cli import cmd_tiles

        args = build_parser().parse_args(
            ["tiles", "--input", "synthetic:10", "--zoom", "6"]
        )
        with pytest.raises(SystemExit, match="pixel-delta"):
            cmd_tiles(args)


class TestEndToEnd:
    def test_run_synthetic_to_jsonl(self, tmp_path):
        out = tmp_path / "blobs.jsonl"
        r = _run_cli(
            "run",
            "--backend", "cpu",
            "--input", "synthetic:2000:3",
            "--output", f"jsonl:{out}",
            "--detail-zoom", "12",
        )
        assert r.returncode == 0, r.stderr
        stats = json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["blobs"] > 0
        from heatmap_tpu.io import JSONLBlobSink

        loaded = JSONLBlobSink.load(str(out))
        assert len(loaded) == stats["blobs"]
        assert any(k.startswith("all|alltime|") for k in loaded)

    def test_run_weighted_jsonl_source(self, tmp_path):
        """run --weighted sums the source's value column into blob
        values; composing with --fast fails cleanly."""
        src = tmp_path / "pts.jsonl"
        with open(src, "w") as f:
            for v in (1.25, 2.0):
                f.write(json.dumps({
                    "latitude": 47.6, "longitude": -122.3,
                    "user_id": "alice", "value": v,
                }) + "\n")
        out = tmp_path / "blobs.jsonl"
        r = _run_cli(
            "run", "--backend", "cpu",
            "--input", f"jsonl:{src}", "--output", f"jsonl:{out}",
            "--detail-zoom", "10", "--min-detail-zoom", "4", "--weighted",
        )
        assert r.returncode == 0, r.stderr
        from heatmap_tpu.io import JSONLBlobSink
        from heatmap_tpu.tilemath.tile import Tile

        loaded = JSONLBlobSink.load(str(out))
        detail = Tile.tile_id_from_lat_long(47.6, -122.3, 10)
        alice = next(b if isinstance(b, dict) else json.loads(b)
                     for k, b in loaded.items() if k.startswith("alice|"))
        assert alice[detail] == 3.25
        # --weighted composes with --checkpoint-dir too (values ride
        # the checkpoint); same blobs as the plain weighted run.
        out2 = tmp_path / "blobs_ck.jsonl"
        r2 = _run_cli(
            "run", "--backend", "cpu",
            "--input", f"jsonl:{src}", "--output", f"jsonl:{out2}",
            "--detail-zoom", "10", "--min-detail-zoom", "4", "--weighted",
            "--checkpoint-dir", str(tmp_path / "ck"),
        )
        assert r2.returncode == 0, r2.stderr
        assert out2.read_bytes() == out.read_bytes()

    @pytest.mark.slow
    def test_run_fast_csv_matches_plain(self, tmp_path):
        import csv
        import numpy as np

        from heatmap_tpu import native

        if not native.available():
            pytest.skip("native library not built")
        pts = tmp_path / "pts.csv"
        rng = np.random.default_rng(9)
        with open(pts, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["latitude", "longitude", "user_id", "source",
                        "timestamp"])
            for _ in range(1500):
                w.writerow([
                    rng.uniform(40, 50), rng.uniform(-130, -110),
                    ["alice", "x-2", "rt-4"][rng.integers(0, 3)],
                    "background" if rng.random() < 0.1 else "gps", 1,
                ])
        outs = {}
        summaries = {}
        # "plain" needs --no-fast now: eligible CSV sources auto-route
        # to the fast path, and this test exists to pin the two paths'
        # blob equality. "auto" (no flag) must take fast by itself.
        for name, extra in (("plain", ["--no-fast"]), ("fast", ["--fast"]),
                            ("auto", [])):
            out = tmp_path / f"{name}.jsonl"
            r = _run_cli(
                "run", "--backend", "cpu",
                "--input", f"csv:{pts}",
                "--output", f"jsonl:{out}",
                "--detail-zoom", "12", "--min-detail-zoom", "9",
                *extra,
            )
            assert r.returncode == 0, r.stderr
            from heatmap_tpu.io import JSONLBlobSink

            outs[name] = JSONLBlobSink.load(str(out))
            summaries[name] = json.loads(r.stdout.strip().splitlines()[-1])
        assert outs["plain"] == outs["fast"] == outs["auto"]
        assert summaries["plain"]["ingest"] == "standard"
        assert summaries["fast"]["ingest"] == "fast"
        assert summaries["auto"]["ingest"] == "fast"

    def test_run_with_checkpoint_dir_resumes(self, tmp_path):
        out = tmp_path / "blobs.jsonl"
        ck = tmp_path / "ck"
        common = [
            "run", "--backend", "cpu",
            "--input", "synthetic:3000:5",
            "--output", f"jsonl:{out}",
            "--detail-zoom", "12", "--min-detail-zoom", "9",
            "--batch-size", "512",
            "--checkpoint-dir", str(ck), "--checkpoint-every", "2",
        ]
        r = _run_cli(*common)
        assert r.returncode == 0, r.stderr
        assert any(f.startswith("ckpt-") for f in os.listdir(ck))
        # Rerun resumes from checkpoints and reproduces the same blobs.
        from heatmap_tpu.io import JSONLBlobSink

        first = JSONLBlobSink.load(str(out))
        r2 = _run_cli(*common)
        assert r2.returncode == 0, r2.stderr
        assert JSONLBlobSink.load(str(out)) == first

    def test_run_arrays_output_spec(self, tmp_path):
        import json as _json

        out = tmp_path / "cols"
        r = _run_cli(
            "run", "--backend", "cpu",
            "--input", "synthetic:500:2",
            "--output", f"arrays:{out}",
            "--detail-zoom", "10", "--min-detail-zoom", "8",
        )
        assert r.returncode == 0, r.stderr
        summary = _json.loads(r.stdout.strip().splitlines()[-1])
        # detail z10 down to min_detail_zoom+1 = z9: two levels.
        assert summary["rows"] > 0 and summary["levels"] == 2
        assert any(f.name.endswith(".npz") for f in out.iterdir())

    def test_multihost_single_process_falls_through(self, tmp_path):
        import json as _json

        out = tmp_path / "mh.jsonl"
        r = _run_cli(
            "run", "--backend", "cpu", "--multihost",
            "--input", "synthetic:500:2",
            "--output", f"jsonl:{out}",
            "--detail-zoom", "10", "--min-detail-zoom", "8",
        )
        assert r.returncode == 0, r.stderr
        assert _json.loads(r.stdout.strip().splitlines()[-1])["blobs"] > 0
        r = _run_cli("run", "--backend", "cpu", "--multihost", "--fast",
                     "--input", "csv:x.csv")
        assert r.returncode != 0 and "standard job path" in r.stderr

    def test_multihost_bounded_flag_accepted(self, tmp_path):
        """--multihost composes with --max-points-in-flight (and the
        spill knob) since the bounded slice ingest landed; the old
        rejection must stay gone."""
        import json as _json

        out = tmp_path / "mhb.jsonl"
        r = _run_cli(
            "run", "--backend", "cpu", "--multihost",
            "--input", "synthetic:900:2",
            "--output", f"jsonl:{out}",
            "--detail-zoom", "10", "--min-detail-zoom", "8",
            "--max-points-in-flight", "200",
            "--merge-spill-dir", str(tmp_path / "spill"),
        )
        assert r.returncode == 0, r.stderr
        assert _json.loads(r.stdout.strip().splitlines()[-1])["blobs"] > 0

    def test_fast_rejects_non_csv_source(self):
        r = _run_cli("run", "--backend", "cpu", "--fast",
                     "--input", "synthetic:10")
        assert r.returncode != 0
        assert "csv" in r.stderr

    def test_fast_with_checkpoint_dir_matches_fast_alone(self, tmp_path):
        from heatmap_tpu.io import JSONLBlobSink
        from heatmap_tpu.io.hmpb import convert_to_hmpb

        hp = tmp_path / "pts.hmpb"
        convert_to_hmpb("synthetic:2000:3", str(hp))
        outs = {}
        for name, extra in (
            ("plain", []),
            ("ckpt", ["--checkpoint-dir", str(tmp_path / "ck"),
                      "--checkpoint-every", "2"]),
        ):
            out = tmp_path / f"{name}.jsonl"
            r = _run_cli(
                "run", "--backend", "cpu", "--fast",
                "--input", f"hmpb:{hp}",
                "--output", f"jsonl:{out}",
                "--detail-zoom", "11", "--min-detail-zoom", "9",
                "--batch-size", "512",
                *extra,
            )
            assert r.returncode == 0, r.stderr
            outs[name] = JSONLBlobSink.load(str(out))
        assert outs["plain"] == outs["ckpt"]
        # The checkpoint run actually wrote checkpoints.
        assert any((tmp_path / "ck").iterdir())

    @pytest.mark.slow
    def test_hmpb_auto_routes_fast(self, tmp_path):
        """An hmpb input with no flag must take the fast path and match
        the --no-fast standard path blob-for-blob (mirror of the CSV
        auto-routing test; checkpoint runs must stay standard)."""
        from heatmap_tpu.io import JSONLBlobSink
        from heatmap_tpu.io.hmpb import convert_to_hmpb

        hp = tmp_path / "pts.hmpb"
        convert_to_hmpb("synthetic:2000:5", str(hp))
        outs = {}
        ingests = {}
        for name, extra in (
            ("auto", []),
            ("plain", ["--no-fast"]),
            ("ckpt", ["--checkpoint-dir", str(tmp_path / "ck")]),
        ):
            out = tmp_path / f"{name}.jsonl"
            r = _run_cli(
                "run", "--backend", "cpu",
                "--input", f"hmpb:{hp}",
                "--output", f"jsonl:{out}",
                "--detail-zoom", "11", "--min-detail-zoom", "9",
                *extra,
            )
            assert r.returncode == 0, r.stderr
            outs[name] = JSONLBlobSink.load(str(out))
            ingests[name] = json.loads(
                r.stdout.strip().splitlines()[-1])["ingest"]
        assert outs["auto"] == outs["plain"] == outs["ckpt"]
        assert ingests["auto"] == "fast"
        assert ingests["plain"] == "standard"
        # --checkpoint-dir keeps the resumable standard path (format
        # stability for existing checkpoints).
        assert ingests["ckpt"] == "standard"

    def test_stream_synthetic_decay_and_resume(self, tmp_path):
        out = tmp_path / "live"
        ck = tmp_path / "ck"
        common = [
            "stream", "--backend", "cpu",
            "--input", "synthetic:20000:4",
            "--output", str(out),
            "--batch-points", "2048",
            "--interval", "600", "--half-life", "1200",
            "--zoom", "10", "--pixel-delta", "6",
            "--lat-min", "46", "--lat-max", "49",
            "--lon-min", "-124", "--lon-max", "-120",
            "--checkpoint-dir", str(ck), "--checkpoint-every", "3",
        ]
        r = _run_cli(*common)
        assert r.returncode == 0, r.stderr
        stats = json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["batches"] >= 9
        assert stats["tiles"] > 0
        # Decay: live mass is well under the raw point count.
        assert 0 < stats["live_mass"] < 20000
        assert any(f.startswith("ckpt-") for f in os.listdir(ck))
        # Rerun: resumes from the final checkpoint, consumes nothing new,
        # and reproduces the same live mass.
        r2 = _run_cli(*common)
        assert r2.returncode == 0, r2.stderr
        stats2 = json.loads(r2.stdout.strip().splitlines()[-1])
        assert stats2["batches"] == stats["batches"]
        assert stats2["live_mass"] == pytest.approx(stats["live_mass"])

    @pytest.mark.slow
    def test_stream_bin_backend_flag(self, tmp_path):
        """--bin-backend pins the update step's binning kernel; xla and
        the auto route must produce identical live mass (same points,
        bit-exact count kernels either way)."""
        masses = {}
        for be in ("auto", "xla"):
            r = _run_cli(
                "stream", "--backend", "cpu",
                "--input", "synthetic:8000:4",
                "--output", "",
                "--batch-points", "2048",
                "--interval", "600", "--half-life", "1200",
                "--zoom", "10", "--pixel-delta", "6",
                "--lat-min", "46", "--lat-max", "49",
                "--lon-min", "-124", "--lon-max", "-120",
                "--bin-backend", be,
            )
            assert r.returncode == 0, r.stderr
            masses[be] = json.loads(
                r.stdout.strip().splitlines()[-1]
            )["live_mass"]
        assert masses["auto"] == pytest.approx(masses["xla"])

    def test_tiles_synthetic_to_png_tree(self, tmp_path):
        out = tmp_path / "tiles"
        r = _run_cli(
            "tiles",
            "--backend", "cpu",
            "--input", "synthetic:5000:1",
            "--output", str(out),
            "--zoom", "12",
            "--pixel-delta", "6",
        )
        assert r.returncode == 0, r.stderr
        stats = json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["tiles"] >= 1
        assert stats["tile_zoom"] == 6
        pngs = [f for _, _, fs in os.walk(out) for f in fs]
        assert len(pngs) == stats["tiles"]

    @pytest.mark.slow
    def test_tiles_weighted_csv(self, tmp_path):
        """--weighted sums the input's 'value' column (BASELINE config
        3): non-uniform weights change the rendered pixels, uniform
        weights of 1.0 reproduce counting byte-for-byte, and a missing
        value column fails cleanly."""

        def render(csv_path, subdir, *extra):
            out = tmp_path / subdir
            r = _run_cli(
                "tiles", "--backend", "cpu",
                "--input", f"csv:{csv_path}", "--output", str(out),
                "--zoom", "12", "--pixel-delta", "6",
                "--lat-min", "47.0", "--lat-max", "48.5",
                "--lon-min", "-123.0", "--lon-max", "-121.5", *extra,
            )
            assert r.returncode == 0, r.stderr
            assert json.loads(r.stdout.strip().splitlines()[-1])["tiles"] >= 1
            return {
                os.path.relpath(os.path.join(d, f), out):
                    open(os.path.join(d, f), "rb").read()
                for d, _, fs in os.walk(out) for f in fs
            }

        def write_csv(path, value_expr):
            with open(path, "w") as f:
                f.write("latitude,longitude,user_id,source,timestamp,value\n")
                for i in range(50):
                    f.write(f"47.{600 + i},-122.{300 + i},u,gps,1,"
                            f"{value_expr(i)}\n")

        p = tmp_path / "w.csv"
        write_csv(p, lambda i: 1.0 + 10.0 * (i % 7))  # non-uniform
        weighted = render(p, "tw", "--weighted")
        counted = render(p, "tc")
        assert weighted.keys() == counted.keys()
        # Non-uniform weights must actually change at least one pixel.
        assert weighted != counted
        # Uniform weights of 1.0 == counting, byte-for-byte.
        p1 = tmp_path / "w1.csv"
        write_csv(p1, lambda i: 1.0)
        assert render(p1, "t1w", "--weighted") == render(p1, "t1c")
        # No value column -> clean error, not a stack trace.
        p2 = tmp_path / "nw.csv"
        with open(p2, "w") as f:
            f.write("latitude,longitude,user_id,source,timestamp\n")
            f.write("47.6,-122.3,u,gps,1\n")
        r2 = _run_cli(
            "tiles", "--backend", "cpu",
            "--input", f"csv:{p2}", "--output", str(tmp_path / "t2"),
            "--zoom", "12", "--pixel-delta", "6", "--weighted",
        )
        assert r2.returncode != 0
        assert "value" in r2.stderr

    @pytest.mark.slow
    def test_run_cascade_backend_flag(self, tmp_path):
        """--cascade-backend partitioned produces byte-identical blobs
        to the default scatter backend, and the unbounded-weighted rejection
        proves the flag actually reaches the config (byte-equality
        alone would pass even if the plumbing silently dropped it)."""
        outs = {}
        for be in ("scatter", "partitioned"):
            out = tmp_path / f"{be}.jsonl"
            r = _run_cli(
                "run", "--backend", "cpu",
                "--input", "synthetic:4000:6",
                "--output", f"jsonl:{out}",
                "--detail-zoom", "11", "--min-detail-zoom", "5",
                "--cascade-backend", be,
            )
            assert r.returncode == 0, r.stderr
            outs[be] = out.read_bytes()
        assert outs["scatter"] == outs["partitioned"]
        # The flag must reach BatchJobConfig: weighted+partitioned is
        # rejected at config time, before any ingest, cleanly.
        r2 = _run_cli(
            "run", "--backend", "cpu",
            "--input", "synthetic:10", "--output", "memory:",
            "--cascade-backend", "partitioned", "--weighted",
        )
        assert r2.returncode != 0
        assert "bounded-integer" in r2.stderr
        assert "Traceback" not in r2.stderr

    @pytest.mark.slow
    def test_run_data_parallel_flag(self, tmp_path):
        """--data-parallel on/off produce byte-identical blobs, and the
        rejection of --dp-min-emissions with an explicit mode proves
        both flags reach BatchJobConfig (byte-equality alone would pass
        if the plumbing silently dropped them)."""
        outs = {}
        for dp in ("on", "off", "auto"):
            out = tmp_path / f"dp_{dp}.jsonl"
            r = _run_cli(
                "run", "--backend", "cpu",
                "--input", "synthetic:4000:6",
                "--output", f"jsonl:{out}",
                "--detail-zoom", "11", "--min-detail-zoom", "5",
                "--data-parallel", dp,
            )
            assert r.returncode == 0, r.stderr
            outs[dp] = out.read_bytes()
        assert outs["on"] == outs["off"] == outs["auto"]
        r2 = _run_cli(
            "run", "--backend", "cpu",
            "--input", "synthetic:10", "--output", "memory:",
            "--data-parallel", "on", "--dp-min-emissions", "1000",
        )
        assert r2.returncode != 0
        assert "AUTO" in r2.stderr
        assert "Traceback" not in r2.stderr

    def test_info_reports_platform(self):
        r = _run_cli("info", "--backend", "cpu")
        assert r.returncode == 0, r.stderr
        info = json.loads(r.stdout.strip())
        assert info["platform"] == "cpu"
        assert info["x64"] is True


class TestRender:
    @pytest.mark.slow
    def test_render_from_arrays_and_jsonl(self, tmp_path):
        """Stored heatmaps -> PNG tiles from both storage kinds; the
        arrays and jsonl inputs must paint the same tile set for the
        same job."""
        import glob
        import json as _json

        lv = tmp_path / "lv"
        bl = tmp_path / "b.jsonl"
        for out in (f"arrays:{lv}", f"jsonl:{bl}"):
            r = _run_cli(
                "run", "--backend", "cpu",
                "--input", "synthetic:3000:5",
                "--output", out,
                "--detail-zoom", "12", "--min-detail-zoom", "8",
            )
            assert r.returncode == 0, r.stderr
        outs = {}
        for name, spec in (("arrays", f"arrays:{lv}"), ("jsonl", f"jsonl:{bl}")):
            td = tmp_path / f"tiles-{name}"
            r = _run_cli(
                "render", "--input", spec, "--zoom", "10",
                "--pixel-delta", "6", "--output", str(td),
            )
            assert r.returncode == 0, r.stderr
            stats = _json.loads(r.stdout.strip().splitlines()[-1])
            assert stats["tiles"] >= 1 and stats["zoom"] == 10
            outs[name] = sorted(
                p.relative_to(td).as_posix()
                for p in td.rglob("*.png")
            )
        assert outs["arrays"] == outs["jsonl"]

    def test_render_missing_zoom_fails_loudly(self, tmp_path):
        lv = tmp_path / "lv"
        r = _run_cli(
            "run", "--backend", "cpu", "--input", "synthetic:500:1",
            "--output", f"arrays:{lv}",
            "--detail-zoom", "10", "--min-detail-zoom", "8",
        )
        assert r.returncode == 0, r.stderr
        r = _run_cli("render", "--input", f"arrays:{lv}", "--zoom", "3",
                     "--output", str(tmp_path / "t"))
        assert r.returncode != 0
        assert "available" in r.stderr

    def test_render_jsonl_missing_zoom_fails_loudly(self, tmp_path):
        bl = tmp_path / "b.jsonl"
        r = _run_cli(
            "run", "--backend", "cpu", "--input", "synthetic:500:1",
            "--output", f"jsonl:{bl}",
            "--detail-zoom", "10", "--min-detail-zoom", "8",
        )
        assert r.returncode == 0, r.stderr
        r = _run_cli("render", "--input", f"jsonl:{bl}", "--zoom", "3",
                     "--output", str(tmp_path / "t"))
        assert r.returncode != 0
        assert "available" in r.stderr


class TestAutoBounds:
    def test_tiles_auto_bounds_finds_distant_data(self, tmp_path):
        """Data outside the default PNW window: the fixed flags miss it
        entirely; --auto-bounds derives the window from the data."""
        import json as _json

        p = tmp_path / "tokyo.csv"
        rows = ["latitude,longitude,user_id,source,timestamp"]
        rows += [f"{35.68 + i * 1e-4},{139.69 + i * 1e-4},u,gps,{i}"
                 for i in range(200)]
        p.write_text("\n".join(rows) + "\n")
        r0 = _run_cli("tiles", "--backend", "cpu", "--input", str(p),
                      "--zoom", "12", "--pixel-delta", "6",
                      "--output", str(tmp_path / "t0"))
        assert r0.returncode == 0, r0.stderr
        assert _json.loads(r0.stdout.strip().splitlines()[-1])["tiles"] == 0
        r1 = _run_cli("tiles", "--backend", "cpu", "--input", str(p),
                      "--zoom", "12", "--pixel-delta", "6", "--auto-bounds",
                      "--output", str(tmp_path / "t1"))
        assert r1.returncode == 0, r1.stderr
        stats = _json.loads(r1.stdout.strip().splitlines()[-1])
        assert stats["tiles"] >= 1
        lat_min, lat_max, lon_min, lon_max = stats["bounds"]
        assert lat_min < 35.68 < lat_max and lon_min < 139.69 < lon_max

    def test_stream_weighted_csv(self, tmp_path):
        """stream --weighted decays weighted mass: uniform value 5.0
        yields exactly 5x the counted live mass on the same input."""
        p = tmp_path / "w.csv"
        with open(p, "w") as f:
            f.write("latitude,longitude,user_id,source,timestamp,value\n")
            for i in range(4000):
                f.write(f"47.{600 + i % 300},-122.{300 + i % 300},u,gps,1,5\n")
        common = [
            "stream", "--backend", "cpu",
            "--input", f"csv:{p}",
            "--batch-points", "1000",
            "--interval", "600", "--half-life", "1200",
            "--zoom", "10", "--pixel-delta", "6",
            "--lat-min", "46", "--lat-max", "49",
            "--lon-min", "-124", "--lon-max", "-120",
        ]
        rw = _run_cli(*common, "--weighted")
        rc = _run_cli(*common)
        assert rw.returncode == 0, rw.stderr
        assert rc.returncode == 0, rc.stderr
        mw = json.loads(rw.stdout.strip().splitlines()[-1])["live_mass"]
        mc = json.loads(rc.stdout.strip().splitlines()[-1])["live_mass"]
        assert mw == pytest.approx(5.0 * mc, rel=1e-6)
        assert mc > 0

    def test_stream_auto_bounds(self, tmp_path):
        import json as _json

        p = tmp_path / "sydney.csv"
        rows = ["latitude,longitude,user_id,source,timestamp"]
        rows += [f"{-33.86 + i * 1e-4},{151.20 + i * 1e-4},u,gps,{i}"
                 for i in range(300)]
        p.write_text("\n".join(rows) + "\n")
        r = _run_cli("stream", "--backend", "cpu", "--input", str(p),
                     "--zoom", "10", "--pixel-delta", "6", "--auto-bounds",
                     "--batch-points", "128",
                     "--output", str(tmp_path / "t"))
        assert r.returncode == 0, r.stderr
        stats = _json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["tiles"] >= 1 and stats["live_mass"] > 0

    def test_render_from_parquet_arrays(self, tmp_path):
        import json as _json

        lv = tmp_path / "lvpq"
        r = _run_cli(
            "run", "--backend", "cpu", "--input", "synthetic:1200:2",
            "--output", f"arrays-parquet:{lv}",
            "--detail-zoom", "11", "--min-detail-zoom", "8",
        )
        assert r.returncode == 0, r.stderr
        r = _run_cli("render", "--input", f"arrays-parquet:{lv}",
                     "--zoom", "9", "--pixel-delta", "6",
                     "--output", str(tmp_path / "t"))
        assert r.returncode == 0, r.stderr
        assert _json.loads(r.stdout.strip().splitlines()[-1])["tiles"] >= 1
