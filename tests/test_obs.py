"""Telemetry subsystem tests: metrics registry, event log, run report,
CLI wiring, and the no-raw-instrumentation guard.

The smoke tests drive ``cli.cmd_run`` in-process (conftest already
pins the cpu backend + x64); blob byte-equality with telemetry on vs
off is the acceptance bar — telemetry must be purely observational.
"""

import json
import os
import re
import threading

import pytest

from heatmap_tpu import obs
from heatmap_tpu.obs.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Minimal valid payload per event type (keep in sync with EVENT_SCHEMA —
# the round-trip test emits each one).
_PAYLOADS = {
    "run_start": {"config": {"detail_zoom": 12}, "backend": "cpu",
                  "devices": {"platform": "cpu", "n_devices": 8}},
    "stage_end": {"stage": "cascade.device", "wall_s": 0.5,
                  "items": 100, "backend": "scatter"},
    "backend_resolved": {"requested": "auto", "resolved": "scatter",
                         "reason": "non-tpu platform -> xla scatter"},
    "cascade_dispatch": {"backend": "scatter", "jit": True,
                         "n_emissions": 10},
    "partition_planned": {"n_shards": 4, "splits": [12, 90, 400],
                          "sampled_points": 4096, "balance_factor": 1.25,
                          "max_shard_mass": 0.27, "mean_shard_mass": 0.25,
                          "skew_ratio": 1.08, "resplits": 0,
                          "degenerate": False, "fingerprint": "sha256:00",
                          "boundary_tiles": 6},
    "device_memory": {"samples": []},
    "retry": {"shard": 3, "attempt": 1, "error": "RuntimeError('x')"},
    "recovery": {"shard": 3, "attempts": 2},
    "heartbeat": {"process_index": 0, "process_count": 1,
                  "phase": "ingest_done", "uptime_s": 1.5},
    "profiler_unavailable": {"error": "RuntimeError('no profiler')"},
    "http_request": {"route": "tiles", "status": 200,
                     "path": "/tiles/default/7/20/44.json", "ms": 1.2,
                     "bytes": 512, "cache": "hit"},
    "store_reload": {"old_generation": 0, "generation": 1, "levels": 5,
                     "seconds": 0.1, "spec": "delta:store/", "layers": 3,
                     "initial": False},
    "delta_applied": {"epoch": 2, "points": 300, "sign": 1,
                      "seconds": 0.8, "content_hash": "sha256:00",
                      "artifact": "delta-000002", "rows": 120,
                      "duplicate": False, "watermark": 1.7e12,
                      "keys_invalidated": 42},
    "ingest_tick": {"tick": 7, "points": 300, "seconds": 0.12,
                    "epoch": 8, "duplicate": False, "watermark": 1.5e9,
                    "lag_s": 0.34, "queue_depth": 2,
                    "keys_invalidated": 17, "compacted": False},
    "compaction_start": {"root": "store/", "deltas": 3,
                         "base": "base-000001"},
    "compaction_end": {"root": "store/", "seconds": 0.4, "status": "ok",
                       "base": "base-000004", "levels": 5, "rows": 2048,
                       "pruned_entries": 2, "buckets": 4},
    "retraction_applied": {"root": "store/", "rows": 40, "batches": 2,
                           "scanned": 80, "where": {"user_id": "alice"},
                           "epochs": [3, 4], "seconds": 1.2},
    "temporal_served": {"layer": "default", "zoom": 2, "mode": "as_of",
                        "as_of": "1250", "cache": "hit", "ms": 0.8},
    "bucket_roll": {"root": "store/", "prev_ref": 1600.0, "ref": 1700.0,
                    "retired": 1, "keys_invalidated": 12,
                    "windows": ["150"]},
    "fault_injected": {"site": "source.read", "fault_seq": 0, "key": "jsonl",
                       "rule": "source.read=3x5"},
    "degraded_enter": {"cause": "render", "detail": "serving stale tiles"},
    "degraded_exit": {"cause": "render"},
    "degrade_step": {"rung": 1, "from_rung": 0, "direction": "up",
                     "cause": "tiles-fast", "burn": 1.5},
    "quarantine": {"root": "store/", "path": "journal/ckpt-3.npz",
                   "reason": "digest_mismatch", "kind": "journal_entry",
                   "detail": "recorded sha256:aa..., actual sha256:bb..."},
    "anomaly_detected": {"series": "ingest_lag_seconds", "z": 7.2,
                         "threshold": 6.0, "watch": "ingest_lag_seconds",
                         "value": 42.5},
    "shard_orphaned": {"shard": "5", "host": "2", "reason": "heartbeat"},
    "shard_reassigned": {"shard": "5", "from_host": "2", "to_host": "0"},
    "speculative_launch": {"shard": "3", "host": "1", "runtime_s": 4.2,
                           "threshold_s": 1.9},
    "speculative_win": {"shard": "3", "winner": "1", "loser": "0",
                        "quarantined": "quarantine/shard-00003-ab-loser"},
    "fleet_backend_down": {"backend": "b2", "reason": "probe_failures",
                           "detail": "3 consecutive probe failures"},
    "fleet_backend_up": {"backend": "b2", "detail": "half-open probe ok"},
    "synopsis_built": {"zoom": 6, "pairs": 4, "bytes": 2048,
                       "max_err": 12.5, "coefficients": 256,
                       "path": "store/base-000001/synopsis-z06.npz"},
    "synopsis_served": {"layer": "all-alltime", "zoom": 6,
                        "max_err": 12.5, "source_zoom": 6,
                        "stale": False},
    "integral_built": {"zoom": 6, "pairs": 4, "bytes": 2048,
                       "path": "store/base-000001/integral-z06.npz"},
    "query_served": {"op": "sum", "zoom": 8, "path": "integral",
                     "layer": "all-alltime", "bbox_area": 100,
                     "cells": 5, "k": 10, "q": 0.5, "max_err": 12.5,
                     "ms": 0.2},
    "slo_breach": {"slo": "tiles-fast", "burn_rate": 2.5,
                   "kind": "latency", "compliance": 0.9975,
                   "target": 0.999, "window_s": 300.0,
                   "detail": "threshold_ms=50"},
    "incident_flush": {"trigger": "shed", "path": "incidents/ab12-0",
                       "seq": 0, "detail": "in-flight bound 2",
                       "bytes": 4096},
    "prewarm_done": {"keys": 12, "seconds": 0.8, "bytes": 65536,
                     "errors": 0, "planned": 16,
                     "budget_exhausted": False, "source": "startup"},
    "writeplane_append": {"points": 1500, "ranges": 3, "sign": 1,
                          "duplicate": False, "seconds": 0.4,
                          "content_hash": "sha256:00"},
    "writeplane_publish": {"epoch": 4, "ranges": 3, "seconds": 0.02,
                           "live_deltas": 5},
    "writeplane_rebalance": {"range": "r000", "new_range": "r004",
                             "split": 123456, "reason": "hot_range",
                             "seconds": 0.3},
    "run_end": {"status": "ok", "blobs": 42, "checksum": "crc32:00000000",
                "seconds": 1.0},
}


class TestEventSchema:
    def test_catalog_round_trip(self, tmp_path):
        """Every cataloged event type emits, survives the JSONL round
        trip, and re-validates — with one monotonic seq per log."""
        path = str(tmp_path / "events.jsonl")
        with obs.EventLog(path, run_id="testrun") as log:
            for event, payload in _PAYLOADS.items():
                log.emit(event, **payload)
        records = obs.read_events(path)
        assert [r["event"] for r in records] == list(_PAYLOADS)
        for rec in records:
            obs.validate_event(rec)  # must not raise
            assert rec["run_id"] == "testrun"
        assert [r["seq"] for r in records] == list(range(len(_PAYLOADS)))

    def test_payloads_cover_schema(self):
        assert set(_PAYLOADS) == set(obs.EVENT_SCHEMA)

    def test_unknown_field_rejected(self, tmp_path):
        with obs.EventLog(str(tmp_path / "e.jsonl")) as log:
            with pytest.raises(ValueError, match="unknown field"):
                log.emit("run_end", status="ok", bogus_field=1)

    def test_missing_required_rejected(self, tmp_path):
        with obs.EventLog(str(tmp_path / "e.jsonl")) as log:
            with pytest.raises(ValueError, match="missing required"):
                log.emit("stage_end", wall_s=0.1)  # no stage

    def test_unknown_event_type_rejected(self, tmp_path):
        with obs.EventLog(str(tmp_path / "e.jsonl")) as log:
            with pytest.raises(ValueError, match="unknown event type"):
                log.emit("made_up_event", foo=1)

    def test_module_emit_noop_without_log(self):
        assert obs.get_event_log() is None
        assert obs.emit("run_end", status="ok") is None

    def test_concurrent_emit_keeps_seq_dense(self, tmp_path):
        path = str(tmp_path / "e.jsonl")
        with obs.EventLog(path) as log:
            threads = [
                threading.Thread(
                    target=lambda: [log.emit("heartbeat", process_index=0,
                                             process_count=1, phase="p")
                                    for _ in range(200)])
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        seqs = sorted(r["seq"] for r in obs.read_events(path))
        assert seqs == list(range(1600))


class TestMetricsRegistry:
    def test_disabled_is_noop(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total")
        c.inc(5)
        assert c.value() == 0

    def test_counter_concurrency(self):
        reg = MetricsRegistry()
        reg.enabled = True
        c = reg.counter("hits_total", labelnames=("k",))
        n_threads, per_thread = 8, 5000

        def worker():
            for _ in range(per_thread):
                c.inc(k="a")

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value(k="a") == n_threads * per_thread

    def test_label_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.enabled = True
        c = reg.counter("c_total", labelnames=("backend",))
        with pytest.raises(ValueError, match="takes labels"):
            c.inc(1, wrong="x")

    def test_same_name_same_object_type_conflict_raises(self):
        reg = MetricsRegistry()
        assert reg.counter("x_total") is reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        reg.enabled = True
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("n_total").inc(-1)

    def test_histogram_and_prometheus_rendering(self):
        reg = MetricsRegistry()
        reg.enabled = True
        h = reg.histogram("lat_seconds", "spans", labelnames=("stage",),
                          buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v, stage="s")
        reg.gauge("g", "a gauge").set(2.5)
        text = reg.render_prometheus()
        assert '# TYPE lat_seconds histogram' in text
        assert 'lat_seconds_bucket{stage="s",le="0.1"} 1' in text
        assert 'lat_seconds_bucket{stage="s",le="1"} 2' in text
        assert 'lat_seconds_bucket{stage="s",le="+Inf"} 3' in text
        assert 'lat_seconds_count{stage="s"} 3' in text
        assert "g 2.5" in text

    def test_reset_keeps_handles_valid(self):
        reg = MetricsRegistry()
        reg.enabled = True
        c = reg.counter("y_total")
        c.inc(3)
        reg.reset()
        assert c.value() == 0
        c.inc(2)
        assert reg.counter("y_total").value() == 2

    def test_snapshot_is_json_serializable(self):
        reg = MetricsRegistry()
        reg.enabled = True
        reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
        reg.counter("c_total", labelnames=("a",)).inc(1, a="v")
        json.dumps(reg.snapshot())


class TestTracerFeedsRegistry:
    def test_span_records_histogram_items_and_event(self, tmp_path):
        from heatmap_tpu.utils.trace import span

        obs.enable_metrics(True)
        path = str(tmp_path / "e.jsonl")
        obs.set_event_log(obs.EventLog(path))
        with span("unit.stage", items=64, backend="scatter"):
            pass
        obs.get_event_log().close()
        obs.set_event_log(None)
        snap = obs.get_registry().snapshot()
        [sample] = [s for s in snap["stage_duration_seconds"]["samples"]
                    if s["labels"] == {"stage": "unit.stage"}]
        assert sample["count"] == 1
        [items] = [s for s in snap["stage_items_total"]["samples"]
                   if s["labels"] == {"stage": "unit.stage"}]
        assert items["value"] == 64
        [rec] = obs.read_events(path)
        assert rec["event"] == "stage_end"
        assert rec["stage"] == "unit.stage"
        assert rec["items"] == 64
        assert rec["backend"] == "scatter"

    def test_span_free_when_telemetry_off(self):
        from heatmap_tpu.utils.trace import get_tracer, span

        with span("quiet.stage", items=1):
            pass
        assert "quiet.stage" in get_tracer().report()
        snap = obs.get_registry().snapshot()
        assert not any(s["labels"].get("stage") == "quiet.stage"
                       for s in snap["stage_duration_seconds"]["samples"])


class TestProfilerUnavailable:
    def test_warning_attribute_and_event(self, tmp_path, monkeypatch):
        """The jax_profile docstring promises a tracer warning on
        profiler failure — the satellite fix records it and emits the
        profiler_unavailable event."""
        import jax

        from heatmap_tpu.utils.trace import get_tracer, jax_profile

        def boom(logdir):
            raise RuntimeError("profiler not supported here")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        path = str(tmp_path / "e.jsonl")
        obs.set_event_log(obs.EventLog(path))
        with jax_profile(str(tmp_path / "trace")):
            pass
        obs.get_event_log().close()
        obs.set_event_log(None)
        tracer = get_tracer()
        assert tracer.profiler_warning is not None
        assert "profiler not supported here" in tracer.profiler_warning
        [rec] = obs.read_events(path)
        assert rec["event"] == "profiler_unavailable"
        assert "profiler not supported here" in rec["error"]
        report = obs.build_run_report(tracer=tracer)
        assert any("profiler" in w for w in report["warnings"])

    def test_no_warning_when_profiler_starts(self, tmp_path):
        from heatmap_tpu.utils.trace import get_tracer, jax_profile

        with jax_profile(str(tmp_path / "trace")):
            pass
        assert get_tracer().profiler_warning is None


def _run_args(extra):
    from heatmap_tpu.cli import build_parser

    return build_parser().parse_args(
        ["run", "--backend", "cpu", "--input", "synthetic:2000:3",
         "--detail-zoom", "12", *extra])


class TestRunTelemetry:
    def test_events_report_and_blob_equality(self, tmp_path, capsys):
        """One batch job, telemetry off then on: the on-run yields a
        parseable event log (run_start first, run_end last, stage_end +
        backend_resolved + device_memory between), a run_report.json
        with stages/metrics/manifest, a Prometheus dump with io
        counters — and byte-identical blobs to the off-run."""
        from heatmap_tpu.cli import cmd_run

        out_off = tmp_path / "off.jsonl"
        assert cmd_run(_run_args(["--output", f"jsonl:{out_off}"])) == 0

        out_on = tmp_path / "on.jsonl"
        events = tmp_path / "events.jsonl"
        report_path = tmp_path / "run_report.json"
        mdir = tmp_path / "metrics"
        assert cmd_run(_run_args(
            ["--output", f"jsonl:{out_on}",
             "--events", str(events),
             "--report", str(report_path),
             "--metrics-dir", str(mdir)])) == 0
        capsys.readouterr()

        # -- acceptance: blobs byte-identical with telemetry on vs off
        assert out_on.read_bytes() == out_off.read_bytes()

        # -- and with span tracing + an SLO engine on top (the span
        # tree must be purely observational too)
        out_traced = tmp_path / "traced.jsonl"
        trace_out = tmp_path / "trace.json"
        assert cmd_run(_run_args(
            ["--output", f"jsonl:{out_traced}",
             "--trace-out", str(trace_out),
             "--slo", "stage-budget:error_rate:target=0.9"])) == 0
        capsys.readouterr()
        assert out_traced.read_bytes() == out_off.read_bytes()
        traced = json.loads(trace_out.read_text())
        assert any(e.get("name") == "run"
                   for e in traced["traceEvents"])

        # -- and with a brownout controller armed at rung 0: an idle
        # ladder (no burn) must be purely observational too.
        from heatmap_tpu.serve import degrade

        controller = degrade.BrownoutController(poll_interval_s=0.0)
        out_ctl = tmp_path / "ctl.jsonl"
        controller.poll()
        assert cmd_run(_run_args(
            ["--output", f"jsonl:{out_ctl}",
             "--slo", "stage-budget:error_rate:target=0.9"])) == 0
        capsys.readouterr()
        controller.poll()
        assert controller.rung == 0
        assert out_ctl.read_bytes() == out_off.read_bytes()

        # -- event log: ordering + coverage
        records = obs.read_events(str(events))
        for rec in records:
            obs.validate_event(rec)
        kinds = [r["event"] for r in records]
        assert kinds[0] == "run_start"
        assert kinds[-1] == "run_end"
        assert "stage_end" in kinds
        assert "backend_resolved" in kinds
        assert "cascade_dispatch" in kinds
        assert "device_memory" in kinds
        assert len({r["run_id"] for r in records}) == 1
        assert [r["seq"] for r in records] == list(range(len(records)))
        start = records[0]
        assert start["config"]["detail_zoom"] == 12
        assert start["devices"]["platform"] == "cpu"
        end = records[-1]
        assert end["status"] == "ok"
        assert end["blobs"] > 0
        assert end["checksum"].startswith("crc32:")
        [resolved] = [r for r in records if r["event"] == "backend_resolved"]
        assert resolved["requested"] == "auto"
        assert resolved["resolved"] == "scatter"

        # -- run report: parseable, stages with attribution, manifest
        report = json.loads(report_path.read_text())
        assert report["schema"].startswith("heatmap-tpu.run_report")
        assert "cascade.device" in report["stages"]
        assert report["run"]["status"] == "ok"
        assert report["run"]["checksum"] == end["checksum"]
        assert report["backends"][0]["resolved"] == "scatter"
        # io counters made it into the metrics snapshot
        rows = report["metrics"]["source_rows_read_total"]["samples"]
        assert sum(s["value"] for s in rows) == 2000
        blobs_written = report["metrics"]["sink_blobs_written_total"]
        assert sum(s["value"]
                   for s in blobs_written["samples"]) == end["blobs"]
        binned = report["metrics"]["points_binned_total"]["samples"]
        assert binned[0]["labels"] == {"backend": "scatter"}

        # -- Prometheus exposition
        prom = (mdir / "metrics.prom").read_text()
        assert "# TYPE stage_duration_seconds histogram" in prom
        assert 'source_rows_read_total{source="synthetic"} 2000' in prom

    def test_report_flag_prints_table_without_profile(self, tmp_path,
                                                      capsys):
        """Satellite: the span/throughput report under --report alone
        (previously reachable only with --profile)."""
        from heatmap_tpu.cli import cmd_run

        report_path = tmp_path / "r.json"
        assert cmd_run(_run_args(
            ["--output", f"jsonl:{tmp_path / 'b.jsonl'}",
             "--report", str(report_path)])) == 0
        err = capsys.readouterr().err
        assert "run report" in err
        assert "cascade.device" in err
        assert report_path.exists()

    def test_run_end_records_job_error(self, tmp_path):
        """A failing job still closes the event log with
        run_end{status=error} before the error propagates."""
        from heatmap_tpu.cli import cmd_run

        events = tmp_path / "events.jsonl"
        args = _run_args(
            ["--output", f"jsonl:{tmp_path / 'b.jsonl'}",
             "--events", str(events),
             "--timespans", "alltime,year"])
        # Dated timespans need timestamps; synthetic provides them —
        # inject the failure further down instead: weighted without a
        # value column.
        args.weighted = True
        with pytest.raises(ValueError, match="value"):
            cmd_run(args)
        records = obs.read_events(str(events))
        assert records[-1]["event"] == "run_end"
        assert records[-1]["status"] == "error"
        assert "value" in records[-1]["error"]
        assert obs.get_event_log() is None  # log detached + closed


class TestRecoveryEvents:
    def test_retry_and_recovery_emitted(self, tmp_path):
        from heatmap_tpu.utils.recovery import FaultInjector, run_shards

        obs.enable_metrics(True)
        path = str(tmp_path / "e.jsonl")
        obs.set_event_log(obs.EventLog(path))
        inj = FaultInjector({1: 2})
        result = run_shards([10, 20, 30], lambda s: s * 2, retries=3,
                            fault_injector=inj)
        obs.get_event_log().close()
        obs.set_event_log(None)
        assert result == [20, 40, 60]
        records = obs.read_events(path)
        retries = [r for r in records if r["event"] == "retry"]
        assert [r["attempt"] for r in retries] == [1, 2]
        assert all(r["shard"] == 1 for r in retries)
        [rec] = [r for r in records if r["event"] == "recovery"]
        assert rec == {**rec, "shard": 1, "attempts": 2}
        assert obs.SHARD_RETRIES.value() == 2


class TestStreamingTelemetry:
    def test_update_and_default_hook_gauges(self):
        import numpy as np

        from heatmap_tpu.ops import Window
        from heatmap_tpu.streaming import (HeatmapStream, StreamConfig,
                                           run_stream)

        obs.enable_metrics(True)
        window = Window(zoom=8, row0=80, col0=40, height=8, width=8)
        stream = HeatmapStream(StreamConfig(window=window, half_life_s=60.0))
        batches = [
            (float(t), {"latitude": np.full(5, 47.6),
                        "longitude": np.full(5, -122.3),
                        "user_id": ["u"] * 5, "source": ["gps"] * 5,
                        "timestamp": [0] * 5})
            for t in (0, 30, 60)
        ]
        run_stream(stream, batches)
        assert obs.STREAM_POINTS.value() == 15
        assert obs.STREAM_BATCHES.value() == 3
        assert obs.STREAM_TICKS.value() == 3
        assert obs.STREAM_TIME.value() == 60.0


class TestNoRawInstrumentation:
    # Modules allowed to talk to stdout / own a clock: the telemetry
    # subsystem itself, the tracer, and the CLI boundary.
    ALLOWED = ("heatmap_tpu/obs/", "heatmap_tpu/utils/trace.py",
               "heatmap_tpu/cli.py", "heatmap_tpu/__main__.py")
    PATTERN = re.compile(r"(?:(?<![\w.])print\(|time\.perf_counter\()")

    def test_no_raw_print_or_timer_outside_obs(self):
        """All future instrumentation goes through heatmap_tpu.obs /
        utils.trace — raw print()/perf_counter() in library modules
        would bypass the zero-cost-when-off discipline."""
        offenders = []
        pkg = os.path.join(REPO, "heatmap_tpu")
        for dirpath, _, files in os.walk(pkg):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, REPO).replace(os.sep, "/")
                if any(rel.startswith(a) for a in self.ALLOWED):
                    continue
                with open(full) as f:
                    for lineno, line in enumerate(f, 1):
                        code = line.split("#", 1)[0]
                        if self.PATTERN.search(code):
                            offenders.append(f"{rel}:{lineno}")
        assert not offenders, (
            "raw print()/time.perf_counter() outside obs//trace.py — "
            "route instrumentation through heatmap_tpu.obs: "
            + ", ".join(offenders))

    def test_serve_tree_is_guarded(self):
        """The serve/ package is the layer MOST tempted to print (HTTP
        request logging) and to time ad hoc (render latency): pin that
        it exists, is scanned by the walk above, and is not allowed."""
        serve = os.path.join(REPO, "heatmap_tpu", "serve")
        assert os.path.isdir(serve)
        scanned = [f for f in os.listdir(serve) if f.endswith(".py")]
        assert "http.py" in scanned and "cache.py" in scanned
        assert not any(a.startswith("heatmap_tpu/serve")
                       for a in self.ALLOWED)
        # And the guard pattern does bite on what serve must not do.
        assert self.PATTERN.search("print('GET /tiles 200')")
        assert self.PATTERN.search("t0 = time.perf_counter()")

    SLEEP_ALLOWED = ("heatmap_tpu/faults/",)
    SLEEP_PATTERN = re.compile(r"(?<![\w.])time\.sleep\(")

    def test_no_hand_rolled_retry_sleeps(self):
        """Every backoff sleep goes through faults.sleep_backoff — the
        only sanctioned ``time.sleep`` in the library. A hand-rolled
        ``time.sleep`` retry loop would dodge the unified policy table,
        the chaos plane's ``backoff_scale`` (which is how the soak and
        the chaos tests keep injected-fault retries instant), and the
        ``io_retries_total`` accounting (docs/robustness.md)."""
        offenders = []
        pkg = os.path.join(REPO, "heatmap_tpu")
        for dirpath, _, files in os.walk(pkg):
            for fname in files:
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, REPO).replace(os.sep, "/")
                if any(rel.startswith(a) for a in self.SLEEP_ALLOWED):
                    continue
                with open(full) as f:
                    for lineno, line in enumerate(f, 1):
                        code = line.split("#", 1)[0]
                        if self.SLEEP_PATTERN.search(code):
                            offenders.append(f"{rel}:{lineno}")
        assert not offenders, (
            "time.sleep() outside heatmap_tpu/faults/ — use "
            "faults.sleep_backoff / faults.retry_call for retry waits: "
            + ", ".join(offenders))
        # The pattern does bite on what the guard forbids.
        assert self.SLEEP_PATTERN.search("time.sleep(backoff_s * attempt)")

    TRACING_MODULES = ("heatmap_tpu/obs/tracing.py",
                       "heatmap_tpu/obs/slo.py",
                       "heatmap_tpu/obs/recorder.py",
                       "heatmap_tpu/obs/incident.py")
    TRACING_PATTERN = re.compile(
        r"(?:(?<![\w.])print\(|time\.perf_counter\(|(?<![\w.])time\.sleep\()")

    def test_tracing_and_slo_have_no_unsanctioned_clocks(self):
        """obs/tracing.py, obs/slo.py, obs/recorder.py and
        obs/incident.py sit inside the blanket ``heatmap_tpu/obs/``
        allowance above, so they get their own tighter guard: no raw
        print()/perf_counter()/time.sleep() except on lines explicitly
        marked ``# sanctioned:`` (tracing's single ``_now_s`` clock
        site). The SLO engine and the flight recorder run entirely on
        event/span timestamps — they never own a clock or sleep; the
        incident manager's wall clock is time.time (injectable), never
        perf_counter."""
        offenders, sanctioned = [], []
        for rel in self.TRACING_MODULES:
            full = os.path.join(REPO, rel)
            assert os.path.isfile(full), f"{rel} missing"
            with open(full) as f:
                for lineno, line in enumerate(f, 1):
                    if not self.TRACING_PATTERN.search(line):
                        continue
                    if "# sanctioned:" in line:
                        sanctioned.append(f"{rel}:{lineno}")
                    else:
                        offenders.append(f"{rel}:{lineno}")
        assert not offenders, (
            "unsanctioned print()/perf_counter()/sleep() in the "
            "tracing/SLO modules — all timing goes through _now_s "
            "(mark deliberate sites '# sanctioned: <why>'): "
            + ", ".join(offenders))
        # Exactly one sanctioned clock: tracing._now_s. Growing this
        # list is a deliberate act that must touch this test.
        assert sanctioned == ["heatmap_tpu/obs/tracing.py:59"] or (
            len(sanctioned) == 1
            and sanctioned[0].startswith("heatmap_tpu/obs/tracing.py:"))

    def test_tilefs_tree_is_guarded(self):
        """The tilefs/ package sits on the serve path twice over (mmap
        store reads, disk-cache fills) and replays requests at startup
        (prewarm) — ad-hoc warm-progress prints or hand-rolled fill
        timing would bypass the obs discipline: pin that the tree
        exists, is scanned by the walks above, and is not allowed."""
        tfs = os.path.join(REPO, "heatmap_tpu", "tilefs")
        assert os.path.isdir(tfs)
        scanned = [f for f in os.listdir(tfs) if f.endswith(".py")]
        assert "format.py" in scanned and "diskcache.py" in scanned
        assert "prewarm.py" in scanned
        assert not any(a.startswith("heatmap_tpu/tilefs")
                       for a in self.ALLOWED)
        assert not any(a.startswith("heatmap_tpu/tilefs")
                       for a in self.SLEEP_ALLOWED)
        assert self.PATTERN.search("print('prewarmed 64 keys')")

    def test_synopsis_tree_is_guarded(self):
        """The synopsis/ package sits on the serve decode path — ad-hoc
        decode timing or build-progress prints would bypass the obs
        discipline exactly like serve/ would: pin that the tree exists,
        is scanned by the walk above, and is not allowed."""
        syn = os.path.join(REPO, "heatmap_tpu", "synopsis")
        assert os.path.isdir(syn)
        scanned = [f for f in os.listdir(syn) if f.endswith(".py")]
        assert "transform.py" in scanned and "build.py" in scanned
        assert not any(a.startswith("heatmap_tpu/synopsis")
                       for a in self.ALLOWED)
        assert self.PATTERN.search("t0 = time.perf_counter()  # decode")

    # Modules the serve tier's tile DECODE path imports: synopsis
    # decoding must work on a box with no jax install at all
    # (docs/synopsis.md), so module-level jax imports are forbidden.
    # serve/live.py is deliberately absent — it renders via
    # tilemath.mercator and legitimately pulls jax.
    JAX_FREE = ("heatmap_tpu/serve/store.py", "heatmap_tpu/serve/render.py",
                "heatmap_tpu/serve/http.py", "heatmap_tpu/serve/cache.py",
                "heatmap_tpu/serve/router.py",
                "heatmap_tpu/serve/dashboard.py",
                "heatmap_tpu/serve/degrade.py", "heatmap_tpu/synopsis/",
                "heatmap_tpu/analytics/", "heatmap_tpu/tilefs/")
    JAX_IMPORT = re.compile(r"^(?:import jax\b|from jax\b)")

    def test_decode_path_has_no_module_level_jax(self):
        """The serving decode path (TileStore -> render -> http/router
        + the whole synopsis package) must not import jax at module
        level — lazy imports inside ``*_jax`` functions are the
        sanctioned idiom (synopsis/transform.py docstring)."""
        offenders = []
        for target in self.JAX_FREE:
            full = os.path.join(REPO, target)
            if target.endswith("/"):
                files = [os.path.join(full, f) for f in os.listdir(full)
                         if f.endswith(".py")]
            else:
                files = [full]
            assert files, f"{target} matched no files"
            for fpath in files:
                rel = os.path.relpath(fpath, REPO).replace(os.sep, "/")
                with open(fpath) as f:
                    for lineno, line in enumerate(f, 1):
                        if self.JAX_IMPORT.search(line):
                            offenders.append(f"{rel}:{lineno}")
        assert not offenders, (
            "module-level jax import on the serve decode path — import "
            "jax lazily inside *_jax functions instead: "
            + ", ".join(offenders))
        # The pattern bites on both import spellings but not the lazy
        # (indented) idiom.
        assert self.JAX_IMPORT.search("import jax.numpy as jnp")
        assert self.JAX_IMPORT.search("from jax import lax")
        assert not self.JAX_IMPORT.search("    import jax")

    def test_analytics_tree_is_guarded(self):
        """The analytics/ package sits on the /query serve path — query
        latency belongs to the query_seconds histogram and the
        query_served event, never an ad-hoc perf_counter: pin that the
        tree exists, is scanned by the walk above, and is not allowed.
        (Its jax discipline is pinned by JAX_FREE: integral2d_jax
        imports jax lazily, so /query decoding works without jax.)"""
        ana = os.path.join(REPO, "heatmap_tpu", "analytics")
        assert os.path.isdir(ana)
        scanned = [f for f in os.listdir(ana) if f.endswith(".py")]
        assert "integral.py" in scanned and "query.py" in scanned
        assert not any(a.startswith("heatmap_tpu/analytics")
                       for a in self.ALLOWED)
        assert self.PATTERN.search("t0 = time.perf_counter()  # query")

    def test_delta_tree_is_guarded(self):
        """The delta/ package times applies and compactions — that must
        flow through the obs metrics/events, never ad-hoc timers or
        progress prints: pin that the tree exists, is scanned, and is
        not allowed."""
        delta = os.path.join(REPO, "heatmap_tpu", "delta")
        assert os.path.isdir(delta)
        scanned = [f for f in os.listdir(delta) if f.endswith(".py")]
        assert "journal.py" in scanned and "compact.py" in scanned
        assert not any(a.startswith("heatmap_tpu/delta")
                       for a in self.ALLOWED)
        assert self.PATTERN.search("print('compacted 3 deltas')")
