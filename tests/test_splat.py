"""Gaussian splat tests (BASELINE.md config 3): kernel properties,
oracle parity, mass conservation, weighted binning, sharded halo
exchange vs the single-device path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from heatmap_tpu.ops import (
    Window,
    bin_points_splat,
    bin_points_window,
    gaussian_kernel_1d,
    splat_raster,
)
from oracle import splat_oracle_np
from heatmap_tpu.parallel import make_mesh, splat_rowsharded

WINDOW = Window(zoom=10, row0=320, col0=256, height=64, width=64)


def _points(n=500, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(30.0, 52.0, n),
        rng.uniform(-90.0, -68.0, n),
        rng.exponential(2.0, n),
    )


class TestKernel:
    def test_normalized_and_symmetric(self):
        k = np.asarray(gaussian_kernel_1d(9))
        assert k.shape == (9,)
        np.testing.assert_allclose(k.sum(), 1.0, rtol=1e-6)
        np.testing.assert_allclose(k, k[::-1])
        assert k[4] == k.max()

    def test_even_or_nonpositive_size_rejected(self):
        with pytest.raises(ValueError):
            gaussian_kernel_1d(8)
        with pytest.raises(ValueError):
            gaussian_kernel_1d(0)

    def test_size_one_is_identity(self):
        r = jnp.asarray(np.random.default_rng(0).random((16, 16)))
        out = splat_raster(r, gaussian_kernel_1d(1))
        np.testing.assert_allclose(np.asarray(out), np.asarray(r), rtol=1e-6)


class TestSplatRaster:
    def test_matches_direct_2d_oracle(self):
        rng = np.random.default_rng(1)
        r = rng.poisson(2.0, (32, 48)).astype(np.float64)
        out = splat_raster(jnp.asarray(r), gaussian_kernel_1d(9, dtype=jnp.float64))
        np.testing.assert_allclose(np.asarray(out), splat_oracle_np(r, 9), rtol=1e-10)

    def test_interior_mass_preserved(self):
        r = np.zeros((32, 32))
        r[16, 16] = 7.0  # interior point: whole 9x9 stamp stays inside
        out = splat_raster(jnp.asarray(r), gaussian_kernel_1d(9, dtype=jnp.float64))
        np.testing.assert_allclose(float(out.sum()), 7.0, rtol=1e-10)

    def test_int_raster_promoted_to_float(self):
        r = jnp.ones((8, 8), jnp.int32)
        out = splat_raster(r, gaussian_kernel_1d(3))
        assert jnp.issubdtype(out.dtype, jnp.floating)


class TestBinPointsSplat:
    def test_weighted_end_to_end_vs_oracle(self):
        lat, lon, w = _points()
        base = bin_points_window(
            jnp.asarray(lat), jnp.asarray(lon), WINDOW,
            weights=jnp.asarray(w), proj_dtype=jnp.float64, dtype=jnp.float64,
        )
        out = bin_points_splat(
            jnp.asarray(lat), jnp.asarray(lon), WINDOW,
            weights=jnp.asarray(w), proj_dtype=jnp.float64, dtype=jnp.float64,
        )
        np.testing.assert_allclose(
            np.asarray(out), splat_oracle_np(np.asarray(base), 9), rtol=1e-10
        )
        assert float(out.sum()) > 0

    def test_unweighted_defaults_to_counts(self):
        lat, lon, _ = _points(100, seed=3)
        out = bin_points_splat(
            jnp.asarray(lat), jnp.asarray(lon), WINDOW,
            proj_dtype=jnp.float64, dtype=jnp.float64,
        )
        base = bin_points_window(
            jnp.asarray(lat), jnp.asarray(lon), WINDOW,
            proj_dtype=jnp.float64,
        )
        # splat preserves total in-window mass up to edge bleed
        assert float(out.sum()) <= float(base.sum()) + 1e-9


class TestShardedSplat:
    def test_matches_single_device(self, devices):
        mesh = make_mesh(data=8, devices=devices)
        rng = np.random.default_rng(5)
        r = rng.poisson(1.5, (64, 32)).astype(np.float64)
        expected = splat_raster(
            jnp.asarray(r), gaussian_kernel_1d(9, dtype=jnp.float64)
        )
        got = splat_rowsharded(
            jnp.asarray(r), gaussian_kernel_1d(9, dtype=jnp.float64), mesh
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-10)

    def test_shard_too_small_for_halo_rejected(self, devices):
        mesh = make_mesh(data=8, devices=devices)
        r = jnp.zeros((16, 16))  # shard height 2 < half 4
        with pytest.raises(ValueError, match="halo"):
            splat_rowsharded(r, gaussian_kernel_1d(9), mesh)

    def test_height_not_divisible_rejected(self, devices):
        mesh = make_mesh(data=8, devices=devices)
        with pytest.raises(ValueError, match="divisible"):
            splat_rowsharded(jnp.zeros((30, 16)), gaussian_kernel_1d(3), mesh)
