"""Wavelet-synopsis subsystem tests: transform twins, the top-B error
contract, artifact round trips, serving semantics, early serving, and
crash recovery.

The anchors from docs/synopsis.md, in test form:

- every decoded cell differs from the exact count by <= the stamped
  ``max_err`` (the stamp IS the achieved error, not a loose bound);
- ``b=inf`` round-trips integer grids bit-exact;
- ``?synopsis=0`` and every ``z`` whose source level carries no
  synopsis are byte-identical to a store without synopses;
- exact and approximate bytes live in disjoint ETag namespaces and
  distinct cache keys, and the fleet router colocates both variants.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np
import pytest

from heatmap_tpu.serve import ServeApp, TileStore
from heatmap_tpu.synopsis.build import (DEFAULT_MAX_Z, HARD_MAX_Z, SCHEMA,
                                        SynopsisPair, build_pair, decode_pair,
                                        default_b, load_synopses,
                                        synopsis_path, verify_synopsis,
                                        write_synopses)
from heatmap_tpu.synopsis.transform import (grid_from_rows_np, haar2d_np,
                                            inv_haar2d_np)


def _sparse_grid(rng, zoom, nnz, vmax=50):
    """Random sparse integer level rows + the dense grid they imply."""
    n = 1 << zoom
    flat = rng.choice(n * n, size=nnz, replace=False)
    rows, cols = flat // n, flat % n
    values = rng.integers(1, vmax, size=nnz).astype(np.float64)
    return rows, cols, values, grid_from_rows_np(rows, cols, values, n)


class TestTransform:
    @pytest.mark.parametrize("zoom", [0, 1, 3, 6])
    def test_round_trip_is_bit_exact_for_integer_grids(self, zoom):
        rng = np.random.default_rng(7 + zoom)
        n = 1 << zoom
        grid = rng.integers(0, 1000, size=(n, n)).astype(np.float64)
        back = inv_haar2d_np(haar2d_np(grid))
        assert np.array_equal(back, grid)  # exact, not approx

    def test_rejects_non_square_and_non_power_of_two(self):
        with pytest.raises(ValueError, match="square"):
            haar2d_np(np.zeros((4, 8)))
        with pytest.raises(ValueError, match="power-of-two"):
            inv_haar2d_np(np.zeros((6, 6)))

    def test_jax_forward_matches_numpy_twin(self):
        from heatmap_tpu.synopsis.transform import haar2d_jax

        rng = np.random.default_rng(11)
        grid = rng.integers(0, 100, size=(16, 16)).astype(np.float64)
        np.testing.assert_array_equal(np.asarray(haar2d_jax(grid)),
                                      haar2d_np(grid))

    def test_jax_scatter_ignores_pad_lanes(self):
        """Bucketed-padded emission arrays (zero-weight pad lanes under
        a valid mask) must produce the same grid as the unpadded batch."""
        from heatmap_tpu.synopsis.transform import grid_from_rows_jax

        rng = np.random.default_rng(13)
        rows, cols, values, grid = _sparse_grid(rng, 4, 40)
        pad = 17
        prow = np.concatenate([rows, np.zeros(pad, np.int64)])
        pcol = np.concatenate([cols, np.zeros(pad, np.int64)])
        pval = np.concatenate([values, np.full(pad, 99.0)])
        valid = np.concatenate([np.ones(len(rows), bool),
                                np.zeros(pad, bool)])
        got = np.asarray(grid_from_rows_jax(prow, pcol, pval, 16,
                                            valid=valid))
        np.testing.assert_array_equal(got, grid)


class TestErrorContract:
    def test_stamp_is_the_achieved_error_across_b_sweep(self):
        """Property sweep: for every coefficient budget the stamped
        ``max_err`` equals the worst decoded-cell error exactly — the
        serving decoder runs the identical deterministic inverse."""
        rng = np.random.default_rng(42)
        for seed in range(4):
            rows, cols, values, grid = _sparse_grid(
                np.random.default_rng(seed), 5, 120)
            for b in (1, 4, 16, 64, 256, math.inf):
                idx, val, stamped = build_pair(rows, cols, values, 5, b=b)
                decoded = decode_pair(idx, val, 32)
                achieved = float(np.abs(decoded - grid).max())
                assert achieved == stamped  # not approx: same computation
                assert np.abs(np.maximum(decoded, 0.0) - grid).max() \
                    <= stamped  # the serve-side clamp never widens it
                if not math.isinf(b):
                    assert len(idx) <= b

    def test_b_inf_is_bit_exact(self):
        rng = np.random.default_rng(3)
        rows, cols, values, grid = _sparse_grid(rng, 5, 200)
        idx, val, stamped = build_pair(rows, cols, values, 5, b=math.inf)
        assert stamped == 0.0
        assert np.array_equal(decode_pair(idx, val, 32), grid)

    def test_build_is_deterministic(self):
        rows, cols, values, _ = _sparse_grid(np.random.default_rng(9),
                                             5, 150)
        a = build_pair(rows, cols, values, 5, b=20)
        b = build_pair(rows, cols, values, 5, b=20)
        assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
        assert a[2] == b[2]

    def test_hard_max_z_refusal(self):
        with pytest.raises(ValueError, match=str(HARD_MAX_Z)):
            build_pair([0], [0], [1.0], HARD_MAX_Z + 1)

    def test_default_b_floor_and_ratio(self):
        assert default_b(7) == 16
        assert default_b(800) == 100

    def test_decode_extras_are_exact_additions(self):
        """Delta overlays / provisional counts scatter-add ON TOP of the
        decoded grid — linearity keeps the stamped bound intact."""
        rows, cols, values, grid = _sparse_grid(np.random.default_rng(5),
                                                4, 30)
        idx, val, stamped = build_pair(rows, cols, values, 4, b=8)
        pair = SynopsisPair("all", "alltime", 4, 16, len(idx), stamped,
                            idx, val)
        extra = ([2, 2, 7], [3, 3, 1], [1.0, 2.0, 5.0])
        plain = pair.decode()
        overlaid = pair.decode(extra_rows=extra)
        expect = plain.copy()
        np.add.at(expect, ([2, 2, 7], [3, 3, 1]), [1.0, 2.0, 5.0])
        np.testing.assert_array_equal(overlaid, expect)
        truth = grid.copy()
        np.add.at(truth, ([2, 2, 7], [3, 3, 1]), [1.0, 2.0, 5.0])
        assert np.abs(overlaid - truth).max() <= stamped + 1e-12


def _level_cols(rng, zoom, pairs, nnz=80):
    """A finalized-shape level dict (string-column flavour) with one
    row block per (user, timespan) pair."""
    rs, cs, vs, us, ts = [], [], [], [], []
    for user, span in pairs:
        rows, cols, values, _ = _sparse_grid(rng, zoom, nnz)
        rs.append(rows)
        cs.append(cols)
        vs.append(values)
        us += [user] * nnz
        ts += [span] * nnz
    return {"zoom": zoom, "coarse_zoom": max(zoom - 2, 0),
            "row": np.concatenate(rs), "col": np.concatenate(cs),
            "value": np.concatenate(vs),
            "user": np.asarray(us), "timespan": np.asarray(ts)}


class TestArtifacts:
    def test_write_load_round_trip_and_verify(self, tmp_path):
        rng = np.random.default_rng(21)
        cols = _level_cols(rng, 5, [("all", "alltime"), ("u1", "year")])
        out = write_synopses(str(tmp_path), levels={5: cols})
        assert set(out) == {5}
        assert out[5]["pairs"] == 2
        path = synopsis_path(str(tmp_path), 5)
        assert os.path.exists(path) and verify_synopsis(path) is None
        loaded = load_synopses(str(tmp_path))
        assert sorted((p.user, p.timespan) for p in loaded[5]) == [
            ("all", "alltime"), ("u1", "year")]
        worst = 0.0
        for p in loaded[5]:
            sel = (cols["user"] == p.user) & (cols["timespan"] == p.timespan)
            grid = grid_from_rows_np(cols["row"][sel], cols["col"][sel],
                                     cols["value"][sel], 32)
            assert np.abs(p.decode() - grid).max() <= p.max_err
            worst = max(worst, p.max_err)
        assert out[5]["max_err"] == worst

    def test_max_z_gates_which_levels_get_synopses(self, tmp_path):
        rng = np.random.default_rng(22)
        levels = {5: _level_cols(rng, 5, [("all", "alltime")]),
                  7: _level_cols(rng, 7, [("all", "alltime")])}
        out = write_synopses(str(tmp_path), levels=levels, max_z=6)
        assert set(out) == {5}
        assert not os.path.exists(synopsis_path(str(tmp_path), 7))

    def test_verify_flags_torn_and_wrong_schema(self, tmp_path):
        torn = tmp_path / "synopsis-z05.npz"
        torn.write_bytes(b"\x00garbage not a zip")
        assert verify_synopsis(str(torn)) is not None
        wrong = tmp_path / "synopsis-z06.npz"
        np.savez(wrong, schema=np.asarray("other.v9"))
        detail = verify_synopsis(str(wrong))
        assert detail is not None and SCHEMA in detail
        assert load_synopses(str(tmp_path)) == {}  # both skipped


@pytest.fixture(scope="module")
def syn_store(tmp_path_factory):
    """One real batch job egressed through the arrays-synopsis sink:
    exact levels at zooms 7-10 plus synopsis artifacts for 7/8/9 (all
    < DEFAULT_MAX_Z; zoom-10 detail stays exact-only)."""
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("syn_store")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                            result_delta=2)
    with open_sink(f"arrays-synopsis:{root}/levels") as sink:
        run_job(open_source("synthetic:3000:7"), sink, config)
    assert DEFAULT_MAX_Z == 10  # fixture zoom choices assume it
    return f"arrays:{root}/levels"


def _busy_tile(layer, src_zoom, tile_zoom):
    """(x, y) of the tile covering the heaviest exact cell — guaranteed
    non-empty on both the exact and the synopsis path."""
    level = layer.levels[src_zoom]
    code = int(level.codes[int(np.argmax(level.values))])
    row = col = 0
    for bit in range(src_zoom):
        col |= ((code >> (2 * bit)) & 1) << bit
        row |= ((code >> (2 * bit + 1)) & 1) << bit
    shift = src_zoom - tile_zoom
    return col >> shift, row >> shift


class TestServing:
    def test_store_indexes_synopses_below_max_z(self, syn_store):
        layer = TileStore(syn_store).layer("default")
        assert sorted(layer.synopses) == [7, 8, 9]
        for view in layer.synopses.values():
            assert view.max_err >= 0.0 and not view.stale

    def test_decoded_level_respects_stamp_every_cell(self, syn_store):
        layer = TileStore(syn_store).layer("default")
        for zoom, view in layer.synopses.items():
            exact = layer.levels[zoom]
            ex = dict(zip(exact.codes.tolist(), exact.values.tolist()))
            ap = dict(zip(view.level.codes.tolist(),
                          view.level.values.tolist()))
            worst = max(abs(ex.get(c, 0.0) - ap.get(c, 0.0))
                        for c in set(ex) | set(ap))
            assert worst <= view.max_err + 1e-9

    def test_synopsis_tile_headers_etag_and_revalidation(self, syn_store):
        store = TileStore(syn_store)
        app = ServeApp(store)
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json"
        res = app.handle("GET", path + "?synopsis=1")
        status, ctype, body, etag, route, _ = res
        assert (status, route) == (200, "tiles")
        assert etag.startswith('"syn-')
        marker = res.headers["X-Heatmap-Synopsis"]
        view = layer.synopses[7]
        assert marker == f"max_err={view.max_err:.6g}"
        not_mod = app.handle("GET", path + "?synopsis=1",
                             if_none_match=etag)
        assert not_mod[0] == 304 and not_mod[2] == b""
        assert not_mod.headers["X-Heatmap-Synopsis"] == marker
        # Exact bytes never revalidate against a synopsis ETag and
        # vice versa: disjoint namespaces by construction.
        exact = app.handle("GET", path)
        assert exact[0] == 200 and not exact[3].startswith('"syn-')
        assert exact[3] != etag
        assert app.handle("GET", path, if_none_match=etag)[0] == 200
        assert app.handle("GET", path + "?synopsis=1",
                          if_none_match=exact[3])[0] == 200

    def test_exact_path_is_byte_identical_with_synopses_present(
            self, syn_store):
        store = TileStore(syn_store)
        app = ServeApp(store)
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json"
        plain = app.handle("GET", path)
        off = app.handle("GET", path + "?synopsis=0")
        assert tuple(off)[:5] == tuple(plain)[:5]  # cache marker aside
        assert getattr(off, "headers", None) is None
        # z whose source level carries no synopsis: ?synopsis=1 falls
        # through to exact bytes, exact ETag, no annotation.
        dx, dy = _busy_tile(layer, 10, 8)
        deep = f"/tiles/default/8/{dx}/{dy}.json"
        on = app.handle("GET", deep + "?synopsis=1")
        assert tuple(on)[:5] == tuple(app.handle("GET", deep))[:5]
        assert getattr(on, "headers", None) is None
        assert not on[3].startswith('"syn-')

    def test_synopsis_default_flag(self, syn_store):
        store = TileStore(syn_store)
        app = ServeApp(store, synopsis_default=True)
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json"
        assert app.handle("GET", path).headers is not None
        opted_out = app.handle("GET", path + "?synopsis=0")
        assert getattr(opted_out, "headers", None) is None
        # last value wins, per urllib convention
        assert app._synopsis_opt("synopsis=0&synopsis=1") is True
        assert ServeApp(store)._synopsis_opt("foo=1") is False

    def test_router_colocates_synopsis_with_exact(self):
        from heatmap_tpu.serve.router import route_key

        assert route_key("/tiles/default/4/3/5.json?synopsis=1") == \
            route_key("/tiles/default/4/3/5.json")
        assert route_key("/tiles/default/4/3/5.png") == \
            route_key("/tiles/default/4/3/5.json")

    def test_stats_carry_synopsis_state(self, syn_store):
        store = TileStore(syn_store)
        stats = store.stats()
        assert stats["synopsis_epoch"] == store.synopsis_epoch
        layer_stats = stats["layers"][store.layer_names()[0]]
        assert layer_stats["synopsis_zooms"] == [7, 8, 9]
        assert layer_stats["synopsis_stale"] is False


class TestEarlyServing:
    def test_provisional_publish_marks_stale_and_refresh_supersedes(
            self, syn_store):
        store = TileStore(syn_store)
        app = ServeApp(store)
        layer = store.layer("default")
        epoch0, gen0 = store.synopsis_epoch, store.generation
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json?synopsis=1"
        before = app.handle("GET", path)
        assert "stale" not in before.headers["X-Heatmap-Synopsis"]

        rows = ([1, 2], [3, 4], [5.0, 7.0])
        updated = store.publish_provisional(
            {(layer.user, layer.timespan): {7: rows, 8: rows}})
        assert updated == 2
        # synopsis tiles retire (epoch moved), exact tiles stay cached
        # (generation did not).
        assert store.synopsis_epoch > epoch0
        assert store.generation == gen0
        assert store.layer("default").synopses[7].stale
        assert store.stats()["layers"]["default"]["synopsis_stale"] is True
        during = app.handle("GET", path)
        assert "stale=1" in during.headers["X-Heatmap-Synopsis"]

        store.refresh_layers()  # the exact apply's supersession
        assert not store.layer("default").synopses[7].stale
        after = app.handle("GET", path)
        assert "stale" not in after.headers["X-Heatmap-Synopsis"]
        assert after[2] == before[2]  # overlay fully discarded

    def test_publish_ignores_unknown_pairs_and_zooms(self, syn_store):
        store = TileStore(syn_store)
        rows = ([0], [0], [1.0])
        assert store.publish_provisional(
            {("nobody", "never"): {7: rows}}) == 0
        assert store.publish_provisional(
            {("all", "alltime"): {6: rows}}) == 0


class TestRecovery:
    def test_sweep_quarantines_torn_synopses_in_current_base(
            self, tmp_path):
        from heatmap_tpu.delta.recover import sweep

        root = tmp_path / "store"
        bdir = root / "base-000001"
        bdir.mkdir(parents=True)
        (root / "CURRENT").write_text(json.dumps(
            {"schema": "heatmap-tpu.delta_store.v1", "base": "base-000001",
             "applied_through": 1, "config": None}))
        cols = _level_cols(np.random.default_rng(31), 5,
                           [("all", "alltime")])
        write_synopses(str(bdir), levels={5: cols})
        (bdir / "synopsis-z06.npz").write_bytes(b"not a zip at all")
        (bdir / "synopsis-z07.npz.tmp").write_bytes(b"crashed staging")

        result = sweep(str(root))
        got = {(i["reason"], os.path.basename(i["path"]))
               for i in result["quarantined"]}
        assert got == {("torn_synopsis", "synopsis-z06.npz"),
                       ("orphan_tmp", "synopsis-z07.npz.tmp")}
        assert all(i["kind"] == "synopsis" for i in result["quarantined"])
        # the healthy artifact survives in place and still verifies
        good = synopsis_path(str(bdir), 5)
        assert os.path.exists(good) and verify_synopsis(good) is None
        qdir = root / "quarantine"
        assert sorted(os.listdir(qdir)) == ["synopsis-z06.npz",
                                            "synopsis-z07.npz.tmp"]
        # idempotent: a second sweep finds a clean store
        assert sweep(str(root))["quarantined"] == []
