"""Streaming engine tests: decay semantics, sharded parity, resume.

BASELINE.md config 4 coverage; oracle is the pure-numpy
streaming.decayed_oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heatmap_tpu.ops import Window
from heatmap_tpu.parallel import make_mesh
from heatmap_tpu.streaming import (
    HeatmapStream,
    StreamConfig,
    decayed_oracle,
    run_stream,
)

WINDOW = Window(zoom=10, row0=320, col0=256, height=64, width=64)


def _timed_points(n_batches=5, n=400, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    t = 100.0
    for _ in range(n_batches):
        lat = rng.uniform(30.0, 52.0, n)
        lon = rng.uniform(-90.0, -68.0, n)
        out.append((t, lat, lon))
        t += rng.uniform(10.0, 2000.0)
    return out

def test_matches_oracle_f64():
    cfg = StreamConfig(window=WINDOW, half_life_s=600.0,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    stream = HeatmapStream(cfg)
    pts = _timed_points()
    for t, lat, lon in pts:
        stream.update(lat, lon, t)
    expected = decayed_oracle(WINDOW, pts, 600.0)
    np.testing.assert_allclose(stream.snapshot(), expected, rtol=1e-12)
    assert stream.n_batches == len(pts)


def test_no_decay_equals_plain_binning():
    cfg = StreamConfig(window=WINDOW, half_life_s=1e18,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    stream = HeatmapStream(cfg)
    pts = _timed_points(3)
    for t, lat, lon in pts:
        stream.update(lat, lon, t)
    no_decay = decayed_oracle(WINDOW, pts, 1e18)
    np.testing.assert_allclose(stream.snapshot(), no_decay, rtol=1e-12)
    assert stream.snapshot().sum() > 0


def test_decay_halves_after_half_life():
    cfg = StreamConfig(window=WINDOW, half_life_s=100.0,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    stream = HeatmapStream(cfg)
    lat, lon = np.array([41.0]), np.array([-80.0])
    stream.update(lat, lon, 0.0)
    total0 = stream.snapshot().sum()
    stream.update(np.empty(0), np.empty(0), 100.0)  # one half-life later
    np.testing.assert_allclose(stream.snapshot().sum(), total0 / 2, rtol=1e-12)


def test_time_going_backwards_rejected():
    stream = HeatmapStream(StreamConfig(window=WINDOW))
    stream.update(np.array([41.0]), np.array([-80.0]), 10.0)
    with pytest.raises(ValueError, match="backwards"):
        stream.update(np.array([41.0]), np.array([-80.0]), 5.0)


def test_pad_to_single_compile_and_overflow():
    cfg = StreamConfig(window=WINDOW, half_life_s=500.0, pad_to=512,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    stream = HeatmapStream(cfg)
    pts = _timed_points(4, n=400, seed=2)
    for t, lat, lon in pts:
        stream.update(lat, lon, t)
    expected = decayed_oracle(WINDOW, pts, 500.0)
    np.testing.assert_allclose(stream.snapshot(), expected, rtol=1e-12)
    with pytest.raises(ValueError, match="pad_to"):
        stream.update(np.zeros(513), np.zeros(513), 1e6)


def test_sharded_stream_matches_unsharded(devices):
    mesh = make_mesh(data=8, devices=devices)
    cfg = StreamConfig(window=WINDOW, half_life_s=700.0,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    sharded = HeatmapStream(cfg, mesh=mesh)
    pts = _timed_points(4, n=403, seed=5)  # odd n: exercises padding
    for t, lat, lon in pts:
        sharded.update(lat, lon, t)
    expected = decayed_oracle(WINDOW, pts, 700.0)
    np.testing.assert_allclose(sharded.snapshot(), expected, rtol=1e-12)
    # raster is genuinely row-sharded across the mesh
    shard_shapes = {s.data.shape for s in sharded.raster.addressable_shards}
    assert shard_shapes == {(WINDOW.height // 8, WINDOW.width)}


def test_checkpoint_resume_reproduces_stream():
    cfg = StreamConfig(window=WINDOW, half_life_s=300.0,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    pts = _timed_points(6, seed=9)
    full = HeatmapStream(cfg)
    for t, lat, lon in pts:
        full.update(lat, lon, t)

    first = HeatmapStream(cfg)
    for t, lat, lon in pts[:3]:
        first.update(lat, lon, t)
    ckpt = first.state_dict()

    resumed = HeatmapStream(cfg).load_state_dict(ckpt)
    for t, lat, lon in pts[3:]:
        resumed.update(lat, lon, t)
    np.testing.assert_allclose(resumed.snapshot(), full.snapshot(), rtol=1e-12)
    assert resumed.n_batches == full.n_batches


def test_run_stream_driver_filters_background():
    cfg = StreamConfig(window=WINDOW, half_life_s=1e18,
                       proj_dtype=jnp.float64, acc_dtype=jnp.float64)
    batches = [
        (
            0.0,
            {
                "latitude": np.array([41.0, 41.2]),
                "longitude": np.array([-80.0, -81.0]),
                "user_id": ["a", "b"],
                "source": ["gps", "background"],
                "timestamp": [None, None],
            },
        )
    ]
    seen = []
    stream = run_stream(HeatmapStream(cfg), batches,
                        on_batch=lambda s, t: seen.append(t))
    assert stream.snapshot().sum() == 1.0  # background row dropped
    assert seen == [0.0]


def test_restore_rejects_shifted_window(tmp_path):
    """A checkpoint written for one window origin must not restore into
    a same-shaped but shifted window (silent geographic misplacement
    under e.g. --auto-bounds over a file whose extent moved)."""
    import pytest

    from heatmap_tpu.ops import Window
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig
    from heatmap_tpu.utils import CheckpointManager

    win = Window(zoom=10, row0=256, col0=256, height=128, width=128)
    s = HeatmapStream(StreamConfig(window=win, half_life_s=10.0))
    s.update(np.full(10, 47.6), np.full(10, -122.3), 1.0)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    s.checkpoint(mgr)

    shifted = Window(zoom=10, row0=384, col0=256, height=128, width=128)
    s2 = HeatmapStream(StreamConfig(window=shifted, half_life_s=10.0))
    with pytest.raises(ValueError, match="window"):
        s2.restore(mgr)
    # Same origin restores fine.
    s3 = HeatmapStream(StreamConfig(window=win, half_life_s=10.0))
    s3.restore(mgr)
    assert s3.n_batches == 1


def test_restore_rejects_weighted_mode_flip(tmp_path):
    """A checkpoint recorded as weighted must not resume as counted
    (and vice versa) — the raster would blend value-sums and counts."""
    import pytest

    from heatmap_tpu.ops import Window
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig
    from heatmap_tpu.utils import CheckpointManager

    win = Window(zoom=10, row0=256, col0=256, height=128, width=128)
    cfg = StreamConfig(window=win, half_life_s=10.0)
    s = HeatmapStream(cfg)
    s.update(np.full(10, 47.6), np.full(10, -122.3), 1.0,
             weights=np.full(10, 3.0))
    mgr = CheckpointManager(str(tmp_path / "ck"))
    s.checkpoint(mgr, weighted=True)

    with pytest.raises(ValueError, match="weighted"):
        HeatmapStream(cfg).restore(mgr, weighted=False)
    s2 = HeatmapStream(cfg)
    s2.restore(mgr, weighted=True)
    assert s2.n_batches == 1
    # Checkpoints without a recorded mode (library callers, older
    # files) restore under either declaration.
    mgr2 = CheckpointManager(str(tmp_path / "ck2"))
    s.checkpoint(mgr2)
    HeatmapStream(cfg).restore(mgr2, weighted=False)
    HeatmapStream(cfg).restore(mgr2, weighted=True)
