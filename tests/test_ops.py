"""Tests for aggregation ops: dense histograms, sparse reduce, pyramids."""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heatmap_tpu.ops import (
    Window,
    bin_points_window,
    bin_rowcol_window,
    coarsen_raster,
    pyramid_from_raster,
    pyramid_sparse_morton,
    window_from_bounds,
    aggregate_keys,
)
from heatmap_tpu.tilemath import mercator, morton
import oracle


def _rand_points(n, seed=0, lat=(30.0, 60.0), lon=(-10.0, 30.0)):
    rng = np.random.default_rng(seed)
    return rng.uniform(*lat, n), rng.uniform(*lon, n)


# -- Window ----------------------------------------------------------------


def test_window_validation():
    Window(zoom=4, row0=0, col0=0, height=16, width=16)
    with pytest.raises(ValueError):
        Window(zoom=4, row0=8, col0=0, height=16, width=16)
    with pytest.raises(ValueError):
        Window(zoom=4, row0=0, col0=-1, height=4, width=4)


def test_window_from_bounds_covers_points():
    lats, lons = _rand_points(2000, seed=1)
    win = window_from_bounds((30.0, 60.0), (-10.0, 30.0), zoom=10, align_levels=3)
    assert win.aligned_to(3)
    row, col, valid = mercator.project_points(lats, lons, 10)
    assert bool(valid.all())
    r = np.asarray(row)
    c = np.asarray(col)
    assert (r >= win.row0).all() and (r < win.row0 + win.height).all()
    assert (c >= win.col0).all() and (c < win.col0 + win.width).all()


def test_window_pad_multiple_stays_in_grid():
    win = window_from_bounds((84.0, 85.0), (170.0, 179.9), zoom=6, pad_multiple=16)
    assert win.row0 + win.height <= 1 << 6
    assert win.col0 + win.width <= 1 << 6
    assert win.height % 16 == 0


def test_window_rejects_empty_and_polar_bounds():
    with pytest.raises(ValueError):
        Window(zoom=4, row0=0, col0=0, height=0, width=4)
    with pytest.raises(ValueError):
        Window(zoom=4, row0=0, col0=0, height=-8, width=4)
    # Bbox entirely poleward of the mercator edge covers no tiles.
    with pytest.raises(ValueError):
        window_from_bounds((86.0, 89.0), (10.0, 20.0), zoom=8)


def test_window_pad_uses_lcm_not_product():
    # align 2^3=8 with pad_multiple=16 -> quantum lcm=16, not 128.
    win = window_from_bounds(
        (52.4, 52.6), (13.3, 13.5), zoom=12, align_levels=3, pad_multiple=16
    )
    assert win.height % 16 == 0 and win.width % 16 == 0
    assert win.aligned_to(3)
    assert win.height <= 32 and win.width <= 32


def test_morton_encode_zoom_guard():
    with pytest.raises(ValueError):
        morton.morton_encode(np.int32(0), np.int32(0), dtype=jnp.int32, zoom=16)
    morton.morton_encode(np.int32(0), np.int32(0), dtype=jnp.int32, zoom=15)


# -- dense histogram -------------------------------------------------------


def test_bin_points_window_matches_numpy():
    lats, lons = _rand_points(10_000, seed=2)
    zoom = 10
    win = window_from_bounds((30.0, 60.0), (-10.0, 30.0), zoom=zoom)
    raster = np.asarray(bin_points_window(lats, lons, win))
    assert raster.sum() == 10_000

    expected = np.zeros(win.shape, np.int64)
    for la, lo in zip(lats, lons):
        r = int(oracle.row_from_latitude(la, zoom)) - win.row0
        c = int(oracle.column_from_longitude(lo, zoom)) - win.col0
        expected[r, c] += 1
    np.testing.assert_array_equal(raster, expected)


def test_bin_weighted_and_out_of_window_drop():
    win = Window(zoom=5, row0=8, col0=8, height=4, width=4)
    rows = np.array([8, 8, 9, 0, 31], np.int32)  # last two outside
    cols = np.array([8, 8, 11, 0, 31], np.int32)
    w = np.array([1.5, 2.5, 3.0, 100.0, 100.0], np.float32)
    raster = np.asarray(bin_rowcol_window(rows, cols, win, weights=w))
    assert raster.dtype == np.float32
    assert raster.sum() == pytest.approx(7.0)
    assert raster[0, 0] == pytest.approx(4.0)
    assert raster[1, 3] == pytest.approx(3.0)


def test_bin_respects_valid_mask():
    win = Window(zoom=8, row0=0, col0=0, height=8, width=8)
    rows = np.array([0, 1], np.int32)
    cols = np.array([0, 1], np.int32)
    valid = np.array([True, False])
    raster = np.asarray(bin_rowcol_window(rows, cols, win, valid=valid))
    assert raster.sum() == 1


def test_bin_points_jit_compatible():
    win = Window(zoom=10, row0=0, col0=0, height=64, width=64)
    lats = np.full(100, 84.5)
    lons = np.full(100, -179.0)

    fn = jax.jit(lambda la, lo: bin_points_window(la, lo, win))
    raster = np.asarray(fn(lats, lons))
    assert raster.sum() == 100


# -- pyramid (dense) -------------------------------------------------------


def test_coarsen_raster():
    r = jnp.arange(16, dtype=jnp.int32).reshape(4, 4)
    c = np.asarray(coarsen_raster(r))
    np.testing.assert_array_equal(c, [[10, 18], [42, 50]])
    with pytest.raises(ValueError):
        coarsen_raster(jnp.zeros((3, 4)))


def test_pyramid_preserves_totals_and_alignment():
    lats, lons = _rand_points(5000, seed=3)
    zoom, levels = 12, 5
    win = window_from_bounds((30.0, 60.0), (-10.0, 30.0), zoom=zoom, align_levels=levels)
    raster = bin_points_window(lats, lons, win)
    pyr = pyramid_from_raster(raster, levels)
    assert len(pyr) == levels + 1
    for lvl, level_raster in enumerate(pyr):
        assert int(level_raster.sum()) == 5000
        assert level_raster.shape == (win.height >> lvl, win.width >> lvl)

    # Level counts must equal direct binning at the coarser zoom
    # (the shift-pyramid == reference center-re-projection contract).
    for lvl in (1, 3, 5):
        sub_zoom = zoom - lvl
        sub_win = Window(
            zoom=sub_zoom,
            row0=win.row0 >> lvl,
            col0=win.col0 >> lvl,
            height=win.height >> lvl,
            width=win.width >> lvl,
        )
        direct = np.asarray(bin_points_window(lats, lons, sub_win))
        np.testing.assert_array_equal(np.asarray(pyr[lvl]), direct)


# -- sparse ----------------------------------------------------------------


def test_aggregate_keys_matches_counter():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 50, 1000).astype(np.int32)
    uniq, sums, n = aggregate_keys(keys)
    n = int(n)
    expected = collections.Counter(keys.tolist())
    assert n == len(expected)
    got = dict(zip(np.asarray(uniq[:n]).tolist(), np.asarray(sums[:n]).tolist()))
    assert got == {int(k): int(v) for k, v in expected.items()}
    # Sorted ascending, sentinel-padded.
    assert np.all(np.diff(np.asarray(uniq[:n])) > 0)
    assert np.all(np.asarray(uniq[n:]) == np.iinfo(np.int32).max)
    assert np.asarray(sums[n:]).sum() == 0


def test_aggregate_keys_weighted_valid_capacity():
    keys = np.array([5, 5, 3, 3, 3, 9], np.int32)
    w = np.array([1.0, 2.0, 10.0, 20.0, 30.0, 7.0], np.float32)
    valid = np.array([True, True, True, True, True, False])
    uniq, sums, n = aggregate_keys(keys, weights=w, valid=valid, capacity=4)
    assert int(n) == 2
    np.testing.assert_array_equal(np.asarray(uniq[:2]), [3, 5])
    np.testing.assert_allclose(np.asarray(sums[:2]), [60.0, 3.0])


def test_aggregate_keys_capacity_overflow_drops():
    keys = np.array([1, 2, 3, 4], np.int32)
    uniq, sums, n = aggregate_keys(keys, capacity=2)
    # n reports true uniques; only first `capacity` sorted keys materialize.
    assert int(n) == 4
    np.testing.assert_array_equal(np.asarray(uniq), [1, 2])


def test_aggregate_keys_jit():
    fn = jax.jit(lambda k: aggregate_keys(k, capacity=8))
    uniq, sums, n = fn(jnp.asarray(np.array([2, 2, 7], np.int32)))
    assert int(n) == 2


# -- sparse morton pyramid -------------------------------------------------


def test_pyramid_sparse_morton_matches_counters():
    rng = np.random.default_rng(5)
    zoom, levels = 12, 4
    rows = rng.integers(0, 1 << zoom, 3000).astype(np.int32)
    cols = rng.integers(0, 1 << zoom, 3000).astype(np.int32)
    codes = np.asarray(morton.morton_encode(rows, cols, dtype=jnp.int32))

    out = pyramid_sparse_morton(jnp.asarray(codes), levels=levels, capacity=3000)
    assert len(out) == levels + 1
    for lvl, (uniq, sums, n) in enumerate(out):
        n = int(n)
        expected = collections.Counter(
            zip((rows >> lvl).tolist(), (cols >> lvl).tolist())
        )
        assert n == len(expected)
        u = np.asarray(uniq[:n])
        s = np.asarray(sums[:n])
        dec_r, dec_c = morton.morton_decode(jnp.asarray(u))
        got = dict(
            zip(
                zip(np.asarray(dec_r).tolist(), np.asarray(dec_c).tolist()),
                s.tolist(),
            )
        )
        assert got == dict(expected)
        assert int(s.sum()) == 3000


@pytest.mark.slow
def test_pyramid_sparse_morton_adaptive_matches_fixed():
    """adaptive=True shrinks level arrays but the aggregates (and the
    true unique counts overflow detection relies on) are identical."""
    rng = np.random.default_rng(9)
    codes = jnp.asarray(rng.integers(0, 1 << 18, 5000), jnp.int64)
    fixed = pyramid_sparse_morton(codes, levels=6)
    adapt = pyramid_sparse_morton(codes, levels=6, adaptive=True)
    for (fk, fs, fn), (ak, as_, an) in zip(fixed, adapt):
        n = int(fn)
        assert int(an) == n
        np.testing.assert_array_equal(np.asarray(fk)[:n], np.asarray(ak)[:n])
        np.testing.assert_array_equal(np.asarray(fs)[:n], np.asarray(as_)[:n])
    assert adapt[-1][0].shape[0] < fixed[-1][0].shape[0]


def test_pyramid_sparse_morton_adaptive_keeps_overflow_detectable():
    """A per-level capacity smaller than the real unique count must
    still report the TRUE count under adaptive=True — the input slice
    may never drop real aggregates pre-reduction (that would falsify
    n_unique and silently truncate sums)."""
    rng = np.random.default_rng(10)
    # ~2000 distinct level-0 codes whose parents stay ~distinct.
    codes = jnp.asarray(rng.permutation(1 << 14)[:2000] * 4, jnp.int64)
    caps = [4096, 64]  # level-1 capacity far below the real uniques
    fixed = pyramid_sparse_morton(codes, levels=1, capacity=caps)
    adapt = pyramid_sparse_morton(codes, levels=1, capacity=caps,
                                  adaptive=True)
    true_n = int(fixed[1][2])
    assert true_n > 64  # the scenario is real
    assert int(adapt[1][2]) == true_n  # overflow stays detectable


def test_pyramid_sparse_morton_weighted_with_invalid():
    zoom = 6
    rows = np.array([1, 1, 2, 3], np.int32)
    cols = np.array([1, 1, 2, 3], np.int32)
    codes = morton.morton_encode(rows, cols, dtype=jnp.int32)
    w = np.array([1.0, 2.0, 4.0, 8.0], np.float32)
    valid = np.array([True, True, True, False])
    out = pyramid_sparse_morton(
        codes, weights=w, valid=valid, levels=zoom, capacity=4
    )
    # Top level: everything in one root tile, sum excludes invalid lane.
    uniq, sums, n = out[-1]
    assert int(n) == 1
    assert float(sums[0]) == pytest.approx(7.0)
    assert int(uniq[0]) == 0


def test_aggregate_keys_sentinel_reservation_documented():
    # intmax keys are reserved as sentinel and dropped; pinned behavior.
    uniq, sums, n = aggregate_keys(np.array([5, np.iinfo(np.int32).max], np.int32))
    assert int(n) == 1 and int(sums[0]) == 1


def test_window_from_bounds_rejects_impossible_alignment():
    with pytest.raises(ValueError):
        window_from_bounds((30, 60), (-10, 30), zoom=3, align_levels=5)


def test_pick_backend_weighted_large_window_routes_partitioned(monkeypatch):
    """On TPU, auto routes large-window WEIGHTED binning to the
    partitioned MXU path (340.6 ms vs 432.5 ms XLA scatter at the z15
    headline window, k=8, v5e-1 round-5 sweep — PERF_NOTES.md). The
    platform is faked: the routing decision is host-side and must not
    need a chip to be testable."""
    import types

    from heatmap_tpu.ops import histogram

    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **k: [types.SimpleNamespace(platform="tpu")])
    big = histogram.Window(zoom=15, row0=0, col0=0, height=1024, width=1280)
    assert big.height * big.width > histogram.PALLAS_AUTO_MAX_CELLS
    assert histogram._pick_backend("auto", big, weighted=True) == "partitioned"
    assert histogram._pick_backend("auto", big, weighted=False) == "partitioned"
    # Small windows keep the pallas route; explicit backends pass through.
    small = histogram.Window(zoom=10, row0=0, col0=0, height=64, width=64)
    assert histogram._pick_backend("auto", small, weighted=True) == "pallas"
    assert histogram._pick_backend("xla", big, weighted=True) == "xla"
