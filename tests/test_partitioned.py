"""Sort-partitioned MXU binning (ops.partitioned), interpret mode.

Every case is diffed bit-exact against the XLA scatter contract
(ops.histogram.bin_rowcol_window), including the lax.cond fallback for
hostile distributions.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from heatmap_tpu.ops import Window
from heatmap_tpu.ops.histogram import bin_rowcol_window
from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned

WINDOW = Window(zoom=12, row0=512, col0=256, height=1024, width=640)


def _diff(row, col, window=WINDOW, valid=None, **kw):
    row = jnp.asarray(row, jnp.int32)
    col = jnp.asarray(col, jnp.int32)
    expected = bin_rowcol_window(row, col, window, valid=valid)
    got = bin_rowcol_window_partitioned(
        row, col, window, valid=valid, interpret=True, **kw
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    return np.asarray(expected)


def test_clustered_mostly_good_chunks():
    rng = np.random.default_rng(0)
    n = 1 << 15
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    row[:500] = rng.integers(0, 4096, 500)  # sparse fringe + out-of-window
    col[:500] = rng.integers(0, 4096, 500)
    assert _diff(row, col).sum() > 0


@pytest.mark.parametrize("block_cells", [1 << 12, 1 << 14, 1 << 16])
def test_block_cells_sweep_bit_exact(block_cells):
    """Every supported block size (64/128/256 side) is bit-exact,
    including block-boundary straddles at that size's alignment."""
    rng = np.random.default_rng(6)
    n = 1 << 14
    row = np.concatenate([
        rng.integers(520, 560, n // 2),
        # dense run straddling this block size's boundary
        np.full(n // 2, 512 + (block_cells // WINDOW.width)),
    ])
    col = rng.integers(300, 500, n)
    _diff(row, col, block_cells=block_cells)


def test_bad_block_cells_rejected():
    rng = np.random.default_rng(7)
    row = rng.integers(520, 560, 256)
    col = rng.integers(300, 340, 256)
    for bad in (1 << 13, 100, 1 << 10):
        with pytest.raises(ValueError, match="block_cells"):
            bin_rowcol_window_partitioned(
                jnp.asarray(row, jnp.int32), jnp.asarray(col, jnp.int32),
                WINDOW, interpret=True, block_cells=bad,
            )


def test_uniform_triggers_fallback():
    """Uniform over the window makes most chunks straddle blocks; the
    cond fallback must still be bit-exact."""
    rng = np.random.default_rng(1)
    n = 1 << 14
    _diff(rng.integers(512, 1536, n), rng.integers(256, 896, n))


def test_all_out_of_window():
    rng = np.random.default_rng(2)
    assert _diff(
        rng.integers(0, 500, 300), rng.integers(0, 250, 300)
    ).sum() == 0


def test_tiny_and_empty():
    _diff(np.asarray([515, 516]), np.asarray([300, 301]))
    _diff(np.empty(0, np.int64), np.empty(0, np.int64))


def test_valid_mask():
    rng = np.random.default_rng(3)
    n = 4096
    valid = jnp.asarray(rng.random(n) < 0.5)
    _diff(rng.integers(515, 530, n), rng.integers(300, 330, n), valid=valid)


def test_single_block_window():
    w = Window(zoom=12, row0=512, col0=256, height=128, width=128)
    rng = np.random.default_rng(4)
    _diff(rng.integers(500, 660, 5000), rng.integers(250, 400, 5000),
          window=w)


def test_block_boundary_straddle():
    """Dense runs exactly on an aligned block boundary (cells 65535 and
    65536 of the window) exercise straddling-chunk bad-path routing."""
    w = WINDOW
    cells = np.concatenate([
        np.full(3000, (1 << 16) - 1),
        np.full(3000, 1 << 16),
        np.arange(6000) % (w.height * w.width),
    ])
    row = cells // w.width + w.row0
    col = cells % w.width + w.col0
    _diff(row, col)


def test_backend_plumbing_counts_and_weighted():
    rng = np.random.default_rng(5)
    row = jnp.asarray(rng.integers(500, 700, 1000), jnp.int32)
    col = jnp.asarray(rng.integers(280, 360, 1000), jnp.int32)
    valid = jnp.asarray(rng.random(1000) < 0.7)
    # Positive dispatch: backend="partitioned" through the public
    # entry forwards valid= and dtype= and matches the scatter path.
    via_backend = bin_rowcol_window(
        row, col, WINDOW, valid=valid, backend="partitioned",
        dtype=jnp.float32,
    )
    expected = bin_rowcol_window(row, col, WINDOW, valid=valid,
                                 dtype=jnp.float32)
    assert via_backend.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(via_backend),
                                  np.asarray(expected))
    # Weighted dispatch through the public entry (integer-valued f32
    # weights: order-independent sums, so exact equality holds).
    w = jnp.asarray(rng.integers(0, 8, 1000), jnp.float32)
    via_w = bin_rowcol_window(
        row, col, WINDOW, weights=w, valid=valid, backend="partitioned",
    )
    exp_w = bin_rowcol_window(row, col, WINDOW, weights=w, valid=valid)
    assert via_w.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(via_w), np.asarray(exp_w))


@pytest.mark.parametrize("streams", [2, 4, 8])
def test_streams_bit_exact_clustered(streams):
    """k-stream variant (batched row sorts, per-stream output slabs
    summed) must match the scatter contract exactly, including padding
    chunks landing in the trailing streams."""
    rng = np.random.default_rng(11)
    n = (1 << 15) + 777  # deliberately not a multiple of streams*chunk
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    row[:500] = rng.integers(0, 4096, 500)
    col[:500] = rng.integers(0, 4096, 500)
    assert _diff(row, col, streams=streams).sum() > 0


def test_streams_uniform_fallback_and_pileup():
    rng = np.random.default_rng(12)
    n = 1 << 14
    # Uniform over the whole window: mostly bad chunks -> in-jit
    # full-scatter fallback must reshape the stream matrix correctly.
    row = rng.integers(0, 4096, n)
    col = rng.integers(0, 4096, n)
    _diff(row, col, streams=4)
    # Single-cell pileup + out-of-window fringe.
    row2 = np.full(n, 600)
    col2 = np.full(n, 400)
    row2[: n // 8] = rng.integers(-100, 5000, n // 8)
    col2[: n // 8] = rng.integers(-100, 5000, n // 8)
    _diff(row2, col2, streams=4)


def test_clamp_streams_bounds_slab_memory():
    """streams must shrink for giant windows (the x8 default would OOM
    where streams=1 fits HBM) and stay untouched for measured configs."""
    from heatmap_tpu.ops.partitioned import (
        STREAM_SLAB_BUDGET, clamp_streams,
    )
    from heatmap_tpu.ops.histogram import Window

    # Headline-class window (8192^2 = 256 MiB slab): default untouched.
    z15 = Window(zoom=15, row0=0, col0=0, height=8192, width=8192)
    assert clamp_streams(8, z15) == 8
    # Near the int32 cell-id cap (~8 GiB of cells): forced to 1.
    giant = Window(zoom=21, row0=0, col0=0, height=1 << 16, width=1 << 15)
    assert clamp_streams(8, giant) == 1
    # Mid-size: partial clamp, and the budget is actually respected.
    mid = Window(zoom=18, row0=0, col0=0, height=1 << 14, width=1 << 14)
    k = clamp_streams(8, mid)
    assert 1 <= k < 8
    assert k * (1 << 28) * 4 <= STREAM_SLAB_BUDGET
    # Tiny windows never exceed the requested count.
    small = Window(zoom=10, row0=0, col0=0, height=256, width=256)
    assert clamp_streams(8, small) == 8


def test_streams_one_equals_flat_path():
    rng = np.random.default_rng(13)
    n = 1 << 14
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    a = _diff(row, col, streams=1)
    b = _diff(row, col, streams=8)
    np.testing.assert_array_equal(a, b)


def _diff_weighted(row, col, weights, window=WINDOW, valid=None, exact=True,
                   **kw):
    """Weighted twin of _diff. ``exact`` for integer-valued weights
    (order-independent f32 sums); otherwise allclose within f32
    reordering tolerance."""
    row = jnp.asarray(row, jnp.int32)
    col = jnp.asarray(col, jnp.int32)
    weights = jnp.asarray(weights, jnp.float32)
    expected = bin_rowcol_window(row, col, window, weights=weights,
                                 valid=valid)
    got = bin_rowcol_window_partitioned(
        row, col, window, weights=weights, valid=valid, interpret=True, **kw
    )
    assert got.dtype == jnp.float32
    if exact:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))
    else:
        # Summation-order difference grows with per-cell fan-in: a few
        # ulps of the cell sum (observed ~15 ulps at 100k-point
        # pileups), so the relative tolerance is the meaningful one.
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-4)
    return np.asarray(expected)


def test_weighted_clustered_bit_exact():
    rng = np.random.default_rng(20)
    n = (1 << 15) + 333  # not a multiple of chunk: exercises weight padding
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    row[:500] = rng.integers(0, 4096, 500)  # fringe + out-of-window
    col[:500] = rng.integers(0, 4096, 500)
    w = rng.integers(0, 16, n).astype(np.float32)
    assert _diff_weighted(row, col, w).sum() > 0


def test_weighted_uniform_fallback():
    """Hostile distribution routes to the weighted full-scatter
    fallback inside the cond; must still match exactly."""
    rng = np.random.default_rng(21)
    n = 1 << 14
    w = rng.integers(1, 4, n).astype(np.float32)
    _diff_weighted(rng.integers(512, 1536, n), rng.integers(256, 896, n), w)


def test_weighted_valid_mask_and_pileup():
    rng = np.random.default_rng(22)
    n = 1 << 14
    valid = jnp.asarray(rng.random(n) < 0.6)
    # Single-cell pileup: per-cell sum ~n*mean(w) stays far below 2^24.
    row = np.full(n, 600)
    col = np.full(n, 400)
    row[: n // 8] = rng.integers(-100, 5000, n // 8)
    col[: n // 8] = rng.integers(-100, 5000, n // 8)
    w = rng.integers(0, 8, n).astype(np.float32)
    _diff_weighted(row, col, w, valid=valid)


@pytest.mark.parametrize("streams", [2, 8])
def test_weighted_streams(streams):
    rng = np.random.default_rng(23)
    n = (1 << 14) + 77
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    w = rng.integers(0, 8, n).astype(np.float32)
    _diff_weighted(row, col, w, streams=streams)


def test_weighted_float_weights_close():
    """Arbitrary float weights: summation order differs from the
    scatter path, so the contract is allclose, not bit-equal."""
    rng = np.random.default_rng(24)
    n = 1 << 14
    row = rng.integers(520, 620, n)
    col = rng.integers(300, 500, n)
    w = rng.random(n).astype(np.float32) * 3.7
    _diff_weighted(row, col, w, exact=False)


def test_weighted_empty_and_zero_weights():
    _diff_weighted(np.empty(0, np.int64), np.empty(0, np.int64),
                   np.empty(0, np.float32))
    rng = np.random.default_rng(25)
    n = 4096
    out = _diff_weighted(rng.integers(520, 620, n),
                         rng.integers(300, 500, n),
                         np.zeros(n, np.float32))
    assert out.sum() == 0
