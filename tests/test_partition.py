"""Locality-aware Morton-range sharding (parallel/partition.py).

Three layers under test: the planner (deterministic quantile splits,
skew-resistant re-splitting, boundary-tile enumeration), the host-side
router (multiset-preserving scatter into per-shard segments), and the
end-to-end gate — a spatially partitioned job's blobs are byte-identical
to the uniform round-robin dispatch on every tested shape, including
weighted, retraction, and elastic-failover jobs.
"""

import os

import numpy as np
import pytest

from heatmap_tpu import obs
from heatmap_tpu.parallel import (
    PartitionPlan,
    plan_partition,
    plan_shards,
    route_emissions,
    run_job_elastic,
)
from heatmap_tpu.pipeline import BatchJobConfig, run_job
from heatmap_tpu.tilemath import split_boundary_codes_np

DZ = 12
SPACE = 1 << (2 * DZ)


def _rows(n=500, seed=0,
          users=("alice", "bob", "rt-bus7", "xscout", "carol")):
    rng = np.random.default_rng(seed)
    return [{
        "latitude": float(rng.uniform(40.0, 55.0)),
        "longitude": float(rng.uniform(-5.0, 15.0)),
        "user_id": users[int(rng.integers(0, len(users)))],
        "timestamp": 1_500_000_000_000 + int(rng.integers(0, 10**9)),
        "source": "gps" if rng.uniform() > 0.1 else "background",
    } for _ in range(n)]


class _ColSource:
    def __init__(self, rows):
        self.rows = rows

    def batches(self, batch_size):
        for i in range(0, len(self.rows), batch_size):
            chunk = self.rows[i:i + batch_size]
            out = {
                "latitude": [r["latitude"] for r in chunk],
                "longitude": [r["longitude"] for r in chunk],
                "user_id": [r["user_id"] for r in chunk],
                "timestamp": [r.get("timestamp") for r in chunk],
                "source": [r.get("source", "gps") for r in chunk],
            }
            if any("value" in r for r in chunk):
                out["value"] = [float(r.get("value", 1.0)) for r in chunk]
            yield out


def _cfg(**kw):
    # data_parallel=True + spatial_partition="morton" exercises the
    # range-sharded mesh route at test sizes the auto thresholds
    # deliberately route single-device.
    base = dict(detail_zoom=DZ, min_detail_zoom=6, data_parallel=True,
                spatial_partition="morton")
    base.update(kw)
    return BatchJobConfig(**base)


# -- planner ---------------------------------------------------------------


def test_plan_determinism_and_monotonicity():
    rng = np.random.default_rng(3)
    codes = rng.integers(0, SPACE, 50_000)
    a = plan_partition(codes, 8, detail_zoom=DZ, seed=5)
    b = plan_partition(codes, 8, detail_zoom=DZ, seed=5)
    assert a.splits == b.splits and a.fingerprint == b.fingerprint
    assert len(a.splits) == 7 and a.n_shards == 8
    assert list(a.splits) == sorted(a.splits)
    # A different seed samples differently but stays a valid plan.
    c = plan_partition(codes, 8, detail_zoom=DZ, seed=6)
    assert list(c.splits) == sorted(c.splits)
    # Ownership convention: a split opens the range to its right.
    s0 = a.splits[0]
    assert a.shard_of_codes(np.asarray([s0 - 1, s0, s0 + 1])).tolist() \
        == [0, 1, 1]


def test_plan_quantiles_balance_distinct_codes():
    """Quantile splits over distinct codes are balanced without any
    re-splitting — skew comes only from duplicate-code mass."""
    rng = np.random.default_rng(11)
    codes = rng.choice(SPACE, size=40_000, replace=False)
    plan = plan_partition(codes, 8, detail_zoom=DZ)
    assert plan.resplits == 0
    assert plan.skew_ratio <= 1.25
    assert not plan.degenerate


def test_resplit_bounds_pathological_hotspot_skew():
    """20% of the mass on ONE code collapses the naive quantile splits
    (duplicates denote empty ranges); the re-split loop peels the
    uniform tail back out and lands under the ISSUE's skew gate."""
    rng = np.random.default_rng(7)
    hot = np.full(10_000, 123_456, np.int64)
    cold = rng.choice(SPACE, size=40_000, replace=False)
    codes = np.concatenate([hot, cold])
    plan = plan_partition(codes, 8, detail_zoom=DZ, seed=1)
    assert plan.resplits >= 1
    assert plan.skew_ratio <= 2.0, plan.shard_mass
    assert not plan.degenerate


def test_degenerate_plans():
    # No samples, single shard, or one range owning ~all mass.
    assert plan_partition(np.asarray([], np.int64), 4,
                          detail_zoom=DZ).degenerate
    assert plan_partition(np.arange(100), 1, detail_zoom=DZ).degenerate
    one_code = np.full(5_000, 42, np.int64)
    assert plan_partition(one_code, 4, detail_zoom=DZ).degenerate
    # valid mask removes all mass -> degenerate, not a crash.
    assert plan_partition(np.arange(100), 4, detail_zoom=DZ,
                          valid=np.zeros(100, bool)).degenerate


def test_boundary_codes_match_brute_force():
    """A tile at level L straddles a split iff its first and last
    detail children land on different shards — checked independently
    through shard_of_codes for every level and random split set."""
    rng = np.random.default_rng(19)
    for trial in range(5):
        splits = np.sort(rng.integers(1, SPACE, 7))
        plan = PartitionPlan(detail_zoom=DZ, n_shards=8,
                             splits=tuple(int(s) for s in splits),
                             sampled_points=1, balance_factor=1.25,
                             shard_mass=(1.0,) * 8, resplits=0,
                             fingerprint="t")
        assert split_boundary_codes_np(splits, 0).size == 0
        for lvl in range(1, 7):
            got = set(plan.boundary_codes(lvl).tolist())
            cand = np.unique(splits >> np.int64(2 * lvl))
            lo = cand << np.int64(2 * lvl)
            hi = lo + (np.int64(1) << np.int64(2 * lvl)) - 1
            first = plan.shard_of_codes(lo)
            last = plan.shard_of_codes(hi)
            want = set(cand[first != last].tolist())
            assert got == want, (trial, lvl)
        total = plan.boundary_tiles_total(6)
        assert total == sum(len(plan.boundary_codes(v))
                            for v in range(1, 7))
        # Per level there are at most n_shards - 1 straddling tiles.
        assert all(len(plan.boundary_codes(v)) <= 7 for v in range(1, 7))


def test_route_emissions_round_trip():
    rng = np.random.default_rng(23)
    n = 4_096
    codes = rng.integers(0, SPACE, n)
    slots = rng.integers(0, 5, n).astype(np.int32)
    valid = rng.random(n) > 0.1
    w = rng.integers(1, 9, n).astype(np.float64)
    plan = plan_partition(codes, 8, detail_zoom=DZ)
    rc, rs, rv, rw, seg = route_emissions(plan, codes, slots, valid=valid,
                                          weights=w)
    assert rc.shape == (8 * seg,)
    # Multiset preservation: valid lanes survive exactly once.
    want = sorted(zip(codes[valid], slots[valid], w[valid]))
    got = sorted(zip(rc[rv], rs[rv], rw[rv]))
    assert got == want
    # Segment ownership: every valid lane sits in its shard's segment.
    sid = plan.shard_of_codes(rc[rv])
    assert np.array_equal(sid, np.flatnonzero(rv) // seg)
    # Bucketed padding: seg honors the bucket map.
    *_, seg2 = route_emissions(plan, codes, slots, valid=valid,
                               bucket=lambda x: 1 << int(np.ceil(
                                   np.log2(max(x, 1)))))
    assert seg2 >= seg and seg2 & (seg2 - 1) == 0


def test_route_emissions_empty_ranges():
    """Duplicate splits denote empty ranges: their segments stay fully
    padded and the round trip still preserves the multiset."""
    splits = (100, 100, 100)
    plan = PartitionPlan(detail_zoom=DZ, n_shards=4, splits=splits,
                         sampled_points=1, balance_factor=1.25,
                         shard_mass=(0.5, 0.0, 0.0, 0.5), resplits=0,
                         fingerprint="t")
    codes = np.asarray([5, 50, 99, 100, 101, SPACE - 1], np.int64)
    slots = np.zeros(6, np.int32)
    rc, rs, rv, _, seg = route_emissions(plan, codes, slots)
    assert sorted(rc[rv].tolist()) == sorted(codes.tolist())
    # Shards 1 and 2 (between duplicate splits) hold nothing.
    assert not rv[1 * seg:3 * seg].any()


# -- observability ---------------------------------------------------------


def test_partition_planned_event_and_metrics(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rng = np.random.default_rng(31)
    codes = rng.choice(SPACE, size=20_000, replace=False)
    obs.enable_metrics(True)
    obs.set_event_log(obs.EventLog(path))
    try:
        plan = plan_partition(codes, 8, detail_zoom=DZ, n_levels=6)
        assert obs.PARTITION_SKEW.value() == pytest.approx(
            plan.skew_ratio)
        assert obs.BOUNDARY_TILES.value() == plan.boundary_tiles_total(6)
    finally:
        log = obs.get_event_log()
        obs.set_event_log(None)
        log.close()
        obs.enable_metrics(False)
    [rec] = obs.read_events(path)
    assert rec["event"] == "partition_planned"
    assert rec["n_shards"] == 8 and len(rec["splits"]) == 7
    assert rec["fingerprint"] == plan.fingerprint
    assert rec["boundary_tiles"] == plan.boundary_tiles_total(6)
    assert not rec["degenerate"]


def test_dp_mesh_for_degenerate_plan_falls_back(tmp_path):
    """Satellite fix: a degenerate plan must NOT serialize the cascade
    on one shard — dispatch keeps the mesh, drops the plan, and leaves
    a backend_resolved audit record."""
    from heatmap_tpu.pipeline.batch import _dp_mesh, _dp_mesh_for

    cfg = _cfg()
    mesh = _dp_mesh(cfg)
    assert mesh is not None
    plan = plan_partition(np.full(5_000, 42, np.int64), 8, detail_zoom=DZ)
    assert plan.degenerate
    path = str(tmp_path / "events.jsonl")
    obs.set_event_log(obs.EventLog(path))
    try:
        assert _dp_mesh_for(mesh, cfg, 5_000, plan=plan) is mesh
    finally:
        log = obs.get_event_log()
        obs.set_event_log(None)
        log.close()
    recs = [r for r in obs.read_events(path)
            if r["event"] == "backend_resolved"]
    assert recs and recs[0]["resolved"] == "uniform-dp"
    assert recs[0]["spatial_partition"] == "morton"


# -- config surface --------------------------------------------------------


def test_spatial_partition_config_rejections():
    with pytest.raises(ValueError, match="spatial_partition"):
        BatchJobConfig(spatial_partition="hilbert")
    with pytest.raises(ValueError, match="data_parallel"):
        BatchJobConfig(spatial_partition="morton", data_parallel=False)
    # morton + adaptive_capacity now composes (the gspmd dispatch
    # routes on-device against traced splits); only the shard_map
    # oracle — whose routing is host-side and shape-coupled — still
    # rejects it at config time.
    with pytest.raises(ValueError, match="adaptive"):
        BatchJobConfig(spatial_partition="morton", data_parallel=True,
                       adaptive_capacity=True, dispatch="shard_map")
    # The composing modes construct fine.
    BatchJobConfig(spatial_partition="off")
    BatchJobConfig(spatial_partition="morton", data_parallel=True,
                   pad_bucketing="pow2")
    BatchJobConfig(spatial_partition="morton", data_parallel=True,
                   adaptive_capacity=True)


# -- elastic Morton shards -------------------------------------------------


def test_plan_shards_morton_ranges():
    ranges = [(0, 100), (100, 100), (100, SPACE)]
    plan = plan_shards(5, 3, "jfp", code_ranges=ranges)
    assert [s.index for s in plan] == [0, 1, 2]
    # Every Morton shard spans the FULL batch range; the code range is
    # the ownership filter.
    assert all(s.lo == 0 and s.hi == 5 for s in plan)
    assert [(s.code_lo, s.code_hi) for s in plan] == ranges
    assert len({s.fingerprint for s in plan}) == 3
    # Batch-mode fingerprints must not collide with Morton ones.
    batch = plan_shards(5, 3, "jfp")
    assert {s.fingerprint for s in plan}.isdisjoint(
        {s.fingerprint for s in batch})


def test_elastic_empty_range_shard_publishes_empty_partial(tmp_path):
    """An empty code range yields an empty partial (not a crash, not a
    missing manifest entry) and the merge of the remaining shards still
    reproduces the full job."""
    import threading

    from heatmap_tpu.parallel.elastic import ShardLineage, _make_executor

    rows = _rows(n=120, seed=13)
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)
    ranges = [(0, 1 << 20), (1 << 20, 1 << 20), (1 << 20, 1 << 20)]
    plan = plan_shards(1, 3, "jfp", code_ranges=ranges)
    execute = _make_executor(_ColSource(rows), cfg, 128, threading.Lock())
    lineage = ShardLineage(str(tmp_path / "lin"))
    for s in plan:
        levels, meta = execute(s)
        won, _ = lineage.publish(s, "h0", levels, meta)
        assert won
    # Shards 1 and 2 own empty ranges -> empty partials.
    merged = lineage.merge(plan)
    assert isinstance(merged, list)


def test_run_job_elastic_rejects_unknown_partition(tmp_path):
    with pytest.raises(ValueError, match="partition"):
        run_job_elastic(_ColSource(_rows(8)), None,
                        BatchJobConfig(detail_zoom=10, min_detail_zoom=8),
                        n_total=8, lineage_dir=str(tmp_path),
                        partition="hilbert")


# -- end-to-end byte equality ----------------------------------------------


def test_run_job_morton_byte_identical_small():
    """The acceptance gate at tier-1 size: a Morton-partitioned job's
    blobs equal the uniform dispatch byte-for-byte."""
    rows = _rows(n=800, seed=42)
    morton = run_job(_ColSource(rows), config=_cfg())
    off = run_job(_ColSource(rows), config=_cfg(spatial_partition="off"))
    assert morton == off and len(morton) > 0


@pytest.mark.slow
def test_run_job_morton_partitioned_backend_byte_identical():
    rows = _rows(n=2000, seed=9)
    morton = run_job(_ColSource(rows),
                     config=_cfg(cascade_backend="partitioned"))
    off = run_job(_ColSource(rows),
                  config=_cfg(cascade_backend="partitioned",
                              spatial_partition="off"))
    assert morton == off and len(morton) > 0


@pytest.mark.slow
def test_run_job_morton_weighted_integer_byte_identical():
    rng = np.random.default_rng(15)
    rows = _rows(n=1500, seed=15)
    for r in rows:
        r["value"] = float(rng.integers(1, 12))
    morton = run_job(_ColSource(rows), config=_cfg(weighted=True))
    off = run_job(_ColSource(rows),
                  config=_cfg(weighted=True, spatial_partition="off"))
    assert morton == off and len(morton) > 0


@pytest.mark.slow
def test_run_job_morton_pad_bucketing_byte_identical():
    """Routed per-shard segments hit the bucketed compile cache; the
    padding changes shapes only, never bytes."""
    rows = _rows(n=2000, seed=5)
    morton = run_job(_ColSource(rows), config=_cfg(pad_bucketing="pow2"))
    off = run_job(_ColSource(rows),
                  config=_cfg(pad_bucketing="pow2",
                              spatial_partition="off"))
    assert morton == off and len(morton) > 0


@pytest.mark.slow
def test_run_job_morton_clustered_hotspot_byte_identical():
    """An 80%-clustered set (the shape the planner exists for) still
    meets the byte gate."""
    rng = np.random.default_rng(77)
    rows = _rows(n=2000, seed=77)
    k = int(len(rows) * 0.8)
    for r in rows[:k]:
        r["latitude"] = float(47.6 + rng.normal(0, 0.05))
        r["longitude"] = float(-122.3 + rng.normal(0, 0.05))
    morton = run_job(_ColSource(rows), config=_cfg())
    off = run_job(_ColSource(rows), config=_cfg(spatial_partition="off"))
    assert morton == off and len(morton) > 0


@pytest.mark.slow
def test_retraction_delta_morton_byte_identical(tmp_path):
    """Retractions (delta/compute.py sign=-1) negate finalized levels
    AFTER the cascade, so the partitioned route must produce identical
    artifact files."""
    from heatmap_tpu.delta.compute import compute_delta

    rows = _rows(n=1200, seed=21)
    dirs = {}
    for name, sp in (("morton", "morton"), ("off", "off")):
        out = str(tmp_path / name)
        compute_delta(_ColSource(rows), out, _cfg(spatial_partition=sp),
                      sign=-1)
        dirs[name] = out

    def blob(d):
        return {f: open(os.path.join(d, f), "rb").read()
                for f in sorted(os.listdir(d))
                if os.path.isfile(os.path.join(d, f))}

    a, b = blob(dirs["morton"]), blob(dirs["off"])
    assert sorted(a) == sorted(b)
    assert all(a[k] == b[k] for k in a)


@pytest.mark.slow
def test_run_job_elastic_morton_byte_identical(tmp_path):
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.io.sources import SyntheticSource

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)
    out = {}
    for mode in ("batch", "morton"):
        d = str(tmp_path / mode)
        run_job_elastic(SyntheticSource(n=900, seed=7),
                        LevelArraysSink(d), cfg, batch_size=150,
                        lineage_dir=str(tmp_path / f"lin-{mode}"),
                        n_hosts=3, partition=mode)
        out[mode] = {f: open(os.path.join(d, f), "rb").read()
                     for f in sorted(os.listdir(d))
                     if os.path.isfile(os.path.join(d, f))}
    assert out["batch"] == out["morton"]
