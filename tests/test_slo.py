"""SLO engine tests: spec grammar, burn-rate math, breach edges,
freshness, the log-less observer path, and run-report folding."""

from __future__ import annotations

import pytest

from heatmap_tpu import obs
from heatmap_tpu.obs import slo


def _http(engine, ts, *, route="tiles", status=200, ms=5.0):
    engine.observe({"event": "http_request", "ts": ts, "route": route,
                    "status": status, "ms": ms})


class TestSpecGrammar:
    def test_defaults(self):
        spec = slo.parse_slo_spec("errs:error_rate")
        assert (spec.name, spec.kind) == ("errs", "error_rate")
        assert spec.target == 0.999
        assert spec.window_s == 300.0
        assert spec.route is None
        assert spec.budget == pytest.approx(0.001)

    def test_full_parse_with_route(self):
        spec = slo.parse_slo_spec(
            "tiles-fast:latency:threshold_ms=50,target=0.99,"
            "window_s=60,route=tiles")
        assert spec.threshold_ms == 50.0
        assert spec.target == 0.99
        assert spec.window_s == 60.0
        assert spec.route == "tiles"
        assert spec.describe() == {
            "name": "tiles-fast", "kind": "latency", "target": 0.99,
            "window_s": 60.0, "threshold_ms": 50.0, "route": "tiles"}

    def test_staleness_parse(self):
        spec = slo.parse_slo_spec("fresh:staleness:max_age_s=120")
        assert spec.max_age_s == 120.0

    @pytest.mark.parametrize("bad, match", [
        ("just-a-name", "want NAME:KIND"),
        ("x:availability", "unknown SLO kind"),
        ("x:latency", "threshold_ms"),
        ("x:staleness", "max_age_s"),
        ("x:error_rate:color=red", "unknown SLO param"),
        ("x:error_rate:target", "key=value"),
        ("x:error_rate:target=1.5", "target"),
        ("x:error_rate:window_s=0", "window_s"),
    ])
    def test_rejects(self, bad, match):
        with pytest.raises(ValueError, match=match):
            slo.parse_slo_spec(bad)


class TestBurnRate:
    def test_error_rate_math(self):
        engine = slo.SLOEngine(
            [slo.parse_slo_spec("e:error_rate:target=0.9,window_s=300")])
        now = 1000.0
        for i in range(8):
            _http(engine, now - i, status=200)
        for i in range(2):
            _http(engine, now - i, status=503)
        [st] = engine.evaluate(now=now)
        assert (st["total"], st["good"]) == (10, 8)
        assert st["compliance"] == pytest.approx(0.8)
        # budget 0.1, bad fraction 0.2 -> burn 2x
        assert st["burn_rate"] == pytest.approx(2.0)
        assert st["breaching"] is True

    def test_latency_threshold(self):
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "l:latency:threshold_ms=10,target=0.5,window_s=300")])
        now = 1000.0
        _http(engine, now, ms=5.0)
        _http(engine, now, ms=50.0)
        _http(engine, now, ms=None)  # unmeasured: excluded, not bad
        [st] = engine.evaluate(now=now)
        assert (st["total"], st["good"]) == (2, 1)
        assert st["burn_rate"] == pytest.approx(1.0)
        assert st["breaching"] is False  # burn must EXCEED 1.0

    def test_no_data_is_compliant(self):
        engine = slo.SLOEngine([slo.parse_slo_spec("e:error_rate")])
        [st] = engine.evaluate(now=1000.0)
        assert st["total"] == 0
        assert st["compliance"] == 1.0
        assert st["breaching"] is False

    def test_route_filter(self):
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "e:error_rate:target=0.9,route=tiles")])
        now = 1000.0
        _http(engine, now, route="tiles", status=200)
        _http(engine, now, route="healthz", status=500)  # filtered out
        [st] = engine.evaluate(now=now)
        assert (st["total"], st["good"]) == (1, 1)
        assert st["breaching"] is False

    def test_window_eviction(self):
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "e:error_rate:target=0.9,window_s=60")])
        now = 1000.0
        _http(engine, now - 120, status=503)  # outside the window
        _http(engine, now - 10, status=200)
        [st] = engine.evaluate(now=now)
        assert (st["total"], st["good"]) == (1, 1)
        assert st["breaching"] is False


class TestStaleness:
    def test_no_freshness_signal_is_ok(self):
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "f:staleness:max_age_s=60")])
        [st] = engine.evaluate(now=1000.0)
        assert st["breaching"] is False
        assert st["age_s"] is None

    def test_fresh_ok_then_stale_breaches(self):
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "f:staleness:max_age_s=60,target=0.5")])
        engine.observe({"event": "delta_applied", "ts": 990.0})
        [st] = engine.evaluate(now=1000.0)
        assert st["breaching"] is False
        assert st["age_s"] == pytest.approx(10.0)
        [st] = engine.evaluate(now=990.0 + 300.0)
        assert st["breaching"] is True
        # store_reload also counts as freshness, and only forward
        engine.observe({"event": "store_reload", "ts": 1280.0})
        engine.observe({"event": "delta_applied", "ts": 100.0})  # older
        [st] = engine.evaluate(now=1290.0)
        assert st["breaching"] is False
        assert st["age_s"] == pytest.approx(10.0)


class TestBreachEdges:
    def test_breach_event_on_rising_edges_only(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        obs.set_event_log(obs.EventLog(path))
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "e:error_rate:target=0.9,window_s=60")])
        slo.set_engine(engine)
        now = 1000.0
        _http(engine, now, status=503)
        engine.evaluate(now=now)          # rising edge -> one event
        engine.evaluate(now=now)          # still breaching -> no event
        engine.evaluate(now=now + 120.0)  # window empty -> cleared
        _http(engine, now + 130.0, status=503)
        engine.evaluate(now=now + 130.0)  # second rising edge
        obs.get_event_log().close()
        obs.set_event_log(None)
        breaches = [r for r in obs.read_events(path)
                    if r["event"] == "slo_breach"]
        assert len(breaches) == 2
        assert all(r["slo"] == "e" for r in breaches)
        assert breaches[0]["burn_rate"] == pytest.approx(10.0)


class TestObserverWiring:
    def test_emit_feeds_engine_without_event_log(self):
        """`serve --slo` without `--events`: emit returns None (nothing
        persisted) but the observer still sees every record."""
        engine = obs.install_specs(["e:error_rate:target=0.9"])
        assert obs.get_event_log() is None
        assert obs.emit("http_request", route="tiles", status=503,
                        ms=1.0) is None
        [st] = engine.evaluate()
        assert (st["total"], st["good"]) == (1, 0)
        assert st["breaching"] is True

    def test_install_specs_empty_clears_engine(self):
        obs.install_specs(["e:error_rate"])
        assert slo.get_engine() is not None
        assert obs.install_specs([]) is None
        assert slo.get_engine() is None
        assert obs.slo_status() is None

    def test_ingest_log_replays_finished_run(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        obs.set_event_log(obs.EventLog(path))
        obs.emit("http_request", route="tiles", status=200, ms=2.0)
        obs.emit("http_request", route="tiles", status=500, ms=2.0)
        obs.get_event_log().close()
        obs.set_event_log(None)
        engine = slo.SLOEngine([slo.parse_slo_spec(
            "e:error_rate:target=0.9,window_s=1e9")])
        assert engine.ingest_log(path) >= 2
        [st] = engine.evaluate()
        assert (st["total"], st["good"]) == (2, 1)


class TestReportFolding:
    def test_report_folds_trace_and_slo(self):
        from heatmap_tpu.obs import tracing
        from heatmap_tpu.obs.report import (build_run_report,
                                            format_run_report)

        tracing.enable_tracing()
        with tracing.span("run"):
            with tracing.span("ingest"):
                pass
        engine = obs.install_specs(["e:error_rate:target=0.9"])
        _http(engine, 0.0)  # ancient ts: evaluates as no-data -> ok
        report = build_run_report()
        assert report["trace"]["n_spans"] == 2
        assert report["trace"]["roots"][0]["name"] == "run"
        assert report["slo"]["ok"] is True
        text = format_run_report(report)
        assert "traces:" in text
        assert "slo " in text
