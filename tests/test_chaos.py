"""Fast chaos subset (tools/chaos_soak.py distilled for tier-1).

Two pins, selectable with ``-m chaos``:

1. **Byte identity** — a delta store built under a seeded fault plane
   (torn reads, failed sink publishes, torn journal appends, failed
   compaction publishes) converges to the same served bytes as a
   fault-free build of the same batches.
2. **Graceful serve degradation** — at the ``ServeApp.handle`` level:
   render faults yield stale 200s (warm cache) or typed 503s (cold),
   never a 500; ``/healthz`` flips to ``degraded`` and recovers on the
   next fresh render; a failed reload keeps the last-good index; an
   injected ``http.request`` fault is a typed 503.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from heatmap_tpu import delta, faults
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.pipeline import BatchJobConfig
from heatmap_tpu.serve import ServeApp, TileCache, TileStore

pytestmark = pytest.mark.chaos

CFG = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)

#: Count rules spaced inside each site's retry budget; scale=0 keeps
#: the backoffs sleepless in tier-1.
SPEC = ("seed=3,scale=0,source.read=20x2,sink.write=10x2,"
        "journal.append=4x2,compact.publish=2x2")


def _build(root, chaos=False):
    if chaos:
        faults.install_spec(SPEC)
    try:
        delta.apply_batch(root, SyntheticSource(n=200, seed=1), CFG,
                          batch_size=64)
        delta.apply_batch(root, SyntheticSource(n=150, seed=2), CFG,
                          batch_size=64)
        summary = delta.compact(root)
        return summary
    finally:
        faults.install(None)


class TestByteIdentity:
    def test_chaos_build_matches_clean_build(self, tmp_path):
        clean, hurt = str(tmp_path / "clean"), str(tmp_path / "hurt")
        s1 = _build(clean)
        plane_before = faults.get_plane()
        s2 = _build(hurt, chaos=True)
        assert faults.get_plane() is plane_before  # uninstalled after
        assert s1["base"] == s2["base"]
        a = delta.load_overlay_levels(clean)
        b = delta.load_overlay_levels(hurt)
        assert len(a) == len(b)
        for la, lb in zip(a, b):
            for col in ("row", "col", "value", "zoom"):
                np.testing.assert_array_equal(np.asarray(la[col]),
                                              np.asarray(lb[col]))

    def test_chaos_rules_actually_fired(self, tmp_path):
        faults.install_spec(SPEC)
        try:
            _ = delta.apply_batch(str(tmp_path / "s"),
                                  SyntheticSource(n=200, seed=1), CFG,
                                  batch_size=64)
            counts = faults.get_plane().counts()
        finally:
            faults.install(None)
        assert sum(counts.values()) >= 5
        assert {"source.read", "journal.append"} <= set(counts)


@pytest.fixture()
def app(tmp_path):
    root = str(tmp_path / "store")
    _build(root)
    store = TileStore(f"delta:{root}")
    return ServeApp(store, TileCache(max_bytes=8 << 20))


def _first_tile(app):
    for name, layer in sorted(app.store.layers.items()):
        if name == "default":
            continue
        for want, level in sorted(layer.levels.items()):
            z = want - layer.result_delta
            if z < 0:
                continue
            code = int(np.min(level.codes)) >> (2 * layer.result_delta)
            from heatmap_tpu.tilemath.morton import morton_decode_np

            rows, cols = morton_decode_np(np.asarray([code]))
            return name, z, int(cols[0]), int(rows[0])
    raise AssertionError("store has no servable tiles")


class TestServeDegradation:
    def test_cold_render_fault_is_typed_503(self, app):
        name, z, x, y = _first_tile(app)
        faults.install_spec("seed=1,scale=0,tile.render=1")
        try:
            status, _, body, _, route, cache = app.handle(
                "GET", f"/tiles/{name}/{z}/{x}/{y}.json")
        finally:
            faults.install(None)
        assert status == 503
        assert route == "tiles"
        assert "render failed" in json.loads(body)["error"]
        assert "render" in app.degraded_causes()

    def test_warm_cache_serves_stale_200(self, app):
        name, z, x, y = _first_tile(app)
        path = f"/tiles/{name}/{z}/{x}/{y}.json"
        status, _, fresh, _, _, cache = app.handle("GET", path)
        assert (status, cache) == (200, "miss")
        # Generation bump stales the entry; the replacing render fails.
        app.store.reload()
        faults.install_spec("seed=1,scale=0,tile.render=1")
        try:
            status, _, body, _, _, cache = app.handle("GET", path)
        finally:
            faults.install(None)
        assert (status, cache) == (200, "stale")
        assert body == fresh  # last-good bytes, verbatim
        assert app.degraded_causes().get("render") == "serving stale tiles"
        # Next fresh render heals the flag.
        status, _, body2, _, _, cache = app.handle("GET", path)
        assert (status, cache) == (200, "miss")
        assert body2 == fresh
        assert app.degraded_causes() == {}

    def test_healthz_degraded_then_recovers(self, app):
        name, z, x, y = _first_tile(app)
        path = f"/tiles/{name}/{z}/{x}/{y}.json"
        faults.install_spec("seed=1,scale=0,tile.render=1")
        try:
            assert app.handle("GET", path)[0] == 503
            status, _, body, _, _, _ = app.handle("GET", "/healthz")
        finally:
            faults.install(None)
        health = json.loads(body)
        assert status == 200
        assert health["status"] == "degraded"
        assert "render" in health["degraded"]
        assert app.handle("GET", path)[0] == 200  # fault budget spent
        health = json.loads(app.handle("GET", "/healthz")[2])
        assert health["status"] == "ok"
        assert "degraded" not in health

    def test_http_request_fault_is_typed_503(self, app):
        faults.install_spec("seed=1,scale=0,http.request=1")
        try:
            status, _, body, _, route, _ = app.handle("GET", "/healthz")
        finally:
            faults.install(None)
        assert (status, route) == (503, "error")
        assert json.loads(body)["error"] == "service unavailable"

    def test_failed_reload_keeps_last_good_index(self, app, monkeypatch):
        name, z, x, y = _first_tile(app)
        path = f"/tiles/{name}/{z}/{x}/{y}.json"
        assert app.handle("GET", path)[0] == 200
        gen = app.store.generation

        def boom(_initial=False):
            raise OSError("store root unreachable")

        monkeypatch.setattr(app.store, "reload", boom)
        status, _, body, _, route, _ = app.handle("POST", "/reload")
        assert (status, route) == (503, "reload")
        assert json.loads(body)["generation"] == gen
        assert app.store.generation == gen
        assert "reload" in app.degraded_causes()
        # The last-good index still serves (cache hit or re-render).
        assert app.handle("GET", path)[0] == 200
        monkeypatch.undo()
        status, _, body, _, _, _ = app.handle("POST", "/reload")
        assert status == 200
        assert app.degraded_causes() == {}

    def test_render_faults_never_500(self, app):
        """Sweep every tile under a heavy render-fault probability: each
        response is 200 or typed 503, and every tile converges."""
        faults.install_spec("seed=9,scale=0,tile.render=p0.5")
        statuses = set()
        try:
            name, z, x, y = _first_tile(app)
            path = f"/tiles/{name}/{z}/{x}/{y}.json"
            ok = False
            for _ in range(64):
                status = app.handle("GET", path)[0]
                statuses.add(status)
                if status == 200:
                    ok = True
                    break
        finally:
            faults.install(None)
        assert ok
        assert statuses <= {200, 503}
