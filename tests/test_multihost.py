"""Multi-host layer (parallel.multihost).

Two layers of evidence: the unit tests here pin the pieces
(deterministic process-shard math, the shard+merge algebra — per-host
cascade then blob merge must equal the global cascade, everything
linear in counts — and the single-process degradation contract), and
``test_multiproc_end_to_end`` executes the REAL runtime — k local
processes under ``jax.distributed`` with gloo CPU collectives running
the actual gather allgather and sharded ``all_to_all`` egress
(tools/multiproc_check.py). Only true DCN/ICI transport needs a pod.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from heatmap_tpu.parallel.multihost import (
    _merge_blob_values,
    gather_blobs,
    make_hybrid_mesh,
    process_shard_bounds,
    run_job_multihost,
    shard_source_rows,
)


def test_process_shard_bounds_partition():
    for n in (0, 1, 7, 64, 1001):
        for k in (1, 2, 3, 8):
            slices = [process_shard_bounds(n, k, i) for i in range(k)]
            # Contiguous, disjoint, covering, balanced within 1.
            assert slices[0][0] == 0 and slices[-1][1] == n
            for (a0, a1), (b0, b1) in zip(slices, slices[1:]):
                assert a1 == b0
            sizes = [b - a for a, b in slices]
            assert max(sizes) - min(sizes) <= 1


def test_process_shard_bounds_validates():
    with pytest.raises(ValueError):
        process_shard_bounds(10, 4, 4)


def test_shard_source_range_shardable():
    """Range-shardable sources (Cassandra/Cosmos) get this process's
    interleaved assignment instead of row slicing."""
    from heatmap_tpu.io.sources import CassandraSource
    from heatmap_tpu.parallel.multihost import shard_source

    src = CassandraSource()
    mine = shard_source(src, process_count=4, process_index=2)
    assert (mine.shard_index, mine.shard_count) == (2, 4)
    assert (src.shard_index, src.shard_count) == (0, 1)  # untouched
    owned = [i for i, _ in mine.my_ranges()]
    assert owned == list(range(2, src.config.n_ranges, 4))
    # Pre-sharded sources are a configuration error, not silent data loss.
    with pytest.raises(ValueError, match="already carries"):
        shard_source(mine, process_count=4, process_index=0)


def test_run_job_multihost_gather_rejects_columnar_sinks(tmp_path):
    """Explicit gather egress is blob-based; columnar sinks must be
    refused at submit time (sharded egress is the columnar path)."""
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.parallel.multihost import run_job_multihost

    with pytest.raises(ValueError, match="sharded"):
        run_job_multihost(SyntheticSource(n=10),
                          LevelArraysSink(str(tmp_path / "c")),
                          egress="gather")
    with pytest.raises(ValueError, match="egress"):
        run_job_multihost(SyntheticSource(n=10), egress="bogus")


def test_run_job_multihost_columnar_single_process(tmp_path):
    """Columnar sinks now work through run_job_multihost (the round-2
    refusal is lifted): single-process degrades to run_job, writing the
    same level files a plain columnar job writes."""
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8)
    src = SyntheticSource(n=500, seed=3)
    run_job_multihost(src, LevelArraysSink(str(tmp_path / "mh")), config=cfg)
    run_job(src, LevelArraysSink(str(tmp_path / "ref")), config=cfg)
    got = LevelArraysSink.load(str(tmp_path / "mh"))
    want = LevelArraysSink.load(str(tmp_path / "ref"))
    assert set(got) == set(want)
    for zoom in want:
        for col in ("row", "col", "value", "user", "timespan"):
            np.testing.assert_array_equal(got[zoom][col], want[zoom][col])


def test_shard_source_returns_none_for_plain_sources():
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.parallel.multihost import shard_source

    assert shard_source(SyntheticSource(n=10), 2, 0) is None


def test_shard_source_rows_covers_exactly():
    batches = [np.full(10, i) for i in range(7)]
    seen = []
    for i in range(3):
        seen += [int(b[0]) for b in shard_source_rows(
            iter(batches), n_total=70, batch_size=10,
            process_count=3, process_index=i,
        )]
    assert seen == list(range(7))


def test_make_hybrid_mesh_single_process_matches_make_mesh(devices):
    from heatmap_tpu.parallel import make_mesh

    mesh = make_hybrid_mesh(devices=devices)
    ref = make_mesh(devices=devices)
    assert mesh.shape == ref.shape
    assert list(mesh.devices.flat) == list(ref.devices.flat)


def test_gather_blobs_single_process_identity():
    blobs = {"all|alltime|3_1_2": json.dumps({"8_40_65": 2.0})}
    assert gather_blobs(blobs) is blobs


def test_merge_blob_values_sums_json_dicts():
    a = json.dumps({"t1": 1.0, "t2": 2.0})
    b = json.dumps({"t2": 3.0, "t3": 4.0})
    assert json.loads(_merge_blob_values(a, b)) == {
        "t1": 1.0, "t2": 5.0, "t3": 4.0
    }
    # Raw-dict form too (non-JSON sinks).
    assert _merge_blob_values({"t": 1}, {"t": 2}) == {"t": 3}


def test_merge_blob_values_rejects_non_numeric_collisions():
    """Anything but summable {tile: number} dicts at a merge point is
    corruption — loud, never last-process-wins (round-2 weak #6)."""
    with pytest.raises(ValueError, match="non-numeric"):
        _merge_blob_values({"t": "x"}, {"t": 1.0})
    with pytest.raises(ValueError, match="not mergeable"):
        _merge_blob_values(json.dumps([1, 2]), json.dumps({"t": 1.0}))
    # Disjoint keys never collide, so shape of the VALUE only matters
    # on actual collisions — including non-numeric new keys.
    assert _merge_blob_values({"a": 1}, {"b": 2}) == {"a": 1, "b": 2}
    assert _merge_blob_values({"a": 1}, {"b": "meta"}) == {
        "a": 1, "b": "meta"
    }


@pytest.mark.slow
def test_sharded_cascade_merge_equals_global():
    """Per-host run + blob merge == single global run (linearity)."""
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.pipeline.batch import _run_loaded, load_columns

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8)
    src = SyntheticSource(n=3000, seed=4)
    batch_size = 256
    global_blobs = run_job(src, config=cfg, batch_size=batch_size)

    k = 3
    merged: dict = {}
    for pi in range(k):
        lats, lons, users, stamps = [], [], [], []
        for batch in shard_source_rows(src.batches(batch_size),
                                       n_total=3000, batch_size=batch_size,
                                       process_count=k, process_index=pi):
            cols = load_columns(batch)
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            users.extend(cols["user_id"])
            stamps.extend(cols["timestamp"])
        if not lats or sum(len(a) for a in lats) == 0:
            continue
        local = _run_loaded(
            {
                "latitude": np.concatenate(lats),
                "longitude": np.concatenate(lons),
                "user_id": users,
                "timestamp": stamps,
            },
            cfg,
            as_json=True,
        )
        for key, val in local.items():
            merged[key] = (
                _merge_blob_values(merged[key], val) if key in merged else val
            )
    assert set(merged) == set(global_blobs)
    for key in global_blobs:
        assert json.loads(merged[key]) == pytest.approx(
            json.loads(global_blobs[key])
        )


@pytest.mark.slow
def test_sharded_weighted_merge_equals_global():
    """The multihost ingest path with config.weighted: per-host
    weighted runs merged via _merge_blob_values equal one global
    weighted run exactly (integer weights -> exact f64 sums; collisions
    sum across hosts just like counts)."""
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.pipeline.batch import _run_loaded, load_columns

    rng = np.random.default_rng(6)
    n = 2400
    lat = 47.6 + rng.normal(0, 0.3, n)
    lon = -122.3 + rng.normal(0, 0.4, n)
    users = [f"u{int(i)}" for i in rng.integers(0, 12, n)]
    value = rng.integers(0, 9, n).astype(np.float64)

    class _WSrc:
        def batches(self, batch_size):
            for lo in range(0, n, batch_size):
                hi = min(lo + batch_size, n)
                yield {
                    "latitude": lat[lo:hi], "longitude": lon[lo:hi],
                    "user_id": users[lo:hi], "source": [],
                    "timestamp": [], "value": value[lo:hi],
                }

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8, weighted=True)
    batch_size = 256
    global_blobs = run_job(_WSrc(), config=cfg, batch_size=batch_size)

    k = 3
    merged: dict = {}
    for pi in range(k):
        lats, lons, us, stamps, vals = [], [], [], [], []
        for batch in shard_source_rows(_WSrc().batches(batch_size),
                                       n_total=n, batch_size=batch_size,
                                       process_count=k, process_index=pi):
            cols = load_columns(batch)
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            us.extend(cols["user_id"])
            stamps.extend(cols["timestamp"])
            vals.append(cols["value"])
        if not lats or sum(len(a) for a in lats) == 0:
            continue
        local = _run_loaded(
            {
                "latitude": np.concatenate(lats),
                "longitude": np.concatenate(lons),
                "user_id": us,
                "timestamp": stamps,
                "value": np.concatenate(vals),
            },
            cfg,
            as_json=True,
        )
        for key, val in local.items():
            merged[key] = (
                _merge_blob_values(merged[key], val) if key in merged else val
            )
    assert set(merged) == set(global_blobs)
    for key in global_blobs:
        assert json.loads(merged[key]) == json.loads(global_blobs[key])


def test_blob_owner_deterministic_in_range():
    from heatmap_tpu.parallel.multihost import blob_owner

    keys = [f"u{i}|alltime|3_{i % 7}_{i % 5}" for i in range(1000)]
    owners = [blob_owner(k, 4) for k in keys]
    assert owners == [blob_owner(k, 4) for k in keys]  # stable
    assert set(owners) == {0, 1, 2, 3}  # every shard used
    assert all(0 <= o < 4 for o in owners)


def test_scatter_blobs_partition_merge_equals_gather():
    """Sharded egress algebra: per-host partition + owner-side merge
    yields disjoint shards whose union equals the full gather merge."""
    from heatmap_tpu.parallel.multihost import (
        blob_owner, merge_blob_parts, partition_blobs,
    )

    rng = np.random.default_rng(5)
    k = 3
    # Overlapping keys across hosts (straddling blobs) with numeric
    # JSON payloads that must SUM on collision.
    locals_ = []
    for host in range(k):
        blobs = {}
        for i in rng.integers(0, 40, 25):
            key = f"u{i % 6}|alltime|4_{i % 4}_{i % 3}"
            blobs[key] = json.dumps({f"9_{i}_{i}": float(host + 1)})
        locals_.append(blobs)

    want = merge_blob_parts(locals_)

    owned = []
    for owner in range(k):
        parts = [partition_blobs(loc, k)[owner] for loc in locals_]
        owned.append(merge_blob_parts(parts))
    # Disjoint at blob granularity, each key on its blob_owner shard...
    seen = {}
    for host, shard in enumerate(owned):
        for key in shard:
            assert key not in seen
            assert blob_owner(key, k) == host
            seen[key] = shard[key]
    # ...and the union IS the gather result.
    assert set(seen) == set(want)
    for key in want:
        assert json.loads(seen[key]) == json.loads(want[key])


def test_scatter_blobs_fake_transport_wiring():
    """scatter_blobs end to end with an injected transport simulating
    3 processes: every host receives exactly its owner shard."""
    from heatmap_tpu.parallel.multihost import (
        blob_owner, partition_blobs, scatter_blobs,
    )

    k = 3
    locals_ = [
        {f"u{j}|alltime|2_{j}_1": json.dumps({"7_1_1": 1.0 * (i + 1)})
         for j in range(6)}
        for i in range(k)
    ]
    # Phase 1: what every host would SEND (payloads[d] JSON of its
    # owner-d sub-dict) — precomputed so the fake transport can hand
    # host i row i of every sender.
    sent = [
        [json.dumps(p).encode() for p in partition_blobs(loc, k)]
        for loc in locals_
    ]
    results = []
    for i in range(k):
        transport = lambda payloads, i=i: [sent[s][i] for s in range(k)]
        results.append(
            scatter_blobs(locals_[i], process_count=k, transport=transport)
        )
    all_keys = set().union(*locals_)
    for i, owned in enumerate(results):
        assert set(owned) == {key for key in all_keys
                              if blob_owner(key, k) == i}
        for key, val in owned.items():
            # 3 hosts each contributed 1.0*(host+1) under the same
            # inner tile key -> summed to 6.0.
            assert json.loads(val) == {"7_1_1": 6.0}


@pytest.mark.slow
def test_scatter_levels_equals_global_columnar_run(tmp_path):
    """The VERDICT r2 'done' bar: per-host cascade + level scatter +
    per-host columnar writes reassemble to exactly the global columnar
    run, with no host ever holding the full result."""
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.pipeline.batch import _run_loaded, load_columns
    from heatmap_tpu.parallel.multihost import (
        _CaptureLevels, _levels_from_bytes, _levels_to_bytes,
        merge_level_parts, partition_levels,
    )

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8)
    src = SyntheticSource(n=3000, seed=9)
    batch_size = 256
    run_job(src, LevelArraysSink(str(tmp_path / "global")), config=cfg,
            batch_size=batch_size)
    want = LevelArraysSink.load(str(tmp_path / "global"))

    k = 3
    # Phase 1: per-host local cascades -> per-destination payloads
    # (through the real serialization, as the jax transport would).
    sent: list[list[bytes]] = []
    for pi in range(k):
        lats, lons, users, stamps = [], [], [], []
        for batch in shard_source_rows(src.batches(batch_size),
                                       n_total=3000, batch_size=batch_size,
                                       process_count=k, process_index=pi):
            cols = load_columns(batch)
            lats.append(cols["latitude"])
            lons.append(cols["longitude"])
            users.extend(cols["user_id"])
            stamps.extend(cols["timestamp"])
        cap = _CaptureLevels()
        if lats and sum(len(a) for a in lats):
            _run_loaded(
                {
                    "latitude": np.concatenate(lats),
                    "longitude": np.concatenate(lons),
                    "user_id": users,
                    "timestamp": stamps,
                },
                cfg, as_json=False, sink=cap,
            )
        sent.append([_levels_to_bytes(p)
                     for p in partition_levels(cap.levels, k)])

    # Phase 2: deliver + merge + per-host columnar write.
    for pi in range(k):
        owned = merge_level_parts(
            _levels_from_bytes(sent[s][pi]) for s in range(k)
        )
        LevelArraysSink(str(tmp_path / f"host{pi}")).write_levels(owned)

    # Reassemble the per-host shards and compare to the global run.
    for zoom, wlvl in want.items():
        rows = {c: [] for c in ("row", "col", "value", "user", "timespan")}
        for pi in range(k):
            got = LevelArraysSink.load(str(tmp_path / f"host{pi}"))
            if zoom in got:
                for c in rows:
                    rows[c].append(got[zoom][c])
        got_cols = {c: np.concatenate(rows[c]) for c in rows}
        assert len(got_cols["value"]) == len(wlvl["value"])
        # Order-insensitive compare: sort both sides the same way.
        def _order(c):
            return np.lexsort((c["col"], c["row"], c["user"], c["timespan"]))
        go, wo = _order(got_cols), _order(wlvl)
        for c in rows:
            np.testing.assert_array_equal(
                got_cols[c][go], np.asarray(wlvl[c])[wo]
            )


def test_run_job_multihost_weighted_single_process():
    """config.weighted flows through run_job_multihost's single-process
    fall-through (and the multi-process branch shares the same
    _run_loaded call, exercised shard-by-shard above)."""
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    rng = np.random.default_rng(8)
    n = 500
    lat = 47.6 + rng.normal(0, 0.2, n)
    lon = -122.3 + rng.normal(0, 0.2, n)

    class _WSrc:
        def batches(self, batch_size):
            yield {
                "latitude": lat, "longitude": lon,
                "user_id": ["u"] * n, "source": [], "timestamp": [],
                "value": np.full(n, 2.0),
            }

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, weighted=True)
    a = run_job_multihost(_WSrc(), config=cfg)
    b = run_job(_WSrc(), config=cfg)
    assert a == b and len(a) > 0


def test_run_job_multihost_single_process_falls_through():
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=9)
    src = SyntheticSource(n=1000, seed=1)
    assert run_job_multihost(src, config=cfg) == run_job(src, config=cfg)


@pytest.mark.slow
def test_multiproc_end_to_end():
    """REAL 2-process execution of the multihost layer: distributed
    init, process-sharded ingest, gather_blobs' framed allgather and
    scatter_blobs/scatter_levels' all_to_all over gloo CPU collectives,
    per-host sink shards reassembling to the single-process oracle
    (tools/multiproc_check.py — subprocesses, so the suite's own jax
    stays single-process)."""
    # The tool's --timeout is its TOTAL child budget; the outer
    # timeout only needs a teardown margin on top.
    r = subprocess.run(
        [sys.executable, "tools/multiproc_check.py", "--k", "2",
         "--n", "2000", "--timeout", "390"],
        capture_output=True, text=True, cwd=_REPO_ROOT, timeout=450,
        env=_multiproc_env(),
    )
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no output; stderr: {r.stderr[-1500:]}"
    verdict = json.loads(lines[-1])
    assert r.returncode == 0 and verdict["ok"], (
        f"multiproc check failed: {lines}\nstderr: {r.stderr[-1500:]}"
    )


def _multiproc_env():
    # The children force jax_platforms=cpu themselves; they only need
    # the repo (and the site dir that may hold the accelerator plugin)
    # importable.
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_slice_source_recuts_oversized_batches():
    """_SliceSource pins the shard assignment at the construction batch
    size and re-cuts locally when the bounded path re-reads at a
    smaller granularity — rows, order, and column alignment preserved."""
    from heatmap_tpu.parallel.multihost import _SliceSource

    class _Src:
        def batches(self, bs):
            for i in range(0, 250, bs):
                m = min(bs, 250 - i)
                yield {
                    "latitude": np.arange(i, i + m, dtype=np.float64),
                    "longitude": np.arange(i, i + m, dtype=np.float64),
                    "user_id": [f"u{j}" for j in range(i, i + m)],
                    "timestamp": [None] * m,
                }

    src = _SliceSource(_Src(), n_total=250, batch_size=100)
    out = list(src.batches(40))
    assert all(len(b["latitude"]) <= 40 for b in out)
    lats = np.concatenate([b["latitude"] for b in out])
    np.testing.assert_array_equal(lats, np.arange(250, dtype=np.float64))
    users = [u for b in out for u in b["user_id"]]
    assert users == [f"u{j}" for j in range(250)]
    # At or above the pinned size: batches pass through untouched.
    passthrough = list(src.batches(100))
    assert [len(b["latitude"]) for b in passthrough] == [100, 100, 50]


@pytest.mark.slow
def test_run_job_multihost_bounded_single_process_matches():
    """max_points_in_flight routes the single-process fallthrough
    through run_job's bounded path — blobs equal the unbounded run."""
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.parallel.multihost import run_job_multihost
    from heatmap_tpu.pipeline import BatchJobConfig

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=7)
    want = run_job_multihost(SyntheticSource(n=2000, seed=3), config=cfg,
                             batch_size=256, max_points_in_flight=0)
    got = run_job_multihost(SyntheticSource(n=2000, seed=3), config=cfg,
                            batch_size=256, max_points_in_flight=300)
    assert got == want and len(got) > 0


@pytest.mark.slow
def test_multiproc_skew_exchange():
    """REAL 4-process gloo run of the skew-proof byte exchange: one
    payload 100x the rest passes under a max_bytes the old dense
    (k, global-max) frame would have violated, with chunked ppermute
    rounds bounding every collective buffer (VERDICT r3 weak #5)."""
    r = subprocess.run(
        [sys.executable, "tools/multiproc_check.py", "--skew-only",
         "--k", "4", "--timeout", "300"],
        capture_output=True, text=True, cwd=_REPO_ROOT, timeout=360,
        env=_multiproc_env(),
    )
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no output; stderr: {r.stderr[-1500:]}"
    verdict = json.loads(lines[-1])
    assert r.returncode == 0 and verdict["ok"], (
        f"skew exchange failed: {lines}\nstderr: {r.stderr[-1500:]}"
    )


def test_never_beating_host_caught_at_first_boundary():
    """The detection gap pinned by the satellite: a host whose gauge
    sample went STALE is caught by the age map alone, but a host that
    NEVER heartbeat has no sample to go stale — without ``expected`` it
    is invisible, and with ``expected`` it is flagged (age=inf) at the
    first phase boundary instead of hanging the job."""
    import time as _time

    from heatmap_tpu import obs
    from heatmap_tpu.parallel.multihost import (StragglerTimeout,
                                                check_heartbeats)

    obs.enable_metrics(True)
    try:
        now = _time.time()
        obs.heartbeat("join", process=0)
        obs.heartbeat("join", process=1)
        # Hosts 0 and 1 beat; host 2 never does.

        # Observed-hosts-only semantics: everything fresh, no straggler
        # — the never-beating host is invisible.
        ages = check_heartbeats(5.0, now=now)
        assert set(ages) == {"0", "1"}

        # expected= closes the gap at the first boundary, with age=inf.
        with pytest.raises(StragglerTimeout) as ei:
            check_heartbeats(5.0, now=now, expected=[0, 1, 2])
        assert ei.value.stale == {"2": float("inf")}

        # Contrast: a host that DID beat and then went silent is the
        # ordinary stale case, caught without expected=.
        with pytest.raises(StragglerTimeout) as ei:
            check_heartbeats(5.0, now=now + 10.0)
        assert set(ei.value.stale) == {"0", "1"}
    finally:
        obs.enable_metrics(False)


def test_check_heartbeats_expected_matches_beaten_hosts():
    """expected= is a no-op when every expected label has beaten."""
    from heatmap_tpu import obs
    from heatmap_tpu.parallel.multihost import check_heartbeats

    obs.enable_metrics(True)
    try:
        for p in range(3):
            obs.heartbeat("join", process=p)
        ages = check_heartbeats(5.0, expected=[0, 1, 2])
        assert set(ages) == {"0", "1", "2"}
    finally:
        obs.enable_metrics(False)
