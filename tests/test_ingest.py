"""Continuous-ingest subsystem tests (heatmap_tpu/ingest/ +
pipeline/bucketing.py).

The loop invariants the subsystem stands on:

- **Byte neutrality of bucketed padding** — pow2/geometric padded runs
  emit blobs byte-identical to exact padding (pad lanes are masked and
  decode truncates to real unique counts).
- **Compile bound** — N ticks of N distinct batch sizes incur at most
  bucket-count cascade compiles, asserted via the bucketing cache's
  signature mirror of the jit key.
- **Back-pressure** — a slow consumer bounds how far the producer can
  read ahead (queue depth + one in flight).
- **Watermark monotonicity** — out-of-order micro-batches never move
  the event-time watermark backwards.
- **Crash-mid-tick recovery** — a fault storm that kills an apply
  between artifact write and journal append heals byte-identical
  through delta/recover.py on the re-run, exactly once per batch.

Tier-1: CPU backend, small shapes, no network.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from heatmap_tpu import delta, faults, ingest, obs
from heatmap_tpu.delta.compute import ColumnsSource
from heatmap_tpu.pipeline import BatchJobConfig, bucketing, run_batch
from heatmap_tpu.serve.store import TileStore

from test_delta import _collect_docs


def _rows(n, seed=0, t0=1.5e9, users=4):
    rng = np.random.default_rng(seed)
    return [
        {"latitude": float(la), "longitude": float(lo),
         "user_id": f"u{i % users}", "timestamp": t0 + i, "source": "gps"}
        for i, (la, lo) in enumerate(zip(
            rng.uniform(37.0, 37.2, n), rng.uniform(-122.2, -122.0, n)))
    ]


def _cols(n, seed=0, t0=1.5e9, users=4):
    rng = np.random.default_rng(seed)
    return {
        "latitude": rng.uniform(37.0, 37.2, n),
        "longitude": rng.uniform(-122.2, -122.0, n),
        "user_id": [f"u{i % users}" for i in range(n)],
        "source": ["gps"] * n,
        "timestamp": [t0 + i for i in range(n)],
    }


class TestBucketSize:
    def test_exact_is_identity(self):
        for n in (0, 1, 7, 4096, 100_000):
            assert bucketing.bucket_size(n, "exact") == n

    def test_min_bucket_floor(self):
        assert bucketing.bucket_size(1, "pow2") == bucketing.DEFAULT_MIN_BUCKET
        assert bucketing.bucket_size(10, "pow2", min_bucket=64) == 64
        assert bucketing.bucket_size(64, "geometric", min_bucket=64) == 64

    def test_pow2_rounds_up(self):
        assert bucketing.bucket_size(4097, "pow2") == 8192
        assert bucketing.bucket_size(8192, "pow2") == 8192
        assert bucketing.bucket_size(8193, "pow2") == 16384

    def test_geometric_ladder_minimal_and_covering(self):
        """Every rung covers its inputs and is the MINIMAL such rung."""
        mb = 1 << 12
        for n in (4097, 5000, 5120, 5121, 9000, 123_457):
            size = bucketing.bucket_size(n, "geometric", min_bucket=mb)
            assert size >= n
            # the next rung down must NOT cover n
            import math
            k = round(math.log(size / mb) / math.log(
                bucketing.GEOMETRIC_RATIO))
            if k > 0:
                prev = int(math.ceil(
                    mb * bucketing.GEOMETRIC_RATIO ** (k - 1)))
                assert prev < n

    def test_geometric_tighter_than_pow2(self):
        """The 1.25x ladder wastes less than pow2 on a mid-bucket size."""
        n = 100_000
        g = bucketing.bucket_size(n, "geometric")
        p = bucketing.bucket_size(n, "pow2")
        assert n <= g <= p

    def test_zero_and_unknown_mode(self):
        assert bucketing.bucket_size(0, "pow2") == 0
        with pytest.raises(ValueError, match="unknown pad_bucketing"):
            bucketing.bucket_size(5, "nope")

    def test_bucket_slots_pow2(self):
        assert bucketing.bucket_slots(1) == 2
        assert bucketing.bucket_slots(3) == 4
        assert bucketing.bucket_slots(64) == 64
        assert bucketing.bucket_slots(65) == 128

    def test_config_validation(self):
        with pytest.raises(ValueError, match="unknown pad_bucketing"):
            BatchJobConfig(pad_bucketing="nope")
        with pytest.raises(ValueError, match="pad_bucket_min"):
            BatchJobConfig(pad_bucket_min=0)


class TestByteNeutrality:
    BASE = dict(detail_zoom=10, min_detail_zoom=5, result_delta=3)

    def test_bucketed_blobs_byte_identical(self):
        rows = _rows(700, seed=1)
        blobs = {}
        for mode in bucketing.BUCKETING_MODES:
            cfg = BatchJobConfig(**self.BASE, pad_bucketing=mode,
                                 pad_bucket_min=1 << 9)
            blobs[mode] = run_batch(rows, config=cfg, as_json=False)
        assert blobs["pow2"] == blobs["exact"]
        assert blobs["geometric"] == blobs["exact"]
        assert len(blobs["exact"]) > 4  # non-trivial pyramid

    def test_weighted_path_byte_identical(self):
        rows = [{**r, "value": float(1 + i % 3)}
                for i, r in enumerate(_rows(400, seed=2))]
        out = {}
        for mode in ("exact", "pow2"):
            cfg = BatchJobConfig(**self.BASE, weighted=True,
                                 pad_bucketing=mode, pad_bucket_min=1 << 9)
            out[mode] = run_batch(rows, config=cfg, as_json=False)
        assert out["pow2"] == out["exact"]


class TestCompileBound:
    def test_n_distinct_sizes_at_most_bucket_count_compiles(self):
        """N ticks of N distinct batch sizes reuse compilations: misses
        (the jit-cache mirror) are bounded by the number of distinct
        buckets, not the number of distinct sizes."""
        cfg = BatchJobConfig(detail_zoom=9, min_detail_zoom=5,
                             result_delta=3, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        sizes = [130, 190, 220, 250, 300, 420, 510, 600]
        buckets = {bucketing.bucket_size(s, "pow2", 1 << 8) for s in sizes}
        assert len(buckets) < len(sizes)  # the test must exercise reuse
        bucketing.reset_cache_stats()
        for i, s in enumerate(sizes):
            # same 4-user set every tick: the slot count stays stable,
            # so the only compile pressure is the batch size
            run_batch(_rows(s, seed=10 + i), config=cfg, as_json=False)
        stats = bucketing.cache_stats()
        assert stats["misses"] <= len(buckets)
        assert stats["hits"] == len(sizes) - stats["misses"]

    def test_exact_mode_compiles_per_size(self):
        """Control: exact padding's signature count grows with every
        distinct size — the regression the buckets exist to stop."""
        cfg = BatchJobConfig(detail_zoom=9, min_detail_zoom=5,
                             result_delta=3)
        sizes = (60, 61, 62)
        bucketing.reset_cache_stats()
        for i, s in enumerate(sizes):
            run_batch(_rows(s, seed=20 + i), config=cfg, as_json=False)
        assert bucketing.cache_stats()["misses"] == len(sizes)


class TestRunTicks:
    def test_synchronous_when_no_depth(self):
        seen = []
        stats = ingest.run_ticks(
            iter("abc"), lambda item, ctx: seen.append((item, ctx.index)))
        assert seen == [("a", 0), ("b", 1), ("c", 2)]
        assert stats == {"ticks": 3, "max_queue_depth": 0}

    def test_backpressure_bounds_producer_readahead(self):
        """A slow consumer blocks the producer: at every tick the
        source has yielded at most consumed + depth + 1 items (queue
        resident + the one the producer holds in put)."""
        depth = 2
        produced = [0]

        def source():
            for i in range(12):
                produced[0] += 1
                yield i

        violations = []

        def slow_tick(item, ctx):
            # let the producer run ahead as far as the queue allows
            deadline = time.monotonic() + 0.3
            while produced[0] < min(12, item + 1 + depth + 1) \
                    and time.monotonic() < deadline:
                threading.Event().wait(0.005)
            ahead = produced[0] - (item + 1)
            if ahead > depth + 1:
                violations.append((item, produced[0]))

        stats = ingest.run_ticks(source(), slow_tick, queue_depth=depth)
        assert stats["ticks"] == 12
        assert not violations, f"producer outran back-pressure: {violations}"
        assert stats["max_queue_depth"] <= depth

    def test_producer_error_propagates(self):
        def bad_source():
            yield 1
            raise OSError("source died")

        done = []
        with pytest.raises(OSError, match="source died"):
            ingest.run_ticks(bad_source(),
                             lambda item, ctx: done.append(item),
                             queue_depth=2)
        assert done == [1]

    def test_tick_error_stops_producer(self):
        produced = [0]

        def source():
            for i in range(1000):
                produced[0] += 1
                yield i

        def boom(item, ctx):
            raise RuntimeError("tick failed")

        with pytest.raises(RuntimeError, match="tick failed"):
            ingest.run_ticks(source(), boom, queue_depth=2)
        assert produced[0] < 1000  # producer did not drain the source

    def test_depth_validation(self):
        with pytest.raises(ValueError, match="queue_depth"):
            ingest.run_ticks(iter([]), lambda i, c: None, queue_depth=0)


@pytest.fixture()
def event_capture():
    """Collect emitted events via the observer hook (no log file)."""
    from heatmap_tpu.obs import events as events_mod

    records = []
    events_mod._observer = records.append
    yield records
    events_mod._observer = None


class TestIngestLoop:
    CFG = dict(detail_zoom=9, min_detail_zoom=5, result_delta=3)

    def test_watermark_monotonic_under_out_of_order_batches(
            self, tmp_path, event_capture):
        """Micro-batches arriving with DECREASING event time never move
        the watermark backwards: it is the monotonic max."""
        cols = _cols(300, seed=3, t0=2.0e9)
        # reverse event time across batches: batch 0 has the NEWEST rows
        order = np.argsort([-t for t in cols["timestamp"]])
        cols = {k: [v[i] for i in order] for k, v in cols.items()}
        cfg = BatchJobConfig(**self.CFG, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        stats = ingest.run_ingest(
            str(tmp_path / "store"), ColumnsSource(cols), cfg,
            ingest=ingest.IngestConfig(micro_batch=75, queue_depth=2,
                                       compact_every=0))
        assert stats.ticks == 4
        marks = [r["watermark"] for r in event_capture
                 if r["event"] == "ingest_tick"]
        assert len(marks) == 4
        assert marks == sorted(marks)  # non-decreasing
        assert stats.watermark == max(float(t) for t in cols["timestamp"])
        # first batch already carried the global max: later (older)
        # batches must not have lowered it
        assert marks[0] == marks[-1]

    def test_loop_matches_oneshot_and_is_idempotent(self, tmp_path):
        """The acceptance anchor: a looped run (bucketed, compacted,
        published per tick) serves byte-identical docs to a one-shot
        exact apply — and re-draining the same source is a no-op."""
        cols = _cols(900, seed=4)
        cfg = BatchJobConfig(**self.CFG, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        root = str(tmp_path / "loop_store")
        # retention covers every tick: compaction prunes journal
        # entries (and their dedup hashes) beyond the retention
        # window, so a full-source replay is only exactly-once while
        # the hashes survive — docs/ingest.md documents the window.
        stats = ingest.run_ingest(
            root, ColumnsSource(cols), cfg,
            ingest=ingest.IngestConfig(micro_batch=250, queue_depth=2,
                                       compact_every=2, retention=4))
        assert stats.ticks == 4 and stats.compactions >= 1
        one = str(tmp_path / "oneshot_store")
        delta.apply_batch(one, ColumnsSource(cols),
                          BatchJobConfig(**self.CFG))
        docs_loop = _collect_docs(TileStore(f"delta:{root}"))
        docs_one = _collect_docs(TileStore(f"delta:{one}"))
        assert docs_loop.keys() == docs_one.keys()
        assert docs_loop == docs_one
        # replay: every batch's content hash is already journaled
        replay = ingest.run_ingest(
            root, ColumnsSource(cols), cfg,
            ingest=ingest.IngestConfig(micro_batch=250, queue_depth=2,
                                       compact_every=0))
        assert replay.duplicates == replay.ticks
        assert replay.epochs == []
        assert _collect_docs(TileStore(f"delta:{root}")) == docs_one

    def test_publish_refreshes_live_store(self, tmp_path):
        """A store mounted before the loop serves the new mass after
        ticks without a generation bump (targeted invalidation)."""
        root = str(tmp_path / "store")
        cfg = BatchJobConfig(**self.CFG, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        delta.init_store(root)
        store = TileStore(f"delta:{root}")
        gen0 = store.generation
        assert _collect_docs(store) == {}
        ingest.run_ingest(
            root, ColumnsSource(_cols(300, seed=5)), cfg, store=store,
            ingest=ingest.IngestConfig(micro_batch=100, queue_depth=None,
                                       compact_every=0))
        assert len(_collect_docs(store)) > 0
        assert store.generation == gen0

    def test_crash_mid_tick_heals_byte_identical(self, tmp_path):
        """A storm at journal.append past the retry budget kills an
        apply AFTER its artifact dir is written but BEFORE the journal
        entry lands — the torn state delta/recover.py exists for. The
        re-run sweeps the orphan, re-journals the batch under a fresh
        epoch, and the final store is byte-identical to a clean
        one-shot, with every batch applied exactly once."""
        cols = _cols(600, seed=6)
        cfg = BatchJobConfig(**self.CFG, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        root = str(tmp_path / "crash_store")
        ing = ingest.IngestConfig(micro_batch=200, queue_depth=None,
                                  compact_every=0)
        # tick 0 lands cleanly, then a storm kills every later journal
        # append (99 >> the retry budget: 3 ingest.tick attempts x 4
        # append attempts). The duplicate path never reaches the
        # append site, so the replayed tick 0 sails through and the
        # crash hits tick 1 after its artifact dir is written.
        ingest.run_ingest(
            root, ColumnsSource(cols), cfg,
            ingest=ingest.IngestConfig(micro_batch=200, queue_depth=None,
                                       compact_every=0, max_ticks=1))
        faults.install_spec("seed=3,scale=0,journal.append=99")
        with pytest.raises(faults.InjectedFault):
            ingest.run_ingest(root, ColumnsSource(cols), cfg, ingest=ing)
        faults.install(None)
        assert len(delta.live_entries(root)) == 1  # only tick 0 journaled
        # the crashed tick's artifact dir is orphaned (journal lost);
        # restart drains the whole source again — duplicates no-op,
        # the crashed batch re-journals, the orphan is swept
        stats = ingest.run_ingest(root, ColumnsSource(cols), cfg,
                                  ingest=ing)
        assert stats.ticks == 3
        assert stats.duplicates == 1
        live = delta.live_entries(root)
        assert len(live) == 3  # exactly once per batch
        hashes = [e["content_hash"] for e in live]
        assert len(set(hashes)) == 3
        one = str(tmp_path / "clean_store")
        delta.apply_batch(one, ColumnsSource(cols),
                          BatchJobConfig(**self.CFG))
        assert _collect_docs(TileStore(f"delta:{root}")) == \
            _collect_docs(TileStore(f"delta:{one}"))

    def test_tick_site_faults_absorbed_by_retry(self, tmp_path):
        """An ingest.tick storm inside the retry budget is invisible in
        the result: same ticks and epochs, faults counted by the plane."""
        cols = _cols(300, seed=7)
        cfg = BatchJobConfig(**self.CFG, pad_bucketing="pow2",
                             pad_bucket_min=1 << 8)
        faults.install_spec("seed=5,scale=0,ingest.tick=2x2")
        stats = ingest.run_ingest(
            str(tmp_path / "store"), ColumnsSource(cols), cfg,
            ingest=ingest.IngestConfig(micro_batch=150, queue_depth=None,
                                       compact_every=0))
        injected = faults.get_plane().injected
        faults.install(None)
        assert stats.ticks == 2 and stats.duplicates == 0
        assert len(stats.epochs) == 2
        assert injected == 2  # both faults fired, both absorbed
        assert faults.get_plane() is None

    def test_ingest_config_validation(self):
        with pytest.raises(ValueError, match="micro_batch"):
            ingest.IngestConfig(micro_batch=0)
        with pytest.raises(ValueError, match="sign"):
            ingest.IngestConfig(sign=2)


class TestStalenessSLO:
    def test_ingest_tick_feeds_staleness_freshness(self):
        from heatmap_tpu.obs import slo

        engine = slo.SLOEngine([slo.SLOSpec(
            "fresh", "staleness", max_age_s=60.0)])
        slo.set_engine(engine)
        try:
            obs.emit("ingest_tick", tick=0, points=10, seconds=0.01)
            status = engine.status()
            (obj,) = status["objectives"]
            assert obj["name"] == "fresh"
            assert obj["compliance"] == 1.0
        finally:
            slo.set_engine(None)
