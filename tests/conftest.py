"""Test harness: 8 virtual CPU devices + x64, per SURVEY.md §4.3.

Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
