"""Test harness: 8 virtual CPU devices + x64, per SURVEY.md §4.3.

Must run before the first backend initialization anywhere in the test
session. Note: the environment's axon TPU plugin (sitecustomize) forces
``jax_platforms=axon`` via jax.config at interpreter start, so the
JAX_PLATFORMS env var is ineffective — the override must go through
``jax.config.update`` after importing jax.

Sizing caveat for new mesh tests: virtual devices SERIALIZE on the
host's cores, and XLA's CPU collective rendezvous aborts the process
when a participant arrives >60s after the first — keep per-shard work
well under that (docs/DESIGN.md §4 verification-ladder caveat;
observed at 2M-point DP shapes on a 1-core host).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    assert devs[0].platform == "cpu"


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(autouse=True)
def _reset_telemetry():
    """Isolate process-wide telemetry state between tests: the default
    tracer, the metrics registry (values + enabled flag), the installed
    event log, and the stage-tracing global — so a test that flips
    ``enable_stage_tracing(True)`` (or enables metrics) cannot leak
    instrumentation cost or state into later hot-path tests."""
    yield
    from heatmap_tpu import faults, obs
    from heatmap_tpu.delta import recover
    from heatmap_tpu.obs import (anomaly, incident, recorder, slo,
                                 timeseries, tracing)
    from heatmap_tpu.utils import trace

    trace.get_tracer().reset()
    trace.enable_stage_tracing(False)
    obs.enable_metrics(False)
    obs.get_registry().reset()
    log = obs.get_event_log()
    if log is not None:
        log.close()
        obs.set_event_log(None)
    tracing.disable_tracing()  # unhooks trace/events integrations too
    slo.set_engine(None)
    incident.set_manager(None)
    timeseries.shutdown()  # stops any sampler thread + clears the store
    anomaly.set_engine(None)
    recorder.install(None)  # restores the tracing/events hooks to None
    faults.install(None)  # disarm any chaos a test left installed
    recover.clear_verified_cache()
