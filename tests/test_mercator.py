"""Golden tests: vectorized Mercator vs the scalar CPython-double oracle."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from heatmap_tpu.tilemath import mercator
import oracle


def _random_points(n, seed=0, lat_range=(-85.0, 85.0), lon_range=(-180.0, 179.9999)):
    rng = np.random.default_rng(seed)
    lats = rng.uniform(*lat_range, n)
    lons = rng.uniform(*lon_range, n)
    return lats, lons


@pytest.mark.parametrize("zoom", [0, 1, 5, 10, 15, 18, 21])
def test_row_col_bit_identity_f64(zoom):
    lats, lons = _random_points(20_000, seed=zoom)
    rows = np.asarray(mercator.row_from_latitude(lats, zoom, dtype=jnp.float64))
    cols = np.asarray(mercator.column_from_longitude(lons, zoom, dtype=jnp.float64))
    exp_rows = np.array([oracle.row_from_latitude(la, zoom) for la in lats])
    exp_cols = np.array([oracle.column_from_longitude(lo, zoom) for lo in lons])
    np.testing.assert_array_equal(rows, exp_rows)
    np.testing.assert_array_equal(cols, exp_cols)


@pytest.mark.parametrize("zoom,max_rate", [(5, 2e-4), (10, 7e-3), (15, 0.15)])
def test_f32_fast_path_agreement(zoom, max_rate):
    # f32 is the fast TPU path. Its mercator_y carries a ~25-ulp error
    # (tan/log chain, amplified by sec(lat) conditioning at high
    # latitudes), so the boundary-mismatch rate grows as ~2^zoom * err.
    # These thresholds document the measured contract; exact binning uses
    # f64 or the host-side native loader (mercator.py precision policy).
    lats, lons = _random_points(50_000, seed=7)
    r32 = np.asarray(mercator.row_from_latitude(lats, zoom, dtype=jnp.float32))
    r64 = np.asarray(mercator.row_from_latitude(lats, zoom, dtype=jnp.float64))
    mismatch = np.mean(r32 != r64)
    assert mismatch < max_rate, f"f32 row mismatch rate {mismatch} at z{zoom}"
    # Mismatches, when they occur, are off by exactly one row.
    diff = np.abs(r32[r32 != r64] - r64[r32 != r64])
    if diff.size:
        assert diff.max() == 1.0


def test_inverse_projection_matches_oracle():
    # Continuous outputs can differ from libm by ~1 ulp (XLA's exp/atan
    # are not the platform libm), so assert ulp-tight closeness here;
    # *tile assignment* identity (the thing that matters) is asserted in
    # test_keys.py::test_parent_equals_reference_center_reprojection.
    zooms = [1, 8, 16, 21]
    for zoom in zooms:
        rows = np.arange(0, 1 << min(zoom, 12), max(1, (1 << min(zoom, 12)) // 257))
        lat = np.asarray(mercator.latitude_from_row(rows, zoom, dtype=jnp.float64))
        exp = np.array([oracle.latitude_from_row(r, zoom) for r in rows])
        np.testing.assert_allclose(lat, exp, rtol=1e-12, atol=1e-11)
        lon = np.asarray(mercator.longitude_from_column(rows, zoom, dtype=jnp.float64))
        exp_lon = np.array([oracle.longitude_from_column(r, zoom) for r in rows])
        np.testing.assert_array_equal(lon, exp_lon)  # lon path is arithmetic-only


def test_no_clamp_quirks():
    # SURVEY.md §8.5: no pole clamp, no antimeridian wrap.
    zoom = 10
    # lon == 180 -> column == 2^zoom (out of range, preserved behavior).
    col = float(mercator.column_from_longitude(180.0, zoom, dtype=jnp.float64))
    assert col == float(1 << zoom)
    # |lat| beyond the mercator edge -> row outside [0, 2^zoom).
    row_hi = float(mercator.row_from_latitude(89.0, zoom, dtype=jnp.float64))
    assert row_hi < 0 or row_hi >= (1 << zoom) or row_hi == 0
    assert row_hi == oracle.row_from_latitude(89.0, zoom)
    # lat == 90 -> non-finite (tan/cos blow up), not an exception.
    row_pole = mercator.row_from_latitude(90.0, zoom, dtype=jnp.float64)
    # CPython raises/returns inf depending on libm; we just require non-crash
    # and that project_points masks it out.
    _, _, valid = mercator.project_points(
        np.array([90.0, 0.0]), np.array([0.0, 0.0]), zoom
    )
    assert not bool(valid[0]) and bool(valid[1])
    del row_pole


def test_project_points_validity_mask():
    zoom = 8
    lats = np.array([0.0, 86.0, -86.0, 90.0, 45.0])
    lons = np.array([0.0, 0.0, 0.0, 0.0, 180.0])
    row, col, valid = mercator.project_points(lats, lons, zoom)
    assert valid.tolist() == [True, False, False, False, False]
    assert 0 <= int(row[0]) < (1 << zoom)
    assert 0 <= int(col[0]) < (1 << zoom)


def test_floor_semantics_negative():
    # floor, not truncation: a latitude slightly above the mercator edge
    # gives row -1, not 0 (SURVEY.md §8.5).
    zoom = 4
    lat = 85.3  # above MAX_LATITUDE -> mercator_y slightly negative
    row = float(mercator.row_from_latitude(lat, zoom, dtype=jnp.float64))
    assert row == oracle.row_from_latitude(lat, zoom)
    assert row == -1.0


def test_max_latitude_constant():
    assert math.isclose(mercator.MAX_LATITUDE, 85.05112877980659, abs_tol=1e-12)


def test_tile_center_matches_oracle():
    zoom = 12
    rows = np.array([0, 100, 2047, 4095])
    cols = np.array([5, 999, 4000, 0])
    lat, lon = mercator.tile_center_latlon(rows, cols, zoom, dtype=jnp.float64)
    for i in range(len(rows)):
        exp_lat, exp_lon, _ = oracle.tile_center(f"{zoom}_{rows[i]}_{cols[i]}")
        np.testing.assert_allclose(float(lat[i]), exp_lat, rtol=1e-12, atol=1e-11)
        assert float(lon[i]) == exp_lon
