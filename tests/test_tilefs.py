"""tilefs subsystem tests: format, zero-copy store, disk cache, prewarm.

Tier-1 throughout. The load-bearing contract is byte-identity: a store
served from mmap'd ``tilefs-z*.bin`` mirrors must produce the same
bytes AND the same ETags as the heap-npz store for every tile shape —
exact, synopsis, /query, brownout — before and after compaction. The
disk cache and prewarm layers sit strictly below that contract (a torn
entry is a miss, a warm is a replay of ordinary requests), so their
tests pin crash-safety and determinism, not new byte shapes.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import struct
import zlib

import numpy as np
import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.serve import ServeApp, TileStore
from heatmap_tpu.serve.store import Level, MappedLevel
from heatmap_tpu.tilefs import (DiskTileCache, PrewarmConfig, build_plan,
                                list_tilefs, open_tilefs, sniff_tilefs,
                                tilefs_path, verify_tilefs, warm,
                                write_tilefs)
from heatmap_tpu.tilefs import format as tilefs_format
from heatmap_tpu.tilefs.format import (ENDIAN_MARK, HEADER_SIZE, MAGIC,
                                       TRAILER_MAGIC, VERSION, TilefsError)


# -- format ----------------------------------------------------------------


def _sample_pairs(rng):
    """Two pairs with duplicate codes and unsorted rows — exercises the
    writer-side stable sort."""
    codes = rng.integers(0, 1 << 20, 64).astype(np.int64)
    codes[10] = codes[11] = codes[12]  # duplicates must keep row order
    values = rng.uniform(0.5, 9.0, 64)
    return [("all", "alltime", codes, values),
            ("u1", "2024", codes[:7], values[:7] * 3)]


class TestFormat:
    def test_round_trip_matches_level_sort(self, tmp_path):
        rng = np.random.default_rng(7)
        pairs = _sample_pairs(rng)
        path = write_tilefs(str(tmp_path), 9, 7, pairs)
        assert path == tilefs_path(str(tmp_path), 9)
        r = open_tilefs(path)
        assert (r.zoom, r.coarse_zoom) == (9, 7)
        assert len(r.pairs) == 2
        for seg, (user, ts, codes, values) in zip(r.pairs, pairs):
            assert (seg["user"], seg["timespan"]) == (user, ts)
            got_codes, got_values = r.arrays(seg)
            # Bit-identical to what Level.__init__ computes from the
            # same rows: stable argsort, duplicates preserved.
            lvl = Level(9, codes, values)
            np.testing.assert_array_equal(got_codes, lvl.codes)
            np.testing.assert_array_equal(got_values, lvl.values)
            assert seg["vmax"] == float(values.max())
            # Zero-copy: the views are read-only mmap windows.
            assert not got_codes.flags.writeable

    def test_list_and_sniff(self, tmp_path):
        assert list_tilefs(str(tmp_path)) == {}
        assert not sniff_tilefs(str(tmp_path))
        rng = np.random.default_rng(0)
        write_tilefs(str(tmp_path), 8, 6, _sample_pairs(rng))
        write_tilefs(str(tmp_path), 10, 8, _sample_pairs(rng))
        assert sorted(list_tilefs(str(tmp_path))) == [8, 10]
        assert sniff_tilefs(str(tmp_path))

    def test_truncation_is_torn(self, tmp_path):
        rng = np.random.default_rng(1)
        path = write_tilefs(str(tmp_path), 9, 7, _sample_pairs(rng))
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 5)
        assert not sniff_tilefs(str(tmp_path))
        with pytest.raises(TilefsError, match="trailer magic"):
            open_tilefs(path)
        assert "trailer magic" in verify_tilefs(path)

    def _rewrite_header(self, path, *, version=VERSION, endian=ENDIAN_MARK):
        """Patch the header with a valid crc so only the targeted field
        trips the reader (a crc failure would mask the real check)."""
        head = struct.pack(tilefs_format._HEADER_FMT, MAGIC, version,
                           endian, 9, 7)
        head += struct.pack("=I", zlib.crc32(head))
        with open(path, "r+b") as f:
            f.write(head.ljust(HEADER_SIZE, b"\0"))

    def test_version_refusal(self, tmp_path):
        path = write_tilefs(str(tmp_path), 9, 7,
                            _sample_pairs(np.random.default_rng(2)))
        self._rewrite_header(path, version=VERSION + 1)
        with pytest.raises(TilefsError, match="version"):
            open_tilefs(path)

    def test_endianness_refusal(self, tmp_path):
        path = write_tilefs(str(tmp_path), 9, 7,
                            _sample_pairs(np.random.default_rng(3)))
        # The marker as the OTHER byte order would read it.
        swapped = int.from_bytes(
            ENDIAN_MARK.to_bytes(4, "little"), "big")
        self._rewrite_header(path, endian=swapped)
        with pytest.raises(TilefsError, match="endianness"):
            open_tilefs(path)

    def test_verify_catches_payload_corruption(self, tmp_path):
        path = write_tilefs(str(tmp_path), 9, 7,
                            _sample_pairs(np.random.default_rng(4)))
        r = open_tilefs(path)
        off = int(r.pairs[0]["values_off"])
        with open(path, "r+b") as f:
            f.seek(off + 3)
            f.write(b"\xff")
        # The lazy open still succeeds (payload pages unchecked) ...
        open_tilefs(path)
        # ... but the deep verify names the damaged segment.
        assert "values crc mismatch" in verify_tilefs(path)

    def test_tilefs_read_fault_site(self, tmp_path):
        path = write_tilefs(str(tmp_path), 9, 7,
                            _sample_pairs(np.random.default_rng(5)))
        faults.install_spec("seed=1,tilefs.read=1")
        try:
            with pytest.raises(faults.InjectedFault):
                open_tilefs(path)
        finally:
            faults.install(None)
        open_tilefs(path)  # healthy once the plane is gone


# -- byte-identity through the store --------------------------------------


@pytest.fixture(scope="module")
def stores(tmp_path_factory):
    """One small pipeline artifact, served three ways: heap npz
    (control), tilefs mirrors (bare-path sniffed), and a delta store
    whose converted base carries mirrors plus one live overlay."""
    from heatmap_tpu.delta import apply_batch
    from heatmap_tpu.delta.compact import compact, init_store, read_current
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("tilefs_stores")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                            result_delta=2)
    heap = os.path.join(root, "heap")
    with open_sink(f"arrays-synopsis:{heap}") as sink:
        sink.integrals = True
        run_job(open_source("synthetic:2500:5"), sink, config)
    mapped = os.path.join(root, "mapped")
    shutil.copytree(heap, mapped)
    tilefs_format.write_tilefs_from_loaded(mapped,
                                           LevelArraysSink.load(mapped))
    delta_root = os.path.join(root, "delta")
    init_store(delta_root)
    apply_batch(delta_root, open_source("synthetic:1200:5"), config)
    compact(delta_root)
    cur = read_current(delta_root)
    base = os.path.join(delta_root, cur["base"])
    tilefs_format.write_tilefs_from_loaded(base,
                                           LevelArraysSink.load(base))
    # One live delta on top, so identity covers heap-composed overlays.
    apply_batch(delta_root, open_source("synthetic:900:5"), config)
    return {"heap": heap, "mapped": mapped, "delta": delta_root,
            "config": config}


def _occupied(app, zoom=8, limit=6, fmt="json"):
    paths = []
    for x in range(1 << zoom):
        for y in range(1 << zoom):
            p = f"/tiles/default/{zoom}/{x}/{y}.{fmt}"
            if app.handle("GET", p)[0] == 200:
                paths.append(p)
                if len(paths) >= limit:
                    return paths
    return paths


def _assert_identical(app_a, app_b, paths):
    for p in paths:
        ra, rb = app_a.handle("GET", p), app_b.handle("GET", p)
        assert ra[0] == rb[0], p
        assert ra[2] == rb[2], p  # body bytes
        assert ra[3] == rb[3], p  # ETag


class TestByteIdentity:
    def test_sniffed_kind_and_mapped_levels(self, stores):
        store = TileStore(stores["mapped"])  # bare path sniff
        assert store.kind == "tilefs"
        levels = store.layers["default"].levels
        assert all(isinstance(l, MappedLevel) for l in levels.values())

    def test_tiles_and_etags(self, stores):
        a = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        b = ServeApp(TileStore(stores["mapped"]))
        paths = _occupied(a)
        assert paths
        _assert_identical(a, b, paths)
        _assert_identical(a, b,
                          [p.replace(".json", ".png") for p in paths])

    def test_synopsis_and_query_identity(self, stores):
        a = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        b = ServeApp(TileStore(stores["mapped"]))
        paths = _occupied(a, limit=3)
        _assert_identical(a, b, [p + "?synopsis=1" for p in paths])
        _assert_identical(a, b, [
            "/query?layer=default&z=10&bbox=0,0,1023,1023&op=sum",
            "/query?layer=default&z=10&bbox=10,10,600,600&op=max"])

    def test_brownout_identity(self, stores):
        """Forced-synopsis (rung >= 1) tiles are byte-identical too —
        the approximate path reads the same synopsis artifacts either
        way; the mirrors change only where exact rows come from."""
        from heatmap_tpu.serve import degrade as degrade_mod

        apps = []
        for spec in (f"arrays:{stores['heap']}", stores["mapped"]):
            ctl = degrade_mod.controller_from_flags(True, 10.0, 30.0, "")
            ctl.rung = 1
            apps.append(ServeApp(TileStore(spec), degrade=ctl))
        paths = _occupied(apps[0], limit=3)
        _assert_identical(apps[0], apps[1], paths)

    def test_delta_overlay_identity_and_epoch(self, stores):
        """Converted base + live heap overlay == pure heap overlay,
        including the journal-derived delta_epoch both sides stamp."""
        control = os.path.join(os.path.dirname(stores["delta"]),
                               "delta_control")
        if not os.path.isdir(control):
            shutil.copytree(stores["delta"], control)
            for p in glob.glob(os.path.join(control, "base-*",
                                            "tilefs-*.bin")):
                os.unlink(p)
        a = ServeApp(TileStore(f"delta:{control}"))
        b = ServeApp(TileStore(f"delta:{stores['delta']}"))
        assert a.store.delta_epoch == b.store.delta_epoch > 0
        paths = _occupied(a, limit=4)
        assert paths
        _assert_identical(a, b, paths)

    def test_identity_survives_compaction(self, stores):
        """Compacting the mirror-carrying store rebuilds the mirrors in
        the new base (inheritance) and serves the same bytes as the
        freshly compacted heap control."""
        from heatmap_tpu.delta.compact import compact, read_current

        control = os.path.join(os.path.dirname(stores["delta"]),
                               "compact_control")
        converted = os.path.join(os.path.dirname(stores["delta"]),
                                 "compact_converted")
        for dst in (control, converted):
            if not os.path.isdir(dst):
                shutil.copytree(stores["delta"], dst)
        for p in glob.glob(os.path.join(control, "base-*",
                                        "tilefs-*.bin")):
            os.unlink(p)
        compact(control)
        compact(converted)
        cur = read_current(converted)
        new_base = os.path.join(converted, cur["base"])
        assert sniff_tilefs(new_base)  # inherited, not lost
        assert all(verify_tilefs(p) is None
                   for p in list_tilefs(new_base).values())
        a = ServeApp(TileStore(f"delta:{control}"))
        b = ServeApp(TileStore(f"delta:{converted}"))
        paths = _occupied(a, limit=4)
        _assert_identical(a, b, paths)

    def test_torn_mirror_falls_back_to_heap(self, stores, tmp_path):
        """A torn mirror costs the mmap, never the bytes: the store
        falls back to the npz level for that zoom and /reload keeps
        serving last-good."""
        broken = os.path.join(tmp_path, "broken")
        shutil.copytree(stores["mapped"], broken)
        victim = sorted(list_tilefs(broken).values())[0]
        with open(victim, "r+b") as f:
            f.truncate(os.path.getsize(victim) - 7)
        a = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        b = ServeApp(TileStore(broken))
        zoom_bad = min(list_tilefs(broken))
        levels = b.store.layers["default"].levels
        assert isinstance(levels[zoom_bad], Level)  # heap fallback
        _assert_identical(a, b, _occupied(a, limit=4))
        assert b.store.reload() > 0  # rebuild keeps working


# -- disk cache ------------------------------------------------------------


class TestDiskCache:
    def test_round_trip_bytes_and_str(self, tmp_path):
        dc = DiskTileCache(str(tmp_path))
        key = (("default", 8, 1, 2, "png"), 3, 7)
        assert dc.get(key) is None
        assert dc.put(key, b"\x89PNG-bytes")
        assert dc.get(key) == b"\x89PNG-bytes"
        assert dc.put(("k2",), "json-text")
        assert dc.get(("k2",)) == "json-text"
        st = dc.stats()
        assert st["entries"] == 2 and st["bytes"] > 0

    def test_torn_entry_is_a_miss_and_healed(self, tmp_path):
        dc = DiskTileCache(str(tmp_path))
        dc.put(("k",), b"payload-bytes")
        (entry,) = glob.glob(str(tmp_path) + "/*/*")
        with open(entry, "r+b") as f:
            f.truncate(os.path.getsize(entry) - 4)
        assert dc.get(("k",)) is None  # torn -> miss
        assert not os.path.exists(entry)  # and unlinked
        assert dc.put(("k",), b"payload-bytes")  # refill works
        assert dc.get(("k",)) == b"payload-bytes"

    def test_sweep_removes_tmp_and_torn(self, tmp_path):
        dc = DiskTileCache(str(tmp_path))
        dc.put(("keep",), b"ok")
        sub = os.path.join(str(tmp_path), "ab")
        os.makedirs(sub, exist_ok=True)
        with open(os.path.join(sub, ".tmp-orphan"), "wb") as f:
            f.write(b"partial")
        with open(os.path.join(sub, "deadbeef"), "wb") as f:
            f.write(b"notaheader")
        # (A fresh DiskTileCache would sweep in its constructor —
        # exercise the explicit call the attach path uses.)
        removed = dc.sweep()
        assert removed == 2
        assert dc.get(("keep",)) == b"ok"

    def test_eviction_bounds_bytes(self, tmp_path):
        dc = DiskTileCache(str(tmp_path), max_bytes=4096)
        for i in range(64):
            dc.put((i,), os.urandom(256))
        assert dc.stats()["bytes"] <= 4096

    def test_write_fault_is_a_skipped_fill(self, tmp_path):
        dc = DiskTileCache(str(tmp_path))
        faults.install_spec("seed=1,diskcache.write=1")
        try:
            assert dc.put(("k",), b"v") is False
        finally:
            faults.install(None)
        assert dc.get(("k",)) is None
        assert not glob.glob(str(tmp_path) + "/*/.tmp-*")  # no litter

    def test_serveapp_disk_tier_identity(self, stores, tmp_path):
        """Write-through then read-back through a COLD heap cache:
        bytes and ETags must match a never-cached control, and the key
        must retire when the generation moves."""
        control = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        dc_root = os.path.join(tmp_path, "dc")
        filled = ServeApp(TileStore(f"arrays:{stores['heap']}"),
                          disk_cache=DiskTileCache(dc_root))
        paths = _occupied(control, limit=4)
        _assert_identical(control, filled, paths)
        assert filled.disk_cache.stats()["entries"] > 0
        # Fresh app, fresh heap cache, same disk dir: served from disk.
        reread = ServeApp(TileStore(f"arrays:{stores['heap']}"),
                          disk_cache=DiskTileCache(dc_root))
        _assert_identical(control, reread, paths)
        png = [p.replace(".json", ".png") for p in paths]
        _assert_identical(control, reread, png)


# -- prewarm ---------------------------------------------------------------


def _write_events(path, recs):
    log = obs.EventLog(str(path))
    old = obs.get_event_log() if hasattr(obs, "get_event_log") else None
    obs.set_event_log(log)
    try:
        for rec in recs:
            obs.emit("http_request", **rec)
    finally:
        obs.set_event_log(old)
        log.close()


class TestPrewarm:
    def _events(self, tmp_path):
        path = os.path.join(tmp_path, "events.jsonl")
        recs = []
        # /a twice, /b three times but earlier, junk that must drop.
        recs += [dict(route="tiles", path="/tiles/default/8/1/1.json",
                      status=200, ms=1.0)] * 3
        recs += [dict(route="tiles", path="/tiles/default/8/2/2.json",
                      status=200, ms=1.0)] * 2
        recs += [dict(route="tiles", path="/tiles/default/8/9/9.json",
                      status=404, ms=1.0)]  # non-2xx drops
        recs += [dict(route="query", path="/query?op=sum", status=200,
                      ms=1.0)]  # non-tile drops
        recs += [dict(route="tiles",
                      path="/tiles/default/8/3/3.json?synopsis=1&x=1",
                      status=200, ms=1.0)]
        _write_events(path, recs)
        return path

    def test_plan_is_deterministic_and_filtered(self, tmp_path):
        path = self._events(tmp_path)
        plan = build_plan([path], top_k=8)
        assert plan == build_plan([path], top_k=8)  # byte-determinism
        assert "/tiles/default/8/1/1.json" in plan
        assert "/tiles/default/8/2/2.json" in plan
        # Query strings normalize away except the synopsis opt-in.
        assert "/tiles/default/8/3/3.json?synopsis=1" in plan
        assert all("/query" not in p and "/8/9/9" not in p for p in plan)
        assert build_plan([path], top_k=1) == [plan[0]]

    def test_recency_decay_orders_the_head(self, tmp_path):
        path = os.path.join(tmp_path, "decay.jsonl")
        # "old" dominates by raw count, "new" by recency under a short
        # half-life: positional decay must rank "new" first.
        recs = [dict(route="tiles", path="/tiles/default/8/0/0.json",
                     status=200, ms=1.0)] * 4
        recs += [dict(route="tiles", path="/tiles/default/8/5/5.json",
                      status=200, ms=1.0)] * 2
        _write_events(path, recs)
        plan = build_plan([path], top_k=2, half_life=1.0)
        assert plan[0] == "/tiles/default/8/5/5.json"

    def test_warm_fills_caches_and_emits(self, stores, tmp_path):
        app = ServeApp(TileStore(f"arrays:{stores['heap']}"),
                       disk_cache=DiskTileCache(
                           os.path.join(tmp_path, "dc")))
        paths = _occupied(app, limit=3)
        app.cache.clear()
        ev = os.path.join(tmp_path, "warm.jsonl")
        _write_events(ev, [dict(route="tiles", path=p, status=200,
                                ms=1.0) for p in paths])
        app.prewarm = PrewarmConfig(events=(ev,), top_k=8)
        summary = app.prewarm_now(source="startup")
        assert summary["keys"] == len(paths)
        assert summary["errors"] == 0
        assert summary["source"] == "startup"
        assert app.disk_cache.stats()["entries"] >= len(paths)
        assert app._health()["prewarm"]["keys"] == len(paths)

    def test_budget_exhaustion_is_honest(self, stores, tmp_path):
        app = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        paths = _occupied(app, limit=3)
        ev = os.path.join(tmp_path, "warm.jsonl")
        _write_events(ev, [dict(route="tiles", path=p, status=200,
                                ms=1.0) for p in paths])
        app.prewarm = PrewarmConfig(events=(ev,), top_k=8,
                                    budget_bytes=1)
        summary = app.prewarm_now()
        assert summary["budget_exhausted"]
        assert summary["keys"] < summary["planned"]

    def test_reload_rewarms(self, stores, tmp_path):
        app = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        paths = _occupied(app, limit=2)
        ev = os.path.join(tmp_path, "warm.jsonl")
        _write_events(ev, [dict(route="tiles", path=p, status=200,
                                ms=1.0) for p in paths])
        app.prewarm = PrewarmConfig(events=(ev,), top_k=4)
        status = app._handle_reload()[0]
        assert status == 200
        assert app._prewarm_last["source"] == "reload"

    def test_no_config_is_a_noop(self, stores):
        app = ServeApp(TileStore(f"arrays:{stores['heap']}"))
        assert app.prewarm_now() is None
        assert "prewarm" not in app._health()


# -- converter -------------------------------------------------------------


class TestConverter:
    def test_cli_in_place_and_verify(self, stores, tmp_path):
        import subprocess
        import sys

        target = os.path.join(tmp_path, "conv")
        shutil.copytree(stores["heap"], target)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "tilefs_convert.py"),
             f"arrays:{target}", "--verify"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        assert summary["verified"] and summary["files"]
        assert sniff_tilefs(target)
