"""Partitioned multi-writer write plane tests (heatmap_tpu/writeplane/).

The anchor: **an N-writer plane serves byte-identical docs to a
single-writer delta store fed the same batches** — including a
retraction batch, a boundary-straddling batch, a mid-run hot-range
re-split, duplicate re-submits, and per-range compaction. Plus the
operational contracts: a torn manifest quarantines and readers fall
back to the last good epoch (never a mixed-epoch overlay), a writer
killed mid-apply heals exactly-once on restart, and a per-range
compaction below the retention floor or the in-flight depth is
refused.

Tier-1: CPU backend, real cascade runs (small shapes), no network.
"""

from __future__ import annotations

import glob
import json
import os
import threading

import numpy as np
import pytest

from heatmap_tpu import delta, faults
from heatmap_tpu.delta.compute import ColumnsSource, read_columns
from heatmap_tpu.io import open_source
from heatmap_tpu.pipeline import BatchJobConfig
from heatmap_tpu.serve import TileStore
from heatmap_tpu.serve.render import tile_json_bytes
from heatmap_tpu.tilemath.morton import morton_decode_np
from heatmap_tpu.writeplane import (PlaneConfig, WritePlane, load_snapshot,
                                    overlay_dirs, read_manifest, read_pointer,
                                    run_plane_ingest, sweep_plane)
from heatmap_tpu.writeplane import manifest as wp_manifest

BASE_SPEC = "synthetic:600:7"
DELTA_SPEC = "synthetic:400:11"
RETRACT_ROWS = 150  # first N base rows get retracted

CONFIG = dict(detail_zoom=8, min_detail_zoom=6, result_delta=2)


def _collect_docs(store: TileStore) -> dict:
    """Every servable JSON tile of every layer: {(layer, z, x, y):
    bytes} — the same enumeration test_delta.py anchors on, so the two
    stores must agree on which tiles exist, not just their contents."""
    docs = {}
    for name, layer in store.layers.items():
        if name == "default":  # alias of all|alltime, not a new layer
            continue
        shift = 2 * layer.result_delta
        for want, level in layer.levels.items():
            z = want - layer.result_delta
            if z < 0:
                continue
            rows, cols = morton_decode_np(np.unique(level.codes >> shift))
            for r, c in zip(rows, cols):
                docs[(name, z, int(c), int(r))] = tile_json_bytes(
                    layer, z, int(c), int(r))
    return docs


def _slice_cols(cols: dict, sl: slice) -> dict:
    return {k: v[sl] for k, v in cols.items()}


@pytest.fixture(scope="module")
def scenario(tmp_path_factory):
    """One 4-writer run with every hard case folded in — rebalance
    mid-stream, a retraction, a duplicate re-submit, per-range
    compaction — against a single-writer reference fed the identical
    batches."""
    config = BatchJobConfig(**CONFIG)
    b1 = read_columns(open_source(BASE_SPEC))
    b2 = read_columns(open_source(DELTA_SPEC))
    retract = _slice_cols(b1, slice(0, RETRACT_ROWS))

    sroot = str(tmp_path_factory.mktemp("wp_single") / "store")
    delta.apply_batch(sroot, ColumnsSource(b1), config)
    delta.apply_batch(sroot, ColumnsSource(b2), config)
    delta.apply_batch(sroot, ColumnsSource(retract), config, sign=-1)
    docs_ref = _collect_docs(TileStore(f"delta:{sroot}"))

    proot = str(tmp_path_factory.mktemp("wp_plane") / "plane")
    plane = WritePlane(proot, config, PlaneConfig(n_writers=4))
    r1 = plane.append_columns(b1)
    rb = plane.rebalance(force_range="r000", reason="test")
    r2 = plane.append_columns(b2)
    r3 = plane.append_columns(retract, sign=-1)
    plane.publish()
    docs_before = _collect_docs(TileStore(proot))

    r2_dup = plane.append_columns(b2)
    plane.publish()
    docs_after_dup = _collect_docs(TileStore(proot))

    for name in plane.order:
        plane.compact_range(name)
    docs_after_compact = _collect_docs(TileStore(proot))

    return {
        "config": config, "b1": b1, "b2": b2, "retract": retract,
        "sroot": sroot, "proot": proot, "plane": plane,
        "r1": r1, "r2": r2, "r3": r3, "r2_dup": r2_dup, "rebalance": rb,
        "docs_ref": docs_ref, "docs_before": docs_before,
        "docs_after_dup": docs_after_dup,
        "docs_after_compact": docs_after_compact,
    }


class TestRouting:
    def test_route_is_a_disjoint_union(self, scenario):
        plane, b1 = scenario["plane"], scenario["b1"]
        parts = plane.route(b1)
        total = sum(len(sub["latitude"]) for _, sub in parts)
        assert total == len(b1["latitude"])
        names = [name for name, _ in parts]
        assert len(names) == len(set(names))

    def test_route_is_deterministic(self, scenario):
        plane, b1 = scenario["plane"], scenario["b1"]
        first = plane.route(b1)
        second = plane.route(b1)
        assert [n for n, _ in first] == [n for n, _ in second]
        for (_, a), (_, b) in zip(first, second):
            np.testing.assert_array_equal(a["latitude"], b["latitude"])

    def test_batches_straddle_range_boundaries(self, scenario):
        """The scenario batches genuinely split across writers — the
        byte-identity tests below are vacuous otherwise."""
        assert len(scenario["r1"].results) >= 2
        assert len(scenario["r2"].results) >= 2

    def test_route_requires_a_plan(self, tmp_path):
        plane = WritePlane(str(tmp_path / "p"), BatchJobConfig(**CONFIG),
                           PlaneConfig(n_writers=2))
        with pytest.raises(ValueError, match="no partition plan"):
            plane.route({"latitude": np.zeros(1), "longitude": np.zeros(1)})


class TestByteIdentity:
    def test_four_writers_with_rebalance_and_retraction(self, scenario):
        """The acceptance gate: 4 writers + a mid-run re-split + a
        retraction batch serve byte-identical docs to one writer."""
        assert scenario["rebalance"] is not None
        assert len(scenario["docs_ref"]) > 50  # non-trivial pyramid
        assert scenario["docs_before"] == scenario["docs_ref"]

    def test_duplicate_resubmit_changes_nothing(self, scenario):
        assert scenario["r2_dup"].duplicate
        assert scenario["docs_after_dup"] == scenario["docs_ref"]

    def test_identity_survives_per_range_compaction(self, scenario):
        assert scenario["docs_after_compact"] == scenario["docs_ref"]

    def test_two_writer_pumps_match_single_writer(self, tmp_path):
        """The CI fast leg: a pumped 2-writer drain over micro-batches
        is byte-identical to a single-writer delta store fed the same
        micro-batches."""
        config = BatchJobConfig(**CONFIG)
        sroot = str(tmp_path / "single")
        for batch in open_source(BASE_SPEC).batches(200):
            delta.apply_batch(sroot, ColumnsSource(batch), config)
        ref = _collect_docs(TileStore(f"delta:{sroot}"))

        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=2))
        stats = run_plane_ingest(plane, open_source(BASE_SPEC),
                                 micro_batch=200)
        assert stats.failed == 0
        assert stats.completed == stats.batches
        assert _collect_docs(TileStore(proot)) == ref

    def test_bucketed_padding_is_byte_neutral(self, tmp_path):
        """With ``pad_bucketing="pow2"`` the plane pads each routed
        sub-batch to a bucketed point count (masked-invalid lanes, the
        ``pad_emissions`` contract) — the overlay must not notice, and
        point accounting must count real rows only."""
        config = BatchJobConfig(**CONFIG, pad_bucketing="pow2",
                                pad_bucket_min=1 << 7)
        sroot = str(tmp_path / "single")
        for batch in open_source(BASE_SPEC).batches(200):
            delta.apply_batch(sroot, ColumnsSource(batch), config)
        ref = _collect_docs(TileStore(f"delta:{sroot}"))

        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=3))
        stats = run_plane_ingest(plane, open_source(BASE_SPEC),
                                 micro_batch=200)
        assert stats.failed == 0
        assert stats.points == 600  # real rows, not pad lanes
        assert _collect_docs(TileStore(proot)) == ref


class TestManifest:
    def test_snapshots_are_digest_stamped(self, scenario):
        proot = scenario["proot"]
        epoch = read_pointer(proot)
        snap = load_snapshot(proot, epoch)
        assert snap["epoch"] == epoch
        assert snap["digest"].startswith("sha256:")

    def test_overlay_never_mixes_epochs(self, scenario):
        """A reader pinned to an older epoch sees exactly that
        snapshot's artifact list — overlay_dirs derives from the
        snapshot alone, never from globbing live range state."""
        proot = scenario["proot"]
        epochs = wp_manifest.list_epochs(proot)
        assert len(epochs) >= 2
        old = load_snapshot(proot, epochs[-2])
        for d in overlay_dirs(proot, old):
            rel = os.path.relpath(d, proot)
            parts = rel.split(os.sep)  # ranges/rNNN/<artifact>
            entry = old["ranges"][parts[1]]
            assert parts[2] in ([entry["base"]] + list(entry["deltas"]))

    def test_torn_manifest_falls_back_and_quarantines(self, scenario,
                                                      tmp_path):
        """Corrupting the pointed-at snapshot mid-write: readers serve
        the last good epoch; the sweep quarantines the torn file and
        repairs the pointer."""
        config = scenario["config"]
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=2))
        plane.append_columns(scenario["b1"])
        plane.publish()
        good_docs = _collect_docs(TileStore(proot))
        good_epoch = read_pointer(proot)

        plane.append_columns(scenario["b2"])
        plane.publish()
        torn = wp_manifest.manifest_path(proot, read_pointer(proot))
        with open(torn, "w") as f:
            f.write('{"epoch": tru')  # torn mid-write

        # Readers fall back to the last valid epoch, not an error and
        # not a mix of old pointer + new range dirs.
        assert _collect_docs(TileStore(proot)) == good_docs
        res = sweep_plane(proot)
        reasons = [q["reason"] for q in res["quarantined"]]
        assert "torn_manifest" in reasons
        assert not os.path.exists(torn)
        assert read_pointer(proot) == good_epoch
        assert _collect_docs(TileStore(proot)) == good_docs

    def test_orphan_range_is_quarantined(self, scenario, tmp_path):
        config = scenario["config"]
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=2))
        plane.append_columns(scenario["b1"])
        plane.publish()
        orphan = os.path.join(proot, "ranges", "r099")
        os.makedirs(orphan)
        res = sweep_plane(proot)
        assert "orphan_range" in [q["reason"] for q in res["quarantined"]]
        assert not os.path.exists(orphan)

    def test_manifest_history_is_bounded(self, scenario):
        proot = scenario["proot"]
        plane = scenario["plane"]
        n = len(glob.glob(os.path.join(proot, "manifest-*.json")))
        assert n <= plane.plane.manifest_keep


class TestExactlyOnce:
    def test_writer_killed_mid_apply_heals_on_restart(self, scenario,
                                                      tmp_path):
        """Kill one of three writers terminally mid-run: survivors keep
        applying and publishing; re-running the same stream after a
        restart heals to byte-identity with the single-writer store."""
        config = scenario["config"]
        sroot = str(tmp_path / "single")
        for batch in open_source(BASE_SPEC).batches(200):
            delta.apply_batch(sroot, ColumnsSource(batch), config)
        ref = _collect_docs(TileStore(f"delta:{sroot}"))

        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=3))
        victim = "r001"
        faults.install_spec(
            f"scale=0,writeplane.append@{victim}=99")
        try:
            stats = run_plane_ingest(plane, open_source(BASE_SPEC),
                                     micro_batch=200)
        finally:
            faults.install(None)
        assert stats.pumps[victim].dead
        assert stats.failed > 0
        # Survivors kept publishing: the manifest advanced past the
        # planning epoch even though every batch had a dead part.
        assert stats.epoch > 1
        survivors = [n for n in plane.order if n != victim]
        assert any(stats.pumps[n].applied for n in survivors)

        plane2 = WritePlane(proot, config, PlaneConfig(n_writers=3))
        stats2 = run_plane_ingest(plane2, open_source(BASE_SPEC),
                                  micro_batch=200)
        assert stats2.failed == 0
        assert _collect_docs(TileStore(proot)) == ref

    def test_replay_after_resplit_still_dedups(self, scenario, tmp_path):
        """The ledger layer: after a rebalance changes routing, a
        replayed stream dedups at the full-batch hash, so the re-split
        cannot double-apply anything."""
        config = scenario["config"]
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=2))
        run_plane_ingest(plane, open_source(BASE_SPEC), micro_batch=200)
        before = _collect_docs(TileStore(proot))

        plane2 = WritePlane(proot, config, PlaneConfig(n_writers=2))
        assert plane2.rebalance(force_range="r000") is not None
        stats = run_plane_ingest(plane2, open_source(BASE_SPEC),
                                 micro_batch=200)
        assert stats.duplicates == stats.batches
        assert _collect_docs(TileStore(proot)) == before

    def test_restart_adopts_the_persisted_plan(self, scenario, tmp_path):
        config = scenario["config"]
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, config, PlaneConfig(n_writers=3))
        plane.append_columns(scenario["b1"])
        plane.publish()
        plane2 = WritePlane(proot, config, PlaneConfig(n_writers=3))
        assert plane2.planned
        assert plane2.splits == plane.splits
        assert plane2.order == plane.order

    def test_config_mismatch_is_refused(self, scenario, tmp_path):
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, scenario["config"],
                           PlaneConfig(n_writers=2))
        plane.append_columns(scenario["b1"])
        plane.publish()
        other = BatchJobConfig(detail_zoom=9, min_detail_zoom=6,
                               result_delta=2)
        with pytest.raises(ValueError, match="detail_zoom"):
            WritePlane(proot, other, PlaneConfig(n_writers=2))


class TestRetentionFloor:
    def test_compact_below_floor_is_refused(self, scenario):
        plane = scenario["plane"]
        with pytest.raises(ValueError, match="retention_floor|floor"):
            plane.compact_range(plane.order[0], retention=1)

    def test_compact_below_inflight_depth_is_refused(self, tmp_path):
        """The delta-store guard the plane rides on: shrinking the
        dedup window below the queued-batch depth is refused."""
        root = str(tmp_path / "store")
        delta.apply_batch(root, open_source("synthetic:100:7"),
                          BatchJobConfig(**CONFIG))
        with pytest.raises(ValueError, match="in-flight"):
            delta.compact(root, retention=2, inflight=5)

    def test_plane_config_floor_is_validated(self):
        with pytest.raises(ValueError, match="retention_floor"):
            PlaneConfig(retention=1, retention_floor=3)

    def test_deep_queue_defers_compaction(self, scenario):
        plane = scenario["plane"]
        # compact_every=0 planes never auto-compact...
        assert plane.maybe_compact(plane.order[0], inflight=0) is None
        # ...and an over-deep queue defers rather than raises.
        deep = WritePlane.maybe_compact
        assert deep(plane, plane.order[0],
                    inflight=plane.plane.retention + 1) is None


class TestRebalance:
    def test_resplit_summary_and_lineage(self, scenario):
        rb = scenario["rebalance"]
        assert rb["range"] == "r000"
        assert rb["new_range"] == "r004"
        snap = read_manifest(scenario["proot"])
        assert snap["ranges"][rb["new_range"]]["parent"] == "r000"
        # The child owns the right half: it sits directly after its
        # parent in interval order.
        order = snap["order"]
        assert order.index(rb["new_range"]) == order.index("r000") + 1

    def test_balanced_plane_declines_to_split(self, scenario, tmp_path):
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, scenario["config"],
                           PlaneConfig(n_writers=2, balance_factor=1e9))
        plane.append_columns(scenario["b1"])
        assert plane.rebalance() is None

    def test_unknown_force_range_is_refused(self, scenario):
        with pytest.raises(ValueError, match="unknown range"):
            scenario["plane"].rebalance(force_range="r999")

    def test_rebalance_defers_under_inflight_queue(self, scenario):
        """The handoff compact obeys the in-flight guard: a rebalance
        whose fold would shrink the dedup window below the hot range's
        queued batches defers instead of double-count-arming it."""
        plane = scenario["plane"]
        assert plane.rebalance(force_range=plane.order[0],
                               inflight=plane.plane.retention + 1) is None


class TestConcurrency:
    def test_concurrent_ledger_records_never_lose_entries(self, scenario,
                                                          tmp_path):
        """Ledger appends from many pump threads serialize on the plane
        lock: every hash lands under a distinct epoch. (The unguarded
        find → next_epoch → rename sequence would let two threads claim
        one epoch, and the later rename silently drops the earlier
        batch from the exactly-once ledger.)"""
        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, scenario["config"],
                           PlaneConfig(n_writers=2, ledger_keep=256))
        hashes = [f"sha256:{i:064x}" for i in range(24)]
        threads = [threading.Thread(target=plane.record_batch, args=(h,),
                                    kwargs=dict(points=1, sign=1))
                   for h in hashes]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        entries = plane._ledger.entries()
        assert sorted(e["content_hash"] for e in entries) == sorted(hashes)
        assert len({e["epoch"] for e in entries}) == len(hashes)

    def test_pump_bookkeeping_failure_fails_fast(self, scenario, tmp_path,
                                                 monkeypatch):
        """A failure escaping the pump body (a coordinator bug, not an
        apply error) takes the writer-loss path: the pump marks itself
        dead and fails its parts, so the router keeps draining instead
        of blocking forever on the dead range's full queue."""
        from heatmap_tpu.writeplane import pumps as pumps_mod

        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, scenario["config"],
                           PlaneConfig(n_writers=2))
        orig = pumps_mod.PlanePumps._pump_one

        def boom(self, name, q, ps, seq, sub, sign):
            if name == "r000":
                raise KeyError("bookkeeping bug")
            return orig(self, name, q, ps, seq, sub, sign)

        monkeypatch.setattr(pumps_mod.PlanePumps, "_pump_one", boom)
        stats = pumps_mod.run_plane_ingest(plane, open_source(BASE_SPEC),
                                           micro_batch=100)
        assert stats.pumps["r000"].dead
        assert "bookkeeping bug" in stats.pumps["r000"].error
        assert stats.failed > 0
        assert stats.batches == 6  # the whole stream drained — no hang

    def test_double_completed_part_is_a_noop(self, scenario, tmp_path):
        from heatmap_tpu.writeplane.pumps import PlanePumps

        proot = str(tmp_path / "plane")
        plane = WritePlane(proot, scenario["config"], PlaneConfig())
        pumps = PlanePumps(plane)
        pumps._part_done(999, ok=False)  # unknown seq: no KeyError
        assert pumps.stats.failed == 0


class TestServeIntegration:
    def test_bare_path_sniffs_as_writeplane(self, scenario):
        store = TileStore(scenario["proot"])
        assert store.kind == "writeplane"
        explicit = TileStore(f"writeplane:{scenario['proot']}")
        assert explicit.kind == "writeplane"

    def test_delta_epoch_tracks_the_manifest(self, scenario):
        store = TileStore(scenario["proot"])
        assert store.delta_epoch == read_pointer(scenario["proot"])

    def test_empty_plane_serves_empty(self, tmp_path):
        proot = str(tmp_path / "plane")
        WritePlane(proot, BatchJobConfig(**CONFIG), PlaneConfig())
        store = TileStore(f"writeplane:{proot}")
        assert _collect_docs(store) == {}
