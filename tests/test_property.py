"""Property-based tests (hypothesis): randomized shapes/values against
the exact contracts the example-based suites pin pointwise.

The reference has no tests at all (SURVEY.md §4); the oracle suites
here cover chosen examples, and these properties sweep the input space
around them: Morton codec bijectivity, partitioned-kernel equality
with the scatter contract under arbitrary point distributions and
tunables, and blob-id formatting parity between the native and numpy
paths for arbitrary names.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")  # optional test dep
from hypothesis import given, settings, strategies as st  # noqa: E402

from heatmap_tpu import native
from heatmap_tpu.tilemath import morton

# Module-scale hypothesis budget: each example runs jitted numpy/JAX
# code, so keep example counts small but shapes meaningful.
_FAST = settings(max_examples=25, deadline=None)
_SLOW = settings(max_examples=10, deadline=None)


@_FAST
@given(
    zoom=st.integers(min_value=0, max_value=31),
    data=st.data(),
)
def test_morton_roundtrip_random(zoom, data):
    n = data.draw(st.integers(min_value=1, max_value=2048))
    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    rows = rng.integers(0, 1 << zoom, n) if zoom else np.zeros(n, np.int64)
    cols = rng.integers(0, 1 << zoom, n) if zoom else np.zeros(n, np.int64)
    codes = morton.morton_encode_np(rows, cols)
    r2, c2 = morton.morton_decode_np(codes)
    np.testing.assert_array_equal(r2, rows)
    np.testing.assert_array_equal(c2, cols)
    # Parent coarsening: one right-shift by 2 halves each axis.
    if zoom:
        pr, pc = morton.morton_decode_np(np.asarray(codes) >> 2)
        np.testing.assert_array_equal(pr, rows >> 1)
        np.testing.assert_array_equal(pc, cols >> 1)


@_SLOW
@given(
    seed=st.integers(0, 2**32 - 1),
    n=st.integers(min_value=1, max_value=1 << 13),
    block_cells=st.sampled_from([1 << 12, 1 << 14, 1 << 16]),
    chunk=st.sampled_from([256, 512, 1024]),
    streams=st.sampled_from([1, 2, 4]),
    spread=st.floats(min_value=0.01, max_value=1.0),
)
@pytest.mark.slow
def test_partitioned_matches_scatter_random(seed, n, block_cells, chunk,
                                            streams, spread):
    """Any distribution, any tunables: partitioned == scatter exactly
    (interpret mode; the on-chip verifier re-checks under Mosaic)."""
    import jax.numpy as jnp

    from heatmap_tpu.ops import Window
    from heatmap_tpu.ops.histogram import bin_rowcol_window
    from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned

    window = Window(zoom=12, row0=256, col0=128, height=512, width=384)
    rng = np.random.default_rng(seed)
    # spread interpolates clustered -> uniform-over-superset (includes
    # out-of-window points on every side).
    r0 = 256 + 256 * rng.random(n)
    c0 = 128 + 192 * rng.random(n)
    rows = (r0 + spread * rng.normal(0, 400, n)).astype(np.int64)
    cols = (c0 + spread * rng.normal(0, 300, n)).astype(np.int64)
    want = np.asarray(bin_rowcol_window(
        jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32), window
    ))
    got = np.asarray(bin_rowcol_window_partitioned(
        jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32), window,
        block_cells=block_cells, chunk=chunk, streams=streams,
        interpret=True,
    ))
    np.testing.assert_array_equal(got, want)


@pytest.mark.skipif(native.format_blob_ids is None,
                    reason="native library not built")
@_FAST
@given(
    seed=st.integers(0, 2**32 - 1),
    names=st.lists(
        st.text(
            # Any unicode except the reference's '|' separator, NUL
            # (ids embed in 'user|timespan|tile' strings), and
            # surrogates (not UTF-8-encodable).
            alphabet=st.characters(blacklist_characters="|\x00",
                                   blacklist_categories=("Cs",)),
            min_size=0, max_size=12,
        ),
        min_size=1, max_size=8, unique=True,
    ),
    zoom=st.integers(0, 31),
)
def test_native_blob_ids_match_python_random(seed, names, zoom):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 300))
    user_names = np.array(names)
    ts_names = np.array(["alltime"])
    uidx = rng.integers(0, len(user_names), n).astype(np.int32)
    tidx = np.zeros(n, np.int32)
    crow = rng.integers(0, 1 << min(zoom, 30), n).astype(np.int32) \
        if zoom else np.zeros(n, np.int32)
    ccol = rng.integers(0, 1 << min(zoom, 30), n).astype(np.int32) \
        if zoom else np.zeros(n, np.int32)
    want = [f"{user_names[u]}|alltime|{zoom}_{r}_{c}"
            for u, r, c in zip(uidx, crow, ccol)]
    got = native.format_blob_ids(uidx, tidx, crow, ccol, zoom,
                                 user_names, ts_names)
    assert got == want


@_FAST
@given(
    seed=st.integers(0, 2**32 - 1),
    pos=st.integers(min_value=0, max_value=199),
    flip=st.integers(min_value=1, max_value=255),
)
def test_hmpb_corruption_fails_cleanly(tmp_path_factory, seed, pos, flip):
    """Flipping any byte in an HMPB file's first 200 bytes (magic +
    header region) must either raise a clean ValueError or yield an
    internally consistent read — never crash with a different
    exception type mid-read."""
    from heatmap_tpu.io.hmpb import HMPBSource, write_hmpb

    tmp = tmp_path_factory.mktemp("fuzz")
    rng = np.random.default_rng(seed)
    path = str(tmp / "p.hmpb")
    n = 50
    write_hmpb(path, rng.random(n), rng.random(n),
               rng.integers(0, 3, n).astype(np.int32), ["a", "b", "c"])
    data = bytearray(open(path, "rb").read())
    if pos >= len(data):
        return
    data[pos] ^= flip
    bad = str(tmp / "bad.hmpb")
    open(bad, "wb").write(bytes(data))
    try:
        src = HMPBSource(bad)
    except ValueError:
        return  # clean rejection
    # Accepted: must be internally consistent (n parsed, columns
    # sliceable) — reading it must not crash.
    got = list(src.fast_batches(32))
    assert sum(len(b["latitude"]) for b in got) == src.n


@given(
    n=st.integers(1, 120),
    k=st.integers(1, 4),
    seed=st.integers(0, 10_000),
)
@_FAST
def test_merge_level_dirs_partition_invariant(n, k, seed):
    """Randomly splitting a level's rows across k shard dirs and
    merging reproduces the direct (timespan, user, row, col)
    aggregation — for any partition, including empty shards and
    duplicate rows straddling shards."""
    import tempfile

    from heatmap_tpu.io.merge import merge_level_dirs
    from heatmap_tpu.io.sinks import LevelArraysSink

    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 8, n).astype(np.int64)
    cols = rng.integers(0, 8, n).astype(np.int64)
    users = rng.integers(0, 3, n)
    tss = rng.integers(0, 2, n)
    values = rng.integers(1, 10, n).astype(np.float64)
    user_names = np.asarray(["all", "bob", "route"])
    ts_names = np.asarray(["alltime", "month"])

    def lvl_for(sel):
        return {
            "zoom": 8, "coarse_zoom": 3,
            "row": rows[sel], "col": cols[sel], "value": values[sel],
            "user_idx": users[sel].astype(np.int32),
            "timespan_idx": tss[sel].astype(np.int32),
            "user_names": user_names, "timespan_names": ts_names,
            "coarse_row": (rows[sel] >> 5), "coarse_col": (cols[sel] >> 5),
        }

    assign = rng.integers(0, k, n)
    with tempfile.TemporaryDirectory() as tmp:
        dirs = []
        for d in range(k):
            path = f"{tmp}/host{d}"
            LevelArraysSink(path).write_levels(
                [lvl_for(np.flatnonzero(assign == d))]
            )
            dirs.append(path)
        merged = merge_level_dirs(dirs)
    assert len(merged) == 1
    got = merged[0]
    # Direct oracle: dict aggregation.
    want: dict = {}
    for i in range(n):
        key = (ts_names[tss[i]], user_names[users[i]],
               int(rows[i]), int(cols[i]))
        want[key] = want.get(key, 0.0) + values[i]
    got_keys = list(zip(
        np.asarray(got["timespan_names"])[got["timespan_idx"]],
        np.asarray(got["user_names"])[got["user_idx"]],
        (int(r) for r in got["row"]), (int(c) for c in got["col"]),
    ))
    assert len(got_keys) == len(want)
    for key, val in zip(got_keys, got["value"]):
        assert want[key] == val, key


@given(
    n_blobs=st.integers(1, 30),
    k=st.integers(1, 4),
    seed=st.integers(0, 10_000),
)
@_FAST
def test_merge_blob_files_partition_invariant(n_blobs, k, seed):
    """Random blob partitions (with duplicates across shards) merge to
    the per-tile sums of all shards' contributions."""
    import json as _json
    import tempfile

    from heatmap_tpu.io.merge import merge_blob_files
    from heatmap_tpu.io.sinks import JSONLBlobSink

    rng = np.random.default_rng(seed)
    want: dict = {}
    shards: list[list] = [[] for _ in range(k)]
    for b in range(n_blobs):
        bid = f"u{b % 3}|alltime|3_{b}_{b}"
        # Each blob appears in 1..k shards with its own tile dicts;
        # the merge must sum them all.
        for d in range(k):
            if d and rng.random() < 0.5:
                continue
            tiles = {
                f"8_{t}_{t}": float(rng.integers(1, 9))
                for t in range(int(rng.integers(1, 4)))
            }
            shards[d].append((bid, _json.dumps(tiles)))
            agg = want.setdefault(bid, {})
            for t, v in tiles.items():
                agg[t] = agg.get(t, 0.0) + v
    with tempfile.TemporaryDirectory() as tmp:
        paths = []
        for d, items in enumerate(shards):
            p = f"{tmp}/s{d}.jsonl"
            with JSONLBlobSink(p) as sink:
                sink.write(items)
            paths.append(p)
        got = merge_blob_files(paths)
    assert got == want
