"""Golden-vector tests for the stdlib PNG encoder (io/png.py).

The encoder is zero-dependency by design, so the decoder here is too:
chunk walking + CRC verification + zlib inflate + filter-byte strip,
all stdlib. Pixel round-trips pin the wire format for every supported
color type; the colormap tests pin the perceptual contract the serving
path relies on (more mass never renders darker, empty renders clear).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np
import pytest

from heatmap_tpu.io.png import colorize, png_bytes, raster_to_png

SIGNATURE = b"\x89PNG\r\n\x1a\n"
CHANNELS = {0: 1, 2: 3, 6: 4}  # gray, RGB, RGBA


def iter_chunks(data: bytes):
    """Yield (tag, payload), verifying EVERY chunk CRC against the spec
    definition: crc32 over tag+payload."""
    assert data[:8] == SIGNATURE, "bad PNG signature"
    off = 8
    while off < len(data):
        (length,) = struct.unpack(">I", data[off:off + 4])
        tag = data[off + 4:off + 8]
        payload = data[off + 8:off + 8 + length]
        (crc,) = struct.unpack(
            ">I", data[off + 8 + length:off + 12 + length])
        assert crc == (zlib.crc32(tag + payload) & 0xFFFFFFFF), (
            f"CRC mismatch in {tag!r} chunk")
        yield tag, payload
        off += 12 + length


def decode_png(data: bytes) -> np.ndarray:
    """Minimal stdlib decoder for images png_bytes produces (8-bit,
    filter 0, no interlace)."""
    chunks = list(iter_chunks(data))
    tags = [t for t, _ in chunks]
    assert tags[0] == b"IHDR" and tags[-1] == b"IEND"
    w, h, depth, color_type, comp, filt, interlace = struct.unpack(
        ">IIBBBBB", chunks[0][1])
    assert (depth, comp, filt, interlace) == (8, 0, 0, 0)
    ch = CHANNELS[color_type]
    raw = zlib.decompress(
        b"".join(p for t, p in chunks if t == b"IDAT"))
    rows = np.frombuffer(raw, np.uint8).reshape(h, 1 + w * ch)
    assert (rows[:, 0] == 0).all(), "png_bytes writes filter type 0 only"
    img = rows[:, 1:].reshape(h, w, ch)
    return img[..., 0] if ch == 1 else img


class TestWireFormat:
    def test_signature_ihdr_and_chunk_order(self):
        data = png_bytes(np.arange(6, dtype=np.uint8).reshape(2, 3))
        tags = [t for t, _ in iter_chunks(data)]
        assert tags == [b"IHDR", b"IDAT", b"IEND"]
        _, ihdr = next(iter_chunks(data))
        w, h, depth, color_type = struct.unpack(">IIBB", ihdr[:10])
        assert (w, h, depth, color_type) == (3, 2, 8, 0)

    def test_corruption_is_detected(self):
        data = bytearray(png_bytes(np.zeros((4, 4), np.uint8)))
        data[40] ^= 0xFF  # somewhere inside IDAT payload
        with pytest.raises(AssertionError, match="CRC mismatch"):
            list(iter_chunks(bytes(data)))

    @pytest.mark.parametrize("shape,color_type", [
        ((5, 7), 0), ((4, 3, 3), 2), ((3, 4, 4), 6)])
    def test_pixel_roundtrip(self, shape, color_type):
        rng = np.random.default_rng(sum(shape))
        img = rng.integers(0, 256, shape, dtype=np.uint8)
        # Pin the extremes explicitly: filter-0 rows must carry 0x00
        # and 0xFF through compression untouched.
        img.flat[0], img.flat[-1] = 0, 255
        out = decode_png(png_bytes(img))
        np.testing.assert_array_equal(out, img)

    def test_rejects_non_uint8_and_bad_shapes(self):
        with pytest.raises(ValueError, match="uint8"):
            png_bytes(np.zeros((2, 2), np.float32))
        with pytest.raises(ValueError, match="shape"):
            png_bytes(np.zeros((2, 2, 2), np.uint8))


class TestColormap:
    def test_monotone_brightness(self):
        """Higher count must never render darker (at fixed vmax) — the
        invariant that makes adjacent served tiles comparable."""
        counts = np.arange(0, 1001, dtype=np.float64)[None, :]
        rgba = colorize(counts, vmax=1000.0)
        brightness = rgba[0, :, :3].astype(np.int64).sum(axis=1)
        assert (np.diff(brightness) >= 0).all()
        assert brightness[-1] > brightness[1]  # actually spans the ramp

    def test_alpha_marks_empty_cells(self):
        raster = np.array([[0.0, 1.0], [3.0, 0.0]])
        rgba = colorize(raster)
        np.testing.assert_array_equal(
            rgba[..., 3], np.where(raster > 0, 255, 0))
        assert colorize(raster, alpha=False)[..., 3].min() == 255

    def test_vmax_pins_the_scale_across_tiles(self):
        """The same count must colorize identically whatever else is in
        the tile — the shared-vmax contract serve/render.py uses."""
        a = colorize(np.array([[5.0, 50.0]]), vmax=100.0)
        b = colorize(np.array([[5.0, 100.0]]), vmax=100.0)
        np.testing.assert_array_equal(a[0, 0], b[0, 0])

    def test_raster_to_png_roundtrip(self):
        raster = np.array([[0.0, 2.0], [7.0, 0.0]])
        img = decode_png(raster_to_png(raster))
        assert img.shape == (2, 2, 4)
        np.testing.assert_array_equal(
            img[..., 3], np.where(raster > 0, 255, 0))
