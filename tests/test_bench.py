"""bench.py is a driver contract: ONE JSON line with the headline
metric. Pin its shape (including the CPU fallback fields) so refactors
can't silently break the round artifact."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(*extra):
    return subprocess.run(
        [sys.executable, "bench.py", "--cpu", "--n", "262144",
         "--steps", "2", "--baseline-n", "65536", *extra],
        capture_output=True, text=True, timeout=420, cwd=REPO,
        env=dict(os.environ, PYTHONPATH=REPO),
    )


def test_bench_emits_one_json_line():
    r = _run_bench()
    assert r.returncode == 0, r.stderr
    line = r.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["unit"] == "points/sec"
    assert out["value"] > 0
    assert out["vs_baseline"] > 0
    assert out["device"] == "cpu"
    assert out["bin_backend_resolved"] == "xla"  # auto on CPU


def test_bench_backend_failure_falls_back():
    # pallas has no compiled CPU lowering; the bench must degrade to
    # the scatter path and say so, never emit value=0.
    r = _run_bench("--bin-backend", "pallas")
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["value"] > 0
    assert out["bin_backend_resolved"] == "xla"
    assert "fallback" in out["note_backend"]
