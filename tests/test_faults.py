"""Chaos plane + unified retry tests (heatmap_tpu/faults/).

The plane's contract is DETERMINISM: a (seed, rule set) pair fires the
same faults at the same check sequence every run — which is what lets
tools/chaos_soak.py assert byte-identity between a faulted and a
fault-free pipeline, and what makes any chaos failure replayable from
its spec string. The retry side's contract is the policy table: every
guarded site retries with bounded-exponential-plus-full-jitter backoff
and a per-operation deadline, deterministic config errors excepted
(``NonRetryable``).
"""

from __future__ import annotations

import os
import time

import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.utils.recovery import FaultInjector, ShardFailure, run_shards


class TestFaultPlane:
    def test_count_rule_fires_first_n_checks(self):
        plane = faults.FaultPlane(seed=1)
        plane.add_rule("source.read", count=2)
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                plane.check("source.read")
        for _ in range(10):
            plane.check("source.read")  # budget spent — clean forever
        assert plane.injected == 2
        assert plane.counts() == {"source.read": 2}

    def test_spacing_spreads_faults_across_checks(self):
        """N faults every K-th check — isolated transients, so each one
        lands inside a fresh per-retry budget instead of N consecutive
        failures exhausting it (the soak's bread and butter)."""
        plane = faults.FaultPlane(seed=1)
        plane.add_rule("sink.write", count=3, spacing=4)
        fired = []
        for i in range(16):
            try:
                plane.check("sink.write")
            except faults.InjectedFault:
                fired.append(i)
        assert len(fired) == 3
        # consecutive firings are >= spacing checks apart
        assert all(b - a >= 4 for a, b in zip(fired, fired[1:]))

    def test_keyed_rule_only_matches_its_key(self):
        plane = faults.FaultPlane(seed=1)
        plane.add_rule("shard.compute", key=3, count=1)
        plane.check("shard.compute", key=2)
        with pytest.raises(faults.InjectedFault):
            plane.check("shard.compute", key=3)
        plane.check("shard.compute", key=3)  # spent

    def test_probability_rule_is_seed_deterministic(self):
        def firing_pattern(seed):
            plane = faults.FaultPlane(seed=seed)
            plane.add_rule("tile.render", prob=0.3)
            out = []
            for i in range(200):
                try:
                    plane.check("tile.render", key=i % 7)
                except faults.InjectedFault:
                    out.append(i)
            return out

        a, b, c = firing_pattern(5), firing_pattern(5), firing_pattern(6)
        assert a == b  # same seed -> identical fault schedule
        assert a != c  # different seed -> different schedule
        assert 20 < len(a) < 100  # ~30% of 200, loosely

    def test_unknown_site_rejected(self):
        plane = faults.FaultPlane()
        with pytest.raises(ValueError, match="unknown fault site"):
            plane.add_rule("not.a.site", count=1)
        with pytest.raises(ValueError, match="unknown fault site"):
            plane.check("not.a.site")

    def test_fault_carries_site_key_seq(self):
        plane = faults.FaultPlane(seed=1)
        plane.add_rule("journal.append", count=1)
        with pytest.raises(faults.InjectedFault) as ei:
            plane.check("journal.append", key="current")
        assert ei.value.site == "journal.append"
        assert ei.value.key == "current"
        assert ei.value.seq == 0

    def test_fired_faults_hit_obs(self):
        obs.enable_metrics(True)
        log_path = None
        plane = faults.FaultPlane(seed=1)
        plane.add_rule("source.read", count=1)
        with pytest.raises(faults.InjectedFault):
            plane.check("source.read", key="csv")
        from heatmap_tpu.obs import FAULTS_INJECTED

        assert FAULTS_INJECTED.value(site="source.read") == 1
        assert log_path is None  # event-log coverage lives in test_obs


class TestSpecGrammar:
    def test_full_grammar_round_trip(self):
        plane = faults.install_spec(
            "seed=9,scale=0.5,source.read=3,sink.write=2x5,"
            "tile.render=p0.25,shard.compute@1=1")
        try:
            assert plane.seed == 9
            assert plane.backoff_scale == 0.5
            descs = [r.describe() for r in plane._rules]
            assert descs == ["source.read=3", "sink.write=2x5",
                             "tile.render=p0.25", "shard.compute@1=1"]
        finally:
            faults.install(None)

    def test_bad_specs_rejected(self):
        for spec in ("source.read", "source.read=x", "nope=3",
                     "source.read=p2.0", "source.read=0x0"):
            with pytest.raises(ValueError):
                faults.parse_spec(spec)

    def test_install_from_env_flag_wins(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "seed=1,source.read=1")
        try:
            plane = faults.install_from_env("seed=2,sink.write=1")
            assert plane.seed == 2  # CLI spec beats the env var
            assert [r.site for r in plane._rules] == ["sink.write"]
            plane = faults.install_from_env(None)
            assert plane.seed == 1  # env var alone
        finally:
            faults.install(None)

    def test_no_spec_means_no_plane(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        assert faults.install_from_env(None) is None
        assert faults.get_plane() is None
        faults.check("source.read")  # global no-op must stay cheap + silent


class TestRetryCall:
    def test_retries_through_injected_faults(self):
        faults.install_spec("seed=1,scale=0,sink.write=2")
        calls = []
        out = faults.retry_call(lambda: calls.append(1) or "ok",
                                site="sink.write")
        assert out == "ok"
        assert len(calls) == 1  # faults fire BEFORE the op; op ran once
        assert faults.get_plane().injected == 2

    def test_budget_exhaustion_reraises_the_fault(self):
        faults.install_spec("seed=1,scale=0,journal.append=99")
        with pytest.raises(faults.InjectedFault):
            faults.retry_call(lambda: "never", site="journal.append")
        # journal.append policy: 3 retries -> 4 checks total
        assert faults.get_plane().injected == 4

    def test_nonretryable_fails_immediately(self):
        class Boom(RuntimeError, faults.NonRetryable):
            pass

        calls = []

        def fn():
            calls.append(1)
            raise Boom("config error")

        with pytest.raises(Boom):
            faults.retry_call(fn, site="source.read")
        assert len(calls) == 1

    def test_real_transient_errors_also_retry(self):
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise OSError("disk hiccup")
            return "recovered"

        policy = faults.RetryPolicy(retries=3, base_s=0.0, cap_s=0.0,
                                    deadline_s=None)
        assert faults.retry_call(flaky, site="sink.write",
                                 policy=policy) == "recovered"
        assert len(attempts) == 3

    def test_deadline_bounds_total_retry_time(self):
        t = [0.0]

        def clock():
            return t[0]

        def fn():
            t[0] += 10.0
            raise RuntimeError("slow failure")

        policy = faults.RetryPolicy(retries=99, base_s=0.0, cap_s=0.0,
                                    deadline_s=25.0)
        with pytest.raises(RuntimeError, match="slow failure"):
            faults.retry_call(fn, site="sink.write", policy=policy,
                              clock=clock)
        assert t[0] <= 40.0  # deadline cut it off long before 99 retries

    def test_backoff_is_bounded_and_jittered(self):
        vals = [faults.backoff_s("sink.write", "k", attempt,
                                 base_s=0.05, cap_s=2.0)
                for attempt in range(1, 12)]
        assert all(0.0 <= v <= 2.0 for v in vals)  # full jitter in [0, cap]
        assert len(set(vals)) > 5  # jitter actually varies by attempt
        # deterministic: same (site, key, attempt) -> same delay
        assert vals[3] == faults.backoff_s("sink.write", "k", 4,
                                           base_s=0.05, cap_s=2.0)

    def test_scale_zero_makes_backoff_instant(self):
        faults.install_spec("seed=1,scale=0,sink.write=1")
        assert faults.backoff_s("sink.write", None, 5,
                                base_s=1.0, cap_s=60.0) == 0.0


class TestResumableIter:
    def test_stream_resumes_without_loss_or_duplication(self):
        faults.install_spec("seed=1,scale=0,source.read=3x4")
        items = list(faults.resumable_iter(lambda: iter(range(10)),
                                           site="source.read"))
        assert items == list(range(10))
        assert faults.get_plane().injected == 3

    def test_attempt_budget_resets_per_delivered_item(self):
        """12 isolated transients across a 40-item stream — far more
        total faults than any single retry budget, survivable because
        delivery resets the attempt counter."""
        faults.install_spec("seed=2,scale=0,source.read=12x3")
        items = list(faults.resumable_iter(lambda: iter(range(40)),
                                           site="source.read"))
        assert items == list(range(40))
        assert faults.get_plane().injected == 12

    def test_consecutive_faults_exhaust_the_budget(self):
        faults.install_spec("seed=1,scale=0,source.read=99")
        with pytest.raises(faults.InjectedFault):
            list(faults.resumable_iter(lambda: iter(range(5)),
                                       site="source.read"))

    def test_nonretryable_from_stream_passes_through(self):
        class Cfg(RuntimeError, faults.NonRetryable):
            pass

        def make():
            def gen():
                yield 1
                raise Cfg("bad config")
            return gen()

        rebuilds = []

        def counted():
            rebuilds.append(1)
            return make()

        with pytest.raises(Cfg):
            list(faults.resumable_iter(counted, site="source.read"))
        assert len(rebuilds) == 1  # no retry on a deterministic error

    def test_poison_batch_caps_rebuilds(self):
        """Regression: a deterministically-failing position under a
        permissive policy (huge attempt budget, no deadline) used to
        rebuild the stream forever. The per-position cap turns it into
        a typed NonRetryable after MAX_REBUILDS_PER_POSITION tries."""
        policy = faults.RetryPolicy(retries=10**9, base_s=0.0,
                                    cap_s=0.0, deadline_s=None)
        rebuilds = []

        def counted():
            rebuilds.append(1)

            def gen():
                yield from range(3)
                raise RuntimeError("poisoned batch at position 3")

            return gen()

        with pytest.raises(faults.PoisonedStream) as ei:
            list(faults.resumable_iter(counted, site="source.read",
                                       policy=policy))
        err = ei.value
        assert isinstance(err, faults.NonRetryable)
        assert (err.site, err.position) == ("source.read", 3)
        assert err.rebuilds == faults.MAX_REBUILDS_PER_POSITION
        assert len(rebuilds) == faults.MAX_REBUILDS_PER_POSITION
        assert "position 3" in str(err)

    def test_poison_cap_resets_when_position_advances(self):
        """Transients spread across positions never hit the cap: each
        delivered item resets the per-position rebuild counter."""
        faults.install_spec("seed=4,scale=0,source.read=30x2")
        items = list(faults.resumable_iter(lambda: iter(range(40)),
                                           site="source.read",
                                           max_rebuilds=3))
        assert items == list(range(40))


class TestRunShards:
    def test_exponential_backoff_replaces_linear(self):
        """backoff_s now seeds bounded-exp-plus-jitter; with the plane's
        scale at 0 the waits collapse, so a retried run is instant."""
        faults.install_spec("seed=1,scale=0")
        inj = FaultInjector({0: 2, 1: 1})
        t0 = time.monotonic()
        out = run_shards([10, 20], lambda s: s + 1, retries=3,
                         backoff_s=5.0, fault_injector=inj)
        assert out == [11, 21]
        assert time.monotonic() - t0 < 1.0  # 5s linear backoff would hang
        assert inj.injected == 3

    def test_fail_fast_cancels_outstanding_shards(self):
        """First ShardFailure cancels queued futures: with one worker, a
        poisoned shard 0 must prevent later shards from running."""
        ran = []

        def process(s):
            ran.append(s)
            if s == 0:
                raise RuntimeError("poisoned")
            time.sleep(0.05)  # hold the worker so the cancel can land
            return s

        with pytest.raises(ShardFailure):
            run_shards(list(range(6)), process, retries=0, max_workers=2)
        # cancellation is best-effort (in-flight shards finish), but the
        # tail of the queue must never start
        assert len(ran) < 6

    def test_deadline_s_bounds_a_shards_retry_loop(self):
        t = {"n": 0}

        def process(s):
            t["n"] += 1
            raise RuntimeError("always fails")

        with pytest.raises(ShardFailure) as ei:
            run_shards([0], process, retries=10 ** 6, backoff_s=0.0,
                       deadline_s=0.0)
        assert ei.value.shard_index == 0
        assert t["n"] < 100  # deadline, not the million retries


class TestHeartbeatFaults:
    def test_injected_heartbeat_loss_goes_stale_and_times_out(self):
        from heatmap_tpu.parallel.multihost import (StragglerTimeout,
                                                    check_heartbeats)

        obs.enable_metrics(True)
        obs.heartbeat("phase_a")  # real heartbeat lands
        ages = obs.heartbeat_ages()
        assert list(ages) == ["0"] and ages["0"] < 5.0

        faults.install_spec("seed=1,multihost.heartbeat=99")
        obs.heartbeat("phase_b")  # lost in transit: gauge NOT updated
        now = time.time() + 30.0
        with pytest.raises(StragglerTimeout) as ei:
            check_heartbeats(10.0, now=now)
        assert "0" in ei.value.stale
        assert ei.value.stale["0"] > 10.0

    def test_check_heartbeats_quiet_when_fresh(self):
        from heatmap_tpu.parallel.multihost import check_heartbeats

        obs.enable_metrics(True)
        obs.heartbeat("x")
        ages = check_heartbeats(60.0)
        assert set(ages) == {"0"}

    def test_disabled_registry_never_times_out(self):
        from heatmap_tpu.parallel.multihost import check_heartbeats

        assert check_heartbeats(0.001) == {}


class TestCLIWiring:
    def test_chaos_flag_parses_and_installs(self):
        from heatmap_tpu.cli import build_parser

        parser = build_parser()
        args = parser.parse_args(
            ["run", "--input", "synthetic:10", "--chaos",
             "seed=4,source.read=1"])
        assert args.chaos == "seed=4,source.read=1"
        plane = faults.install_from_env(args.chaos)
        assert plane.seed == 4

    def test_env_var_name_is_stable(self):
        assert faults.ENV_VAR == "HEATMAP_TPU_CHAOS"
        assert os.environ.get(faults.ENV_VAR) is None  # tests run clean
