"""IO layer tests: sources, sinks, PNG encoding, source->sink job.

Covers the reference's storage boundary semantics (SURVEY.md C11/C12):
column contract, background filtering downstream, upsert-by-id egress.
"""

import json

import numpy as np
import pytest

from heatmap_tpu.io import (
    CSVSource,
    DirectoryBlobSink,
    JSONLBlobSink,
    JSONLSource,
    MemorySink,
    ParquetSource,
    PNGTileSink,
    SyntheticSource,
    colorize,
    open_sink,
    open_source,
    png_bytes,
)
from heatmap_tpu.io.sources import CassandraSource
from heatmap_tpu.ops import Window
from heatmap_tpu.pipeline import BatchJobConfig, run_batch, run_job


def _write_csv(path, rows):
    cols = ["latitude", "longitude", "user_id", "source", "timestamp"]
    with open(path, "w") as f:
        f.write(",".join(cols) + "\n")
        for r in rows:
            f.write(",".join(str(r[c]) for c in cols) + "\n")


ROWS = [
    {"latitude": 47.6, "longitude": -122.3, "user_id": "alice", "source": "gps", "timestamp": 1},
    {"latitude": 47.61, "longitude": -122.31, "user_id": "bob", "source": "gps", "timestamp": 2},
    {"latitude": 47.62, "longitude": -122.32, "user_id": "x-9", "source": "gps", "timestamp": 3},
    {"latitude": 47.63, "longitude": -122.33, "user_id": "rt-1", "source": "background", "timestamp": 4},
]

#: Fixed fake Murmur3 tokens spread over the ring so rows land in
#: different token ranges (CassandraSource shard/recovery tests).
_FAKE_TOKENS = {
    "alice": -(1 << 62),
    "bob": -12345,
    "x-9": 1 << 61,
    "rt-1": (1 << 63) - 7,
}


class _FakeTokenSession:
    """Fake driver session honoring the token-range predicate contract:
    execute(cql) filters ROWS by each row's fake partition token."""

    import re as _re

    _PAT = _re.compile(r"token\(.*\) >= (-?\d+) AND token\(.*\) <= (-?\d+)")

    def execute(self, q):
        assert "rhom.locations" in q  # reference heatmap.py:137
        m = self._PAT.search(q)
        assert m, f"query missing token-range predicate: {q}"
        lo, hi = int(m.group(1)), int(m.group(2))
        return iter(
            [r for r in ROWS if lo <= _FAKE_TOKENS[r["user_id"]] <= hi]
        )


class TestSources:
    def test_synthetic_deterministic_and_batched(self):
        src = SyntheticSource(n=1000, seed=7)
        b1 = list(src.batches(300))
        b2 = list(SyntheticSource(n=1000, seed=7).batches(300))
        assert [len(b["latitude"]) for b in b1] == [300, 300, 300, 100]
        np.testing.assert_array_equal(b1[0]["latitude"], b2[0]["latitude"])
        assert any(u.startswith("x-") for b in b1 for u in b["user_id"])
        assert any(u.startswith("rt-") for b in b1 for u in b["user_id"])
        assert any(s == "background" for b in b1 for s in b["source"])

    def test_csv_roundtrip(self, tmp_path):
        p = tmp_path / "pts.csv"
        _write_csv(p, ROWS)
        batches = list(CSVSource(str(p), use_native=False).batches(3))
        assert sum(len(b["latitude"]) for b in batches) == 4
        assert batches[0]["user_id"][0] == "alice"
        np.testing.assert_allclose(batches[0]["latitude"][0], 47.6)

    def test_jsonl_roundtrip(self, tmp_path):
        p = tmp_path / "pts.jsonl"
        with open(p, "w") as f:
            for r in ROWS:
                f.write(json.dumps(r) + "\n")
        (b,) = list(JSONLSource(str(p)).batches())
        assert b["user_id"] == ["alice", "bob", "x-9", "rt-1"]

    def test_parquet_roundtrip(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        p = tmp_path / "pts.parquet"
        tbl = pa.table({k: [r[k] for r in ROWS] for k in ROWS[0]})
        pq.write_table(tbl, p)
        (b,) = list(ParquetSource(str(p)).batches())
        assert b["user_id"] == ["alice", "bob", "x-9", "rt-1"]
        assert b["latitude"].dtype == np.float64

    def test_value_column_passthrough(self, tmp_path):
        """Weighted inputs (BASELINE config 3): a 'value' column rides
        through CSV/JSONL/Parquet batches and load_columns' background
        filter; sources without one omit the key entirely."""
        from heatmap_tpu.pipeline import load_columns

        vrows = [dict(r, value=v) for r, v in zip(ROWS, (2.5, 0.5, 3.0, 7.0))]
        # CSV (the value column routes past the native decoder).
        p = tmp_path / "w.csv"
        cols = ["latitude", "longitude", "user_id", "source", "timestamp",
                "value"]
        with open(p, "w") as f:
            f.write(",".join(cols) + "\n")
            for r in vrows:
                f.write(",".join(str(r[c]) for c in cols) + "\n")
        (b,) = list(CSVSource(str(p)).batches())
        np.testing.assert_allclose(b["value"], [2.5, 0.5, 3.0, 7.0])
        # JSONL.
        pj = tmp_path / "w.jsonl"
        with open(pj, "w") as f:
            for r in vrows:
                f.write(json.dumps(r) + "\n")
        (bj,) = list(JSONLSource(str(pj)).batches())
        np.testing.assert_allclose(bj["value"], [2.5, 0.5, 3.0, 7.0])
        # Parquet.
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        pp = tmp_path / "w.parquet"
        pq.write_table(
            pa.table({k: [r[k] for r in vrows] for k in vrows[0]}), pp)
        (bp,) = list(ParquetSource(str(pp)).batches())
        np.testing.assert_allclose(bp["value"], [2.5, 0.5, 3.0, 7.0])
        # load_columns drops the background row's value with the row.
        lc = load_columns(bj)
        np.testing.assert_allclose(lc["value"], [2.5, 0.5, 3.0])
        # No value column -> key absent end to end.
        _write_csv(tmp_path / "nw.csv", ROWS)
        (nb,) = list(CSVSource(str(tmp_path / "nw.csv")).batches())
        assert "value" not in nb
        assert "value" not in load_columns(nb)

    def test_value_column_missing_entries_default_to_one(self, tmp_path):
        pj = tmp_path / "m.jsonl"
        with open(pj, "w") as f:
            f.write(json.dumps(dict(ROWS[0], value=4.0)) + "\n")
            f.write(json.dumps(ROWS[1]) + "\n")  # no value -> 1.0
        (b,) = list(JSONLSource(str(pj)).batches())
        np.testing.assert_allclose(b["value"], [4.0, 1.0])

    def test_jsonl_late_value_raises_read_value_false_ignores(self, tmp_path):
        """The first JSONL row decides weightedness for the whole file;
        a 'value' appearing later is an error (silent dropping would
        corrupt sums, per-batch flapping would abort consumers
        mid-stream). read_value=False ignores values entirely."""
        pj = tmp_path / "late.jsonl"
        with open(pj, "w") as f:
            f.write(json.dumps(ROWS[0]) + "\n")  # no value
            f.write(json.dumps(dict(ROWS[1], value=9.0)) + "\n")
        with pytest.raises(ValueError, match="value"):
            list(JSONLSource(str(pj)).batches())
        (b,) = list(JSONLSource(str(pj), read_value=False).batches())
        assert "value" not in b
        # read_value=True forces weighted reading: row 1's missing
        # value defaults to 1.0, the late value is kept, no error.
        (bt,) = list(JSONLSource(str(pj), read_value=True).batches())
        np.testing.assert_allclose(bt["value"], [1.0, 9.0])

    def test_read_value_false_keeps_csv_native_path(self, tmp_path):
        """A value-bearing CSV with read_value=False must omit the
        column (and so stays eligible for the native fast parser)."""
        p = tmp_path / "w.csv"
        with open(p, "w") as f:
            f.write("latitude,longitude,user_id,source,timestamp,value\n")
            f.write("47.6,-122.3,u,gps,1,2.5\n")
        (b,) = list(CSVSource(str(p), read_value=False).batches())
        assert "value" not in b
        (bw,) = list(CSVSource(str(p)).batches())
        np.testing.assert_allclose(bw["value"], [2.5])

    def test_rows_view_matches_batches(self, tmp_path):
        p = tmp_path / "pts.csv"
        _write_csv(p, ROWS)
        rows = list(CSVSource(str(p), use_native=False).rows())
        assert [r["user_id"] for r in rows] == ["alice", "bob", "x-9", "rt-1"]

    def test_open_source_specs(self, tmp_path):
        assert isinstance(open_source("synthetic:100"), SyntheticSource)
        assert open_source("synthetic:100:3").seed == 3
        assert isinstance(open_source("csv:/x.csv"), CSVSource)
        assert isinstance(open_source(str(tmp_path / "a.jsonl")), JSONLSource)
        cs = open_source("cassandra:10.0.0.5")
        assert isinstance(cs, CassandraSource)
        assert cs.config.endpoint == "10.0.0.5"
        with pytest.raises(ValueError):
            open_source("nope")

    def test_cassandra_without_driver_raises_helpfully(self):
        src = CassandraSource()
        with pytest.raises(RuntimeError, match="cassandra-driver"):
            next(src.batches())

    def test_cassandra_with_injected_session(self):
        src = CassandraSource(session_factory=_FakeTokenSession)
        (b,) = list(src.batches())
        # Row order follows token-range order, not table order; the
        # multiset of rows must be exactly the table.
        assert sorted(b["user_id"]) == ["alice", "bob", "rt-1", "x-9"]

    def test_cassandra_token_ranges_cover_ring_exactly(self):
        from heatmap_tpu.io.sources import TOKEN_MAX, TOKEN_MIN, token_ranges

        for n in (1, 3, 64):
            rs = token_ranges(n)
            assert rs[0][0] == TOKEN_MIN and rs[-1][1] == TOKEN_MAX
            for (lo, hi), (lo2, _) in zip(rs, rs[1:]):
                assert lo <= hi and lo2 == hi + 1

    def test_cassandra_shards_partition_rows(self):
        # Interleaved shards together read every row exactly once.
        parts = [
            CassandraSource(
                session_factory=_FakeTokenSession,
                shard_index=i, shard_count=3,
            )
            for i in range(3)
        ]
        seen = []
        for src in parts:
            for b in src.batches():
                seen.extend(b["user_id"])
        assert sorted(seen) == ["alice", "bob", "rt-1", "x-9"]
        # Shard 0 with the same config sees a strict subset.
        assert len(seen) == len(ROWS)

    def test_cassandra_range_reread_is_deterministic(self):
        # Recovery: re-reading one failed range yields exactly the rows
        # whose tokens fall in that range, every time.
        src = CassandraSource(session_factory=_FakeTokenSession)
        from heatmap_tpu.io.sources import token_ranges

        per_range = {}
        for i, (lo, hi) in enumerate(token_ranges(src.config.n_ranges)):
            got = [u for b in src.range_batches(i) for u in b["user_id"]]
            again = [u for b in src.range_batches(i) for u in b["user_id"]]
            assert got == again
            if got:
                per_range[i] = got
            for u in got:
                tok = _FAKE_TOKENS[u]
                assert lo <= tok <= hi
        assert sorted(u for us in per_range.values() for u in us) == [
            "alice", "bob", "rt-1", "x-9",
        ]

    def test_cassandra_invalid_shard_assignment_raises(self):
        with pytest.raises(ValueError, match="shard"):
            CassandraSource(session_factory=_FakeTokenSession,
                            shard_index=3, shard_count=3)
        with pytest.raises(ValueError, match="shard"):
            CassandraSource(session_factory=_FakeTokenSession,
                            shard_index=-1)

    def test_cassandra_query_names_partition_key(self):
        from heatmap_tpu.io.sources import CassandraConfig

        src = CassandraSource(
            config=CassandraConfig(partition_keys=("device_id", "day")),
            session_factory=_FakeTokenSession,
        )
        q = src._range_query(-5, 5)
        assert "token(device_id, day) >= -5" in q
        assert "token(device_id, day) <= 5" in q


class _FakeCosmosClient:
    """Fake ContainerProxy adapter: two partition key ranges splitting
    ROWS by row parity."""

    calls: list = []

    def partition_key_range_ids(self):
        return ["0", "1"]

    def query_items(self, sql, partition_key_range_id=None):
        assert sql.startswith("SELECT c.latitude")
        type(self).calls.append(partition_key_range_id)
        return iter([
            dict(r) for i, r in enumerate(ROWS)
            if str(i % 2) == partition_key_range_id
        ])


class TestCosmosDBSource:
    def test_reads_all_ranges(self):
        from heatmap_tpu.io.sources import CosmosDBSource

        src = CosmosDBSource(client_factory=_FakeCosmosClient)
        (b,) = list(src.batches())
        assert sorted(b["user_id"]) == ["alice", "bob", "rt-1", "x-9"]

    def test_shards_partition_ranges(self):
        from heatmap_tpu.io.sources import CosmosDBSource

        seen = []
        for i in range(2):
            src = CosmosDBSource(client_factory=_FakeCosmosClient,
                                 shard_index=i, shard_count=2)
            for b in src.batches():
                seen.extend(b["user_id"])
        assert sorted(seen) == ["alice", "bob", "rt-1", "x-9"]

    def test_range_reread_is_deterministic(self):
        from heatmap_tpu.io.sources import CosmosDBSource

        src = CosmosDBSource(client_factory=_FakeCosmosClient)
        got = [u for b in src.range_batches("1") for u in b["user_id"]]
        assert got == [u for b in src.range_batches("1")
                       for u in b["user_id"]]
        assert got == [ROWS[1]["user_id"], ROWS[3]["user_id"]]

    def test_missing_env_raises_helpfully(self, monkeypatch):
        from heatmap_tpu.io.sources import CosmosDBSource

        monkeypatch.delenv("LOCATIONS_COSMOSDB_HOST", raising=False)
        with pytest.raises(RuntimeError, match="LOCATIONS_COSMOSDB_HOST"):
            next(CosmosDBSource().batches())

    def test_open_source_specs_route_to_cosmos(self):
        from heatmap_tpu.io.sources import CosmosDBSource

        # Falsy cassandra endpoint selects CosmosDB, like the
        # reference's truthiness test (reference heatmap.py:132).
        assert isinstance(open_source("cassandra:"), CosmosDBSource)
        assert isinstance(open_source("cosmosdb:"), CosmosDBSource)

    def test_invalid_shard_assignment_raises(self):
        from heatmap_tpu.io.sources import CosmosDBSource

        with pytest.raises(ValueError, match="shard"):
            CosmosDBSource(shard_index=2, shard_count=2)


class TestLevelArraysSink:
    def test_columnar_egress_matches_blob_path(self, tmp_path):
        """arrays: sink receives the same information as the blob
        format — reconstruct blobs from the columns and diff exactly."""
        from heatmap_tpu.io.sinks import LevelArraysSink

        src = SyntheticSource(n=3000, seed=4)
        cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8)
        want = run_job(src, config=cfg)  # reference-format blobs (json)

        sink = LevelArraysSink(str(tmp_path / "cols"))
        stats = run_job(src, sink, config=cfg)
        assert stats["egress"] == "levels"
        assert stats["rows"] > 0

        got: dict = {}
        for zoom, cols in LevelArraysSink.load(str(tmp_path / "cols")).items():
            cz = int(cols["coarse_zoom"])
            for i in range(len(cols["value"])):
                bid = (f"{cols['user'][i]}|{cols['timespan'][i]}|"
                       f"{cz}_{cols['coarse_row'][i]}_{cols['coarse_col'][i]}")
                did = f"{zoom}_{cols['row'][i]}_{cols['col'][i]}"
                got.setdefault(bid, {})[did] = float(cols["value"][i])
        assert got == {k: json.loads(v) for k, v in want.items()}

    def test_parquet_format_roundtrips_identically(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        src = SyntheticSource(n=1500, seed=6)
        cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8)
        run_job(src, LevelArraysSink(str(tmp_path / "npz")), config=cfg)
        run_job(src, LevelArraysSink(str(tmp_path / "pq"), format="parquet"),
                config=cfg)
        a = LevelArraysSink.load(str(tmp_path / "npz"))
        b = LevelArraysSink.load(str(tmp_path / "pq"))
        assert a.keys() == b.keys()
        for z in a:
            for k in a[z]:
                np.testing.assert_array_equal(a[z][k], b[z][k])

    def test_open_sink_parquet_spec_and_bad_format(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        s = open_sink(f"arrays-parquet:{tmp_path / 'c'}")
        assert isinstance(s, LevelArraysSink) and s.format == "parquet"
        with pytest.raises(ValueError, match="format"):
            LevelArraysSink(str(tmp_path / "x"), format="csv")

    def test_columnar_sink_rejects_blob_records(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        with pytest.raises(TypeError, match="columnar"):
            LevelArraysSink(str(tmp_path / "c")).write([("id", "{}")])

    def test_open_sink_arrays_spec(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        s = open_sink(f"arrays:{tmp_path / 'c'}")
        assert isinstance(s, LevelArraysSink)

    def test_bounded_job_routes_columnar(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        src = SyntheticSource(n=2000, seed=9)
        cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=7)
        want = run_job(src, config=cfg)
        sink = LevelArraysSink(str(tmp_path / "cols"))
        stats = run_job(src, sink, config=cfg, batch_size=256,
                        max_points_in_flight=512)
        assert stats["egress"] == "levels"
        total = sum(len(json.loads(v)) for v in want.values())
        assert stats["rows"] == total


class TestSinks:
    def test_jsonl_sink_upsert_semantics(self, tmp_path):
        p = tmp_path / "out.jsonl"
        with JSONLBlobSink(str(p)) as sink:
            sink.write([("a|alltime|5_1_2", {"6_2_4": 1.0})])
            sink.write([("a|alltime|5_1_2", {"6_2_4": 3.0})])
        loaded = JSONLBlobSink.load(str(p))
        assert loaded == {"a|alltime|5_1_2": {"6_2_4": 3.0}}

    def test_directory_sink(self, tmp_path):
        sink = DirectoryBlobSink(str(tmp_path / "blobs"))
        sink.write([("u|alltime|3_1_1", {"8_32_32": 2.0})])
        files = list((tmp_path / "blobs").iterdir())
        assert len(files) == 1
        assert json.loads(files[0].read_text()) == {"8_32_32": 2.0}

    def test_open_sink_specs(self, tmp_path):
        assert isinstance(open_sink("memory:"), MemorySink)
        assert isinstance(open_sink(f"jsonl:{tmp_path}/o.jsonl"), JSONLBlobSink)
        assert isinstance(open_sink(str(tmp_path / "o.jsonl")), JSONLBlobSink)
        assert isinstance(open_sink(f"dir:{tmp_path}/d"), DirectoryBlobSink)

    def test_per_process_sink_spec(self):
        """Sharded multihost egress derives distinct per-host paths for
        path-backed sinks and passes through process-local / upsert
        sinks unchanged."""
        from heatmap_tpu.io.sinks import per_process_sink_spec as pps

        assert pps("jsonl:/out/h.jsonl", 2) == "jsonl:/out/h.jsonl.p002"
        assert pps("/out/h.jsonl", 7) == "jsonl:/out/h.jsonl.p007"
        assert pps("arrays:/out/cols", 0) == "arrays:/out/cols/host000"
        assert pps("arrays-parquet:/o", 1) == "arrays-parquet:/o/host001"
        assert pps("dir:/out/blobs", 11) == "dir:/out/blobs/host011"
        assert pps("memory:", 3) == "memory:"
        assert pps("cassandra:", 5) == "cassandra:"
        with pytest.raises(ValueError):
            pps("bogus:/x", 0)
        # Derived specs all open.
        for spec in ("jsonl:/tmp/x.jsonl.p002", "dir:/tmp/d/host000"):
            open_sink(spec)

    def test_cassandra_sink_batches_async_inserts(self):
        """C12 egress (reference heatmap.py:149-150,157): statements
        carry (id, json) params against rhom.heatmaps, async futures
        drain every `concurrency` writes and at close."""
        from heatmap_tpu.io.sinks import CassandraBlobSink

        class FakeFuture:
            def __init__(self, log):
                self.log = log
                self.resolved = False

            def result(self):
                self.resolved = True
                self.log.append("drain")

        class FakeSession:
            def __init__(self):
                self.calls = []
                self.log = []

            def execute_async(self, cql, params):
                self.calls.append((cql, params))
                self.log.append("insert")
                return FakeFuture(self.log)

        session = FakeSession()
        with CassandraBlobSink(session=session, concurrency=2) as sink:
            sink.write([
                ("u1|alltime|3_1_1", {"8_32_32": 2.0}),
                ("u2|alltime|3_1_2", {"8_33_32": 1.0}),
                ("u3|alltime|3_1_3", {"8_34_32": 4.0}),
            ])
        assert len(session.calls) == 3
        cql, params = session.calls[0]
        assert "INSERT INTO rhom.heatmaps" in cql
        assert params[0] == "u1|alltime|3_1_1"
        assert json.loads(params[1]) == {"8_32_32": 2.0}
        # Futures 1-2 drained at the concurrency threshold (after the
        # 2nd insert), the 3rd at close — nothing left pending.
        assert session.log == ["insert", "insert", "drain", "drain",
                               "insert", "drain"]
        assert sink._pending == []

    def test_cassandra_sink_without_session_raises(self):
        from heatmap_tpu.io.sinks import CassandraBlobSink

        with pytest.raises(RuntimeError, match="session"):
            CassandraBlobSink().write_one("id", {"t": 1.0})

    def test_cassandra_sink_custom_table_and_keyspace(self):
        from heatmap_tpu.io.sinks import CassandraBlobSink

        class FakeSession:
            def __init__(self):
                self.calls = []

            def execute_async(self, cql, params):
                self.calls.append(cql)

                class _F:
                    def result(self):
                        pass

                return _F()

        session = FakeSession()
        sink = CassandraBlobSink(session=session, keyspace="ks", table="hm")
        sink.write_one("a|b|1_0_0", {"2_0_0": 1.0})
        sink.close()
        assert "INSERT INTO ks.hm " in session.calls[0]


class TestPNG:
    def test_png_decodes_via_pil(self):
        PIL = pytest.importorskip("PIL.Image")
        import io as _io

        raster = np.zeros((16, 16), np.int32)
        raster[3, 4] = 10
        raster[8, 8] = 100
        data = png_bytes(colorize(raster))
        img = PIL.open(_io.BytesIO(data))
        arr = np.asarray(img)
        assert arr.shape == (16, 16, 4)
        assert arr[3, 4, 3] == 255  # occupied -> opaque
        assert arr[0, 0, 3] == 0  # empty -> transparent
        # hotter cell is brighter
        assert int(arr[8, 8, :3].sum()) > int(arr[3, 4, :3].sum())

    def test_png_grayscale_and_rgb_shapes(self):
        assert png_bytes(np.zeros((4, 4), np.uint8))[:4] == b"\x89PNG"
        assert png_bytes(np.zeros((4, 4, 3), np.uint8))[:4] == b"\x89PNG"
        with pytest.raises(ValueError):
            png_bytes(np.zeros((4, 4), np.float32))

    def test_tile_sink_writes_zxy_tree(self, tmp_path):
        window = Window(zoom=10, row0=256, col0=512, height=8, width=8)
        raster = np.zeros((8, 8), np.int32)
        raster[1, 2] = 5
        sink = PNGTileSink(str(tmp_path / "tiles"), pixel_delta=2)  # 4px tiles
        n = sink.write_window(raster, window)
        assert n == 1
        # tile zoom 8; x = col0/4 = 128, y = row0/4 + 0 = 64
        assert (tmp_path / "tiles" / "8" / "128" / "64.png").exists()


class TestRunJob:
    def test_run_job_matches_run_batch(self, tmp_path):
        src = SyntheticSource(n=500, seed=3)
        sink = MemorySink()
        cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=5)
        blobs = run_job(src, sink, cfg, batch_size=128)
        rows = list(SyntheticSource(n=500, seed=3).rows())
        expected = run_batch(rows, cfg, as_json=True)
        assert blobs == expected
        assert sink.blobs == expected
        assert len(blobs) > 0

    def test_run_job_filters_background(self):
        src = SyntheticSource(n=300, seed=1, background_frac=1.0)
        assert run_job(src, None, BatchJobConfig(detail_zoom=10)) == {}


class TestLevelArraysSinkCompat:
    def test_load_reads_pre_dictionary_npz(self, tmp_path):
        """Files written before dictionary encoding (plain user/timespan
        string columns, no *_names tables) must still load."""
        from heatmap_tpu.io.sinks import LevelArraysSink

        d = tmp_path / "old"
        d.mkdir()
        cols = {
            "row": np.array([1, 2], np.int64),
            "col": np.array([3, 4], np.int64),
            "value": np.array([1.0, 2.0]),
            "user": np.array(["alice", "all"]),
            "timespan": np.array(["alltime", "alltime"]),
            "coarse_row": np.array([0, 0], np.int64),
            "coarse_col": np.array([0, 0], np.int64),
            "zoom": np.asarray(9),
            "coarse_zoom": np.asarray(4),
        }
        with open(d / "level_z09.npz", "wb") as f:
            np.savez(f, **cols)
        out = LevelArraysSink.load(str(d))
        assert list(out) == [9]
        np.testing.assert_array_equal(out[9]["user"], cols["user"])
        np.testing.assert_array_equal(out[9]["timespan"], cols["timespan"])

    def test_load_reads_pre_dictionary_parquet(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from heatmap_tpu.io.sinks import LevelArraysSink

        d = tmp_path / "oldpq"
        d.mkdir()
        t = pa.table({
            "row": np.array([1], np.int64),
            "col": np.array([2], np.int64),
            "value": np.array([3.0]),
            "user": ["alice"],          # plain string, not dictionary
            "timespan": ["alltime"],
            "coarse_row": np.array([0], np.int64),
            "coarse_col": np.array([0], np.int64),
            "zoom": np.array([7], np.int64),
            "coarse_zoom": np.array([2], np.int64),
        })
        pq.write_table(t, str(d / "level_z07.parquet"))
        out = LevelArraysSink.load(str(d))
        assert out[7]["user"][0] == "alice"
        assert out[7]["timespan"][0] == "alltime"
        assert int(out[7]["coarse_zoom"]) == 2

    def test_npz_compressed_format_roundtrips(self, tmp_path):
        from heatmap_tpu.io.sinks import LevelArraysSink

        src = SyntheticSource(n=800, seed=3)
        cfg = BatchJobConfig(detail_zoom=9, min_detail_zoom=7)
        run_job(src, LevelArraysSink(str(tmp_path / "a")), config=cfg)
        run_job(src, LevelArraysSink(str(tmp_path / "b"),
                                     format="npz-compressed"), config=cfg)
        a = LevelArraysSink.load(str(tmp_path / "a"))
        b = LevelArraysSink.load(str(tmp_path / "b"))
        assert a.keys() == b.keys()
        for z in a:
            assert a[z].keys() == b[z].keys()
            for k in a[z]:
                np.testing.assert_array_equal(a[z][k], b[z][k])


# -- shard merging (heatmap_tpu.io.merge + CLI merge) ----------------------


class TestMergeShards:
    def _job_blobs(self, tmp_path, n=1500, seed=4):
        from heatmap_tpu.io.sources import SyntheticSource
        from heatmap_tpu.pipeline import BatchJobConfig, run_job

        cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=7)
        return run_job(SyntheticSource(n=n, seed=seed), config=cfg)

    def test_blob_merge_equals_unsharded(self, tmp_path):
        """Splitting a job's blobs across two jsonl shards and merging
        reproduces the full dict exactly."""
        import json as _json

        from heatmap_tpu.io.merge import merge_blob_files
        from heatmap_tpu.io.sinks import JSONLBlobSink

        blobs = self._job_blobs(tmp_path)
        items = sorted(blobs.items())
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        with JSONLBlobSink(str(a)) as s:
            s.write(items[::2])
        with JSONLBlobSink(str(b)) as s:
            s.write(items[1::2])
        merged = merge_blob_files([str(a), str(b)])
        assert merged.keys() == blobs.keys()
        for key in blobs:
            assert merged[key] == _json.loads(blobs[key]), key

    def test_blob_merge_sums_collisions(self, tmp_path):
        """The same shard merged twice doubles every value — upsert-sum
        semantics, matching the cross-host merge."""
        import json as _json

        from heatmap_tpu.io.merge import merge_blob_files
        from heatmap_tpu.io.sinks import JSONLBlobSink

        blobs = self._job_blobs(tmp_path, n=400)
        p = tmp_path / "x.jsonl"
        with JSONLBlobSink(str(p)) as s:
            s.write(sorted(blobs.items()))
        merged = merge_blob_files([str(p), str(p)])
        for key in blobs:
            want = {k: 2 * v for k, v in _json.loads(blobs[key]).items()}
            assert merged[key] == want, key

    def test_blob_merge_rejects_non_summable(self, tmp_path):
        import json as _json

        p1, p2 = tmp_path / "1.jsonl", tmp_path / "2.jsonl"
        p1.write_text(_json.dumps(
            {"id": "a|alltime|3_1_2", "heatmap": '{"8_1_2": "oops"}'}
        ) + "\n")
        p2.write_text(_json.dumps(
            {"id": "a|alltime|3_1_2", "heatmap": '{"8_1_2": 2.0}'}
        ) + "\n")
        from heatmap_tpu.io.merge import merge_blob_files

        with pytest.raises((TypeError, ValueError)):
            merge_blob_files([str(p1), str(p2)])

    def test_level_dirs_merge_equals_unsharded(self, tmp_path):
        """Two per-host columnar shards (from a real sharded-egress
        partition) merge back to the unsharded job's level arrays."""
        from heatmap_tpu.io.merge import merge_level_dirs
        from heatmap_tpu.io.sinks import LevelArraysSink
        from heatmap_tpu.io.sources import SyntheticSource
        from heatmap_tpu.parallel.multihost import partition_levels
        from heatmap_tpu.pipeline import BatchJobConfig, run_job

        cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=7)
        ref_dir = tmp_path / "ref"
        run_job(SyntheticSource(n=1500, seed=4),
                LevelArraysSink(str(ref_dir)), config=cfg)
        want = LevelArraysSink.load(str(ref_dir))

        # Partition the finalized levels like sharded egress does and
        # write each part through its own per-host sink dir.
        ref_levels = []

        class _Cap:
            def write_levels(self, levels):
                ref_levels.extend(levels)
                return 0

        run_job(SyntheticSource(n=1500, seed=4), _Cap(), config=cfg)
        parts = partition_levels(ref_levels, 2)
        shard_dirs = []
        for i, part in enumerate(parts):
            d = tmp_path / f"host{i}"
            LevelArraysSink(str(d)).write_levels(part)
            shard_dirs.append(str(d))

        merged_dir = tmp_path / "merged"
        LevelArraysSink(str(merged_dir)).write_levels(
            merge_level_dirs(shard_dirs)
        )
        got = LevelArraysSink.load(str(merged_dir))
        assert got.keys() == want.keys()
        for z, wlvl in want.items():
            glvl = got[z]
            ow = np.lexsort((wlvl["col"], wlvl["row"], wlvl["user"],
                             wlvl["timespan"]))
            og = np.lexsort((glvl["col"], glvl["row"], glvl["user"],
                             glvl["timespan"]))
            for k in ("row", "col", "value", "user", "timespan",
                      "coarse_row", "coarse_col"):
                np.testing.assert_array_equal(
                    np.asarray(glvl[k])[og], np.asarray(wlvl[k])[ow],
                    err_msg=f"z{z} {k}",
                )

    @pytest.mark.slow
    def test_cli_merge_blobs(self, tmp_path):
        import json as _json
        import os
        import subprocess
        import sys

        blobs = self._job_blobs(tmp_path, n=400)
        items = sorted(blobs.items())
        from heatmap_tpu.io.sinks import JSONLBlobSink

        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        with JSONLBlobSink(str(a)) as s:
            s.write(items[::2])
        with JSONLBlobSink(str(b)) as s:
            s.write(items[1::2])
        out = tmp_path / "merged.jsonl"
        r = subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", "merge",
             "--inputs", str(a), str(b), "--output", f"jsonl:{out}"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        assert r.returncode == 0, r.stderr[-800:]
        stats = _json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["mode"] == "blobs" and stats["blobs"] == len(blobs)
        loaded = JSONLBlobSink.load(str(out))
        assert loaded.keys() == blobs.keys()
        for key in blobs:
            assert loaded[key] == _json.loads(blobs[key]), key

    def test_cli_merge_rejects_mixed_inputs(self, tmp_path):
        import os
        import subprocess
        import sys

        f = tmp_path / "a.jsonl"
        f.write_text("")
        d = tmp_path / "dir"
        d.mkdir()
        r = subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", "merge",
             "--inputs", str(f), str(d), "--output", "memory:"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        assert r.returncode != 0
        assert "all one kind" in r.stderr or "not a mix" in r.stderr


    def test_cli_merge_rejects_mismatched_output_kind(self, tmp_path):
        import os
        import subprocess
        import sys

        d1, d2 = tmp_path / "h0", tmp_path / "h1"
        d1.mkdir(); d2.mkdir()
        repo = os.path.dirname(os.path.dirname(__file__))
        r = subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", "merge",
             "--inputs", str(d1), str(d2), "--output", "jsonl:x.jsonl"],
            capture_output=True, text=True, cwd=repo,
        )
        assert r.returncode != 0 and "arrays:DIR" in r.stderr
        f1, f2 = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        f1.write_text(""); f2.write_text("")
        r = subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", "merge",
             "--inputs", str(f1), str(f2), "--output", "arrays:out"],
            capture_output=True, text=True, cwd=repo,
        )
        assert r.returncode != 0 and "columnar-only" in r.stderr

    def test_merge_module_initializes_no_backend(self):
        """Merging must never initialize a jax backend: on a machine
        with a dead accelerator relay, backend init hangs — the
        offline-merge contract in io/merge.py's docstring."""
        import subprocess
        import sys

        code = (
            "import heatmap_tpu.io.merge as m\n"
            "print(sorted(m.merge_blob_parts([{'a': {'t': 1}},"
            " {'a': {'t': 2}}])['a'].items()))\n"
            "from jax._src import xla_bridge\n"
            "print('backends_initialized', bool(xla_bridge._backends))\n"
        )
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-800:]
        assert "('t', 3)" in r.stdout
        # Private-API probe: if the attribute moves, the line above
        # fails the subprocess and this assert reports it loudly.
        assert "backends_initialized False" in r.stdout, r.stdout

    @pytest.mark.slow
    def test_cli_merge_level_dirs(self, tmp_path):
        import json as _json
        import os
        import subprocess
        import sys

        from heatmap_tpu.io.sinks import LevelArraysSink
        from heatmap_tpu.io.sources import SyntheticSource
        from heatmap_tpu.pipeline import BatchJobConfig, run_job

        cfg = BatchJobConfig(detail_zoom=9, min_detail_zoom=7)
        ref = tmp_path / "ref"
        run_job(SyntheticSource(n=600, seed=8), LevelArraysSink(str(ref)),
                config=cfg)
        want = LevelArraysSink.load(str(ref))
        # Two "shards": the same dir twice — the merge must double
        # every value (upsert-sum semantics, easy to assert exactly).
        out = tmp_path / "merged"
        r = subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", "merge",
             "--inputs", str(ref), str(ref),
             "--output", f"arrays:{out}"],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        assert r.returncode == 0, r.stderr[-800:]
        stats = _json.loads(r.stdout.strip().splitlines()[-1])
        assert stats["mode"] == "levels" and stats["levels"] == len(want)
        got = LevelArraysSink.load(str(out))
        assert got.keys() == want.keys()
        for z in want:
            assert np.asarray(got[z]["value"]).sum() == \
                2 * np.asarray(want[z]["value"]).sum(), z

    def test_level_dirs_merge_rejects_mismatched_coarse_zoom(self, tmp_path):
        """Shards that disagree on a level's coarse_zoom are not shards
        of one job — the merge must refuse, not silently mix result
        granularities."""
        from heatmap_tpu.io.merge import merge_level_dirs
        from heatmap_tpu.io.sinks import LevelArraysSink

        def lvl(coarse_zoom):
            return {
                "zoom": 8, "coarse_zoom": coarse_zoom,
                "row": np.asarray([1]), "col": np.asarray([2]),
                "value": np.asarray([1.0]),
                "user_idx": np.asarray([0], np.int32),
                "timespan_idx": np.asarray([0], np.int32),
                "user_names": np.asarray(["all"]),
                "timespan_names": np.asarray(["alltime"]),
                "coarse_row": np.asarray([0]),
                "coarse_col": np.asarray([0]),
            }

        a, b = tmp_path / "a", tmp_path / "b"
        LevelArraysSink(str(a)).write_levels([lvl(3)])
        LevelArraysSink(str(b)).write_levels([lvl(4)])
        with pytest.raises(ValueError, match="coarse_zoom"):
            merge_level_dirs([str(a), str(b)])

    @staticmethod
    def _lvl(rows, cols, values, zoom=8, coarse_zoom=3, user="all"):
        n = len(rows)
        return {
            "zoom": zoom, "coarse_zoom": coarse_zoom,
            "row": np.asarray(rows), "col": np.asarray(cols),
            "value": np.asarray(values, np.float64),
            "user_idx": np.zeros(n, np.int32),
            "timespan_idx": np.zeros(n, np.int32),
            "user_names": np.asarray([user]),
            "timespan_names": np.asarray(["alltime"]),
            "coarse_row": np.zeros(n, np.int64),
            "coarse_col": np.zeros(n, np.int64),
        }

    def test_level_parts_empty_part_is_identity(self):
        """An empty part (a host that ingested nothing) contributes
        nothing — the merge equals merging the non-empty part alone."""
        from heatmap_tpu.io.merge import merge_level_parts

        part = [self._lvl([1, 2], [3, 4], [1.0, 2.0])]
        alone = merge_level_parts([part])
        with_empty = merge_level_parts([part, []])
        assert len(with_empty) == len(alone) == 1
        for key in ("row", "col", "value", "user_idx", "timespan_idx"):
            np.testing.assert_array_equal(with_empty[0][key], alone[0][key])

    def test_level_parts_disjoint_keys_union_unsummed(self):
        """Parts with disjoint (timespan, user, row, col) keys union:
        every row survives with its original value — re-aggregation
        only sums genuine collisions."""
        from heatmap_tpu.io.merge import merge_level_parts

        a = [self._lvl([1], [1], [5.0])]
        b = [self._lvl([2], [2], [7.0])]
        (merged,) = merge_level_parts([a, b])
        np.testing.assert_array_equal(merged["row"], [1, 2])
        np.testing.assert_array_equal(merged["col"], [1, 2])
        np.testing.assert_array_equal(merged["value"], [5.0, 7.0])

    def test_level_dirs_missing_shard_dir_raises(self, tmp_path):
        """A listed-but-absent shard dir is a hard error (a silently
        skipped host would under-count every tile it owned)."""
        from heatmap_tpu.io.merge import merge_level_dirs
        from heatmap_tpu.io.sinks import LevelArraysSink

        a = tmp_path / "host000"
        LevelArraysSink(str(a)).write_levels([self._lvl([1], [1], [1.0])])
        with pytest.raises(FileNotFoundError):
            merge_level_dirs([str(a), str(tmp_path / "host001")])

    def test_level_dirs_empty_shard_dir_contributes_nothing(self, tmp_path):
        """An existing-but-empty shard dir (host wrote no levels) is a
        valid empty contribution, not an error."""
        from heatmap_tpu.io.merge import merge_level_dirs
        from heatmap_tpu.io.sinks import LevelArraysSink

        a, b = tmp_path / "host000", tmp_path / "host001"
        LevelArraysSink(str(a)).write_levels([self._lvl([1], [2], [3.0])])
        b.mkdir()
        merged = merge_level_dirs([str(a), str(b)])
        (alone,) = merge_level_dirs([str(a)])
        assert len(merged) == 1
        np.testing.assert_array_equal(merged[0]["value"], alone["value"])
        np.testing.assert_array_equal(merged[0]["row"], alone["row"])
