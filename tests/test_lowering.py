"""Mosaic TPU lowering regression tests (no chip needed).

``jax.export`` can lower a jitted function for the *tpu* platform from
a CPU-only process, running the real Mosaic kernel-lowering pass that
``interpret=True`` tests skip. Round 2's on-chip verify run caught a
lowering-only bug exactly here: under ``jax_enable_x64`` (which the
whole test session and the production cascade run with — conftest.py,
pipeline z21 precision policy), weak Python-int literals inside a
Pallas kernel trace as int64 scalars, and Mosaic's int64->int32
convert lowering recurses until RecursionError. These tests pin every
shipping kernel's TPU lowering under x64 so that class of bug is
caught by the CPU suite, not by a scarce relay window.

The export is lowering-only: nothing executes, so the tests are fast
and deterministic. Bit-exactness vs the scatter paths is covered
separately (interpret-mode tests + tools/verify_partitioned_onchip.py
on real hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.export  # jax<0.5 only exposes jax.export as a submodule import
import jax.numpy as jnp
import numpy as np
import pytest

from heatmap_tpu.ops.histogram import Window
from heatmap_tpu.ops.pallas_kernels import bin_rowcol_window_pallas
from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned
from heatmap_tpu.ops.sparse_partitioned import (
    aggregate_sorted_keys_partitioned,
)

N = 1 << 12


def _export_tpu(fn, *args):
    """Lower ``jit(fn)`` for the TPU platform; raises on Mosaic bugs."""
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


@pytest.fixture(scope="module")
def rowcol():
    rng = np.random.default_rng(7)
    # int64 inputs on purpose: the x64 batch job hands the kernels
    # int64 rows/cols; the kernels must cast internally.
    row = jnp.asarray(rng.integers(0, 512, N), jnp.int64)
    col = jnp.asarray(rng.integers(0, 640, N), jnp.int64)
    return row, col


def test_partitioned_count_lowers_for_tpu(rowcol):
    win = Window(zoom=15, row0=0, col0=0, height=512, width=640)
    f = functools.partial(bin_rowcol_window_partitioned, window=win,
                          interpret=False)
    _export_tpu(lambda r, c: f(r, c), *rowcol)


def test_partitioned_count_streams_lowers_for_tpu(rowcol):
    win = Window(zoom=15, row0=0, col0=0, height=512, width=640)
    f = functools.partial(bin_rowcol_window_partitioned, window=win,
                          interpret=False, streams=8)
    _export_tpu(lambda r, c: f(r, c), *rowcol)


def test_partitioned_weighted_lowers_for_tpu(rowcol):
    win = Window(zoom=15, row0=0, col0=0, height=512, width=640)
    w = jnp.asarray(np.random.default_rng(8).integers(1, 16, N), jnp.float32)
    f = functools.partial(bin_rowcol_window_partitioned, window=win,
                          interpret=False)
    _export_tpu(lambda r, c, w_: f(r, c, weights=w_), *rowcol, w)


def test_pallas_window_kernel_lowers_for_tpu(rowcol):
    win = Window(zoom=12, row0=0, col0=0, height=256, width=256)
    f = functools.partial(bin_rowcol_window_pallas, window=win,
                          interpret=False)
    _export_tpu(lambda r, c: f(r, c), *rowcol)


def test_segment_kernel_lowers_for_tpu():
    keys = np.sort(
        np.random.default_rng(9).integers(0, 1 << 42, N).astype(np.int64)
    )
    f = functools.partial(aggregate_sorted_keys_partitioned,
                          capacity=1 << 14, interpret=False)
    _export_tpu(f, jnp.asarray(keys))


def test_segment_kernel_streams_lowers_for_tpu():
    keys = np.sort(
        np.random.default_rng(10).integers(0, 1 << 42, N).astype(np.int64)
    )
    f = functools.partial(aggregate_sorted_keys_partitioned,
                          capacity=1 << 14, interpret=False,
                          slab=1 << 12, chunk=512, streams=4)
    _export_tpu(f, jnp.asarray(keys))
