"""HMPB binary columnar point format (io.hmpb)."""

import csv
import json
import subprocess
import sys
import os

import numpy as np
import pytest

from heatmap_tpu.io.hmpb import (
    TS_MISSING,
    HMPBSource,
    convert_to_hmpb,
    write_hmpb,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_write_read_roundtrip(tmp_path):
    p = str(tmp_path / "pts.hmpb")
    rng = np.random.default_rng(0)
    n = 1000
    lat = rng.uniform(-85, 85, n)
    lon = rng.uniform(-180, 180, n)
    rid = rng.integers(-1, 3, n).astype(np.int32)
    ts = rng.integers(0, 2**31, n)
    bg = (rng.random(n) < 0.1).astype(np.uint8)
    write_hmpb(p, lat, lon, rid, ["all-u", "bob", "route"],
               timestamp=ts, background=bg)
    src = HMPBSource(p)
    assert src.n == n
    assert src.names == ["all-u", "bob", "route"]
    got = list(src.fast_batches(256))
    assert [len(b["latitude"]) for b in got] == [256, 256, 256, 232]
    assert got[0]["new_group_names"] == src.names
    assert got[1]["new_group_names"] == []
    np.testing.assert_array_equal(
        np.concatenate([b["latitude"] for b in got]), lat)
    np.testing.assert_array_equal(
        np.concatenate([b["routed"] for b in got]), rid)
    np.testing.assert_array_equal(
        np.concatenate([b["background"] for b in got]), bg.astype(bool))


def test_value_column_roundtrip_and_legacy(tmp_path):
    """The optional value section round-trips through fast_batches and
    the string view; files without it read as before (has_value False,
    no 'value' key) and a truncated value section is detected."""
    p = str(tmp_path / "w.hmpb")
    rng = np.random.default_rng(2)
    n = 500
    lat = rng.uniform(-80, 80, n)
    lon = rng.uniform(-170, 170, n)
    rid = rng.integers(-1, 2, n).astype(np.int32)
    val = rng.random(n) * 9
    write_hmpb(p, lat, lon, rid, ["u1", "rt-x"], value=val)
    src = HMPBSource(p)
    assert src.has_value
    got = list(src.fast_batches(128))
    np.testing.assert_array_equal(
        np.concatenate([b["value"] for b in got]), val)
    np.testing.assert_array_equal(
        np.concatenate([b["latitude"] for b in got]), lat)
    (sb,) = list(src.batches(n))
    np.testing.assert_array_equal(sb["value"], val)
    # Legacy layout: no value written -> no value read.
    p2 = str(tmp_path / "nv.hmpb")
    write_hmpb(p2, lat, lon, rid, ["u1", "rt-x"])
    src2 = HMPBSource(p2)
    assert not src2.has_value
    assert all("value" not in b for b in src2.fast_batches(128))
    # The value section participates in the size check.
    data = open(p, "rb").read()
    trunc = str(tmp_path / "trunc.hmpb")
    open(trunc, "wb").write(data[: len(data) - 4 * n])
    with pytest.raises(ValueError, match="truncated"):
        HMPBSource(trunc)
    # Wrong-length value arrays are rejected at write time.
    with pytest.raises(ValueError, match="value"):
        write_hmpb(str(tmp_path / "bad.hmpb"), lat, lon, rid,
                   ["u1", "rt-x"], value=val[:-1])


def test_unknown_header_column_rejected(tmp_path):
    p = str(tmp_path / "f.hmpb")
    write_hmpb(p, np.zeros(1), np.zeros(1), np.zeros(1, np.int32), ["u"])
    data = bytearray(open(p, "rb").read())
    # Rewrite the header with a column name this reader doesn't know.
    from heatmap_tpu.io.hmpb import MAGIC

    hlen = int(np.frombuffer(data[len(MAGIC):len(MAGIC) + 8], "<u8")[0])
    start = len(MAGIC) + 8
    header = json.loads(bytes(data[start:start + hlen]).decode())
    header["columns"] = header["columns"] + ["wormhole"]
    new = json.dumps(header).encode()
    pad = (-(len(MAGIC) + 8 + len(new))) % 8
    body = data[start + hlen + ((-(start + hlen)) % 8):]
    out = MAGIC + np.uint64(len(new)).astype("<u8").tobytes() + new \
        + b"\x00" * pad + bytes(body)
    p2 = str(tmp_path / "f2.hmpb")
    open(p2, "wb").write(out)
    with pytest.raises(ValueError, match="wormhole"):
        HMPBSource(p2)


def test_convert_carries_value_column(tmp_path):
    """convert_to_hmpb from a weighted CSV routes off the native
    decoder and lands the value section; sharded convert carries it
    per part; hmpb->hmpb reconvert preserves it."""
    p = tmp_path / "w.csv"
    with open(p, "w") as f:
        f.write("latitude,longitude,user_id,source,timestamp,value\n")
        for i in range(40):
            f.write(f"47.{600 + i},-122.{300 + i},u{i % 5},gps,1,{i}.5\n")
    out = str(tmp_path / "w.hmpb")
    convert_to_hmpb(f"csv:{p}", out)
    src = HMPBSource(out)
    assert src.has_value
    (b,) = list(src.fast_batches(100))
    np.testing.assert_allclose(b["value"], [i + 0.5 for i in range(40)])
    # Sharded.
    outdir = str(tmp_path / "shards")
    info = convert_to_hmpb(f"csv:{p}", outdir, shard_rows=15)
    assert info["parts"] == 3
    from heatmap_tpu.io.hmpb import HMPBDirSource

    vals = np.concatenate([
        bb["value"] for bb in HMPBDirSource(outdir).fast_batches(100)
    ])
    np.testing.assert_allclose(vals, [i + 0.5 for i in range(40)])
    # Reconvert.
    out2 = str(tmp_path / "w2.hmpb")
    convert_to_hmpb(f"hmpb:{out}", out2)
    assert HMPBSource(out2).has_value


def test_write_validates(tmp_path):
    p = str(tmp_path / "bad.hmpb")
    with pytest.raises(ValueError):
        write_hmpb(p, np.zeros(3), np.zeros(2), np.zeros(3, np.int32), [])
    with pytest.raises(ValueError):
        write_hmpb(p, np.zeros(1), np.zeros(1),
                   np.asarray([5], np.int32), ["only-one"])


def test_reader_rejects_non_hmpb(tmp_path):
    p = tmp_path / "x.hmpb"
    p.write_bytes(b"not a real file")
    with pytest.raises(ValueError):
        HMPBSource(str(p))


def test_truncated_file_detected(tmp_path):
    p = str(tmp_path / "t.hmpb")
    write_hmpb(p, np.zeros(100), np.zeros(100),
               np.zeros(100, np.int32), ["u"])
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 50)
    with pytest.raises(ValueError):
        HMPBSource(p)


def _write_csv(path, n, seed=0):
    rng = np.random.default_rng(seed)
    users = ["alice", "bob", "x-9", "rt-1", ""]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["latitude", "longitude", "user_id", "source", "timestamp"])
        for _ in range(n):
            w.writerow([
                rng.uniform(40, 50), rng.uniform(-130, -110),
                users[rng.integers(0, len(users))],
                "background" if rng.random() < 0.1 else "gps",
                int(rng.integers(0, 2**31)),
            ])


def test_convert_csv_and_run_job_fast_parity(tmp_path):
    from heatmap_tpu.io.sources import CSVSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

    csv_p = str(tmp_path / "pts.csv")
    hmpb_p = str(tmp_path / "pts.hmpb")
    _write_csv(csv_p, 2000, seed=5)
    stats = convert_to_hmpb(f"csv:{csv_p}", hmpb_p)
    assert stats["n"] == 2000
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=9)
    via_hmpb = run_job_fast(HMPBSource(hmpb_p), config=cfg)
    via_strings = run_job(CSVSource(csv_p, use_native=False), config=cfg)
    assert via_hmpb == via_strings


def test_string_batches_view_routes_identically(tmp_path):
    """HMPBSource.batches reconstructs user ids that ROUTE identically,
    so the generic pipeline gives the same blobs as the fast path."""
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

    csv_p = str(tmp_path / "pts.csv")
    hmpb_p = str(tmp_path / "pts.hmpb")
    _write_csv(csv_p, 1000, seed=6)
    convert_to_hmpb(f"csv:{csv_p}", hmpb_p)
    src = HMPBSource(hmpb_p)
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=9)
    assert run_job(src, config=cfg) == run_job_fast(src, config=cfg)


def test_convert_from_synthetic_source(tmp_path):
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast
    from heatmap_tpu.io.sources import SyntheticSource

    hmpb_p = str(tmp_path / "s.hmpb")
    stats = convert_to_hmpb("synthetic:3000:2", hmpb_p, batch_size=512)
    assert stats["n"] == 3000
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=9)
    via_hmpb = run_job_fast(HMPBSource(hmpb_p), config=cfg)
    direct = run_job(SyntheticSource(n=3000, seed=2), config=cfg,
                     batch_size=512)
    assert via_hmpb == direct


def test_cli_convert_then_fast_run(tmp_path):
    csv_p = tmp_path / "pts.csv"
    hmpb_p = tmp_path / "pts.hmpb"
    out = tmp_path / "blobs.jsonl"
    _write_csv(str(csv_p), 800, seed=7)
    env = dict(os.environ, PYTHONPATH=REPO)

    def run(*argv):
        return subprocess.run(
            [sys.executable, "-m", "heatmap_tpu", *argv],
            capture_output=True, text=True, timeout=240, cwd=REPO, env=env,
        )

    r = run("convert", "--input", f"csv:{csv_p}", "--output", str(hmpb_p))
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["n"] == 800
    r = run("run", "--backend", "cpu", "--fast",
            "--input", str(hmpb_p), "--output", f"jsonl:{out}",
            "--detail-zoom", "12", "--min-detail-zoom", "9")
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["blobs"] > 0


def test_alignment_and_endianness(tmp_path):
    """Every column starts naturally aligned for its element type and
    data is little-endian regardless of host order (the external-reader
    contract in the module docstring). Odd n exercises the worst case."""
    p = str(tmp_path / "a.hmpb")
    write_hmpb(p, np.asarray([1.5, 2.0, 3.0]), np.asarray([2.5, 1.0, 0.5]),
               np.asarray([0, 0, 0], np.int32), ["zz"], timestamp=[7, 8, 9])
    src = HMPBSource(p)
    for name, (off, dtype) in src._maps.items():
        assert off % np.dtype(dtype).itemsize == 0, (name, off)
    raw = open(p, "rb").read()
    off = src._maps["latitude"][0]
    assert off % 8 == 0
    assert raw[off:off + 8] == np.float64(1.5).astype("<f8").tobytes()


def test_convert_datetime_timestamps(tmp_path):
    import datetime as dt

    from heatmap_tpu.io.hmpb import _stamp_to_i64

    d = dt.datetime(2021, 6, 1, 12, tzinfo=dt.timezone.utc)
    assert _stamp_to_i64(d) == int(d.timestamp() * 1000)
    assert _stamp_to_i64(dt.date(2021, 6, 1)) == int(
        dt.datetime(2021, 6, 1, tzinfo=dt.timezone.utc).timestamp() * 1000
    )
    assert _stamp_to_i64(None) == TS_MISSING
    assert _stamp_to_i64("1500") == 1500


def test_hmpb_to_hmpb_reconvert(tmp_path):
    csv_p = str(tmp_path / "pts.csv")
    h1 = str(tmp_path / "a.hmpb")
    h2 = str(tmp_path / "b.hmpb")
    _write_csv(csv_p, 500, seed=9)
    convert_to_hmpb(f"csv:{csv_p}", h1)
    convert_to_hmpb(f"hmpb:{h1}", h2)
    a, b = HMPBSource(h1), HMPBSource(h2)
    assert a.n == b.n and a.names == b.names
    (ba,), (bb,) = list(a.fast_batches(1000)), list(b.fast_batches(1000))
    for k in ("latitude", "longitude", "timestamp", "routed", "background"):
        np.testing.assert_array_equal(ba[k], bb[k])


def test_missing_timestamps_sentinel(tmp_path):
    p = str(tmp_path / "nt.hmpb")
    write_hmpb(p, np.zeros(3), np.zeros(3), np.zeros(3, np.int32), ["u"])
    (b,) = list(HMPBSource(p).fast_batches(10))
    assert (b["timestamp"] == TS_MISSING).all()
    (sb,) = list(HMPBSource(p).batches(10))
    assert sb["timestamp"] == [None, None, None]


class TestHMPBDirSource:
    def _make_dir(self, tmp_path, n=5000, parts=4):
        from heatmap_tpu.io.hmpb import convert_to_hmpb

        csv = tmp_path / "pts.csv"
        _write_csv(csv, n, seed=11)
        d = tmp_path / "shards"
        stats = convert_to_hmpb(str(csv), str(d),
                                shard_rows=-(-n // parts))
        return d, stats

    def test_sharded_convert_and_fast_job_parity(self, tmp_path):
        """A directory of part files must produce exactly the blobs of
        the single-file conversion, through run_job_fast (per-file name
        tables remap into one global intern)."""
        import jax

        jax.config.update("jax_enable_x64", True)
        from heatmap_tpu.io.hmpb import HMPBDirSource, HMPBSource, convert_to_hmpb
        from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

        d, stats = self._make_dir(tmp_path)
        assert stats["parts"] >= 4
        single = tmp_path / "one.hmpb"
        convert_to_hmpb(str(tmp_path / "pts.csv"), str(single))
        cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=8)
        want = run_job_fast(HMPBSource(str(single)), config=cfg,
                            batch_size=700)
        got = run_job_fast(HMPBDirSource(str(d)), config=cfg,
                           batch_size=700)
        assert want == got

    def test_interleaved_shards_cover_all_files_once(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource

        d, _ = self._make_dir(tmp_path)
        full = HMPBDirSource(str(d))
        seen = []
        for k in range(3):
            s = HMPBDirSource(str(d), shard_index=k, shard_count=3)
            seen.extend(i for i, _ in s.my_files())
        assert sorted(seen) == list(range(full.n_ranges))

    def test_range_batches_reread_one_file(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource, HMPBSource

        d, _ = self._make_dir(tmp_path)
        s = HMPBDirSource(str(d))
        got = [u for b in s.range_batches(1) for u in b["user_id"]]
        again = [u for b in s.range_batches(1) for u in b["user_id"]]
        assert got == again
        direct = [u for b in HMPBSource(s.files[1]).batches()
                  for u in b["user_id"]]
        assert got == direct

    def test_open_source_detects_directory(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource
        from heatmap_tpu.io.sources import open_source

        d, _ = self._make_dir(tmp_path)
        assert isinstance(open_source(f"hmpb:{d}"), HMPBDirSource)
        with pytest.raises(ValueError, match="no .hmpb files"):
            HMPBDirSource(str(tmp_path))

    def test_bad_shard_assignment_rejected(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource

        d, _ = self._make_dir(tmp_path)
        with pytest.raises(ValueError, match="shard"):
            HMPBDirSource(str(d), shard_index=3, shard_count=3)

    def test_multihost_shard_source_reinstantiates(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource
        from heatmap_tpu.parallel.multihost import shard_source

        d, _ = self._make_dir(tmp_path)
        s = shard_source(HMPBDirSource(str(d)), process_count=2,
                         process_index=1)
        assert isinstance(s, HMPBDirSource)
        assert s.shard_count == 2 and s.shard_index == 1
        assert all(i % 2 == 1 for i, _ in s.my_files())

    def test_reconvert_removes_stale_parts(self, tmp_path):
        from heatmap_tpu.io.hmpb import HMPBDirSource, convert_to_hmpb

        d, stats = self._make_dir(tmp_path, n=5000, parts=5)
        assert stats["parts"] >= 5
        stats2 = convert_to_hmpb(str(tmp_path / "pts.csv"), str(d),
                                 shard_rows=5000)
        assert stats2["parts"] == 1
        assert HMPBDirSource(str(d)).n_ranges == 1


class TestFastBounded:
    @pytest.mark.slow
    def test_fast_bounded_matches_fast_and_string(self, tmp_path):
        """--fast --max-points-in-flight: chunked cascade with fast
        ingest must produce the exact blobs of both the unbounded fast
        path and the bounded string path, at the default z21 shape."""
        import jax

        jax.config.update("jax_enable_x64", True)
        from heatmap_tpu.io.hmpb import HMPBSource, convert_to_hmpb
        from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

        csv = tmp_path / "pts.csv"
        _write_csv(csv, 4000, seed=23)
        hmpb = tmp_path / "p.hmpb"
        convert_to_hmpb(str(csv), str(hmpb))
        cfg = BatchJobConfig()
        want = run_job_fast(HMPBSource(str(hmpb)), config=cfg,
                            batch_size=700)
        got = run_job_fast(HMPBSource(str(hmpb)), config=cfg,
                           batch_size=700, max_points_in_flight=900)
        assert want == got
        seq = run_job_fast(HMPBSource(str(hmpb)), config=cfg,
                           batch_size=700, max_points_in_flight=900,
                           overlap_ingest=False)
        assert want == seq
        # The string bounded path agrees too (cross-ingest identity).
        from heatmap_tpu.io.sources import CSVSource

        st = run_job(CSVSource(str(csv)), config=cfg, batch_size=700,
                     max_points_in_flight=900)
        assert want == st

    def test_fast_bounded_rejects_checkpoint_combo(self, tmp_path):
        from heatmap_tpu.io.hmpb import convert_to_hmpb
        from heatmap_tpu.pipeline import run_job_fast

        csv = tmp_path / "pts.csv"
        _write_csv(csv, 50, seed=1)
        hmpb = tmp_path / "p.hmpb"
        convert_to_hmpb(str(csv), str(hmpb))
        from heatmap_tpu.io.hmpb import HMPBSource

        with pytest.raises(ValueError, match="mutually exclusive"):
            run_job_fast(HMPBSource(str(hmpb)),
                         checkpoint_dir=str(tmp_path / "ck"),
                         max_points_in_flight=100)

    @pytest.mark.slow
    def test_fast_bounded_dated_timespans(self, tmp_path):
        import jax

        jax.config.update("jax_enable_x64", True)
        from heatmap_tpu.io.hmpb import HMPBSource, convert_to_hmpb
        from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

        csv = tmp_path / "pts.csv"
        _write_csv(csv, 1500, seed=8)  # every row carries an i64 stamp
        hmpb = tmp_path / "p.hmpb"
        convert_to_hmpb(str(csv), str(hmpb))
        cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=8,
                             timespans=("alltime", "day"))
        want = run_job_fast(HMPBSource(str(hmpb)), config=cfg,
                            batch_size=400)
        got = run_job_fast(HMPBSource(str(hmpb)), config=cfg,
                           batch_size=400, max_points_in_flight=500)
        assert want == got
