"""Hierarchical tracing tests: span trees, cross-thread propagation,
traceparent continuation, Chrome export, and critical-path analysis.

The acceptance bar (ISSUE 6): a sampled serve request and a delta
apply each produce ONE connected span tree — a single root, every
span's parent present, the trace id stamped onto the corresponding
``http_request``/``stage_end`` events — exported as Chrome trace-event
JSON that ``tools/trace_analyze.py`` loads, with self-times summing to
the root's wall clock within 5%.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from heatmap_tpu import obs
from heatmap_tpu.obs import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import trace_analyze  # noqa: E402  (tools/ is import-shared, not a pkg)


class TestSpanTree:
    def test_off_by_default_and_hooks_uninstalled(self):
        from heatmap_tpu.obs import events
        from heatmap_tpu.utils import trace as utrace

        assert not tracing.tracing_enabled()
        assert tracing.begin_span("x") is None
        assert tracing.current_span() is None
        assert tracing.current_traceparent() is None
        # zero-cost stance: with tracing off nothing is hooked
        assert utrace._tree_begin is None
        assert utrace._tree_end is None
        assert events._trace_ids is None
        # and context_bound is the identity
        fn = lambda: None  # noqa: E731
        assert tracing.context_bound(fn) is fn

    def test_root_on_demand_nesting_and_new_trace_after_unwind(self):
        collector = tracing.enable_tracing()
        with tracing.span("root") as root:
            assert root.parent_id is None
            with tracing.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
                with tracing.span("grandchild") as g:
                    assert g.parent_id == child.span_id
        assert {s["name"] for s in collector.spans()} == {
            "root", "child", "grandchild"}
        with tracing.span("root2") as root2:
            assert root2.parent_id is None
            assert root2.trace_id != root.trace_id

    def test_unsampled_root_suppresses_descendants(self):
        collector = tracing.enable_tracing(sample=0.0)
        sentinel = tracing.begin_span("root")
        assert not isinstance(sentinel, tracing.Span)
        # descendants no-op instead of opening fresh roots
        assert tracing.begin_span("child") is None
        assert tracing.current_span() is None
        # the sentinel still renders a (sampled=00) traceparent so
        # downstream services can honor the decision
        tp = tracing.current_traceparent()
        assert tp is not None and tp.endswith("-00")
        tracing.end_span(sentinel)
        assert collector.spans() == []
        # context unwound: the next root starts clean
        with tracing.span("after") as sp:
            assert sp is None  # sample=0.0: never sampled

    def test_sampling_is_seeded_and_reproducible(self):
        a = tracing.TraceCollector(sample=0.5, seed=7)
        b = tracing.TraceCollector(sample=0.5, seed=7)
        decisions = [a.sample_decision() for _ in range(64)]
        assert decisions == [b.sample_decision() for _ in range(64)]
        assert any(decisions) and not all(decisions)

    def test_collector_caps_buffered_spans(self, monkeypatch):
        monkeypatch.setattr(tracing, "MAX_SPANS", 3)
        collector = tracing.enable_tracing()
        for i in range(5):
            with tracing.span(f"s{i}"):
                pass
        assert len(collector.spans()) == 3
        assert collector.dropped == 2
        assert collector.summary()["dropped"] == 2


class TestTraceparent:
    def test_roundtrip_matches_ambient_span(self):
        tracing.enable_tracing()
        with tracing.span("root"):
            cur = tracing.current_span()
            tp = tracing.current_traceparent()
            assert tracing.parse_traceparent(tp) == (
                cur.trace_id, cur.span_id, True)

    @pytest.mark.parametrize("bad", [
        None, "", "not-a-header", "00-abc-def-01",
        "00-" + "g" * 32 + "-" + "0" * 16 + "-01",   # non-hex trace id
        "00-" + "0" * 32 + "-" + "0" * 15 + "-01",   # short span id
    ])
    def test_malformed_headers_are_ignored_not_fatal(self, bad):
        assert tracing.parse_traceparent(bad) is None

    def test_incoming_header_overrides_probabilistic_sampling(self):
        # sampled flag forces recording even at sample=0
        collector = tracing.enable_tracing(sample=0.0)
        header = f"00-{'ab' * 16}-{'cd' * 8}-01"
        sp = tracing.begin_span("serve.request", traceparent=header)
        assert isinstance(sp, tracing.Span)
        assert sp.trace_id == "ab" * 16
        assert sp.parent_id == "cd" * 8
        tracing.end_span(sp)
        [rec] = collector.spans()
        assert rec["trace_id"] == "ab" * 16
        # ...and flags=00 forces suppression even at sample=1
        collector = tracing.enable_tracing(sample=1.0)
        sp = tracing.begin_span(
            "serve.request", traceparent=f"00-{'ab' * 16}-{'cd' * 8}-00")
        assert not isinstance(sp, tracing.Span)
        tracing.end_span(sp)
        assert collector.spans() == []


class TestThreadPropagation:
    def test_context_bound_carries_span_into_pool_worker(self):
        tracing.enable_tracing()
        seen = []
        with tracing.span("root") as root:

            def work():
                with tracing.span("pool.child") as child:
                    seen.append((child.trace_id, child.parent_id))

            with concurrent.futures.ThreadPoolExecutor(2) as pool:
                pool.submit(tracing.context_bound(work)).result()
        assert seen == [(root.trace_id, root.span_id)]

    def test_unbound_thread_starts_its_own_trace(self):
        tracing.enable_tracing()
        seen = []
        with tracing.span("root") as root:

            def work():
                with tracing.span("orphan") as sp:
                    seen.append((sp.trace_id, sp.parent_id))

            t = threading.Thread(target=work)
            t.start()
            t.join()
        [(trace_id, parent_id)] = seen
        assert trace_id != root.trace_id  # fresh context -> fresh root
        assert parent_id is None


class TestEventLogStorm:
    def test_eight_thread_storm_is_monotonic_and_untorn(self, tmp_path):
        """8 threads x 250 emits through the module-level emit path:
        every JSONL line must parse (no torn writes) and the seq
        column must be exactly 0..N-1 in file order."""
        path = str(tmp_path / "storm.jsonl")
        obs.set_event_log(obs.EventLog(path))
        filler = "/tiles/default/7/20/44.json" * 20  # force long lines

        def worker():
            for _ in range(250):
                obs.emit("http_request", route="tiles", status=200,
                         path=filler, ms=1.0)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        obs.get_event_log().close()
        obs.set_event_log(None)
        with open(path) as f:
            lines = f.read().splitlines()
        assert len(lines) == 2000
        records = [json.loads(line) for line in lines]  # untorn
        assert [r["seq"] for r in records] == list(range(2000))
        assert all(r["path"] == filler for r in records)


@pytest.fixture(scope="module")
def tile_artifacts(tmp_path_factory):
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("trace_artifacts")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=5)
    with open_sink(f"arrays:{root}/levels") as sink:
        run_job(open_source("synthetic:2000:7"), sink, config)
    return f"arrays:{root}/levels"


def _pick_tile(app):
    from heatmap_tpu.tilemath.morton import morton_decode_np

    layer = app.store.layer("default")
    d = layer.detail_zooms[-1]
    delta = layer.result_delta
    code = int(layer.levels[d].codes[0]) >> (2 * delta)
    r, c = morton_decode_np(np.asarray([code], np.int64))
    return d - delta, int(c[0]), int(r[0])


class TestServeRequestTrace:
    def test_sampled_request_yields_connected_tree(self, tile_artifacts,
                                                   tmp_path):
        from heatmap_tpu.obs import slo
        from heatmap_tpu.serve import (ServeApp, TileCache, TileStore,
                                       serve_in_thread)

        obs.enable_metrics(True)
        collector = tracing.enable_tracing()
        slo.install_specs(["tiles-ok:error_rate:target=0.9,window_s=60"])
        ev_path = str(tmp_path / "ev.jsonl")
        obs.set_event_log(obs.EventLog(ev_path))
        # render_timeout_s routes renders through the worker pool, which
        # is the cross-thread propagation path under test
        app = ServeApp(TileStore(tile_artifacts),
                       TileCache(max_bytes=1 << 20), render_timeout_s=30.0)
        server, base = serve_in_thread(app)
        try:
            z, x, y = _pick_tile(app)
            resp = urllib.request.urlopen(
                f"{base}/tiles/default/{z}/{x}/{y}.json")
            assert resp.status == 200
            echoed = resp.headers.get("traceparent")
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz").read())
        finally:
            server.shutdown()
            server.server_close()
        obs.get_event_log().close()
        obs.set_event_log(None)

        spans = collector.spans()
        reqs = [s for s in spans if s["name"] == "serve.request"
                and "/tiles/" in s["attrs"].get("path", "")]
        assert len(reqs) == 1
        root = reqs[0]
        assert root["parent_id"] is None
        tree = [s for s in spans if s["trace_id"] == root["trace_id"]]
        ids = {s["span_id"] for s in tree}
        assert all(s["parent_id"] in ids for s in tree
                   if s["parent_id"] is not None)
        # the render ran in the pool thread yet joined the request tree
        [worker] = [s for s in tree if s["name"] == "tile.render.worker"]
        assert worker["tid"] != root["tid"]
        # the response echoes the request's trace identity
        assert echoed is not None
        assert tracing.parse_traceparent(echoed)[0] == root["trace_id"]
        # the http_request event carries the same identity
        tile_reqs = [r for r in obs.read_events(ev_path)
                     if r["event"] == "http_request"
                     and "/tiles/" in r.get("path", "")]
        assert [r["trace_id"] for r in tile_reqs] == [root["trace_id"]]
        # /healthz folds the live SLO status (served 200s -> ok)
        assert health["slo"]["ok"] is True
        assert [o["name"] for o in health["slo"]["objectives"]] == [
            "tiles-ok"]

    def test_incoming_traceparent_continues_client_trace(
            self, tile_artifacts):
        from heatmap_tpu.serve import (ServeApp, TileCache, TileStore,
                                       serve_in_thread)

        collector = tracing.enable_tracing(sample=0.0)  # header decides
        client_trace = "ab" * 16
        app = ServeApp(TileStore(tile_artifacts),
                       TileCache(max_bytes=1 << 20))
        server, base = serve_in_thread(app)
        try:
            z, x, y = _pick_tile(app)
            req = urllib.request.Request(
                f"{base}/tiles/default/{z}/{x}/{y}.json",
                headers={"traceparent": f"00-{client_trace}-{'cd' * 8}-01"})
            urllib.request.urlopen(req)
            # unsampled request: no spans recorded for it
            urllib.request.urlopen(f"{base}/tiles/default/{z}/{x}/{y}.json")
        finally:
            server.shutdown()
            server.server_close()
        spans = collector.spans()
        assert spans, "sampled flag must override sample=0.0"
        assert {s["trace_id"] for s in spans} == {client_trace}
        [root] = [s for s in spans if s["name"] == "serve.request"]
        assert root["parent_id"] == "cd" * 8  # parented to the client


class TestDeltaApplyTraceAndAnalysis:
    def test_apply_tree_export_and_critical_path(self, tmp_path):
        from heatmap_tpu import delta
        from heatmap_tpu.io import open_source
        from heatmap_tpu.pipeline import BatchJobConfig

        collector = tracing.enable_tracing()
        ev_path = str(tmp_path / "ev.jsonl")
        obs.set_event_log(obs.EventLog(ev_path))
        config = BatchJobConfig(detail_zoom=10, min_detail_zoom=5)
        delta.apply_batch(str(tmp_path / "store"),
                          open_source("synthetic:800:3"), config)
        obs.get_event_log().close()
        obs.set_event_log(None)

        # -- connected tree: one root, one trace, every parent present
        spans = collector.spans()
        roots = [s for s in spans if s["parent_id"] is None]
        assert [r["name"] for r in roots] == ["delta.apply"]
        assert len({s["trace_id"] for s in spans}) == 1
        ids = {s["span_id"] for s in spans}
        assert all(s["parent_id"] in ids for s in spans
                   if s["parent_id"] is not None)
        assert {"delta.compute", "run_job", "cascade"} <= {
            s["name"] for s in spans}

        # -- stage_end events are stamped with the same trace
        stage_recs = [r for r in obs.read_events(ev_path)
                      if r["event"] == "stage_end"]
        assert stage_recs
        assert {r["trace_id"] for r in stage_recs} == {
            roots[0]["trace_id"]}

        # -- Chrome export: valid, loadable, analyzable
        out = str(tmp_path / "trace.json")
        n = collector.export_chrome(out)
        assert n == len(spans)
        with open(out) as f:
            doc = json.load(f)
        assert doc["displayTimeUnit"] == "ms"
        phases = {e["ph"] for e in doc["traceEvents"]}
        assert phases == {"M", "X"}
        loaded = trace_analyze.load_events(out)
        assert len(loaded) == len(spans)

        # -- critical path + self-time attribution
        result = trace_analyze.analyze(loaded)
        assert result["n_traces"] == 1
        [row] = result["traces"]
        assert row["root"] == "delta.apply"
        # self-times over the tree sum to the root's wall within 5%
        assert row["self_sum_us"] == pytest.approx(row["wall_us"],
                                                   rel=0.05)
        path_names = [h["name"] for h in row["critical_path"]]
        assert path_names[0] == "delta.apply"
        assert len(path_names) >= 3
        # top_self covers every distinct span name
        assert {t["name"] for t in result["top_self"]} <= {
            s["name"] for s in spans}
        # the formatted report renders without error
        assert "critical path" in trace_analyze.format_report(result)
