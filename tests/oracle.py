"""Pure-Python oracle of the reference job's semantics, for golden tests.

Re-implements (NOT copies) the behavioral contract documented in
SURVEY.md §2/§3/§8 from the reference formulas (reference tile.py:8-30,
heatmap.py:25-129): scalar CPython-double tile math, user-group routing,
the per-level flatMap→reduceByKey→map→groupByKey cascade — including its
latent '`all`'-amplification bug (SURVEY.md §8.1), reproducible here so
the framework's compat mode can be tested against it.
"""

from __future__ import annotations

import math
from collections import defaultdict

DETAIL_ZOOM_DELTA = 5
KEY_SEP = "|"


# -- scalar tile math (reference tile.py:16-30 semantics) -------------------


def row_from_latitude(lat: float, zoom: int) -> float:
    phi = lat * math.pi / 180
    return math.floor(
        (1 - math.log(math.tan(phi) + 1 / math.cos(phi)) / math.pi) / 2 * (1 << zoom)
    )


def column_from_longitude(lon: float, zoom: int) -> float:
    return math.floor((lon + 180.0) / 360.0 * (1 << zoom))


def latitude_from_row(row: float, zoom: int) -> float:
    n = math.pi - 2.0 * math.pi * row / (1 << zoom)
    return 180.0 / math.pi * math.atan(0.5 * (math.exp(n) - math.exp(-n)))


def longitude_from_column(col: float, zoom: int) -> float:
    return float(col) / (1 << zoom) * 360.0 - 180.0


def tile_id(lat: float, lon: float, zoom: int) -> str:
    return f"{zoom}_{int(row_from_latitude(lat, zoom))}_{int(column_from_longitude(lon, zoom))}"


def tile_center(tid: str):
    z, r, c = (int(p) for p in tid.split("_"))
    lat_n = latitude_from_row(r, z)
    lat_s = latitude_from_row(r + 1, z)
    lon_w = longitude_from_column(c, z)
    lon_e = longitude_from_column(c + 1, z)
    return (lat_n + lat_s) / 2.0, (lon_e + lon_w) / 2.0, z


# -- pipeline semantics (reference heatmap.py) ------------------------------


def user_groups(user_id: str):
    """Reference heatmap.py:64-70: 'all' + routed user id (x-excluded, rt- pooled)."""
    groups = ["all"]
    if not user_id[:1] == "x":
        groups.append("route" if user_id[:3] == "rt-" else user_id)
    return groups


def load_points(rows, detail_zoom: int):
    """Reference dataframe_loader semantics (heatmap.py:25-36)."""
    out = []
    for row in rows:
        if row.get("source") == "background":
            continue
        out.append(
            {
                "tileId": tile_id(row["latitude"], row["longitude"], detail_zoom),
                "userId": row["user_id"],
                "count": 1.0,
            }
        )
    return out


def cascade(locations, detail_zoom: int, min_detail_zoom: int, amplify_all: bool = True):
    """The reference build_heatmaps cascade (heatmap.py:107-118).

    Returns {(userId|timespan|coarseTileId): {detailTileId: count}} for
    detail zooms ``detail_zoom`` down to ``min_detail_zoom+1``.

    ``amplify_all=True`` reproduces the reference's re-expansion of
    already-aggregated records each level (the '`all`' amplification,
    SURVEY.md §8.1: all_z = 2*all_{z+1} + sum_users user_{z+1}).
    ``amplify_all=False`` computes the mathematically correct rollup:
    group expansion applied once, at the detail level.
    """
    heatmaps = {}
    if amplify_all:
        records = [
            (loc["userId"], loc["tileId"], loc["count"]) for loc in locations
        ]
    else:
        # Correct mode: expand groups once at ingest.
        records = [
            (g, loc["tileId"], loc["count"])
            for loc in locations
            for g in user_groups(loc["userId"])
        ]

    for zoom in range(detail_zoom, min_detail_zoom, -1):
        # flatMap(mapper): re-bin tile center at `zoom`, expand groups
        # (reference heatmap.py:57-77).
        counts = defaultdict(float)
        for user_id, tid, count in records:
            lat, lon, _ = tile_center(tid)
            new_tid = tile_id(lat, lon, zoom)
            if amplify_all:
                for g in user_groups(user_id):
                    counts[(g, new_tid)] += count
            else:
                counts[(user_id, new_tid)] += count

        # map_to_resultset + groupByKey (reference heatmap.py:79-90,112).
        level = defaultdict(dict)
        for (user_id, tid), count in counts.items():
            lat, lon, z = tile_center(tid)
            coarse = tile_id(lat, lon, z - DETAIL_ZOOM_DELTA)
            level[f"{user_id}{KEY_SEP}alltime{KEY_SEP}{coarse}"][tid] = count
        heatmaps.update(level)

        # heatmap_to_locations (reference heatmap.py:92-105): next level
        # consumes this level's aggregates.
        records = [
            (key.split(KEY_SEP)[0], tid, cnt)
            for key, hm in level.items()
            for tid, cnt in hm.items()
        ]
    return heatmaps


def run_job(rows, detail_zoom: int = 21, min_detail_zoom: int = 5, amplify_all: bool = True):
    """End-to-end oracle of batchMain (reference heatmap.py:152-158), sans I/O."""
    return cascade(
        load_points(rows, detail_zoom), detail_zoom, min_detail_zoom, amplify_all
    )


def splat_oracle_np(raster, size=9, sigma=None):
    """Direct (non-separable) numpy 2D Gaussian convolution — the
    independent oracle for ops.splat's separable formulation."""
    import numpy as np

    if sigma is None:
        sigma = size / 4.0
    x = np.arange(size, dtype=np.float64) - (size - 1) / 2.0
    k1 = np.exp(-0.5 * (x / sigma) ** 2)
    k1 /= k1.sum()
    k2 = np.outer(k1, k1)
    r = np.asarray(raster, np.float64)
    h, w = r.shape
    half = size // 2
    padded = np.zeros((h + 2 * half, w + 2 * half))
    padded[half : half + h, half : half + w] = r
    out = np.zeros_like(r)
    for dy in range(size):
        for dx in range(size):
            out += k2[dy, dx] * padded[dy : dy + h, dx : dx + w]
    return out
