"""Flight recorder + incident bundle tests (docs/observability.md).

Pins the tentpole contracts: bounded rings under concurrency,
tail-based promotion of unsampled trees byte-for-byte into the
collector, dedup against head-sampled roots, one-bundle-per-episode
trigger edges with a deterministic injectable clock, atomic size-capped
bundles with age-wins pruning, OpenMetrics exemplar round-trips, and
the zero-cost-when-off acceptance bar: blobs byte-identical with the
recorder + incident manager armed vs everything off.
"""

import json
import os
import threading
import time

import pytest

from heatmap_tpu import obs
from heatmap_tpu.obs import incident, tracing
from heatmap_tpu.obs import recorder as recorder_mod
from heatmap_tpu.obs.incident import IncidentManager
from heatmap_tpu.obs.recorder import FlightRecorder


def _shadow_tree(names=("serve.request", "tile.render")):
    """Open an unsampled root + child chain; returns the open spans
    root-first (caller ends them)."""
    spans = []
    for name in names:
        spans.append(tracing.begin_span(name))
    return spans


class TestRingBounded:
    def test_ring_bounded_under_thread_storm(self):
        """8 threads, 1600 completed spans, one 64-slot subsystem ring:
        the ring never exceeds its bound and every eviction is counted
        (ring size + dropped == spans recorded, exactly)."""
        obs.enable_metrics(True)
        tracing.enable_tracing(sample=0.0)
        rec = FlightRecorder(max_spans=64)
        recorder_mod.install(rec)
        n_threads, per_thread = 8, 100

        def worker():
            for _ in range(per_thread):
                root = tracing.begin_span("storm.op")
                child = tracing.begin_span("storm.child")
                tracing.end_span(child)
                tracing.end_span(root)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = n_threads * per_thread * 2
        stats = rec.stats()
        assert stats["subsystems"] == ["storm"]
        assert stats["spans"] == 64
        assert stats["dropped"] == total - 64
        assert obs.RECORDER_DROPPED.value() == total - 64
        # The eviction index stays consistent: every ringed span is
        # still reachable through its trace.
        assert len(rec.span_records()) == 64

    def test_event_ring_bounded(self):
        rec = FlightRecorder(max_events=8)
        recorder_mod.install(rec)
        for i in range(20):
            rec.record_event({"event": "http_request", "ts": float(i),
                              "seq": i, "status": 200})
        assert len(rec.event_records()) == 8
        # Oldest-first by the envelope (ts, seq).
        assert [r["seq"] for r in rec.event_records()] == list(range(12, 20))
        assert rec.dropped == 12

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError, match="positive"):
            FlightRecorder(max_spans=0)


class TestTailPromotion:
    def test_unsampled_error_tree_promotes_byte_for_byte(self):
        """sample=0 (strictly harder than the acceptance 0.01): the
        whole request tree runs as shadow spans, renders flags 00 on
        the wire, and a 503 promotes it into the collector as the
        exact records a head-sampled run would have contributed."""
        collector = tracing.enable_tracing(sample=0.0)
        rec = FlightRecorder(max_spans=64)
        recorder_mod.install(rec)

        root, child = _shadow_tree()
        assert isinstance(root, tracing.Span) and root.shadow
        assert tracing.current_traceparent().endswith("-00")
        tracing.end_span(child)
        assert collector.spans() == []  # head decision: dropped

        assert recorder_mod.maybe_promote(root, status=503)
        tracing.end_span(root)  # root rides the live-forward path
        got = collector.spans()
        assert {r["name"] for r in got} == {"serve.request", "tile.render"}
        assert {r["trace_id"] for r in got} == {root.trace_id}
        ringed = {r["span_id"]: r for r in rec.span_records()}
        for r in got:
            assert json.dumps(r, sort_keys=True) == json.dumps(
                ringed[r["span_id"]], sort_keys=True)

    def test_tail_latency_threshold_promotes(self):
        collector = tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder(tail_latency_s=0.05))
        root = tracing.begin_span("serve.request")
        assert not recorder_mod.maybe_promote(root, ms=10.0)
        assert recorder_mod.maybe_promote(root, ms=80.0)
        tracing.end_span(root)
        assert [r["name"] for r in collector.spans()] == ["serve.request"]

    def test_fast_ok_tree_stays_out_of_collector(self):
        collector = tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder(tail_latency_s=10.0))
        root = tracing.begin_span("serve.request")
        assert not recorder_mod.maybe_promote(root, status=200, ms=1.0)
        tracing.end_span(root)
        assert collector.spans() == []

    def test_promotion_dedups_against_head_sampled_roots(self):
        """A sampled tree reaches the collector once through the normal
        path; promoting it again copies nothing (sampled spans are
        never shadow) and is idempotent."""
        collector = tracing.enable_tracing(sample=1.0)
        rec = FlightRecorder()
        recorder_mod.install(rec)
        root = tracing.begin_span("serve.request")
        assert not root.shadow
        recorder_mod.maybe_promote(root, status=503)
        tracing.end_span(root)
        assert len(collector.spans()) == 1
        assert rec.promote(root.trace_id) == 0  # second promote: no-op
        assert len(collector.spans()) == 1

    def test_fault_injected_event_promotes_ambient_tree(self):
        obs.enable_metrics(True)  # record_fault gates on telemetry
        collector = tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder())
        root = tracing.begin_span("ingest.tick")
        obs.record_fault("ingest.tick", 0, key=0)
        tracing.end_span(root)
        assert [r["name"] for r in collector.spans()] == ["ingest.tick"]


def _fake_clock(start=1000.0):
    state = [start]

    def clock():
        return state[0]

    clock.advance = lambda s: state.__setitem__(0, state[0] + s)
    return clock


class TestIncidentTriggers:
    def test_one_bundle_per_storm_episode(self, tmp_path):
        """Seeded fault storm: threshold faults in-window flush exactly
        one bundle; the episode resets; a repeat storm inside the
        rate-limit window is suppressed, after it flushes again."""
        clock = _fake_clock()
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="ep",
                              storm_threshold=3, storm_window_s=10.0,
                              min_interval_s=30.0, clock=clock)
        incident.set_manager(mgr)
        for i in range(6):  # two full episodes back to back
            mgr.on_event({"event": "fault_injected", "ts": float(i),
                          "site": "tile.render", "fault_seq": i})
        assert len(mgr.flushed) == 1  # second episode rate-limited
        assert mgr.suppressed == 1
        clock.advance(31.0)
        for i in range(3):
            mgr.on_event({"event": "fault_injected", "ts": 100.0 + i,
                          "site": "tile.render", "fault_seq": 6 + i})
        assert len(mgr.flushed) == 2
        triggers = [json.load(open(os.path.join(p, "manifest.json")))
                    ["trigger"] for p in mgr.flushed]
        assert triggers == ["fault_storm", "fault_storm"]

    def test_slo_breach_and_degraded_enter_edges(self, tmp_path):
        clock = _fake_clock()
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="edge",
                              min_interval_s=30.0, clock=clock)
        incident.set_manager(mgr)
        mgr.on_event({"event": "slo_breach", "slo": "tiles-fast"})
        mgr.on_event({"event": "degraded_enter", "cause": "render"})
        # Distinct kinds rate-limit independently.
        assert len(mgr.flushed) == 2
        mgr.on_event({"event": "slo_breach", "slo": "tiles-fast"})
        assert len(mgr.flushed) == 2 and mgr.suppressed == 1

    def test_module_trigger_noop_without_manager(self):
        assert incident.get_manager() is None
        assert incident.trigger("exception", detail="x") is None

    def test_trigger_emits_incident_flush_event(self, tmp_path):
        clock = _fake_clock()
        events_path = str(tmp_path / "events.jsonl")
        obs.set_event_log(obs.EventLog(events_path, run_id="t"))
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="t",
                              clock=clock)
        incident.set_manager(mgr)
        obs.enable_metrics(True)
        path = mgr.trigger("shed", detail="bound 2")
        obs.get_event_log().close()
        obs.set_event_log(None)
        assert path is not None
        [rec] = [r for r in obs.read_events(events_path)
                 if r["event"] == "incident_flush"]
        assert rec["trigger"] == "shed" and rec["path"] == path
        assert obs.INCIDENTS_TOTAL.value(trigger="shed") == 1


class TestBundles:
    def test_bundle_is_atomic_and_complete(self, tmp_path):
        out = tmp_path / "inc"
        tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder())
        mgr = IncidentManager(str(out), run_id="ab12",
                              clock=_fake_clock())
        incident.set_manager(mgr)
        root, child = _shadow_tree()
        tracing.end_span(child)
        tracing.end_span(root)
        path = mgr.trigger("exception", detail="RuntimeError('x')")
        assert os.path.basename(path) == "ab12-0"
        assert sorted(os.listdir(path)) == [
            "events.json", "manifest.json", "metrics.json", "state.json",
            "trace.json"]
        # No torn tmp dirs left behind.
        assert not [n for n in os.listdir(out) if n.startswith(".tmp-")]
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert manifest["trigger"] == "exception"
        assert manifest["run_id"] == "ab12" and manifest["seq"] == 0
        for name, nbytes in manifest["files"].items():
            assert os.path.getsize(os.path.join(path, name)) == nbytes
        # trace.json replays as a valid Perfetto doc holding the tree.
        doc = json.load(open(os.path.join(path, "trace.json")))
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert names == {"serve.request", "tile.render"}

    def test_size_cap_trims_tails(self, tmp_path):
        recorder_mod.install(FlightRecorder(max_events=512))
        rec = recorder_mod.get_recorder()
        for i in range(400):
            rec.record_event({"event": "http_request", "ts": float(i),
                              "seq": i, "pad": "x" * 256})
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="cap",
                              max_bytes=20_000, clock=_fake_clock())
        incident.set_manager(mgr)
        path = mgr.trigger("shed")
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        assert manifest["bytes"] <= 20_000
        tail = json.load(open(os.path.join(path, "events.json")))
        assert 0 < len(tail) < 400
        # Oldest-first trimming: the newest events survive.
        assert tail[-1]["seq"] == 399

    def test_prune_age_wins(self, tmp_path):
        out = tmp_path / "inc"
        mgr = IncidentManager(str(out), run_id="pr", keep=2,
                              min_age_s=5.0, min_interval_s=0.0)
        incident.set_manager(mgr)
        for _ in range(4):
            mgr.trigger("shed")
        assert len(mgr.flushed) == 4
        # All four are younger than min_age_s: count says prune, age
        # wins — nothing is deleted.
        assert mgr.prune()["pruned"] == 0
        assert len(os.listdir(out)) == 4
        # Backdate the two oldest; now count AND age agree.
        old = time.time() - 100.0
        for name in ("pr-0", "pr-1"):
            os.utime(os.path.join(out, name), (old, old))
        assert mgr.prune()["pruned"] == 2
        assert sorted(os.listdir(out)) == ["pr-2", "pr-3"]


class TestExemplars:
    def test_exemplar_render_round_trip(self):
        """A histogram observation inside a span renders its trace
        identity on the matching bucket line (OpenMetrics style) and
        in the snapshot; registry reset clears it."""
        obs.enable_metrics(True)
        tracing.enable_tracing(sample=1.0)
        reg = obs.get_registry()
        h = reg.histogram("rt_seconds", "round trip", buckets=(0.01, 1.0))
        root = tracing.begin_span("serve.request")
        h.observe(0.005)
        tracing.end_span(root)
        prom = reg.render_prometheus()
        [line] = [l for l in prom.splitlines()
                  if l.startswith('rt_seconds_bucket{le="0.01"}')]
        assert f'trace_id="{root.trace_id}"' in line
        assert f'span_id="{root.span_id}"' in line
        snap = reg.snapshot()["rt_seconds"]["samples"][0]
        assert snap["exemplars"]["0.01"] == {
            "trace_id": root.trace_id, "span_id": root.span_id,
            "value": 0.005}
        reg.reset()
        assert " # {" not in reg.render_prometheus()

    def test_shadow_span_supplies_exemplar_identity(self):
        """Unsampled (shadow) requests still stamp exemplars — that is
        the acceptance path: the 503's trace_id is on /metrics even at
        sample=0.01, and promotion puts the matching tree in the
        trace."""
        obs.enable_metrics(True)
        collector = tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder())
        reg = obs.get_registry()
        h = reg.histogram("sx_seconds", buckets=(0.01,))
        root = tracing.begin_span("serve.request")
        h.observe(0.001)
        recorder_mod.maybe_promote(root, status=503)
        tracing.end_span(root)
        assert f'trace_id="{root.trace_id}"' in reg.render_prometheus()
        assert {r["trace_id"] for r in collector.spans()} == {root.trace_id}

    def test_no_exemplars_without_tracing(self):
        obs.enable_metrics(True)
        reg = obs.get_registry()
        reg.histogram("nt_seconds", buckets=(0.01,)).observe(0.001)
        assert " # {" not in reg.render_prometheus()
        assert "exemplars" not in reg.snapshot()["nt_seconds"]["samples"][0]


def _run_args(extra):
    from heatmap_tpu.cli import build_parser

    return build_parser().parse_args(
        ["run", "--backend", "cpu", "--input", "synthetic:1500:3",
         "--detail-zoom", "11", *extra])


class TestRecorderCLI:
    def test_blobs_byte_identical_recorder_on_vs_off(self, tmp_path,
                                                     capsys):
        """Acceptance bar: arming the flight recorder + incident
        manager (with head sampling at 0) must not move a single output
        byte."""
        from heatmap_tpu.cli import cmd_run

        out_off = tmp_path / "off.jsonl"
        assert cmd_run(_run_args(["--output", f"jsonl:{out_off}"])) == 0
        out_on = tmp_path / "on.jsonl"
        assert cmd_run(_run_args(
            ["--output", f"jsonl:{out_on}",
             "--trace-out", str(tmp_path / "trace.json"),
             "--trace-sample", "0.0",
             "--flight-recorder-spans", "128",
             "--tail-latency-ms", "60000",
             "--incident-dir", str(tmp_path / "incidents")])) == 0
        capsys.readouterr()
        assert out_on.read_bytes() == out_off.read_bytes()

    def test_recorder_not_armed_without_telemetry_surface(self):
        from heatmap_tpu.cli import _setup_tracing

        args = _run_args(["--output", "memory:"])
        assert _setup_tracing(args) is None
        assert recorder_mod.get_recorder() is None
        assert incident.get_manager() is None

    def test_flag_validation(self, tmp_path):
        from heatmap_tpu.cli import _setup_tracing

        args = _run_args(["--output", "memory:",
                          "--trace-out", str(tmp_path / "t.json"),
                          "--flight-recorder-spans", "-1"])
        with pytest.raises(SystemExit, match="flight-recorder-spans"):
            _setup_tracing(args)
        args = _run_args(["--output", "memory:",
                          "--trace-out", str(tmp_path / "t.json"),
                          "--tail-latency-ms", "0"])
        with pytest.raises(SystemExit, match="tail-latency-ms"):
            _setup_tracing(args)

    def test_failing_job_flushes_exception_bundle(self, tmp_path, capsys):
        """Uncaught job error -> one exception bundle, and the failed
        (unsampled) root rides tail promotion into the exported trace
        (the acceptance trigger path end to end through cmd_run)."""
        from heatmap_tpu.cli import cmd_run

        inc_dir = tmp_path / "incidents"
        trace_out = tmp_path / "trace.json"
        args = _run_args(
            ["--no-fast",  # skip the probe: fail inside the job proper
             "--output", f"jsonl:{tmp_path / 'b.jsonl'}",
             "--trace-out", str(trace_out),
             "--trace-sample", "0.0",
             "--incident-dir", str(inc_dir)])
        args.input = f"csv:{tmp_path / 'does-not-exist.csv'}"
        with pytest.raises(OSError):
            cmd_run(args)
        capsys.readouterr()
        bundles = [d for d in os.listdir(inc_dir)
                   if not d.startswith(".tmp-")]
        assert len(bundles) == 1
        manifest = json.load(open(
            os.path.join(inc_dir, bundles[0], "manifest.json")))
        assert manifest["trigger"] == "exception"
        assert "FileNotFoundError" in manifest["detail"]
        # The bundle flushes before the root closes; the root itself
        # live-forwards into the collector and lands in --trace-out.
        doc = json.load(open(trace_out))
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert "run" in names


class TestIncidentReportTool:
    def test_report_folds_bundle(self, tmp_path, capsys):
        import subprocess
        import sys

        tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder())
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="rep",
                              clock=_fake_clock())
        incident.set_manager(mgr)
        root, child = _shadow_tree()
        tracing.end_span(child)
        recorder_mod.maybe_promote(root, status=503)
        tracing.end_span(root)
        path = mgr.trigger("shed", detail="bound 2")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "incident_report.py"),
             path, "--json"],
            capture_output=True, text=True, check=True)
        report = json.loads(proc.stdout)
        assert report["trigger"] == "shed"
        assert report["run_id"] == "rep" and report["seq"] == 0
        assert report["trace"]["n_spans"] == 2
        [trace_row] = report["trace"]["traces"]
        assert [h["name"] for h in trace_row["critical_path"]] == [
            "serve.request", "tile.render"]

    def test_report_prints_pre_trigger_telemetry_movers(self, tmp_path):
        import subprocess
        import sys

        from heatmap_tpu.obs import timeseries
        from heatmap_tpu.obs.timeseries import TimeSeriesStore

        clock = _fake_clock()
        store = TimeSeriesStore(clock=clock)
        for i in range(20):
            store.observe("ingest_lag_seconds", 2.0 + (8.0 if i >= 15
                                                       else 0.0),
                          ts=clock() + i * 10.0)
        timeseries.install(store)
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="tel",
                              clock=lambda: clock() + 200.0)
        incident.set_manager(mgr)
        try:
            path = mgr.trigger("anomaly", detail="ingest_lag_seconds")
        finally:
            incident.set_manager(None)
            timeseries.install(None)
        assert os.path.exists(os.path.join(path, "telemetry.json"))
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        tool = os.path.join(repo, "tools", "incident_report.py")
        report = json.loads(subprocess.run(
            [sys.executable, tool, path, "--json"],
            capture_output=True, text=True, check=True).stdout)
        assert report["trigger"] == "anomaly"
        (mover,) = report["telemetry"]["movers"]
        assert mover["series"] == "ingest_lag_seconds"
        assert mover["first"] == 2.0 and mover["last"] == 10.0
        assert mover["delta"] == 8.0
        # The human rendering answers "what changed before the trigger".
        text = subprocess.run(
            [sys.executable, tool, path],
            capture_output=True, text=True, check=True).stdout
        assert "before the trigger" in text
        assert "ingest_lag_seconds" in text

    def test_trace_analyze_accepts_bundle_dir(self, tmp_path):
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import trace_analyze

        tracing.enable_tracing(sample=0.0)
        recorder_mod.install(FlightRecorder())
        root, child = _shadow_tree()
        tracing.end_span(child)
        tracing.end_span(root)
        mgr = IncidentManager(str(tmp_path / "inc"), run_id="ta",
                              clock=_fake_clock())
        incident.set_manager(mgr)
        path = mgr.trigger("shed")
        spans = trace_analyze.load_events(path)  # a directory, not a file
        result = trace_analyze.analyze(spans)
        assert result["n_spans"] == 2
        [row] = result["traces"]
        assert row["root"] == "serve.request" and not row["partial"]

    def test_trace_analyze_tolerates_truncated_tree(self):
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import trace_analyze

        # A ring eviction can drop a subtree's real parent; the orphan
        # must analyze as a flagged partial root, not crash.
        spans = [
            {"name": "serve.request", "ts_us": 0.0, "dur_us": 100.0,
             "tid": 1, "trace_id": "t1", "span_id": "a",
             "parent_id": None, "attrs": {}},
            {"name": "tile.render", "ts_us": 10.0, "dur_us": 40.0,
             "tid": 1, "trace_id": "t2", "span_id": "c",
             "parent_id": "gone", "attrs": {}},
        ]
        result = trace_analyze.analyze(spans)
        rows = {r["root"]: r for r in result["traces"]}
        assert not rows["serve.request"]["partial"]
        assert rows["tile.render"]["partial"]  # dangling parent_id
        assert rows["tile.render"]["critical_path"][0]["name"] == \
            "tile.render"
