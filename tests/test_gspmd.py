"""Device-resident GSPMD cascade (parallel/gspmd.py + the dispatch knob).

Four layers under test:

- the global-view NamedSharding programs themselves (uniform DP and
  Morton-range), gated byte-identical against the shard_map oracle at
  the kernel level (padded level arrays AND counts);
- the end-to-end ``dispatch="gspmd"`` route through run_job — every
  tested shape (weighted, retraction sign=-1, pow2-bucketed,
  Morton-partitioned, morton + adaptive_capacity, multihost-elastic)
  must serve blobs byte-identical to ``dispatch="shard_map"``;
- donation safety: re-using a donated buffer is a typed
  :class:`DonatedBufferError` on every platform, ``donate_argnums`` is
  dropped automatically on CPU, and results are byte-identical either
  way;
- the host->device feeder (pipeline/feeder.py): order preservation,
  overlap stats, the ``feeder.put`` fault site, and byte-identical
  ingest with the feeder on/off.

Plus the jax<0.5 compat-shim regression: importing the gspmd module
under ``mesh.force_cpu_devices`` must yield a working multi-device CPU
mesh (satellite of the same PR).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.parallel import gspmd, sharded
from heatmap_tpu.parallel.mesh import make_mesh, named_sharding
from heatmap_tpu.pipeline import BatchJobConfig, feeder, run_job
from heatmap_tpu.pipeline.batch import run_batch

DZ = 12
SPACE = 1 << (2 * DZ)


def _rows(n=500, seed=0,
          users=("alice", "bob", "rt-bus7", "xscout", "carol")):
    rng = np.random.default_rng(seed)
    return [{
        "latitude": float(rng.uniform(40.0, 55.0)),
        "longitude": float(rng.uniform(-5.0, 15.0)),
        "user_id": users[int(rng.integers(0, len(users)))],
        "timestamp": 1_500_000_000_000 + int(rng.integers(0, 10**9)),
    } for _ in range(n)]


class _ColSource:
    def __init__(self, rows):
        self.rows = rows

    def batches(self, batch_size):
        for i in range(0, len(self.rows), batch_size):
            chunk = self.rows[i:i + batch_size]
            out = {
                "latitude": [r["latitude"] for r in chunk],
                "longitude": [r["longitude"] for r in chunk],
                "user_id": [r["user_id"] for r in chunk],
                "timestamp": [r.get("timestamp") for r in chunk],
            }
            if any("value" in r for r in chunk):
                out["value"] = [float(r.get("value", 1.0)) for r in chunk]
            yield out


def _cfg(**kw):
    base = dict(detail_zoom=DZ, min_detail_zoom=6, data_parallel=True)
    base.update(kw)
    return BatchJobConfig(**base)


def _levels_equal(a, b):
    """Level-tuple equality up to each level's REAL row count (the
    padded tails may differ only past n; they don't here, but the
    contract is the prefix)."""
    assert len(a) == len(b)
    for (au, as_, an), (bu, bs, bn) in zip(a, b):
        n = int(an)
        assert n == int(bn)
        assert np.array_equal(np.asarray(au), np.asarray(bu))
        assert np.array_equal(np.asarray(as_), np.asarray(bs))


def _keys(n, seed, n_slots=20):
    rng = np.random.default_rng(seed)
    code = rng.integers(0, SPACE, n)
    slot = rng.integers(0, n_slots, n)
    return jnp.asarray((slot << np.int64(2 * DZ)) | code, jnp.int64)


# -- kernel-level byte identity --------------------------------------------


def test_gspmd_uniform_matches_shard_map_kernel():
    mesh = make_mesh()
    ck = _keys(4096, 3)
    w = jnp.asarray(np.random.default_rng(4).integers(1, 9, 4096),
                    jnp.float64)
    valid = jnp.asarray(np.random.default_rng(5).random(4096) > 0.1)
    for weights in (None, w):
        got = gspmd.pyramid_gspmd_uniform(
            ck, mesh, weights=weights, valid=valid, levels=6,
            capacity=4096,
            acc_dtype=jnp.float64 if weights is not None else None)
        want = sharded.pyramid_sparse_morton_sharded(
            ck, mesh, weights=weights, valid=valid, levels=6,
            capacity=4096,
            acc_dtype=jnp.float64 if weights is not None else None)
        _levels_equal(got, want)


def test_gspmd_uniform_eager_equals_jit():
    mesh = make_mesh()
    ck = _keys(2048, 7)
    eager = gspmd.pyramid_gspmd_uniform(ck, mesh, levels=5, capacity=2048)
    jitted = jax.jit(
        lambda k: gspmd.pyramid_gspmd_uniform(k, mesh, levels=5,
                                              capacity=2048))(ck)
    _levels_equal(eager, jitted)


def test_route_on_device_matches_host_router():
    """On-device ownership mask == the host searchsorted convention
    (shard = #{splits <= code}, side='right')."""
    rng = np.random.default_rng(11)
    n = 2048
    code = rng.integers(0, SPACE, n)
    ck = jnp.asarray((rng.integers(0, 8, n) << np.int64(2 * DZ)) | code)
    splits = np.sort(rng.integers(1, SPACE, 7))
    owned = np.asarray(gspmd.route_on_device(
        ck, jnp.asarray(splits), code_bits=2 * DZ, n_shards=8))
    want = np.searchsorted(splits, code, side="right")
    assert owned.shape == (8, n)
    assert np.array_equal(np.argmax(owned, axis=0), want)
    assert np.array_equal(owned.sum(axis=0), np.ones(n))  # exactly one owner


# -- end-to-end byte identity ----------------------------------------------


def _ab(rows, **kw):
    a = run_job(_ColSource(rows), config=_cfg(dispatch="gspmd", **kw))
    b = run_job(_ColSource(rows), config=_cfg(dispatch="shard_map", **kw))
    assert a == b and len(a) > 0
    return a


def test_run_job_gspmd_uniform_byte_identical():
    _ab(_rows(n=800, seed=42), spatial_partition="off")


def test_run_job_gspmd_morton_byte_identical():
    _ab(_rows(n=800, seed=42), spatial_partition="morton")


@pytest.mark.slow
def test_run_job_gspmd_weighted_byte_identical():
    rng = np.random.default_rng(15)
    rows = _rows(n=1200, seed=15)
    for r in rows:
        r["value"] = float(rng.integers(1, 12))
    _ab(rows, weighted=True, spatial_partition="morton")


@pytest.mark.slow
def test_run_job_gspmd_pad_bucketing_byte_identical():
    _ab(_rows(n=1500, seed=5), pad_bucketing="pow2",
        spatial_partition="morton")


def test_run_job_gspmd_morton_adaptive_composes():
    """The lifted rejection: morton + adaptive_capacity under gspmd
    runs, and its blobs equal BOTH the shard_map uniform-DP oracle and
    the non-adaptive gspmd run (adaptive is result-neutral)."""
    rows = _rows(n=800, seed=9)
    adaptive = run_job(_ColSource(rows), config=_cfg(
        dispatch="gspmd", spatial_partition="morton",
        adaptive_capacity=True))
    plain = run_job(_ColSource(rows), config=_cfg(
        dispatch="gspmd", spatial_partition="morton"))
    oracle = run_job(_ColSource(rows), config=_cfg(
        dispatch="shard_map", spatial_partition="off"))
    assert adaptive == plain == oracle and len(adaptive) > 0


@pytest.mark.slow
def test_retraction_delta_gspmd_byte_identical(tmp_path):
    """sign=-1 negates finalized levels AFTER the cascade; the gspmd
    route must produce identical artifact files."""
    from heatmap_tpu.delta.compute import compute_delta

    rows = _rows(n=1000, seed=21)
    dirs = {}
    for name in ("gspmd", "shard_map"):
        out = str(tmp_path / name)
        compute_delta(_ColSource(rows), out,
                      _cfg(dispatch=name, spatial_partition="morton"),
                      sign=-1)
        dirs[name] = out

    def blob(d):
        return {f: open(os.path.join(d, f), "rb").read()
                for f in sorted(os.listdir(d))
                if os.path.isfile(os.path.join(d, f))}

    assert blob(dirs["gspmd"]) == blob(dirs["shard_map"])


@pytest.mark.slow
def test_run_job_elastic_gspmd_byte_identical(tmp_path):
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.parallel import run_job_elastic

    out = {}
    for name in ("gspmd", "shard_map"):
        cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                             result_delta=2, dispatch=name)
        d = str(tmp_path / name)
        run_job_elastic(SyntheticSource(n=900, seed=7),
                        LevelArraysSink(d), cfg, batch_size=150,
                        lineage_dir=str(tmp_path / f"lin-{name}"),
                        n_hosts=3, partition="morton")
        out[name] = {f: open(os.path.join(d, f), "rb").read()
                     for f in sorted(os.listdir(d))
                     if os.path.isfile(os.path.join(d, f))}
    assert out["gspmd"] == out["shard_map"]


# -- config surface --------------------------------------------------------


def test_dispatch_config_surface():
    with pytest.raises(ValueError, match="dispatch"):
        BatchJobConfig(dispatch="pjit")
    with pytest.raises(ValueError, match="prefix"):
        BatchJobConfig(dispatch="gspmd", data_parallel=True,
                       dp_merge="prefix")
    # auto resolves to gspmd except where no program exists (prefix).
    assert BatchJobConfig().resolved_dispatch == "gspmd"
    assert BatchJobConfig(data_parallel=True, dp_merge="prefix")\
        .resolved_dispatch == "shard_map"
    # morton + adaptive composes under gspmd (auto included) and stays
    # rejected under the shard_map oracle.
    BatchJobConfig(spatial_partition="morton", data_parallel=True,
                   adaptive_capacity=True)
    BatchJobConfig(spatial_partition="morton", data_parallel=True,
                   adaptive_capacity=True, dispatch="gspmd")
    with pytest.raises(ValueError, match="adaptive"):
        BatchJobConfig(spatial_partition="morton", data_parallel=True,
                       adaptive_capacity=True, dispatch="shard_map")


def test_backend_resolved_event_carries_dispatch(tmp_path):
    path = str(tmp_path / "events.jsonl")
    obs.set_event_log(obs.EventLog(path))
    try:
        run_job(_ColSource(_rows(n=200, seed=1)),
                config=_cfg(spatial_partition="off"))
    finally:
        log = obs.get_event_log()
        obs.set_event_log(None)
        log.close()
    recs = [r for r in obs.read_events(path)
            if r["event"] == "backend_resolved"]
    assert recs and recs[0]["dispatch"] == "gspmd"
    dis = [r for r in obs.read_events(path)
           if r["event"] == "cascade_dispatch"]
    assert dis and dis[0]["dispatch"] == "gspmd"


def test_dispatch_overhead_metrics(tmp_path):
    """DispatchTimer splits stage attribution into host vs device and
    feeds the dispatch_overhead_seconds histogram."""
    obs.enable_metrics(True)
    try:
        run_job(_ColSource(_rows(n=200, seed=2)),
                config=_cfg(spatial_partition="off"))
        over = obs.DISPATCH_OVERHEAD.samples()
        assert ("gspmd",) in over and over[("gspmd",)][2] >= 1
        stages = obs.STAGE_SECONDS.samples()
        assert ("cascade.dispatch.host",) in stages
        assert ("cascade.dispatch.device",) in stages
    finally:
        obs.enable_metrics(False)
        obs.get_registry().reset()


# -- donation safety -------------------------------------------------------


def test_donation_dropped_on_cpu():
    assert not gspmd.donation_supported("cpu")
    assert gspmd.donation_supported("tpu")
    assert gspmd.donation_supported("gpu")
    fn = gspmd.donating_jit(lambda x: x + 1, donate_argnums=(0,),
                            ledger=gspmd.DonationLedger())
    assert fn.donation_active is False  # CPU test session


def test_donated_buffer_reuse_is_typed_error():
    led = gspmd.DonationLedger()
    fn = gspmd.donating_jit(lambda x: x * 2, donate_argnums=(0,),
                            ledger=led)
    x = jnp.arange(16, dtype=jnp.int64)
    y = fn(x)
    assert np.array_equal(np.asarray(y), np.arange(16) * 2)
    with pytest.raises(gspmd.DonatedBufferError,
                       match="donated to a previous cascade dispatch"):
        fn(x)
    # A FRESH buffer with identical contents is fine (identity, not
    # value, is what donation consumes).
    z = fn(jnp.arange(16, dtype=jnp.int64))
    assert np.array_equal(np.asarray(y), np.asarray(z))


def test_donation_argnames_guard_kwargs():
    led = gspmd.DonationLedger()
    fn = gspmd.donating_jit(lambda x, w=None: x if w is None else x + w,
                            donate_argnames=("w",), ledger=led)
    w = jnp.ones(8, jnp.float64)
    fn(jnp.zeros(8, jnp.float64), w=w)
    with pytest.raises(gspmd.DonatedBufferError):
        fn(jnp.zeros(8, jnp.float64), w=w)


def test_donating_cascade_byte_identity():
    """The donating jit entry produces the same bytes as the plain
    entry — donation changes buffer lifetime, never values."""
    mesh = make_mesh()
    ck = _keys(2048, 13)

    def prog(k):
        return gspmd.pyramid_gspmd_uniform(k, mesh, levels=5,
                                           capacity=2048)

    plain = jax.jit(prog)(ck)
    donating = gspmd.donating_jit(prog, donate_argnums=(0,),
                                  ledger=gspmd.DonationLedger())
    donated = donating(jnp.array(ck))  # fresh copy — ck stays usable
    _levels_equal(plain, donated)


def test_run_cascade_gspmd_marks_device_inputs():
    """run_cascade's gspmd jit path routes device-resident emissions
    through the donating entry: re-passing the SAME consumed buffers is
    the typed error, on CPU too."""
    from heatmap_tpu.pipeline import cascade as cascade_mod

    cfg = _cfg(spatial_partition="off")
    ccfg = cfg.cascade_config()
    mesh = make_mesh()
    n = 4096
    rng = np.random.default_rng(3)
    codes = jax.device_put(rng.integers(0, SPACE, n))
    slots = jax.device_put(rng.integers(0, 4, n))

    def run():
        return cascade_mod.run_cascade(
            codes, slots, ccfg, n_slots=4, capacity=n, mesh=mesh,
            dispatch="gspmd")

    cascade_mod.decode_levels(run(), ccfg)
    try:
        with pytest.raises(gspmd.DonatedBufferError):
            run()
    finally:
        gspmd.ledger.clear()


# -- mesh compat shim ------------------------------------------------------


def test_force_cpu_devices_shim_imports_gspmd():
    """jax<0.5 has no jax_num_cpu_devices config: force_cpu_devices
    must fall back to XLA_FLAGS and still give the gspmd entry points a
    multi-device CPU mesh (regression for the stale compat shim)."""
    code = (
        "import os\n"
        "os.environ.pop('XLA_FLAGS', None)\n"
        "from heatmap_tpu.parallel import mesh\n"
        "mesh.force_cpu_devices(4)\n"
        "import jax\n"
        "assert jax.device_count() == 4, jax.devices()\n"
        "jax.config.update('jax_enable_x64', True)\n"
        "import jax.numpy as jnp\n"
        "from heatmap_tpu.parallel import gspmd\n"
        "m = mesh.make_mesh()\n"
        "assert m.devices.size == 4\n"
        "lv = gspmd.pyramid_gspmd_uniform(\n"
        "    jnp.arange(64, dtype=jnp.int64), m, levels=2, capacity=64)\n"
        "assert int(lv[0][2]) == 64\n"
        "s = mesh.named_sharding(m, mesh.DATA_AXIS)\n"
        "assert s.is_fully_addressable\n"
        "print('SHIM-OK')\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr
    assert "SHIM-OK" in out.stdout


# -- feeder ----------------------------------------------------------------


def test_feeder_preserves_order_and_counts():
    stats = feeder.FeederStats()
    got = list(feeder.feed(iter(range(20)), lambda x: x * 10, depth=2,
                           stats=stats))
    assert got == [x * 10 for x in range(20)]
    assert stats.batches == 20
    assert 0.0 <= stats.overlap_pct <= 100.0
    assert stats.depth_hwm <= 2


def test_feeder_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        list(feeder.feed(iter([1]), lambda x: x, depth=0))


def test_feeder_device_put_columns_moves_numeric_only():
    cols = {"latitude": np.arange(4, dtype=np.float64),
            "longitude": np.arange(4, dtype=np.float64),
            "value": np.ones(4),
            "timestamp": np.arange(4, dtype=np.int64),
            "user_id": ["a", "b", "c", "d"]}
    fed = feeder.device_put_columns(cols)
    assert isinstance(fed["latitude"], jax.Array)
    assert isinstance(fed["value"], jax.Array)
    # timestamp feeds the host-side labeler; user_id is strings.
    assert isinstance(fed["timestamp"], np.ndarray)
    assert fed["user_id"] is cols["user_id"]
    assert np.array_equal(np.asarray(fed["latitude"]), cols["latitude"])


def test_feeder_fault_site_retries_then_propagates():
    # One injected fault at feeder.put: absorbed by the retry policy,
    # every item still arrives exactly once in order.
    faults.install(faults.FaultPlane(seed=1, backoff_scale=0.0)
                   .add_rule("feeder.put", count=1))
    try:
        got = list(feeder.feed(iter(range(8)), lambda x: x, depth=1))
        assert got == list(range(8))
        assert faults.get_plane().injected == 1
    finally:
        faults.install(None)
    # A storm past the retry budget propagates to the consumer.
    faults.install(faults.FaultPlane(seed=1, backoff_scale=0.0)
                   .add_rule("feeder.put", count=50))
    try:
        with pytest.raises(faults.InjectedFault):
            list(feeder.feed(iter(range(8)), lambda x: x, depth=1))
    finally:
        faults.install(None)


def test_ingest_feeder_byte_identical_store(tmp_path):
    """Draining the same source with the feeder on vs off produces
    byte-identical delta stores: same journal content hashes (the
    feeder moves buffers, never values) and identical artifact files.
    Journal entry FILES carry a wall-clock ``ts`` so they compare by
    content hash, not bytes."""
    from heatmap_tpu import ingest as ingest_mod
    from heatmap_tpu.delta.compact import journal_dir
    from heatmap_tpu.delta.journal import DeltaJournal
    from heatmap_tpu.io import open_source

    digests, hashes = {}, {}
    for depth in (0, 2):
        root = str(tmp_path / f"d{depth}")
        st = ingest_mod.run_ingest(
            root, open_source("synthetic:2000:13"),
            config=BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                                  result_delta=2, pad_bucketing="pow2"),
            ingest=ingest_mod.IngestConfig(micro_batch=512,
                                           feed_depth=depth))
        assert st.ticks == 4 and st.points == 2000
        if depth:
            assert st.feeder_depth_hwm >= 1
        hashes[depth] = [
            (e["epoch"], e["content_hash"], e["points"], e["sign"])
            for e in DeltaJournal(journal_dir(root)).entries()]
        files = {}
        for dirpath, _, names in os.walk(root):
            if "journal" in os.path.relpath(dirpath, root).split(os.sep):
                continue
            for f in names:
                p = os.path.join(dirpath, f)
                files[os.path.relpath(p, root)] = open(p, "rb").read()
        digests[depth] = files
    assert hashes[0] == hashes[2] and len(hashes[0]) == 4
    assert sorted(digests[0]) == sorted(digests[2])
    diff = [k for k in digests[0] if digests[0][k] != digests[2][k]]
    assert not diff, diff
