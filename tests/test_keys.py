"""Tests for integer tile keys, Morton codes, and the string codec."""

import jax.numpy as jnp
import numpy as np

from heatmap_tpu.tilemath import keys, morton
import oracle


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(3)
    zooms = rng.integers(0, 30, 1000)
    rows = np.array([rng.integers(0, 1 << z) if z else 0 for z in zooms])
    cols = np.array([rng.integers(0, 1 << z) if z else 0 for z in zooms])
    packed = keys.pack_key(zooms, rows, cols)
    z, r, c = keys.unpack_key(packed)
    np.testing.assert_array_equal(np.asarray(z), zooms)
    np.testing.assert_array_equal(np.asarray(r), rows)
    np.testing.assert_array_equal(np.asarray(c), cols)


def test_pack_key_sort_order():
    # Lexicographic (zoom, row, col) ordering survives packing.
    rng = np.random.default_rng(4)
    zooms = rng.integers(0, 22, 500)
    rows = rng.integers(0, 1 << 21, 500)
    cols = rng.integers(0, 1 << 21, 500)
    packed = np.asarray(keys.pack_key(zooms, rows, cols))
    order = np.argsort(packed, kind="stable")
    lex = np.lexsort((cols, rows, zooms))
    np.testing.assert_array_equal(
        packed[order], packed[lex]
    )


def test_parent_equals_reference_center_reprojection():
    """parent = (r>>1, c>>1) must equal the reference's center re-binning
    (reference tile.py:60-61) — the correctness basis for the whole
    shift-based pyramid (SURVEY.md §7)."""
    rng = np.random.default_rng(5)
    for zoom in [1, 2, 8, 16, 21]:
        n = 1 << zoom
        rows = rng.integers(0, n, 300)
        cols = rng.integers(0, n, 300)
        pr, pc = keys.parent_rowcol(rows, cols)
        for r, c, er, ec in zip(rows, cols, pr, pc):
            lat, lon, _ = oracle.tile_center(f"{zoom}_{r}_{c}")
            expected = oracle.tile_id(lat, lon, zoom - 1)
            assert expected == f"{zoom - 1}_{er}_{ec}"


def test_rowcol_at_zoom_matches_iterated_reprojection():
    # Multi-level coarsening (z21 -> z16, the DETAIL_ZOOM_DELTA=5 re-key of
    # reference heatmap.py:89) equals 5 single-level reference steps.
    rng = np.random.default_rng(6)
    zoom = 21
    rows = rng.integers(0, 1 << zoom, 100)
    cols = rng.integers(0, 1 << zoom, 100)
    r16, c16 = keys.rowcol_at_zoom(rows, cols, zoom, 16)
    for r, c, er, ec in zip(rows, cols, r16, c16):
        lat, lon, _ = oracle.tile_center(f"{zoom}_{r}_{c}")
        expected = oracle.tile_id(lat, lon, 16)
        assert expected == f"16_{er}_{ec}"


def test_children_rowcol():
    for r, c in [(0, 0), (3, 5), (100, 2047)]:
        kids = keys.children_rowcol(r, c)
        assert set(kids) == {
            (2 * r, 2 * c),
            (2 * r, 2 * c + 1),
            (2 * r + 1, 2 * c),
            (2 * r + 1, 2 * c + 1),
        }
        for kr, kc in kids:
            assert keys.parent_rowcol(kr, kc) == (r, c)


def test_string_codec():
    assert keys.tile_id_string(10, 5, 7) == "10_5_7"
    assert keys.parse_tile_id("10_5_7") == (10, 5, 7)
    assert keys.parse_tile_id("garbage") is None
    assert keys.parse_tile_id("1_2_3_4") is None


def test_tile_id_from_lat_long_matches_oracle():
    rng = np.random.default_rng(7)
    lats = rng.uniform(-85, 85, 200)
    lons = rng.uniform(-180, 180, 200)
    for la, lo in zip(lats, lons):
        for zoom in (10, 21):
            assert keys.tile_id_from_lat_long(la, lo, zoom) == oracle.tile_id(
                la, lo, zoom
            )


def test_tile_ids_to_arrays():
    z, r, c, keep = keys.tile_ids_to_arrays(["3_1_2", "bad", "21_100_200"])
    np.testing.assert_array_equal(z, [3, 21])
    np.testing.assert_array_equal(r, [1, 100])
    np.testing.assert_array_equal(c, [2, 200])
    np.testing.assert_array_equal(keep, [True, False, True])


# -- Morton codes -----------------------------------------------------------


def test_morton_roundtrip_int32():
    rng = np.random.default_rng(8)
    rows = rng.integers(0, 1 << 15, 5000).astype(np.int32)
    cols = rng.integers(0, 1 << 15, 5000).astype(np.int32)
    code = morton.morton_encode(rows, cols, dtype=jnp.int32)
    r, c = morton.morton_decode(code)
    np.testing.assert_array_equal(np.asarray(r), rows)
    np.testing.assert_array_equal(np.asarray(c), cols)


def test_morton_roundtrip_int64():
    rng = np.random.default_rng(9)
    rows = rng.integers(0, 1 << 21, 5000)
    cols = rng.integers(0, 1 << 21, 5000)
    code = morton.morton_encode(rows, cols, dtype=jnp.int64)
    r, c = morton.morton_decode(code)
    np.testing.assert_array_equal(np.asarray(r), rows)
    np.testing.assert_array_equal(np.asarray(c), cols)


def test_morton_parent_is_shift_and_order_preserving():
    rng = np.random.default_rng(10)
    rows = rng.integers(0, 1 << 15, 3000).astype(np.int32)
    cols = rng.integers(0, 1 << 15, 3000).astype(np.int32)
    code = np.asarray(morton.morton_encode(rows, cols, dtype=jnp.int32))
    parent = np.asarray(morton.morton_parent(code))
    pr, pc = morton.morton_decode(jnp.asarray(parent))
    np.testing.assert_array_equal(np.asarray(pr), rows >> 1)
    np.testing.assert_array_equal(np.asarray(pc), cols >> 1)
    # Order preservation: sorted codes stay sorted under the parent shift.
    sorted_codes = np.sort(code)
    parents_of_sorted = sorted_codes >> 2
    assert np.all(np.diff(parents_of_sorted) >= 0)


def test_pack_key_rejects_zoom_30():
    import pytest

    with pytest.raises(ValueError):
        keys.pack_key(30, 0, 0)
    keys.pack_key(29, (1 << 29) - 1, (1 << 29) - 1)  # max lossless
