"""Incremental update engine tests (heatmap_tpu/delta/).

The anchor everything hangs on: **base ⊕ deltas is byte-identical to a
full recompute over the union of surviving points** — at the
served-blob level, before AND after compaction, including a retraction
batch. Plus the two operational contracts: idempotent re-submits (same
bytes, no new epoch) and serve-side targeted invalidation (a delta
apply drops only the affected tile keys; untouched cache entries
survive with no generation bump).

Tier-1: CPU backend, real cascade runs (small shapes), no network.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np
import pytest

from heatmap_tpu import delta
from heatmap_tpu.delta.compute import ColumnsSource, read_columns
from heatmap_tpu.delta.journal import DeltaJournal, batch_content_hash
from heatmap_tpu.io import open_source
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.pipeline import BatchJobConfig, run_job
from heatmap_tpu.serve import TileCache, TileStore
from heatmap_tpu.serve.render import tile_json_bytes
from heatmap_tpu.tilemath.mercator import project_points_np
from heatmap_tpu.tilemath.morton import morton_decode_np

BASE_SPEC = "synthetic:3000:7"
DELTA_SPEC = "synthetic:300:11"
RETRACT_ROWS = 500  # first N base rows get retracted


class _Chain:
    def __init__(self, *sources):
        self.sources = sources

    def batches(self, batch_size: int = 1 << 20):
        for src in self.sources:
            yield from src.batches(batch_size)


def _slice_cols(cols: dict, sl: slice) -> dict:
    return {k: v[sl] for k, v in cols.items()}


def _collect_docs(store: TileStore) -> dict:
    """Every servable JSON tile of every layer: {(layer, z, x, y):
    bytes}. Enumerates stored zooms from the level Morton codes, so the
    two stores must agree on which tiles exist, not just their
    contents."""
    docs = {}
    for name, layer in store.layers.items():
        if name == "default":  # alias of all|alltime, not a new layer
            continue
        shift = 2 * layer.result_delta
        for want, level in layer.levels.items():
            z = want - layer.result_delta
            if z < 0:
                continue
            rows, cols = morton_decode_np(np.unique(level.codes >> shift))
            for r, c in zip(rows, cols):
                docs[(name, z, int(c), int(r))] = tile_json_bytes(
                    layer, z, int(c), int(r))
    return docs


def _tree_digest(root: str) -> str:
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for fn in sorted(filenames):
            path = os.path.join(dirpath, fn)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


@pytest.fixture(scope="module")
def scenario(tmp_path_factory):
    """One full store lifecycle, snapshotted at every contract point:

    epoch 1  base batch        (synthetic:3000:7)
    epoch 2  insert delta      (synthetic:300:11)
    dup      re-apply epoch 2  (must be a no-op)
    epoch 3  retraction        (first 500 base rows, sign=-1)
    compact  retention=2       (folds 1-3 into base-000003)

    The reference pyramid is a single full recompute over the union of
    surviving points (base rows 500.. plus the delta batch).
    """
    root = str(tmp_path_factory.mktemp("delta_store") / "store")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=5)

    r1 = delta.apply_batch(root, open_source(BASE_SPEC), config)
    r2 = delta.apply_batch(root, open_source(DELTA_SPEC), config)

    digest_before_dup = _tree_digest(root)
    epochs_before_dup = DeltaJournal(delta.compact_mod.journal_dir(root)).epochs()
    r2_dup = delta.apply_batch(root, open_source(DELTA_SPEC), config)
    digest_after_dup = _tree_digest(root)
    epochs_after_dup = DeltaJournal(delta.compact_mod.journal_dir(root)).epochs()

    base_cols = read_columns(open_source(BASE_SPEC))
    retract = ColumnsSource(_slice_cols(base_cols, slice(0, RETRACT_ROWS)))
    r3 = delta.apply_batch(root, retract, config, sign=-1)

    # The reference: one job over exactly the surviving points.
    survivors = ColumnsSource(_slice_cols(base_cols,
                                          slice(RETRACT_ROWS, None)))
    full_dir = str(tmp_path_factory.mktemp("delta_full") / "levels")
    run_job(_Chain(survivors, open_source(DELTA_SPEC)),
            LevelArraysSink(full_dir), config)

    docs_full = _collect_docs(TileStore(f"arrays:{full_dir}"))
    docs_before = _collect_docs(TileStore(f"delta:{root}"))

    summary = delta.compact(root, retention=2)
    docs_after = _collect_docs(TileStore(f"delta:{root}"))

    return {
        "root": root, "config": config,
        "r1": r1, "r2": r2, "r2_dup": r2_dup, "r3": r3,
        "digest_before_dup": digest_before_dup,
        "digest_after_dup": digest_after_dup,
        "epochs_before_dup": epochs_before_dup,
        "epochs_after_dup": epochs_after_dup,
        "docs_full": docs_full, "docs_before": docs_before,
        "docs_after": docs_after, "compact_summary": summary,
    }


class TestEquivalence:
    def test_blob_identity_before_compaction(self, scenario):
        """base ⊕ deltas (incl. the retraction) serves byte-identical
        JSON docs to the full recompute — same tile set, same bytes."""
        assert scenario["docs_before"].keys() == scenario["docs_full"].keys()
        assert scenario["docs_before"] == scenario["docs_full"]
        assert len(scenario["docs_full"]) > 50  # non-trivial pyramid

    def test_blob_identity_after_compaction(self, scenario):
        assert scenario["docs_after"] == scenario["docs_full"]

    def test_compaction_summary_and_pointer(self, scenario):
        cur = delta.read_current(scenario["root"])
        assert scenario["compact_summary"]["status"] == "ok"
        assert cur["base"] == "base-000003"
        assert cur["applied_through"] == 3
        # folded artifacts outside the retention window are gone
        assert not os.path.isdir(
            os.path.join(scenario["root"], "delta-000001"))

    def test_retraction_removed_mass(self, scenario):
        """The retraction epoch actually subtracted: its artifact rows
        carry negative values, and the journal records sign=-1."""
        assert scenario["r3"].sign == -1
        assert scenario["r3"].rows > 0
        levels = LevelArraysSink.load(
            os.path.join(scenario["root"], scenario["r3"].artifact))
        finest = levels[max(levels)]
        assert np.all(np.asarray(finest["value"]) < 0)


class TestIdempotency:
    def test_duplicate_apply_is_a_noop(self, scenario):
        """Re-applying a journaled batch: same store bytes, no new
        epoch, no artifact written, duplicate flagged."""
        assert scenario["r2_dup"].duplicate
        assert not scenario["r2"].duplicate
        assert scenario["r2_dup"].epoch == scenario["r2"].epoch
        assert scenario["r2_dup"].artifact == scenario["r2"].artifact
        assert scenario["r2_dup"].rows == 0
        assert scenario["digest_after_dup"] == scenario["digest_before_dup"]
        assert scenario["epochs_after_dup"] == scenario["epochs_before_dup"]

    def test_duplicate_detection_survives_compaction(self, scenario):
        """Epochs inside the retention window stay journaled after
        compaction, so their re-submits are still no-ops."""
        res = delta.apply_batch(scenario["root"], open_source(DELTA_SPEC),
                                scenario["config"])
        assert res.duplicate
        assert res.epoch == scenario["r2"].epoch

    def test_retraction_hash_differs_from_insert(self):
        cols = {"latitude": np.array([1.0]), "longitude": np.array([2.0]),
                "user_id": ["u"]}
        assert (batch_content_hash(cols, sign=1)
                != batch_content_hash(cols, sign=-1))
        assert batch_content_hash(cols, sign=1).startswith("sha256:")

    def test_config_mismatch_refused(self, scenario):
        other = BatchJobConfig(detail_zoom=8, min_detail_zoom=5)
        src = ColumnsSource({"latitude": np.array([1.0]),
                             "longitude": np.array([2.0]),
                             "user_id": ["u-mismatch"]})
        with pytest.raises(ValueError, match="was built with config"):
            delta.apply_batch(scenario["root"], src, other)


class TestServing:
    def test_targeted_invalidation(self, tmp_path):
        """A delta apply invalidates only the affected tile keys: the
        cached tile the delta point lands in is dropped, a cached tile
        elsewhere survives, and the store generation does NOT bump (so
        surviving entries stay valid, unlike reload())."""
        config = BatchJobConfig(detail_zoom=8, min_detail_zoom=5)
        root = str(tmp_path / "store")
        delta.apply_batch(root, open_source("synthetic:1000:7"), config)
        store = TileStore(f"delta:{root}")
        cache = TileCache()
        gen = store.generation

        # One cached tile over the base data, one over the (empty) cell
        # the delta point will land in — distinct z=5 tiles.
        base_cols = read_columns(open_source("synthetic:1000:7"))
        brow, bcol, _ = project_points_np(base_cols["latitude"][:1],
                                          base_cols["longitude"][:1], 8)
        untouched = ("default", 5, int(bcol[0]) >> 3, int(brow[0]) >> 3,
                     "json")
        drow, dcol, _ = project_points_np([40.0], [-100.0], 8)
        touched = ("default", 5, int(dcol[0]) >> 3, int(drow[0]) >> 3,
                   "json")
        assert touched != untouched
        cache.get_or_render(untouched, gen, lambda: b"U0")
        cache.get_or_render(touched, gen, lambda: b"T0")

        res = delta.apply_batch(
            root,
            ColumnsSource({"latitude": np.array([40.0]),
                           "longitude": np.array([-100.0]),
                           "user_id": ["u-delta"]}),
            config)
        assert touched in res.affected_keys
        assert untouched not in res.affected_keys

        dropped = delta.refresh_serving(res, store, cache)
        assert dropped == 1  # only the touched key was cached
        assert store.generation == gen  # no bump — that's the point

        value, hit = cache.get_or_render(untouched, gen, lambda: b"U1")
        assert hit and value == b"U0"  # untouched entry survived
        value, hit = cache.get_or_render(touched, gen, lambda: b"T1")
        assert not hit and value == b"T1"  # touched entry re-rendered

        # And the refreshed index actually serves the delta point.
        layer = store.layer("default")
        doc = tile_json_bytes(layer, touched[1], touched[2], touched[3])
        assert doc is not None

    def test_duplicate_refresh_is_free(self, tmp_path):
        class _Boom:
            def refresh_layers(self):  # pragma: no cover - must not run
                raise AssertionError("duplicate apply must not refresh")

        res = delta.DeltaResult(epoch=1, points=1, sign=1, duplicate=True,
                                artifact="delta-000001", rows=0,
                                seconds=0.0)
        assert delta.refresh_serving(res, _Boom(), TileCache()) == 0

    def test_tile_formats_pinned_to_serve(self):
        from heatmap_tpu.delta import compute
        from heatmap_tpu.serve import live

        assert compute.TILE_FORMATS == live.TILE_FORMATS


class TestStoreLayout:
    def test_orphan_artifact_is_invisible(self, tmp_path):
        """A delta dir with no journal entry (crashed apply: artifact
        written, append lost) never reaches the overlay."""
        root = str(tmp_path / "store")
        delta.init_store(root)
        os.makedirs(os.path.join(root, "delta-000099"))
        assert delta.overlay_dirs(root) == []
        assert delta.load_overlay_levels(root) == []

    def test_base_adoption_refuses_double_init(self, tmp_path):
        src = tmp_path / "base_src"
        src.mkdir()
        (src / "marker").write_text("x")
        root = str(tmp_path / "store")
        cur = delta.init_store(root, base_dir=str(src))
        assert cur["base"] == "base-000000"
        assert os.path.exists(os.path.join(root, "base-000000", "marker"))
        with pytest.raises(ValueError, match="already has base"):
            delta.init_store(root, base_dir=str(src))
