"""Native runtime layer (native/*.cpp via heatmap_tpu.native).

Parity: the native CSV decoder must yield the same batches as the pure
Python csv path (io.sources.CSVSource use_native=False), modulo the
documented timestamp representation (ints vs raw strings).
"""

import csv
import os

import numpy as np
import pytest

from heatmap_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


def _write_csv(path, rows, cols=("latitude", "longitude", "user_id",
                                 "source", "timestamp")):
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for r in rows:
            w.writerow([r.get(c, "") for c in cols])


def _random_rows(n, seed=0):
    rng = np.random.default_rng(seed)
    users = ["alice", "bob", "x-9", "rt-1", 'we"ird', "comma,user", ""]
    rows = []
    for i in range(n):
        rows.append({
            "latitude": float(rng.uniform(-85, 85)),
            "longitude": float(rng.uniform(-180, 180)),
            "user_id": users[int(rng.integers(0, len(users)))],
            "source": "background" if rng.random() < 0.1 else "gps",
            "timestamp": int(rng.integers(0, 2**31)) if rng.random() < 0.9 else "",
        })
    return rows


def test_csv_parity_with_python_path(tmp_path):
    from heatmap_tpu.io.sources import CSVSource

    p = tmp_path / "pts.csv"
    rows = _random_rows(1000)
    _write_csv(p, rows)

    for bs in (64, 1000, 4096):
        nb = list(native.parse_csv_batches(str(p), bs))
        pb = list(CSVSource(str(p), use_native=False).batches(bs))
        assert len(nb) == len(pb)
        for b_n, b_p in zip(nb, pb):
            np.testing.assert_array_equal(b_n["latitude"], b_p["latitude"])
            np.testing.assert_array_equal(b_n["longitude"], b_p["longitude"])
            assert b_n["user_id"] == b_p["user_id"]
            assert b_n["source"] == b_p["source"]
            # Native stamps are ints/None; python path keeps strings.
            norm = [None if s in ("", None) else int(s)
                    for s in b_p["timestamp"]]
            assert list(b_n["timestamp"]) == norm


def test_csv_source_uses_native(tmp_path):
    from heatmap_tpu.io.sources import CSVSource

    p = tmp_path / "pts.csv"
    _write_csv(p, _random_rows(10))
    batches = list(CSVSource(str(p)).batches(100))
    assert len(batches) == 1
    # Native path marker: timestamps are ints, not strings.
    assert all(isinstance(t, (int, type(None))) for t in batches[0]["timestamp"])


def test_quoting_and_escapes(tmp_path):
    p = tmp_path / "q.csv"
    p.write_text(
        "latitude,longitude,user_id,source,timestamp\n"
        '1.5,2.5,"a,b",gps,7\n'
        '3.5,4.5,"say ""hi""",gps,8\r\n'
        "5.5,6.5,plain,bg,\n"
    )
    (b,) = list(native.parse_csv_batches(str(p), 10))
    assert b["user_id"] == ["a,b", 'say "hi"', "plain"]
    assert b["source"] == ["gps", "gps", "bg"]
    assert list(b["timestamp"]) == [7, 8, None]
    np.testing.assert_array_equal(b["latitude"], [1.5, 3.5, 5.5])


def test_bad_numeric_fields_become_nan(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text(
        "latitude,longitude,user_id,source,timestamp\n"
        "oops,1.0,u,gps,1\n"
        ",2.0,u,gps,2\n"
        "3.0,3.0,u,gps,3\n"
    )
    (b,) = list(native.parse_csv_batches(str(p), 10))
    assert np.isnan(b["latitude"][0]) and np.isnan(b["latitude"][1])
    assert b["latitude"][2] == 3.0


def test_missing_optional_columns(tmp_path):
    p = tmp_path / "two.csv"
    p.write_text("latitude,longitude\n1.0,2.0\n3.0,4.0\n")
    (b,) = list(native.parse_csv_batches(str(p), 10))
    assert b["user_id"] == ["", ""]
    assert b["source"] == ["", ""]
    assert list(b["timestamp"]) == [None, None]


def test_empty_file_and_header_only(tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("latitude,longitude,user_id,source,timestamp\n")
    assert list(native.parse_csv_batches(str(p), 10)) == []


def test_no_trailing_newline(tmp_path):
    p = tmp_path / "nt.csv"
    p.write_text("latitude,longitude\n1.0,2.0\n3.0,4.0")
    (b,) = list(native.parse_csv_batches(str(p), 10))
    np.testing.assert_array_equal(b["latitude"], [1.0, 3.0])


def test_feeds_batch_pipeline(tmp_path):
    """Native-decoded batches drive the full job identically."""
    from heatmap_tpu.io.sources import CSVSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    p = tmp_path / "pts.csv"
    _write_csv(p, _random_rows(500, seed=3))
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=9)
    out_native = run_job(CSVSource(str(p), use_native=True), config=cfg)
    out_py = run_job(CSVSource(str(p), use_native=False), config=cfg)
    assert out_native == out_py


def test_fast_mode_routing_and_flags(tmp_path):
    p = tmp_path / "r.csv"
    p.write_text(
        "latitude,longitude,user_id,source,timestamp\n"
        "1.0,1.0,alice,gps,1\n"
        "2.0,2.0,x-9,gps,2\n"
        "3.0,3.0,rt-1,gps,3\n"
        "4.0,4.0,rt-2,background,4\n"
        "5.0,5.0,alice,gps,5\n"
        "6.0,6.0,x,gps,6\n"
    )
    names = []
    rows = []
    for b in native.parse_csv_batches(str(p), 100, fast=True):
        names.extend(b["new_group_names"])
        for i in range(len(b["latitude"])):
            r = b["routed"][i]
            rows.append((
                None if r < 0 else names[r],
                bool(b["background"][i]),
            ))
    assert rows == [
        ("alice", False), (None, False), ("route", False),
        ("route", True), ("alice", False), (None, False),
    ]


def test_fast_mode_worker_invariance(tmp_path):
    """Totals per routed group are identical for any worker count.

    The file must exceed n_workers × the 1 MiB/worker clamp in
    hm_csv_open or every run collapses to one worker and the byte-range
    shard-boundary logic goes untested — so build a ~4 MB file.
    """
    p = tmp_path / "w.csv"
    rows = _random_rows(20000, seed=7)
    pad = "p" * 150  # fatten rows so 20k rows ≈ 4 MB
    for r in rows:
        r["user_id"] = r["user_id"] + pad
    _write_csv(p, rows)
    assert p.stat().st_size > 3 * (1 << 20)

    def totals(workers):
        names, acc = [], {}
        n_batches = 0
        for b in native.parse_csv_batches(str(p), 1024, fast=True,
                                          n_workers=workers):
            n_batches += 1
            names.extend(b["new_group_names"])
            keep = ~b["background"]
            for r in b["routed"][keep]:
                key = None if r < 0 else names[r]
                acc[key] = acc.get(key, 0) + 1
        assert n_batches >= 20
        return acc

    t1, t4 = totals(1), totals(4)
    assert sum(t1.values()) == sum(t4.values()) > 15000
    assert t1 == t4


def test_fractional_and_junk_timestamps(tmp_path):
    p = tmp_path / "ts.csv"
    p.write_text(
        "latitude,longitude,user_id,source,timestamp\n"
        "1.0,1.0,u,gps,1.5e3\n"
        "2.0,2.0,u,gps,123abc\n"
        "3.0,3.0,u,gps,42\n"
    )
    (b,) = list(native.parse_csv_batches(str(p), 10))
    # Float timestamps round-trip via double (epoch-ms semantics);
    # unparseable junk -> missing, not a silent prefix-parse.
    assert list(b["timestamp"]) == [1500, None, 42]


def test_empty_csv_file(tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("")
    assert list(native.parse_csv_batches(str(p), 10)) == []


def test_run_job_fast_matches_run_job(tmp_path):
    from heatmap_tpu.io.sources import CSVSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

    p = tmp_path / "pts.csv"
    _write_csv(p, _random_rows(2000, seed=11))
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=9)
    assert run_job_fast(str(p), config=cfg) == run_job(
        CSVSource(str(p), use_native=False), config=cfg
    )


def test_run_job_fast_dated_timespans_match_string_path(tmp_path):
    """Dated timespans on the integer fast path: the i64 epoch-ms
    column + factorized day labeling must bucket exactly like the
    string path's per-row labels."""
    from heatmap_tpu.io.sources import CSVSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

    p = tmp_path / "pts.csv"
    rows = _random_rows(800, seed=3)
    day_ms = 86_400_000
    for i, r in enumerate(rows):  # all-present epoch-ms over a few days
        r["timestamp"] = (i % 5) * day_ms + 12_345
    _write_csv(p, rows)
    cfg = BatchJobConfig(
        detail_zoom=12, min_detail_zoom=9,
        timespans=("alltime", "day", "month", "year"),
    )
    assert run_job_fast(str(p), config=cfg) == run_job(
        CSVSource(str(p), use_native=False), config=cfg
    )


def test_run_job_fast_dated_raises_on_missing_timestamps(tmp_path):
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

    p = tmp_path / "pts.csv"
    _write_csv(p, _random_rows(50, seed=4))  # ~10% empty timestamps
    with pytest.raises(ValueError, match="timestamp"):
        run_job_fast(str(p), config=BatchJobConfig(timespans=("alltime", "day")))


def test_format_blob_bodies_matches_numpy_oracle():
    """The C formatter must be byte-identical to the numpy join/split
    path for integral values, across thread-slice boundaries."""
    if native.format_blob_bodies is None:
        pytest.skip("native library not built")
    rng = np.random.default_rng(12)
    n = 100_000
    lvl = {
        "zoom": 15,
        "row": np.sort(rng.integers(0, 1 << 15, n)).astype(np.int64),
        "col": rng.integers(0, 1 << 15, n).astype(np.int64),
        "value": rng.integers(1, 10_000_000, n).astype(np.float64),
        "slot": np.zeros(n, np.int64),
    }
    is_start = rng.random(n) < 0.3
    is_start[0] = True
    from heatmap_tpu.pipeline.cascade import _blob_bodies

    got = native.format_blob_bodies(lvl["row"], lvl["col"], lvl["value"],
                                    is_start, 15)
    # Force the numpy path by making one value non-integral, then
    # restore: simpler — call the fragment construction directly.
    frag = np.char.add(
        np.char.add(
            np.char.add('"', np.char.add(np.char.add(np.char.add(
                "15_", lvl["row"].astype(str)), "_"),
                lvl["col"].astype(str))),
            '": ',
        ),
        lvl["value"].astype(str),
    )
    parts = np.char.add(np.where(is_start, "}\x00{", ", "), frag)
    want = ("".join(parts.tolist()) + "}").split("\x00")[1:]
    assert got == want
    # The dispatcher picks the native path for integral values and the
    # numpy path otherwise; both must parse to the same content.
    via_dispatch = _blob_bodies(lvl, is_start)
    assert via_dispatch == want


def test_format_blob_bodies_single_blob_and_empty():
    if native.format_blob_bodies is None:
        pytest.skip("native library not built")
    assert native.format_blob_bodies(
        np.empty(0, np.int64), np.empty(0, np.int64),
        np.empty(0), np.empty(0, bool), 10,
    ) == []
    got = native.format_blob_bodies(
        np.asarray([3], np.int64), np.asarray([7], np.int64),
        np.asarray([2.0]), np.asarray([True]), 4,
    )
    assert got == ['{"4_3_7": 2.0}']


def test_staging_pool_roundtrip_and_backpressure():
    with native.StagingPool(1 << 12, 2) as pool:
        a = pool.acquire((512,), np.float64)
        b = pool.acquire((512,), np.float64)
        assert a is not None and b is not None
        assert pool.acquire((1,), np.float32, block=False) is None
        bid, arr = a
        arr[:] = 2.0
        pool.release(bid)
        c = pool.acquire((256,), np.float64, block=False)
        assert c is not None
        cid, carr = c
        # Buffer was recycled: previous contents visible (no re-zeroing).
        assert carr[0] == 2.0
        pool.release(cid)
        pool.release(b[0])


def test_staging_pool_rejects_oversize():
    with native.StagingPool(1 << 10, 1) as pool:
        with pytest.raises(ValueError):
            pool.acquire((1 << 20,), np.float64)


@pytest.mark.slow
def test_tsan_race_detection():
    """Run the native concurrency self-test under ThreadSanitizer
    (SURVEY.md §5 race-detection subsystem). Skips where TSAN can't
    build/run (no toolchain, unsupported sandbox)."""
    import subprocess

    native_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native")
    build = subprocess.run(
        ["make", "-C", native_dir, "build/tsan_selftest"],
        capture_output=True, text=True, timeout=300,
    )
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr[-200:]}")
    run = subprocess.run(
        [os.path.join(native_dir, "build", "tsan_selftest")],
        capture_output=True, text=True, timeout=300,
    )
    if "unsupported" in run.stderr.lower():
        pytest.skip("tsan runtime unsupported here")
    assert run.returncode == 0, f"TSAN reported races:\n{run.stderr[-2000:]}"
    assert "ok" in run.stdout


def test_decode_keys_matches_numpy_oracle():
    """The threaded C key decoder must agree exactly with the numpy
    decode_level_keys + morton_decode_np chain, across code widths and
    thread counts (including the edge keys at each width)."""
    if native.decode_keys is None:
        pytest.skip("native library not built")
    from heatmap_tpu.pipeline.cascade import decode_level_keys
    from heatmap_tpu.tilemath.morton import _morton_decode_np_pure

    rng = np.random.default_rng(5)
    for detail_zoom, level in ((21, 0), (21, 10), (12, 3), (21, 15)):
        code_bits = 2 * (detail_zoom - level)
        n_slots = 37
        # >= 8 * the decoder's 2^16 per-thread floor, so the
        # n_threads=8 case below genuinely runs 8 threads.
        n = 600_001
        codes = rng.integers(0, 1 << code_bits, n, dtype=np.int64)
        slots = rng.integers(0, n_slots, n, dtype=np.int64)
        keys = (slots << code_bits) | codes
        # Edge keys: zero, max code, max slot.
        keys[0] = 0
        keys[1] = (1 << code_bits) - 1
        keys[2] = ((n_slots - 1) << code_bits) | ((1 << code_bits) - 1)
        want_slot, want_code = decode_level_keys(keys, detail_zoom, level)
        want_row, want_col = _morton_decode_np_pure(want_code)
        for n_threads in (1, 8):
            got_slot, got_code, got_row, got_col = native.decode_keys(
                keys, code_bits, n_threads=n_threads
            )
            np.testing.assert_array_equal(got_slot, want_slot)
            np.testing.assert_array_equal(got_code, want_code)
            np.testing.assert_array_equal(got_row, want_row)
            np.testing.assert_array_equal(got_col, want_col)


def test_decode_keys_empty_and_bad_width():
    if native.decode_keys is None:
        pytest.skip("native library not built")
    s, c, r, col = native.decode_keys(np.empty(0, np.int64), 42)
    assert len(s) == len(c) == len(r) == len(col) == 0
    with pytest.raises(ValueError, match="code_bits"):
        native.decode_keys(np.arange(4, dtype=np.int64), 64)


def test_format_blob_ids_matches_numpy_oracle():
    """The C blob-id formatter must produce exactly the np.char chain's
    strings, including multibyte UTF-8 user names and the reference '|'
    separator (KEY_SEPERATOR [sic], reference heatmap.py:18)."""
    if native.format_blob_ids is None:
        pytest.skip("native library not built")
    rng = np.random.default_rng(9)
    n = 70_001
    user_names = np.array(["all", "route", "u-Ä", "东京", "plain", "x|y"])
    ts_names = np.array(["alltime", "2017_02_03"])
    uidx = rng.integers(0, len(user_names), n).astype(np.int32)
    tidx = rng.integers(0, len(ts_names), n).astype(np.int32)
    crow = rng.integers(0, 1 << 16, n).astype(np.int32)
    ccol = rng.integers(0, 1 << 16, n).astype(np.int32)
    zoom = 11
    want = [
        f"{user_names[u]}|{ts_names[t]}|{zoom}_{r}_{c}"
        for u, t, r, c in zip(uidx, tidx, crow, ccol)
    ]
    for n_threads in (1, 8):
        got = native.format_blob_ids(uidx, tidx, crow, ccol, zoom,
                                     user_names, ts_names,
                                     n_threads=n_threads)
        assert got == want


def test_format_blob_ids_rejects_bad_index():
    if native.format_blob_ids is None:
        pytest.skip("native library not built")
    with pytest.raises(ValueError, match="out of range"):
        native.format_blob_ids(
            np.array([5], np.int32), np.array([0], np.int32),
            np.array([1], np.int32), np.array([1], np.int32),
            10, np.array(["only"]), np.array(["alltime"]),
        )


def test_decode_keys_morton_only_and_2d_rejected():
    if native.decode_keys is None:
        pytest.skip("native library not built")
    keys = np.arange(200_000, dtype=np.int64)
    s, c, r, col = native.decode_keys(keys, 0, morton_only=True)
    assert s is None and c is None
    _, _, wr, wc = native.decode_keys(keys, 0)
    np.testing.assert_array_equal(r, wr)
    np.testing.assert_array_equal(col, wc)
    with pytest.raises(ValueError, match="1-D"):
        native.decode_keys(keys.reshape(-1, 2), 0)


def test_format_blob_ids_rejects_absurd_zoom():
    if native.format_blob_ids is None:
        pytest.skip("native library not built")
    with pytest.raises(ValueError, match="coarse_zoom"):
        native.format_blob_ids(
            np.array([0], np.int32), np.array([0], np.int32),
            np.array([1], np.int32), np.array([1], np.int32),
            2**30, np.array(["u"]), np.array(["alltime"]),
        )
