"""bench.py artifact honesty (VERDICT r4 #8).

The driver's BENCH artifact attaches ``last_tpu_measurement`` to
CPU-fallback runs. That field must be mechanically honest: sourced from
``onchip_state/last_bench_tpu.json`` — written ONLY by an actual
on-chip run of the benchmark itself — or an explicit "never". No
hand-typed perf literal may exist to go stale.
"""

import importlib.util
import json
import sys


def _bench(tmp_path, monkeypatch):
    """Import bench.py fresh with cwd at tmp_path (the module resolves
    onchip_state/ relative to the working directory)."""
    monkeypatch.chdir(tmp_path)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", "/root/repo/bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_no_file_means_never(tmp_path, monkeypatch):
    bench = _bench(tmp_path, monkeypatch)
    rec = bench.last_tpu_measurement()
    assert rec["value"] is None
    assert "never" in rec["measured"]


def test_file_backed_record_is_reported(tmp_path, monkeypatch):
    bench = _bench(tmp_path, monkeypatch)
    (tmp_path / "onchip_state").mkdir()
    stored = {"value": 123456789, "unit": "points/sec",
              "measured": "2026-08-01 00:00 UTC"}
    (tmp_path / "onchip_state" / "last_bench_tpu.json").write_text(
        json.dumps(stored)
    )
    rec = bench.last_tpu_measurement()
    assert rec["value"] == 123456789
    assert rec["measured"] == "2026-08-01 00:00 UTC"


def test_malformed_or_foreign_record_rejected(tmp_path, monkeypatch):
    """A record that is not this benchmark's own output shape (wrong
    unit, corrupt JSON) must NOT be reported as measured evidence."""
    bench = _bench(tmp_path, monkeypatch)
    state = tmp_path / "onchip_state"
    state.mkdir()
    (state / "last_bench_tpu.json").write_text('{"value": 5, "unit": "ms"}')
    assert bench.last_tpu_measurement()["value"] is None
    (state / "last_bench_tpu.json").write_text("{corrupt")
    assert bench.last_tpu_measurement()["value"] is None


def test_source_has_no_hand_typed_fallback_number():
    """The one-line mechanical pin: no numeric perf literal anywhere in
    the fallback path. (171373869 was the round-2..4 hand-maintained
    literal; its family must not come back.)"""
    src = open("/root/repo/bench.py").read()
    assert "171373869" not in src
