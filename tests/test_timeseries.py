"""Telemetry time-series store, anomaly detection, /series, /dashboard.

Pins for PR 17's observability tentpole:

- tier rollups are *deterministic and exact*: every rollup bucket's
  min/max/sum/count/last equals a brute-force recomputation from the
  raw sample stream (no float drift, no order dependence);
- /series answers are byte-identical across repeated queries and stamp
  the achieved tier resolution;
- the sampler-off path is invisible: zero new threads and byte-identical
  tile blobs;
- crash-safety: torn spill snapshots are quarantined (never crash
  startup) and the next spill still works;
- the anomaly pipeline fires exactly one ``anomaly_detected`` edge per
  excursion and exactly one incident bundle with the surrounding
  telemetry history embedded.
"""

import json
import os
import random
import threading

import numpy as np
import pytest

from heatmap_tpu import obs
from heatmap_tpu.obs import anomaly, incident, timeseries
from heatmap_tpu.obs.anomaly import (AnomalyEngine, SeriesDetector, WatchSpec,
                                     parse_watch_spec)
from heatmap_tpu.obs.timeseries import (TelemetrySampler, TimeSeriesStore,
                                        flatten_snapshot, parse_series_key,
                                        series_key)
from heatmap_tpu.serve import ServeApp, TileCache
from heatmap_tpu.serve.router import RouterApp
from heatmap_tpu.serve.store import Layer, Level
from heatmap_tpu.tilemath.morton import morton_encode_np

_TS, _MIN, _MAX, _SUM, _COUNT, _LAST = range(6)


class _Clock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------- keys


class TestSeriesKey:
    def test_round_trip_with_sorted_labels(self):
        key = series_key("ingest_lag_seconds", {"shard": "3", "az": "b"})
        assert key == "ingest_lag_seconds{az=b,shard=3}"
        name, labels = parse_series_key(key)
        assert name == "ingest_lag_seconds"
        assert labels == {"az": "b", "shard": "3"}

    def test_bare_name(self):
        assert series_key("up", {}) == "up"
        assert parse_series_key("up") == ("up", {})

    def test_flatten_snapshot_histogram_to_sum_count(self):
        from heatmap_tpu.obs.metrics import MetricsRegistry
        reg = MetricsRegistry()
        reg.enabled = True
        reg.counter("reqs_total", labelnames=("route",)).inc(route="tile")
        reg.gauge("lag_seconds").set(2.5)
        h = reg.histogram("latency_seconds")
        h.observe(0.1)
        h.observe(0.3)
        flat = flatten_snapshot(reg.snapshot())
        assert flat["reqs_total{route=tile}"] == ("counter", 1.0)
        assert flat["lag_seconds"] == ("gauge", 2.5)
        # Histogram buckets are dropped; _sum/_count survive as counters
        # so the dashboard can derive a windowed mean.
        assert flat["latency_seconds_sum"] == ("counter", pytest.approx(0.4))
        assert flat["latency_seconds_count"] == ("counter", 2.0)
        assert not any(k.startswith("latency_seconds_bucket")
                       for k in flat)


# ------------------------------------------------------- rollup math


def _brute_force_tiers(samples, tiers):
    """Independently recompute the expected ring contents of every tier
    from the raw (ts, value) stream, simulating capacity-driven eviction
    exactly as specified: finest tier holds the newest ``cap`` buckets;
    each evicted *bucket's stats row* folds (in arrival order) into the
    next tier's bucket of its timestamp; rows past the last tier drop.
    Folding stats rows — not re-summing raw samples — matters: it
    reproduces the store's float accumulation order bit-for-bit, so the
    comparison can demand exact equality."""
    def fold(rows, step):
        out = []  # stats rows [bucket_ts, min, max, sum, count, last]
        for ts, mn, mx, sm, ct, last in rows:
            b = ts - (ts % step)
            if out and out[-1][0] == b:
                cur = out[-1]
                cur[1] = min(cur[1], mn)
                cur[2] = max(cur[2], mx)
                cur[3] = cur[3] + sm
                cur[4] = cur[4] + ct
                cur[5] = last
            else:
                out.append([b, mn, mx, sm, ct, last])
        return out

    rows = [(ts, v, v, v, 1, v) for ts, v in samples]
    expect = []
    for step, cap in tiers:
        rows = fold(rows, step)
        expect.append([list(r) for r in rows[-cap:]])
        rows = rows[:-cap]  # evicted rows cascade to the next tier
    return expect


class TestRollupDeterminism:
    TIERS = ((10.0, 4), (60.0, 6), (600.0, 64))

    def _feed(self, store, seed=5, n=400):
        rng = random.Random(seed)
        clock = _Clock(0.0)
        stream = []
        for _ in range(n):
            clock.advance(rng.uniform(3.0, 17.0))
            v = rng.uniform(-50.0, 50.0)
            stream.append((clock.t, v))
            store.observe("sig", v, ts=clock.t)
        return stream

    def test_rollups_match_brute_force_exactly(self):
        store = TimeSeriesStore(tiers=self.TIERS, clock=_Clock(0.0))
        stream = self._feed(store)
        expect = _brute_force_tiers(stream, self.TIERS)
        entry = store._series["sig"]
        for level, rows in enumerate(expect):
            got = [list(p[:6]) for p in entry["tiers"][level]]
            # Exact equality: rollups are pure min/max/sum/count folds,
            # so there is no tolerance to hide drift behind.
            assert got == rows, f"tier {level} mismatch"

    def test_identical_streams_identical_dumps(self):
        a = TimeSeriesStore(tiers=self.TIERS, clock=_Clock(0.0))
        b = TimeSeriesStore(tiers=self.TIERS, clock=_Clock(0.0))
        self._feed(a)
        self._feed(b)
        assert json.dumps(a._dump_locked(), sort_keys=True) == \
            json.dumps(b._dump_locked(), sort_keys=True)

    def test_byte_cap_bounds_series_count(self):
        caps = sum(c for _, c in self.TIERS)
        store = TimeSeriesStore(
            tiers=self.TIERS,
            max_bytes=3 * timeseries.POINT_BYTES * caps)
        assert store.max_series == 3
        for i in range(7):
            store.observe(f"s{i}", 1.0, ts=100.0)
        stats = store.stats()
        assert stats["series"] == 3
        assert stats["dropped_series"] == 4

    def test_tiers_must_be_finest_first(self):
        with pytest.raises(ValueError):
            TimeSeriesStore(tiers=((60.0, 10), (10.0, 10)))
        with pytest.raises(ValueError):
            TimeSeriesStore(tiers=())


# ------------------------------------------------------------ queries


class TestQuery:
    def _hour_store(self):
        # Raw tier only retains 30 buckets (5 min); a 1 h query must be
        # answered from the 60 s rollup tier.
        clock = _Clock(0.0)
        store = TimeSeriesStore(
            tiers=((10.0, 30), (60.0, 120), (600.0, 432)), clock=clock)
        for i in range(720):  # 2 h at 10 s cadence
            clock.advance(10.0)
            store.observe("lag", float(i % 7), ts=clock.t)
        return store, clock

    def test_one_hour_answered_from_rollup_with_resolution_stamp(self):
        store, clock = self._hour_store()
        doc = store.query("lag", start=clock.t - 3600.0, end=clock.t)
        assert doc["requested_step"] is None
        (frame,) = doc["frames"]
        assert frame["tier"] == 1
        assert frame["step"] == 60.0
        pts = frame["points"]
        assert pts, "rollup tier should cover the hour"
        assert all(p[_TS] % 60.0 == 0 for p in pts)
        assert all(clock.t - 3600.0 <= p[_TS] + 60.0 for p in pts)
        # The newest ~5 min still lives in the raw tier, so the rollup
        # frame holds the remaining ~55 one-minute buckets of the hour.
        assert 54 <= len(pts) <= 61

    def test_repeat_queries_byte_identical(self):
        store, clock = self._hour_store()
        kw = dict(start=clock.t - 3600.0, end=clock.t)
        a = json.dumps(store.query("lag", **kw), sort_keys=True)
        b = json.dumps(store.query("lag", **kw), sort_keys=True)
        assert a == b

    def test_step_regroup_preserves_mass(self):
        store, clock = self._hour_store()
        kw = dict(start=clock.t - 3600.0, end=clock.t)
        fine = store.query("lag", **kw)["frames"][0]
        coarse = store.query("lag", step=120.0, **kw)["frames"][0]
        assert coarse["step"] == 120.0
        assert all(p[_TS] % 120.0 == 0 for p in coarse["points"])
        # Regrouping is a pure fold: total count and sum conserved.
        assert sum(p[_COUNT] for p in coarse["points"]) == \
            sum(p[_COUNT] for p in fine["points"])
        assert sum(p[_SUM] for p in coarse["points"]) == \
            pytest.approx(sum(p[_SUM] for p in fine["points"]))

    def test_label_filter_selects_subset(self):
        store = TimeSeriesStore(clock=_Clock(100.0))
        store.observe(series_key("q", {"shard": "0"}), 1.0, ts=100.0)
        store.observe(series_key("q", {"shard": "1"}), 2.0, ts=100.0)
        doc = store.query("q", labels={"shard": "1"})
        assert [f["labels"] for f in doc["frames"]] == [{"shard": "1"}]
        assert store.query("q")["frames"][0]["labels"] == {"shard": "0"}
        assert len(store.query("q")["frames"]) == 2

    def test_recent_window_is_raw_tier(self):
        clock = _Clock(0.0)
        store = TimeSeriesStore(clock=clock)
        for i in range(40):
            clock.advance(10.0)
            store.observe("x", float(i), ts=clock.t)
        win = store.recent_window(seconds=120.0)
        assert win["window_s"] == 120.0
        pts = win["series"]["x"]["points"]
        assert win["series"]["x"]["step"] == 10.0
        assert all(p[_TS] >= clock.t - 120.0 - 10.0 for p in pts)


# -------------------------------------------------------------- spill


class TestSpill:
    def _seeded(self, root, clock):
        store = TimeSeriesStore(spill_dir=str(root), clock=clock)
        for i in range(30):
            clock.advance(10.0)
            store.observe("lag", float(i), ts=clock.t)
        return store

    def test_round_trip(self, tmp_path):
        clock = _Clock(0.0)
        store = self._seeded(tmp_path / "tel", clock)
        store.spill()
        reloaded = TimeSeriesStore(spill_dir=str(tmp_path / "tel"),
                                   clock=clock)
        reloaded.load_spill()
        assert json.dumps(reloaded._dump_locked()["series"],
                          sort_keys=True) == \
            json.dumps(store._dump_locked()["series"], sort_keys=True)

    def test_torn_snap_quarantined_next_spill_works(self, tmp_path):
        clock = _Clock(0.0)
        root = tmp_path / "tel"
        store = self._seeded(root, clock)
        store.spill()
        # Tear the snapshot: manifest byte count no longer matches.
        (snap,) = [p for p in os.listdir(root) if p.startswith("snap-")]
        with open(root / snap / "series.json", "w") as f:
            f.write('{"torn')
        # Plus an orphan tmp dir from a simulated crash mid-publish.
        os.makedirs(root / ".tmp-snap-crashed")
        log_path = tmp_path / "events.jsonl"
        obs.set_event_log(obs.EventLog(str(log_path)))
        try:
            fresh = TimeSeriesStore(spill_dir=str(root), clock=clock)
            fresh.load_spill()  # must not raise
        finally:
            obs.get_event_log().close()
            obs.set_event_log(None)
        assert fresh.stats()["series"] == 0  # nothing restorable
        qdir = root / "quarantine"
        assert qdir.is_dir() and len(os.listdir(qdir)) == 2
        recs = [json.loads(line) for line in
                open(log_path).read().splitlines() if line.strip()]
        reasons = sorted(r["reason"] for r in recs
                         if r.get("event") == "quarantine")
        assert reasons == ["orphan_tmp", "torn_telemetry"]
        assert all(r["kind"] == "telemetry" for r in recs
                   if r.get("event") == "quarantine")
        # The torn snap never blocks forward progress.
        clock.advance(10.0)
        fresh.observe("lag", 1.0, ts=clock.t)
        fresh.spill()
        again = TimeSeriesStore(spill_dir=str(root), clock=clock)
        again.load_spill()
        assert again.stats()["series"] == 1


# ------------------------------------------------------------ sampler


class TestSampler:
    def test_interval_must_be_positive(self):
        with pytest.raises(ValueError):
            TelemetrySampler(TimeSeriesStore(), 0.0)

    def test_sample_once_feeds_store_and_engine(self):
        from heatmap_tpu.obs.metrics import MetricsRegistry
        reg = MetricsRegistry()
        reg.enabled = True
        reg.gauge("lag_seconds").set(4.0)
        clock = _Clock(0.0)
        store = TimeSeriesStore(clock=clock)
        engine = AnomalyEngine([WatchSpec("lag_seconds")], clock=clock)
        sampler = TelemetrySampler(store, 10.0, registry=reg,
                                   engine=engine, clock=clock)
        for _ in range(3):
            clock.advance(10.0)
            sampler.sample_once(clock.t)
        assert sampler.ticks == 3
        assert sampler.errors == 0
        assert store.stats()["samples_total"] == 3
        assert "lag_seconds" in store.series_names()
        assert engine.status()["series_tracked"] == 1

    def test_periodic_spill_every_n_ticks(self, tmp_path):
        from heatmap_tpu.obs.metrics import MetricsRegistry
        reg = MetricsRegistry()
        reg.enabled = True
        reg.gauge("g").set(1.0)
        clock = _Clock(0.0)
        store = TimeSeriesStore(spill_dir=str(tmp_path), clock=clock)
        sampler = TelemetrySampler(store, 10.0, registry=reg, clock=clock,
                                   spill_every_ticks=2)
        for _ in range(4):
            sampler.sample_once(clock.advance(10.0))
        snaps = [p for p in os.listdir(tmp_path) if p.startswith("snap-")]
        assert snaps, "expected a periodic spill after 2 ticks"

    def test_arm_off_means_zero_threads(self):
        # With the sampler never armed there is no store, no engine, and
        # crucially no background thread.
        assert timeseries.get_store() is None
        assert timeseries.get_sampler() is None
        names = [t.name for t in threading.enumerate()]
        assert "telemetry-sampler" not in names

    def test_arm_and_shutdown_lifecycle(self):
        timeseries.arm(30.0)
        try:
            assert timeseries.get_store() is not None
            names = [t.name for t in threading.enumerate()]
            assert "telemetry-sampler" in names
        finally:
            timeseries.shutdown()
        names = [t.name for t in threading.enumerate()]
        assert "telemetry-sampler" not in names
        assert timeseries.get_store() is None


# ---------------------------------------------------- watch grammar


class TestWatchGrammar:
    def test_defaults(self):
        spec = parse_watch_spec("ingest_lag_seconds")
        assert spec == WatchSpec("ingest_lag_seconds")
        assert spec.z == 6.0 and spec.alpha == 0.3

    def test_full_spec(self):
        spec = parse_watch_spec(
            "lag:z=4,alpha=0.5,min_count=20,clear_ratio=0.25")
        assert (spec.name, spec.z, spec.alpha, spec.min_count,
                spec.clear_ratio) == ("lag", 4.0, 0.5, 20, 0.25)

    @pytest.mark.parametrize("bad", [
        "", ":z=4", "lag:z", "lag:zz=4", "lag:z=abc",
        "lag:z=0", "lag:alpha=2",
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_watch_spec(bad)


# ----------------------------------------------------------- detector


class TestDetector:
    def _spec(self):
        return WatchSpec("lag", z=4.0, min_count=5)

    def test_exactly_one_edge_per_excursion(self):
        det = SeriesDetector(self._spec())
        edges = 0
        for i in range(30):  # quiet baseline with deterministic wiggle
            edges += bool(det.observe(10.0 + (i % 3) * 0.01))
        assert edges == 0
        for _ in range(5):  # sustained excursion: one rising edge only
            edges += bool(det.observe(100.0))
        assert edges == 1

    def test_hysteresis_rearms_after_clear(self):
        det = SeriesDetector(self._spec())
        for i in range(30):
            det.observe(10.0 + (i % 3) * 0.01)
        assert sum(bool(det.observe(100.0)) for _ in range(3)) == 1
        for i in range(40):  # long return to baseline clears the breach
            det.observe(10.0 + (i % 3) * 0.01)
        assert not det.breaching
        assert sum(bool(det.observe(100.0)) for _ in range(3)) == 1


# ------------------------------------------- anomaly -> incident path


class TestAnomalyToIncident:
    def test_one_edge_one_bundle_with_embedded_history(self, tmp_path):
        clock = _Clock(1_000.0)
        store = TimeSeriesStore(clock=clock)
        timeseries.install(store)
        engine = AnomalyEngine(
            [WatchSpec("lag_seconds", z=4.0, min_count=5)], clock=clock)
        anomaly.set_engine(engine)
        mgr = incident.IncidentManager(str(tmp_path / "inc"),
                                       min_interval_s=3600.0, clock=clock)
        incident.set_manager(mgr)
        log_path = tmp_path / "events.jsonl"
        obs.set_event_log(obs.EventLog(str(log_path)))
        obs.enable_metrics(True)
        try:
            def tick(value):
                clock.advance(10.0)
                flat = {"lag_seconds": ("gauge", value)}
                store.append_flat(flat, ts=clock.t)
                engine.observe_tick(flat, ts=clock.t)

            for i in range(30):
                tick(2.0 + (i % 3) * 0.01)
            for _ in range(5):  # sustained spike: one edge, not five
                tick(50.0)
            obs.get_event_log().close()
            obs.set_event_log(None)
            recs = [json.loads(line) for line in
                    open(log_path).read().splitlines() if line.strip()]
            edges = [r for r in recs if r.get("event") == "anomaly_detected"]
            assert len(edges) == 1
            assert edges[0]["series"] == "lag_seconds"
            assert edges[0]["z"] >= 4.0
            snap = obs.get_registry().snapshot()
            (sample,) = snap["anomalies_total"]["samples"]
            assert sample == {"labels": {"watch": "lag_seconds"},
                              "value": 1.0}

            bundles = sorted((tmp_path / "inc").iterdir())
            assert len(bundles) == 1
            bundle = str(bundles[0])
            manifest = json.loads(
                open(os.path.join(bundle, "manifest.json")).read())
            assert manifest["trigger"] == "anomaly"
            tel = json.loads(
                open(os.path.join(bundle, "telemetry.json")).read())
            pts = tel["series"]["lag_seconds"]["points"]
            assert pts, "bundle must embed the surrounding history"
            # The embedded window covers the pre-spike baseline too.
            assert min(p[_LAST] for p in pts) < 3.0
            assert max(p[_LAST] for p in pts) == 50.0
        finally:
            incident.set_manager(None)
            anomaly.set_engine(None)
            timeseries.install(None)

    def test_engine_recent_and_status(self):
        clock = _Clock(0.0)
        engine = AnomalyEngine([WatchSpec("x", z=4.0, min_count=5)],
                               clock=clock)
        for i in range(30):
            engine.observe_tick({"x": ("gauge", 1.0 + (i % 3) * 0.01)},
                                ts=clock.advance(10.0))
        engine.observe_tick({"x": ("gauge", 99.0)}, ts=clock.advance(10.0))
        status = engine.status()
        assert status["edges"] == 1
        assert status["breaching"] == ["x"]
        (rec,) = engine.recent()
        assert rec["series"] == "x" and rec["z"] >= 4.0


# ---------------------------------------------------- HTTP endpoints


class _BareTileStore:
    """Just enough TileStore surface for ServeApp routes that don't
    read tiles from disk (/series, /dashboard, cache-keyed renders of
    attached layers)."""
    generation = 0
    delta_epoch = 0
    synopsis_epoch = 0

    def layer(self, name):
        return None

    def layer_names(self):
        return []

    def stats(self):
        return {"layers": {}}


def _bare_app():
    app = ServeApp(_BareTileStore(), TileCache())
    layer = Layer("u", "t", result_delta=2)
    layer.levels[6] = Level(
        6,
        morton_encode_np(np.asarray([16, 17], np.int64),
                         np.asarray([16, 21], np.int64)),
        np.asarray([1.0, 4.0], np.float64),
    )
    app.attach_layer("default", layer)
    return app


class TestSeriesEndpoint:
    def test_missing_name_is_typed_400(self):
        status, ctype, body, *_ = _bare_app().handle("GET", "/series")
        assert status == 400 and ctype == "application/json"
        assert "name" in json.loads(body)["detail"]

    @pytest.mark.parametrize("query", ["name=x&step=-1", "name=x&from=abc"])
    def test_bad_params_are_typed_400(self, query):
        status, _, body, *_ = _bare_app().handle("GET", "/series?" + query)
        assert status == 400
        assert json.loads(body)["error"] == "bad query"

    def test_sampler_off_is_wellformed_not_error(self):
        status, _, body, *_ = _bare_app().handle("GET", "/series?name=x")
        assert status == 200
        doc = json.loads(body)
        assert doc["enabled"] is False and doc["frames"] == []
        assert "--telemetry-sample-interval" in doc["detail"]

    def test_query_with_store_and_repeat_identity(self):
        clock = _Clock(0.0)
        store = TimeSeriesStore(clock=clock)
        for i in range(20):
            clock.advance(10.0)
            store.observe("lag", float(i), ts=clock.t)
        timeseries.install(store)
        try:
            app = _bare_app()
            q = f"name=lag&from={clock.t - 100}&to={clock.t}"
            status, _, body, *_ = app.handle("GET", "/series?" + q)
            assert status == 200
            doc = json.loads(body)
            assert doc["enabled"] is True
            (frame,) = doc["frames"]
            assert frame["step"] == 10.0 and frame["tier"] == 0
            assert app.handle("GET", "/series?" + q)[2] == body
        finally:
            timeseries.install(None)

    def test_sampler_off_blobs_byte_identical_and_no_threads(self):
        # The flagship zero-cost pin: the tile bytes a ServeApp produces
        # must not depend on whether telemetry is armed, and the off
        # path must not create threads.
        before = {t.name for t in threading.enumerate()}
        path = "/tiles/default/2/1/1.png"
        off = _bare_app().handle("GET", path)
        assert off[0] == 200
        assert {t.name for t in threading.enumerate()} == before
        store = TimeSeriesStore(clock=_Clock(0.0))
        store.observe("noise", 1.0, ts=1.0)
        timeseries.install(store)
        try:
            on = _bare_app().handle("GET", path)
        finally:
            timeseries.install(None)
        assert on[2] == off[2]

    def test_health_reports_telemetry_and_anomalies(self):
        clock = _Clock(0.0)
        store = TimeSeriesStore(clock=clock)
        store.observe("x", 1.0, ts=clock.advance(10.0))
        timeseries.install(store)
        anomaly.set_engine(AnomalyEngine([WatchSpec("x")], clock=clock))
        try:
            status, _, body, *_ = _bare_app().handle("GET", "/healthz")
            doc = json.loads(body)
            assert doc["telemetry"]["series"] == 1
            assert doc["anomalies"] == []
            assert [w["name"] for w in doc["anomaly_watches"]] == ["x"]
        finally:
            anomaly.set_engine(None)
            timeseries.install(None)


class TestDashboard:
    def test_serve_page_is_self_contained_html(self):
        status, ctype, body, *_ = _bare_app().handle("GET", "/dashboard")
        assert status == 200
        assert ctype.startswith("text/html")
        page = body.decode("utf-8")
        assert page.startswith("<!DOCTYPE html>")
        # No external assets: everything inline, stdlib-served.
        for banned in ("http://", "https://", "src=", "@import",
                       "<link"):
            assert banned not in page, f"external asset ref: {banned}"
        # The page polls the endpoints this PR ships.
        assert "/series" in page and "/healthz" in page

    def test_router_serves_dashboard_too(self):
        router = RouterApp([])
        status, ctype, body, *_ = router.handle("GET", "/dashboard")
        assert status == 200 and ctype.startswith("text/html")
        assert b"fleet" in body


class _FakeBackend:
    def __init__(self, bid, doc=None, status=200, fail=False):
        self.id = bid
        self._doc = doc
        self._status = status
        self._fail = fail

    def eligible(self):
        return True

    def fetch(self, method, path):
        if self._fail:
            raise OSError("connection refused")
        body = json.dumps(self._doc or {}).encode()
        return self._status, {"Content-Type": "application/json"}, body


class TestRouterSeries:
    def _backend_doc(self):
        return {"enabled": True, "name": "lag", "frames": [
            {"key": "lag", "labels": {}, "step": 10.0, "tier": 0,
             "points": [[10.0, 1.0, 1.0, 1.0, 1.0, 1.0]]}]}

    def test_fleet_merge_labels_origins(self):
        clock = _Clock(100.0)
        store = TimeSeriesStore(clock=clock)
        store.observe("lag", 2.0, ts=clock.t)
        timeseries.install(store)
        try:
            router = RouterApp([])
            router.backends = {
                "b0": _FakeBackend("b0", self._backend_doc()),
                "b1": _FakeBackend("b1", fail=True),  # skipped, not fatal
            }
            status, _, body, *_ = router.handle(
                "GET", "/series?name=lag&fleet=1")
            assert status == 200
            doc = json.loads(body)
            assert doc["enabled"] is True
            origins = sorted(f["backend"] for f in doc["frames"])
            assert origins == ["b0", "router"]
        finally:
            timeseries.install(None)

    def test_without_fleet_flag_local_only(self):
        router = RouterApp([])
        router.backends = {"b0": _FakeBackend("b0", self._backend_doc())}
        status, _, body, *_ = router.handle("GET", "/series?name=lag")
        doc = json.loads(body)
        assert status == 200
        assert doc["enabled"] is False and doc["frames"] == []

    def test_fleet_merge_enabled_when_any_backend_samples(self):
        # Router itself unarmed, but a backend has history: merged doc
        # reports enabled and carries the backend frames.
        router = RouterApp([])
        router.backends = {"b0": _FakeBackend("b0", self._backend_doc())}
        status, _, body, *_ = router.handle(
            "GET", "/series?name=lag&fleet=1")
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert [f["backend"] for f in doc["frames"]] == ["b0"]
        assert "detail" not in doc
