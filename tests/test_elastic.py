"""Elastic multihost execution (parallel.elastic).

Three pillars under test: the shard-lineage manifest (content-hashed
shards, atomic publish, exactly-once by hash), failover re-execution
(orphaned shards round-robin to survivors after a straggler timeout),
and speculative straggler duplication (first-completion-wins, the
loser quarantined — never double-merged). The end-to-end anchors: an
elastic run equals a plain ``run_job`` of the same input, and a run
that loses a host mid-cascade is byte-identical to an unfailed one.
"""

import os

import numpy as np
import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.parallel.elastic import (
    ElasticCoordinator,
    ShardLineage,
    WorkShard,
    job_fingerprint,
    plan_shards,
    run_job_elastic,
    shard_fingerprint,
)
from heatmap_tpu.pipeline import BatchJobConfig, run_job

CFG = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)


def _shards(n, job_fp="jfp"):
    return plan_shards(n, n, job_fp)


def _tiny_levels(value):
    """A minimal one-row finalized level (write_levels input shape)."""
    return [{
        "zoom": 8, "coarse_zoom": 6,
        "row": np.array([3], np.int64), "col": np.array([5], np.int64),
        "value": np.array([float(value)]),
        "user_idx": np.array([0], np.int32),
        "timespan_idx": np.array([0], np.int32),
        "coarse_row": np.array([0], np.int64),
        "coarse_col": np.array([1], np.int64),
        "user_names": np.array(["all"]),
        "timespan_names": np.array(["alltime"]),
    }]


def _levels_bytes(path):
    out = {}
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if os.path.isfile(full):
            with open(full, "rb") as f:
                out[name] = f.read()
    return out


# ------------------------------------------------------------ plan + hashes

def test_plan_shards_partition():
    for n_batches in (1, 5, 8, 17):
        for n_shards in (1, 3, 8, 30):
            plan = plan_shards(n_batches, n_shards, "fp")
            # Contiguous, disjoint, covering, balanced within 1,
            # never an empty shard (n_shards clamps to n_batches).
            assert plan[0].lo == 0 and plan[-1].hi == n_batches
            for a, b in zip(plan, plan[1:]):
                assert a.hi == b.lo
            sizes = [s.hi - s.lo for s in plan]
            assert min(sizes) >= 1
            assert max(sizes) - min(sizes) <= 1
            assert [s.index for s in plan] == list(range(len(plan)))


def test_shard_fingerprints_deterministic_and_distinct():
    a = plan_shards(8, 4, "job-a")
    b = plan_shards(8, 4, "job-a")
    c = plan_shards(8, 4, "job-b")
    assert [s.fingerprint for s in a] == [s.fingerprint for s in b]
    assert len({s.fingerprint for s in a}) == 4  # distinct per range
    # A different job fingerprint shifts every shard identity.
    assert {s.fingerprint for s in a}.isdisjoint(
        {s.fingerprint for s in c})
    assert shard_fingerprint("j", 0, 2) != shard_fingerprint("j", 0, 3)


def test_job_fingerprint_pins_input_and_config():
    src = SyntheticSource(n=100, seed=1)
    base = job_fingerprint(src, CFG, 32, 100)
    assert base == job_fingerprint(SyntheticSource(n=100, seed=1),
                                   CFG, 32, 100)
    assert base != job_fingerprint(SyntheticSource(n=100, seed=2),
                                   CFG, 32, 100)
    assert base != job_fingerprint(src, CFG, 64, 100)
    other = BatchJobConfig(detail_zoom=11, min_detail_zoom=8,
                           result_delta=2)
    assert base != job_fingerprint(src, other, 32, 100)


# ------------------------------------------------------------ lineage

def test_lineage_publish_exactly_once(tmp_path, monkeypatch):
    """The no-double-merge pin: of two racing publishes of one shard,
    exactly one artifact lands in the manifest, the loser is
    quarantined, and the merge reads the winner's bytes only."""
    import heatmap_tpu.parallel.elastic as el

    lineage = ShardLineage(str(tmp_path / "lin"))
    shard = _shards(1)[0]
    real = el.publish_dir
    raced = []

    def racing(tmp, final):
        if not raced:
            raced.append(1)
            # The twin wins the race in the window between our manifest
            # check and our rename: its artifact lands at final first.
            wtmp = final + ".tmp-twin"
            LevelArraysSink(wtmp).write_levels(_tiny_levels(7.0))
            real(wtmp, final)
        return real(tmp, final)

    monkeypatch.setattr(el, "publish_dir", racing)
    won, q = lineage.publish(shard, 2, _tiny_levels(99.0), {"points": 1})
    assert not won
    assert lineage.is_complete(shard)
    assert q is not None and os.path.isdir(q)
    assert os.path.dirname(q) == lineage.quarantine_dir
    merged = lineage.merge([shard])
    assert len(merged) == 1
    assert float(np.asarray(merged[0]["value"])[0]) == 7.0  # winner only
    # A later attempt short-circuits on the manifest without staging.
    won3, q3 = lineage.publish(shard, 3, _tiny_levels(5.0), {})
    assert not won3 and q3 is None
    assert float(np.asarray(lineage.merge([shard])[0]["value"])[0]) == 7.0


def test_lineage_merge_refuses_missing_shards(tmp_path):
    lineage = ShardLineage(str(tmp_path))
    shards = _shards(2)
    lineage.publish(shards[0], 0, _tiny_levels(1.0), {})
    with pytest.raises(RuntimeError, match="missing"):
        lineage.merge(shards)


# ------------------------------------------------------------ coordinator

def test_coordinator_orphan_stale_round_robin():
    shards = _shards(6)
    coord = ElasticCoordinator(shards, [0, 1, 2])
    # Host 2 owns shards 2 and 5; it completes shard 2, then dies.
    s2, mode = coord.next_work(2, now=0.0)
    assert (s2.index, mode) == (2, "own")
    coord.mark_done(s2, 2, now=1.0)
    moved = coord.orphan_stale(["2"])
    assert moved == 1  # only shard 5 was still unfinished
    assert coord.reassigned == 1
    assert coord.owner[5] in (0, 1)
    # Idempotent: a second stale report of the same host is a no-op.
    assert coord.orphan_stale(["2"]) == 0
    # The dead host is never handed new work.
    assert coord.next_work(2, now=2.0) is None
    # Survivors drain their own queues plus the orphan.
    seen = []
    for host in (0, 1):
        while True:
            got = coord.next_work(host, now=3.0)
            if got is None:
                break
            seen.append(got[0].index)
            coord.mark_done(got[0], host, now=4.0)
    assert sorted(seen) == [0, 1, 3, 4, 5]
    assert coord.all_done()


def test_coordinator_orphan_spread_over_survivors():
    """A dead host's whole queue spreads round-robin, not onto one
    survivor."""
    shards = _shards(9)
    coord = ElasticCoordinator(shards, [0, 1, 2])
    assert coord.orphan_stale([0]) == 3  # shards 0, 3, 6
    dests = {coord.owner[i] for i in (0, 3, 6)}
    assert dests == {1, 2}
    got = coord.next_work(1, now=0.0)
    assert got is not None


def test_coordinator_no_survivors_raises():
    coord = ElasticCoordinator(_shards(2), [0, 1])
    with pytest.raises(RuntimeError, match="no surviving"):
        coord.orphan_stale([0, 1])


def test_coordinator_speculation_threshold_fake_clock():
    shards = _shards(5)
    coord = ElasticCoordinator(shards, [0, 1],
                               speculative_quantile=0.5,
                               speculative_factor=2.0, min_samples=3)
    # Host 0 runs shards 0, 2, 4; host 1 starts shard 1 and straggles.
    s1, _ = coord.next_work(1, now=0.0)
    assert s1.index == 1
    for _ in range(3):
        s, _ = coord.next_work(0, now=10.0)
        coord.mark_done(s, 0, now=11.0)  # three 1s completions
    # threshold = 2.0 * median(1s) = 2s; shard 1 has run 12s.
    assert coord.speculation_threshold() == pytest.approx(2.0)
    got = coord.next_work(0, now=12.0)
    assert got is not None
    dup, mode = got
    assert (dup.index, mode) == (1, "speculate")
    # Never duplicated twice, and never offered to its own runner.
    assert coord.next_work(0, now=20.0) is None
    # First completion wins: the duplicate finishes first.
    assert coord.mark_done(dup, 0, now=13.0) is True
    assert coord.mark_done(s1, 1, now=14.0) is False


def test_coordinator_speculation_needs_samples():
    coord = ElasticCoordinator(_shards(4), [0, 1],
                               speculative_quantile=0.5, min_samples=3)
    s, _ = coord.next_work(1, now=0.0)
    for _ in range(2):
        own, _ = coord.next_work(0, now=0.0)
        coord.mark_done(own, 0, now=1.0)
    assert coord.speculation_threshold() is None  # 2 < min_samples
    assert coord.next_work(0, now=100.0) is None


# ------------------------------------------------------------ end to end

def test_run_job_elastic_matches_run_job(tmp_path):
    """pyramid(union) == ⊕ pyramid(shard): the elastic merge equals the
    plain single-process cascade, order-insensitively."""
    src = SyntheticSource(n=1200, seed=3)
    plain_dir, el_dir = str(tmp_path / "plain"), str(tmp_path / "el")
    run_job(SyntheticSource(n=1200, seed=3), LevelArraysSink(plain_dir),
            config=CFG, batch_size=300)
    out = run_job_elastic(src, LevelArraysSink(el_dir), CFG,
                          batch_size=300,
                          lineage_dir=str(tmp_path / "lin"),
                          n_hosts=2)
    assert out["egress"] == "levels-elastic"
    assert out["shards"] == 4 and out["reassigned"] == 0
    plain = LevelArraysSink.load(plain_dir)
    el = LevelArraysSink.load(el_dir)
    assert sorted(plain) == sorted(el)
    for z in plain:
        a, b = plain[z], el[z]
        ka = np.lexsort((np.asarray(a["timespan"], str),
                         np.asarray(a["user"], str),
                         a["col"], a["row"]))
        kb = np.lexsort((np.asarray(b["timespan"], str),
                         np.asarray(b["user"], str),
                         b["col"], b["row"]))
        for col in ("row", "col", "value"):
            np.testing.assert_array_equal(np.asarray(a[col])[ka],
                                          np.asarray(b[col])[kb])


def test_run_job_elastic_resumes_from_lineage(tmp_path):
    """A re-run over an existing manifest re-executes nothing and
    produces identical bytes (exactly-once by shard hash)."""
    src = lambda: SyntheticSource(n=900, seed=5)  # noqa: E731
    lin = str(tmp_path / "lin")
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    run_job_elastic(src(), LevelArraysSink(d1), CFG, batch_size=300,
                    lineage_dir=lin, n_hosts=2)
    stamps = {s: os.path.getmtime(os.path.join(lin, "shards", s))
              for s in os.listdir(os.path.join(lin, "shards"))}
    run_job_elastic(src(), LevelArraysSink(d2), CFG, batch_size=300,
                    lineage_dir=lin, n_hosts=2)
    after = {s: os.path.getmtime(os.path.join(lin, "shards", s))
             for s in os.listdir(os.path.join(lin, "shards"))}
    assert after == stamps  # no artifact was rewritten
    assert _levels_bytes(d1) == _levels_bytes(d2)


def test_run_job_elastic_rejects_blob_sinks(tmp_path):
    class Blobby:
        def write(self, *a):
            pass

    with pytest.raises(ValueError, match="columnar"):
        run_job_elastic(SyntheticSource(n=10), Blobby(), CFG,
                        lineage_dir=str(tmp_path / "lin"))
    with pytest.raises(ValueError, match="on_straggler"):
        run_job_elastic(SyntheticSource(n=10), None, CFG,
                        lineage_dir=str(tmp_path / "lin"),
                        on_straggler="bogus")


def test_host_loss_reassigns_and_stays_byte_identical(tmp_path):
    """The acceptance anchor: kill one simulated host mid-cascade (its
    heartbeats eaten by the ``multihost.heartbeat`` fault site after it
    completes a shard), the job finishes on the survivors, and the
    merged arrays are byte-identical to an unfailed elastic run."""
    # 6 batches -> 6 shards over 3 hosts: host 2 owns shards 2 and 5,
    # so after it completes one shard the wedge leaves one to orphan.
    src = lambda: SyntheticSource(n=900, seed=7)  # noqa: E731
    ok_dir, loss_dir = str(tmp_path / "ok"), str(tmp_path / "loss")
    log_path = str(tmp_path / "events.jsonl")
    obs.enable_metrics(True)
    obs.set_event_log(obs.EventLog(log_path))
    try:
        run_job_elastic(src(), LevelArraysSink(ok_dir), CFG,
                        batch_size=150,
                        lineage_dir=str(tmp_path / "lin-ok"), n_hosts=3)
        obs.get_registry().reset()
        out = run_job_elastic(
            src(), LevelArraysSink(loss_dir), CFG, batch_size=150,
            lineage_dir=str(tmp_path / "lin-loss"), n_hosts=3,
            heartbeat_deadline_s=0.3, on_straggler="reassign",
            wedge_host=2, wedge_after=1,
            wedge_spec="seed=29,scale=0,multihost.heartbeat@p2=999",
            beat_interval_s=0.05)
        assert out["reassigned"] > 0
        assert obs.ELASTIC_REASSIGNMENTS.value() > 0
    finally:
        faults.install(None)  # the wedge installed its own plane
        log = obs.get_event_log()
        obs.set_event_log(None)
        if log is not None:
            log.close()
        obs.enable_metrics(False)
    names = [r["event"] for r in obs.read_events(log_path)]
    assert "shard_orphaned" in names and "shard_reassigned" in names
    assert _levels_bytes(ok_dir) == _levels_bytes(loss_dir)


def test_host_loss_raise_mode_propagates(tmp_path):
    """on_straggler="raise" (the default) keeps the old contract: the
    same mid-cascade death aborts the job with StragglerTimeout."""
    from heatmap_tpu.parallel.multihost import StragglerTimeout

    obs.enable_metrics(True)
    try:
        with pytest.raises(StragglerTimeout):
            run_job_elastic(
                SyntheticSource(n=900, seed=7),
                LevelArraysSink(str(tmp_path / "out")), CFG,
                batch_size=150, lineage_dir=str(tmp_path / "lin"),
                n_hosts=3, heartbeat_deadline_s=0.3,
                on_straggler="raise", wedge_host=2, wedge_after=1,
                wedge_spec="seed=29,scale=0,multihost.heartbeat@p2=999",
                beat_interval_s=0.05)
    finally:
        faults.install(None)
        obs.enable_metrics(False)


def test_run_job_multihost_elastic_routing(tmp_path):
    """run_job_multihost routes to the elastic layer when asked, and
    refuses half-configured elastic flags."""
    from heatmap_tpu.parallel.multihost import run_job_multihost

    with pytest.raises(ValueError, match="elastic_dir"):
        run_job_multihost(SyntheticSource(n=10),
                          on_straggler="reassign")
    with pytest.raises(ValueError, match="reassign"):
        run_job_multihost(SyntheticSource(n=10),
                          elastic_dir=str(tmp_path / "lin"))
    out = run_job_multihost(
        SyntheticSource(n=600, seed=2),
        LevelArraysSink(str(tmp_path / "arr")), CFG, batch_size=200,
        on_straggler="reassign", elastic_dir=str(tmp_path / "lin"),
        elastic_hosts=2)
    assert out["egress"] == "levels-elastic"
    assert out["rows"] > 0
