"""Range-query engine tests (heatmap_tpu/analytics/ + GET /query).

The anchors from docs/analytics.md, in test form:

- every ``/query?op=sum`` answer is EXACTLY equal to the brute-force
  sum over the served exact level rows — weighted, retraction,
  pad-bucketed, and Morton-sharded stores, before AND after
  compaction (integer grids make the SAT exact in f64, not approx);
- ``op=topk`` matches the exhaustive argsort oracle including the
  (value desc, row asc, col asc) tie-break; ``op=quantile`` matches
  the sorted-values oracle for every q including 0 and 1;
- a store predating integral artifacts answers identically through
  the exact-rows fall-through (only the ``path`` marker differs);
- query bytes live in their own ``"q-`` ETag namespace, the fleet
  router colocates every op over the same (layer, z, bbox), torn
  integrals are quarantined as ``torn_integral``, and brownout rung 1
  answers ``op=sum`` from the synopsis grid under a stamped bound.
"""

from __future__ import annotations

import json
import math
import os
import shutil

import numpy as np
import pytest

from heatmap_tpu import delta
from heatmap_tpu.analytics import (HARD_MAX_Z, SCHEMA, IntegralPair,
                                   build_pair, grid_from_sat, integral2d_jax,
                                   integral2d_np, integral_path,
                                   load_integrals, merge_shard_sats,
                                   parse_bbox, quantile, range_sum,
                                   top_k_hotspots, validate_op,
                                   verify_integral, write_integrals)
from heatmap_tpu.analytics.query import level_cells
from heatmap_tpu.delta.compute import ColumnsSource, read_columns
from heatmap_tpu.io import open_sink, open_source
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.pipeline import BatchJobConfig, run_job
from heatmap_tpu.serve import ServeApp, TileCache, TileStore
from heatmap_tpu.serve import degrade
from heatmap_tpu.synopsis.transform import grid_from_rows_np
from heatmap_tpu.tilemath.morton import morton_decode_np


def _sparse_grid(rng, zoom, nnz, vmax=50):
    """Random sparse integer level rows + the dense grid they imply."""
    n = 1 << zoom
    flat = rng.choice(n * n, size=nnz, replace=False)
    rows, cols = flat // n, flat % n
    values = rng.integers(1, vmax, size=nnz).astype(np.float64)
    return rows, cols, values, grid_from_rows_np(rows, cols, values, n)


def _pair(rows, cols, values, zoom):
    sat, cnt = build_pair(rows, cols, values, zoom)
    return IntegralPair("all", "alltime", zoom, sat, cnt)


def _rects(rng, n, count):
    """Random inclusive rects inside an (n, n) grid, plus the full grid
    and a single cell."""
    out = [(0, 0, n - 1, n - 1), (n // 2, n // 2, n // 2, n // 2)]
    for _ in range(count):
        r0, r1 = sorted(int(v) for v in rng.integers(0, n, 2))
        c0, c1 = sorted(int(v) for v in rng.integers(0, n, 2))
        out.append((r0, c0, r1, c1))
    return out


def _brute(grid, rect):
    r0, c0, r1, c1 = rect
    return float(grid[r0:r1 + 1, c0:c1 + 1].sum())


def _level_grid(layer, zoom):
    """Dense grid of a served level — the brute-force ground truth
    decoded straight from the stored Morton rows."""
    level = layer.levels[zoom]
    rows, cols = morton_decode_np(level.codes)
    return grid_from_rows_np(rows.astype(np.int64), cols.astype(np.int64),
                             level.values, 1 << zoom)


def _level_cols(rng, zoom, pairs, nnz=80):
    """A finalized-shape level dict with one row block per pair."""
    rs, cs, vs, us, ts = [], [], [], [], []
    for user, span in pairs:
        rows, cols, values, _ = _sparse_grid(rng, zoom, nnz)
        rs.append(rows)
        cs.append(cols)
        vs.append(values)
        us += [user] * nnz
        ts += [span] * nnz
    return {"zoom": zoom, "coarse_zoom": max(zoom - 2, 0),
            "row": np.concatenate(rs), "col": np.concatenate(cs),
            "value": np.concatenate(vs),
            "user": np.asarray(us), "timespan": np.asarray(ts)}


class TestParsing:
    def test_validate_op(self):
        for op in ("sum", "topk", "quantile"):
            assert validate_op(op) == op
        with pytest.raises(ValueError) as e:
            validate_op("avg")
        msg = str(e.value)
        assert "\n" not in msg
        assert "sum" in msg and "topk" in msg and "quantile" in msg

    def test_parse_bbox_round_trip(self):
        # x0,y0,x1,y1 -> (r0, c0, r1, c1): x is the column axis.
        assert parse_bbox("1,2,3,4", 3) == (2, 1, 4, 3)
        assert parse_bbox("0,0,7,7", 3) == (0, 0, 7, 7)

    def test_parse_bbox_one_line_errors(self):
        for text, zoom in (("1,2,3", 3), ("a,b,c,d", 3), ("0,0,8,8", 3),
                           ("3,0,1,0", 3), ("-1,0,1,1", 3)):
            with pytest.raises(ValueError) as e:
                parse_bbox(text, zoom)
            assert "\n" not in str(e.value)


class TestIntegralCore:
    def test_sat_matches_brute_force_and_inverts(self):
        rng = np.random.default_rng(7)
        _, _, _, grid = _sparse_grid(rng, 5, 120)
        sat = integral2d_np(grid)
        # The defining identity, checked exhaustively at one corner.
        assert np.array_equal(sat, np.cumsum(np.cumsum(grid, 0), 1))
        assert np.array_equal(grid_from_sat(sat), grid)  # exact, not approx
        with pytest.raises(ValueError, match="2D"):
            integral2d_np(np.zeros(8))

    def test_jax_twin_matches_numpy(self):
        rng = np.random.default_rng(11)
        _, _, _, grid = _sparse_grid(rng, 4, 60)
        np.testing.assert_array_equal(np.asarray(integral2d_jax(grid)),
                                      integral2d_np(grid))

    def test_merge_shard_sats_is_the_boundary_fixup(self):
        """Linearity: the SAT of a Morton-sharded level equals the
        elementwise sum of per-shard SATs — each shard scans only its
        own Z-range, the sum applies the cross-shard offsets."""
        rng = np.random.default_rng(13)
        rows, cols, values, grid = _sparse_grid(rng, 5, 200)
        order = np.argsort(rows * 32 + cols)  # any disjoint 3-way split
        parts = []
        for chunk in np.array_split(order, 3):
            parts.append(integral2d_np(grid_from_rows_np(
                rows[chunk], cols[chunk], values[chunk], 32)))
        np.testing.assert_array_equal(merge_shard_sats(parts),
                                      integral2d_np(grid))
        with pytest.raises(ValueError, match="at least one"):
            merge_shard_sats([])
        with pytest.raises(ValueError, match="shapes differ"):
            merge_shard_sats([np.zeros((4, 4)), np.zeros((8, 8))])

    def test_build_pair_hard_max_z_refusal(self):
        with pytest.raises(ValueError, match=str(HARD_MAX_Z)):
            build_pair([0], [0], [1.0], HARD_MAX_Z + 1)

    def test_range_sum_and_count_property_sweep(self):
        rng = np.random.default_rng(21)
        rows, cols, values, grid = _sparse_grid(rng, 6, 400)
        pair = _pair(rows, cols, values, 6)
        for rect in _rects(rng, 64, 200):
            assert range_sum(pair, rect) == _brute(grid, rect)
            r0, c0, r1, c1 = rect
            assert pair.cell_count(*rect) == int(
                (grid[r0:r1 + 1, c0:c1 + 1] != 0.0).sum())

    def test_topk_matches_argsort_oracle_with_ties(self):
        """Small value alphabet forces heavy ties — the descent's
        (value desc, row asc, col asc) tie-break must match the
        lexsort oracle cell for cell."""
        rng = np.random.default_rng(23)
        rows, cols, values, grid = _sparse_grid(rng, 5, 250, vmax=4)
        pair = _pair(rows, cols, values, 5)
        for rect in _rects(rng, 32, 40):
            got = top_k_hotspots(pair, rect, 12)
            rr, cc, vv = level_cells_from_grid(grid, rect)
            order = np.lexsort((cc, rr, -vv))[:12]
            want = [(int(rr[i]), int(cc[i]), float(vv[i])) for i in order]
            assert got == want

    def test_quantile_matches_sorted_oracle(self):
        rng = np.random.default_rng(29)
        rows, cols, values, grid = _sparse_grid(rng, 5, 180, vmax=6)
        pair = _pair(rows, cols, values, 5)
        qs = [0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0]
        for rect in _rects(rng, 32, 25):
            _, _, vv = level_cells_from_grid(grid, rect)
            srt = np.sort(vv)
            for q in qs:
                got = quantile(pair, rect, q)
                if len(srt) == 0:
                    assert got is None
                else:
                    want = float(srt[max(0, math.ceil(q * len(srt)) - 1)])
                    assert got == want
        with pytest.raises(ValueError, match="\\[0, 1\\]"):
            quantile(pair, (0, 0, 3, 3), 1.5)

    def test_dense_window_paths_match_descents(self):
        # Unless a rect is huge AND sparse (area > sparsity * nnz),
        # topk and quantile sort one vectorized SAT-window
        # reconstruction instead of descending; force each path with
        # the sparsity kwarg and pin both to each other and to the
        # oracles on every rect, including edge-touching ones (the
        # window's zero padding).
        rng = np.random.default_rng(37)
        rows, cols, values, grid = _sparse_grid(rng, 5, 400, vmax=9)
        pair = _pair(rows, cols, values, 5)
        rects = _rects(rng, 32, 20) + [(0, 0, 31, 31), (0, 5, 0, 5)]
        for rect in rects:
            rr, cc, vv = level_cells_from_grid(grid, rect)
            srt = np.sort(vv)
            for q in (0.0, 0.3, 0.5, 0.8, 1.0):
                dense = quantile(pair, rect, q, sparsity=10**9)
                descent = quantile(pair, rect, q, sparsity=0)
                if len(srt) == 0:
                    assert dense is None and descent is None
                else:
                    want = float(srt[max(0, math.ceil(q * len(srt)) - 1)])
                    assert dense == want == descent
            order = np.lexsort((cc, rr, -vv))[:7]
            want_top = [(int(rr[i]), int(cc[i]), float(vv[i]))
                        for i in order]
            assert top_k_hotspots(pair, rect, 7, sparsity=10**9) == want_top
            assert top_k_hotspots(pair, rect, 7, sparsity=0) == want_top


def level_cells_from_grid(grid, rect):
    """Occupied cells of a dense grid inside the rect (oracle side)."""
    r0, c0, r1, c1 = rect
    sub = grid[r0:r1 + 1, c0:c1 + 1]
    rr, cc = np.nonzero(sub)
    return rr + r0, cc + c0, sub[rr, cc]


class TestArtifacts:
    def test_write_load_round_trip_and_verify(self, tmp_path):
        rng = np.random.default_rng(31)
        cols = _level_cols(rng, 5, [("all", "alltime"), ("u1", "year")])
        out = write_integrals(str(tmp_path), levels={5: cols})
        assert set(out) == {5} and out[5]["pairs"] == 2
        path = integral_path(str(tmp_path), 5)
        assert os.path.exists(path) and verify_integral(path) is None
        loaded = load_integrals(str(tmp_path))
        assert sorted((p.user, p.timespan) for p in loaded[5]) == [
            ("all", "alltime"), ("u1", "year")]
        for p in loaded[5]:
            sel = (cols["user"] == p.user) & (cols["timespan"] == p.timespan)
            grid = grid_from_rows_np(cols["row"][sel], cols["col"][sel],
                                     cols["value"][sel], 32)
            np.testing.assert_array_equal(p.grid(), grid)

    def test_max_z_gates_which_levels_get_integrals(self, tmp_path):
        rng = np.random.default_rng(32)
        levels = {5: _level_cols(rng, 5, [("all", "alltime")]),
                  7: _level_cols(rng, 7, [("all", "alltime")])}
        out = write_integrals(str(tmp_path), levels=levels, max_z=6)
        assert set(out) == {5}
        assert not os.path.exists(integral_path(str(tmp_path), 7))

    def test_verify_flags_torn_and_wrong_schema(self, tmp_path):
        torn = tmp_path / "integral-z05.npz"
        torn.write_bytes(b"\x00garbage not a zip")
        assert verify_integral(str(torn)) is not None
        wrong = tmp_path / "integral-z06.npz"
        np.savez(wrong, schema=np.asarray("other.v9"))
        detail = verify_integral(str(wrong))
        assert detail is not None and SCHEMA in detail
        assert load_integrals(str(tmp_path)) == {}  # both skipped

    def test_with_extras_is_exact(self):
        rng = np.random.default_rng(33)
        rows, cols, values, grid = _sparse_grid(rng, 4, 30)
        pair = _pair(rows, cols, values, 4)
        folded = pair.with_extras([2, 2, 7], [3, 3, 1], [1.0, 2.0, 5.0])
        truth = grid.copy()
        np.add.at(truth, ([2, 2, 7], [3, 3, 1]), [1.0, 2.0, 5.0])
        np.testing.assert_array_equal(folded.grid(), truth)
        assert folded.cell_count(0, 0, 15, 15) == int((truth != 0).sum())


@pytest.fixture(scope="module")
def int_store(tmp_path_factory):
    """One real batch job egressed through the arrays-integral sink:
    exact levels at zooms 7-10 plus integral artifacts for 7/8/9."""
    root = tmp_path_factory.mktemp("int_store")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                            result_delta=2)
    with open_sink(f"arrays-integral:{root}/levels") as sink:
        run_job(open_source("synthetic:3000:7"), sink, config)
    return f"{root}/levels"


def _query(app, z, rect, op="sum", layer="default", extra=""):
    r0, c0, r1, c1 = rect
    return app.handle(
        "GET", f"/query?layer={layer}&z={z}&bbox={c0},{r0},{c1},{r1}"
               f"&op={op}{extra}")


class TestServing:
    def test_store_indexes_integrals_below_max_z(self, int_store):
        store = TileStore(f"arrays:{int_store}")
        layer = store.layer("default")
        assert sorted(layer.integrals) == [7, 8, 9]
        stats = store.stats()["layers"]["default"]
        assert stats["integral_zooms"] == [7, 8, 9]

    def test_query_sum_is_pinned_to_brute_force(self, int_store):
        store = TileStore(f"arrays:{int_store}")
        app = ServeApp(store)
        layer = store.layer("default")
        for z in (7, 8, 9):
            grid = _level_grid(layer, z)
            rng = np.random.default_rng(z)
            for rect in _rects(rng, 1 << z, 15):
                res = _query(app, z, rect)
                assert res[0] == 200
                doc = json.loads(res[2])
                assert doc["path"] == "integral"
                assert doc["sum"] == _brute(grid, rect)  # EXACT equality
                r0, c0, r1, c1 = rect
                assert doc["cells"] == int(
                    (grid[r0:r1 + 1, c0:c1 + 1] != 0.0).sum())
                assert doc["bbox"] == [c0, r0, c1, r1]

    def test_fall_through_answers_are_identical(self, int_store, tmp_path):
        """A store predating integral artifacts serves the same
        answers through the exact rows — only the path marker moves."""
        stripped = tmp_path / "levels"
        shutil.copytree(int_store, stripped)
        for name in os.listdir(stripped):
            if name.startswith("integral-"):
                os.remove(stripped / name)
        fast = ServeApp(TileStore(f"arrays:{int_store}"))
        slow = ServeApp(TileStore(f"arrays:{stripped}"))
        rng = np.random.default_rng(41)
        for rect in _rects(rng, 1 << 7, 8):
            for op, extra in (("sum", ""), ("topk", "&k=7"),
                              ("quantile", "&q=0.35")):
                a = json.loads(_query(fast, 7, rect, op, extra=extra)[2])
                b = json.loads(_query(slow, 7, rect, op, extra=extra)[2])
                assert a.pop("path") == "integral"
                assert b.pop("path") == "fallback"
                assert a == b

    def test_etag_namespace_304_and_invalidation(self, int_store):
        store = TileStore(f"arrays:{int_store}")
        app = ServeApp(store)
        rect = (0, 0, 127, 127)
        res = _query(app, 7, rect)
        assert res[0] == 200 and res[3].startswith('"q-')
        assert res[5] == "miss"
        again = _query(app, 7, rect)
        assert again[5] == "hit" and again[3] == res[3]
        not_mod = app.handle(
            "GET", "/query?layer=default&z=7&bbox=0,0,127,127&op=sum",
            if_none_match=res[3])
        assert not_mod[0] == 304 and not_mod[2] == b""
        # Tile ETags and query ETags never cross-revalidate.
        layer = store.layer("default")
        level = layer.levels[7]
        code = int(level.codes[int(np.argmax(level.values))])
        rr, cc = morton_decode_np(np.asarray([code], np.int64))
        row, col = int(rr[0]), int(cc[0])
        x, y = col >> 2, row >> 2
        tile = app.handle("GET", f"/tiles/default/5/{x}/{y}.json")
        assert tile[0] == 200 and not tile[3].startswith('"q-')
        assert app.handle("GET", f"/tiles/default/5/{x}/{y}.json",
                          if_none_match=res[3])[0] == 200
        assert app.handle(
            "GET", "/query?layer=default&z=7&bbox=0,0,127,127&op=sum",
            if_none_match=tile[3])[0] == 200
        # A reload bumps the generation: cached query bytes retire.
        store.reload()
        fresh = _query(app, 7, rect)
        assert fresh[0] == 200 and fresh[5] == "miss"

    def test_malformed_params_are_typed_400s(self, int_store):
        app = ServeApp(TileStore(f"arrays:{int_store}"))
        bad = [
            "/query?layer=default&bbox=0,0,1,1",            # missing z
            "/query?layer=default&z=abc&bbox=0,0,1,1",      # bad z
            "/query?layer=default&z=99&bbox=0,0,1,1",       # z out of range
            "/query?layer=default&z=7",                      # missing bbox
            "/query?layer=default&z=7&bbox=1,2,3",           # 3 parts
            "/query?layer=default&z=7&bbox=a,b,c,d",         # non-integer
            "/query?layer=default&z=7&bbox=0,0,999,0",       # off-grid
            "/query?layer=default&z=7&bbox=0,0,1,1&op=avg",  # bad op
            "/query?layer=default&z=7&bbox=0,0,1,1&op=topk&k=0",
            "/query?layer=default&z=7&bbox=0,0,1,1&op=topk&k=x",
            "/query?layer=default&z=7&bbox=0,0,1,1&op=quantile&q=2",
            "/query?layer=default&z=7&bbox=0,0,1,1&op=quantile&q=x",
        ]
        for path in bad:
            status, _, body, _, route, _ = app.handle("GET", path)
            assert (status, route) == (400, "query"), path
            doc = json.loads(body)
            assert doc["error"] == "bad query" and doc["detail"], path

    def test_unknown_layer_and_missing_zoom_404(self, int_store):
        app = ServeApp(TileStore(f"arrays:{int_store}"))
        res = app.handle("GET", "/query?layer=nobody&z=7&bbox=0,0,1,1")
        assert res[0] == 404
        assert "layers" in json.loads(res[2])
        res = app.handle("GET", "/query?layer=default&z=3&bbox=0,0,1,1")
        assert res[0] == 404
        doc = json.loads(res[2])
        assert doc["detail_zooms"] == [7, 8, 9, 10]

    def test_router_colocates_every_op_on_one_backend(self):
        from heatmap_tpu.serve.router import route_key

        base = "/query?layer=default&z=7&bbox=0,0,31,31"
        assert route_key(base + "&op=sum") == route_key(base + "&op=topk&k=5")
        assert route_key(base + "&op=quantile&q=0.9") == route_key(base)
        assert route_key(base) != route_key(
            "/query?layer=default&z=7&bbox=0,0,15,15")
        assert route_key(base) != route_key(
            "/query?layer=other&z=7&bbox=0,0,31,31")


class TestBrownout:
    @pytest.fixture()
    def syn_int_store(self, tmp_path):
        """Small store carrying BOTH synopsis and integral artifacts."""
        config = BatchJobConfig(detail_zoom=8, min_detail_zoom=4,
                                result_delta=2)
        sink = LevelArraysSink(str(tmp_path / "levels"), synopses=True,
                               integrals=True)
        run_job(open_source("synthetic:800:5"), sink, config)
        return TileStore(f"arrays:{tmp_path}/levels")

    @staticmethod
    def _controller(**kw):
        kw.setdefault("burn_source", lambda: {"pinned": 0.75})
        kw.setdefault("clock", lambda: 0.0)
        return degrade.BrownoutController(**kw)

    def test_rung1_answers_sum_from_synopsis_with_bound(self,
                                                       syn_int_store):
        store = syn_int_store
        app = ServeApp(store, TileCache(), degrade=self._controller())
        layer = store.layer("default")
        z = sorted(set(layer.synopses) & set(layer.integrals))[0]
        grid = _level_grid(layer, z)
        rect = (0, 0, (1 << z) - 1, (1 << z) - 1)
        exact = json.loads(_query(app, z, rect)[2])
        assert exact["path"] == "integral"
        app.degrade.rung = 1
        res = _query(app, z, rect)
        assert res[0] == 200
        doc = json.loads(res[2])
        assert doc["path"] == "synopsis"
        area = (1 << z) * (1 << z)
        bound = float(layer.synopses[z].max_err) * area
        assert doc["max_err"] == bound
        assert res.headers["X-Heatmap-Query-Error"] == \
            f"max_err={bound:.6g}"
        # The bound is honest: the synopsis answer is within it.
        assert abs(doc["sum"] - _brute(grid, rect)) <= bound + 1e-9
        # topk/quantile never degrade — exact beats loosely bounded.
        topk = json.loads(_query(app, z, rect, "topk", extra="&k=3")[2])
        assert topk["path"] == "integral"
        assert getattr(_query(app, z, rect, "topk", extra="&k=3"),
                       "headers", None) is None
        # Walking back to rung 0 restores the exact bytes.
        app.degrade.rung = 0
        back = json.loads(_query(app, z, rect)[2])
        assert back == exact


BASE_SPEC = "synthetic:1500:7"
DELTA_SPEC = "synthetic:200:11"
RETRACT_ROWS = 300


class _Chain:
    def __init__(self, *sources):
        self.sources = sources

    def batches(self, batch_size: int = 1 << 20):
        for src in self.sources:
            yield from src.batches(batch_size)


@pytest.fixture(scope="module")
def delta_store(tmp_path_factory):
    """Delta-store lifecycle for /query: base + insert delta +
    retraction, snapshotted before compaction (no base yet — /query
    falls through to exact rows), after compaction (integrals published
    with the new base), and after one more live delta on top of the
    compacted base (integrals answer via with_extras folding)."""
    root = str(tmp_path_factory.mktemp("q_delta") / "store")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                            result_delta=2)
    delta.apply_batch(root, open_source(BASE_SPEC), config)
    delta.apply_batch(root, open_source(DELTA_SPEC), config)
    base_cols = read_columns(open_source(BASE_SPEC))
    retract = ColumnsSource({k: v[:RETRACT_ROWS]
                             for k, v in base_cols.items()})
    delta.apply_batch(root, retract, config, sign=-1)
    return root, config


class TestDeltaStores:
    Z = 7

    def _answers(self, root):
        app = ServeApp(TileStore(f"delta:{root}"))
        layer = app.store.layer("default")
        grid = _level_grid(layer, self.Z)
        rng = np.random.default_rng(53)
        out = []
        for rect in _rects(rng, 1 << self.Z, 10):
            docs = {}
            for op, extra in (("sum", ""), ("topk", "&k=5"),
                              ("quantile", "&q=0.5")):
                res = _query(app, self.Z, rect, op, extra=extra)
                assert res[0] == 200
                docs[op] = json.loads(res[2])
            assert docs["sum"]["sum"] == _brute(grid, rect)  # the pin
            out.append(docs)
        return out

    def test_retraction_store_before_and_after_compaction(
            self, delta_store):
        root, _ = delta_store
        before = self._answers(root)
        assert all(d["sum"]["path"] == "fallback" for d in before)

        summary = delta.compact(root, retention=2)
        assert summary["status"] == "ok"
        assert os.path.exists(integral_path(
            os.path.join(root, summary["base"]), self.Z))
        after = self._answers(root)
        assert all(d["sum"]["path"] == "integral" for d in after)
        # Identical answers through either path, marker aside.
        for b, a in zip(before, after):
            for op in ("sum", "topk", "quantile"):
                bb, aa = dict(b[op]), dict(a[op])
                bb.pop("path"), aa.pop("path")
                assert bb == aa

    def test_live_delta_on_compacted_base_folds_into_integrals(
            self, delta_store):
        root, config = delta_store
        delta.compact(root, retention=2)
        delta.apply_batch(root, open_source("synthetic:150:13"), config)
        # Integrals describe the base; the live delta's rows fold in
        # through with_extras — answers stay pinned to brute force
        # over the OVERLAY (base ⊕ delta) levels.
        for d in self._answers(root):
            assert d["sum"]["path"] == "integral"


class _RowsSource:
    def __init__(self, rows):
        self.rows = rows

    def batches(self, batch_size=1 << 20):
        for i in range(0, len(self.rows), batch_size):
            chunk = self.rows[i:i + batch_size]
            out = {k: [r[k] for r in chunk]
                   for k in ("latitude", "longitude", "user_id",
                             "timestamp", "source")}
            if any("value" in r for r in chunk):
                out["value"] = [float(r.get("value", 1.0)) for r in chunk]
            yield out


def _rows(n, seed, value_max=None):
    rng = np.random.default_rng(seed)
    users = ("alice", "bob", "carol")
    rows = []
    for _ in range(n):
        r = {"latitude": float(rng.uniform(40.0, 55.0)),
             "longitude": float(rng.uniform(-5.0, 15.0)),
             "user_id": users[int(rng.integers(0, len(users)))],
             "timestamp": 1_500_000_000_000 + int(rng.integers(0, 10**9)),
             "source": "gps"}
        if value_max is not None:
            r["value"] = int(rng.integers(1, value_max + 1))
        rows.append(r)
    return rows


class TestStoreShapes:
    """The exact-sum pin across every pipeline shape the ISSUE names:
    integer-weighted jobs, pad-bucketed compiles, and Morton-range
    sharded meshes all publish integrals whose answers equal the
    brute-force sum over their own exact rows."""

    CASES = {
        "weighted": dict(weighted=True),
        "pad_bucketed": dict(pad_bucketing="pow2", pad_bucket_min=64),
        "morton_sharded": dict(data_parallel=True,
                               spatial_partition="morton"),
    }

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_integrals_match_levels(self, case, tmp_path):
        kw = dict(self.CASES[case])
        config = BatchJobConfig(detail_zoom=8, min_detail_zoom=5,
                                result_delta=2, **kw)
        value_max = 5 if kw.get("weighted") else None
        out = str(tmp_path / "levels")
        run_job(_RowsSource(_rows(400, seed=61, value_max=value_max)),
                LevelArraysSink(out, integrals=True), config)
        ints = load_integrals(out)
        levels = LevelArraysSink.load(out)
        assert ints, f"{case}: no integral artifacts written"
        rng = np.random.default_rng(67)
        for zoom, pairs in ints.items():
            cols = levels[zoom]
            users = np.asarray(cols["user"], str)
            tss = np.asarray(cols["timespan"], str)
            for ip in pairs:
                sel = (users == ip.user) & (tss == ip.timespan)
                grid = grid_from_rows_np(
                    np.asarray(cols["row"], np.int64)[sel],
                    np.asarray(cols["col"], np.int64)[sel],
                    np.asarray(cols["value"], np.float64)[sel],
                    1 << zoom)
                np.testing.assert_array_equal(ip.grid(), grid)
                for rect in _rects(rng, 1 << zoom, 10):
                    assert range_sum(ip, rect) == _brute(grid, rect)
                top = top_k_hotspots(ip, (0, 0, ip.n - 1, ip.n - 1), 5)
                for r, c, v in top:
                    assert grid[r, c] == v


class TestRecovery:
    def test_sweep_quarantines_torn_integrals_in_current_base(
            self, tmp_path):
        from heatmap_tpu.delta.recover import sweep

        root = tmp_path / "store"
        bdir = root / "base-000001"
        bdir.mkdir(parents=True)
        (root / "CURRENT").write_text(json.dumps(
            {"schema": "heatmap-tpu.delta_store.v1", "base": "base-000001",
             "applied_through": 1, "config": None}))
        cols = _level_cols(np.random.default_rng(71), 5,
                           [("all", "alltime")])
        write_integrals(str(bdir), levels={5: cols})
        (bdir / "integral-z06.npz").write_bytes(b"torn mid-write")
        (bdir / "integral-z07.npz.tmp").write_bytes(b"crashed staging")

        result = sweep(str(root))
        got = {(i["reason"], os.path.basename(i["path"]))
               for i in result["quarantined"]}
        assert got == {("torn_integral", "integral-z06.npz"),
                       ("orphan_tmp", "integral-z07.npz.tmp")}
        assert all(i["kind"] == "integral" for i in result["quarantined"])
        # The healthy artifact survives in place and still verifies.
        good = integral_path(str(bdir), 5)
        assert os.path.exists(good) and verify_integral(good) is None
        # A reload of the swept store serves /query from what is left.
        assert sweep(str(root))["quarantined"] == []
