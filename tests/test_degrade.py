"""Brownout controller tests (serve/degrade.py): ladder hysteresis,
flap resistance, edge-triggered events, the rung policies on the serve
path, and the rung-0 byte-identity contract.

All tier-1: fake clocks and scripted burn schedules pin the ladder
deterministically — no sleeps, no wall-clock races. The one socket
test (Retry-After jitter) uses the in-process loopback server, same as
tests/test_fleet.py.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.obs import incident, slo
from heatmap_tpu.serve import ServeApp, TileCache, TileStore, serve_in_thread
from heatmap_tpu.serve import degrade
from heatmap_tpu.serve.router import BackendClient, RouterApp


@pytest.fixture(scope="module")
def syn_store(tmp_path_factory):
    """Batch job through the arrays-synopsis sink: exact levels at
    zooms 6-10, synopses for 7/8/9 — zoom-10 detail stays exact-only,
    which is what gives the stretch tests a synopsis-free source."""
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("degrade_store")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                            result_delta=2)
    with open_sink(f"arrays-synopsis:{root}/levels") as sink:
        run_job(open_source("synthetic:3000:7"), sink, config)
    return f"arrays:{root}/levels"


def _busy_tile(layer, src_zoom, tile_zoom):
    import numpy as np

    level = layer.levels[src_zoom]
    code = int(level.codes[int(np.argmax(level.values))])
    row = col = 0
    for bit in range(src_zoom):
        col |= ((code >> (2 * bit)) & 1) << bit
        row |= ((code >> (2 * bit + 1)) & 1) << bit
    shift = src_zoom - tile_zoom
    return col >> shift, row >> shift


def _controller(**kw):
    """Controller with an inert burn source (dead band: holds whatever
    rung a test pins) and no poll rate limit."""
    kw.setdefault("burn_source", lambda: {"pinned": 0.75})
    kw.setdefault("poll_interval_s", 0.0)
    return degrade.BrownoutController(**kw)


# -- ladder state machine ---------------------------------------------------


class TestLadder:
    def test_steps_up_through_every_rung_then_walks_down(self):
        c = degrade.BrownoutController(dwell_s=10.0, hold_s=30.0)
        hot = {"tiles-fast": 2.0}
        assert c.observe(hot, 0.0) == 0      # dwell not elapsed
        assert c.observe(hot, 9.9) == 0
        assert c.observe(hot, 10.0) == 1     # one dwell -> one rung
        assert c.observe(hot, 19.9) == 1     # window restarted at 10.0
        assert c.observe(hot, 20.0) == 2
        assert c.observe(hot, 30.0) == 3
        assert c.observe(hot, 300.0) == 3    # clamped at max_rung
        cool = {"tiles-fast": 0.1}
        assert c.observe(cool, 310.0) == 3   # hold not elapsed
        assert c.observe(cool, 340.0) == 2   # 30s low -> step down
        assert c.observe(cool, 370.0) == 1
        assert c.observe(cool, 400.0) == 0
        assert c.observe(cool, 1000.0) == 0  # clamped at full fidelity

    def test_dead_band_holds_the_rung_and_resets_both_windows(self):
        c = degrade.BrownoutController(dwell_s=10.0, hold_s=10.0)
        c.observe({"s": 2.0}, 0.0)
        c.observe({"s": 2.0}, 10.0)
        assert c.rung == 1
        # Burn falls into the dead band (0.5 < burn < 1.0): the rung
        # holds indefinitely and neither window accumulates.
        for t in range(11, 100):
            assert c.observe({"s": 0.75}, float(t)) == 1
        # A fresh excursion must re-earn the full dwell from scratch.
        assert c.observe({"s": 2.0}, 100.0) == 1
        assert c.observe({"s": 2.0}, 109.9) == 1
        assert c.observe({"s": 2.0}, 110.0) == 2

    def test_oscillation_at_threshold_steps_at_most_once_per_dwell(self):
        """The flap-resistance contract: a burn signal bouncing exactly
        on the up threshold (always >= up) moves the ladder at most
        once per dwell window — never once per sample."""
        c = degrade.BrownoutController(dwell_s=10.0, hold_s=10.0)
        rungs = []
        for t in range(26):  # 1 Hz samples, alternating 1.0 / 1.3
            burn = 1.0 if t % 2 == 0 else 1.3
            rungs.append(c.observe({"s": burn}, float(t)))
        assert rungs[-1] == 2  # floor(25 / dwell) steps, not 25
        steps = sum(1 for a, b in zip(rungs, rungs[1:]) if a != b)
        assert steps == 2
        # And bouncing ACROSS the threshold into the dead band resets
        # the dwell window every sample: the ladder never moves.
        c2 = degrade.BrownoutController(dwell_s=10.0, hold_s=10.0)
        for t in range(100):
            burn = 1.5 if t % 2 == 0 else 0.75
            assert c2.observe({"s": burn}, float(t)) == 0

    def test_transitions_emit_exactly_one_edge_event_each(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = obs.EventLog(path, run_id="ladder")
        obs.set_event_log(log)
        try:
            c = degrade.BrownoutController(dwell_s=1.0, hold_s=1.0)
            for t in range(4):  # 0 -> 1 -> 2 -> 3
                c.observe({"hot": 3.0}, float(t))
            for t in range(4, 8):  # 3 -> 2 -> 1 -> 0
                c.observe({"hot": 0.0}, float(t))
            # Holding at the bottom emits nothing more (edge-triggered).
            for t in range(8, 20):
                c.observe({"hot": 0.0}, float(t))
        finally:
            obs.set_event_log(None)
            log.close()
        steps = [r for r in obs.read_events(path)
                 if r["event"] == "degrade_step"]
        assert len(steps) == 6
        ups, downs = steps[:3], steps[3:]
        assert [s["rung"] for s in ups] == [1, 2, 3]
        assert all(s["direction"] == "up" and s["cause"] == "hot"
                   and s["burn"] == 3.0 for s in ups)
        assert [s["rung"] for s in downs] == [2, 1, 0]
        assert all(s["direction"] == "down" and s["cause"] == "recovery"
                   for s in downs)
        assert all(s["from_rung"] == s["rung"] + 1 for s in downs)
        for s in steps:
            obs.validate_event(s)

    def test_top_rung_fires_one_incident_bundle(self, tmp_path):
        mgr = incident.IncidentManager(str(tmp_path / "incidents"),
                                       run_id="brownout-test")
        incident.set_manager(mgr)
        try:
            c = degrade.BrownoutController(dwell_s=1.0, hold_s=1.0,
                                           max_rung=2)
            for t in range(3):
                c.observe({"hot": 9.0}, float(t))
            assert c.rung == 2
        finally:
            incident.set_manager(None)
        bundles = list((tmp_path / "incidents").iterdir())
        assert len(bundles) == 1
        manifest = json.loads(
            (bundles[0] / "manifest.json").read_text())
        assert manifest["trigger"] == "brownout"
        assert "stale_wide" in manifest["detail"]

    def test_ladder_spec_parsing_and_validation(self):
        assert degrade.parse_ladder_spec("") == {}
        got = degrade.parse_ladder_spec("up=2,down=0.25,ttl=8,shed=1,max=2")
        assert got == {"up_threshold": 2.0, "down_threshold": 0.25,
                       "ttl_stretch": 8.0, "shed_fraction": 1.0,
                       "max_rung": 2}
        with pytest.raises(ValueError, match="unknown ladder knob"):
            degrade.parse_ladder_spec("uq=2")
        with pytest.raises(ValueError, match="not a number"):
            degrade.parse_ladder_spec("up=fast")
        with pytest.raises(ValueError, match="out of range"):
            degrade.parse_ladder_spec("shed=1.5")
        with pytest.raises(ValueError, match="dead band"):
            degrade.BrownoutController(up_threshold=1.0, down_threshold=1.0)
        assert degrade.controller_from_flags(False, 1.0, 1.0, "") is None
        c = degrade.controller_from_flags(True, 2.0, 3.0, "max=1")
        assert (c.dwell_s, c.hold_s, c.max_rung) == (2.0, 3.0, 1)

    def test_shed_is_deterministic_and_seed_keyed(self):
        keys = [("default", str(z), str(x), str(y), "png")
                for z in (3, 4) for x in range(8) for y in range(8)]
        picks = {k for k in keys if degrade.shed_tile(0.5, k)}
        assert picks == {k for k in keys if degrade.shed_tile(0.5, k)}
        assert 0 < len(picks) < len(keys)  # a fraction, not all-or-none
        assert not any(degrade.shed_tile(0.0, k) for k in keys)
        assert all(degrade.shed_tile(1.0, k) for k in keys)
        # A different chaos-plane seed sheds a different subset.
        faults.install(faults.FaultPlane(seed=99))
        try:
            reseeded = {k for k in keys if degrade.shed_tile(0.5, k)}
        finally:
            faults.install(None)
        assert reseeded != picks


# -- rung policies on the serve path ---------------------------------------


class TestServePolicies:
    def test_rung0_is_byte_identical_to_no_controller(self, syn_store):
        store = TileStore(syn_store)
        plain = ServeApp(store, TileCache())
        armed = ServeApp(store, TileCache(), degrade=_controller())
        assert armed.degrade.rung == 0
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        dx, dy = _busy_tile(layer, 10, 8)
        paths = [f"/tiles/default/5/{x}/{y}.json",
                 f"/tiles/default/5/{x}/{y}.png",
                 f"/tiles/default/5/{x}/{y}.json?synopsis=1",
                 f"/tiles/default/8/{dx}/{dy}.json",
                 f"/tiles/default/8/{dx}/{dy}.json?synopsis=1"]
        for path in paths:
            a, b = plain.handle("GET", path), armed.handle("GET", path)
            assert a[0] == b[0] == 200
            assert a[2] == b[2], path   # body bytes
            assert a[3] == b[3], path   # ETag (incl. syn- namespace)
            assert getattr(a, "headers", None) == getattr(
                b, "headers", None), path
        assert armed.cache.ttl_scale == 1.0  # rung 0 never touches TTLs

    def test_rung1_forces_synopsis_with_stamped_error(self, syn_store):
        store = TileStore(syn_store)
        app = ServeApp(store, TileCache(), degrade=_controller())
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json"
        app.degrade.rung = 1
        res = app.handle("GET", path)  # no ?synopsis= opt-in needed
        assert res[0] == 200 and res[3].startswith('"syn-')
        assert res.headers["X-Heatmap-Synopsis"] == (
            f"max_err={layer.synopses[7].max_err:.6g}")
        # Rung 1 does NOT raise the ceiling: a zoom whose source has no
        # synopsis still answers exact, byte-identical to rung 0.
        dx, dy = _busy_tile(layer, 10, 8)
        deep = f"/tiles/default/8/{dx}/{dy}.json"
        exact = ServeApp(store, TileCache()).handle("GET", deep)
        forced = app.handle("GET", deep)
        assert tuple(forced)[:4] == tuple(exact)[:4]
        assert getattr(forced, "headers", None) is None

    def test_rung2_raises_ceiling_and_stretches_ttl(self, syn_store):
        store = TileStore(syn_store)
        ctl = _controller(ttl_stretch=6.0)
        app = ServeApp(store, TileCache(ttl_s=30.0), degrade=ctl)
        layer = store.layer("default")
        dx, dy = _busy_tile(layer, 10, 8)
        deep = f"/tiles/default/8/{dx}/{dy}.json"
        ctl.rung = 2
        res = app.handle("GET", deep)
        assert res[0] == 200 and res[3].startswith('"syn-')
        marker = res.headers["X-Heatmap-Synopsis"]
        assert "stretch=1" in marker
        assert f"max_err={layer.synopses[9].max_err:.6g}" in marker
        assert app.cache.ttl_scale == 6.0  # serve-stale widened
        # Walk back to rung 0: the next request restores the TTLs and
        # the exact bytes (fresh cache key — no synopsis aliasing).
        ctl.rung = 0
        back = app.handle("GET", deep)
        assert app.cache.ttl_scale == 1.0
        exact = ServeApp(store, TileCache()).handle("GET", deep)
        assert tuple(back)[:4] == tuple(exact)[:4]

    def test_ttl_scale_widens_expiry_without_restamping(self):
        now = [0.0]
        cache = TileCache(ttl_s=10.0, clock=lambda: now[0])
        renders = []

        def render():
            renders.append(now[0])
            return b"tile"

        cache.get_or_render("k", 1, render)
        now[0] = 15.0  # past ttl_s but within 4x
        cache.set_ttl_scale(4.0)
        assert cache.get_or_render("k", 1, render)[1] is True
        cache.set_ttl_scale(1.0)  # restore -> the entry is stale again
        assert cache.get_or_render("k", 1, render)[1] is False
        assert renders == [0.0, 15.0]
        with pytest.raises(ValueError):
            cache.set_ttl_scale(0.5)

    def test_rung3_sheds_deterministically_and_halves_admission(
            self, syn_store):
        store = TileStore(syn_store)
        ctl = _controller(shed_fraction=1.0)
        app = ServeApp(store, TileCache(), max_inflight=4, degrade=ctl)
        layer = store.layer("default")
        x, y = _busy_tile(layer, 7, 5)
        path = f"/tiles/default/5/{x}/{y}.json"
        ctl.rung = 3
        status, _, body, _, route, _ = app.handle("GET", path)
        assert (status, route) == (503, "tiles")
        assert json.loads(body)["cause"] == "brownout"
        health = json.loads(app.handle("GET", "/healthz")[2])
        assert health["status"] == "degraded"
        assert "brownout" in health["degraded"]
        # shed_fraction=0 at top rung: admitted, but the in-flight
        # bound is halved (4 -> 2), so two in flight already shed.
        ctl.shed_fraction = 0.0
        app._inflight = 2
        status, _, body, _, _, _ = app.handle("GET", path)
        assert status == 503 and json.loads(body)["cause"] == "shed"
        app._inflight = 0
        assert app.handle("GET", path)[0] == 200
        # Recovery clears the brownout cause on the next admit.
        ctl.rung = 0
        app.handle("GET", path)
        health = json.loads(app.handle("GET", "/healthz")[2])
        assert health["status"] == "ok"

    def test_healthz_surfaces_burn_fractions_and_ladder(self, syn_store):
        store = TileStore(syn_store)
        ctl = _controller()
        app = ServeApp(store, TileCache(), degrade=ctl)
        engine = slo.install_specs(
            ["tiles-fast:latency:target=0.99,threshold_ms=50",
             "tiles-up:error_rate:target=0.999"])
        try:
            obs.emit("http_request", route="tiles", status=200,
                     path="/t", ms=1.0, bytes=10)
            health = json.loads(app.handle("GET", "/healthz")[2])
            burns = health["slo_burn"]
            assert set(burns) == {"tiles-fast", "tiles-up"}
            assert all(isinstance(v, float) for v in burns.values())
            ladder = health["degrade"]
            assert ladder["rung"] == 0
            assert ladder["rung_name"] == "full"
            assert ladder["thresholds"] == {"up": 1.0, "down": 0.5}
            assert engine is not None
        finally:
            slo.set_engine(None)
        # Without a controller the block is simply absent.
        bare = json.loads(
            ServeApp(store, TileCache()).handle("GET", "/healthz")[2])
        assert "degrade" not in bare


# -- fleet-wide rung agreement ---------------------------------------------


class TestFleetAgreement:
    def test_router_adopts_hottest_backend_and_sheds_same_keys(self):
        b0 = BackendClient("b0", "127.0.0.1", 1)
        b1 = BackendClient("b1", "127.0.0.1", 2)
        router = RouterApp([b0, b1], probe_interval_s=1e9)
        assert router.fleet_degrade() is None  # no probe has seen one
        b0.degrade = {"rung": 1, "max_rung": 3, "shed_fraction": 0.5}
        b1.degrade = {"rung": 3, "max_rung": 3, "shed_fraction": 0.5}
        snap = router.fleet_degrade()
        assert snap["rung"] == 3  # max rung wins
        # Router-side shed agrees key-for-key with the backends' own
        # deterministic hash — no forward slot is spent on a key the
        # backend would shed anyway.
        shed = kept = 0
        for x in range(8):
            for y in range(8):
                path = f"/tiles/default/3/{x}/{y}.png"
                key = ("default", "3", str(x), str(y), "png")
                status, _, body, _, _, _ = router.handle("GET", path)
                if degrade.shed_tile(0.5, key):
                    shed += 1
                    assert status == 503
                    assert json.loads(body)["cause"] == "brownout"
                else:
                    kept += 1
                    # Survivors route normally (and 502 here, since no
                    # real backend listens — the point is no shed).
                    assert json.loads(body).get("cause") != "brownout"
        assert shed and kept
        health = router._health()
        assert health["degrade"]["rung"] == 3
        assert health["fleet"]["backends"]["b1"]["degrade_rung"] == 3
        assert health["fleet"]["backends"]["b0"]["degrade_rung"] == 1
        # Below the top rung the router forwards everything.
        b1.degrade = {"rung": 2, "max_rung": 3, "shed_fraction": 0.5}
        status, _, body, _, _, _ = router.handle(
            "GET", "/tiles/default/3/0/0.png")
        assert json.loads(body).get("cause") != "brownout"


# -- Retry-After jitter ----------------------------------------------------


class TestRetryAfterJitter:
    def test_jitter_spreads_across_paths_within_bounds(self, syn_store):
        assert degrade.retry_after_jitter(8.0, "/a", 0) == (
            degrade.retry_after_jitter(8.0, "/a", 0))  # deterministic
        app = ServeApp(TileStore(syn_store), TileCache(),
                       retry_after_s=8.0)
        server, base = serve_in_thread(app)
        try:
            app.handle("POST", "/drain")
            values = []
            for i in range(12):
                req = urllib.request.Request(
                    f"{base}/tiles/default/5/{i}/{i}.json")
                try:
                    urllib.request.urlopen(req, timeout=10)
                    pytest.fail("drained app must shed")
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    values.append(int(e.headers["Retry-After"]))
        finally:
            server.shutdown()
            server.server_close()
        # Full-jitter shape: [0.5, 1.5) x nominal, never the bare
        # nominal for every client (that is the thundering herd).
        assert all(4 <= v <= 12 for v in values)
        assert len(set(values)) > 1
