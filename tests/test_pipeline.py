"""Golden end-to-end tests: pipeline vs the pure-Python reference oracle."""

import json

import numpy as np
import pytest

from heatmap_tpu.pipeline import (
    BatchJobConfig,
    UserVocab,
    route_user,
    run_batch,
    timespan_label,
)
from heatmap_tpu.pipeline.groups import ALL_GROUP, EXCLUDED
import oracle


def _rows(n=500, seed=0, users=("alice", "bob", "rt-bus7", "rt-tram2", "xscout", "carol")):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        rows.append(
            {
                "latitude": float(rng.uniform(40.0, 55.0)),
                "longitude": float(rng.uniform(-5.0, 15.0)),
                "user_id": users[int(rng.integers(0, len(users)))],
                "timestamp": 1_500_000_000_000 + int(rng.integers(0, 10**9)),
                "source": "gps" if rng.uniform() > 0.1 else "background",
            }
        )
    return rows


# -- unit semantics --------------------------------------------------------


def test_route_user_rules():
    # Reference heatmap.py:64-70 semantics.
    assert route_user("alice") == "alice"
    assert route_user("rt-bus7") == "route"
    assert route_user("rt-") == "route"
    assert route_user("xscout") is None
    assert route_user("x") is None
    # 'rt' without dash is a normal user; 'Xupper' is NOT excluded.
    assert route_user("rtbus") == "rtbus"
    assert route_user("Xupper") == "Xupper"
    for uid in ("alice", "rt-bus7", "xscout", "x", "rtbus"):
        expected = oracle.user_groups(uid)
        got = ["all"] + ([route_user(uid)] if route_user(uid) else [])
        assert got == expected


def test_user_vocab():
    v = UserVocab()
    ids = v.group_ids(["alice", "rt-a", "rt-b", "xs", "alice"])
    assert ids[0] == ids[4] != ALL_GROUP
    assert ids[1] == ids[2]  # pooled under route
    assert ids[3] == EXCLUDED
    assert v.name_for(ALL_GROUP) == "all"


def test_timespan_labels():
    import datetime

    d = datetime.date(2017, 3, 7)
    assert timespan_label("alltime", d) == "alltime"
    assert timespan_label("year", d) == "2017"
    assert timespan_label("month", d) == "2017-03"
    assert timespan_label("day", d) == "2017-03-07"
    with pytest.raises(ValueError):
        timespan_label("week", d)


def test_label_ids_datetime64_column():
    import numpy as np

    from heatmap_tpu.pipeline.timespan import TimespanVocab

    vocab = TimespanVocab()
    col = np.asarray(
        ["2017-03-07T12:30", "2017-03-08T01:00", "2017-03-07T23:59"],
        dtype="datetime64[m]",
    )
    ids = vocab.label_ids("day", col)
    assert [vocab.label_for(i) for i in ids] == [
        "2017-03-07", "2017-03-08", "2017-03-07",
    ]
    # Matches the per-object path on equivalent epoch-ms ints.
    ms = col.astype("datetime64[ms]").astype(np.int64)
    vocab2 = TimespanVocab()
    ids2 = vocab2.label_ids("day", [int(m) for m in ms])
    assert [vocab2.label_for(i) for i in ids2] == [
        vocab.label_for(i) for i in ids
    ]
    # NaT == TS_MISSING: missing values raise like timestamp=None.
    nat = np.asarray(["2017-03-07", "NaT"], dtype="datetime64[s]")
    with pytest.raises(ValueError, match="timestamp"):
        TimespanVocab().label_ids("day", nat)


def test_json_blobs_match_dict_path_exactly():
    """The vectorized direct-to-JSON egress must produce byte-identical
    strings to json.dumps over the dict path, including float
    formatting and key order."""
    import json as _json

    import numpy as np

    from heatmap_tpu.pipeline import cascade as cascade_mod
    from heatmap_tpu.pipeline.batch import (
        BatchJobConfig, _cascade_codes, _slot_names, build_emissions,
    )
    from heatmap_tpu.pipeline.groups import UserVocab

    rng = np.random.default_rng(5)
    n = 30000
    lat = np.clip(rng.normal(47, 3, n), -85, 85)
    lon = np.clip(rng.normal(-122, 4, n), -179, 179)
    users = [f"user-{i}" for i in rng.integers(0, 9, n)]
    vocab = UserVocab()
    gids = vocab.group_ids(users)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=7)
    codes, valid = _cascade_codes(lat, lon, cfg.detail_zoom)
    e_codes, e_slots, e_valid, ts_vocab, n_groups, _ = build_emissions(
        codes, valid, gids, [None] * n, cfg
    )
    ccfg = cfg.cascade_config()
    lvl = cascade_mod.build_cascade(
        e_codes, e_slots, ccfg, n_slots=len(ts_vocab) * n_groups,
        valid=e_valid, capacity=len(e_codes),
    )
    fin = cascade_mod.finalize_level_arrays(
        cascade_mod.decode_levels(lvl, ccfg), ccfg,
        _slot_names(vocab, ts_vocab, n_groups),
    )
    want = {
        k: _json.dumps(v)
        for k, v in cascade_mod.blobs_from_level_arrays(fin).items()
    }
    got = cascade_mod.json_blobs_from_level_arrays(fin)
    assert got == want


def test_project_detail_codes_device_matches_host():
    """The on-device f64 projection+interleave must agree bit-for-bit
    with the host numpy path (same IEEE-double op order) at z21,
    including validity at poles/antimeridian edges."""
    import numpy as np

    from heatmap_tpu.pipeline.batch import project_detail_codes

    rng = np.random.default_rng(11)
    lat = np.concatenate([
        np.clip(rng.normal(40, 30, 20000), -89.9, 89.9),
        [90.0, -90.0, 85.06, -85.06, 0.0],
    ])
    lon = np.concatenate([
        rng.uniform(-180.0, 180.0, 20000), [180.0, -180.0, 0.0, 1e-9, -1e-9],
    ])
    dev_codes, dev_valid = project_detail_codes(lat, lon, 21)
    host_codes, host_valid = project_detail_codes(
        lat, lon, 21, prefer_device=False
    )
    np.testing.assert_array_equal(dev_valid, host_valid)
    np.testing.assert_array_equal(dev_codes[dev_valid],
                                  host_codes[host_valid])


# -- golden end-to-end -----------------------------------------------------


@pytest.mark.parametrize("detail_zoom,min_zoom", [(12, 5), (21, 16)])
def test_batch_matches_oracle_correct_mode(detail_zoom, min_zoom):
    rows = _rows(n=300, seed=detail_zoom)
    cfg = BatchJobConfig(detail_zoom=detail_zoom, min_detail_zoom=min_zoom)
    got = run_batch(rows, cfg)
    want = oracle.run_job(
        rows, detail_zoom=detail_zoom, min_detail_zoom=min_zoom, amplify_all=False
    )
    assert got.keys() == want.keys()
    for key in want:
        assert got[key] == want[key], key


@pytest.mark.parametrize("detail_zoom,min_zoom", [(12, 5), (21, 16)])
def test_batch_matches_oracle_amplified_compat(detail_zoom, min_zoom):
    # Reference-compat mode must reproduce the 'all'-amplification bug
    # (SURVEY.md §8.1) exactly as the faithful oracle simulates it.
    rows = _rows(n=300, seed=100 + detail_zoom)
    cfg = BatchJobConfig(
        detail_zoom=detail_zoom, min_detail_zoom=min_zoom, amplify_all=True
    )
    got = run_batch(rows, cfg)
    want = oracle.run_job(
        rows, detail_zoom=detail_zoom, min_detail_zoom=min_zoom, amplify_all=True
    )
    assert got.keys() == want.keys()
    for key in want:
        assert got[key] == pytest.approx(want[key]), key


def test_amplified_all_growth_pattern():
    # The survey's 4-point example: totals 4 -> 11 -> 25 over three levels
    # (SURVEY.md §8.1) when all points share one tile deep in the pyramid.
    rows = [
        {"latitude": 50.0001, "longitude": 8.0001, "user_id": u, "source": "gps"}
        for u in ("a", "b", "c", "xd")
    ]
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=7, amplify_all=True)
    blobs = run_batch(rows, cfg)
    all_totals = {}
    for key, hm in blobs.items():
        user, ts, coarse = key.split("|")
        if user == "all":
            zoom = int(coarse.split("_")[0]) + 5
            all_totals[zoom] = sum(hm.values())
    assert all_totals[10] == 4.0
    assert all_totals[9] == 2 * 4 + 3
    assert all_totals[8] == 2 * 11 + 3


def test_background_rows_dropped():
    rows = [
        {"latitude": 50.0, "longitude": 8.0, "user_id": "a", "source": "background"},
        {"latitude": 50.0, "longitude": 8.0, "user_id": "a", "source": "gps"},
    ]
    blobs = run_batch(rows, BatchJobConfig(detail_zoom=8, min_detail_zoom=6))
    total = sum(v for hm in blobs.items() if hm[0].startswith("all|") for v in hm[1].values())
    assert total == 2.0  # one point at two levels (z8, z7)


def test_empty_input():
    assert run_batch([]) == {}
    assert run_batch([{"latitude": 1, "longitude": 1, "user_id": "a",
                       "source": "background"}]) == {}


def test_as_json_output_shape():
    rows = _rows(n=50, seed=9)
    blobs = run_batch(rows, BatchJobConfig(detail_zoom=10, min_detail_zoom=8),
                      as_json=True)
    for key, payload in blobs.items():
        user, ts, coarse = key.split("|")
        assert ts == "alltime"
        decoded = json.loads(payload)
        assert all(isinstance(v, float) for v in decoded.values())
        # detail ids sit exactly result_delta zooms below the coarse id.
        cz = int(coarse.split("_")[0])
        for det in decoded:
            assert int(det.split("_")[0]) == cz + 5


def test_multi_timespan_emission():
    import datetime

    rows = [
        {
            "latitude": 50.0,
            "longitude": 8.0,
            "user_id": "a",
            "timestamp": datetime.datetime(2017, 3, 7, 12, 0),
            "source": "gps",
        },
        {
            "latitude": 50.0,
            "longitude": 8.0,
            "user_id": "a",
            "timestamp": datetime.datetime(2018, 4, 1, 12, 0),
            "source": "gps",
        },
    ]
    cfg = BatchJobConfig(
        detail_zoom=8, min_detail_zoom=6, timespans=("alltime", "year", "month")
    )
    blobs = run_batch(rows, cfg)
    labels = {k.split("|")[1] for k in blobs}
    assert labels == {"alltime", "2017", "2018", "2017-03", "2018-04"}
    # Quirk-compat mode: only the first timespan emits (SURVEY.md §8.2).
    cfg_q = BatchJobConfig(
        detail_zoom=8, min_detail_zoom=6,
        timespans=("alltime", "year"), first_timespan_only=True,
    )
    labels_q = {k.split("|")[1] for k in run_batch(rows, cfg_q)}
    assert labels_q == {"alltime"}


# -- bounded-memory chunked cascade ---------------------------------------


class _ColSource:
    """Columnar batches over row dicts, for run_job tests."""

    def __init__(self, rows):
        self.rows = rows

    def batches(self, batch_size):
        for i in range(0, len(self.rows), batch_size):
            chunk = self.rows[i : i + batch_size]
            out = {
                "latitude": [r["latitude"] for r in chunk],
                "longitude": [r["longitude"] for r in chunk],
                "user_id": [r["user_id"] for r in chunk],
                "timestamp": [r.get("timestamp") for r in chunk],
                "source": [r.get("source", "gps") for r in chunk],
            }
            if any("value" in r for r in chunk):
                out["value"] = [float(r.get("value", 1.0)) for r in chunk]
            yield out


@pytest.mark.slow
@pytest.mark.parametrize("amplify", [False, True])
def test_run_job_bounded_matches_unbounded(amplify):
    """max_points_in_flight chunks the cascade; linearity of the
    per-level (key, sum) reduction makes the result exactly equal."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(
        detail_zoom=12, min_detail_zoom=6,
        timespans=("alltime", "month"), amplify_all=amplify,
    )
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128)
    bounded = run_job(
        _ColSource(rows), config=cfg, batch_size=128,
        max_points_in_flight=150,
    )
    assert plain == bounded
    # The sequential (no-prefetch-thread) path is byte-identical too.
    sequential = run_job(
        _ColSource(rows), config=cfg, batch_size=128,
        max_points_in_flight=150, overlap_ingest=False,
    )
    assert plain == sequential


@pytest.mark.slow
@pytest.mark.parametrize("amplify", [False, True])
def test_bounded_spill_merge_matches_in_ram(tmp_path, amplify):
    """merge_spill_dir replaces the in-RAM cross-chunk table with disk
    runs + per-level egress merges — byte-identical blobs, spill files
    cleaned up afterwards (both amplify modes: streaming egress for
    False, materialized for True)."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(
        detail_zoom=12, min_detail_zoom=6,
        timespans=("alltime", "month"), amplify_all=amplify,
    )
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128,
                    max_points_in_flight=150)
    spill_root = tmp_path / "spill"
    spilled = run_job(
        _ColSource(rows), config=cfg, batch_size=128,
        max_points_in_flight=150, merge_spill_dir=str(spill_root),
    )
    assert spilled == plain
    # The temp run directory is removed; only the (empty) root remains.
    assert list(spill_root.iterdir()) == []


@pytest.mark.slow
def test_bounded_auto_spill_activates_and_matches(monkeypatch):
    """With AUTO_SPILL_ROWS lowered, a plain bounded run converts its
    in-RAM table to the spill merge mid-job — same blobs, spill
    tempdir cleaned up."""
    import glob

    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6)
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128,
                    max_points_in_flight=150)

    created = []
    real_spill = batch_mod._SpillMerge

    class _Spy(real_spill):
        def __init__(self, root, n_levels):
            super().__init__(root, n_levels)
            created.append(self.dir)

    monkeypatch.setattr(batch_mod, "_SpillMerge", _Spy)
    monkeypatch.setattr(batch_mod, "AUTO_SPILL_ROWS", 500)
    # Pin the auto-spill target to a real (disk-backed) dir so the
    # test is independent of whether the host's /tmp is tmpfs.
    monkeypatch.setattr(batch_mod, "_auto_spill_target",
                        lambda: batch_mod.AUTO_SPILL_DIR)
    monkeypatch.setattr(batch_mod, "AUTO_SPILL_DIR", "/tmp/auto-spill-test")
    auto = run_job(_ColSource(rows), config=cfg, batch_size=128,
                   max_points_in_flight=150)
    assert auto == plain
    assert len(created) == 1  # activation happened exactly once
    assert not glob.glob(created[0] + "*")  # tempdir removed


def test_auto_spill_target_refuses_tmpfs(tmp_path, monkeypatch):
    """A RAM-backed temp dir must disable auto-spill (tmpfs pages
    count against the same memory the spill exists to save)."""
    from heatmap_tpu.pipeline import batch as batch_mod

    mounts = tmp_path / "mounts"
    mounts.write_text(
        "/dev/root / ext4 rw 0 0\n"
        "tmpfs /ramtmp tmpfs rw 0 0\n"
        "/dev/sdb /ramtmp/disk ext4 rw 0 0\n"
    )
    real_fstype = batch_mod._mount_fstype
    fstype = lambda p: real_fstype(p, str(mounts))
    assert fstype("/ramtmp/x") == "tmpfs"
    assert fstype("/ramtmp/disk/x") == "ext4"  # longest prefix wins
    assert fstype("/var/spool") == "ext4"

    monkeypatch.setattr(batch_mod, "AUTO_SPILL_DIR", "/ramtmp/x")
    monkeypatch.setattr(
        batch_mod, "_mount_fstype", lambda p: fstype(p)
    )
    assert batch_mod._auto_spill_target() is None
    monkeypatch.setattr(batch_mod, "AUTO_SPILL_DIR", "/var/spool")
    assert batch_mod._auto_spill_target() == "/var/spool"


@pytest.mark.slow
def test_bounded_spill_cleans_up_on_ingest_failure(tmp_path):
    """A source that dies mid-run must not leave spill run files
    behind (they are tens of GB at the shapes spill targets)."""
    from heatmap_tpu.pipeline import run_job

    good = _rows(n=600, seed=3)

    class _Boom:
        def batches(self, batch_size):
            yield from _ColSource(good).batches(batch_size)
            raise RuntimeError("source died")

    root = tmp_path / "spill"
    with pytest.raises(RuntimeError, match="source died"):
        run_job(_Boom(), config=BatchJobConfig(detail_zoom=10,
                                               min_detail_zoom=8),
                batch_size=100, max_points_in_flight=200,
                merge_spill_dir=str(root))
    assert list(root.iterdir()) == []


def test_spill_requires_bounded_path():
    """merge_spill_dir on a single-shot route must refuse loudly, not
    silently run the in-RAM merge it exists to avoid."""
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import run_job
    from heatmap_tpu.pipeline.batch import run_job_fast

    with pytest.raises(ValueError, match="bounded path"):
        run_job(SyntheticSource(n=50), config=BatchJobConfig(),
                max_points_in_flight=0, merge_spill_dir="/tmp/nope")
    with pytest.raises(ValueError, match="bounded path"):
        run_job_fast(SyntheticSource(n=50), config=BatchJobConfig(),
                     max_points_in_flight=0, merge_spill_dir="/tmp/nope")


@pytest.mark.slow
def test_bounded_spill_weighted_and_columnar(tmp_path):
    """Weighted spill sums match the in-RAM merge exactly (chunk-order
    summation), and the streaming per-level egress composes with a
    columnar sink (per-level write_levels calls, summed stats)."""
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=1500, seed=21)
    for i, r in enumerate(rows):
        r["value"] = float((i % 7) + 1)  # integer-valued -> exact sums
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=7, weighted=True)
    plain = run_job(_ColSource(rows), config=cfg, batch_size=100,
                    max_points_in_flight=200)
    spilled = run_job(
        _ColSource(rows), config=cfg, batch_size=100,
        max_points_in_flight=200, merge_spill_dir=str(tmp_path / "s"),
    )
    assert spilled == plain

    stats_ram = run_job(_ColSource(rows),
                        LevelArraysSink(str(tmp_path / "ram")),
                        config=cfg, batch_size=100,
                        max_points_in_flight=200)
    stats_spill = run_job(
        _ColSource(rows), LevelArraysSink(str(tmp_path / "spl")),
        config=cfg, batch_size=100, max_points_in_flight=200,
        merge_spill_dir=str(tmp_path / "s2"),
    )
    assert stats_spill == stats_ram
    got = LevelArraysSink.load(str(tmp_path / "spl"))
    want = LevelArraysSink.load(str(tmp_path / "ram"))
    assert set(got) == set(want)
    for zoom in want:
        for col in ("row", "col", "value", "user", "timespan"):
            np.testing.assert_array_equal(got[zoom][col], want[zoom][col])


def test_auto_points_in_flight_decision():
    """Oversized sources auto-route to the bounded path; sources that
    fit (or can't be sized) keep the single-shot path."""
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline.batch import (
        _HOST_BYTES_PER_POINT, _auto_points_in_flight,
        _estimate_source_points,
    )

    small = SyntheticSource(n=1000)
    big = SyntheticSource(n=50_000_000)
    assert _estimate_source_points(small) == 1000
    # Fits the budget comfortably: unchanged single-shot.
    assert _auto_points_in_flight(small, ram_budget=1 << 30) is None
    # 50M points vs a 1 GiB budget (~6.7M points): bounded, chunk a
    # quarter of what fits.
    got = _auto_points_in_flight(big, ram_budget=1 << 30)
    fits = (1 << 30) // _HOST_BYTES_PER_POINT
    assert got == max(1 << 16, fits // 4)
    # Tiny-RAM host: the floor must stay under the budget's order of
    # magnitude, not balloon past it (75 MB budget -> ~490k fit; the
    # chunk must be <= what fits, not a fixed 1M).
    tiny = _auto_points_in_flight(big, ram_budget=75 << 20)
    assert tiny <= (75 << 20) // _HOST_BYTES_PER_POINT
    assert tiny >= 1 << 16
    # Unsizeable sources (no n, no path) can't auto-route.
    assert _auto_points_in_flight(object()) is None


def test_estimate_source_points_from_file_size(tmp_path):
    from heatmap_tpu.pipeline.batch import (
        _MIN_TEXT_ROW_BYTES, _estimate_source_points,
    )

    p = tmp_path / "pts.csv"
    p.write_text("lat,lon,user\n" * 1000)
    est = _estimate_source_points(str(p))
    assert est == p.stat().st_size // _MIN_TEXT_ROW_BYTES
    # Path-holding source objects estimate the same way.
    class _S:
        path = str(p)
    assert _estimate_source_points(_S()) == est


def test_run_job_auto_bounds_oversized_source(monkeypatch):
    """With host RAM faked tiny, the default run_job call takes the
    bounded path on its own — and stays exactly equal to single-shot
    (linearity), with 0 forcing single-shot back."""
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import run_job

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=7)
    src = SyntheticSource(n=3000, seed=11)
    plain = run_job(src, config=cfg, max_points_in_flight=0)

    taken = {}
    real_bounded = batch_mod._run_job_bounded

    def spy(source, sink, config, batch_size, max_points, **kw):
        taken["max_points"] = max_points
        return real_bounded(source, sink, config, batch_size, max_points,
                            **kw)

    monkeypatch.setattr(batch_mod, "_run_job_bounded", spy)
    # ~48 KiB budget -> fits ~300 points, so n=3000 must auto-bound
    # (the 64k floor kicks in; correctness is chunk-size independent).
    monkeypatch.setattr(
        batch_mod, "_available_ram_bytes", lambda: 96 * 1024
    )
    auto = run_job(src, config=cfg)
    assert taken["max_points"] == 1 << 16  # floor kicked in
    assert auto == plain


def test_weighted_job_is_linear_in_weights():
    """config.weighted with every value == 2.5 must yield exactly
    2.5x the count job's blob values (the cascade is a linear (key,
    sum) reduction; counts are oracle-verified elsewhere), across every
    level, slot, and timespan."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=800, seed=3)
    wrows = [dict(r, value=2.5) for r in rows]
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6,
                         timespans=("alltime", "month"))
    import dataclasses

    counted = run_job(_ColSource(rows), config=cfg, batch_size=128)
    weighted = run_job(
        _ColSource(wrows),
        config=dataclasses.replace(cfg, weighted=True),
        batch_size=128,
    )
    assert counted.keys() == weighted.keys()
    for key, blob in counted.items():
        c = json.loads(blob)
        w = json.loads(weighted[key])
        assert c.keys() == w.keys(), key
        for tile, cnt in c.items():
            assert w[tile] == pytest.approx(2.5 * cnt), (key, tile)


def test_weighted_job_hand_computed_sums():
    """Distinct per-row values on known tiles: blob values must be the
    exact per-(user, tile) sums, 'all' the total, background dropped,
    x-users only in 'all'."""
    from heatmap_tpu.pipeline import run_job

    base = {"latitude": 47.6, "longitude": -122.3, "timestamp": None}
    rows = [
        dict(base, user_id="alice", value=1.25),
        dict(base, user_id="alice", value=2.0),
        dict(base, user_id="bob", value=10.0),
        dict(base, user_id="x-spy", value=100.0),   # 'all' only
        dict(base, user_id="carol", value=5.0, source="background"),
    ]
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=4, weighted=True)
    blobs = run_job(_ColSource(rows), config=cfg, batch_size=10)
    from heatmap_tpu.tilemath.tile import Tile

    detail = Tile.tile_id_from_lat_long(47.6, -122.3, 10)
    per_user = {}
    for key, blob in blobs.items():
        user = key.split("|")[0]
        doc = json.loads(blob)
        if detail in doc:
            per_user[user] = doc[detail]
    assert per_user["alice"] == pytest.approx(3.25)
    assert per_user["bob"] == pytest.approx(10.0)
    assert "x-spy" not in per_user
    assert "carol" not in per_user
    assert per_user["all"] == pytest.approx(113.25)


def test_weighted_job_missing_value_column_raises():
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=20, seed=1)
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=4, weighted=True)
    with pytest.raises(ValueError, match="value"):
        run_job(_ColSource(rows), config=cfg)


@pytest.mark.slow
def test_weighted_fast_hmpb_matches_string_path(tmp_path):
    """run_job_fast on an HMPB file with a value section must produce
    the same blobs as the string path over the same weighted rows —
    plain AND bounded (integer weights keep every f64 sum exact)."""
    from heatmap_tpu.io.hmpb import HMPBSource, write_hmpb
    from heatmap_tpu.pipeline import run_job, run_job_fast
    from heatmap_tpu.pipeline.groups import route_user

    rng = np.random.default_rng(23)
    rows = [dict(r, value=float(v))
            for r, v in zip(_rows(n=600, seed=19),
                            rng.integers(0, 12, 600))]
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6, weighted=True)
    want = run_job(_ColSource(rows), config=cfg, batch_size=128)

    # Same rows in the fast layout (route host-side like convert does).
    names, intern = [], {}
    rid = np.empty(len(rows), np.int32)
    for i, r in enumerate(rows):
        name = route_user(r["user_id"])
        if name is None:
            rid[i] = -1
            continue
        if name not in intern:
            intern[name] = len(names)
            names.append(name)
        rid[i] = intern[name]
    path = write_hmpb(
        str(tmp_path / "w.hmpb"),
        np.asarray([r["latitude"] for r in rows]),
        np.asarray([r["longitude"] for r in rows]),
        rid, names,
        timestamp=np.asarray([r["timestamp"] for r in rows], np.int64),
        background=np.asarray(
            [r.get("source") == "background" for r in rows], np.uint8),
        value=np.asarray([r["value"] for r in rows]),
    )
    src = HMPBSource(path)
    assert src.has_value
    got = run_job_fast(src, config=cfg, batch_size=128)
    assert want == got
    bounded = run_job_fast(HMPBSource(path), config=cfg, batch_size=128,
                           max_points_in_flight=150)
    assert want == bounded


def test_weighted_fast_without_value_column_raises(tmp_path):
    from heatmap_tpu.io.hmpb import HMPBSource, write_hmpb
    from heatmap_tpu.pipeline import run_job_fast

    path = write_hmpb(str(tmp_path / "nv.hmpb"),
                      np.asarray([47.6]), np.asarray([-122.3]),
                      np.asarray([0], np.int32), ["u1"])
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=4, weighted=True)
    with pytest.raises(ValueError, match="value"):
        run_job_fast(HMPBSource(path), config=cfg)
    with pytest.raises(ValueError, match="value"):
        run_job_fast(HMPBSource(path), config=cfg, max_points_in_flight=10)


@pytest.mark.slow
@pytest.mark.parametrize("overlap", [False, True])
def test_weighted_bounded_matches_plain(overlap):
    """Weighted jobs under max_points_in_flight: integer-valued weights
    keep every f64 sum exact, so the chunked merge must reproduce the
    plain path byte-for-byte."""
    import dataclasses

    from heatmap_tpu.pipeline import run_job

    rng = np.random.default_rng(17)
    rows = [dict(r, value=float(v))
            for r, v in zip(_rows(n=1500, seed=11),
                            rng.integers(0, 20, 1500))]
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6,
                         timespans=("alltime", "month"), weighted=True)
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128)
    bounded = run_job(_ColSource(rows), config=cfg, batch_size=128,
                      max_points_in_flight=200, overlap_ingest=overlap)
    assert plain == bounded


def test_weighted_bounded_missing_value_column_raises():
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=50, seed=1)  # no value column
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=4, weighted=True)
    with pytest.raises(ValueError, match="value"):
        run_job(_ColSource(rows), config=cfg, max_points_in_flight=20)


@pytest.mark.slow
def test_cascade_backend_partitioned_identical_blobs():
    """BatchJobConfig(cascade_backend='partitioned'): the MXU cascade
    reduction produces the same blobs as the scatter backend for count
    jobs; weighted jobs refuse it loudly."""
    import dataclasses

    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=900, seed=31)
    cfg = BatchJobConfig(detail_zoom=13, min_detail_zoom=6,
                         cascade_backend="partitioned")
    a = run_job(_ColSource(rows), config=cfg, batch_size=256)
    b = run_job(_ColSource(rows),
                config=dataclasses.replace(cfg, cascade_backend="scatter"),
                batch_size=256)
    assert a == b and len(a) > 0
    wrows = [dict(r, value=2.0) for r in rows]
    with pytest.raises(ValueError, match="bounded-integer"):
        run_job(_ColSource(wrows),
                config=dataclasses.replace(cfg, weighted=True),
                batch_size=256)
    # Bounded path honors the backend too (identical blobs).
    bounded = run_job(_ColSource(rows), config=cfg, batch_size=256,
                      max_points_in_flight=300)
    assert bounded == a
    # Bounded-integer weighted contract: weight_bound unlocks the
    # partitioned backend for integer-weighted jobs, byte-identical
    # to the scatter backend (VERDICT r4 #7).
    rng = np.random.default_rng(32)
    for r in wrows:
        r["value"] = float(rng.integers(0, 100))
    wp = run_job(_ColSource(wrows),
                 config=dataclasses.replace(cfg, weighted=True,
                                            weight_bound=100),
                 batch_size=256)
    ws = run_job(_ColSource(wrows),
                 config=BatchJobConfig(detail_zoom=13, min_detail_zoom=6,
                                       weighted=True),
                 batch_size=256)
    assert wp == ws and len(wp) > 0
    # A weight outside the declared bound surfaces as overflow, not a
    # silently rounded sum.
    bad = [dict(r, value=250.75) for r in wrows[:4]] + wrows
    with pytest.raises(ValueError, match="overflowed capacity"):
        run_job(_ColSource(bad),
                config=dataclasses.replace(cfg, weighted=True,
                                           weight_bound=100),
                batch_size=256)
    # The contract knob is rejected where it would silently no-op.
    with pytest.raises(ValueError, match="weighted=True"):
        BatchJobConfig(weight_bound=10)
    # A bound past the kernel's exactness limit fails at config time,
    # not mid-job (no slab can keep f32 sums exact there).
    with pytest.raises(ValueError, match="exactness limit"):
        BatchJobConfig(weighted=True, weight_bound=20_000,
                       cascade_backend="partitioned")
    # Scatter has no such limit — big integer weights are fine there.
    BatchJobConfig(weighted=True, weight_bound=20_000)
    # Typos die at config construction, not after a full ingest.
    with pytest.raises(ValueError, match="unknown cascade backend"):
        BatchJobConfig(cascade_backend="partioned")
    # 60-bit key-budget guard: zoom 21 with huge slot counts cannot
    # reconstruct through three 20-bit channels.
    from heatmap_tpu.pipeline.cascade import CascadeConfig, build_cascade

    with pytest.raises(ValueError, match="60-bit"):
        build_cascade(np.zeros(4, np.int64), np.zeros(4, np.int64),
                      CascadeConfig(detail_zoom=21), n_slots=1 << 19,
                      backend="partitioned")


@pytest.mark.slow
def test_adaptive_capacity_identical_results():
    """adaptive_capacity shrinks deep cascade levels to the real
    unique counts; blobs must be identical to the fixed-shape path
    (counted AND weighted), including under amplify_all."""
    import dataclasses

    from heatmap_tpu.pipeline import run_job

    rows = [dict(r, value=float(v))
            for r, v in zip(_rows(n=1200, seed=29),
                            np.random.default_rng(29).integers(0, 9, 1200))]
    for weighted in (False, True):
        for amplify in (False, True):
            cfg = BatchJobConfig(detail_zoom=14, min_detail_zoom=5,
                                 weighted=weighted, amplify_all=amplify,
                                 adaptive_capacity=True)
            a = run_job(_ColSource(rows), config=cfg, batch_size=256)
            b = run_job(_ColSource(rows),
                        config=dataclasses.replace(
                            cfg, adaptive_capacity=False),
                        batch_size=256)
            assert a == b and len(a) > 0, (weighted, amplify)


@pytest.mark.slow
def test_run_job_bounded_propagates_ingest_errors():
    """A source failure in the prefetch thread must surface as the
    job's exception, not a hang or a silent partial result."""
    from heatmap_tpu.pipeline import run_job

    class ExplodingSource:
        def batches(self, batch_size):
            rows = _rows(n=400, seed=3)
            yield {
                "latitude": np.asarray([r["latitude"] for r in rows]),
                "longitude": np.asarray([r["longitude"] for r in rows]),
                "user_id": [r["user_id"] for r in rows],
                "timestamp": [r.get("timestamp") for r in rows],
                "source": [r.get("source", "gps") for r in rows],
            }
            raise OSError("disk vanished mid-scan")

    with pytest.raises(OSError, match="disk vanished"):
        run_job(ExplodingSource(), config=BatchJobConfig(detail_zoom=10,
                                                         min_detail_zoom=7),
                batch_size=100, max_points_in_flight=120)


@pytest.mark.slow
def test_run_job_bounded_device_arrays_stay_small(monkeypatch):
    """A source 10x larger than the bound never materializes more than
    ~one chunk's emissions on device (the config-5 memory shape)."""
    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import cascade as cascade_mod
    from heatmap_tpu.pipeline import run_job

    sizes = []
    real = cascade_mod.run_cascade

    def spy(e_codes, *a, **kw):
        sizes.append(len(e_codes))
        return real(e_codes, *a, **kw)

    monkeypatch.setattr(batch_mod.cascade_mod, "run_cascade", spy)
    rows = _rows(n=3000, seed=9)
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=7)
    bound = 300
    bounded = run_job(_ColSource(rows), config=cfg, batch_size=100,
                      max_points_in_flight=bound)
    assert len(sizes) >= 8  # actually chunked, not one big pass
    # <= 2 emissions per point (all + per-user); chunks never overshoot
    # the bound (flush happens before an overfilling append).
    assert max(sizes) <= 2 * bound
    sizes.clear()
    plain = run_job(_ColSource(rows), config=cfg, batch_size=100)
    assert sizes and sizes[0] > 2 * bound  # unbounded = one big cascade
    assert plain == bounded


@pytest.mark.slow
def test_run_job_bounded_default_zoom_regression():
    """z21 regression: the chunk merge packs (ts, g, code) with
    code_bits = 42, which silently wrapped when the slot columns
    arrived int32 off the native key decoder (int32 << 42). Must match
    the unbounded job exactly at the DEFAULT detail zoom."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=1500, seed=21)
    cfg = BatchJobConfig()  # detail_zoom=21: the reference's real shape
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128)
    bounded = run_job(_ColSource(rows), config=cfg, batch_size=128,
                      max_points_in_flight=200)
    assert plain == bounded


def test_merge_sorted_level_int32_slots_wide_codes():
    """Direct pin of the int32-shift wrap: _merge_sorted_level must pack
    int32 ts/g columns with 42-bit codes without wrapping, regardless
    of whether the native decoder (the int32 provenance) is built."""
    from heatmap_tpu.pipeline.batch import _merge_sorted_level

    empty = {"ts": np.empty(0, np.int64), "g": np.empty(0, np.int64),
             "code": np.empty(0, np.int64),
             "value": np.empty(0, np.float64)}
    code = np.array([1, (1 << 42) - 5], np.int64)
    a = _merge_sorted_level(
        empty, np.zeros(2, np.int32), np.array([3, 200], np.int32),
        code, np.array([1.0, 2.0]),
    )
    m = _merge_sorted_level(
        a, np.zeros(2, np.int32), np.array([3, 299], np.int32),
        code, np.array([5.0, 7.0]),
    )
    # (g=3, code=1)+=5, new (g=200, big) and (g=299, big) stay distinct.
    assert m["g"].tolist() == [3, 200, 299]
    assert m["code"].tolist() == [1, (1 << 42) - 5, (1 << 42) - 5]
    assert m["value"].tolist() == [6.0, 2.0, 7.0]


@pytest.mark.slow
def test_zoom_clamped_capacities_match_unclamped():
    """build_cascade's static per-level capacity clamp (n_slots * 4^zoom
    bounds the key space) must not change any aggregate — only array
    padding. Uses a LOW detail zoom so the clamp actually bites."""
    import jax.numpy as jnp

    from heatmap_tpu.pipeline import cascade as cascade_mod

    rng = np.random.default_rng(21)
    n, n_slots = 20_000, 7
    cfg = cascade_mod.CascadeConfig(detail_zoom=6, min_detail_zoom=2,
                                    result_delta=2)
    codes = jnp.asarray(rng.integers(0, 1 << 12, n), jnp.int64)
    slots = jnp.asarray(rng.integers(0, n_slots, n), jnp.int32)

    clamped = cascade_mod.build_cascade(codes, slots, cfg, n_slots)
    explicit = cascade_mod.build_cascade(
        codes, slots, cfg, n_slots,
        capacity=[n] * (cfg.n_levels + 1))
    for lvl, ((cu, cs, cn), (eu, es, en)) in enumerate(zip(clamped, explicit)):
        zoom = cfg.detail_zoom - lvl
        assert cu.shape[0] <= n_slots << (2 * zoom)
        m = int(en)
        assert int(cn) == m, lvl
        np.testing.assert_array_equal(np.asarray(cu)[:m], np.asarray(eu)[:m])
        np.testing.assert_array_equal(np.asarray(cs)[:m], np.asarray(es)[:m])


# -- data-parallel cascade (local multi-device DP) -------------------------


def _dp_cfg(**kw):
    # data_parallel=True: the equivalence tests below must exercise the
    # mesh route at test-sized inputs, which the auto threshold
    # (AUTO_DP_MIN_EMISSIONS) deliberately routes single-device.
    base = dict(detail_zoom=12, min_detail_zoom=6,
                timespans=("alltime", "month"), data_parallel=True)
    base.update(kw)
    return BatchJobConfig(**base)


def test_dp_mesh_auto_routing():
    """Auto (None) is capable on this 8-device env but engages only at
    AUTO_DP_MIN_EMISSIONS; True always engages; False pins it off; the
    non-composing configs route single-device instead of raising."""
    from heatmap_tpu.pipeline.batch import (
        AUTO_DP_MIN_EMISSIONS, _dp_mesh, _dp_mesh_for,
    )

    auto = _dp_cfg(data_parallel=None)
    mesh = _dp_mesh(auto)
    assert mesh is not None
    assert _dp_mesh(_dp_cfg()) is not None  # True
    assert _dp_mesh(_dp_cfg(data_parallel=False)) is None
    # The partitioned cascade composes with DP (the per-device detail
    # reduction swaps kernels inside the shard_map body), so it no
    # longer forces the single-device route.
    assert _dp_mesh(
        _dp_cfg(data_parallel=None, cascade_backend="partitioned")
    ) is not None
    # adaptive_capacity composes with the mesh under the gspmd
    # dispatch (the default auto resolution); only the shard_map
    # oracle still routes it single-device.
    assert _dp_mesh(
        _dp_cfg(data_parallel=None, adaptive_capacity=True)
    ) is not None
    assert _dp_mesh(
        _dp_cfg(data_parallel=None, adaptive_capacity=True,
                dispatch="shard_map")
    ) is None
    # The size gate: auto stays single-device below the threshold
    # (tiny shards lose to the dispatch), engages at it; explicit True
    # engages at any size.
    assert _dp_mesh_for(mesh, auto, AUTO_DP_MIN_EMISSIONS - 1) is None
    assert _dp_mesh_for(mesh, auto, AUTO_DP_MIN_EMISSIONS) is mesh
    assert _dp_mesh_for(mesh, _dp_cfg(), 8) is mesh


def test_dp_config_rejections():
    """data_parallel=True with a non-composing knob fails at config
    time, not mid-job; the partitioned cascade now composes and is
    accepted."""
    cfg = _dp_cfg(data_parallel=True, cascade_backend="partitioned")
    assert cfg.resolved_cascade_backend == "partitioned"
    # adaptive + DP is accepted under the gspmd dispatch (default auto
    # resolution); the shard_map oracle still rejects at config time.
    _dp_cfg(data_parallel=True, adaptive_capacity=True)
    with pytest.raises(ValueError, match="adaptive"):
        _dp_cfg(data_parallel=True, adaptive_capacity=True,
                dispatch="shard_map")


def test_cascade_backend_auto_resolution(monkeypatch):
    """"auto" routes count jobs to the partitioned MXU kernel ON TPU
    only (off TPU the pallas kernel would run in interpret mode,
    orders slower than native scatter — same gate as
    ops/histogram._pick_backend); weighted jobs stay on scatter;
    explicit choices are honored on any platform."""
    import types

    import jax

    assert BatchJobConfig().resolved_cascade_backend == "scatter"  # CPU
    assert (BatchJobConfig(cascade_backend="partitioned")
            .resolved_cascade_backend == "partitioned")
    monkeypatch.setattr(jax, "devices",
                        lambda: [types.SimpleNamespace(platform="tpu")])
    assert BatchJobConfig().resolved_cascade_backend == "partitioned"
    assert (BatchJobConfig(weighted=True).resolved_cascade_backend
            == "scatter")
    assert (BatchJobConfig(cascade_backend="scatter")
            .resolved_cascade_backend == "scatter")


def test_dp_min_emissions_override():
    """The calibration knob moves the auto threshold; combining it with
    an explicit on/off (where it would silently do nothing) is rejected
    at config time."""
    from heatmap_tpu.pipeline.batch import _dp_mesh, _dp_mesh_for

    tuned = _dp_cfg(data_parallel=None, dp_min_emissions=1000)
    mesh = _dp_mesh(tuned)
    assert mesh is not None
    assert _dp_mesh_for(mesh, tuned, 999) is None
    assert _dp_mesh_for(mesh, tuned, 1000) is mesh
    # 0 engages auto at any size (the "my hardware always wins" pin).
    always = _dp_cfg(data_parallel=None, dp_min_emissions=0)
    assert _dp_mesh_for(mesh, always, 1) is mesh
    with pytest.raises(ValueError, match="AUTO"):
        _dp_cfg(data_parallel=True, dp_min_emissions=1000)
    with pytest.raises(ValueError, match="AUTO"):
        _dp_cfg(data_parallel=False, dp_min_emissions=1000)
    with pytest.raises(ValueError, match=">= 0"):
        _dp_cfg(data_parallel=None, dp_min_emissions=-1)


@pytest.mark.slow
@pytest.mark.parametrize("amplify", [False, True])
def test_run_job_data_parallel_byte_identical(amplify):
    """The flagship job over the 8-device mesh (VERDICT r3 missing #2):
    blobs byte-identical to the single-device cascade at every level,
    in both compat modes."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2500, seed=42)
    dp = run_job(_ColSource(rows), config=_dp_cfg(amplify_all=amplify))
    single = run_job(
        _ColSource(rows),
        config=_dp_cfg(amplify_all=amplify, data_parallel=False),
    )
    assert dp == single and len(dp) > 0


@pytest.mark.slow
@pytest.mark.parametrize("amplify", [False, True])
def test_run_job_dp_prefix_merge_byte_identical(amplify):
    """The coarse-prefix regrouped merge (VERDICT r4 missing #4) emits
    blobs byte-identical to BOTH the replicated-merge DP job and the
    single-device cascade, in both compat modes — same bar as the
    replicated route."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2500, seed=42)
    prefix = run_job(
        _ColSource(rows),
        config=_dp_cfg(amplify_all=amplify, dp_merge="prefix"),
    )
    replicated = run_job(
        _ColSource(rows), config=_dp_cfg(amplify_all=amplify)
    )
    single = run_job(
        _ColSource(rows),
        config=_dp_cfg(amplify_all=amplify, data_parallel=False),
    )
    assert prefix == replicated == single and len(prefix) > 0


@pytest.mark.slow
def test_run_job_dp_partitioned_cascade_byte_identical():
    """DP x partitioned composition at the blob level: the MXU segment
    reduction inside each device's shard_map body must emit blobs
    byte-identical to BOTH the DP scatter cascade and the single-device
    partitioned cascade. Counts are exact integers in any summation
    order, so the bar is equality — the same bar the scatter DP route
    passes."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2500, seed=42)
    dp_part = run_job(_ColSource(rows),
                      config=_dp_cfg(cascade_backend="partitioned"))
    dp_scat = run_job(_ColSource(rows),
                      config=_dp_cfg(cascade_backend="scatter"))
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(cascade_backend="partitioned",
                                    data_parallel=False))
    assert dp_part == dp_scat == single and len(dp_part) > 0


@pytest.mark.slow
def test_run_job_dp_prefix_merge_partitioned_byte_identical():
    """The partitioned cascade under the coarse-prefix regrouped merge:
    the backend choice changes only each device's local reduction, so
    blobs stay byte-identical to the single-device job."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=9)
    prefix = run_job(_ColSource(rows),
                     config=_dp_cfg(cascade_backend="partitioned",
                                    dp_merge="prefix"))
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(cascade_backend="partitioned",
                                    data_parallel=False))
    assert prefix == single and len(prefix) > 0


@pytest.mark.slow
def test_run_job_dp_prefix_merge_weighted_integer_bit_identical():
    """Integer weighted sums through the prefix merge stay bit-exact
    (integer f64 addition is order-free; the regroup only changes the
    order)."""
    from heatmap_tpu.pipeline import run_job

    rng = np.random.default_rng(15)
    rows = _rows(n=1500, seed=15)
    for r in rows:
        r["value"] = float(rng.integers(1, 12))
    prefix = run_job(_ColSource(rows),
                     config=_dp_cfg(weighted=True, dp_merge="prefix"))
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(weighted=True, data_parallel=False))
    assert prefix == single and len(prefix) > 0


@pytest.mark.slow
def test_run_job_dp_prefix_merge_bounded_byte_identical():
    """The prefix merge composes with the bounded chunked path exactly
    like the replicated merge does."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=9)
    prefix = run_job(_ColSource(rows),
                     config=_dp_cfg(dp_merge="prefix"),
                     batch_size=128, max_points_in_flight=300)
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(data_parallel=False),
                     batch_size=128, max_points_in_flight=300)
    assert prefix == single and len(prefix) > 0


def test_dp_merge_config_rejection():
    """A dp_merge typo fails at config time, before ingest."""
    with pytest.raises(ValueError, match="dp_merge"):
        BatchJobConfig(dp_merge="sharded")


def test_run_job_data_parallel_matches_oracle():
    """DP blobs equal the pure-Python reference oracle exactly — the
    sharded route is held to the same golden bar as the single-device
    path, not just to path-vs-path equality."""
    rows = _rows(n=300, seed=77)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=5,
                         data_parallel=True)
    got = run_batch(rows, cfg)
    want = oracle.run_job(rows, detail_zoom=12, min_detail_zoom=5,
                          amplify_all=False)
    assert got.keys() == want.keys()
    for key in want:
        assert got[key] == want[key], key


@pytest.mark.slow
def test_run_job_data_parallel_bounded_byte_identical():
    """DP composes with the bounded chunked path (per-chunk sharded
    cascade, host merge unchanged)."""
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=9)
    dp = run_job(_ColSource(rows), config=_dp_cfg(),
                 batch_size=128, max_points_in_flight=300)
    single = run_job(_ColSource(rows), config=_dp_cfg(data_parallel=False),
                     batch_size=128, max_points_in_flight=300)
    assert dp == single and len(dp) > 0


@pytest.mark.slow
def test_run_job_data_parallel_weighted_integer_bit_identical():
    """Integer-valued weighted sums are exact in f64 under any
    summation order, so the DP route must match bit-for-bit."""
    from heatmap_tpu.pipeline import run_job

    rng = np.random.default_rng(5)
    rows = _rows(n=1500, seed=5)
    for r in rows:
        r["value"] = float(rng.integers(1, 12))
    dp = run_job(_ColSource(rows), config=_dp_cfg(weighted=True))
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(weighted=True, data_parallel=False))
    assert dp == single and len(dp) > 0


@pytest.mark.slow
def test_run_job_data_parallel_fractional_weights_allclose():
    """Fractional weighted sums agree up to f64 summation-order
    rounding (the documented contract, same as the bounded merge)."""
    from heatmap_tpu.pipeline import run_job

    rng = np.random.default_rng(6)
    rows = _rows(n=1500, seed=6)
    for r in rows:
        r["value"] = float(rng.random())
    dp = run_job(_ColSource(rows), config=_dp_cfg(weighted=True))
    single = run_job(_ColSource(rows),
                     config=_dp_cfg(weighted=True, data_parallel=False))
    assert dp.keys() == single.keys()
    for key in single:
        a, b = json.loads(dp[key]), json.loads(single[key])
        assert list(a) == list(b), key
        for field in a:
            assert a[field] == pytest.approx(b[field], rel=1e-12), key


@pytest.mark.slow
def test_dp_cascade_overflow_detected():
    """An undersized capacity must still raise through the sharded
    route — the per-device overflow flag propagates into every level's
    n_unique (the ops/sparse.py contract)."""
    from heatmap_tpu.parallel.mesh import make_mesh
    from heatmap_tpu.pipeline import cascade as cascade_mod
    import jax

    rng = np.random.default_rng(13)
    cfg = cascade_mod.CascadeConfig(detail_zoom=8, min_detail_zoom=4,
                                    result_delta=4)
    codes = rng.integers(0, 1 << 16, 4096)
    slots = np.zeros(4096, np.int64)
    mesh = make_mesh(devices=jax.devices())
    levels = cascade_mod.build_cascade(
        codes, slots, cfg, n_slots=1, capacity=8, mesh=mesh
    )
    with pytest.raises(ValueError, match="overflowed"):
        cascade_mod.decode_levels(levels, cfg)


def test_build_cascade_mesh_rejects_noncomposing():
    """mesh + adaptive still raises at the cascade layer (covers
    callers that bypass BatchJobConfig); mesh + partitioned now
    composes — the segment reduction runs inside the shard_map body —
    and must match the sharded scatter cascade exactly."""
    from heatmap_tpu.parallel.mesh import make_mesh
    from heatmap_tpu.pipeline import cascade as cascade_mod
    import jax

    cfg = cascade_mod.CascadeConfig(detail_zoom=8, min_detail_zoom=4,
                                    result_delta=4)
    codes = np.arange(64, dtype=np.int64)
    slots = np.zeros(64, np.int64)
    mesh = make_mesh(devices=jax.devices())
    with pytest.raises(ValueError, match="adaptive"):
        cascade_mod.build_cascade(codes, slots, cfg, n_slots=1,
                                  adaptive=True, mesh=mesh)
    part = cascade_mod.build_cascade(codes, slots, cfg, n_slots=1,
                                     backend="partitioned", mesh=mesh)
    scat = cascade_mod.build_cascade(codes, slots, cfg, n_slots=1,
                                     backend="scatter", mesh=mesh)
    assert len(part) == len(scat)
    for (pu, ps, pn), (su, ss, sn) in zip(part, scat):
        n = int(sn)
        assert int(pn) == n
        np.testing.assert_array_equal(np.asarray(pu)[:n],
                                      np.asarray(su)[:n])
        np.testing.assert_array_equal(np.asarray(ps)[:n],
                                      np.asarray(ss)[:n])


# -- auto-spill safety rails (ADVICE r3 medium) ----------------------------


def _auto_spill_env(monkeypatch, batch_mod, tmp_path):
    """Force auto-spill eligibility: tiny threshold, per-test dir (a
    shared hardcoded dir would let parallel runs see each other's live
    spill tempdirs)."""
    monkeypatch.setattr(batch_mod, "AUTO_SPILL_ROWS", 500)
    monkeypatch.setattr(batch_mod, "_auto_spill_target",
                        lambda: batch_mod.AUTO_SPILL_DIR)
    monkeypatch.setattr(batch_mod, "AUTO_SPILL_DIR",
                        str(tmp_path / "auto-spill"))


def test_auto_spill_refused_when_projection_exceeds_free_space(
        monkeypatch, tmp_path):
    """A too-small target filesystem must keep the in-RAM fold (with a
    warning), never convert and then ENOSPC a job that RAM finishes."""
    import glob

    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6)
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128,
                    max_points_in_flight=150)

    _auto_spill_env(monkeypatch, batch_mod, tmp_path)
    monkeypatch.setattr(batch_mod, "_free_disk_bytes", lambda p: 1024)
    created = []
    real_spill = batch_mod._SpillMerge

    class _Spy(real_spill):
        def __init__(self, root, n_levels):
            super().__init__(root, n_levels)
            created.append(self.dir)

    monkeypatch.setattr(batch_mod, "_SpillMerge", _Spy)
    with pytest.warns(RuntimeWarning, match="auto-spill skipped"):
        got = run_job(_ColSource(rows), config=cfg, batch_size=128,
                      max_points_in_flight=150)
    assert got == plain
    assert created == []  # never converted
    assert not glob.glob(str(tmp_path / "auto-spill" / "merge-spill-*"))


def test_auto_spill_write_failure_falls_back_to_ram(monkeypatch, tmp_path):
    """An OSError mid-spill on the AUTO path folds the spilled runs
    back into RAM and finishes diskless — byte-identical blobs, spill
    tempdir cleaned up, warning raised (ADVICE r3: auto-spill must not
    fail a job that previously completed fully in RAM)."""
    import glob

    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import run_job

    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6)
    plain = run_job(_ColSource(rows), config=cfg, batch_size=128,
                    max_points_in_flight=150)

    _auto_spill_env(monkeypatch, batch_mod, tmp_path)
    real_spill = batch_mod._SpillMerge
    state = {"adds": 0, "dirs": []}

    class _Failing(real_spill):
        def __init__(self, root, n_levels):
            super().__init__(root, n_levels)
            state["dirs"].append(self.dir)

        def add_level(self, run, level, ts, g, code, value):
            # Let the conversion (run 0) through, then die partway
            # through a later run — some levels written, some not, and
            # the failing level's last file TRUNCATED-but-present (the
            # real ENOSPC shape): recovery must drop it by name, not
            # trust file existence.
            if run >= 1 and level >= 3:
                base = self._base(run, level)
                np.save(base + "_ts.npy", np.asarray(ts, np.int32))
                np.save(base + "_g.npy", np.asarray(g, np.int32))
                np.save(base + "_code.npy", np.asarray(code, np.int64))
                with open(base + "_value.npy", "wb") as f:
                    f.write(b"\x93NUMPY")  # truncated mid-write
                raise OSError(28, "No space left on device")
            return super().add_level(run, level, ts, g, code, value)

    monkeypatch.setattr(batch_mod, "_SpillMerge", _Failing)
    with pytest.warns(RuntimeWarning, match="auto-spill write failed"):
        got = run_job(_ColSource(rows), config=cfg, batch_size=128,
                      max_points_in_flight=150)
    assert got == plain
    assert len(state["dirs"]) == 1
    assert not glob.glob(state["dirs"][0] + "*")  # cleaned up


def test_explicit_spill_write_failure_still_raises(monkeypatch, tmp_path):
    """merge_spill_dir is the operator's explicit choice: a disk error
    there must fail the job loudly, not silently fall back to the
    in-RAM merge whose footprint the operator asked to avoid."""
    from heatmap_tpu.pipeline import batch as batch_mod
    from heatmap_tpu.pipeline import run_job

    real_spill = batch_mod._SpillMerge

    class _Failing(real_spill):
        def add_level(self, run, level, ts, g, code, value):
            if run >= 1:
                raise OSError(28, "No space left on device")
            return super().add_level(run, level, ts, g, code, value)

    monkeypatch.setattr(batch_mod, "_SpillMerge", _Failing)
    rows = _rows(n=2000, seed=7)
    cfg = BatchJobConfig(detail_zoom=12, min_detail_zoom=6)
    with pytest.raises(OSError):
        run_job(_ColSource(rows), config=cfg, batch_size=128,
                max_points_in_flight=150,
                merge_spill_dir=str(tmp_path / "spill"))
    # Cleanup still ran (the ingest-failure cleanup path).
    spill_root = tmp_path / "spill"
    assert not spill_root.exists() or list(spill_root.iterdir()) == []


def test_auto_spill_projection_math():
    from heatmap_tpu.pipeline import batch as batch_mod

    fits = batch_mod._auto_spill_projection_fits
    # Known totals: 1000 table rows + 2 remaining * 500-row chunks
    # -> 24 * 2000 * 1.25 = 60000 bytes projected.
    import unittest.mock as mock
    with mock.patch.object(batch_mod, "_free_disk_bytes",
                           lambda p: 60_000):
        assert fits("/x", 1000, 3, 5, 500)
    with mock.patch.object(batch_mod, "_free_disk_bytes",
                           lambda p: 59_999):
        assert not fits("/x", 1000, 3, 5, 500)
    # Unknown chunk total: assume as many chunks remain as have run.
    with mock.patch.object(batch_mod, "_free_disk_bytes",
                           lambda p: 10**12):
        assert fits("/x", 1000, 3, None, 500)
    # No free-space signal: keep the measured default (spill).
    with mock.patch.object(batch_mod, "_free_disk_bytes",
                           lambda p: None):
        assert fits("/x", 10**12, 1, None, 10**12)


def test_fast_auto_routing_respects_source_bytes_per_point():
    """HMPB mmap ingest (~30 B/point resident) must not be demoted to
    the chunked path by the 160 B string-ingest constant (ADVICE r3):
    the fast auto call consults fast_host_bytes_per_point, the string
    call ignores it."""
    from heatmap_tpu.pipeline.batch import _auto_points_in_flight

    class _FakeHMPB:
        n = 1_000_000
        fast_host_bytes_per_point = 30

    # Effective fast rate: 30 declared + 64/timespan of emission/sort
    # arrays = 94 B/pt at one timespan — fits a 100 B/pt budget where
    # the 160 B string constant would demote.
    budget = 1_000_000 * 100
    assert _auto_points_in_flight(_FakeHMPB(), ram_budget=budget,
                                  fast=True) is None
    assert _auto_points_in_flight(_FakeHMPB(),
                                  ram_budget=budget) is not None
    # More timespans mean more emission arrays per point: the same
    # source stops fitting (30 + 4*64 = 286 B/pt).
    assert _auto_points_in_flight(_FakeHMPB(), ram_budget=budget,
                                  fast=True, n_timespans=4) is not None
    # Weighted adds the f64 value column + expanded e_weights
    # (30 + 64 + 8 + 32 = 134 B/pt > the 100 B/pt budget).
    assert _auto_points_in_flight(_FakeHMPB(), ram_budget=budget,
                                  fast=True, weighted=True) is not None

    class _Plain:
        n = 1_000_000

    # No attribute: fast ingest keeps the conservative constant.
    assert _auto_points_in_flight(_Plain(), ram_budget=budget,
                                  fast=True) is not None


def test_dp_edge_shapes_byte_identical():
    """DP padding edges: fewer points than devices, one point, one
    unique location, all-excluded users — every shape must byte-equal
    the single-device cascade (pad lanes are valid=False and the
    per-device capacity floors at 1)."""
    from heatmap_tpu.pipeline import run_job

    cases = [
        [dict(r, source="gps") for r in _rows(n=3, seed=1)],  # n < ndev
        [dict(r, source="gps") for r in _rows(n=1, seed=2)],  # 1 point
        [dict(r, latitude=50.0, longitude=8.0, source="gps")  # 1 unique
         for r in _rows(n=40, seed=3)],                       # location
        [dict(r, user_id="xonly", source="gps")  # all users excluded:
         for r in _rows(n=24, seed=4)],          # only 'all' slots emit
    ]
    for i, rows in enumerate(cases):
        dp = run_job(_ColSource(rows), config=_dp_cfg())
        single = run_job(_ColSource(rows),
                         config=_dp_cfg(data_parallel=False))
        assert dp == single, f"case {i}"
        assert len(dp) > 0, f"case {i}"


def test_dp_all_background_returns_empty():
    from heatmap_tpu.pipeline import run_job

    rows = [dict(r, source="background") for r in _rows(n=30, seed=5)]
    assert run_job(_ColSource(rows), config=_dp_cfg()) == {}
