"""Temporal plane tests (heatmap_tpu/temporal/ + delta/retract.py).

The anchors, all byte-level:

1. **Bucketing is invisible to all-time serving** — a bucketed
   compaction's top-level base artifact is byte-identical to the
   un-bucketed twin's, and a fold over ALL buckets equals the
   un-bucketed overlay.
2. **Every cut equals a clean recompute** — ``as_of`` folds equal a
   recompute over exactly the batches inside the cut; window folds
   equal a recompute over the trailing buckets; decay folds equal the
   per-bucket-weighted recompute through the same deterministic merge.
3. **Immutable history, targeted invalidation** — an as_of token
   survives unrelated ingest; a bucket roll invalidates exactly the
   retiring bucket's window-variant keys.
4. **Failure containment** — a torn bucket quarantines under the
   recovery sweep and serves last-good bytes (stale-if-error), while
   the all-time path never notices.
5. **Bounded-error time queries** — topk_growth's stamped bound is
   sound against a brute-force series oracle, and a full coefficient
   budget is exact.
6. **Predicate retraction** — ``retract --where user=U`` leaves the
   store byte-identical to a recompute over the surviving points,
   before and after compaction, idempotently.

Tier-1: CPU backend, real cascade runs (small shapes), no network.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

import numpy as np
import pytest

from heatmap_tpu import delta
from heatmap_tpu.delta.compact import (
    drop_zero_rows,
    load_overlay_levels,
    read_current,
)
from heatmap_tpu.delta.retract import parse_where, retract_predicate
from heatmap_tpu.io.merge import _loaded_to_finalized, merge_level_parts
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.pipeline import BatchJobConfig, run_job
from heatmap_tpu.serve import ServeApp, TileCache, TileStore
from heatmap_tpu.serve.render import tile_json_bytes
from heatmap_tpu.temporal import buckets as tb
from heatmap_tpu.temporal import fold as tfold
from heatmap_tpu.temporal import timequery
from heatmap_tpu.temporal.fold import TornBucketError

CONFIG = BatchJobConfig(detail_zoom=8, min_detail_zoom=5)
TCFG = {"width": 100.0, "fanout": 2, "keep": 2, "tiers": 3}


def _batch(seed: int, t0: float | None, n: int = 40) -> dict:
    rng = np.random.default_rng(seed)
    cols = {
        "latitude": rng.uniform(30.0, 50.0, n),
        "longitude": rng.uniform(-120.0, -70.0, n),
        "user_id": ["alice" if i % 2 else "bob" for i in range(n)],
    }
    if t0 is not None:
        cols["timestamp"] = [str(float(t0 + i)) for i in range(n)]
    return cols


def _union(*batches: dict) -> dict:
    keys = set()
    for b in batches:
        keys |= set(b)
    out = {}
    for k in keys:
        vals = []
        for b in batches:
            v = b.get(k)
            if v is None:
                vals.extend([None] * len(b["latitude"]))
            else:
                vals.extend(list(np.asarray(v)) if isinstance(v, np.ndarray)
                            else list(v))
        out[k] = vals
    # timestamp None placeholders only arise when mixing timed and
    # timeless batches; the oracles never do that.
    assert all(v is not None for vs in out.values() for v in vs)
    return out


def _levelbytes(levels: list) -> list:
    """Canonical (dtype + raw bytes) form of finalized level dicts —
    equality here means the artifacts serialize identically."""
    out = []
    for lvl in levels:
        rec = {}
        for k, v in sorted(lvl.items()):
            if hasattr(v, "__len__") and not isinstance(v, str):
                a = np.asarray(v)
                rec[k] = (str(a.dtype), a.tobytes())
            else:
                rec[k] = v
        out.append((int(lvl["zoom"]), rec))
    return out


def _oracle_levels(*dir_weight_pairs) -> list:
    """Clean-recompute oracle: merge per-group run_job artifacts
    through the SAME deterministic combine the fold uses (per-unit
    value scaling -> merge_level_parts -> drop_zero_rows)."""
    parts = []
    for d, w in dir_weight_pairs:
        loaded = LevelArraysSink.load(d)
        part = []
        for z in sorted(loaded):
            cols = loaded[z]
            if w != 1.0:
                cols = dict(cols)
                cols["value"] = np.asarray(cols["value"], np.float64) * w
            part.append(_loaded_to_finalized(cols))
        parts.append(part)
    return drop_zero_rows(merge_level_parts(parts))


def _tree_digest(root: str) -> str:
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for fn in sorted(filenames):
            path = os.path.join(dirpath, fn)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _base_file_hashes(root: str, *, skip=("TEMPORAL.json",)) -> dict:
    """sha256 of every top-level file in CURRENT's base dir (the
    all-time artifact; buckets/ and the manifest are temporal-only)."""
    base = os.path.join(root, read_current(root)["base"])
    out = {}
    for name in sorted(os.listdir(base)):
        p = os.path.join(base, name)
        if os.path.isfile(p) and name not in skip:
            with open(p, "rb") as f:
                out[name] = hashlib.sha256(f.read()).hexdigest()
    return out


@pytest.fixture(scope="module")
def scenario(tmp_path_factory):
    """One bucketed store lifecycle with per-group recompute oracles.

    Batches (width=100, fanout=2, keep=2, tiers=3):

      b1 t0=1000 -> bucket (1000,1100)   b2 t0=1120 -> (1100,1200)
      b3 t0=1310 -> bucket (1300,1400)   b4 t0=1440 -> (1400,1500)
      b5 timeless -> bucket-none

    After compaction max_edge=1500 coarsens b1+b2 into tier-1
    bucket-1000-1200 while b3/b4 stay tier-0. Fold snapshots are taken
    at the compacted state (ref=1500); a live batch b6 (t0=1520) is
    applied afterwards to pin live-delta folding and as_of-token
    immutability under ingest.
    """
    tp = tmp_path_factory.mktemp("temporal")
    root = str(tp / "store")
    rootu = str(tp / "store_unbucketed")
    batches = {k: _batch(i, t0) for i, (k, t0) in enumerate(
        [("b1", 1000), ("b2", 1120), ("b3", 1310), ("b4", 1440),
         ("b5", None)])}

    os.makedirs(root)
    tfold.ensure_config(root, **TCFG)
    for key in ("b1", "b2", "b3", "b4", "b5"):
        delta.apply_batch(root, delta.ColumnsSource(batches[key]), CONFIG)
        delta.apply_batch(rootu, delta.ColumnsSource(batches[key]), CONFIG)
    comp = delta.compact(root, retention=10)
    compu = delta.compact(rootu, retention=10)

    # Clean per-group recomputes, one run_job per bucket's points.
    groups = {
        "g12": _union(batches["b1"], batches["b2"]),
        "g3": batches["b3"], "g4": batches["b4"], "gnone": batches["b5"],
    }
    gdirs = {}
    for name, cols in groups.items():
        d = str(tp / f"oracle_{name}")
        run_job(delta.ColumnsSource(cols), LevelArraysSink(d), CONFIG)
        gdirs[name] = d

    folds = {
        "all": tfold.fold_levels(root, tfold.select_fold(root)),
        "asof": tfold.fold_levels(root, tfold.select_fold(root,
                                                          as_of=1250)),
        "window": tfold.fold_levels(root, tfold.select_fold(
            root, window=150.0)),
        "decay": tfold.fold_levels(root, tfold.select_fold(
            root, decay=100.0), decay_half_life=100.0),
    }
    token_before_live = tfold.select_fold(root, as_of=1250).token

    res6 = delta.apply_batch(
        root, delta.ColumnsSource(_batch(6, 1520, n=20)), CONFIG)

    return {
        "root": root, "rootu": rootu, "batches": batches,
        "gdirs": gdirs, "folds": folds, "comp": comp, "compu": compu,
        "token_before_live": token_before_live, "res6": res6,
    }


class TestBucketedCompaction:
    def test_manifest_shape_and_coarsening(self, scenario):
        cur = read_current(scenario["root"])
        man = tb.read_manifest(os.path.join(scenario["root"], cur["base"]))
        assert man is not None and man["schema"] == tb.TEMPORAL_SCHEMA
        names = {b["name"]: b for b in man["buckets"]}
        assert set(names) == {"bucket-1000-1200", "bucket-1300-1400",
                              "bucket-1400-1500"}
        assert names["bucket-1000-1200"]["tier"] == 1  # b1+b2 coarsened
        assert sorted(names["bucket-1000-1200"]["epochs"]) == [1, 2]
        assert man["none"] is not None  # the timeless batch b5
        assert scenario["comp"]["buckets"] == 4  # 3 timed + none

    def test_alltime_artifact_byte_identical_to_unbucketed(self, scenario):
        """The tentpole gate: bucketing adds buckets/ + TEMPORAL.json
        and changes NOTHING else — the all-time base files match the
        un-bucketed twin's byte for byte."""
        assert (_base_file_hashes(scenario["root"])
                == _base_file_hashes(scenario["rootu"]))
        assert scenario["compu"].get("buckets") is None

    def test_fold_over_everything_equals_overlay(self, scenario):
        """Fold(all buckets + live) == the un-bucketed overlay, live
        delta included."""
        got = tfold.fold_levels(scenario["root"],
                                tfold.select_fold(scenario["root"]))
        assert _levelbytes(got) == _levelbytes(
            load_overlay_levels(scenario["root"]))

    def test_config_pinned_first_writer_wins(self, scenario, tmp_path):
        with pytest.raises(ValueError, match="pinned temporal config"):
            tfold.ensure_config(scenario["root"], width=999.0)
        # absent config + no offer stays off
        assert tfold.ensure_config(str(tmp_path / "empty")) is None


class TestCuts:
    def test_as_of_equals_clean_recompute(self, scenario):
        g = scenario["gdirs"]
        assert _levelbytes(scenario["folds"]["asof"]) == _levelbytes(
            _oracle_levels((g["g12"], 1.0), (g["gnone"], 1.0)))

    def test_window_equals_clean_recompute(self, scenario):
        g = scenario["gdirs"]
        assert _levelbytes(scenario["folds"]["window"]) == _levelbytes(
            _oracle_levels((g["g3"], 1.0), (g["g4"], 1.0),
                           (g["gnone"], 1.0)))

    def test_decay_equals_weighted_recompute(self, scenario):
        """Per-bucket scalar decay at ref=1500, half-life 100:
        bucket-1000-1200 -> 0.125, 1300-1400 -> 0.5, 1400-1500 -> 1.0,
        bucket-none never ages."""
        g = scenario["gdirs"]
        assert _levelbytes(scenario["folds"]["decay"]) == _levelbytes(
            _oracle_levels((g["g12"], 0.125), (g["g3"], 0.5),
                           (g["g4"], 1.0), (g["gnone"], 1.0)))

    def test_as_of_before_all_timed_data(self, scenario):
        """A cut below every epoch selects no timed units; only the
        timeless bucket-none rows (no timestamp -> no history axis)
        remain, in every cut by design."""
        sel = tfold.select_fold(scenario["root"], as_of=10.0)
        assert not sel.buckets and not sel.live
        assert sel.none is not None
        assert _levelbytes(
            tfold.fold_levels(scenario["root"], sel)) == _levelbytes(
            _oracle_levels((scenario["gdirs"]["gnone"], 1.0)))

    def test_as_of_token_survives_unrelated_ingest(self, scenario):
        """History below a cut is immutable: applying b6 (wm 1520+)
        did not move the as_of=1250 selection token, so every cache
        entry keyed by it stays structurally valid."""
        assert not scenario["res6"].duplicate
        sel = tfold.select_fold(scenario["root"], as_of=1250)
        assert sel.token == scenario["token_before_live"]

    def test_live_delta_folds_into_window(self, scenario):
        """b6 is live (not yet compacted) and newest: the window ref
        advances to its tier-0 edge and the fold includes it."""
        sel = tfold.select_fold(scenario["root"], window=150.0)
        assert sel.ref == 1600.0
        assert [u["epoch"] for u in sel.live] == [6]


class TestServing:
    @pytest.fixture()
    def app(self, scenario):
        return ServeApp(TileStore(f"delta:{scenario['root']}"),
                        TileCache())

    def test_as_of_tile_bytes_match_oracle_store(self, scenario, app,
                                                 tmp_path):
        g = scenario["gdirs"]
        d = str(tmp_path / "asof_oracle")
        LevelArraysSink(d).write_levels(
            _oracle_levels((g["g12"], 1.0), (g["gnone"], 1.0)))
        oracle = TileStore(f"arrays:{d}")
        layer = oracle.layer("default")
        zooms = sorted(z for z in layer.levels if z <= 6)
        z = zooms[-1]
        compared = 0
        for x in range(1 << z):
            for y in range(1 << z):
                want = tile_json_bytes(layer, z, x, y)
                r = app.handle("GET",
                               f"/tiles/default/{z}/{x}/{y}.json?as_of=1250")
                if want is None:
                    assert r[0] == 404
                else:
                    assert r[0] == 200 and r[2] == want
                    compared += 1
        assert compared > 0

    def test_temporal_etag_namespace_and_304(self, scenario, app):
        r = app.handle("GET", "/tiles/default/2/0/1.json?window=150")
        assert r[0] == 200 and r[3].startswith('"t-')
        assert r.headers == {"X-Heatmap-Temporal": "window"}
        r304 = app.handle("GET", "/tiles/default/2/0/1.json?window=150",
                          if_none_match=r[3])
        assert r304[0] == 304 and r304[2] == b""
        # the all-time twin never revalidates against the temporal tag
        r_all = app.handle("GET", "/tiles/default/2/0/1.json",
                           if_none_match=r[3])
        assert r_all[0] == 200 and not r_all[3].startswith('"t-')

    def test_window_param_registered_for_invalidation(self, scenario,
                                                      app):
        app.handle("GET", "/tiles/default/2/0/1.json?window=150")
        assert app.cache.window_params() == ("150",)

    def test_bad_temporal_params_are_typed_400s(self, scenario, app):
        for q in ("window=bogus", "as_of=nope", "decay=-3"):
            r = app.handle("GET", f"/tiles/default/2/0/1.json?{q}")
            assert r[0] == 400
            assert json.loads(r[2])["error"] == "bad temporal query"

    def test_store_without_temporal_config_400s(self, scenario):
        app = ServeApp(TileStore(f"delta:{scenario['rootu']}"),
                       TileCache())
        r = app.handle("GET", "/tiles/default/2/0/1.json?as_of=1250")
        assert r[0] == 400
        assert "no temporal config" in json.loads(r[2])["detail"]

    def test_torn_bucket_serves_last_good_stale(self, scenario,
                                                tmp_path):
        """Corrupting a bucket under a cached as_of tile: the re-render
        raises TornBucketError inside the fold, the stale-if-error
        cache answers 200 with the last-good bytes, and the all-time
        path (which never reads buckets) is untouched."""
        root = str(tmp_path / "store")
        shutil.copytree(scenario["root"], root)
        app = ServeApp(TileStore(f"delta:{root}"), TileCache())
        # find a tile with data so there are last-good bytes to keep
        sel = tfold.select_fold(root, as_of=1250)
        url = None
        for z in (3, 2, 1):
            for x in range(1 << z):
                for y in range(1 << z):
                    r = app.handle(
                        "GET", f"/tiles/default/{z}/{x}/{y}.json?as_of=1250")
                    if r[0] == 200:
                        url = f"/tiles/default/{z}/{x}/{y}.json?as_of=1250"
                        good = r[2]
                        break
                if url:
                    break
            if url:
                break
        assert url is not None
        all_before = app.handle("GET", "/tiles/default/2/0/1.json")
        bdir = os.path.join(root, read_current(root)["base"],
                            tb.BUCKETS_DIRNAME, "bucket-1000-1200")
        levels = [f for f in os.listdir(bdir) if f.endswith(".npz")]
        with open(os.path.join(bdir, levels[0]), "wb") as f:
            f.write(b"torn")
        app.store.reload()  # bump the generation -> entry goes stale
        r = app.handle("GET", url)
        assert r[0] == 200 and r[2] == good and r[5] == "stale"
        assert "render" in app.degraded_causes()
        # all-time serving never touches buckets
        r_all = app.handle("GET", "/tiles/default/2/0/1.json")
        assert r_all[0] == all_before[0] and r_all[2] == all_before[2]
        # a cold key (no last-good bytes) is a typed 503, never a 500
        r_cold = app.handle("GET",
                            "/tiles/default/1/1/1.json?as_of=1250")
        assert r_cold[0] in (404, 503)

    def test_torn_bucket_quarantined_by_sweep(self, scenario, tmp_path):
        from heatmap_tpu.delta import recover

        root = str(tmp_path / "store")
        shutil.copytree(scenario["root"], root)
        bdir = os.path.join(root, read_current(root)["base"],
                            tb.BUCKETS_DIRNAME, "bucket-1300-1400")
        levels = [f for f in os.listdir(bdir) if f.endswith(".npz")]
        with open(os.path.join(bdir, levels[0]), "wb") as f:
            f.write(b"torn")
        items = recover.sweep(root)["quarantined"]
        torn = [i for i in items if i["reason"] == "torn_bucket"]
        assert len(torn) == 1
        assert not os.path.isdir(bdir)  # moved into quarantine
        # fold over the quarantined bucket now raises (serve maps this
        # to stale-if-error); the all-time overlay still loads
        with pytest.raises(TornBucketError):
            tfold.fold_levels(root, tfold.select_fold(root, window=300.0))
        assert load_overlay_levels(root)


class TestBucketRoll:
    def test_roll_invalidates_exactly_the_retiring_keys(self, scenario,
                                                        tmp_path):
        from heatmap_tpu.delta.compute import affected_tile_keys
        from heatmap_tpu.ingest.loop import _roll_windows

        root = str(tmp_path / "store")
        shutil.copytree(scenario["root"], root)
        cache = TileCache()
        holder: list = []
        assert _roll_windows(root, cache, holder) == 0  # primes prev
        assert holder == [1600.0]
        cache.note_window_param("150")

        cur = read_current(root)
        bdir = os.path.join(root, cur["base"], tb.BUCKETS_DIRNAME,
                            "bucket-1400-1500")
        retiring = sorted(affected_tile_keys(LevelArraysSink.load(bdir)))
        doomed = tuple(retiring[0]) + ("w", "150")
        survivor_window = ("not-a-real-tile", 9, 9, 9, "json", "w", "150")
        survivor_token = tuple(retiring[0]) + ("t", "sometoken")
        for key in (doomed, survivor_window, survivor_token):
            cache.get_or_render(key, 0, lambda: b"x")

        # advance the newest edge 1600 -> 1700: window=150's trailing
        # edge sweeps (1450, 1550], retiring bucket-1400-1500
        delta.apply_batch(root, delta.ColumnsSource(_batch(7, 1610, n=10)),
                          CONFIG)
        n = _roll_windows(root, cache, holder)
        assert holder == [1700.0]
        assert n >= 1
        assert cache.get_or_render(doomed, 0, lambda: b"re")[1] is False
        assert cache.get_or_render(survivor_window, 0,
                                   lambda: b"re")[1] is True
        assert cache.get_or_render(survivor_token, 0,
                                   lambda: b"re")[1] is True


class TestTimeQuery:
    def _brute_growth(self, root: str, *, zoom: int, window: float):
        """Independent oracle: per-cell exact growth from the raw
        bucket/live level rows — newer-half sum minus older-half sum
        over the slot edges, no wavelets anywhere."""
        sel = tfold.select_fold(root, window=window)
        cur = read_current(root)
        base = cur.get("base")
        units = [(os.path.join(root, base, tb.BUCKETS_DIRNAME, b["name"]),
                  float(b["t1"])) for b in sel.buckets]
        units += [(os.path.join(root, u["artifact"]), u["t1"])
                  for u in sel.live]
        mid = sel.ref - window / 2.0
        acc: dict = {}
        for d, t1 in units:
            loaded = LevelArraysSink.load(d)
            lvl = loaded.get(zoom)
            if lvl is None:
                continue
            keep = ((np.asarray(lvl["user"], str) == "all")
                    & (np.asarray(lvl["timespan"], str) == "alltime"))
            sign = 1.0 if t1 > mid else -1.0
            for r, c, v in zip(np.asarray(lvl["row"])[keep],
                               np.asarray(lvl["col"])[keep],
                               np.asarray(lvl["value"])[keep]):
                acc[(int(r), int(c))] = acc.get((int(r), int(c)), 0.0) \
                    + sign * float(v)
        return acc

    def test_bound_is_sound_and_full_budget_exact(self, scenario):
        doc = timequery.topk_growth(
            scenario["root"], user="all", timespan="alltime", zoom=8,
            window=300.0, k=10, coeffs=2)
        oracle = self._brute_growth(scenario["root"], zoom=8,
                                    window=300.0)
        assert doc["cells"]
        for cell in doc["cells"]:
            exact = oracle.get((cell["row"], cell["col"]), 0.0)
            assert abs(cell["growth"] - exact) <= cell["bound"] + 1e-12
        full = timequery.topk_growth(
            scenario["root"], user="all", timespan="alltime", zoom=8,
            window=300.0, k=10, coeffs=64)
        assert full["max_err"] == 0.0
        for cell in full["cells"]:
            assert cell["growth"] == oracle[(cell["row"], cell["col"])]

    def test_query_endpoint(self, scenario):
        app = ServeApp(TileStore(f"delta:{scenario['root']}"),
                       TileCache())
        r = app.handle(
            "GET", "/query?op=topk_growth&layer=default&z=8&window=300&k=5")
        assert r[0] == 200
        doc = json.loads(r[2])
        assert doc["op"] == "topk_growth" and len(doc["cells"]) == 5
        assert r[3].startswith('"q-')
        assert "X-Heatmap-Query-Error" in (r.headers or {})
        r2 = app.handle(
            "GET", "/query?op=topk_growth&layer=default&z=8&window=300&k=5")
        assert r2[5] == "hit"
        r400 = app.handle("GET", "/query?op=topk_growth&layer=default&z=8")
        assert r400[0] == 400
        assert "window" in json.loads(r400[2])["detail"]

    def test_haar_roundtrip_exact_on_integers(self):
        from heatmap_tpu.synopsis.transform import haar1d_np, inv_haar1d_np

        rng = np.random.default_rng(3)
        x = rng.integers(0, 1000, size=(5, 16)).astype(np.float64)
        assert (inv_haar1d_np(haar1d_np(x)) == x).all()


@pytest.fixture(scope="module")
def retract_scenario(tmp_path_factory):
    """Two twin stores: A gets alice+bob then a predicate retraction of
    alice; B only ever sees bob (the clean survivor recompute)."""
    tp = tmp_path_factory.mktemp("retract")
    roots = {"A": str(tp / "A"), "B": str(tp / "B")}
    for r in roots.values():
        os.makedirs(r)
        tfold.ensure_config(r, **TCFG)
    for i, t0 in enumerate([1000, 1150]):
        b = _batch(i, t0)
        delta.apply_batch(roots["A"], delta.ColumnsSource(b), CONFIG)
        keep = [j for j, u in enumerate(b["user_id"]) if u != "alice"]
        bb = {k: ([v[j] for j in keep] if isinstance(v, list)
                  else np.asarray(v)[keep]) for k, v in b.items()}
        delta.apply_batch(roots["B"], delta.ColumnsSource(bb), CONFIG)
    summary = retract_predicate(roots["A"], parse_where(["user=alice"]))
    return {"roots": roots, "summary": summary}


class TestRetraction:
    def test_counter_batches_land_per_bucket(self, retract_scenario):
        s = retract_scenario["summary"]
        assert s["rows"] == 40  # 20 alice rows per batch
        assert s["batches"] == 2  # one per temporal bucket
        assert s["scanned"] == 80

    def test_byte_identical_to_survivor_recompute(self, retract_scenario):
        roots = retract_scenario["roots"]
        assert _levelbytes(load_overlay_levels(roots["A"])) == \
            _levelbytes(load_overlay_levels(roots["B"]))

    def test_idempotent_rerun_applies_nothing(self, retract_scenario):
        roots = retract_scenario["roots"]
        digest = _tree_digest(roots["A"])
        again = retract_predicate(roots["A"], parse_where(["user=alice"]))
        assert again["rows"] == 0 and again["batches"] == 0
        assert _tree_digest(roots["A"]) == digest

    def test_identity_holds_after_compaction(self, retract_scenario):
        roots = retract_scenario["roots"]
        delta.compact(roots["A"], retention=10)
        delta.compact(roots["B"], retention=10)
        assert _base_file_hashes(roots["A"]) == _base_file_hashes(
            roots["B"])
        # temporal folds converge too: the counter-batches landed in
        # the same buckets as the rows they removed
        for kw in ({"as_of": 1100}, {"window": 150.0}):
            fa = tfold.fold_levels(roots["A"],
                                   tfold.select_fold(roots["A"], **kw))
            fb = tfold.fold_levels(roots["B"],
                                   tfold.select_fold(roots["B"], **kw))
            assert _levelbytes(fa) == _levelbytes(fb)

    def test_where_parsing(self):
        assert parse_where(["user=alice"]) == {"user_id": "alice"}
        assert parse_where(["layer=x", "source=gps"]) == {
            "user_id": "x", "source": "gps"}
        with pytest.raises(ValueError, match="column=value"):
            parse_where(["nonsense"])
        with pytest.raises(ValueError, match="not a point column"):
            parse_where(["zoom=3"])
        with pytest.raises(ValueError, match="at least one"):
            parse_where([])

    def test_unpinned_store_refuses(self, tmp_path):
        root = str(tmp_path / "empty")
        with pytest.raises(ValueError, match="no pinned config"):
            retract_predicate(root, parse_where(["user=alice"]))
