"""The on-chip evidence machinery itself (tools/onchip_runner.py
helpers, bench.py last-TPU persistence): every hardware measurement
flows through these, so a bug here silently corrupts or discards a
round's evidence. All CPU-testable."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_runner():
    spec = importlib.util.spec_from_file_location(
        "onchip_runner", os.path.join(REPO, "tools", "onchip_runner.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


runner = _load_runner()


def test_last_json_ignores_previous_attempts(tmp_path):
    log = tmp_path / "item.log"
    log.write_text(
        "===== attempt at 2026-07-31 01:00:00 =====\n"
        + json.dumps({"device": "tpu", "value": 111}) + "\n"
        + "===== attempt at 2026-07-31 02:00:00 =====\n"
        + "some warning line\n"
    )
    # The stale success line from attempt 1 must not satisfy the check.
    assert runner._last_json_with(str(log), "device") is None
    assert runner._check_bench(str(log)) is False


def test_last_json_takes_last_matching_line(tmp_path):
    log = tmp_path / "item.log"
    log.write_text(
        "===== attempt at 2026-07-31 02:00:00 =====\n"
        + json.dumps({"device": "cpu", "value": 1}) + "\n"
        + json.dumps({"device": "tpu", "value": 2}) + "\n"
        + "{torn json\n"
    )
    rec = runner._last_json_with(str(log), "device")
    assert rec == {"device": "tpu", "value": 2}
    assert runner._check_bench(str(log)) is True


def test_check_bench_rejects_cpu_fallback_notes(tmp_path):
    log = tmp_path / "item.log"
    log.write_text(
        "===== attempt at x =====\n"
        + json.dumps({"device": "tpu", "note": "tpu-unavailable"}) + "\n"
    )
    # A noted fallback must not count as on-chip evidence.
    assert runner._check_bench(str(log)) is False


def test_done_json_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setattr(runner, "STATE_DIR", str(tmp_path))
    runner.save_done({"bench": {"at": "now"}})
    assert runner.load_done() == {"bench": {"at": "now"}}
    # Corrupt file -> empty dict, not a crash.
    (tmp_path / "done.json").write_text("{torn")
    assert runner.load_done() == {}


def test_bench_last_tpu_roundtrip(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.chdir(tmp_path)
    assert bench._load_last_tpu() is None
    bench._save_last_tpu({"value": 123, "unit": "points/sec",
                          "device": "tpu"})
    rec = bench._load_last_tpu()
    assert rec["value"] == 123 and "measured" in rec
    # A record with the wrong unit (corrupt/foreign file) is rejected.
    with open(bench._LAST_TPU_PATH, "w") as f:
        json.dump({"value": 1, "unit": "bananas"}, f)
    assert bench._load_last_tpu() is None


def test_runlist_items_reference_existing_tools():
    for item in runner.runlist():
        script = item["cmd"][1]
        if script.endswith(".py") and script != sys.executable:
            assert os.path.exists(os.path.join(REPO, script)), script


def _load_decisions():
    spec = importlib.util.spec_from_file_location(
        "apply_decisions", os.path.join(REPO, "tools", "apply_decisions.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_decision_rules_fire_on_synthetic_evidence(tmp_path, capsys, monkeypatch):
    dec = _load_decisions()
    with open(tmp_path / "sweep.jsonl", "w") as f:
        for rec in [
            {"config": "xla-scatter weighted", "ms": 400.0},
            {"config": "partitioned weighted k=8", "ms": 300.0},
            {"config": "cascade-pyramid16 scatter", "ms": 5000.0},
            {"config": "cascade-pyramid16 partitioned", "ms": 1000.0},
            {"config": "cascade-pyramid16 partitioned k=4", "ms": 800.0},
            {"config": "partitioned bc=65536 chunk=1024 bf=8 k=8", "ms": 197.0},
            {"config": "partitioned bc=65536 chunk=1024 bf=128 k=8", "ms": 180.0},
            {"check": "stream", "backend": "auto", "batch": 262144,
             "device": "tpu", "pts_per_s": 100e6, "steps_per_s": 380.0},
            {"check": "stream", "backend": "pallas", "batch": 262144,
             "device": "tpu", "pts_per_s": 150e6, "steps_per_s": 570.0},
        ]:
            f.write(json.dumps(rec) + "\n")
    epoch = dec._verify_epoch()
    with open(tmp_path / "verify.jsonl", "w") as f:
        # Current-epoch verdicts gate the flip; a legacy un-prefixed
        # FALSE line must be ignored as stale rather than blocking.
        f.write(json.dumps({f"{epoch}|seg-clustered|{{}}": True}) + "\n")
        f.write(json.dumps({f"{epoch}|seg-pileup|{{}}": True}) + "\n")
        f.write(json.dumps({"seg-clustered|{}": False}) + "\n")
    monkeypatch.setattr(sys, "argv",
                        ["apply_decisions", "--state-dir", str(tmp_path)])
    dec.main()
    lines = [json.loads(line) for line in
             capsys.readouterr().out.strip().splitlines()]
    by = {r["decision"]: r for r in lines}
    # These two winners are committed repo defaults now, so the rules
    # report them "applied" rather than as forever-pending FLIPs.
    assert by["weighted-routing"]["verdict"].startswith("applied")
    assert by["weighted-routing"]["repo_default"] == "partitioned"
    assert "partitioned k=4" in by["cascade-backend"]["verdict"]
    assert by["cascade-backend"]["verdict"].startswith("applied")
    assert "128" in by["bad-frac-default"]["verdict"]
    assert by["bad-frac-default"]["verdict"].startswith("applied")
    # Stream rule: a pinned backend >10% over auto flips the default;
    # CPU rows must never count as on-chip evidence.
    assert "pallas" in by["stream-backend"]["verdict"]
    assert by["stream-backend"]["onchip_rows"] == 2


def test_decision_rules_block_on_failed_verify(tmp_path, capsys, monkeypatch):
    dec = _load_decisions()
    with open(tmp_path / "sweep.jsonl", "w") as f:
        f.write(json.dumps({"config": "cascade-pyramid16 scatter",
                            "ms": 5000.0}) + "\n")
        f.write(json.dumps({"config": "cascade-pyramid16 partitioned",
                            "ms": 1000.0}) + "\n")
    epoch = dec._verify_epoch()
    with open(tmp_path / "verify.jsonl", "w") as f:
        f.write(json.dumps({f"{epoch}|seg-clustered|{{}}": False}) + "\n")
    monkeypatch.setattr(sys, "argv",
                        ["apply_decisions", "--state-dir", str(tmp_path)])
    dec.main()
    lines = [json.loads(line) for line in
             capsys.readouterr().out.strip().splitlines()]
    by = {r["decision"]: r for r in lines}
    # A faster kernel that is not bit-exact must stay blocked.
    assert by["cascade-backend"]["verdict"].startswith("blocked")


def test_runlist_value_order():
    """Driver-visible artifacts first (a short relay window must land
    bench + the cascade A/B before the long sweeps), streaming last."""
    names = [item["name"] for item in runner.runlist()]
    assert names[0] == "bench"
    assert names[1] == "bench_job"
    assert names[-1] == "bench_stream"


def _load_verify():
    spec = importlib.util.spec_from_file_location(
        "verify_partitioned_onchip",
        os.path.join(REPO, "tools", "verify_partitioned_onchip.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_verify_transient_classification():
    """Transient = transport exception types or a gRPC status-code
    message PREFIX — not a substring anywhere (a kernel assertion about
    a 'connection matrix' must not read as a network blip)."""
    v = _load_verify()
    assert v._is_transient(
        RuntimeError("UNAVAILABLE: TPU worker process crashed or restarted"))
    assert v._is_transient(RuntimeError("DEADLINE_EXCEEDED: rpc"))
    assert v._is_transient(ConnectionError("relay dropped"))
    assert v._is_transient(TimeoutError("init"))
    assert not v._is_transient(
        ValueError("bad connection matrix in kernel layout"))
    assert not v._is_transient(RuntimeError("Mosaic failed to legalize"))


def test_verify_transient_skip_leaves_combo_unsettled(tmp_path):
    """An injected transient failure is retried, never settled into
    state, and drives a DISTINCT nonzero rc (4 — outside the runner's
    ok_rcs (0, 3)) so partial coverage cannot read as verified."""
    v = _load_verify()
    v.TRANSIENT_SKIPS = 0
    state_path = str(tmp_path / "verify.jsonl")
    state = {}

    def boom():
        raise RuntimeError("UNAVAILABLE: TPU worker process crashed")

    assert v._run_combo(state_path, state, "seg-x|{}", boom) is None
    assert v.TRANSIENT_SKIPS == 1
    assert state == {}  # unsettled: the next resume retries it
    assert not os.path.exists(state_path) or not open(state_path).read()
    assert v._final_rc(0, 0, v.TRANSIENT_SKIPS) == 4
    assert v._verdict(0, 0, v.TRANSIENT_SKIPS) == "UNSETTLED"
    # rc 4 must not be accepted by the runner's verify item.
    item = next(it for it in runner.runlist()
                if it["name"] == "verify_partitioned")
    assert 4 not in item.get("ok_rcs", (0,))
    # Deterministic failures ARE settled (and rc 3, retry-proof).
    def det():
        raise ValueError("Mosaic failed to legalize operation")

    v.TRANSIENT_SKIPS = 0
    assert v._run_combo(state_path, state, "seg-y|{}", det) is None
    assert v.TRANSIENT_SKIPS == 0
    assert state[f"{v.EPOCH}|seg-y|{{}}"].startswith("error:")
    assert v._final_rc(0, 1, 0) == 3
    assert v._final_rc(1, 1, 1) == 1  # mismatch dominates


def test_runner_requeues_verify_on_epoch_change():
    """A done.json verify entry recorded under a different kernel epoch
    is stale — the runner must re-queue the item, not skip it."""
    items = runner.runlist()
    epoch = runner.current_epoch()
    done = {it["name"]: {"at": "now", "epoch": epoch} for it in items}
    assert runner.build_queue(items, done, epoch) == []
    done["verify_partitioned"]["epoch"] = "0" * 10
    stale = runner.build_queue(items, done, epoch)
    assert [it["name"] for it in stale] == ["verify_partitioned"]
    # Epoch-insensitive items never re-queue on epoch drift alone.
    done["verify_partitioned"]["epoch"] = epoch
    done["bench"] = {"at": "now"}
    assert runner.build_queue(items, done, "f" * 10) == [
        it for it in items if it.get("epoch")]


def test_epoch_shared_between_tools():
    """runner, verify tool, and apply_decisions must agree on the
    epoch, or a re-verified kernel looks stale to the gate forever."""
    v = _load_verify()
    dec = _load_decisions()
    assert runner.current_epoch() == v.EPOCH == dec._verify_epoch()


def test_check_stream_passes_on_any_good_row(tmp_path):
    """A trailing error row (pallas not compiling on some backends is
    expected) must not fail an attempt whose other cells landed."""
    log = tmp_path / "bench_stream.log"
    log.write_text(
        "===== attempt at now =====\n"
        '{"check": "stream", "backend": "xla", "batch": 1, '
        '"device": "tpu", "pts_per_s": 1.0}\n'
        '{"check": "stream", "backend": "pallas", "batch": 1, '
        '"device": "tpu", "error": "Mosaic"}\n'
    )
    assert runner._check_stream(str(log)) is True
    # CPU-only rows or all-error attempts still fail.
    log.write_text(
        "===== attempt at now =====\n"
        '{"check": "stream", "backend": "xla", "batch": 1, '
        '"device": "cpu", "pts_per_s": 1.0}\n'
    )
    assert runner._check_stream(str(log)) is False
