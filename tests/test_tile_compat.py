"""API-parity tests for the scalar Tile compatibility class."""

import numpy as np

from heatmap_tpu.tilemath import Tile
import oracle


def test_classmethod_surface():
    for name in (
        "tile_id_from_lat_long",
        "tile_from_tile_id",
        "tile_id_from_row_column",
        "decode_tile_id",
        "tile_ids_for_all_zoom_levels",
        "row_from_latitude",
        "column_from_longitude",
        "latitude_from_row",
        "longitude_from_column",
    ):
        assert callable(getattr(Tile, name)), name
    assert Tile.MAX_ZOOM == 16 and Tile.MIN_ZOOM == 0


def test_tile_id_matches_oracle():
    rng = np.random.default_rng(0)
    for la, lo in zip(rng.uniform(-85, 85, 100), rng.uniform(-180, 180, 100)):
        for z in (0, 7, 16, 21):
            assert Tile.tile_id_from_lat_long(la, lo, z) == oracle.tile_id(la, lo, z)


def test_tile_from_tile_id_fields():
    t = Tile.tile_from_tile_id("10_397_163")
    assert (t.zoom, t.row, t.column) == (10, 397, 163)
    exp_lat, exp_lon, _ = oracle.tile_center("10_397_163")
    assert t.center_latitude == exp_lat
    assert t.center_longitude == exp_lon
    assert t.latitude_north > t.center_latitude > t.latitude_south
    assert t.longitude_west < t.center_longitude < t.longitude_east
    assert Tile.tile_from_tile_id("malformed") is None
    assert Tile.tile_from_tile_id("1_2") is None


def test_decode_tile_id():
    assert Tile.decode_tile_id("5_10_20") == {
        "id": "5_10_20",
        "zoom": 5,
        "row": 10,
        "column": 20,
    }
    assert Tile.decode_tile_id("nope") is None


def test_parent_and_children_roundtrip():
    t = Tile.tile_from_tile_id("10_397_163")
    assert t.parent_id() == "9_198_81"
    p = t.parent()
    assert (p.row, p.column) == (t.row >> 1, t.column >> 1)
    kids = t.children()
    assert len(kids) == 4
    for kid in kids:
        kt = Tile.tile_from_tile_id(kid)
        assert kt.zoom == 11
        assert (kt.row >> 1, kt.column >> 1) == (t.row, t.column)


def test_tile_ids_for_all_zoom_levels_excludes_zoom0():
    ids = Tile.tile_ids_for_all_zoom_levels("16_25000_11000")
    assert len(ids) == 16  # zooms 16..1, zoom 0 excluded (reference quirk)
    assert ids[0].startswith("16_")
    assert ids[-1].startswith("1_")
