"""Tile-serving subsystem tests: store, cache, render, HTTP, live.

Tier-1 throughout: CPU backend, loopback sockets only (in-process
ThreadingHTTPServer on an ephemeral port), and artifacts produced by
the real batch pipeline so the serving path is tested against exactly
what jobs write — including the byte-identity contract between
``GET .../{z}/{x}/{y}.json`` and the blob-sink JSON for the same tile.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from heatmap_tpu.serve import ServeApp, TileCache, TileStore, serve_in_thread
from heatmap_tpu.serve.render import tile_array, tile_json_bytes, tile_png_bytes
from heatmap_tpu.serve.store import Layer, Level
from heatmap_tpu.tilemath.morton import morton_encode_np


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """One small batch job, egressed BOTH ways: columnar arrays and
    jsonl blobs (same points, so the two stores must serve identical
    JSON documents)."""
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("serve_artifacts")
    config = BatchJobConfig(detail_zoom=10, min_detail_zoom=5)
    blobs = None
    for spec in (f"arrays:{root}/levels", f"jsonl:{root}/blobs.jsonl"):
        with open_sink(spec) as sink:
            out = run_job(open_source("synthetic:3000:7"), sink, config)
            if spec.startswith("jsonl:"):
                blobs = out
    assert blobs
    return {"arrays": f"arrays:{root}/levels",
            "jsonl": f"jsonl:{root}/blobs.jsonl",
            "path": root}


def _blob_docs(jsonl_path):
    docs = {}
    with open(jsonl_path) as f:
        for line in f:
            if line.strip():
                rec = json.loads(line)
                docs[rec["id"]] = rec["heatmap"]
    return docs


class TestTileStore:
    def test_layers_and_default_alias(self, artifacts):
        sa = TileStore(artifacts["arrays"])
        sj = TileStore(artifacts["jsonl"])
        assert sa.layer_names() == sj.layer_names()
        assert "default" in sa.layer_names()
        assert sa.layer("default").user == "all"
        assert sa.layer("default").timespan == "alltime"
        # default is an alias, not a copy
        assert sa.layer("default") is sa.layer("all|alltime")

    def test_layer_selection_and_unknown_selector(self, artifacts):
        store = TileStore(artifacts["arrays"],
                          layers={"heat": "all|alltime"})
        assert store.layer_names() == ["heat"]
        with pytest.raises(ValueError, match="no-such-user"):
            TileStore(artifacts["arrays"], layers={"x": "no-such-user"})

    def test_unknown_store_kind_is_one_line_error(self, tmp_path):
        with pytest.raises(ValueError, match="arrays, jsonl, dir"):
            TileStore(f"arras:{tmp_path}")

    def test_reload_bumps_generation(self, artifacts):
        store = TileStore(artifacts["arrays"])
        g0 = store.generation
        assert store.reload() == g0 + 1
        assert store.generation == g0 + 1

    def test_level_range_is_the_morton_contract(self, artifacts):
        """Every value under a coarse tile is in [code<<2d,(code+1)<<2d)
        — the searchsorted range must reproduce a brute-force scan."""
        layer = TileStore(artifacts["arrays"]).layer("default")
        d = layer.detail_zooms[-1]
        level = layer.levels[d]
        delta = layer.result_delta
        coarse = int(level.codes[len(level) // 2]) >> (2 * delta)
        codes, values = level.range(coarse << (2 * delta),
                                    (coarse + 1) << (2 * delta))
        mask = (level.codes >> (2 * delta)) == coarse
        np.testing.assert_array_equal(codes, level.codes[mask])
        np.testing.assert_array_equal(values, level.values[mask])
        assert len(codes) > 0

    def test_multihost_shard_dirs_merge(self, tmp_path):
        """arrays: pointed at a dir of host*/ shards loads the merged
        pyramid — total mass is the sum of the shards'."""
        from heatmap_tpu.io import open_sink, open_source
        from heatmap_tpu.pipeline import BatchJobConfig, run_job

        config = BatchJobConfig(detail_zoom=8, min_detail_zoom=5)
        masses = []
        for host in ("host000", "host001"):
            with open_sink(f"arrays:{tmp_path}/{host}") as sink:
                run_job(open_source(f"synthetic:500:{len(masses)}"),
                        sink, config)
            masses.append(sum(
                TileStore(f"arrays:{tmp_path}/{host}")
                .layer("default").levels[8].values.sum() for _ in (0,)))
        merged = TileStore(f"arrays:{tmp_path}")
        got = merged.layer("default").levels[8].values.sum()
        assert got == pytest.approx(sum(masses))


class TestRenderJSON:
    def test_every_blob_byte_matches_both_stores(self, artifacts):
        """THE serving parity contract: the JSON endpoint's bytes for a
        stored tile equal the blob-sink JSON document, whether the
        store loaded columnar arrays or the blob records themselves."""
        sa = TileStore(artifacts["arrays"])
        sj = TileStore(artifacts["jsonl"])
        docs = _blob_docs(f"{artifacts['path']}/blobs.jsonl")
        assert docs
        checked = 0
        for blob_id, raw in docs.items():
            user, ts, tid = blob_id.split("|", 2)
            z, r, c = map(int, tid.split("_"))
            for store in (sa, sj):
                got = tile_json_bytes(store.layer(f"{user}|{ts}"), z, c, r)
                assert got == raw.encode(), (blob_id, store.kind)
            checked += 1
        assert checked == len(docs)

    def test_empty_tile_is_none(self, artifacts):
        layer = TileStore(artifacts["arrays"]).layer("default")
        # zoom-5 coarse grid corner: synthetic data is a Seattle-ish
        # cluster, so tile (5,0,0) (Arctic/antimeridian) is empty.
        assert tile_json_bytes(layer, 5, 0, 0) is None
        assert tile_png_bytes(layer, 5, 0, 0) is None


def _layer_with_level(zoom, rows, cols, values, delta=2):
    layer = Layer("u", "t", result_delta=delta)
    layer.levels[zoom] = Level(
        zoom,
        morton_encode_np(np.asarray(rows, np.int64),
                         np.asarray(cols, np.int64)),
        np.asarray(values, np.float64),
    )
    return layer


class TestSynthesizedZooms:
    """Hand-built single-level layers make every synthesis path exact
    and checkable: rollup (finer source), quadrant upsample (coarser
    source), ancestor fill (tile inside one stored cell)."""

    def test_rollup_conserves_and_places_mass(self):
        # Stored detail zoom 6; request tile (z=2, x=1, y=1) at delta 2
        # -> want zoom 4, rollup shift 2 zooms. Zoom-6 rows/cols 16..31
        # live under zoom-2 tile (1,1), whose 4x4 want-zoom raster
        # covers zoom-4 rows/cols 4..7.
        layer = _layer_with_level(
            6, rows=[16, 17, 21], cols=[16, 16, 21], values=[1.0, 2.0, 4.0])
        raster, src = tile_array(layer, 2, 1, 1)
        assert src == 6
        # zoom-6 (16..17,16)>>2 -> zoom-4 (4,4) -> raster (0,0);
        # zoom-6 (21,21)>>2    -> zoom-4 (5,5) -> raster (1,1)
        expected = np.zeros((4, 4))
        expected[0, 0] = 3.0
        expected[1, 1] = 4.0
        np.testing.assert_array_equal(raster, expected)

    def test_exact_zoom_matches_rollup_of_itself(self):
        layer = _layer_with_level(
            4, rows=[8, 9], cols=[8, 11], values=[5.0, 7.0])
        raster, src = tile_array(layer, 2, 2, 2)
        assert src == 4
        expected = np.zeros((4, 4))
        expected[0, 0] = 5.0
        expected[1, 3] = 7.0
        np.testing.assert_array_equal(raster, expected)

    def test_quadrant_upsample_paints_blocks(self):
        # Stored zoom 4 only; request (z=1, x=0, y=0) -> want zoom 3,
        # source coarser path: side=2^(4-1)=8 > px=4? No: src>=z and
        # src<want requires src in (z, want); use delta 2, z=1, want=3,
        # src=... stored 2: side=2, k=2.
        layer = _layer_with_level(
            2, rows=[0, 1], cols=[0, 1], values=[3.0, 9.0])
        raster, src = tile_array(layer, 1, 0, 0)
        assert src == 2
        expected = np.kron(np.array([[3.0, 0.0], [0.0, 9.0]]),
                           np.ones((2, 2)))
        np.testing.assert_array_equal(raster, expected)

    def test_ancestor_fill(self):
        # Stored zoom 1; request z=3 (finer than stored): the whole
        # requested tile sits inside one stored cell.
        layer = _layer_with_level(1, rows=[1], cols=[0], values=[6.0])
        raster, src = tile_array(layer, 3, 1, 5)  # (3,5,1)>>2 == (1,1,0)
        assert src == 1
        assert (raster == 6.0).all()
        empty, _ = tile_array(layer, 3, 7, 1)  # under empty cell (1,0,1)
        assert empty is None


class TestTileCache:
    def test_lru_evicts_by_bytes(self):
        cache = TileCache(max_bytes=100)
        for i, key in enumerate(("a", "b", "c")):
            cache.get_or_render(key, 0, lambda: b"x" * 40)
        # 3*40 > 100 -> "a" (least recent) evicted
        assert len(cache) == 2
        _, hit = cache.get_or_render("b", 0, lambda: b"new")
        assert hit  # b survived
        _, hit = cache.get_or_render("a", 0, lambda: b"re-rendered")
        assert not hit

    def test_ttl_expiry_with_fake_clock(self):
        now = [0.0]
        cache = TileCache(max_bytes=1000, ttl_s=10.0, clock=lambda: now[0])
        cache.get_or_render("k", 0, lambda: b"v")
        now[0] = 9.9
        assert cache.get_or_render("k", 0, lambda: b"v2")[1] is True
        now[0] = 10.1
        value, hit = cache.get_or_render("k", 0, lambda: b"v2")
        assert (value, hit) == (b"v2", False)

    def test_generation_invalidates_lazily(self):
        cache = TileCache(max_bytes=1000)
        cache.get_or_render("k", 1, lambda: b"gen1")
        value, hit = cache.get_or_render("k", 2, lambda: b"gen2")
        assert (value, hit) == (b"gen2", False)

    def test_invalidate_keys_is_targeted(self):
        cache = TileCache(max_bytes=1000)
        for key in ("keep", "drop"):
            cache.get_or_render(key, 0, lambda: b"v")
        assert cache.invalidate_keys(["drop", "absent"]) == 1
        assert cache.get_or_render("keep", 0, lambda: b"")[1] is True
        assert cache.get_or_render("drop", 0, lambda: b"")[1] is False

    def test_single_flight_8_concurrent_first_requests_render_once(self):
        cache = TileCache(max_bytes=1000)
        renders = []
        gate = threading.Event()

        def render():
            renders.append(1)
            gate.wait(5)
            return b"tile-bytes"

        results = []
        threads = [threading.Thread(
            target=lambda: results.append(
                cache.get_or_render("tile", 0, render)))
            for _ in range(8)]
        for t in threads:
            t.start()
        # All 8 in flight against a cold key before the render finishes.
        for _ in range(100):
            if len(renders) == 1:
                break
            threading.Event().wait(0.01)
        gate.set()
        for t in threads:
            t.join(10)
        assert len(renders) == 1, "N concurrent misses must render once"
        assert len(results) == 8
        assert all(v == b"tile-bytes" for v, _ in results)
        assert sum(1 for _, hit in results if not hit) == 1

    def test_single_flight_error_propagates_and_is_not_cached(self):
        cache = TileCache(max_bytes=1000)

        def boom():
            raise RuntimeError("render failed")

        with pytest.raises(RuntimeError, match="render failed"):
            cache.get_or_render("k", 0, boom)
        value, hit = cache.get_or_render("k", 0, lambda: b"recovered")
        assert (value, hit) == (b"recovered", False)

    def test_zero_budget_disables_storage_not_dedup(self):
        cache = TileCache(max_bytes=0)
        cache.get_or_render("k", 0, lambda: b"v")
        assert len(cache) == 0
        assert cache.get_or_render("k", 0, lambda: b"v")[1] is False


@pytest.fixture()
def served(artifacts):
    from heatmap_tpu import obs

    obs.enable_metrics(True)  # /metrics is part of the surface under test
    store = TileStore(artifacts["arrays"])
    app = ServeApp(store, TileCache(max_bytes=1 << 20, ttl_s=None))
    server, base = serve_in_thread(app)
    yield app, base
    server.shutdown()
    server.server_close()


def _get(url, **headers):
    req = urllib.request.Request(url, headers=headers)
    try:
        resp = urllib.request.urlopen(req)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _pick_tile(app):
    layer = app.store.layer("default")
    d = layer.detail_zooms[-1]
    delta = layer.result_delta
    code = int(layer.levels[d].codes[0]) >> (2 * delta)
    from heatmap_tpu.tilemath.morton import morton_decode_np

    r, c = morton_decode_np(np.asarray([code], np.int64))
    return d - delta, int(c[0]), int(r[0])


@pytest.mark.usefixtures("served")
class TestHTTP:
    def test_json_200_etag_304_and_metrics(self, served):
        app, base = served
        z, x, y = _pick_tile(app)
        url = f"{base}/tiles/default/{z}/{x}/{y}.json"
        status, headers, body = _get(url)
        assert status == 200
        assert headers["Content-Type"] == "application/json"
        assert body == tile_json_bytes(app.store.layer("default"), z, x, y)
        etag = headers["ETag"]
        # ETag is stable across requests...
        status2, headers2, _ = _get(url)
        assert (status2, headers2["ETag"]) == (200, etag)
        # ...and revalidation is a 304 with an empty body.
        status3, headers3, body3 = _get(url, **{"If-None-Match": etag})
        assert (status3, body3) == (304, b"")
        assert headers3["ETag"] == etag
        # The revalidation shows up as a cache hit on /metrics.
        _, _, metrics = _get(f"{base}/metrics")
        text = metrics.decode()
        assert 'http_requests_total{route="tiles",status="304"} 1' in text
        hits = [l for l in text.splitlines()
                if l.startswith("tile_cache_hits_total")]
        assert hits and float(hits[0].split()[-1]) >= 2

    def test_png_bytes_match_direct_render(self, served):
        app, base = served
        z, x, y = _pick_tile(app)
        status, headers, body = _get(f"{base}/tiles/default/{z}/{x}/{y}.png")
        assert status == 200
        assert headers["Content-Type"] == "image/png"
        assert body[:8] == b"\x89PNG\r\n\x1a\n"
        assert body == tile_png_bytes(app.store.layer("default"), z, x, y)

    def test_404s(self, served):
        _, base = served
        for path in ("/tiles/nope/3/1/1.json",   # unknown layer
                     "/tiles/default/3/900/1.json",  # off-grid
                     "/tiles/default/5/0/0.json",    # empty tile
                     "/nothing-here"):
            status, _, body = _get(base + path)
            assert status == 404, path
            json.loads(body)  # error bodies are JSON

    def test_metrics_scrape_parses(self, served):
        import re

        app, base = served
        z, x, y = _pick_tile(app)
        _get(f"{base}/tiles/default/{z}/{x}/{y}.json")  # produce samples
        status, headers, body = _get(f"{base}/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        line_re = re.compile(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})?"
            r"\s[-+]?([0-9.eE+-]+|Inf|NaN)$")
        lines = body.decode().splitlines()
        assert lines
        for line in lines:
            if line.startswith("#"):
                assert line.startswith(("# HELP", "# TYPE"))
            else:
                assert line_re.match(line), line
        # Process-identity gauges are part of the default scrape:
        # uptime ticks forward and build_info carries the version label.
        text = body.decode()
        [uptime_line] = [l for l in text.splitlines()
                         if l.startswith("process_uptime_seconds ")]
        assert float(uptime_line.split()[-1]) > 0
        from heatmap_tpu import __version__

        assert (f'heatmap_build_info{{version="{__version__}"}} 1'
                in text)

    def test_healthz_and_reload(self, served):
        app, base = served
        status, _, body = _get(f"{base}/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert "default" in health["layers"]
        assert health["generation"] == 0
        req = urllib.request.Request(f"{base}/reload", method="POST",
                                     data=b"")
        resp = urllib.request.urlopen(req)
        assert json.loads(resp.read())["generation"] == 1
        assert app.store.generation == 1


class TestLiveLayer:
    def test_tick_serves_and_invalidates_targeted_keys(self, artifacts):
        from heatmap_tpu.ops import Window
        from heatmap_tpu.serve import LiveLayer
        from heatmap_tpu.streaming import HeatmapStream, StreamConfig
        from heatmap_tpu.tilemath.mercator import (latitude_from_row,
                                                   longitude_from_column)

        window = Window(zoom=8, row0=80, col0=40, height=8, width=8)
        stream = HeatmapStream(StreamConfig(window=window, half_life_s=60.0))
        layer = LiveLayer(stream, name="live")
        assert layer.result_delta == 5

        store = TileStore(artifacts["arrays"])
        app = ServeApp(store, TileCache(max_bytes=1 << 20))
        app.attach_layer("live", layer)
        assert "live" in app.layer_names()

        # Cold layer: the live tile over the window is empty (404-path).
        z, x, y = 3, 40 >> 5, 80 >> 5
        assert tile_json_bytes(layer, z, x, y) is None
        # Prime the cache with the empty result's sibling... then tick.
        lat = np.full(6, float(latitude_from_row(80.5, 8)))
        lon = np.full(6, float(longitude_from_column(40.5, 8)))
        keys = layer.tick(lat, lon, t=0.0)
        assert ("live", z, x, y, "json") in keys
        assert ("live", 8, 40, 80, "png") in keys
        # Zooms/tiles the batch never touched are not invalidated.
        assert not any(k[1] == 3 and (k[2], k[3]) != (x, y) for k in keys)
        app.cache.invalidate_keys(keys)
        body = tile_json_bytes(layer, z, x, y)
        doc = json.loads(body)
        assert doc == {"8_80_40": 6.0}
        # Attached layers survive a store reload...
        app.store.reload()
        assert app.layer("live") is layer
        # ...and serve through the HTTP app core.
        status, _, served_body, _, route, _ = app.handle(
            "GET", f"/tiles/live/{z}/{x}/{y}.json")
        assert (status, route) == (200, "tiles")
        assert served_body == body

    def test_decay_between_ticks(self):
        from heatmap_tpu.ops import Window
        from heatmap_tpu.serve import LiveLayer
        from heatmap_tpu.streaming import HeatmapStream, StreamConfig
        from heatmap_tpu.tilemath.mercator import (latitude_from_row,
                                                   longitude_from_column)

        window = Window(zoom=8, row0=80, col0=40, height=8, width=8)
        stream = HeatmapStream(StreamConfig(window=window, half_life_s=60.0))
        layer = LiveLayer(stream, name="live")
        lat = np.full(4, float(latitude_from_row(80.5, 8)))
        lon = np.full(4, float(longitude_from_column(40.5, 8)))
        layer.tick(lat, lon, t=0.0)
        layer.tick(lat[:0], lon[:0], t=60.0)  # one half-life, no points
        value = layer.levels[8].lookup(
            int(morton_encode_np(np.int64(80), np.int64(40))))
        assert value == pytest.approx(2.0, rel=1e-5)


class TestSinkSpecValidation:
    def test_typo_kind_is_one_line_valueerror(self):
        from heatmap_tpu.io import validate_sink_spec
        from heatmap_tpu.io.sinks import open_sink

        for fn in (validate_sink_spec, open_sink):
            with pytest.raises(ValueError) as ei:
                fn("josnl:x")
            msg = str(ei.value)
            assert "\n" not in msg
            for kind in ("jsonl", "arrays", "dir", "memory", "cassandra"):
                assert kind in msg

    def test_valid_specs_pass(self, tmp_path):
        from heatmap_tpu.io import validate_sink_spec

        for spec in ("jsonl:a.out", "arrays:d/", "dir:d/", "memory:",
                     "cassandra:", "bare.jsonl", "x.ndjson"):
            assert validate_sink_spec(spec) == spec

    def test_cli_rejects_at_parse_time(self, capsys):
        from heatmap_tpu.cli import build_parser

        with pytest.raises(SystemExit):
            build_parser().parse_args(
                ["run", "--input", "synthetic:10", "--output", "josnl:x"])
        err = capsys.readouterr().err
        assert "jsonl, arrays" in err
