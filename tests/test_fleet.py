"""Serve-fleet tests: rendezvous routing, circuit breakers, admission
control, failover, re-admission, rolling reload, and hedging.

Tier-1 throughout: CPU backend, loopback sockets only (in-process
ThreadingHTTPServer backends on ephemeral ports, or a thread-mode
FleetSupervisor), fake clocks for every breaker-timing assertion, and
the byte-equality pin: every document served through the fleet must be
identical — body and ETag — to the single-process ServeApp over the
same store.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from heatmap_tpu import faults, obs
from heatmap_tpu.serve import (
    BackendClient,
    CircuitBreaker,
    FleetSupervisor,
    RouterApp,
    ServeApp,
    TileCache,
    TileStore,
    rendezvous_order,
    route_key,
    serve_in_thread,
)


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """One small batch job egressed as a columnar arrays store — the
    shared ground truth every fleet in this file serves."""
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    root = tmp_path_factory.mktemp("fleet_artifacts")
    config = BatchJobConfig(detail_zoom=9, min_detail_zoom=5)
    with open_sink(f"arrays:{root}/levels") as sink:
        run_job(open_source("synthetic:2000:11"), sink, config)
    return f"arrays:{root}/levels"


def _get(url, **headers):
    req = urllib.request.Request(url, headers=headers)
    try:
        resp = urllib.request.urlopen(req)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _post(url):
    req = urllib.request.Request(url, method="POST")
    try:
        resp = urllib.request.urlopen(req)
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _tile_paths(store, limit=24):
    """A deterministic sample of tile request paths across zooms."""
    import numpy as np

    from heatmap_tpu.tilemath.morton import morton_decode_np

    paths = []
    layer = store.layer("default")
    delta = layer.result_delta
    for d in layer.detail_zooms:
        codes = np.unique(
            np.asarray(layer.levels[d].codes[:64], np.int64) >> (2 * delta))
        rows, cols = morton_decode_np(codes[:4])
        for r, c in zip(rows, cols):
            paths.append(
                f"/tiles/default/{d - delta}/{int(c)}/{int(r)}.json")
            if len(paths) >= limit:
                return paths
    return paths


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# -- rendezvous determinism -------------------------------------------------


class TestRendezvous:
    def test_placement_is_a_pure_function_of_key_and_ring(self):
        ring = [f"b{i}" for i in range(5)]
        for key in ("default/3/1/2", "default/9/100/7", "/healthz"):
            order = rendezvous_order(key, ring)
            assert sorted(order) == sorted(ring)
            # Same inputs, same ranking — regardless of input order.
            assert rendezvous_order(key, ring) == order
            assert rendezvous_order(key, list(reversed(ring))) == order

    def test_membership_change_moves_only_the_lost_backends_keys(self):
        n = 4
        ring = [f"b{i}" for i in range(n)]
        keys = [f"layer/{z}/{x}/{y}"
                for z in range(4) for x in range(8) for y in range(8)]
        owner_before = {k: rendezvous_order(k, ring)[0] for k in keys}
        removed = "b2"
        shrunk = [b for b in ring if b != removed]
        moved = 0
        for k in keys:
            after = rendezvous_order(k, shrunk)[0]
            if owner_before[k] == removed:
                moved += 1
            else:
                # HRW property: survivors keep every key they owned.
                assert after == owner_before[k]
        # Only the removed backend's share moves: ~1/N of the keys.
        assert moved / len(keys) <= 1.0 / n + 0.10

    def test_route_key_colocates_tile_formats(self):
        assert (route_key("/tiles/default/3/1/2.json")
                == route_key("/tiles/default/3/1/2.png")
                == "default/3/1/2")
        assert route_key("/healthz") == "/healthz"


# -- circuit breaker state machine ------------------------------------------


class TestCircuitBreaker:
    def test_threshold_edge_and_single_half_open_trial(self):
        clock = _FakeClock()
        br = CircuitBreaker("b0", fail_threshold=3, open_base_s=1.0,
                            clock=clock)
        assert br.admits() and br.state == CircuitBreaker.CLOSED
        assert br.record_failure() is False
        assert br.record_failure() is False
        assert br.admits()  # below threshold: still in the ring
        assert br.record_failure() is True  # the closed -> open edge
        assert not br.admits()
        assert br.state == CircuitBreaker.OPEN
        assert not br.admits_trial()  # cooldown not yet expired
        clock.t += 2.0
        assert br.state == CircuitBreaker.HALF_OPEN
        assert br.admits_trial()  # the single trial
        assert not br.admits_trial()  # ...is single
        assert not br.admits()  # regular traffic stays off
        # Trial success re-closes (True = the re-close edge).
        assert br.record_success() is True
        assert br.admits()
        assert br.record_success() is False  # steady state: no edge

    def test_failed_trial_reopens_silently_with_escalating_cooldown(self):
        clock = _FakeClock()
        br = CircuitBreaker("b0", fail_threshold=1, open_base_s=1.0,
                            open_cap_s=60.0, clock=clock)
        cooldowns = []
        assert br.record_failure() is True
        cooldowns.append(br._open_until - clock.t)
        for _ in range(2):
            clock.t = br._open_until
            assert br.admits_trial()
            # Half-open trial fails: re-open is silent (no edge).
            assert br.record_failure() is False
            cooldowns.append(br._open_until - clock.t)
        # Deterministic: episode i cooldown is base * 2**(i-1) with
        # seeded jitter in [0.5, 1.0) — the faults/retry.py shape.
        for episode, got in enumerate(cooldowns, start=1):
            jitter = 0.5 + 0.5 * faults.hash01(0, "breaker", "b0", episode)
            assert got == pytest.approx(1.0 * 2.0 ** (episode - 1) * jitter)
        assert cooldowns[2] > cooldowns[0]

    def test_force_opens_immediately(self):
        br = CircuitBreaker("b0", fail_threshold=5, clock=_FakeClock())
        assert br.record_failure(force=True) is True
        assert not br.admits()

    def test_success_resets_the_failure_streak(self):
        br = CircuitBreaker("b0", fail_threshold=3, clock=_FakeClock())
        for _ in range(4):
            assert br.record_failure() is False or pytest.fail(
                "streak should reset before the threshold")
            br.record_success()
        assert br.admits()


# -- ring membership events (edge-triggered pairs) --------------------------


class TestFleetEvents:
    def test_one_down_up_pair_per_outage(self, tmp_path):
        clock = _FakeClock()
        backend = BackendClient("b7", "127.0.0.1", 1,
                                breaker=CircuitBreaker(
                                    "b7", fail_threshold=2, clock=clock))
        router = RouterApp([backend], clock=clock)
        log = obs.EventLog(str(tmp_path / "events.jsonl"))
        obs.set_event_log(log)
        try:
            router.note_failure(backend, "connect", "refused")
            router.note_failure(backend, "connect", "refused")  # opens
            router.note_failure(backend, "connect", "refused")  # still open
            clock.t += 60.0
            assert backend.breaker.admits_trial()
            router.note_failure(backend, "probe")  # failed trial: silent
            clock.t += 120.0
            assert backend.breaker.admits_trial()
            router.note_success(backend)  # trial success: re-admitted
            router.note_success(backend)  # steady state: no second event
        finally:
            obs.set_event_log(None)
            log.close()
        events = [(e["event"], e["backend"]) for e in
                  obs.read_events(str(tmp_path / "events.jsonl"))
                  if e["event"].startswith("fleet_backend")]
        assert events == [("fleet_backend_down", "b7"),
                          ("fleet_backend_up", "b7")]


# -- single-backend admission + drain (ServeApp side) -----------------------


class TestServeAppAdmission:
    @pytest.fixture()
    def served(self, artifacts):
        app = ServeApp(TileStore(artifacts), TileCache(max_bytes=1 << 20),
                       max_inflight=4, retry_after_s=2.0)
        server, base = serve_in_thread(app)
        yield app, base
        server.shutdown()
        server.server_close()

    def test_shed_is_typed_503_with_retry_after(self, served, artifacts):
        app, base = served
        path = _tile_paths(app.store, limit=1)[0]
        app.max_inflight = 0  # saturate the bound without racing threads
        status, headers, body = _get(base + path)
        assert status == 503
        assert json.loads(body)["cause"] == "shed"
        # Seeded jitter spreads Retry-After over [0.5, 1.5) * nominal so
        # a synchronized shed doesn't re-stampede (serve/degrade.py).
        assert 1 <= int(headers["Retry-After"]) <= 3
        _, _, health = _get(f"{base}/healthz")
        health = json.loads(health)
        assert health["status"] == "degraded"
        assert "shed" in health["degraded"]
        app.max_inflight = 4
        status, _, _ = _get(base + path)
        assert status == 200  # and the admit clears the shed cause
        health = json.loads(_get(f"{base}/healthz")[2])
        assert health["status"] == "ok"

    def test_drain_undrain_roundtrip(self, served):
        app, base = served
        path = _tile_paths(app.store, limit=1)[0]
        status, body = _post(f"{base}/drain")
        assert (status, json.loads(body)["draining"]) == (200, True)
        status, headers, body = _get(base + path)
        assert (status, json.loads(body)["cause"]) == (503, "drain")
        assert "Retry-After" in headers
        health = json.loads(_get(f"{base}/healthz")[2])
        assert health["draining"] is True and "drain" in health["degraded"]
        status, body = _post(f"{base}/undrain")
        assert (status, json.loads(body)["draining"]) == (200, False)
        assert _get(base + path)[0] == 200


# -- the router over live thread backends -----------------------------------


@pytest.fixture()
def fleet3(artifacts):
    """Three ServeApps over the same store behind one RouterApp, plus
    the single-process reference app for byte-equality checks."""
    store = TileStore(artifacts)
    reference = ServeApp(store, TileCache(max_bytes=1 << 20))
    backends, servers = [], []
    for i in range(3):
        app = ServeApp(TileStore(artifacts), TileCache(max_bytes=1 << 20))
        server, base = serve_in_thread(app)
        host, port = base.rsplit("://", 1)[1].rsplit(":", 1)
        backends.append(BackendClient(f"b{i}", host, int(port)))
        servers.append(server)
    router = RouterApp(backends, probe_interval_s=0.05).start()
    server, base = serve_in_thread(router)
    yield {"router": router, "base": base, "reference": reference,
           "store": store, "backends": backends, "servers": servers}
    router.close()
    server.shutdown()
    server.server_close()
    for s in servers:
        s.shutdown()
        s.server_close()


class TestRouterByteEquality:
    def test_every_path_matches_the_single_process_app(self, fleet3):
        base, ref = fleet3["base"], fleet3["reference"]
        for path in _tile_paths(fleet3["store"]):
            want_status, want_ctype, want_body, want_etag, _, _ = (
                ref.handle("GET", path))
            status, headers, body = _get(base + path)
            assert (status, body) == (want_status, want_body), path
            assert headers["Content-Type"] == want_ctype
            assert headers["ETag"] == want_etag
            # Revalidation through the router is still a 304.
            status, headers, body = _get(
                base + path, **{"If-None-Match": want_etag})
            assert (status, body) == (304, b"")
            assert headers["ETag"] == want_etag

    def test_png_tiles_match_too(self, fleet3):
        base, ref = fleet3["base"], fleet3["reference"]
        path = _tile_paths(fleet3["store"], limit=1)[0].replace(
            ".json", ".png")
        want = ref.handle("GET", path)
        status, headers, body = _get(base + path)
        assert (status, body) == (want[0], want[2])
        assert headers["Content-Type"] == "image/png"

    def test_router_healthz_names_the_ring(self, fleet3):
        health = json.loads(_get(fleet3["base"] + "/healthz")[2])
        assert health["role"] == "router"
        assert sorted(health["fleet"]["eligible"]) == ["b0", "b1", "b2"]
        assert health["fleet"]["backends"]["b1"]["breaker"] == "closed"


class TestFailoverAndReadmission:
    def test_connection_failure_retries_next_replica(self, fleet3, tmp_path,
                                                     artifacts):
        base, ref, store = (fleet3["base"], fleet3["reference"],
                            fleet3["store"])
        log = obs.EventLog(str(tmp_path / "events.jsonl"))
        obs.set_event_log(log)
        try:
            victim = fleet3["backends"][0]
            fleet3["servers"][0].shutdown()
            fleet3["servers"][0].server_close()
            # Every request answers 200 even when rendezvous points at
            # the dead backend — one silent retry on the next replica.
            for path in _tile_paths(store):
                want = ref.handle("GET", path)
                status, _, body = _get(base + path)
                assert (status, body) == (want[0], want[2]), path
            # The failures tripped the victim's breaker out of the ring.
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                health = json.loads(_get(base + "/healthz")[2])
                if victim.id not in health["fleet"]["eligible"]:
                    break
                time.sleep(0.02)
            assert victim.id not in health["fleet"]["eligible"]
            # Revive it on a fresh port: the half-open probe re-admits.
            app = ServeApp(TileStore(artifacts), TileCache(max_bytes=1 << 20))
            server, vbase = serve_in_thread(app)
            fleet3["servers"][0] = server
            host, port = vbase.rsplit("://", 1)[1].rsplit(":", 1)
            victim.set_address(host, int(port))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                health = json.loads(_get(base + "/healthz")[2])
                if victim.id in health["fleet"]["eligible"]:
                    break
                time.sleep(0.02)
            assert victim.id in health["fleet"]["eligible"]
        finally:
            obs.set_event_log(None)
            log.close()
        events = [(e["event"], e["backend"]) for e in
                  obs.read_events(str(tmp_path / "events.jsonl"))
                  if e["event"].startswith("fleet_backend")]
        assert (events.count(("fleet_backend_down", victim.id)),
                events.count(("fleet_backend_up", victim.id))) == (1, 1)


class TestRollingReload:
    def test_reload_is_atomic_per_backend(self, fleet3):
        base = fleet3["base"]
        status, body = _post(f"{base}/reload")
        doc = json.loads(body)
        assert status == 200 and doc["ok"] is True
        assert all(doc["backends"][b]["ok"] for b in ("b0", "b1", "b2"))

    def test_failed_backend_keeps_last_good_and_is_ejected(self, fleet3):
        base, store, ref = (fleet3["base"], fleet3["store"],
                            fleet3["reference"])
        victim = fleet3["backends"][1]
        good_host, good_port = victim.address.rsplit(":", 1)
        victim.set_address("127.0.0.1", 1)  # unreachable: reload must fail
        status, body = _post(f"{base}/reload")
        doc = json.loads(body)
        assert status == 503 and doc["ok"] is False
        assert doc["backends"][victim.id]["ok"] is False
        health = json.loads(_get(base + "/healthz")[2])
        assert victim.id not in health["fleet"]["eligible"]
        assert (health["fleet"]["backends"][victim.id]["ejected"]
                == "reload_failed")
        # The ring still answers, byte-identical, without the victim.
        for path in _tile_paths(store, limit=6):
            want = ref.handle("GET", path)
            status, _, body = _get(base + path)
            assert (status, body) == (want[0], want[2])
        # Next successful rolling reload re-admits it.
        victim.set_address(good_host, int(good_port))
        status, body = _post(f"{base}/reload")
        assert (status, json.loads(body)["ok"]) == (200, True)
        health = json.loads(_get(base + "/healthz")[2])
        assert victim.id in health["fleet"]["eligible"]


class TestRouterAdmission:
    def test_empty_ring_is_typed_503_never_500(self, artifacts):
        backend = BackendClient("b0", "127.0.0.1", 1)
        backend.breaker.record_failure(force=True)
        router = RouterApp([backend])
        server, base = serve_in_thread(router)
        try:
            status, headers, body = _get(base + "/tiles/default/5/0/0.json")
            assert status == 503
            assert json.loads(body)["cause"] == "no_backends"
            assert "Retry-After" in headers
        finally:
            server.shutdown()
            server.server_close()

    def test_queue_deadline_overload_is_typed_503(self, fleet3):
        router = fleet3["router"]
        router.max_inflight = 0  # no slots: every request waits, then sheds
        router.queue_deadline_s = 0.05
        status, headers, body = _get(
            fleet3["base"] + _tile_paths(fleet3["store"], limit=1)[0])
        assert status == 503
        assert json.loads(body)["cause"] == "overload"
        assert "Retry-After" in headers


# -- hedged reads -----------------------------------------------------------


class _SlowFastPair:
    """Two one-trick HTTP servers: ``slow`` stalls until released,
    ``fast`` answers immediately — distinct bodies tell who won."""

    def __init__(self):
        self.release = threading.Event()
        pair = self

        class Slow(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                pair.release.wait(5.0)
                self._answer(b'{"who": "slow"}')

            def log_message(self, *a):
                pass

            def _answer(self, body):
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass  # hedge winner cancelled us mid-write

        class Fast(Slow):
            def do_GET(self):
                self._answer(b'{"who": "fast"}')

        self.slow_server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Slow)
        self.fast_server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), Fast)
        for s in (self.slow_server, self.fast_server):
            threading.Thread(target=s.serve_forever, daemon=True).start()

    def close(self):
        self.release.set()
        for s in (self.slow_server, self.fast_server):
            s.shutdown()
            s.server_close()


class TestHedging:
    def test_hedge_fires_past_the_latency_quantile_and_fast_wins(self):
        pair = _SlowFastPair()
        try:
            path = "/tiles/default/4/2/3.json"
            first, second = rendezvous_order(route_key(path), ["a", "b"])
            ports = {first: pair.slow_server.server_address[1],
                     second: pair.fast_server.server_address[1]}
            backends = [BackendClient(bid, "127.0.0.1", port)
                        for bid, port in ports.items()]
            router = RouterApp(backends, hedge_min_wait_s=0.01)
            for _ in range(64):  # arm the hedge trigger
                router._latency.record(0.002)
            status, _, body, _, _, _ = router.handle("GET", path)
            assert (status, json.loads(body)["who"]) == (200, "fast")
            # The cancelled slow attempt never fed its breaker.
            slow = next(b for b in backends if b.id == first)
            assert slow.breaker.state == CircuitBreaker.CLOSED
        finally:
            pair.close()


# -- thread-mode supervisor: crash, restart, re-admission -------------------


class TestSupervisorRestart:
    def test_killed_backend_returns_to_the_ring(self, artifacts, tmp_path):
        log = obs.EventLog(str(tmp_path / "events.jsonl"))
        obs.set_event_log(log)
        sup = FleetSupervisor(
            None, 2, mode="thread",
            store_factory=lambda: TileStore(artifacts),
            cache_bytes=1 << 20, probe_interval_s=0.05,
            restart_base_s=0.05, restart_cap_s=0.2,
            monitor_interval_s=0.02)
        try:
            sup.start()
            server, base = serve_in_thread(sup.router)
            store = TileStore(artifacts)
            reference = ServeApp(store, TileCache(max_bytes=1 << 20))
            paths = _tile_paths(store, limit=8)
            for path in paths:  # warm: the whole ring answers
                assert _get(base + path)[0] == 200
            sup.kill_backend("b0")
            # A thread-mode restart completes in well under a poll
            # interval, so the transient down is asserted through the
            # event log (persistent) rather than a /healthz race: wait
            # for the full down -> restart -> half-open-probe -> up
            # cycle, then for the ring to report whole.
            def cycle_done():
                kinds = [e["event"] for e in
                         obs.read_events(str(tmp_path / "events.jsonl"))
                         if e.get("backend") == "b0"]
                return ("fleet_backend_down" in kinds
                        and "fleet_backend_up" in kinds)

            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and not cycle_done():
                time.sleep(0.05)
            assert cycle_done(), "no down/up event pair for b0"
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                health = json.loads(_get(base + "/healthz")[2])
                if "b0" in health["fleet"]["eligible"]:
                    break
                time.sleep(0.05)
            assert "b0" in health["fleet"]["eligible"]
            for path in paths:  # byte-identical through the healed ring
                want = reference.handle("GET", path)
                status, _, body = _get(base + path)
                assert (status, body) == (want[0], want[2]), path
            server.shutdown()
            server.server_close()
        finally:
            sup.stop()
            obs.set_event_log(None)
            log.close()


# -- merged /metrics exposition ---------------------------------------------


class TestFleetMetricsMerge:
    """``/metrics?fleet=1`` must be ONE valid Prometheus document.

    The regression this pins: backend chunks naively appended after the
    router's own exposition repeat metric families (``http_requests_total``
    lives on the router shell AND on every backend), and strict
    text-format parsers reject families split into non-contiguous runs —
    effectively losing the router's own registry (``fleet_*``, its
    shell's request counter) on a real scrape. ``merge_expositions``
    regroups samples under one contiguous block per family.
    """

    BACKEND_TEXT = (
        "# HELP http_requests_total HTTP requests served\n"
        "# TYPE http_requests_total counter\n"
        'http_requests_total{route="tile",status="200"} 5\n'
        "# HELP serve_request_seconds Request latency\n"
        "# TYPE serve_request_seconds histogram\n"
        'serve_request_seconds_bucket{le="0.1"} 3\n'
        'serve_request_seconds_bucket{le="+Inf"} 5\n'
        "serve_request_seconds_sum 0.4\n"
        "serve_request_seconds_count 5\n"
    )

    @staticmethod
    def _scrape_parse(text):
        """Strict text-format walk: returns ``{family: [sample lines]}``
        and fails the test if any family appears in two separate runs —
        exactly the property a conforming scraper relies on."""
        runs: dict[str, list[str]] = {}
        histograms = set()
        current = None

        def enter(family):
            nonlocal current
            if family != current:
                assert family not in runs, (
                    f"family {family!r} split into non-contiguous runs")
                runs[family] = []
                current = family

        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split()
                assert parts[1] in ("HELP", "TYPE"), line
                if parts[1] == "TYPE" and parts[3] == "histogram":
                    histograms.add(parts[2])
                enter(parts[2])
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)]
                if name.endswith(suffix) and base in histograms:
                    family = base
            enter(family)
            runs[family].append(line)
        return runs

    def _router_with_fakes(self):
        from heatmap_tpu.serve.router import RouterApp

        text = self.BACKEND_TEXT

        class _Backend:
            def __init__(self, bid):
                self.id = bid

            def eligible(self):
                return True

            def fetch(self, method, path):
                return 200, {}, text.encode()

        router = RouterApp([])
        router.backends = {"b0": _Backend("b0"), "b1": _Backend("b1")}
        return router

    def test_merged_exposition_scrape_parses_with_router_registry(self):
        from heatmap_tpu.serve.http import HTTP_REQUESTS
        from heatmap_tpu.serve.router import FLEET_REQUESTS

        obs.enable_metrics(True)
        # Router-own samples that share a family with every backend
        # (http_requests_total) and one that exists only on the router.
        HTTP_REQUESTS.inc(route="metrics", status="200")
        FLEET_REQUESTS.inc(backend="b0", outcome="ok")
        router = self._router_with_fakes()
        status, ctype, body, *_ = router.handle("GET", "/metrics?fleet=1")
        assert status == 200 and ctype.startswith("text/plain")
        runs = self._scrape_parse(body.decode())

        # Router's own registry survives the merge un-relabeled...
        assert any('backend=' not in line
                   for line in runs["http_requests_total"])
        assert runs["fleet_requests_total"]
        # ...next to both backends' relabeled samples, in ONE run.
        for bid in ("b0", "b1"):
            assert any(f'backend="{bid}"' in line
                       for line in runs["http_requests_total"]), bid
            assert any(f'backend="{bid}"' in line
                       for line in runs["serve_request_seconds"]), bid
        # Histogram suffix series stay grouped under their family.
        kinds = {s.split("{", 1)[0].split(" ", 1)[0]
                 for s in runs["serve_request_seconds"]}
        assert {"serve_request_seconds_sum",
                "serve_request_seconds_count"} <= kinds

    def test_plain_metrics_unchanged_without_fleet_flag(self):
        obs.enable_metrics(True)
        from heatmap_tpu.serve.router import FLEET_REQUESTS

        FLEET_REQUESTS.inc(backend="b0", outcome="ok")
        router = self._router_with_fakes()
        status, _, body, *_ = router.handle("GET", "/metrics")
        assert status == 200
        assert b'backend="b0"' not in body or b"fleet_" in body
        # No backend scrape happened: the fake backends' histogram
        # family never appears.
        assert b"serve_request_seconds" not in body


class TestFleetTelemetryForwarding:
    """The supervisor forwards --telemetry-sample-interval/--watch to
    process-mode children the same way it forwards --slo, so the
    router's fleet-merged /series carries per-backend history."""

    def test_process_backend_argv_carries_telemetry_flags(self, tmp_path):
        from heatmap_tpu.serve.fleet import _ProcessBackend

        backend = _ProcessBackend(
            "b0", "arrays:/nonexistent", workdir=str(tmp_path),
            telemetry_opts={"interval": 2.5,
                            "watches": ["ingest_lag_seconds:z=6"]})
        captured = {}

        class _Boom(Exception):
            pass

        def fake_popen(argv, **kwargs):
            captured["argv"] = argv
            raise _Boom

        import subprocess

        real = subprocess.Popen
        subprocess.Popen = fake_popen
        try:
            with pytest.raises(_Boom):
                backend.start()
        finally:
            subprocess.Popen = real
        argv = captured["argv"]
        i = argv.index("--telemetry-sample-interval")
        assert argv[i + 1] == "2.5"
        j = argv.index("--watch")
        assert argv[j + 1] == "ingest_lag_seconds:z=6"

    def test_supervisor_plumbs_telemetry_opts_to_handles(self):
        from heatmap_tpu.serve.fleet import FleetSupervisor

        sup = FleetSupervisor(
            "arrays:/nonexistent", 1,
            telemetry_opts={"interval": 1.0, "watches": []})
        sup._workdir = "."
        handle = sup._make_handle("b0")
        assert handle._telemetry_opts == {"interval": 1.0, "watches": []}

    def test_no_telemetry_opts_means_no_forwarding(self):
        from heatmap_tpu.serve.fleet import _ProcessBackend

        backend = _ProcessBackend("b0", "arrays:/nonexistent",
                                  workdir=".")
        assert backend._telemetry_opts is None
