"""Aux subsystems: tracing, checkpoint/resume, shard recovery.

SURVEY.md §5: the reference has none of these in-repo (Spark provided
fault tolerance; no tracing, no checkpoints). These tests pin down the
greenfield implementations.
"""

import os
import shutil
import time

import numpy as np
import pytest

from heatmap_tpu.utils import (
    CheckpointManager,
    FaultInjector,
    ShardFailure,
    Tracer,
    load_checkpoint,
    run_shards,
    save_checkpoint,
)


# ---------------------------------------------------------------- trace

def test_tracer_spans_and_throughput():
    tr = Tracer()
    with tr.span("work", items=100):
        time.sleep(0.01)
    with tr.span("work", items=50):
        pass
    r = tr.report()["work"]
    assert r["count"] == 2
    assert r["items"] == 150
    assert r["total_s"] >= 0.01
    assert r["max_s"] >= r["mean_s"]
    assert r["items_per_s"] > 0
    assert "work" in tr.format_report()
    tr.reset()
    assert tr.report() == {}


def test_tracer_nested_spans():
    tr = Tracer()
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    assert set(tr.report()) == {"outer", "inner"}


# ----------------------------------------------------------- checkpoint

def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "c.npz")
    arrays = {"a": np.arange(5), "b": np.ones((2, 3), np.float32)}
    save_checkpoint(p, arrays, {"step": 7, "note": "hi"})
    got, meta = load_checkpoint(p)
    np.testing.assert_array_equal(got["a"], arrays["a"])
    np.testing.assert_array_equal(got["b"], arrays["b"])
    assert meta == {"step": 7, "note": "hi"}


def test_checkpoint_atomic_no_partial_on_failure(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"a": np.arange(3)}, {"v": 1})

    class Boom:
        def __array__(self):
            raise RuntimeError("mid-serialize failure")

    with pytest.raises(Exception):
        save_checkpoint(p, {"a": Boom()})
    # Old checkpoint intact, no temp litter.
    got, meta = load_checkpoint(p)
    np.testing.assert_array_equal(got["a"], np.arange(3))
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_checkpoint_manager_latest_and_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for step in (1, 5, 9):
        mgr.save(step, {"x": np.full(2, step)})
    assert mgr.steps() == [5, 9]  # pruned to keep=2
    assert mgr.latest_step() == 9
    arrays, meta = mgr.load()
    assert meta["step"] == 9
    np.testing.assert_array_equal(arrays["x"], [9, 9])
    arrays5, _ = mgr.load(5)
    np.testing.assert_array_equal(arrays5["x"], [5, 5])


def test_checkpoint_manager_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.latest_step() is None
    with pytest.raises(FileNotFoundError):
        mgr.load()


def test_prune_survives_concurrently_deleted_file(tmp_path, monkeypatch):
    """A file that vanishes between the listing and the unlink (another
    maintenance pass got there first) must not abort the prune — the
    remaining doomed checkpoints still get deleted."""
    import heatmap_tpu.utils.checkpoint as ckpt_mod

    mgr = CheckpointManager(str(tmp_path), keep=5)
    for step in (1, 2, 3, 4):
        mgr.save(step, {"x": np.zeros(1)})
    victim = mgr._path(1)
    real_unlink = os.unlink

    def racing_unlink(path, *args, **kwargs):
        if os.path.abspath(path) == os.path.abspath(victim):
            real_unlink(path)  # the "other" pass deletes it first...
        return real_unlink(path, *args, **kwargs)  # ...then we ENOENT

    monkeypatch.setattr(ckpt_mod.os, "unlink", racing_unlink)
    mgr.prune(keep=1)  # must not raise on the vanished ckpt-1
    assert mgr.steps() == [4]


def test_prune_keep_zero_and_validation(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=3)
    for step in (1, 2):
        mgr.save(step, {"x": np.zeros(1)})
    with pytest.raises(ValueError, match="keep"):
        mgr.prune(keep=-1)
    mgr.prune(keep=0)
    assert mgr.steps() == []


def test_steps_on_removed_directory_is_empty(tmp_path):
    """steps() on a directory a concurrent pass removed entirely reads
    as an empty store, not a crash."""
    d = tmp_path / "ckpts"
    mgr = CheckpointManager(str(d))
    shutil.rmtree(d)
    assert mgr.steps() == []
    assert mgr.latest_step() is None


# ------------------------------------------------------------- recovery

def test_run_shards_success_order():
    out = run_shards([3, 1, 4], lambda s: s * 10)
    assert out == [30, 10, 40]


def test_run_shards_retries_transient_fault():
    inj = FaultInjector({1: 2})  # shard 1 fails twice, then succeeds
    retries_seen = []
    out = run_shards(
        [0, 1, 2], lambda s: s,
        retries=2, fault_injector=inj,
        on_retry=lambda i, a, e: retries_seen.append((i, a)),
    )
    assert out == [0, 1, 2]
    assert inj.injected == 2
    assert retries_seen == [(1, 1), (1, 2)]


def test_run_shards_exhausted_budget_raises():
    inj = FaultInjector({0: 5})
    with pytest.raises(ShardFailure) as ei:
        run_shards([0], lambda s: s, retries=2, fault_injector=inj)
    assert ei.value.shard_index == 0
    assert ei.value.attempts == 3


def test_run_shards_threaded_matches_sequential():
    """max_workers > 1 keeps shard-order results and retry semantics."""
    inj = FaultInjector({0: 1, 2: 2, 5: 1})
    seq = run_shards(list(range(8)), lambda s: s * 3, retries=2,
                     fault_injector=FaultInjector({0: 1, 2: 2, 5: 1}))
    par = run_shards(list(range(8)), lambda s: s * 3, retries=2,
                     fault_injector=inj, max_workers=4)
    assert par == seq == [i * 3 for i in range(8)]
    assert inj.injected == 4
    with pytest.raises(ShardFailure):
        run_shards([0, 1], lambda s: s, retries=1,
                   fault_injector=FaultInjector({1: 5}), max_workers=2)


def test_run_shards_result_identical_with_and_without_faults():
    """Idempotent re-execution: transient faults never change results."""
    shards = list(range(6))
    clean = run_shards(shards, lambda s: s ** 2)
    faulty = run_shards(
        shards, lambda s: s ** 2,
        retries=3, fault_injector=FaultInjector({0: 1, 3: 2, 5: 3}),
    )
    assert clean == faulty


def test_run_shards_fallback_replaces_shard_failure():
    """Failover hook: an exhausted shard calls fallback instead of
    raising, and the hook's return value becomes the shard's result."""
    inj = FaultInjector({1: 9})
    seen = []

    def fallback(i, shard, err):
        seen.append((i, shard, type(err).__name__))
        return f"recovered-{shard}"

    out = run_shards(["a", "b", "c"], lambda s: s, retries=1,
                     fault_injector=inj, fallback=fallback)
    assert out == ["a", "recovered-b", "c"]
    assert seen == [(1, "b", "InjectedFault")]


def test_run_shards_fallback_exception_propagates():
    def fallback(i, shard, err):
        raise KeyError("no standby executor")

    with pytest.raises(KeyError):
        run_shards([0], lambda s: s, retries=0,
                   fault_injector=FaultInjector({0: 5}),
                   fallback=fallback)


def test_run_shards_speculative_duplicate_first_completion_wins():
    """Straggler duplication: after three completions, a shard stuck
    beyond factor x quantile is launched a second time; the duplicate
    completes, the original unblocks, and the (identical, by the
    determinism contract) result lands exactly once."""
    import threading

    release = threading.Event()
    lock = threading.Lock()
    launches = {"slow": 0}
    spec_events = []

    def process(s):
        if s == "slow":
            with lock:
                launches["slow"] += 1
                first = launches["slow"] == 1
            if first:
                # The straggler: parked until its duplicate launches.
                assert release.wait(30), "speculation never fired"
            else:
                release.set()
            return "slow-result"
        return s * 2

    def on_speculate(i, elapsed, threshold):
        spec_events.append((i, elapsed, threshold))

    out = run_shards([1, 2, 3, "slow"], process, max_workers=4,
                     speculate_factor=1.5, speculate_quantile=0.5,
                     on_speculate=on_speculate)
    assert out == [2, 4, 6, "slow-result"]
    assert launches["slow"] == 2  # original + exactly one duplicate
    assert len(spec_events) == 1
    i, elapsed, threshold = spec_events[0]
    assert i == 3 and elapsed > threshold >= 0.0


# -------------------------------------------------- resumable batch job

def _mini_cfg():
    from heatmap_tpu.pipeline import BatchJobConfig

    return BatchJobConfig(detail_zoom=11, min_detail_zoom=8)


def test_run_job_resumable_matches_run_job(tmp_path):
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import run_job, run_job_resumable

    src = SyntheticSource(n=4000, seed=2)
    plain = run_job(src, config=_mini_cfg(), batch_size=512)
    resumable = run_job_resumable(
        src, str(tmp_path / "ck"), config=_mini_cfg(),
        batch_size=512, checkpoint_every=2,
    )
    assert plain == resumable


def test_run_job_resumable_resumes_after_crash(tmp_path):
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import run_job, run_job_resumable

    src = SyntheticSource(n=4000, seed=2)
    ckdir = str(tmp_path / "ck")
    # Crash on batch index 5 (after the step-4 checkpoint).
    inj = FaultInjector({5: 1})
    with pytest.raises(RuntimeError):
        run_job_resumable(
            src, ckdir, config=_mini_cfg(), batch_size=512,
            checkpoint_every=2, fault_injector=inj,
        )
    mgr = CheckpointManager(ckdir)
    assert mgr.latest_step() == 4
    # Rerun resumes from the checkpoint and completes identically.
    resumed = run_job_resumable(
        src, ckdir, config=_mini_cfg(), batch_size=512, checkpoint_every=2,
    )
    assert resumed == run_job(src, config=_mini_cfg(), batch_size=512)


def test_run_job_resumable_weighted_crash_resume(tmp_path):
    """Weighted checkpoint/resume: values ride the checkpoint, a crash
    + resume reproduces the uninterrupted weighted run exactly, and a
    resume under the flipped mode is refused."""
    import dataclasses
    import json

    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_resumable

    rng = np.random.default_rng(41)
    n = 4000
    lat = 47.6 + rng.normal(0, 0.3, n)
    lon = -122.3 + rng.normal(0, 0.4, n)
    users = [f"u{int(i)}" for i in rng.integers(0, 10, n)]
    value = rng.integers(0, 7, n).astype(np.float64)

    class _WSrc:
        def batches(self, batch_size):
            for lo in range(0, n, batch_size):
                hi = min(lo + batch_size, n)
                yield {
                    "latitude": lat[lo:hi], "longitude": lon[lo:hi],
                    "user_id": users[lo:hi], "source": [],
                    "timestamp": [], "value": value[lo:hi],
                }

    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8, weighted=True)
    want = run_job(_WSrc(), config=cfg, batch_size=512)
    ckdir = str(tmp_path / "ck")
    with pytest.raises(RuntimeError):
        run_job_resumable(_WSrc(), ckdir, config=cfg, batch_size=512,
                          checkpoint_every=2,
                          fault_injector=FaultInjector({5: 1}))
    assert CheckpointManager(ckdir).latest_step() == 4
    # Flipped mode must refuse before ingesting anything.
    with pytest.raises(RuntimeError, match="weighted"):
        run_job_resumable(
            _WSrc(), ckdir,
            config=dataclasses.replace(cfg, weighted=False),
            batch_size=512, checkpoint_every=2,
        )
    resumed = run_job_resumable(_WSrc(), ckdir, config=cfg,
                                batch_size=512, checkpoint_every=2)
    assert resumed == want
    # Spot-check a real weighted value survived the round trip.
    assert any(v != 1.0 for blob in want.values()
               for v in json.loads(blob).values())


def test_run_job_fast_weighted_crash_resume(tmp_path):
    """Fast-path weighted checkpoint/resume over an HMPB value
    section."""
    import dataclasses

    from heatmap_tpu.io.hmpb import HMPBSource, write_hmpb
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

    rng = np.random.default_rng(43)
    n = 3000
    path = write_hmpb(
        str(tmp_path / "w.hmpb"),
        47.6 + rng.normal(0, 0.3, n),
        -122.3 + rng.normal(0, 0.4, n),
        rng.integers(0, 5, n).astype(np.int32),
        [f"u{i}" for i in range(5)],
        value=rng.integers(0, 9, n).astype(np.float64),
    )
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8, weighted=True)
    want = run_job_fast(HMPBSource(path), config=cfg, batch_size=512)
    ckdir = str(tmp_path / "ck")
    with pytest.raises(RuntimeError):
        run_job_fast(HMPBSource(path), config=cfg, batch_size=512,
                     checkpoint_dir=ckdir, checkpoint_every=2,
                     fault_injector=FaultInjector({4: 1}))
    with pytest.raises(RuntimeError, match="weighted"):
        run_job_fast(HMPBSource(path),
                     config=dataclasses.replace(cfg, weighted=False),
                     batch_size=512, checkpoint_dir=ckdir,
                     checkpoint_every=2)
    resumed = run_job_fast(HMPBSource(path), config=cfg, batch_size=512,
                           checkpoint_dir=ckdir, checkpoint_every=2)
    assert resumed == want


def test_run_job_fast_resumes_after_crash(tmp_path):
    """Fast-path checkpoint/resume, with dated timespans riding the
    i64 epoch-ms column through the checkpoint."""
    from heatmap_tpu.io.hmpb import HMPBSource, convert_to_hmpb
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

    hp = str(tmp_path / "pts.hmpb")
    convert_to_hmpb("synthetic:4000:5", hp)
    cfg = BatchJobConfig(detail_zoom=11, min_detail_zoom=8,
                         timespans=("alltime", "day"))
    clean = run_job_fast(HMPBSource(hp), config=cfg, batch_size=512)
    ckdir = str(tmp_path / "ck")
    inj = FaultInjector({5: 1})  # crash on batch 5, after the step-4 ckpt
    with pytest.raises(RuntimeError):
        run_job_fast(HMPBSource(hp), config=cfg, batch_size=512,
                     checkpoint_dir=ckdir, checkpoint_every=2,
                     fault_injector=inj)
    assert CheckpointManager(ckdir).latest_step() == 4
    resumed = run_job_fast(HMPBSource(hp), config=cfg, batch_size=512,
                           checkpoint_dir=ckdir, checkpoint_every=2)
    assert resumed == clean


def test_run_job_fast_checkpointing_matches_plain(tmp_path):
    from heatmap_tpu.io.hmpb import HMPBSource, convert_to_hmpb
    from heatmap_tpu.pipeline import run_job_fast

    hp = str(tmp_path / "pts.hmpb")
    convert_to_hmpb("synthetic:3000:7", hp)
    plain = run_job_fast(HMPBSource(hp), config=_mini_cfg(), batch_size=512)
    ckpt = run_job_fast(HMPBSource(hp), config=_mini_cfg(), batch_size=512,
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every=2)
    assert plain == ckpt


def test_checkpoint_job_path_mismatch_refused(tmp_path):
    """A fast resume must refuse a string-path checkpoint and vice
    versa — batch indices only mean the same rows under the reader
    that wrote them."""
    from heatmap_tpu.io.hmpb import HMPBSource, convert_to_hmpb
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import run_job_fast, run_job_resumable

    ckdir = str(tmp_path / "ck")
    run_job_resumable(SyntheticSource(n=2000, seed=1), ckdir,
                      config=_mini_cfg(), batch_size=512,
                      checkpoint_every=1)
    hp = str(tmp_path / "pts.hmpb")
    convert_to_hmpb("synthetic:2000:1", hp)
    with pytest.raises(RuntimeError, match="job path"):
        run_job_fast(HMPBSource(hp), config=_mini_cfg(), batch_size=512,
                     checkpoint_dir=ckdir)

    ck2 = str(tmp_path / "ck2")
    run_job_fast(HMPBSource(hp), config=_mini_cfg(), batch_size=512,
                 checkpoint_dir=ck2, checkpoint_every=1)
    with pytest.raises(RuntimeError, match="job path"):
        run_job_resumable(SyntheticSource(n=2000, seed=1), ck2,
                          config=_mini_cfg(), batch_size=512)


def test_run_job_resumable_rejects_bad_interval(tmp_path):
    from heatmap_tpu.io.sources import SyntheticSource
    from heatmap_tpu.pipeline import run_job_resumable

    with pytest.raises(ValueError):
        run_job_resumable(SyntheticSource(n=10), str(tmp_path / "ck"),
                          checkpoint_every=0)


def test_run_job_resumable_datetime_timestamps_roundtrip(tmp_path):
    """Dated timespans with datetime timestamps survive checkpoint/resume."""
    import datetime as dt

    from heatmap_tpu.pipeline import BatchJobConfig, run_job_resumable

    class DatetimeSource:
        def batches(self, batch_size):
            base = dt.datetime(2020, 3, 1, tzinfo=dt.timezone.utc)
            for k in range(4):
                yield {
                    "latitude": np.full(50, 40.0 + k),
                    "longitude": np.full(50, -100.0),
                    "user_id": ["u1"] * 50,
                    "source": ["gps"] * 50,
                    "timestamp": [base + dt.timedelta(days=40 * k)] * 50,
                }

    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                         timespans=("alltime", "month"))
    ckdir = str(tmp_path / "ck")
    inj = FaultInjector({3: 1})
    with pytest.raises(RuntimeError):
        run_job_resumable(DatetimeSource(), ckdir, config=cfg,
                          checkpoint_every=1, fault_injector=inj)
    resumed = run_job_resumable(DatetimeSource(), ckdir, config=cfg,
                                checkpoint_every=1)
    clean = run_job_resumable(DatetimeSource(), str(tmp_path / "ck2"),
                              config=cfg, checkpoint_every=10)
    assert resumed == clean
    assert any("|2020-03|" in k for k in clean)


def test_run_job_resumable_mixed_none_timestamps_roundtrip(tmp_path):
    """A mixed None/real timestamp stream must checkpoint the real ones
    (as TS_MISSING-sentinel int64), not drop the whole column — resumed
    runs bucket dated timespans exactly like uninterrupted ones."""
    import datetime as dt

    from heatmap_tpu.pipeline import BatchJobConfig, run_job_resumable

    class MixedSource:
        def batches(self, batch_size):
            base = dt.datetime(2021, 6, 1, tzinfo=dt.timezone.utc)
            for k in range(4):
                n = 50
                stamps = [
                    (base + dt.timedelta(days=40 * k)) if i % 2 == 0 else None
                    for i in range(n)
                ]
                yield {
                    "latitude": np.full(n, 40.0 + k),
                    "longitude": np.full(n, -100.0),
                    "user_id": ["u1"] * n,
                    "source": ["gps"] * n,
                    "timestamp": stamps,
                }

    from heatmap_tpu.io.hmpb import TS_MISSING
    from heatmap_tpu.pipeline import run_job

    # Dated timespans reject None rows loudly (timespan._to_date), so
    # run alltime; what matters is the checkpoint neither drops the
    # real stamps nor invents fake ones for the None rows.
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8)
    ckdir = str(tmp_path / "ck")
    inj = FaultInjector({3: 1})
    with pytest.raises(RuntimeError):
        run_job_resumable(MixedSource(), ckdir, config=cfg,
                          checkpoint_every=1, fault_injector=inj)
    arrays, _meta = CheckpointManager(ckdir).load()
    ts = arrays["timestamps_ms"]
    assert (ts == TS_MISSING).sum() == len(ts) // 2
    assert (ts != TS_MISSING).sum() == len(ts) // 2
    resumed = run_job_resumable(MixedSource(), ckdir, config=cfg,
                                checkpoint_every=1)
    assert resumed == run_job(MixedSource(), config=cfg)


def test_streaming_checkpoint_restore(tmp_path):
    import jax.numpy as jnp

    from heatmap_tpu.ops import Window
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig

    rng = np.random.default_rng(0)
    window = Window(zoom=9, row0=160, col0=128, height=32, width=32)
    cfg = StreamConfig(window=window, half_life_s=60.0)
    mgr = CheckpointManager(str(tmp_path / "stream"))

    s1 = HeatmapStream(cfg)
    for k in range(3):
        s1.update(rng.uniform(30, 50, 100), rng.uniform(-100, -60, 100),
                  t=10.0 * k)
    s1.checkpoint(mgr)

    s2 = HeatmapStream(cfg).restore(mgr)
    assert s2.t == s1.t and s2.n_batches == 3
    np.testing.assert_array_equal(s2.snapshot(), s1.snapshot())
    # Continue both identically.
    lat = rng.uniform(30, 50, 50)
    lon = rng.uniform(-100, -60, 50)
    for s in (s1, s2):
        s.update(lat, lon, t=35.0)
    np.testing.assert_allclose(s1.snapshot(), s2.snapshot())
