"""Crash-consistency tests: delta-store recovery sweep + torn writes.

The recovery model under test (delta/recover.py): every store write is
atomic, so a crash leaves only *garbage* — orphan ``*.tmp`` staging,
a torn journal entry, an artifact whose journal append never landed, a
base that published but never flipped. The sweep quarantines all of it
(move, never delete) and the next submit of a quarantined batch
re-journals under a fresh epoch and applies exactly once.

The torn-write cases are the satellite's pinned scenarios: a journal
entry npz truncated mid-file, and an entry whose ``content_hash`` was
tampered after the fact (digest mismatch against the artifact bytes).
In both, ``delta_applied`` must never fire for the quarantined entry
and the re-submitted batch must land exactly once.
"""

from __future__ import annotations

import glob
import os

import pytest

from heatmap_tpu import delta, obs
from heatmap_tpu.delta import recover
from heatmap_tpu.delta.compact import read_current
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.pipeline import BatchJobConfig
from heatmap_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

CFG = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)


def _apply(root, n=400, seed=1, **kw):
    return delta.apply_batch(root, SyntheticSource(n=n, seed=seed), CFG,
                             batch_size=200, **kw)


def _journal_entries(root):
    return sorted(glob.glob(os.path.join(root, "journal", "ckpt-*.npz")))


def _quarantined(root):
    q = os.path.join(root, recover.QUARANTINE_DIRNAME)
    return sorted(os.listdir(q)) if os.path.isdir(q) else []


class TestSweepBasics:
    def test_missing_root_is_empty(self, tmp_path):
        assert recover.sweep(str(tmp_path / "nope")) == {"quarantined": []}

    def test_clean_store_untouched(self, tmp_path):
        root = str(tmp_path / "store")
        r = _apply(root)
        recover.clear_verified_cache()
        assert recover.sweep(root)["quarantined"] == []
        assert os.path.isdir(os.path.join(root, r.artifact))
        assert len(_journal_entries(root)) == 1

    def test_orphan_tmp_dirs_quarantined(self, tmp_path):
        root = str(tmp_path / "store")
        _apply(root)
        os.makedirs(os.path.join(root, "base-000001.tmp"))
        open(os.path.join(root, "journal", "junk.tmp"), "w").close()
        items = recover.sweep(root)["quarantined"]
        assert {(i["reason"], i["kind"]) for i in items} == {
            ("orphan_tmp", "tmp")}
        assert {i["path"] for i in items} == {"base-000001.tmp",
                                              os.path.join("journal",
                                                           "junk.tmp")}
        assert "base-000001.tmp" in _quarantined(root)

    def test_orphan_artifact_quarantined(self, tmp_path):
        """A delta dir with no journal entry = a crashed apply (artifact
        written, append lost). Invisible to reads already; the sweep
        moves it out so the retried batch starts clean."""
        root = str(tmp_path / "store")
        _apply(root)
        os.makedirs(os.path.join(root, "delta-000099"))
        items = recover.sweep(root)["quarantined"]
        assert [(i["path"], i["reason"]) for i in items] == [
            ("delta-000099", "orphan_artifact")]

    def test_orphan_base_quarantined(self, tmp_path):
        """A base dir CURRENT does not point at = a compaction that
        crashed between publish_dir and the pointer flip (or between
        flip and prune). The sweep clears it so the NEXT compaction's
        publish_dir target starts absent — the no-clobber contract."""
        root = str(tmp_path / "store")
        _apply(root)
        _apply(root, seed=2)
        summary = delta.compact(root)
        assert summary["status"] == "ok"
        cur_base = read_current(root)["base"]
        os.makedirs(os.path.join(root, "base-000099"))
        items = recover.sweep(root)["quarantined"]
        assert [(i["path"], i["reason"]) for i in items] == [
            ("base-000099", "orphan_base")]
        assert read_current(root)["base"] == cur_base


class TestTornWrites:
    def test_truncated_journal_entry(self, tmp_path):
        """Journal entry npz torn mid-write (power cut beat the fsync):
        the sweep quarantines entry AND artifact, the overlay serves
        nothing from it, and the re-submitted batch applies exactly
        once under a fresh epoch — with no ``delta_applied`` event ever
        naming the quarantined epoch as a duplicate."""
        root = str(tmp_path / "store")
        r1 = _apply(root)
        entry = _journal_entries(root)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[: len(blob) // 2])
        recover.clear_verified_cache()

        ev_path = str(tmp_path / "events.jsonl")
        with obs.EventLog(ev_path) as log:
            obs.set_event_log(log)
            items = recover.sweep(root)["quarantined"]
            assert {(i["reason"], i["kind"]) for i in items} == {
                ("unreadable", "journal_entry"),
                ("orphan_artifact", "delta_artifact")}
            assert delta.overlay_dirs(root) == []
            # Re-submit: same bytes, fresh epoch, applied exactly once.
            r2 = _apply(root)
            obs.set_event_log(None)
        assert r2.duplicate is False
        assert r2.points == r1.points
        assert len(_journal_entries(root)) == 1
        events = obs.read_events(ev_path)
        quarantines = [e for e in events if e["event"] == "quarantine"]
        assert len(quarantines) == 2
        applied = [e for e in events if e["event"] == "delta_applied"]
        assert [e.get("duplicate", False) for e in applied] == [False]

    def test_corrupted_content_hash(self, tmp_path):
        """Entry meta tampered after the fact: the recorded entry_digest
        no longer matches the digest over identity + artifact bytes."""
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        arrays, meta = load_checkpoint(entry)
        meta["content_hash"] = "sha256:" + "0" * 64
        save_checkpoint(entry, arrays, meta)
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert {(i["reason"], i["kind"]) for i in items} == {
            ("digest_mismatch", "journal_entry"),
            ("orphan_artifact", "delta_artifact")}
        r2 = _apply(root)
        assert r2.duplicate is False
        assert len(_journal_entries(root)) == 1

    def test_torn_artifact_bytes(self, tmp_path):
        """The digest also covers the artifact files, so a torn
        ARTIFACT (entry intact) is caught too."""
        root = str(tmp_path / "store")
        r1 = _apply(root)
        art = os.path.join(root, r1.artifact)
        victim = sorted(f for f in os.listdir(art)
                        if os.path.isfile(os.path.join(art, f)))[0]
        with open(os.path.join(art, victim), "ab") as f:
            f.write(b"torn")
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert ("digest_mismatch", "journal_entry") in {
            (i["reason"], i["kind"]) for i in items}

    def test_missing_meta_fields_malformed(self, tmp_path):
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        arrays, meta = load_checkpoint(entry)
        del meta["content_hash"]
        save_checkpoint(entry, arrays, meta)
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert ("malformed", "journal_entry") in {
            (i["reason"], i["kind"]) for i in items}

    def test_verified_cache_skips_rehash(self, tmp_path):
        """Entries/artifacts are immutable once journaled, so (path,
        size, mtime_ns) is a sound memo key: the second sweep must not
        re-read artifact bytes (observable via the monkeypatched
        digest)."""
        root = str(tmp_path / "store")
        _apply(root)
        recover.clear_verified_cache()
        assert recover.sweep(root)["quarantined"] == []
        calls = []
        real = recover.entry_digest

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        orig = recover.entry_digest
        recover.entry_digest = counting
        try:
            assert recover.sweep(root)["quarantined"] == []
        finally:
            recover.entry_digest = orig
        assert calls == []  # memoised — no second hash of the artifact


class TestApplyAndCompactRunTheSweep:
    def test_apply_batch_sweeps_first(self, tmp_path):
        """init_store (the head of every apply) runs the sweep, so a
        crashed store heals on the next submit without an operator
        step."""
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[:100])
        recover.clear_verified_cache()
        r2 = _apply(root, seed=3)
        assert r2.duplicate is False
        assert _quarantined(root)  # the torn entry was moved aside
        assert len(_journal_entries(root)) == 1

    def test_compact_sweeps_then_publishes_atomically(self, tmp_path):
        """compact() sweeps orphan tmp/base dirs first, so its
        publish_dir target (which refuses to clobber) starts absent —
        and the post-crash retry converges to the same base."""
        root = str(tmp_path / "store")
        _apply(root)
        _apply(root, seed=2)
        # Garbage from a hypothetical crashed pass: a staged tmp dir AND
        # a published-but-unflipped base at the very name compact wants.
        os.makedirs(os.path.join(root, "base-000002.tmp"))
        os.makedirs(os.path.join(root, "base-000002"))
        summary = delta.compact(root)
        assert summary["status"] == "ok"
        assert summary["base"] == "base-000002"
        assert read_current(root)["base"] == "base-000002"
        assert {"base-000002.tmp", "base-000002"} <= set(_quarantined(root))
        # The store still reads as one coherent overlay.
        assert delta.load_overlay_levels(root)

    def test_resubmit_after_quarantine_is_byte_identical(self, tmp_path):
        """The healed store serves the same overlay as a never-crashed
        one — quarantine + re-submit is invisible at the read level."""
        import numpy as np

        clean = str(tmp_path / "clean")
        hurt = str(tmp_path / "hurt")
        for root in (clean, hurt):
            _apply(root)
        entry = _journal_entries(hurt)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[: len(blob) // 3])
        recover.clear_verified_cache()
        recover.sweep(hurt)
        _apply(hurt)  # re-submit the same batch
        a = delta.load_overlay_levels(clean)
        b = delta.load_overlay_levels(hurt)
        assert len(a) == len(b)
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(
                np.asarray(la["value"]), np.asarray(lb["value"]))


class TestPublishDirContract:
    def test_publish_dir_refuses_existing_target(self, tmp_path):
        from heatmap_tpu.utils.checkpoint import publish_dir

        src = tmp_path / "stage.tmp"
        src.mkdir()
        (src / "f").write_bytes(b"x")
        dst = tmp_path / "final"
        dst.mkdir()
        with pytest.raises(OSError):
            publish_dir(str(src), str(dst))

    def test_publish_dir_moves_and_fsyncs(self, tmp_path):
        from heatmap_tpu.utils.checkpoint import publish_dir

        src = tmp_path / "stage.tmp"
        src.mkdir()
        (src / "a").write_bytes(b"aa")
        (src / "b").write_bytes(b"bb")
        dst = tmp_path / "final"
        publish_dir(str(src), str(dst))
        assert not src.exists()
        assert sorted(os.listdir(dst)) == ["a", "b"]
        assert (dst / "a").read_bytes() == b"aa"
