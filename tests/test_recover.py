"""Crash-consistency tests: delta-store recovery sweep + torn writes.

The recovery model under test (delta/recover.py): every store write is
atomic, so a crash leaves only *garbage* — orphan ``*.tmp`` staging,
a torn journal entry, an artifact whose journal append never landed, a
base that published but never flipped. The sweep quarantines all of it
(move, never delete) and the next submit of a quarantined batch
re-journals under a fresh epoch and applies exactly once.

The torn-write cases are the satellite's pinned scenarios: a journal
entry npz truncated mid-file, and an entry whose ``content_hash`` was
tampered after the fact (digest mismatch against the artifact bytes).
In both, ``delta_applied`` must never fire for the quarantined entry
and the re-submitted batch must land exactly once.
"""

from __future__ import annotations

import glob
import os

import pytest

from heatmap_tpu import delta, obs
from heatmap_tpu.delta import recover
from heatmap_tpu.delta.compact import read_current
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.pipeline import BatchJobConfig
from heatmap_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

CFG = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)


def _apply(root, n=400, seed=1, **kw):
    return delta.apply_batch(root, SyntheticSource(n=n, seed=seed), CFG,
                             batch_size=200, **kw)


def _journal_entries(root):
    return sorted(glob.glob(os.path.join(root, "journal", "ckpt-*.npz")))


def _quarantined(root):
    q = os.path.join(root, recover.QUARANTINE_DIRNAME)
    return sorted(os.listdir(q)) if os.path.isdir(q) else []


class TestSweepBasics:
    def test_missing_root_is_empty(self, tmp_path):
        assert recover.sweep(str(tmp_path / "nope")) == {"quarantined": []}

    def test_clean_store_untouched(self, tmp_path):
        root = str(tmp_path / "store")
        r = _apply(root)
        recover.clear_verified_cache()
        assert recover.sweep(root)["quarantined"] == []
        assert os.path.isdir(os.path.join(root, r.artifact))
        assert len(_journal_entries(root)) == 1

    def test_orphan_tmp_dirs_quarantined(self, tmp_path):
        root = str(tmp_path / "store")
        _apply(root)
        os.makedirs(os.path.join(root, "base-000001.tmp"))
        open(os.path.join(root, "journal", "junk.tmp"), "w").close()
        items = recover.sweep(root)["quarantined"]
        assert {(i["reason"], i["kind"]) for i in items} == {
            ("orphan_tmp", "tmp")}
        assert {i["path"] for i in items} == {"base-000001.tmp",
                                              os.path.join("journal",
                                                           "junk.tmp")}
        assert "base-000001.tmp" in _quarantined(root)

    def test_orphan_artifact_quarantined(self, tmp_path):
        """A delta dir with no journal entry = a crashed apply (artifact
        written, append lost). Invisible to reads already; the sweep
        moves it out so the retried batch starts clean."""
        root = str(tmp_path / "store")
        _apply(root)
        os.makedirs(os.path.join(root, "delta-000099"))
        items = recover.sweep(root)["quarantined"]
        assert [(i["path"], i["reason"]) for i in items] == [
            ("delta-000099", "orphan_artifact")]

    def test_orphan_base_quarantined(self, tmp_path):
        """A base dir CURRENT does not point at = a compaction that
        crashed between publish_dir and the pointer flip (or between
        flip and prune). The sweep clears it so the NEXT compaction's
        publish_dir target starts absent — the no-clobber contract."""
        root = str(tmp_path / "store")
        _apply(root)
        _apply(root, seed=2)
        summary = delta.compact(root)
        assert summary["status"] == "ok"
        cur_base = read_current(root)["base"]
        os.makedirs(os.path.join(root, "base-000099"))
        items = recover.sweep(root)["quarantined"]
        assert [(i["path"], i["reason"]) for i in items] == [
            ("base-000099", "orphan_base")]
        assert read_current(root)["base"] == cur_base


class TestTornWrites:
    def test_truncated_journal_entry(self, tmp_path):
        """Journal entry npz torn mid-write (power cut beat the fsync):
        the sweep quarantines entry AND artifact, the overlay serves
        nothing from it, and the re-submitted batch applies exactly
        once under a fresh epoch — with no ``delta_applied`` event ever
        naming the quarantined epoch as a duplicate."""
        root = str(tmp_path / "store")
        r1 = _apply(root)
        entry = _journal_entries(root)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[: len(blob) // 2])
        recover.clear_verified_cache()

        ev_path = str(tmp_path / "events.jsonl")
        with obs.EventLog(ev_path) as log:
            obs.set_event_log(log)
            items = recover.sweep(root)["quarantined"]
            assert {(i["reason"], i["kind"]) for i in items} == {
                ("unreadable", "journal_entry"),
                ("orphan_artifact", "delta_artifact")}
            assert delta.overlay_dirs(root) == []
            # Re-submit: same bytes, fresh epoch, applied exactly once.
            r2 = _apply(root)
            obs.set_event_log(None)
        assert r2.duplicate is False
        assert r2.points == r1.points
        assert len(_journal_entries(root)) == 1
        events = obs.read_events(ev_path)
        quarantines = [e for e in events if e["event"] == "quarantine"]
        assert len(quarantines) == 2
        applied = [e for e in events if e["event"] == "delta_applied"]
        assert [e.get("duplicate", False) for e in applied] == [False]

    def test_corrupted_content_hash(self, tmp_path):
        """Entry meta tampered after the fact: the recorded entry_digest
        no longer matches the digest over identity + artifact bytes."""
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        arrays, meta = load_checkpoint(entry)
        meta["content_hash"] = "sha256:" + "0" * 64
        save_checkpoint(entry, arrays, meta)
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert {(i["reason"], i["kind"]) for i in items} == {
            ("digest_mismatch", "journal_entry"),
            ("orphan_artifact", "delta_artifact")}
        r2 = _apply(root)
        assert r2.duplicate is False
        assert len(_journal_entries(root)) == 1

    def test_torn_artifact_bytes(self, tmp_path):
        """The digest also covers the artifact files, so a torn
        ARTIFACT (entry intact) is caught too."""
        root = str(tmp_path / "store")
        r1 = _apply(root)
        art = os.path.join(root, r1.artifact)
        victim = sorted(f for f in os.listdir(art)
                        if os.path.isfile(os.path.join(art, f)))[0]
        with open(os.path.join(art, victim), "ab") as f:
            f.write(b"torn")
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert ("digest_mismatch", "journal_entry") in {
            (i["reason"], i["kind"]) for i in items}

    def test_missing_meta_fields_malformed(self, tmp_path):
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        arrays, meta = load_checkpoint(entry)
        del meta["content_hash"]
        save_checkpoint(entry, arrays, meta)
        recover.clear_verified_cache()
        items = recover.sweep(root)["quarantined"]
        assert ("malformed", "journal_entry") in {
            (i["reason"], i["kind"]) for i in items}

    def test_verified_cache_skips_rehash(self, tmp_path):
        """Entries/artifacts are immutable once journaled, so (path,
        size, mtime_ns) is a sound memo key: the second sweep must not
        re-read artifact bytes (observable via the monkeypatched
        digest)."""
        root = str(tmp_path / "store")
        _apply(root)
        recover.clear_verified_cache()
        assert recover.sweep(root)["quarantined"] == []
        calls = []
        real = recover.entry_digest

        def counting(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        orig = recover.entry_digest
        recover.entry_digest = counting
        try:
            assert recover.sweep(root)["quarantined"] == []
        finally:
            recover.entry_digest = orig
        assert calls == []  # memoised — no second hash of the artifact


class TestApplyAndCompactRunTheSweep:
    def test_apply_batch_sweeps_first(self, tmp_path):
        """init_store (the head of every apply) runs the sweep, so a
        crashed store heals on the next submit without an operator
        step."""
        root = str(tmp_path / "store")
        _apply(root)
        entry = _journal_entries(root)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[:100])
        recover.clear_verified_cache()
        r2 = _apply(root, seed=3)
        assert r2.duplicate is False
        assert _quarantined(root)  # the torn entry was moved aside
        assert len(_journal_entries(root)) == 1

    def test_compact_sweeps_then_publishes_atomically(self, tmp_path):
        """compact() sweeps orphan tmp/base dirs first, so its
        publish_dir target (which refuses to clobber) starts absent —
        and the post-crash retry converges to the same base."""
        root = str(tmp_path / "store")
        _apply(root)
        _apply(root, seed=2)
        # Garbage from a hypothetical crashed pass: a staged tmp dir AND
        # a published-but-unflipped base at the very name compact wants.
        os.makedirs(os.path.join(root, "base-000002.tmp"))
        os.makedirs(os.path.join(root, "base-000002"))
        summary = delta.compact(root)
        assert summary["status"] == "ok"
        assert summary["base"] == "base-000002"
        assert read_current(root)["base"] == "base-000002"
        assert {"base-000002.tmp", "base-000002"} <= set(_quarantined(root))
        # The store still reads as one coherent overlay.
        assert delta.load_overlay_levels(root)

    def test_resubmit_after_quarantine_is_byte_identical(self, tmp_path):
        """The healed store serves the same overlay as a never-crashed
        one — quarantine + re-submit is invisible at the read level."""
        import numpy as np

        clean = str(tmp_path / "clean")
        hurt = str(tmp_path / "hurt")
        for root in (clean, hurt):
            _apply(root)
        entry = _journal_entries(hurt)[0]
        blob = open(entry, "rb").read()
        with open(entry, "wb") as f:
            f.write(blob[: len(blob) // 3])
        recover.clear_verified_cache()
        recover.sweep(hurt)
        _apply(hurt)  # re-submit the same batch
        a = delta.load_overlay_levels(clean)
        b = delta.load_overlay_levels(hurt)
        assert len(a) == len(b)
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(
                np.asarray(la["value"]), np.asarray(lb["value"]))


class TestQuarantineBounded:
    """quarantine/ growth is bounded: the gauge tracks its size and
    prune_quarantine deletes beyond-retention entries — but never one
    younger than the minimum age (the operator's incident window)."""

    def _seed_quarantine(self, root, names, age_s=0.0, now=None):
        import time as _time

        now = _time.time() if now is None else now
        qdir = os.path.join(root, recover.QUARANTINE_DIRNAME)
        os.makedirs(qdir, exist_ok=True)
        for k, name in enumerate(names):
            full = os.path.join(qdir, name)
            with open(full, "w") as f:
                f.write("x" * 10)
            # Strictly older entries first; distinct mtimes keep the
            # newest-first sort deterministic.
            os.utime(full, (now - age_s - k, now - age_s - k))
        return qdir

    def test_gauge_tracks_quarantine_bytes(self, tmp_path):
        from heatmap_tpu.delta.metrics import QUARANTINE_BYTES

        root = str(tmp_path / "store")
        obs.enable_metrics(True)
        try:
            assert recover.quarantine_bytes(root) == 0
            self._seed_quarantine(root, ["a.tmp", "b.tmp", "c.tmp"])
            assert recover.quarantine_bytes(root) == 30
            assert QUARANTINE_BYTES.value() == 30
        finally:
            obs.enable_metrics(False)

    def test_prune_deletes_oldest_beyond_keep(self, tmp_path):
        root = str(tmp_path / "store")
        self._seed_quarantine(root, ["q0", "q1", "q2", "q3"],
                              age_s=3600.0)
        out = recover.prune_quarantine(root, keep=2)
        # Entries were seeded newest-to-oldest: q2/q3 are the oldest.
        assert out["pruned"] == ["q2", "q3"]
        assert out["kept"] == 2 and out["bytes"] == 20
        assert _quarantined(root) == ["q0", "q1"]

    def test_prune_never_touches_young_entries(self, tmp_path):
        """The satellite pin: age wins over count — an entry younger
        than min_age_s survives even when the count cap says prune."""
        import time as _time

        root = str(tmp_path / "store")
        now = _time.time()
        qdir = self._seed_quarantine(root, ["old0", "old1"],
                                     age_s=100_000.0, now=now)
        for name in ("young0", "young1"):
            with open(os.path.join(qdir, name), "w") as f:
                f.write("y" * 10)
        out = recover.prune_quarantine(root, keep=0,
                                       min_age_s=24 * 3600.0, now=now)
        assert sorted(out["pruned"]) == ["old0", "old1"]
        assert sorted(_quarantined(root)) == ["young0", "young1"]
        # Once they age past the window, the same call removes them.
        later = now + 2 * 24 * 3600.0
        out2 = recover.prune_quarantine(root, keep=0,
                                        min_age_s=24 * 3600.0, now=later)
        assert sorted(out2["pruned"]) == ["young0", "young1"]
        assert _quarantined(root) == []
        assert out2["bytes"] == 0

    def test_prune_validates_keep(self, tmp_path):
        with pytest.raises(ValueError):
            recover.prune_quarantine(str(tmp_path), keep=-1)

    def test_compact_prunes_under_retention(self, tmp_path):
        """compact() bounds quarantine growth with its --retention
        knob, but respects the day-long minimum age for fresh garbage."""
        from heatmap_tpu.delta.compact import QUARANTINE_MIN_AGE_S

        root = str(tmp_path / "store")
        _apply(root, seed=1)
        _apply(root, seed=2)
        # Old garbage beyond both caps, plus a fresh orphan the sweep
        # quarantines during this compaction — the fresh one survives.
        self._seed_quarantine(
            root, [f"g{i}" for i in range(5)],
            age_s=QUARANTINE_MIN_AGE_S + 3600.0)
        os.makedirs(os.path.join(root, "crash.tmp"))
        summary = delta.compact(root, retention=2)
        assert summary["status"] == "ok"
        left = _quarantined(root)
        assert "crash.tmp" in left  # younger than the minimum age
        assert len([n for n in left if n.startswith("g")]) <= 2


class TestPublishDirContract:
    def test_publish_dir_refuses_existing_target(self, tmp_path):
        from heatmap_tpu.utils.checkpoint import publish_dir

        src = tmp_path / "stage.tmp"
        src.mkdir()
        (src / "f").write_bytes(b"x")
        dst = tmp_path / "final"
        dst.mkdir()
        with pytest.raises(OSError):
            publish_dir(str(src), str(dst))

    def test_publish_dir_moves_and_fsyncs(self, tmp_path):
        from heatmap_tpu.utils.checkpoint import publish_dir

        src = tmp_path / "stage.tmp"
        src.mkdir()
        (src / "a").write_bytes(b"aa")
        (src / "b").write_bytes(b"bb")
        dst = tmp_path / "final"
        publish_dir(str(src), str(dst))
        assert not src.exists()
        assert sorted(os.listdir(dst)) == ["a", "b"]
        assert (dst / "a").read_bytes() == b"aa"
