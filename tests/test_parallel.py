"""Sharded kernels on the 8-device virtual CPU mesh (SURVEY.md §4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heatmap_tpu.ops import (
    bin_points_window,
    pyramid_from_raster,
    pyramid_sparse_morton,
    aggregate_keys,
    window_from_bounds,
)
from heatmap_tpu.parallel import (
    aggregate_keys_sharded,
    bin_points_replicated,
    bin_points_rowsharded,
    make_mesh,
    pad_to_multiple,
    pyramid_rowsharded,
    pyramid_sparse_morton_sharded,
)
from heatmap_tpu.tilemath import mercator, morton


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _points(n=10_007, seed=0):  # deliberately not divisible by 8
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(35.0, 55.0, n),
        rng.uniform(-5.0, 20.0, n),
    )


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.shape == {"data": 8, "tile": 1}
    m2 = make_mesh(data=4, tile=2)
    assert m2.shape == {"data": 4, "tile": 2}
    with pytest.raises(ValueError):
        make_mesh(data=5, tile=2)


def test_pad_to_multiple():
    a = np.arange(10, dtype=np.float32)
    (pa,), mask = pad_to_multiple([a], 8)
    assert pa.shape == (16,) and mask.sum() == 10
    (pb,), mask2 = pad_to_multiple([a], 5)
    assert pb.shape == (10,) and mask2.all()


@pytest.mark.slow
def test_replicated_binning_matches_single_device(mesh):
    lats, lons = _points()
    win = window_from_bounds((35.0, 55.0), (-5.0, 20.0), zoom=10, align_levels=3)
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    got = np.asarray(
        bin_points_replicated(jnp.asarray(pla), jnp.asarray(plo), win, mesh,
                              valid=jnp.asarray(valid))
    )
    want = np.asarray(bin_points_window(lats, lons, win))
    np.testing.assert_array_equal(got, want)
    assert got.sum() == len(lats)


@pytest.mark.slow
def test_rowsharded_binning_matches_single_device(mesh):
    lats, lons = _points(seed=1)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=10, align_levels=3, pad_multiple=8
    )
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    sharded = bin_points_rowsharded(
        jnp.asarray(pla), jnp.asarray(plo), win, mesh, valid=jnp.asarray(valid)
    )
    assert sharded.shape == win.shape  # global logical shape
    want = np.asarray(bin_points_window(lats, lons, win))
    np.testing.assert_array_equal(np.asarray(sharded), want)


@pytest.mark.slow
def test_rowsharded_weighted(mesh):
    lats, lons = _points(seed=2)
    w = np.random.default_rng(3).uniform(0.0, 2.0, len(lats)).astype(np.float32)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=9, align_levels=0, pad_multiple=8
    )
    (pla, plo, pw), valid = pad_to_multiple([lats, lons, w], 8)
    got = np.asarray(
        bin_points_rowsharded(
            jnp.asarray(pla), jnp.asarray(plo), win, mesh,
            weights=jnp.asarray(pw), valid=jnp.asarray(valid),
        )
    )
    want = np.asarray(bin_points_window(lats, lons, win, weights=w))
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.slow
def test_pyramid_rowsharded_matches_dense(mesh):
    lats, lons = _points(seed=4)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=11, align_levels=6, pad_multiple=8
    )
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    sharded = bin_points_rowsharded(
        jnp.asarray(pla), jnp.asarray(plo), win, mesh, valid=jnp.asarray(valid)
    )
    levels = 6
    pyr = pyramid_rowsharded(sharded, levels, mesh)
    want_raster = bin_points_window(lats, lons, win)
    want_pyr = pyramid_from_raster(want_raster, levels)
    assert len(pyr) == levels + 1
    for got, want in zip(pyr, want_pyr):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_aggregate_keys_sharded_matches_local(mesh):
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 500, 8 * 1000).astype(np.int32)
    w = rng.uniform(0, 1, keys.size).astype(np.float32)
    gu, gs, gn = aggregate_keys_sharded(
        jnp.asarray(keys), mesh, weights=jnp.asarray(w), capacity=1024
    )
    lu, ls, ln = aggregate_keys(jnp.asarray(keys), weights=jnp.asarray(w), capacity=8192)
    n = int(gn)
    assert n == int(ln)
    np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(lu[:n]))
    np.testing.assert_allclose(np.asarray(gs[:n]), np.asarray(ls[:n]), rtol=1e-5)


@pytest.mark.slow
def test_pyramid_sparse_sharded_matches_local(mesh):
    lats, lons = _points(seed=6)
    zoom, levels = 12, 5
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    row, col, pvalid = mercator.project_points(pla, plo, zoom)
    codes = morton.morton_encode(row, col, dtype=jnp.int32, zoom=zoom)
    v = jnp.asarray(valid) & pvalid

    got = pyramid_sparse_morton_sharded(
        codes, mesh, valid=v, levels=levels, capacity=16384
    )
    want = pyramid_sparse_morton(codes, valid=v, levels=levels, capacity=len(pla))
    assert len(got) == len(want)
    for (gu, gs, gn), (wu, ws, wn) in zip(got, want):
        n = int(wn)
        assert int(gn) == n
        np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(wu[:n]))
        np.testing.assert_array_equal(np.asarray(gs[:n]), np.asarray(ws[:n]))


@pytest.mark.slow
def test_pyramid_sparse_sharded_partitioned_matches_local(mesh):
    """DP x partitioned composition: the MXU segment reduction runs
    INSIDE each device's shard_map body; counts are exact integers in
    any summation order, so the bar is bit-equality against the
    single-device scatter pyramid — not allclose."""
    lats, lons = _points(seed=6)
    zoom, levels = 12, 5
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    row, col, pvalid = mercator.project_points(pla, plo, zoom)
    codes = morton.morton_encode(row, col, dtype=jnp.int32, zoom=zoom)
    v = jnp.asarray(valid) & pvalid

    got = pyramid_sparse_morton_sharded(
        codes, mesh, valid=v, levels=levels, capacity=16384,
        backend="partitioned",
    )
    want = pyramid_sparse_morton(codes, valid=v, levels=levels,
                                 capacity=len(pla))
    assert len(got) == len(want)
    for (gu, gs, gn), (wu, ws, wn) in zip(got, want):
        n = int(wn)
        assert int(gn) == n
        np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(wu[:n]))
        np.testing.assert_array_equal(np.asarray(gs[:n]), np.asarray(ws[:n]))


@pytest.mark.slow
def test_pyramid_sparse_sharded_partitioned_weighted_bit_exact(mesh):
    """Bounded-integer weights through the sharded partitioned detail
    stage: integer f64 sums are order-free, so the sharded result is
    bit-identical to the local scatter pyramid."""
    rng = np.random.default_rng(23)
    n = 8 * 1024
    codes = jnp.asarray(rng.integers(0, 4000, n), jnp.int32)
    w = jnp.asarray(rng.integers(0, 100, n), jnp.float64)
    got = pyramid_sparse_morton_sharded(
        codes, mesh, weights=w, levels=3, capacity=4096,
        acc_dtype=jnp.float64, backend="partitioned", weight_bound=100,
    )
    want = pyramid_sparse_morton(codes, weights=w, levels=3, capacity=n,
                                 acc_dtype=jnp.float64)
    assert len(got) == len(want)
    for (gu, gs, gn), (wu, ws, wn) in zip(got, want):
        k = int(wn)
        assert int(gn) == k
        np.testing.assert_array_equal(np.asarray(gu[:k]), np.asarray(wu[:k]))
        np.testing.assert_array_equal(np.asarray(gs[:k]), np.asarray(ws[:k]))


# -- coarse-prefix regrouped merge (O(uniques/k) per stage) ----------------


def _prefix_kernel():
    from heatmap_tpu.parallel import pyramid_sparse_morton_prefix_sharded

    return pyramid_sparse_morton_prefix_sharded


def _assert_levels_equal(got, want, exact_sums=True):
    assert len(got) == len(want)
    for (gu, gs, gn), (wu, ws, wn) in zip(got, want):
        n = int(wn)
        assert int(gn) == n
        np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(wu[:n]))
        if exact_sums:
            np.testing.assert_array_equal(
                np.asarray(gs[:n]), np.asarray(ws[:n])
            )
        else:
            np.testing.assert_allclose(
                np.asarray(gs[:n]), np.asarray(ws[:n]), rtol=1e-12
            )


@pytest.mark.slow
def test_pyramid_prefix_sharded_matches_local(mesh):
    """Counts: the prefix-regrouped merge is bit-identical to the
    single-device pyramid (and therefore to the replicated merge, which
    has the same contract)."""
    lats, lons = _points(seed=16)
    zoom, levels = 12, 5
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    row, col, pvalid = mercator.project_points(pla, plo, zoom)
    codes = morton.morton_encode(row, col, dtype=jnp.int32, zoom=zoom)
    v = jnp.asarray(valid) & pvalid

    got = _prefix_kernel()(codes, mesh, valid=v, levels=levels,
                           capacity=16384)
    want = pyramid_sparse_morton(codes, valid=v, levels=levels,
                                 capacity=len(pla))
    _assert_levels_equal(got, want)


@pytest.mark.slow
def test_pyramid_prefix_sharded_partitioned_matches_local(mesh):
    """The partitioned detail stage under the coarse-prefix regrouped
    merge: same bit-equality bar as the replicated merge — the backend
    choice changes only each device's local reduction, never what
    crosses the collective."""
    lats, lons = _points(seed=16)
    zoom, levels = 12, 5
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    row, col, pvalid = mercator.project_points(pla, plo, zoom)
    codes = morton.morton_encode(row, col, dtype=jnp.int32, zoom=zoom)
    v = jnp.asarray(valid) & pvalid

    got = _prefix_kernel()(codes, mesh, valid=v, levels=levels,
                           capacity=16384, backend="partitioned")
    want = pyramid_sparse_morton(codes, valid=v, levels=levels,
                                 capacity=len(pla))
    _assert_levels_equal(got, want)


@pytest.mark.slow
def test_pyramid_prefix_sharded_unique_heavy(mesh):
    """The regime the kernel exists for: uniques ~ points (every key
    distinct). Results must still match bit-for-bit, with per-level
    capacities tight enough that the REPLICATED keyspace would not even
    fit in a per-device range buffer of the old shape."""
    n = 8 * 2048
    codes = jnp.asarray(np.random.default_rng(17).permutation(n),
                        jnp.int32)
    levels = 4
    got = _prefix_kernel()(codes, mesh, levels=levels, capacity=n)
    want = pyramid_sparse_morton(codes, levels=levels, capacity=n)
    _assert_levels_equal(got, want)


@pytest.mark.slow
def test_pyramid_prefix_sharded_weighted(mesh):
    """Integer-valued f64 weights are bit-exact; fractional weighted
    sums agree to f64 summation-order rounding (the documented
    contract)."""
    rng = np.random.default_rng(18)
    n = 8 * 1024
    codes = jnp.asarray(rng.integers(0, 4000, n), jnp.int32)
    wi = jnp.asarray(rng.integers(1, 100, n), jnp.float64)
    got = _prefix_kernel()(codes, mesh, weights=wi, levels=3,
                           capacity=4096, acc_dtype=jnp.float64)
    want = pyramid_sparse_morton(codes, weights=wi, levels=3, capacity=n,
                                 acc_dtype=jnp.float64)
    _assert_levels_equal(got, want)

    wf = jnp.asarray(rng.uniform(0, 1, n), jnp.float64)
    got = _prefix_kernel()(codes, mesh, weights=wf, levels=3,
                           capacity=4096, acc_dtype=jnp.float64)
    want = pyramid_sparse_morton(codes, weights=wf, levels=3, capacity=n,
                                 acc_dtype=jnp.float64)
    _assert_levels_equal(got, want, exact_sums=False)


@pytest.mark.slow
def test_pyramid_prefix_sharded_skew_and_overflow(mesh):
    """All data under ONE coarse 4^levels block (prefix rounding can't
    split it): one device owns everything. With full send capacity the
    result is still exact; with a send capacity too small for the skew
    the loss is LOUD (n_unique > capacity at every level), never a
    silently wrong sum."""
    rng = np.random.default_rng(19)
    n = 8 * 512
    levels = 3
    # Keys within a single 4^3=64-aligned block.
    codes = jnp.asarray(1024 + rng.integers(0, 64, n), jnp.int32)
    got = _prefix_kernel()(codes, mesh, levels=levels, capacity=1024)
    want = pyramid_sparse_morton(codes, levels=levels, capacity=n)
    _assert_levels_equal(got, want)

    # Unique-heavy AND skew-concentrated: per-(source,dest) traffic is
    # ~the whole shard, so a tiny send cap must overflow loudly.
    wide = jnp.asarray(rng.permutation(64 * n)[:n] % (1 << 20), jnp.int32)
    tight = _prefix_kernel()(wide, mesh, levels=levels, capacity=n,
                             send_capacity=4)
    for u, s, cnt in tight:
        assert int(cnt) > u.shape[0]


@pytest.mark.slow
def test_pyramid_prefix_sharded_2d_mesh():
    """The flattened (data, tile) axes drive the same kernel."""
    m = make_mesh(data=4, tile=2)
    lats, lons = _points(seed=20)
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    row, col, pvalid = mercator.project_points(pla, plo, 11)
    codes = morton.morton_encode(row, col, dtype=jnp.int32, zoom=11)
    v = jnp.asarray(valid) & pvalid
    got = _prefix_kernel()(codes, m, valid=v, levels=4, capacity=8192)
    want = pyramid_sparse_morton(codes, valid=v, levels=4,
                                 capacity=len(pla))
    _assert_levels_equal(got, want)


def test_sharded_kernels_under_jit(mesh):
    # The compiled path used in production: whole step under jax.jit.
    lats, lons = _points(seed=7, n=8 * 512)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=8, align_levels=2, pad_multiple=8
    )

    @jax.jit
    def step(la, lo):
        raster = bin_points_rowsharded(la, lo, win, mesh)
        return pyramid_rowsharded(raster, 2, mesh)

    pyr = step(jnp.asarray(lats), jnp.asarray(lons))
    want = pyramid_from_raster(bin_points_window(lats, lons, win), 2)
    for got, w in zip(pyr, want):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(w))


@pytest.mark.slow
def test_aggregate_keys_sharded_local_overflow_signal(mesh):
    # Review repro: device-local capacity overflow must surface in
    # n_unique even when the merged count looks clean.
    keys = np.concatenate(
        [np.array([0, 1, 2, 3, 4, 5], np.int32)]
        + [np.array([0, 1, 2, 3, 4, 0], np.int32)] * 7
    )
    gu, gs, gn = aggregate_keys_sharded(jnp.asarray(keys), mesh, capacity=5)
    assert int(gn) > 5  # overflow signalled (device 0 dropped key 5)
    # With capacity covering the global uniques the same data is exact.
    gu, gs, gn = aggregate_keys_sharded(jnp.asarray(keys), mesh, capacity=6)
    assert int(gn) == 6
    np.testing.assert_array_equal(np.asarray(gu[:6]), np.arange(6))


@pytest.mark.slow
def test_aggregate_keys_sharded_local_capacity_exact(mesh):
    # The knob changes padding, never results.
    rng = np.random.default_rng(8)
    keys = rng.integers(0, 100, 8 * 256).astype(np.int32)
    want_u, want_s, want_n = aggregate_keys(jnp.asarray(keys), capacity=2048)
    for lc in (100, 256, 4096):
        gu, gs, gn = aggregate_keys_sharded(
            jnp.asarray(keys), mesh, capacity=256, local_capacity=lc
        )
        n = int(want_n)
        assert int(gn) == n
        np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(want_u[:n]))
        np.testing.assert_array_equal(np.asarray(gs[:n]), np.asarray(want_s[:n]))


# -- 2D (data x tile) meshes ----------------------------------------------


@pytest.fixture(scope="module", params=[(4, 2), (2, 4)],
                ids=["4x2", "2x4"])
def mesh2d(request):
    data, tile = request.param
    return make_mesh(data=data, tile=tile)


@pytest.mark.slow
def test_point_kernels_on_2d_mesh_match_single_device(mesh2d):
    """Existing point-parallel kernels shard over the flattened
    (data, tile) axes — tile > 1 uses all devices, same results."""
    lats, lons = _points(seed=11)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=10, align_levels=3, pad_multiple=8
    )
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    la, lo, v = jnp.asarray(pla), jnp.asarray(plo), jnp.asarray(valid)
    want = np.asarray(bin_points_window(lats, lons, win))
    np.testing.assert_array_equal(
        np.asarray(bin_points_replicated(la, lo, win, mesh2d, valid=v)), want
    )
    sharded = bin_points_rowsharded(la, lo, win, mesh2d, valid=v)
    np.testing.assert_array_equal(np.asarray(sharded), want)
    pyr = pyramid_rowsharded(sharded, 3, mesh2d)
    for got, w in zip(pyr, pyramid_from_raster(jnp.asarray(want), 3)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(w))


@pytest.mark.slow
def test_sparse_kernels_on_2d_mesh_match_local(mesh2d):
    rng = np.random.default_rng(12)
    keys = rng.integers(0, 300, 8 * 512).astype(np.int32)
    gu, gs, gn = aggregate_keys_sharded(jnp.asarray(keys), mesh2d, capacity=512)
    lu, ls, ln = aggregate_keys(jnp.asarray(keys), capacity=len(keys))
    n = int(gn)
    assert n == int(ln)
    np.testing.assert_array_equal(np.asarray(gu[:n]), np.asarray(lu[:n]))
    np.testing.assert_array_equal(np.asarray(gs[:n]), np.asarray(ls[:n]))


@pytest.mark.slow
def test_bandsharded_binning_matches_single_device(mesh2d):
    """The all_to_all tile-space regroup (groupByKey analog): counts
    match the single-device raster exactly, output sharded by band."""
    from heatmap_tpu.parallel import bin_points_bandsharded

    lats, lons = _points(seed=13)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=10, align_levels=3, pad_multiple=8
    )
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    got, dropped = bin_points_bandsharded(
        jnp.asarray(pla), jnp.asarray(plo), win, mesh2d,
        valid=jnp.asarray(valid),
    )
    assert int(dropped) == 0  # default capacity: structurally zero
    want = np.asarray(bin_points_window(lats, lons, win))
    np.testing.assert_array_equal(np.asarray(got), want)
    assert got.sharding.spec[0] == "tile"  # rows band-sharded


@pytest.mark.slow
def test_bandsharded_weighted(mesh2d):
    from heatmap_tpu.parallel import bin_points_bandsharded

    lats, lons = _points(seed=14)
    w = np.random.default_rng(15).uniform(0.0, 2.0, len(lats)).astype(np.float32)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=9, align_levels=0, pad_multiple=8
    )
    (pla, plo, pw), valid = pad_to_multiple([lats, lons, w], 8)
    got, _ = bin_points_bandsharded(
        jnp.asarray(pla), jnp.asarray(plo), win, mesh2d,
        weights=jnp.asarray(pw), valid=jnp.asarray(valid),
    )
    want = np.asarray(bin_points_window(lats, lons, win, weights=w))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


@pytest.mark.slow
def test_bandsharded_under_jit(mesh2d):
    from heatmap_tpu.parallel import bin_points_bandsharded

    lats, lons = _points(seed=16, n=8 * 256)
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=8, align_levels=2, pad_multiple=8
    )

    @jax.jit
    def step(la, lo):
        return bin_points_bandsharded(la, lo, win, mesh2d)[0]

    got = np.asarray(step(jnp.asarray(lats), jnp.asarray(lons)))
    want = np.asarray(bin_points_window(lats, lons, win))
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_bandsharded_send_capacity_overflow_is_loud(mesh2d):
    """A skewed band (every point in one raster band) past
    send_capacity must be COUNTED, not silently dropped
    (ops/sparse.py overflow contract applied to the all_to_all)."""
    from heatmap_tpu.parallel import bin_points_bandsharded

    T = mesh2d.shape["tile"]
    win = window_from_bounds(
        (35.0, 55.0), (-5.0, 20.0), zoom=10, align_levels=3, pad_multiple=8
    )
    band_h = win.height // T
    # All points in the FIRST band: rows [row0, row0+band_h) only.
    n = 8 * 64
    rng = np.random.default_rng(21)
    rows = win.row0 + rng.integers(0, band_h, n)
    cols = win.col0 + rng.integers(0, win.width, n)
    lats = np.asarray(mercator.latitude_from_row(rows + 0.5, win.zoom))
    lons = np.asarray(mercator.longitude_from_column(cols + 0.5, win.zoom))
    cap = 16  # per-destination slots; n // (D*T) points/device, all -> dest 0
    band, dropped = bin_points_bandsharded(
        jnp.asarray(lats), jnp.asarray(lons), win, mesh2d, send_capacity=cap
    )
    n_dev = mesh2d.devices.size
    expect_dropped = n - n_dev * min(cap, n // n_dev)
    assert int(dropped) == expect_dropped > 0
    # Kept points all landed in the raster (none lost untracked).
    assert int(np.asarray(band).sum()) == n - int(dropped)

    # Adequate capacity: zero drops and exact counts.
    band2, dropped2 = bin_points_bandsharded(
        jnp.asarray(lats), jnp.asarray(lons), win, mesh2d,
        send_capacity=n // n_dev,
    )
    assert int(dropped2) == 0
    want = np.asarray(bin_points_window(np.asarray(lats), np.asarray(lons), win))
    np.testing.assert_array_equal(np.asarray(band2), want)


def test_bandsharded_rejects_tile1():
    from heatmap_tpu.parallel import bin_points_bandsharded

    win = window_from_bounds((35.0, 55.0), (-5.0, 20.0), zoom=8,
                             align_levels=2, pad_multiple=8)
    with pytest.raises(ValueError):
        bin_points_bandsharded(
            jnp.zeros(8), jnp.zeros(8), win, make_mesh()
        )


@pytest.mark.slow
def test_replicated_binning_partitioned_backend(mesh):
    """Shard-local kernel routing: backend="partitioned" (interpret on
    CPU) under shard_map must match the xla-scatter mesh result — the
    multi-chip analog of the single-chip backend-equality tests."""
    lats, lons = _points(seed=5)
    win = window_from_bounds((35.0, 55.0), (-5.0, 20.0), zoom=10,
                             align_levels=3, pad_multiple=8)
    (pla, plo), valid = pad_to_multiple([lats, lons], 8)
    args = (jnp.asarray(pla), jnp.asarray(plo), win, mesh)
    got = np.asarray(bin_points_replicated(
        *args, valid=jnp.asarray(valid), backend="partitioned"))
    want = np.asarray(bin_points_replicated(
        *args, valid=jnp.asarray(valid), backend="xla"))
    np.testing.assert_array_equal(got, want)
    assert got.sum() == len(lats)


# -- compiled-HLO collective placement ------------------------------------


def _collectives(fn, *args):
    """Sorted set of collective op kinds in the OPTIMIZED module."""
    import re

    txt = jax.jit(fn).lower(*args).compile().as_text()
    return sorted(set(re.findall(
        r"(all-reduce|reduce-scatter|all-to-all|all-gather"
        r"|collective-permute)", txt)))


def test_collective_placement_pinned_in_hlo(mesh, mesh2d):
    """Structural pin for the three check_vma=False kernels (VERDICT r3
    weak #3): the vma check cannot cover pallas-routing shard_maps, so
    assert the compiled module's collective set directly —

    - replicated binning: exactly one psum family (all-reduce), and
      crucially NO all-to-all / reduce-scatter;
    - rowsharded binning: reduce-scatter ONLY — XLA keeping the
      psum_scatter form (an all-reduce here would mean every device
      materializes the full raster, the exact cost the kernel exists
      to avoid);
    - bandsharded binning: the tile-axis all-to-all regroup plus the
      data-axis all-reduce, nothing else.

    Value-equality tests cannot distinguish these programs; the HLO
    can."""
    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.parallel import (
        bin_points_bandsharded, bin_points_replicated,
        bin_points_rowsharded,
    )

    win = window_from_bounds((35.0, 55.0), (-5.0, 20.0), zoom=8,
                             align_levels=3, pad_multiple=8)
    n = 8 * 256
    lat, lon = jnp.zeros(n), jnp.zeros(n)

    assert _collectives(
        lambda a, b: bin_points_replicated(a, b, win, mesh), lat, lon
    ) == ["all-reduce"]
    assert _collectives(
        lambda a, b: bin_points_rowsharded(a, b, win, mesh), lat, lon
    ) == ["reduce-scatter"]
    assert _collectives(
        lambda a, b: bin_points_bandsharded(a, b, win, mesh2d)[0],
        lat, lon,
    ) == ["all-reduce", "all-to-all"]


def test_prefix_merge_collectives_pinned_in_hlo(mesh):
    """Structural pin for the coarse-prefix merge: the compiled module
    must contain the all-to-all regroup (the kernel's entire point — a
    regression to the replicated formulation would drop it), and every
    collective operand must stay compact (O(ndev * local_capacity)) —
    the n-sized key stream never rides a collective."""
    import re

    n, cap = 8 * 8192, 256
    codes = jnp.zeros(n, jnp.int64)
    compiled = jax.jit(
        lambda k: _prefix_kernel()(k, mesh, levels=3, capacity=cap)[0]
    ).lower(codes).compile()
    txt = compiled.as_text()
    assert " all-to-all" in txt
    ops = ("all-reduce", "reduce-scatter", "all-to-all", "all-gather",
           "collective-permute")
    sizes = []
    for line in txt.splitlines():
        if not any(f" {op}(" in line or f" {op}-" in line
                   for op in ops):
            continue
        for dims in re.findall(r"\[([\d,]+)\]", line):
            sizes.append(
                int(np.prod([int(d) for d in dims.split(",") if d]))
            )
    assert sizes, "expected collectives in the prefix merge"
    # local_capacity = min(cap, n//8) = 256; the biggest legitimate
    # collective is the (ndev, send_cap) = 8*256 = 2048-lane exchange.
    # Any n-derived operand is >= n/ndev = 8192.
    assert max(sizes) < n // 8, (max(sizes), sorted(set(sizes)))


def test_sharded_aggregation_collectives_stay_compact(mesh):
    """The sparse aggregation path must move only COMPACT per-device
    partials through collectives — never the n-sized key stream. The
    merge re-reduce runs outside shard_map as plain jit ops, so GSPMD
    is free to pick the collective kinds; what the design pins is that
    every collective operand is O(ndev * local_capacity), which is the
    whole point of the local-reduce-then-merge formulation."""
    import re

    from heatmap_tpu.parallel import aggregate_keys_sharded

    n, cap = 8 * 8192, 256
    keys = jnp.zeros(n, jnp.int64)
    txt = jax.jit(
        lambda k: aggregate_keys_sharded(k, mesh, capacity=cap)[0]
    ).lower(keys).compile().as_text()
    ops = ("all-reduce", "reduce-scatter", "all-to-all", "all-gather",
           "collective-permute")
    # Scan WHOLE instruction lines and take every array shape on them
    # (results AND operands, tuple-shaped variadic combiners included):
    # a reduce-scatter's small RESULT must not hide its n-sized
    # operand, and an XLA combiner pass must not make shapes invisible
    # to the match.
    sizes = []
    for line in txt.splitlines():
        if not any(f" {op}(" in line or f" {op}-" in line
                   for op in ops):
            continue
        for dims in re.findall(r"\[([\d,]+)\]", line):
            sizes.append(
                int(np.prod([int(d) for d in dims.split(",") if d]))
            )
    assert sizes, "expected at least one collective in the merge"
    # Compact partials are ndev * local_capacity = 2048 elements; any
    # n-derived size is at least n/ndev = 8192. The bound sits strictly
    # between, so n-sized movement fails however GSPMD spells it.
    assert max(sizes) < n // 8, (max(sizes), sorted(set(sizes)))
