#!/usr/bin/env python
"""Wavelet-synopsis bench: compression, decode latency, error, and
early-serve lag: BENCH_synopsis.json.

Four headline sections (docs/synopsis.md):

- ``bytes``       per synopsized zoom, exact level artifact bytes vs
                  synopsis artifact bytes; ``bytes_ratio`` is the
                  aggregate exact/synopsis quotient at the default
                  coefficient budget (acceptance: >= 4x);
- ``decode_ms``   p50/p99 of one pair-level decode (sparse
                  coefficients -> dense grid), the latency a synopsis
                  tile render pays on a cache miss;
- ``max_err``     the worst stamped L-inf bound across pairs and
                  zooms, re-verified here against a freshly decoded
                  grid (the stamp is the achieved error, so the two
                  must agree exactly);
- ``early_serve`` provisional-publish-to-exact-apply lag from a real
                  ``ingest.run_ingest`` drain against a delta store
                  whose base carries synopses: for each tick,
                  ``ts(delta_applied) - ts(synopsis_built
                  provisional)`` — how much sooner a coarse overview
                  tile reflects the micro-batch than the exact apply
                  lands.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_synopsis.py \
        [--points 30000] [--decode-iters 50] [--out BENCH_synopsis.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _pct(sorted_vals: list, q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _materialize(spec: str) -> dict:
    from heatmap_tpu.io import open_source

    cols: dict = {}
    for batch in open_source(spec).batches(1 << 20):
        for c, v in batch.items():
            cols.setdefault(c, []).extend(v)
    return cols


def bench_compression(level_dir: str) -> dict:
    """Exact-vs-synopsis artifact bytes per zoom + the aggregate ratio."""
    from heatmap_tpu.synopsis.build import synopsis_path

    per_zoom, exact_total, syn_total = {}, 0, 0
    for name in sorted(os.listdir(level_dir)):
        if not (name.startswith("level_z") and name.endswith(".npz")):
            continue
        zoom = int(name[len("level_z"):len("level_z") + 2])
        spath = synopsis_path(level_dir, zoom)
        if not os.path.exists(spath):
            continue
        exact = os.path.getsize(os.path.join(level_dir, name))
        syn = os.path.getsize(spath)
        per_zoom[zoom] = {"exact_bytes": exact, "synopsis_bytes": syn,
                          "ratio": round(exact / syn, 2)}
        exact_total += exact
        syn_total += syn
    return {"per_zoom": per_zoom, "exact_bytes": exact_total,
            "synopsis_bytes": syn_total,
            "bytes_ratio": round(exact_total / syn_total, 2)
            if syn_total else None}


def bench_decode(level_dir: str, iters: int) -> dict:
    """Decode latency for the LARGEST synopsized zoom (worst case: the
    dense grid is biggest) + the re-verified worst error stamp."""
    from heatmap_tpu.synopsis.build import load_synopses
    from heatmap_tpu.synopsis.transform import grid_from_rows_np
    from heatmap_tpu.io.sinks import LevelArraysSink

    syn = load_synopses(level_dir)
    zoom = max(syn)
    samples = []
    for _ in range(iters):
        for pair in syn[zoom]:
            t0 = time.perf_counter()
            pair.decode()
            samples.append(1e3 * (time.perf_counter() - t0))
    samples.sort()

    # Re-verify: the stamp is the achieved error, so a fresh decode
    # against the exact level must reproduce it exactly, every pair.
    levels = LevelArraysSink.load(level_dir)
    worst = 0.0
    for z, pairs in syn.items():
        cols = levels[z]
        users = np.asarray(cols["user"], str)
        tss = np.asarray(cols["timespan"], str)
        for pair in pairs:
            sel = (users == pair.user) & (tss == pair.timespan)
            grid = grid_from_rows_np(
                np.asarray(cols["row"])[sel], np.asarray(cols["col"])[sel],
                np.asarray(cols["value"])[sel], pair.n)
            achieved = float(np.abs(pair.decode() - grid).max())
            if achieved != pair.max_err:
                raise SystemExit(
                    f"error contract violated at z{z} "
                    f"({pair.user},{pair.timespan}): stamped "
                    f"{pair.max_err} != achieved {achieved}")
            worst = max(worst, pair.max_err)
    return {"zoom": zoom, "pairs": len(syn[zoom]),
            "decode_ms": {"p50": _pct(samples, 0.50),
                          "p99": _pct(samples, 0.99)},
            "max_err": worst, "verified": True}


def bench_early_serve(cols: dict, tmpdir: str) -> dict:
    """Provisional-to-exact lag through the real ingest loop."""
    from heatmap_tpu import delta, ingest
    from heatmap_tpu.obs import events
    from heatmap_tpu.pipeline import BatchJobConfig
    from heatmap_tpu.serve import TileCache, TileStore

    config = BatchJobConfig(detail_zoom=8, min_detail_zoom=4,
                            result_delta=2)
    root = os.path.join(tmpdir, "delta-store")
    delta.init_store(root)
    store, cache = TileStore(f"delta:{root}"), TileCache()
    events_path = os.path.join(tmpdir, "events.jsonl")
    log = events.EventLog(events_path)
    events.set_event_log(log)
    try:
        # compact_every=1 publishes a synopsis-bearing base after the
        # first tick, so every later tick early-serves.
        ingest.run_ingest(
            root, _FixedChunks(cols, 4096), config, store=store,
            cache=cache,
            ingest=ingest.IngestConfig(micro_batch=4096, queue_depth=2,
                                       compact_every=1))
    finally:
        events.set_event_log(None)
        log.close()
    records = events.read_events(events_path)
    lags, provisional = [], 0
    last_prov_ts = None
    for rec in records:
        if rec["event"] == "synopsis_built" and rec.get("provisional"):
            provisional += 1
            last_prov_ts = rec["ts"]
        elif rec["event"] == "delta_applied" and last_prov_ts is not None:
            lags.append(1e3 * (rec["ts"] - last_prov_ts))
            last_prov_ts = None
    lags.sort()
    return {"ticks": sum(r["event"] == "ingest_tick" for r in records),
            "provisional_publishes": provisional,
            "lag_ms": {"p50": _pct(lags, 0.50), "p99": _pct(lags, 0.99)}}


class _FixedChunks:
    """Re-chunk a materialized columnar batch into fixed micro-batches."""

    def __init__(self, cols: dict, size: int):
        self.cols = cols
        self.size = size

    def batches(self, batch_size: int = 1 << 20):
        n = len(self.cols["latitude"])
        for i in range(0, n, self.size):
            yield {c: v[i:i + self.size] for c, v in self.cols.items()}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=30_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--decode-iters", type=int, default=50)
    ap.add_argument("--out", default="BENCH_synopsis.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import obs
    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    obs.enable_metrics(True)
    tmpdir = tempfile.mkdtemp(prefix="benchsynopsis-")
    try:
        level_dir = os.path.join(tmpdir, "levels")
        config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                                result_delta=2)
        with open_sink(f"arrays-synopsis:{level_dir}") as sink:
            run_job(open_source(f"synthetic:{args.points}:{args.seed}"),
                    sink, config)
        compression = bench_compression(level_dir)
        print(json.dumps({"bytes_ratio": compression["bytes_ratio"]}),
              flush=True)
        decode = bench_decode(level_dir, args.decode_iters)
        print(json.dumps({"decode_ms": decode["decode_ms"],
                          "max_err": decode["max_err"]}), flush=True)
        cols = _materialize(f"synthetic:{args.points}:{args.seed + 1}")
        early = bench_early_serve(cols, tmpdir)
        print(json.dumps({"early_serve": early}), flush=True)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    record = {"bench": "synopsis", "points": args.points,
              "compression": compression, "decode": decode,
              "early_serve": early}
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
        f.write("\n")
    print(json.dumps({"wrote": args.out}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
