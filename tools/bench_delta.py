#!/usr/bin/env python
"""Incremental delta apply vs. full recompute: BENCH_delta.json.

For each base:delta size ratio, builds a delta store whose base holds
``--base-points`` synthetic points (compacted, so the overlay starts
clean), then measures two ways of absorbing one new batch of
``base_points / ratio`` points:

- **full**  — the reference shape: re-run the whole batch job over the
  union of old + new points (``run_job`` into a fresh columnar
  artifact);
- **incremental** — ``delta.apply_batch``: journal the batch, cascade
  only the new points, emit a delta artifact the serve overlay merges
  on read.

Both paths run in process on the same backend; the pyramids they
produce are byte-equivalent at the served-blob level (pinned by
tests/test_delta.py), so the comparison is pure wall-clock. The
headline number is the speedup at 100:1 — the "minutes-scale full
recompute becomes seconds-scale delta apply" claim made measurable.

The record mirrors tools/bench_job.py / load_gen.py: one JSON object
with the headline numbers plus the same folded ``run_report`` block
(obs.build_run_report over the shared in-process registry), so delta
benches land in the bench trajectory schema-compatible with the rest.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_delta.py \
        [--base-points 200000] [--ratios 100,20,5] \
        [--detail-zoom 12] [--out BENCH_delta.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


class _Chain:
    """Concatenate sources: the union job reads old + new points as one
    stream (synthetic sources are deterministic, so re-opening them
    replays the exact same points the store ingested)."""

    def __init__(self, *sources):
        self.sources = sources

    def batches(self, batch_size: int = 1 << 20):
        for src in self.sources:
            yield from src.batches(batch_size)


def bench_ratio(ratio: int, base_points: int, config, tmpdir: str) -> dict:
    from heatmap_tpu import delta
    from heatmap_tpu.io import open_source
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import run_job

    delta_points = max(1, base_points // ratio)
    base_spec = f"synthetic:{base_points}:7"
    delta_spec = f"synthetic:{delta_points}:11"
    root = os.path.join(tmpdir, f"store-{ratio}")

    # Base build rides the delta engine itself (apply + compact) — it
    # also warms the jit caches so neither measured path pays first-
    # compile alone.
    t0 = time.perf_counter()
    delta.apply_batch(root, open_source(base_spec), config)
    delta.compact(root, retention=0)
    base_s = time.perf_counter() - t0

    # Full recompute over the union (the reference's only option).
    full_dir = os.path.join(tmpdir, f"full-{ratio}")
    t0 = time.perf_counter()
    full_stats = run_job(
        _Chain(open_source(base_spec), open_source(delta_spec)),
        LevelArraysSink(full_dir), config)
    full_s = time.perf_counter() - t0

    # Incremental: journal + cascade only the new points. One warmup
    # apply (different seed, same size) first — steady-state serving
    # applies a stream of similar-size batches, so the measured apply
    # should not be the one paying the small-shape jit compile.
    delta.apply_batch(root, open_source(f"synthetic:{delta_points}:13"),
                      config)
    t0 = time.perf_counter()
    res = delta.apply_batch(root, open_source(delta_spec), config)
    incr_s = time.perf_counter() - t0

    shutil.rmtree(full_dir, ignore_errors=True)
    shutil.rmtree(root, ignore_errors=True)
    return {
        "ratio": ratio,
        "base_points": base_points,
        "delta_points": delta_points,
        "base_build_s": round(base_s, 3),
        "full_recompute_s": round(full_s, 3),
        "incremental_apply_s": round(incr_s, 3),
        "speedup": round(full_s / incr_s, 2) if incr_s else None,
        "full_rows": int(full_stats.get("rows", 0))
        if isinstance(full_stats, dict) else None,
        "delta_rows": res.rows,
        "affected_keys": len(res.affected_keys),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--base-points", type=int, default=200_000)
    ap.add_argument("--ratios", default="100,20,5",
                    help="comma list of base:delta ratios")
    ap.add_argument("--detail-zoom", type=int, default=12)
    ap.add_argument("--min-detail-zoom", type=int, default=5)
    ap.add_argument("--out", default="BENCH_delta.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import obs
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.pipeline import BatchJobConfig
    from heatmap_tpu.utils.trace import get_tracer

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_analyze

    obs.enable_metrics(True)
    collector = tracing.enable_tracing()
    config = BatchJobConfig(detail_zoom=args.detail_zoom,
                            min_detail_zoom=args.min_detail_zoom)
    ratios = [int(r) for r in args.ratios.split(",") if r.strip()]
    tmpdir = tempfile.mkdtemp(prefix="benchdelta-")
    results = []
    try:
        for ratio in ratios:
            row = bench_ratio(ratio, args.base_points, config, tmpdir)
            print(json.dumps({k: row[k] for k in
                              ("ratio", "full_recompute_s",
                               "incremental_apply_s", "speedup")}),
                  flush=True)
            results.append(row)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    record = {
        "bench": "delta",
        "base_points": args.base_points,
        "detail_zoom": args.detail_zoom,
        "results": results,
        # Same folded block bench_job.py embeds: delta benches stay
        # schema-compatible with job benches in the bench trajectory.
        "run_report": obs.build_run_report(tracer=get_tracer(),
                                           registry=obs.get_registry()),
        # Span-tree digest: top self-time spans + the slowest trace's
        # critical path (tools/trace_analyze.py).
        "trace": trace_analyze.summarize(collector.to_chrome()),
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
        f.write("\n")
    print(json.dumps({"wrote": args.out}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
