#!/usr/bin/env python
"""Scale soak checks: equivalences the unit suite can't afford.

The unit tests (tests/) run small shapes; overflow/capacity bugs can
hide above them (the int32 chunk-merge wrap only fired at ~300 users x
z21 x multiple chunks). This tool runs minutes-long cross-path
equivalence checks at configurable scale on the current backend:

  fast-vs-bounded   run_job_fast vs chunked run_job: byte-identical
                    level arrays (the strongest whole-chain check)
  mesh              sharded reduce-by-key over the device mesh vs the
                    single-device kernel, on skewed keys
  dp-job            run_job_fast data-parallel over the mesh vs
                    single-device: byte-equal level arrays at scale
  resume            crash (fault injection) + resume == uninterrupted
  streaming         sharded decayed raster: deterministic replay
  weighted          weighted job linearity (3x values == 3x counts,
                    exact) + weighted partitioned-vs-scatter kernels

    PYTHONPATH=.:$PYTHONPATH XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/soak.py [--n 2000000] [--checks fast-vs-bounded,...]

Every check runs and reports one JSON line; the exit code is non-zero
if any failed. CPU by default (--tpu to let the default backend
through); the mesh checks need the 8-device XLA_FLAGS above.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

CHECKS = ("fast-vs-bounded", "mesh", "dp-job", "resume", "streaming", "weighted")


def _synth_hmpb(path, n, n_users=300, seed=1, dated=False,
                weighted=False):
    from heatmap_tpu.io.hmpb import write_hmpb

    rng = np.random.default_rng(seed)
    names = ["all"] + [f"user{i}" for i in range(n_users)] + ["route"]
    return write_hmpb(
        path,
        47.6 + rng.normal(0, 0.5, n),
        -122.3 + rng.normal(0, 0.7, n),
        rng.integers(1, len(names), n, dtype=np.int32),
        names,
        timestamp=rng.integers(1_500_000_000_000, 1_600_000_000_000, n)
        if dated else None,
        background=(rng.random(n) < 0.02).astype(np.uint8),
        # Integer-valued f64 weights: exact sums under any split, so
        # weighted cross-path checks can assert byte equality.
        value=rng.integers(1, 12, n).astype(np.float64)
        if weighted else None,
    )


def _assert_levels_equal(a_dir, b_dir):
    """Full-column byte equality of two LevelArraysSink dirs;
    -> (levels, rows)."""
    from heatmap_tpu.io.sinks import LevelArraysSink

    la, lb = LevelArraysSink.load(a_dir), LevelArraysSink.load(b_dir)
    assert la.keys() == lb.keys(), (sorted(la), sorted(lb))
    rows = 0
    for z in la:
        for k in ("row", "col", "value", "user", "timespan",
                  "coarse_row", "coarse_col"):
            np.testing.assert_array_equal(la[z][k], lb[z][k])
        rows += len(la[z]["value"])
    return len(la), rows


def check_fast_vs_bounded(n, tmp):
    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job, run_job_fast

    hmpb = _synth_hmpb(os.path.join(tmp, "p.hmpb"), n)
    # data_parallel=False: this check is about fast-vs-bounded
    # equality, and auto-DP at soak sizes trips XLA's CPU collective
    # rendezvous timeout on low-core hosts (8 virtual devices
    # SERIALIZE on the cores available; a participant arriving >60s
    # after the first aborts the process — a CPU-emulation artifact,
    # not a program property). DP equality has its own check below
    # with a deliberately bounded per-shard size.
    cfg = BatchJobConfig(data_parallel=False)
    a = os.path.join(tmp, "a")
    b = os.path.join(tmp, "b")
    run_job_fast(HMPBSource(hmpb), LevelArraysSink(a), config=cfg)
    run_job(HMPBSource(hmpb), LevelArraysSink(b), config=cfg,
            max_points_in_flight=max(n // 4, 1000))
    levels, rows = _assert_levels_equal(a, b)
    return {"levels": levels, "rows": rows}


def check_mesh(n, tmp):
    """Device-mesh sharded aggregation vs single-device, at scale.

    (run_job_multihost falls through to run_job in a single process,
    so comparing those two here would be vacuous — the mesh coverage
    must drive the sharded kernels directly.)
    """
    import jax
    import jax.numpy as jnp

    from heatmap_tpu.ops.sparse import aggregate_keys
    from heatmap_tpu.parallel import make_mesh
    from heatmap_tpu.parallel.sharded import aggregate_keys_sharded

    if len(jax.devices()) < 2:
        return {"skipped": "needs a multi-device mesh (set XLA_FLAGS)"}
    mesh = make_mesh()
    ndev = len(jax.devices())
    n = max(n - n % ndev, ndev)  # shardable length
    rng = np.random.default_rng(4)
    # Skewed keys: hot head + long tail, the shape that trips local
    # capacity/overflow logic.
    keys = jnp.asarray(np.concatenate([
        rng.integers(0, 500, n // 2),
        rng.integers(0, n, n - n // 2),
    ]).astype(np.int64))
    want_k, want_s, want_n = aggregate_keys(keys, capacity=n)
    got_k, got_s, got_n = aggregate_keys_sharded(
        keys, capacity=n, mesh=mesh
    )
    wn, gn = int(want_n), int(got_n)
    assert wn == gn, f"unique counts diverged: {wn} vs {gn}"
    np.testing.assert_array_equal(np.asarray(want_k)[:wn],
                                  np.asarray(got_k)[:gn])
    np.testing.assert_array_equal(np.asarray(want_s)[:wn],
                                  np.asarray(got_s)[:gn])
    return {"uniques": wn, "devices": len(jax.devices()),
            "mesh": dict(mesh.shape)}


def check_dp_job(n, tmp):
    """Flagship job data-parallel over the virtual mesh vs
    single-device, at scale: byte-equal level arrays. The unit suite
    pins small shapes; this drives the padding + zoom-clamped
    capacities through the sharded cascade at soak size."""
    import jax

    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.io.sinks import LevelArraysSink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast

    if len(jax.devices()) < 2:
        return {"skipped": "needs a multi-device mesh (set XLA_FLAGS)"}
    # Bound the DP size: on a low-core host the virtual devices'
    # collective participants serialize, and XLA's CPU rendezvous
    # aborts the process if one arrives >60s late — 500k points keeps
    # per-shard work far under that while still 10x the unit suite.
    n = min(n, 500_000)
    hmpb = _synth_hmpb(os.path.join(tmp, "dp.hmpb"), n)
    a, b = os.path.join(tmp, "dp-a"), os.path.join(tmp, "dp-b")
    run_job_fast(HMPBSource(hmpb), LevelArraysSink(a),
                 config=BatchJobConfig(data_parallel=True))
    run_job_fast(HMPBSource(hmpb), LevelArraysSink(b),
                 config=BatchJobConfig(data_parallel=False))
    levels, rows = _assert_levels_equal(a, b)
    # Weighted variant: integer-valued f64 weights stay bit-exact
    # through the sharded cascade's merge at scale.
    whmpb = _synth_hmpb(os.path.join(tmp, "dpw.hmpb"), n, weighted=True)
    wa, wb = os.path.join(tmp, "dpw-a"), os.path.join(tmp, "dpw-b")
    wcfg = dict(weighted=True)
    run_job_fast(HMPBSource(whmpb), LevelArraysSink(wa),
                 config=BatchJobConfig(data_parallel=True, **wcfg))
    run_job_fast(HMPBSource(whmpb), LevelArraysSink(wb),
                 config=BatchJobConfig(data_parallel=False, **wcfg))
    wlevels, wrows = _assert_levels_equal(wa, wb)
    # Coarse-prefix merge at soak size: the O(uniques/k) route must
    # match the single-device arrays byte-for-byte too (drives the
    # PSRS splitters + hybrid prefix depth on real clustered z21 data,
    # where the first full-depth build overflowed).
    p = os.path.join(tmp, "dp-p")
    run_job_fast(HMPBSource(hmpb), LevelArraysSink(p),
                 config=BatchJobConfig(data_parallel=True,
                                       dp_merge="prefix"))
    _assert_levels_equal(p, b)
    return {"levels": levels, "rows": rows, "weighted_rows": wrows,
            "prefix_merge": "ok", "devices": len(jax.devices())}


def check_resume(n, tmp):
    from heatmap_tpu.io.hmpb import HMPBSource
    from heatmap_tpu.pipeline import BatchJobConfig, run_job_fast
    from heatmap_tpu.utils.recovery import FaultInjector

    hmpb = _synth_hmpb(os.path.join(tmp, "r.hmpb"), n, dated=True)
    # data_parallel=False: see check_fast_vs_bounded's rendezvous note.
    cfg = BatchJobConfig(timespans=("alltime", "day"),
                         data_parallel=False)
    bs = max(n // 8, 1)  # always >= 8 batches, so the mid fault fires
    n_batches = -(-n // bs)
    fail_at = n_batches // 2
    want = run_job_fast(HMPBSource(hmpb), config=cfg, batch_size=bs)
    ck = os.path.join(tmp, "ck")
    try:
        run_job_fast(HMPBSource(hmpb), config=cfg, batch_size=bs,
                     checkpoint_dir=ck, checkpoint_every=2,
                     fault_injector=FaultInjector({fail_at: 1}))
        raise AssertionError("expected the injected fault to fire")
    except RuntimeError:
        pass
    got = run_job_fast(HMPBSource(hmpb), config=cfg, batch_size=bs,
                       checkpoint_dir=ck, checkpoint_every=2)
    assert want == got, (
        f"resume diverged: {len(want)} vs {len(got)} blobs"
    )
    return {"blobs": len(want)}


def check_streaming(n, tmp):
    import jax

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.parallel import make_mesh
    from heatmap_tpu.streaming import HeatmapStream, StreamConfig

    win = window_from_bounds((44.0, 51.0), (-127.0, -117.0), zoom=12,
                             align_levels=10, pad_multiple=256)
    mesh = make_mesh() if len(jax.devices()) > 1 else None
    batch = max(n // 50, 1000)

    def run():
        s = HeatmapStream(
            StreamConfig(window=win, half_life_s=30.0, pad_to=batch),
            mesh=mesh,
        )
        rng = np.random.default_rng(0)
        t = 0.0
        for _ in range(50):
            t += 5.0
            s.update(47.6 + rng.normal(0, 0.5, batch),
                     -122.3 + rng.normal(0, 0.7, batch), t)
        return np.asarray(s.raster)

    r1, r2 = run(), run()
    np.testing.assert_array_equal(r1, r2)
    return {"batches": 50, "batch": batch, "mass": float(r1.sum()),
            "sharded": mesh is not None}


def check_weighted(n, tmp):
    """Weighted-path equivalences at scale.

    (a) Job linearity: run --weighted semantics with every value == 3
    must equal exactly 3x the counted blobs (integer-valued weights
    keep the f64 sums exact at any fan-in). (b) Kernel cross-path:
    weighted sort-partitioned binning vs the weighted XLA scatter,
    bit-equal for integer weights at a million-point z15 window.
    """
    import jax.numpy as jnp

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.ops.histogram import bin_rowcol_window
    from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned
    from heatmap_tpu.pipeline import BatchJobConfig, run_job
    from heatmap_tpu.tilemath import mercator

    rng = np.random.default_rng(9)
    n_job = min(n, 200_000)  # the string job path is host-bound
    users = (["all_is_reserved"] + [f"u{i}" for i in range(50)]
             + ["x-hidden", "rt-bus"])
    lat = 47.6 + rng.normal(0, 0.5, n_job)
    lon = -122.3 + rng.normal(0, 0.7, n_job)
    uid = rng.integers(0, len(users), n_job)

    class _Src:
        def __init__(self, with_values):
            self.with_values = with_values

        def batches(self, batch_size):
            for i in range(0, n_job, batch_size):
                sl = slice(i, i + batch_size)
                out = {
                    "latitude": lat[sl], "longitude": lon[sl],
                    "user_id": [users[j] for j in uid[sl]],
                    "source": [], "timestamp": [],
                }
                if self.with_values:
                    out["value"] = np.full(len(lat[sl]), 3.0)
                yield out

    # data_parallel=False: see check_fast_vs_bounded's rendezvous note.
    cfg = BatchJobConfig(detail_zoom=14, min_detail_zoom=6,
                         data_parallel=False)
    counted = run_job(_Src(False), config=cfg, batch_size=1 << 16)
    weighted = run_job(_Src(True),
                       config=dataclasses.replace(cfg, weighted=True),
                       batch_size=1 << 16)
    assert counted.keys() == weighted.keys()
    checked = 0
    for key, blob in counted.items():
        c = json.loads(blob)
        w = json.loads(weighted[key])
        assert c.keys() == w.keys(), key
        for tile, cnt in c.items():
            assert w[tile] == 3.0 * cnt, (key, tile, w[tile], cnt)
            checked += 1

    win = window_from_bounds((44.0, 51.0), (-127.0, -117.0), zoom=15,
                             align_levels=12, pad_multiple=256)
    m = min(n, 1 << 20)
    kl = jnp.asarray((47.6 + rng.normal(0, 0.5, m)).astype(np.float32))
    ko = jnp.asarray((-122.3 + rng.normal(0, 0.7, m)).astype(np.float32))
    kw = jnp.asarray(rng.integers(0, 16, m).astype(np.float32))
    r, c, v = mercator.project_points(kl, ko, win.zoom, dtype=jnp.float32)
    a = np.asarray(bin_rowcol_window(r, c, win, weights=kw, valid=v))
    b = np.asarray(bin_rowcol_window_partitioned(r, c, win, weights=kw,
                                                 valid=v))
    np.testing.assert_array_equal(a, b)
    return {"blob_values_checked": checked, "kernel_points": m,
            "kernel_mass": float(a.sum())}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2_000_000)
    ap.add_argument("--checks", default=",".join(CHECKS))
    ap.add_argument("--tpu", action="store_true",
                    help="run on the default backend instead of forcing CPU")
    args = ap.parse_args()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    fns = {"fast-vs-bounded": check_fast_vs_bounded,
           "mesh": check_mesh,
           "dp-job": check_dp_job,
           "resume": check_resume,
           "streaming": check_streaming,
           "weighted": check_weighted}
    failed = 0
    for name in args.checks.split(","):
        name = name.strip()
        if name not in fns:
            raise SystemExit(f"unknown check {name!r}; valid: {CHECKS}")
        tmp = tempfile.mkdtemp(prefix=f"soak-{name}-")
        t0 = time.perf_counter()
        try:
            extra = fns[name](args.n, tmp)
            print(json.dumps({"check": name, "ok": True,
                              "s": round(time.perf_counter() - t0, 1),
                              **extra}), flush=True)
        except Exception as e:  # noqa: BLE001 — run all, report each
            failed += 1
            print(json.dumps({"check": name, "ok": False,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
