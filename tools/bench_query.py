#!/usr/bin/env python
"""Range-query bench: /query latency, integral-vs-fallback A/B, and
fleet-router throughput: BENCH_query.json.

Three headline sections (docs/analytics.md):

- ``direct``   per op (``--ops``), p50/p99 of one ServeApp /query
               request over distinct random rects (every request a
               cache miss — the evaluator is what is being measured)
               on the integral path, next to the SAME rects against a
               copy of the store with its integral artifacts stripped
               (the exact-rows fall-through). ``speedup_p99`` is the
               fallback/integral p99 quotient; the acceptance bar is
               >= 10x for ``sum`` on a warmed store;
- ``router``   sustained RPS + latency percentiles for ``op=sum``
               through a real thread-mode fleet (RouterApp in front of
               ``--backends`` backends relayed over HTTP) — the
               placement key colocates every op over one (layer, z,
               bbox), so repeated analytics of a region ride one
               backend's LRU;
- ``bytes``    integral artifact bytes per zoom next to the exact
               level bytes they index.

The store is built from UNIFORMLY spread points (not the stock
clustered ``synthetic:`` mixture): the hot-spot mixture leaves coarse
levels nearly empty — dozens of occupied cells at z<=9 — so the
exact-rows fall-through costs less than request overhead and the A/B
cannot show the evaluator gap. Uniform points at the default
``--points 200000`` give ~100k occupied cells at the top integral
zoom, the regime integral pyramids exist for.

    PYTHONPATH=.:$PYTHONPATH python tools/bench_query.py \
        [--points 200000] [--iters 300] [--ops sum,topk,quantile] \
        [--out BENCH_query.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time


class _UniformSource:
    """Uniform world-spanning GPS points, a pure function of
    (seed, chunk index) like the stock sources — see the module
    docstring for why the bench wants WIDE levels."""

    def __init__(self, n: int, seed: int):
        self.n, self.seed = int(n), int(seed)

    def close(self) -> None:
        pass

    def batches(self, batch_size: int = 1 << 16):
        import numpy as np

        done = 0
        chunk = 0
        while done < self.n:
            m = min(self.n - done, 1 << 16)
            rng = np.random.default_rng([self.seed, chunk])
            yield {
                "latitude": rng.uniform(-60.0, 70.0, m),
                "longitude": rng.uniform(-179.0, 179.0, m),
                "user_id": ["u%d" % (j % 7) for j in range(done, done + m)],
                "timestamp": [1_500_000_000 + j for j in range(done, done + m)],
                "source": ["gps"] * m,
            }
            done += m
            chunk += 1


def _pct(sorted_vals: list, q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _ops_list(text: str) -> list:
    """Comma-separated op list; each token is validated against
    analytics.VALID_OPS with its one-line error."""
    from heatmap_tpu.analytics import validate_op

    ops = [validate_op(tok.strip()) for tok in text.split(",") if tok.strip()]
    if not ops:
        raise ValueError(f"--ops got no operations in {text!r}")
    return ops


def _top_k(text: str) -> int:
    k = int(text)
    if k < 1:
        raise ValueError(f"--top-k must be >= 1, got {k}")
    return k


def _quantile_q(text: str) -> float:
    q = float(text)
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"--quantile-q must be in [0, 1], got {q}")
    return q


def _rects(rng, n: int, count: int) -> list:
    out = []
    for _ in range(count):
        r0, r1 = sorted(int(v) for v in rng.integers(0, n, 2))
        c0, c1 = sorted(int(v) for v in rng.integers(0, n, 2))
        out.append((r0, c0, r1, c1))
    return out


def _query_path(z: int, rect, op: str, k: int, q: float) -> str:
    r0, c0, r1, c1 = rect
    path = f"/query?layer=default&z={z}&bbox={c0},{r0},{c1},{r1}&op={op}"
    if op == "topk":
        path += f"&k={k}"
    elif op == "quantile":
        path += f"&q={q}"
    return path


def _time_requests(app, paths: list) -> list:
    samples = []
    for path in paths:
        t0 = time.perf_counter()
        res = app.handle("GET", path)
        dt = 1e3 * (time.perf_counter() - t0)
        if res[0] != 200:
            raise SystemExit(f"bench query failed {res[0]}: {path} "
                             f"{res[2][:200]!r}")
        samples.append(dt)
    samples.sort()
    return samples


def bench_direct(level_dir: str, stripped_dir: str, z: int, args) -> dict:
    """Integral vs fall-through A/B over identical rect sequences."""
    import numpy as np

    from heatmap_tpu.serve import ServeApp, TileStore

    rng = np.random.default_rng(args.seed + 1)
    rects = _rects(rng, 1 << z, args.iters)
    out = {}
    for op in args.ops:
        paths = [_query_path(z, r, op, args.top_k, args.quantile_q)
                 for r in rects]
        # Fresh apps per leg: identical cold caches, every distinct
        # rect a miss — the evaluator is what is being measured.
        fast = _time_requests(ServeApp(TileStore(f"arrays:{level_dir}")),
                              paths)
        slow = _time_requests(ServeApp(TileStore(f"arrays:{stripped_dir}")),
                              paths)
        row = {
            "integral_ms": {"p50": _pct(fast, 0.50), "p99": _pct(fast, 0.99)},
            "fallback_ms": {"p50": _pct(slow, 0.50), "p99": _pct(slow, 0.99)},
        }
        if row["integral_ms"]["p99"]:
            row["speedup_p99"] = round(
                row["fallback_ms"]["p99"] / row["integral_ms"]["p99"], 2)
        out[op] = row
    return out


def bench_router(level_dir: str, z: int, args) -> dict:
    """op=sum RPS + latency through a thread-mode fleet router."""
    import numpy as np

    from heatmap_tpu.serve import FleetSupervisor, TileStore, route_key

    rng = np.random.default_rng(args.seed + 2)
    rects = _rects(rng, 1 << z, 64)
    paths = [_query_path(z, r, "sum", args.top_k, args.quantile_q)
             for r in rects]
    # Placement sanity: every op over one (layer, z, bbox) colocates.
    assert route_key(paths[0]) == route_key(
        _query_path(z, rects[0], "topk", args.top_k, args.quantile_q))
    sup = FleetSupervisor(
        None, args.backends, mode="thread",
        store_factory=lambda: TileStore(f"arrays:{level_dir}"),
        cache_bytes=32 << 20, probe_interval_s=0.1,
        monitor_interval_s=0.05)
    try:
        sup.start()
        for path in paths:  # warm every backend's route + caches
            sup.router.handle("GET", path)
        samples = []
        t0 = time.perf_counter()
        for i in range(args.iters):
            path = paths[i % len(paths)]
            s0 = time.perf_counter()
            res = sup.router.handle("GET", path)
            samples.append(1e3 * (time.perf_counter() - s0))
            if res[0] != 200:
                raise SystemExit(
                    f"router query failed {res[0]}: {path}")
        wall = time.perf_counter() - t0
    finally:
        sup.stop()
    samples.sort()
    return {"backends": args.backends, "requests": args.iters,
            "rps": round(args.iters / wall, 1),
            "latency_ms": {"p50": _pct(samples, 0.50),
                           "p99": _pct(samples, 0.99)}}


def bench_bytes(level_dir: str) -> dict:
    """Integral artifact bytes per zoom vs the exact level bytes."""
    per_zoom = {}
    for name in sorted(os.listdir(level_dir)):
        if not (name.startswith("integral-z") and name.endswith(".npz")):
            continue
        zoom = int(name[len("integral-z"):len("integral-z") + 2])
        level = os.path.join(level_dir, f"level_z{zoom:02d}.npz")
        per_zoom[zoom] = {
            "integral_bytes": os.path.getsize(
                os.path.join(level_dir, name)),
            "exact_bytes": (os.path.getsize(level)
                            if os.path.exists(level) else None),
        }
    return per_zoom


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=int, default=200_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--iters", type=int, default=300,
                    help="requests per op and per router window")
    ap.add_argument("--ops", type=_ops_list, default=None,
                    help="comma-separated /query ops to bench "
                    "(default: all)")
    ap.add_argument("--top-k", type=_top_k, default=10)
    ap.add_argument("--quantile-q", type=_quantile_q, default=0.5)
    ap.add_argument("--backends", type=int, default=2)
    ap.add_argument("--out", default="BENCH_query.json")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu import obs
    from heatmap_tpu.analytics import VALID_OPS
    from heatmap_tpu.io import open_sink
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    if args.ops is None:
        args.ops = list(VALID_OPS)
    obs.enable_metrics(True)
    tmpdir = tempfile.mkdtemp(prefix="benchquery-")
    try:
        level_dir = os.path.join(tmpdir, "levels")
        config = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                                result_delta=2)
        with open_sink(f"arrays-integral:{level_dir}") as sink:
            run_job(_UniformSource(args.points, args.seed), sink, config)
        # The A/B twin: same exact rows, integral artifacts stripped.
        stripped = os.path.join(tmpdir, "levels-stripped")
        shutil.copytree(level_dir, stripped)
        for name in os.listdir(stripped):
            if name.startswith("integral-"):
                os.remove(os.path.join(stripped, name))
        z = max(int(n[len("integral-z"):len("integral-z") + 2])
                for n in os.listdir(level_dir)
                if n.startswith("integral-z"))

        direct = bench_direct(level_dir, stripped, z, args)
        print(json.dumps({"zoom": z, "direct": {
            op: {"integral_p99": row["integral_ms"]["p99"],
                 "speedup_p99": row.get("speedup_p99")}
            for op, row in direct.items()}}), flush=True)
        router = bench_router(level_dir, z, args)
        print(json.dumps({"router_rps": router["rps"],
                          "router_p99": router["latency_ms"]["p99"]}),
              flush=True)
        artifact_bytes = bench_bytes(level_dir)
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)

    record = {"bench": "query", "points": args.points, "zoom": z,
              "iters": args.iters, "direct": direct, "router": router,
              "bytes": artifact_bytes}
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
        f.write("\n")
    print(json.dumps({"wrote": args.out}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
