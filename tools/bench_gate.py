#!/usr/bin/env python
"""Bench trend gate: fold BENCH artifacts into series, fail on regression.

Reads every bench artifact the repo's tooling writes —

- ``BENCH_r*.json``   round records (tools/bench.py trajectory): the
  ``parsed.value`` points/sec headline, keyed per device (a cpu
  fallback round must never gate against a tpu round);
- ``BENCH_delta.json``  (tools/bench_delta.py): per-ratio incremental
  apply seconds (lower is better) and full/incremental speedup;
- ``BENCH_serve.json``  (tools/load_gen.py): rps (higher) and p99
  latency ms (lower), plus the fleet scaling curve
  (``serve:fleet:rps[N]`` / ``p99_ms[N]``), kill-one-backend
  availability when ``--fleet`` was run, the flight-recorder A/B
  tax (``obs:recorder_overhead_pct``, lower, noise-floored at 5%),
  the telemetry-sampler A/B tax (``obs:telemetry_overhead_pct``,
  lower, same 5% floor) with the dashboard's ``/series`` polling
  latency (``obs:series_query_p99_ms``, lower, 1 ms floor),
  and — when ``--cold-vs-warm`` ran — the tilefs restart A/B
  (``serve:cold_p99_ms[cold|warmed]`` lower, the cold/warmed
  ``serve:cold_warm_speedup`` higher) plus the mapped/heap fleet
  memory ratio (``serve:fleet_rss_ratio``, lower);
- ``BENCH_adaptive.json`` (tools/load_gen.py --adaptive): overload-
  stage availability for the brownout ramp, controller on and off
  (``adaptive:availability[on|off]``, higher), and the hot-stage p99
  with the ladder active (``adaptive:p99_ms[on]``, lower);
- ``BENCH_ingest.json`` (tools/bench_ingest.py): per micro-batch and
  padding mode, sustained points/sec (higher), ingest->servable p99
  lag ms (lower), and the feeder's transfer-overlap share
  (``ingest:feed_overlap_pct[...]``, higher, noise-floored at 50%);
- ``BENCH_dispatch.json`` (tools/bench_job.py --dispatch-sweep):
  gspmd vs shard_map host-dispatch overhead share per dataset
  (``dispatch:overhead_pct[ds,mode]``, lower) and the gspmd leg's
  end-to-end wall seconds (lower; rows that failed the byte gate are
  never folded);
- ``BENCH_writeplane.json`` (tools/bench_writeplane.py): per writer
  count, multi-writer drain points/sec (``writeplane:pts_per_s[N]``,
  higher) and enqueue->servable p50 lag seconds
  (``writeplane:lag_p50_s[N]``, lower; cells that failed the byte gate
  against the single-writer reference are never folded);
- ``BENCH_synopsis.json`` (tools/bench_synopsis.py): wavelet-synopsis
  exact/synopsis bytes ratio (higher) and pair decode p99 ms (lower);
- ``BENCH_query.json`` (tools/bench_query.py): per-op integral-path
  /query p99 ms (lower), the integral-vs-fallback sum speedup
  (``query:speedup_p99[sum]``, higher — the acceptance bar is >= 10x
  on a warmed store), and fleet-router query RPS (higher) with its
  p99 (lower);
- ``BENCH_temporal.json`` (tools/bench_temporal.py): temporal-plane
  fold p99 ms per cut kind (``temporal:fold_p99_ms[...]``, lower),
  predicate-retraction rows/sec (``temporal:retract_rows_per_s``,
  higher), and ``op=topk_growth`` evaluator p99 ms (lower); nothing
  is folded when the all-time or retraction byte gate failed;
- ``BENCH_partition.json`` (tools/bench_job.py --partition-sweep):
  Morton-range vs uniform-DP modeled merge-volume ratio per dataset
  (``partition:merge_ratio[...]``, higher), the Morton leg's wall
  seconds (lower), and the Zipf plan's skew ratio (lower; rows that
  failed the byte gate are never folded);
- ``onchip_state/sweep.jsonl`` stream cells (tools/bench_stream.py):
  per (backend, batch, device) update-loop points/sec (higher);

— prints the folded trend table, and exits non-zero when the newest
value of any series regresses more than ``--threshold`` (default 15%)
against the best prior round of the same series. Missing artifacts and
series with no prior point are reported and skipped, never failed: the
gate only compares what has actually been measured twice.

``BENCH_r*`` rounds carry their history in-repo. The delta/serve
artifacts are single snapshots, so their history lives in a state file
(``--state``, default BENCH_trend.json): pass ``--update`` to fold the
current values in after a green run (CI does compare-only).

    python tools/bench_gate.py [--threshold 0.15] [--update]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def _load(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_gate: skipping unreadable {path}: {e}",
              file=sys.stderr)
        return None


def round_series(root: str) -> dict:
    """``{series_key: [(round, value), ...]}`` from BENCH_r*.json.
    Higher is better; failed rounds (rc != 0 / no parsed value) are
    skipped."""
    series: dict = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        doc = _load(path)
        if m is None or not isinstance(doc, dict):
            continue
        parsed = doc.get("parsed")
        if doc.get("rc") != 0 or not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)):
            continue
        device = parsed.get("device", "unknown")
        key = f"job:points_per_s[{device}]"
        series.setdefault(key, []).append((int(m.group(1)), float(value)))
    return series


def snapshot_metrics(root: str) -> dict:
    """``{series_key: (value, higher_is_better)}`` from the snapshot
    artifacts (delta + serve benches)."""
    out: dict = {}
    doc = _load(os.path.join(root, "BENCH_delta.json"))
    if isinstance(doc, dict):
        for row in doc.get("results", []):
            ratio = row.get("ratio")
            if ratio is None:
                continue
            if isinstance(row.get("incremental_apply_s"), (int, float)):
                out[f"delta:apply_s[{ratio}]"] = (
                    float(row["incremental_apply_s"]), False)
            if isinstance(row.get("speedup"), (int, float)):
                out[f"delta:speedup[{ratio}]"] = (float(row["speedup"]),
                                                  True)
    doc = _load(os.path.join(root, "BENCH_serve.json"))
    if isinstance(doc, dict):
        if isinstance(doc.get("rps"), (int, float)):
            out["serve:rps"] = (float(doc["rps"]), True)
        p99 = (doc.get("latency_ms") or {}).get("p99")
        if isinstance(p99, (int, float)):
            out["serve:p99_ms"] = (float(p99), False)
        # Fleet scaling curve + kill-one availability (load_gen --fleet).
        fleet = doc.get("fleet") or {}
        for row in fleet.get("curve", []):
            n = row.get("n")
            if n is None:
                continue
            if isinstance(row.get("rps"), (int, float)):
                out[f"serve:fleet:rps[{n}]"] = (float(row["rps"]), True)
            p99 = (row.get("latency_ms") or {}).get("p99")
            if isinstance(p99, (int, float)):
                out[f"serve:fleet:p99_ms[{n}]"] = (float(p99), False)
        kill = fleet.get("kill_one") or {}
        if isinstance(kill.get("availability"), (int, float)):
            out["serve:fleet:kill_one_availability"] = (
                float(kill["availability"]), True)
        # Flight-recorder A/B tax (load_gen._recorder_overhead). Floored
        # at 5% before the relative comparison: the honest value hovers
        # near zero where bench noise would make a ratio gate flap, so
        # the gate only alarms once the recorder costs real throughput
        # (> 5% * (1 + threshold)). The raw value stays in
        # BENCH_serve.json.
        pct = (doc.get("obs") or {}).get("recorder_overhead_pct")
        if isinstance(pct, (int, float)):
            out["obs:recorder_overhead_pct"] = (max(float(pct), 5.0),
                                                False)
        # Telemetry-sampler A/B tax (load_gen._telemetry_overhead) under
        # the same 5% noise floor — the sampler is a background thread
        # with zero hot-path hooks, so any real regression here means
        # someone wired telemetry into the request path. The /series
        # query latency rides along: the dashboard polls it every few
        # seconds, so it must stay interactive.
        pct = (doc.get("obs") or {}).get("telemetry_overhead_pct")
        if isinstance(pct, (int, float)):
            out["obs:telemetry_overhead_pct"] = (max(float(pct), 5.0),
                                                 False)
        q99 = (((doc.get("obs") or {}).get("series_query_ms") or {})
               .get("p99"))
        if isinstance(q99, (int, float)):
            out["obs:series_query_p99_ms"] = (max(float(q99), 1.0),
                                              False)
        # tilefs cold-vs-warmed restart A/B (load_gen --cold-vs-warm):
        # first-touch p99 for both legs, the cold/warmed speedup (the
        # ISSUE bar is warmed materially below cold — a shrinking
        # speedup means the disk tier + prewarm stopped earning their
        # keep), and the fleet Pss ratio of N mmap'd backends vs N
        # heap backends (sub-linear fleet memory; lower is better).
        cw = doc.get("cold_warm") or {}
        for leg in ("cold", "warmed"):
            p99 = ((cw.get(leg) or {}).get("latency_ms") or {}).get("p99")
            if isinstance(p99, (int, float)):
                out[f"serve:cold_p99_ms[{leg}]"] = (float(p99), False)
        if isinstance(cw.get("speedup_p99"), (int, float)):
            out["serve:cold_warm_speedup"] = (float(cw["speedup_p99"]),
                                              True)
        ratio = (doc.get("fleet_rss") or {}).get("pss_ratio")
        if isinstance(ratio, (int, float)):
            out["serve:fleet_rss_ratio"] = (float(ratio), False)
    doc = _load(os.path.join(root, "BENCH_adaptive.json"))
    if isinstance(doc, dict):
        # Brownout ramp (load_gen --adaptive): availability over the
        # overload stages for both legs — the controller-on leg must
        # not quietly lose ground, and the controller-off leg anchors
        # what the same ramp does without the ladder — plus the hot
        # p99 with the ladder active.
        for leg in ("on", "off"):
            row = (doc.get("legs") or {}).get(leg) or {}
            if isinstance(row.get("overload_availability"), (int, float)):
                out[f"adaptive:availability[{leg}]"] = (
                    float(row["overload_availability"]), True)
        p99 = ((doc.get("legs") or {}).get("on") or {}).get(
            "overload_p99_ms")
        if isinstance(p99, (int, float)):
            out["adaptive:p99_ms[on]"] = (float(p99), False)
    doc = _load(os.path.join(root, "BENCH_ingest.json"))
    if isinstance(doc, dict):
        for row in doc.get("results", []):
            batch, mode = row.get("micro_batch"), row.get("mode")
            if batch is None or mode is None:
                continue
            cell = f"{batch},{mode}"
            if isinstance(row.get("pts_per_s"), (int, float)):
                out[f"ingest:pts_per_s[{cell}]"] = (
                    float(row["pts_per_s"]), True)
            p99 = (row.get("lag_ms") or {}).get("p99")
            if isinstance(p99, (int, float)):
                out[f"ingest:lag_p99_ms[{cell}]"] = (float(p99), False)
            # Feeder overlap (pipeline/feeder.py): the share of
            # host->device transfer time hidden behind tick compute
            # must not quietly collapse. Floored at 50% before the
            # relative comparison: on CPU the transfer is near-free
            # and the honest value hovers anywhere in 0..100 where a
            # ratio gate would flap; the raw value stays in
            # BENCH_ingest.json.
            if isinstance(row.get("feed_overlap_pct"), (int, float)):
                out[f"ingest:feed_overlap_pct[{cell}]"] = (
                    max(float(row["feed_overlap_pct"]), 50.0), True)
    doc = _load(os.path.join(root, "BENCH_partition.json"))
    if isinstance(doc, dict):
        # Morton-range sharding A/B (bench_job --partition-sweep): the
        # modeled merge-volume ratio must not shrink, the Morton wall
        # time must not regress, and the Zipf plan's skew must stay
        # bounded (the ISSUE gate is <= 2.0 after re-splitting).
        for row in doc.get("results", []):
            ds = row.get("dataset")
            if ds is None or not row.get("byte_identical"):
                continue
            if isinstance(row.get("merge_ratio"), (int, float)):
                out[f"partition:merge_ratio[{ds}]"] = (
                    float(row["merge_ratio"]), True)
            wall = (row.get("wall_s") or {}).get("morton")
            if isinstance(wall, (int, float)):
                out[f"partition:wall_s[{ds}]"] = (float(wall), False)
            if ds == "zipf" and isinstance(row.get("skew_ratio"),
                                           (int, float)):
                out["partition:skew_ratio[zipf]"] = (
                    float(row["skew_ratio"]), False)
    doc = _load(os.path.join(root, "BENCH_dispatch.json"))
    if isinstance(doc, dict):
        # Device-resident dispatch A/B (bench_job --dispatch-sweep):
        # the host share of a cascade dispatch must not creep back up
        # for either program (the gspmd leg is the product, the
        # shard_map leg anchors what the oracle costs), nor may the
        # gspmd wall time regress; rows that failed the byte gate are
        # never folded.
        for row in doc.get("results", []):
            ds = row.get("dataset")
            if ds is None or not row.get("byte_identical"):
                continue
            for mode in ("gspmd", "shard_map"):
                pct = (row.get("overhead_pct") or {}).get(mode)
                if isinstance(pct, (int, float)):
                    out[f"dispatch:overhead_pct[{ds},{mode}]"] = (
                        float(pct), False)
            wall = (row.get("wall_s") or {}).get("gspmd")
            if isinstance(wall, (int, float)):
                out[f"dispatch:wall_s[{ds}]"] = (float(wall), False)
    doc = _load(os.path.join(root, "BENCH_writeplane.json"))
    if isinstance(doc, dict):
        # Partitioned write plane (bench_writeplane): per writer count,
        # drain throughput (higher) and enqueue->servable p50 lag
        # seconds (lower); cells that failed the byte gate against the
        # single-writer reference are never folded.
        for row in doc.get("results", []):
            n = row.get("writers")
            if n is None or not row.get("byte_identical"):
                continue
            if isinstance(row.get("pts_per_s"), (int, float)):
                out[f"writeplane:pts_per_s[{n}]"] = (
                    float(row["pts_per_s"]), True)
            p50 = (row.get("lag_s") or {}).get("p50")
            if isinstance(p50, (int, float)):
                out[f"writeplane:lag_p50_s[{n}]"] = (float(p50), False)
    doc = _load(os.path.join(root, "BENCH_synopsis.json"))
    if isinstance(doc, dict):
        ratio = (doc.get("compression") or {}).get("bytes_ratio")
        if isinstance(ratio, (int, float)):
            out["synopsis:bytes_ratio"] = (float(ratio), True)
        p99 = ((doc.get("decode") or {}).get("decode_ms") or {}).get("p99")
        if isinstance(p99, (int, float)):
            out["synopsis:decode_p99"] = (float(p99), False)
    doc = _load(os.path.join(root, "BENCH_query.json"))
    if isinstance(doc, dict):
        # Range-query engine (bench_query): integral-path latency per
        # op, the sum A/B speedup (the ISSUE bar is >= 10x), and the
        # fleet-router throughput leg.
        for op, row in (doc.get("direct") or {}).items():
            p99 = (row.get("integral_ms") or {}).get("p99")
            if isinstance(p99, (int, float)):
                out[f"query:{op}_p99_ms"] = (float(p99), False)
            if op == "sum" and isinstance(row.get("speedup_p99"),
                                          (int, float)):
                out["query:speedup_p99[sum]"] = (
                    float(row["speedup_p99"]), True)
        router = doc.get("router") or {}
        if isinstance(router.get("rps"), (int, float)):
            out["query:router_rps"] = (float(router["rps"]), True)
        p99 = (router.get("latency_ms") or {}).get("p99")
        if isinstance(p99, (int, float)):
            out["query:router_p99_ms"] = (float(p99), False)
    doc = _load(os.path.join(root, "BENCH_temporal.json"))
    if isinstance(doc, dict):
        # Temporal plane (bench_temporal): fold latency per cut kind
        # and growth-query latency (lower), retraction throughput
        # (higher). The all-time byte gate guards every cell — a fast
        # fold that diverged from the un-bucketed overlay is a bug,
        # not a win.
        if doc.get("alltime_byte_identical"):
            for cut, row in (doc.get("fold") or {}).items():
                p99 = (row.get("ms") or {}).get("p99")
                if isinstance(p99, (int, float)):
                    out[f"temporal:fold_p99_ms[{cut}]"] = (float(p99),
                                                           False)
            p99 = ((doc.get("growth") or {}).get("ms") or {}).get("p99")
            if isinstance(p99, (int, float)):
                out["temporal:growth_p99_ms"] = (float(p99), False)
        retract = doc.get("retract") or {}
        if retract.get("byte_identical") and isinstance(
                retract.get("rows_per_s"), (int, float)):
            out["temporal:retract_rows_per_s"] = (
                float(retract["rows_per_s"]), True)
    out.update(stream_metrics(root))
    return out


def stream_metrics(root: str) -> dict:
    """Stream-bench cells from the on-chip sweep JSONL (the relay's
    append-only state file; non-stream checks and unparsable lines are
    ignored). Last row wins per cell, matching the resume contract —
    a re-measured cell supersedes the crashed attempt's row."""
    out: dict = {}
    path = os.path.join(root, "onchip_state", "sweep.jsonl")
    if not os.path.exists(path):
        return out
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        print(f"bench_gate: skipping unreadable {path}: {e}",
              file=sys.stderr)
        return out
    for line in lines:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict) or rec.get("check") != "stream":
            continue
        if not isinstance(rec.get("pts_per_s"), (int, float)):
            continue
        cell = (f"{rec.get('backend')},{rec.get('batch')},"
                f"{rec.get('device', 'unknown')}")
        out[f"stream:pts_per_s[{cell}]"] = (float(rec["pts_per_s"]), True)
    return out


def regression(best_prior: float, current: float,
               higher_is_better: bool) -> float:
    """Fractional regression of ``current`` vs ``best_prior`` (>0 means
    worse); best_prior must be > 0."""
    if higher_is_better:
        return (best_prior - current) / best_prior
    return (current - best_prior) / best_prior


def main() -> int:
    ap = argparse.ArgumentParser(
        description="fail when the newest bench round regresses >15%")
    ap.add_argument("--root", default=".",
                    help="directory holding the BENCH artifacts")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="max tolerated fractional regression")
    ap.add_argument("--state", default="BENCH_trend.json",
                    help="trend history for the snapshot artifacts "
                    "(relative to --root)")
    ap.add_argument("--update", action="store_true",
                    help="fold current snapshot values into --state "
                    "after a green comparison")
    args = ap.parse_args()

    failures, compared, skipped = [], 0, 0

    # BENCH_r* rounds: newest round vs the best earlier one per series.
    for key, points in sorted(round_series(args.root).items()):
        points.sort()
        if len(points) < 2:
            skipped += 1
            print(f"  {key:32s} r{points[-1][0]:02d}={points[-1][1]:,.0f}"
                  f"  (no prior round; skipped)")
            continue
        cur_round, cur = points[-1]
        best_round, best = max(points[:-1], key=lambda p: p[1])
        reg = regression(best, cur, higher_is_better=True)
        compared += 1
        verdict = "REGRESSION" if reg > args.threshold else "ok"
        print(f"  {key:32s} r{cur_round:02d}={cur:,.0f} vs best "
              f"r{best_round:02d}={best:,.0f}  "
              f"({-reg:+.1%})  {verdict}")
        if reg > args.threshold:
            failures.append(key)

    # Snapshot artifacts vs the recorded trend state.
    state_path = os.path.join(args.root, args.state)
    state = _load(state_path) if os.path.exists(state_path) else None
    history = state.get("series", {}) if isinstance(state, dict) else {}
    current = snapshot_metrics(args.root)
    for key, (value, higher) in sorted(current.items()):
        prior = [v for v in history.get(key, [])
                 if isinstance(v, (int, float)) and v > 0]
        if not prior:
            skipped += 1
            print(f"  {key:32s} {value:g}  (no prior; skipped)")
            continue
        best = max(prior) if higher else min(prior)
        reg = regression(best, value, higher)
        compared += 1
        verdict = "REGRESSION" if reg > args.threshold else "ok"
        print(f"  {key:32s} {value:g} vs best {best:g}  "
              f"({-reg:+.1%})  {verdict}")
        if reg > args.threshold:
            failures.append(key)

    if failures:
        print(f"bench_gate: FAIL — {len(failures)} series regressed "
              f"past {args.threshold:.0%}: {', '.join(failures)}")
        return 1
    if args.update and current:
        for key, (value, _higher) in current.items():
            history.setdefault(key, []).append(value)
        tmp = state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"series": history}, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, state_path)
        print(f"bench_gate: folded {len(current)} series into "
              f"{state_path}")
    print(f"bench_gate: ok ({compared} compared, {skipped} skipped)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
