"""Chaos soak: the full pipeline under deterministic fault injection,
byte-identical to a fault-free run.

Drives ingest -> cascade -> delta apply -> compact -> serve twice over
the same synthetic input: once clean, once with a seeded fault plane
(faults/plane.py) firing hundreds of injected failures across every
site — source reads, sink publishes, journal appends, compaction
publishes, shard compute, tile renders, HTTP requests, and lost
multihost heartbeats. A separate phase soaks the continuous-ingest
loop (heatmap_tpu/ingest/): an ``ingest.*`` storm the retries absorb,
then a kill mid-tick whose restart must heal exactly-once and serve
byte-identical to a one-shot apply. A dispatch phase storms the
double-buffered host->device feeder (``feeder.put``): absorbed
transfer faults re-feed the same batch invisibly, a kill mid-feed
crashes the loop with exactly the fed-ahead ticks journaled, and the
restart re-feeds the crashed batch exactly-once — served bytes
identical to an unfed one-shot apply. A host-loss phase kills one
simulated host mid-cascade (its heartbeats eaten by the
``multihost.heartbeat`` site) and requires the elastic layer
(heatmap_tpu/parallel/elastic.py) to reassign its shards and still
produce byte-identical arrays and tiles. A backend-loss phase SIGKILLs
one process of a 3-backend serve fleet (serve/fleet.py) under Zipf
load: the router's failover must keep clients at zero 5xx, the breaker
must open and re-close through the supervisor restart + half-open
probe, and the recovered fleet must serve bytes identical to the clean
single-process run. A synopsis phase tears a wavelet-synopsis artifact
mid-write: the recovery sweep must quarantine it, serving must fall
back to exact bytes for that level while other levels keep their
synopses, and no request may see a 500. A query phase does the same to
an integral-histogram artifact: the sweep must quarantine the torn
integral and its orphaned staging tmp, /query must fall through to
exact level rows with answers identical modulo the path marker, and
the surviving zooms must keep their O(1) fast path. A temporal phase
tears one time bucket under a bucketed store mid-serve: warmed
``?as_of``/``?decay`` tiles must keep answering their last-good bytes
(stale-if-error), the sweep must quarantine exactly the torn bucket,
all-time tiles must stay byte-identical (the plain path never reads
buckets), and no request may see a 5xx. A tilefs phase
serves a converted store zero-copy through the disk render cache while
``tilefs.read`` faults force per-zoom npz fallbacks mid-reload,
``diskcache.write`` faults skip fills, a torn disk-cache entry must
read as a miss, and a torn mirror + crashed staging tmp must be
quarantined — bytes identical to heap serving at every step, never a
500 (heatmap_tpu.tilefs, docs/tilefs.md). An adaptive phase
scripts one
overload episode against the brownout controller (serve/degrade.py)
under a fake clock: the ladder must step up 0->1->2->3 and walk back
down identically across repeat runs, with zero 500s and — recovered at
rung 0 — bytes identical to a controller-less server. The chaos run
must converge to the *same bytes*:
level arrays, journal state, and every served JSON tile. Along the way
the HTTP tier must degrade gracefully (typed 503s / stale serves,
``/healthz`` reporting ``degraded``) and never return a 500.

Usage:
    python tools/chaos_soak.py [--n 3000] [--chaos SPEC] [--keep]

Every phase reports one JSON line; the exit code is non-zero if any
failed. A fast subset runs in tier-1 as tests/test_chaos.py (-m chaos).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
import traceback
import urllib.error
import urllib.parse
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_enable_x64", True)  # composite keys need int64

import numpy as np

from heatmap_tpu import delta, faults, obs
from heatmap_tpu.io.sinks import LevelArraysSink
from heatmap_tpu.io.sources import SyntheticSource
from heatmap_tpu.parallel.multihost import (StragglerTimeout,
                                            check_heartbeats,
                                            run_job_multihost)
from heatmap_tpu.pipeline import BatchJobConfig, run_job
from heatmap_tpu.serve import ServeApp, TileCache, TileStore, serve_in_thread
from heatmap_tpu.tilemath.morton import morton_decode_np
from heatmap_tpu.utils.recovery import run_shards

CFG = BatchJobConfig(detail_zoom=10, min_detail_zoom=8, result_delta=2)

#: Default plane: count rules spaced so transient bursts stay inside
#: each site's retry budget (faults/retry.py POLICIES), probability
#: rules on the serve tier where the HTTP client retries 503s.
DEFAULT_CHAOS = ",".join([
    "seed=11", "scale=0",
    "source.read=60x2",
    "sink.write=30x2",
    "journal.append=8x2",
    "compact.publish=4x2",
    "shard.compute=40x3",
    "tile.render=p0.3",
    "http.request=p0.2",
    "multihost.heartbeat=6x2",
])

FETCH_ATTEMPTS = 64  # per-URL 503-retry budget under probability rules

#: Ingest-phase storms (the continuous loop has its own plane: the
#: chaos plane above is spent by the time the ingest phase runs).
#: Absorbed storm: one ingest.tick + one ingest.publish fault per tick,
#: inside both retry budgets, so the loop result must be unchanged.
INGEST_CHAOS = "seed=13,scale=0,ingest.tick=2x2,ingest.publish=2x2"
#: Kill storm: every journal append fails past the whole retry stack
#: (3 ingest.tick attempts x 4 journal.append attempts), crashing the
#: first non-duplicate tick AFTER its artifact dir is written — the
#: torn state delta/recover.py heals on the next run's startup sweep.
INGEST_KILL = "seed=13,scale=0,journal.append=99"

#: Write-plane storms (the writer_loss phase installs its own planes).
#: Absorbed storm: spaced writeplane.append + writeplane.publish
#: faults, each inside its site's retry budget — the 3-writer drain
#: must complete with zero failed batches and byte-identical output.
WRITEPLANE_CHAOS = ("seed=17,scale=0,"
                    "writeplane.append=4x3,writeplane.publish=2x3")
#: Kill storm: every apply on range r001 fails past the whole retry
#: budget — that pump dies mid-run (writer loss), the survivors keep
#: applying and publishing manifest epochs, and the dead range's
#: batches are never ledgered, so a restart re-drain heals them
#: exactly-once.
WRITEPLANE_KILL = "seed=17,scale=0,writeplane.append@r001=99"


# ---------------------------------------------------------------- pipeline

def _pipeline(root: str, arrays_dir: str, n: int):
    """Ingest -> cascade -> 3 delta applies -> compact -> post-compact
    apply. Identical call sequence for the clean and chaos runs."""
    run_job(SyntheticSource(n=n, seed=7), LevelArraysSink(arrays_dir),
            config=CFG, batch_size=512)
    shards = [(i, min(i + 8, 96)) for i in range(0, 96, 8)]
    digests = run_shards(shards, lambda s: s[1] - s[0], retries=2)
    applies = [
        delta.apply_batch(root, SyntheticSource(n=n // 3, seed=1), CFG,
                          batch_size=256),
        delta.apply_batch(root, SyntheticSource(n=n // 3, seed=2), CFG,
                          batch_size=256),
        delta.apply_batch(root, SyntheticSource(n=n // 4, seed=3), CFG,
                          batch_size=256),
    ]
    summary = delta.compact(root)
    post = delta.apply_batch(root, SyntheticSource(n=n // 5, seed=4), CFG,
                             batch_size=256)
    return {"shard_rows": int(sum(digests)),
            "epochs": [r.epoch for r in applies + [post]],
            "compact": summary.get("base"),
            "points": int(sum(r.points for r in applies + [post]))}


def _tile_coords(store: TileStore):
    """Every servable JSON tile of every layer, from the stored Morton
    codes (the tests/test_delta.py enumeration)."""
    coords = []
    for name, layer in sorted(store.layers.items()):
        if name == "default":
            continue
        shift = 2 * layer.result_delta
        for want, level in layer.levels.items():
            z = want - layer.result_delta
            if z < 0:
                continue
            rows, cols = morton_decode_np(np.unique(level.codes >> shift))
            for r, c in zip(rows, cols):
                coords.append((name, z, int(c), int(r)))
    return coords


def _get(url: str):
    """-> (status, body). 503s come back as data, not exceptions."""
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _fetch_all(base: str, coords, ctx):
    """Fetch every tile, retrying typed 503s; record status codes and
    whether /healthz reported ``degraded`` while render faults were
    live. Any 500, or a URL that never converges, is a failure."""
    docs, probes = {}, 0
    for name, z, x, y in coords:
        url = (f"{base}/tiles/{urllib.parse.quote(name, safe='')}"
               f"/{z}/{x}/{y}.json")
        for attempt in range(FETCH_ATTEMPTS):
            status, body = _get(url)
            ctx["codes"][status] = ctx["codes"].get(status, 0) + 1
            assert status != 500, f"HTTP 500 from {url}: {body[:200]!r}"
            if status == 200:
                docs[(name, z, x, y)] = body
                break
            assert status == 503, f"unexpected {status} from {url}"
            # A render fault just degraded the app: /healthz must say so
            # (itself retried through http.request faults).
            if b"render" in body and not ctx["saw_degraded"] and probes < 8:
                probes += 1
                for _ in range(FETCH_ATTEMPTS):
                    hs, hb = _get(f"{base}/healthz")
                    assert hs != 500
                    if hs == 200:
                        health = json.loads(hb)
                        if health.get("status") == "degraded":
                            ctx["saw_degraded"] = True
                            ctx["degraded_causes"] = health.get("degraded")
                        break
        else:
            raise AssertionError(f"{url} never returned 200 in "
                                 f"{FETCH_ATTEMPTS} attempts")
    return docs


def _serve_docs(root: str, ctx=None, kind: str = "delta"):
    """Serve a store root over real HTTP and fetch every tile."""
    ctx = ctx if ctx is not None else {"codes": {}, "saw_degraded": False}
    store = TileStore(f"{kind}:{root}")
    app = ServeApp(store, TileCache(max_bytes=64 << 20),
                   render_timeout_s=30.0)
    server, base = serve_in_thread(app)
    try:
        docs = _fetch_all(base, _tile_coords(store), ctx)
    finally:
        server.shutdown()
    ctx["docs"] = docs
    return ctx


def _levels_bytes(path: str) -> dict:
    out = {}
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if os.path.isfile(full):
            with open(full, "rb") as f:
                out[name] = f.read()
    return out


# ------------------------------------------------------------------ phases

def phase_baseline(ctx):
    faults.install(None)
    t0 = time.monotonic()
    info = _pipeline(ctx["base_root"], ctx["base_arrays"], ctx["n"])
    served = _serve_docs(ctx["base_root"])
    ctx["base_docs"] = served["docs"]
    assert served["codes"].get(500, 0) == 0
    return {**info, "tiles": len(served["docs"]),
            "seconds": round(time.monotonic() - t0, 1)}


def phase_chaos_pipeline(ctx):
    plane = faults.install_spec(ctx["chaos"])
    t0 = time.monotonic()
    info = _pipeline(ctx["chaos_root"], ctx["chaos_arrays"], ctx["n"])
    return {**info, "faults_so_far": plane.injected,
            "seconds": round(time.monotonic() - t0, 1)}


def phase_chaos_serve(ctx):
    """Serve the chaos store while render/request faults are still
    firing: every tile must converge to 200 (typed 503s in between,
    never a 500) and /healthz must report ``degraded`` mid-storm."""
    served = _serve_docs(ctx["chaos_root"],
                         ctx.setdefault("serve_ctx",
                                        {"codes": {}, "saw_degraded": False}))
    ctx["chaos_docs"] = served["docs"]
    codes = served["codes"]
    assert codes.get(500, 0) == 0, f"500s observed: {codes}"
    assert codes.get(503, 0) > 0, \
        f"soak never exercised the degraded path: {codes}"
    assert served["saw_degraded"], "/healthz never reported degraded"
    return {"codes": {str(k): v for k, v in sorted(codes.items())},
            "tiles": len(served["docs"]),
            "degraded_causes": served.get("degraded_causes")}


def phase_heartbeat(ctx):
    """Lost-heartbeat detection: injected multihost.heartbeat faults
    suppress the liveness gauge, and the deadline monitor raises a
    typed StragglerTimeout once the surviving mark goes stale."""
    obs.enable_metrics(True)
    try:
        plane = faults.get_plane()
        before = plane.counts().get("multihost.heartbeat", 0)
        for _ in range(12):
            obs.heartbeat("soak")  # every other one is lost in transit
        lost = plane.counts().get("multihost.heartbeat", 0) - before
        assert lost >= 4, f"heartbeat faults never fired ({lost})"
        ages = check_heartbeats(deadline_s=3600.0)  # fresh: no straggler
        try:
            check_heartbeats(deadline_s=0.5, now=time.time() + 10)
        except StragglerTimeout as e:
            stale = e.stale
        else:
            raise AssertionError("stale heartbeats went undetected")
        return {"lost": lost, "ages": {k: round(v, 3) for k, v in
                                       ages.items()},
                "stale_processes": sorted(stale)}
    finally:
        obs.enable_metrics(False)


def phase_fault_floor(ctx):
    """The acceptance floor: >= 200 injected faults across >= 6 sites."""
    counts = faults.get_plane().counts()
    total = sum(counts.values())
    assert total >= 200, f"only {total} faults injected: {counts}"
    assert len(counts) >= 6, f"only {len(counts)} sites fired: {counts}"
    ctx["fault_counts"] = counts
    return {"total": total, "sites": counts}


def phase_byte_equality(ctx):
    """The anchor: the chaos run's bytes are identical to the clean
    run's — level arrays from the cascade AND every served tile."""
    faults.install(None)
    a = _levels_bytes(ctx["base_arrays"])
    b = _levels_bytes(ctx["chaos_arrays"])
    assert sorted(a) == sorted(b), "level-array file sets diverged"
    for name in a:
        assert a[name] == b[name], f"level arrays diverged at {name}"
    base, chaos = ctx["base_docs"], ctx["chaos_docs"]
    assert sorted(base) == sorted(chaos), (
        f"served tile sets diverged: {len(base)} vs {len(chaos)}")
    mism = [k for k in base if base[k] != chaos[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    # Fault-free aftermath: the degraded flags cleared and the chaos
    # store serves clean (no stale 503s linger once the plane is gone).
    served = _serve_docs(ctx["chaos_root"])
    assert served["codes"].get(503, 0) == 0
    assert served["codes"].get(500, 0) == 0
    return {"levels": len(a), "tiles": len(base),
            "clean_refetch_codes": {str(k): v for k, v in
                                    sorted(served["codes"].items())}}


def phase_ingest_crash(ctx):
    """The continuous-ingest loop under an ``ingest.*`` storm with a
    kill mid-tick: absorbed faults are invisible in the outcome, the
    killed run heals exactly-once on restart (duplicates no-op, the
    crashed batch re-journals, the orphan artifact is swept), and the
    recovered store serves byte-identical to a one-shot apply of the
    same points. Runs after fault_floor — it installs its own planes."""
    from heatmap_tpu import ingest

    n = ctx["n"]
    cols: dict = {}
    for batch in SyntheticSource(n=n, seed=21).batches(1 << 20):
        for c, v in batch.items():
            cols.setdefault(c, []).extend(v)
    micro = max(1, -(-n // 4))  # 4 ticks
    ticks_total = -(-n // micro)
    root = os.path.join(os.path.dirname(ctx["base_root"]), "store-ingest")
    # The loop runs the bucketed compile cache; the one-shot reference
    # stays exact — byte-neutrality of the padding is part of the soak.
    icfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                          result_delta=2, pad_bucketing="pow2",
                          pad_bucket_min=1 << 8)

    # A live store rides through the whole phase so every tick also
    # publishes (exercising the ingest.publish site and its faults).
    delta.init_store(root)
    store, cache = TileStore(f"delta:{root}"), TileCache()

    # 1. Absorbed storm: the first two ticks land despite one tick
    #    fault and one publish fault each (inside the retry budgets).
    plane = faults.install_spec(INGEST_CHAOS)
    first = ingest.run_ingest(
        root, delta.ColumnsSource(cols), icfg, store=store, cache=cache,
        ingest=ingest.IngestConfig(micro_batch=micro, queue_depth=2,
                                   compact_every=0, max_ticks=2))
    absorbed = plane.injected
    assert first.ticks == 2 and first.duplicates == 0, vars(first)
    assert absorbed >= 4, f"absorbed storm never fired ({absorbed})"

    # 2. Kill mid-tick: duplicates sail through (the dedup path never
    #    reaches journal.append), the first fresh tick dies with its
    #    artifact dir orphaned.
    faults.install_spec(INGEST_KILL)
    try:
        ingest.run_ingest(root, delta.ColumnsSource(cols), icfg,
                          store=store, cache=cache,
                          ingest=ingest.IngestConfig(micro_batch=micro,
                                                     queue_depth=2,
                                                     compact_every=0))
    except faults.InjectedFault:
        pass
    else:
        raise AssertionError("kill storm never crashed the loop")
    faults.install(None)
    assert len(delta.live_entries(root)) == 2, "crashed tick journaled"

    # 3. Recovery: re-drain the whole source; exactly-once epochs.
    stats = ingest.run_ingest(root, delta.ColumnsSource(cols), icfg,
                              store=store, cache=cache,
                              ingest=ingest.IngestConfig(
                                  micro_batch=micro, queue_depth=2,
                                  compact_every=0))
    assert stats.ticks == ticks_total and stats.duplicates == 2, \
        vars(stats)
    live = delta.live_entries(root)
    hashes = [e["content_hash"] for e in live]
    assert len(live) == ticks_total and len(set(hashes)) == ticks_total
    epochs = [e["epoch"] for e in live]
    assert epochs == sorted(epochs)

    # 4. Byte identity vs a one-shot apply of the union.
    ref = os.path.join(os.path.dirname(ctx["base_root"]),
                       "store-ingest-ref")
    delta.apply_batch(ref, delta.ColumnsSource(cols),
                      BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                                     result_delta=2))
    got = _serve_docs(root)["docs"]
    want = _serve_docs(ref)["docs"]
    assert sorted(got) == sorted(want), (
        f"served tile sets diverged: {len(got)} vs {len(want)}")
    mism = [k for k in want if got[k] != want[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    return {"ticks": ticks_total, "absorbed_faults": absorbed,
            "epochs": epochs, "tiles": len(got)}


def phase_writer_loss(ctx):
    """The partitioned write plane under its own storms: an absorbed
    append/publish storm is invisible in the outcome; killing 1 of 3
    writers mid-apply leaves the survivors applying and publishing
    manifest epochs; a restart re-drain of the same stream heals the
    dead range exactly-once and the plane serves byte-identical to a
    single-writer delta store fed the same micro-batches."""
    from heatmap_tpu.writeplane import PlaneConfig, WritePlane, \
        run_plane_ingest

    n = ctx["n"]
    wcfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                          result_delta=2)
    micro = max(1, -(-n // 6))  # 6 micro-batches
    base_dir = os.path.dirname(ctx["base_root"])

    # Single-writer reference over the same micro-batches.
    ref = os.path.join(base_dir, "store-wp-ref")
    for batch in SyntheticSource(n=n, seed=23).batches(micro):
        delta.apply_batch(ref, delta.ColumnsSource(batch), wcfg)

    # 1. Absorbed storm: spaced append + publish faults inside the
    #    retry budgets — the drain completes as if nothing happened.
    root_a = os.path.join(base_dir, "wp-absorbed")
    plane = faults.install_spec(WRITEPLANE_CHAOS)
    stats = run_plane_ingest(
        WritePlane(root_a, wcfg, PlaneConfig(n_writers=3)),
        SyntheticSource(n=n, seed=23), micro_batch=micro)
    absorbed = plane.injected
    faults.install(None)
    assert stats.failed == 0 and stats.completed == stats.batches, \
        vars(stats)
    assert absorbed >= 4, f"absorbed storm never fired ({absorbed})"
    got = _serve_docs(root_a, kind="writeplane")["docs"]
    want = _serve_docs(ref)["docs"]
    assert sorted(got) == sorted(want) and all(
        got[k] == want[k] for k in want), "absorbed storm changed bytes"

    # 2. Writer loss: r001's pump dies terminally mid-run; the other
    #    two writers keep applying and the manifest keeps advancing.
    root_k = os.path.join(base_dir, "wp-killed")
    faults.install_spec(WRITEPLANE_KILL)
    stats = run_plane_ingest(
        WritePlane(root_k, wcfg, PlaneConfig(n_writers=3)),
        SyntheticSource(n=n, seed=23), micro_batch=micro)
    faults.install(None)
    assert stats.pumps["r001"].dead, "kill storm never killed the pump"
    assert stats.failed > 0
    assert stats.epoch > 1, "survivors stopped publishing"
    survivors = [p for name, p in stats.pumps.items() if name != "r001"]
    assert any(p.applied for p in survivors), "survivors applied nothing"

    # 3. Restart re-drain heals exactly-once: survivors' sub-batches
    #    dedup in their range journals, r001 applies its missing
    #    halves, and the plane converges to the reference bytes.
    heal = run_plane_ingest(
        WritePlane(root_k, wcfg, PlaneConfig(n_writers=3)),
        SyntheticSource(n=n, seed=23), micro_batch=micro)
    assert heal.failed == 0, vars(heal)
    got = _serve_docs(root_k, kind="writeplane")["docs"]
    assert sorted(got) == sorted(want), (
        f"served tile sets diverged: {len(got)} vs {len(want)}")
    mism = [k for k in want if got[k] != want[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    return {"absorbed_faults": absorbed, "batches": stats.batches,
            "healed_duplicates": heal.duplicates, "tiles": len(got)}


#: dispatch-phase storms (the feeder has its own planes, installed
#: here). Absorbed storm: two spaced ``feeder.put`` faults, each inside
#: the site's retry budget, so the re-fed batches are invisible.
DISPATCH_CHAOS = "seed=31,scale=0,feeder.put=2x2"
#: Kill storm: batch index 2's transfer fails past the whole retry
#: budget — the loop crashes mid-feed after the fed-ahead ticks landed.
DISPATCH_KILL = "seed=31,scale=0,feeder.put@2=99"


def phase_dispatch(ctx):
    """The double-buffered feeder (pipeline/feeder.py) under a
    ``feeder.put`` storm with a kill mid-feed: absorbed faults re-feed
    the same batch invisibly (``device_put`` is idempotent), the killed
    run crashes with exactly the fed-ahead ticks journaled, the restart
    re-feeds the crashed batch and the journal's content hashes keep
    every batch exactly-once, and the recovered store serves
    byte-identical to a one-shot apply of the same points. The overlap
    telemetry must show the feeder actually ran ahead. Installs its own
    planes (runs after fault_floor)."""
    from heatmap_tpu import ingest

    n = ctx["n"]
    cols: dict = {}
    for batch in SyntheticSource(n=n, seed=23).batches(1 << 20):
        for c, v in batch.items():
            cols.setdefault(c, []).extend(v)
    micro = max(1, -(-n // 6))  # 6 ticks: 2 land, 1 killed, 3 recovered
    ticks_total = -(-n // micro)
    assert ticks_total >= 4, ticks_total
    root = os.path.join(os.path.dirname(ctx["base_root"]),
                        "store-dispatch")
    # Multi-device runs soak the one-program gspmd dispatch under the
    # storm too (parallel/gspmd.py); single-device runs still pin the
    # feeder contract on the plain path.
    dcfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                          result_delta=2,
                          data_parallel=True if len(jax.devices()) > 1
                          else None)
    icfg = ingest.IngestConfig(micro_batch=micro, queue_depth=2,
                               compact_every=0, feed_depth=1)

    delta.init_store(root)
    store, cache = TileStore(f"delta:{root}"), TileCache()

    # 1. Absorbed storm: the first two ticks land despite one transfer
    #    fault each (inside the feeder.put retry budget).
    plane = faults.install_spec(DISPATCH_CHAOS)
    first = ingest.run_ingest(
        root, delta.ColumnsSource(cols), dcfg, store=store, cache=cache,
        ingest=dataclasses.replace(icfg, max_ticks=2))
    absorbed = plane.injected
    assert first.ticks == 2 and first.duplicates == 0, vars(first)
    assert absorbed >= 2, f"absorbed storm never fired ({absorbed})"

    # 2. Kill mid-feed: duplicates of the landed ticks sail through the
    #    feeder, then batch 2's transfer dies past its retries — the
    #    worker aborts, the in-flight batches drain, and the loop
    #    crashes with nothing new journaled.
    faults.install_spec(DISPATCH_KILL)
    try:
        ingest.run_ingest(root, delta.ColumnsSource(cols), dcfg,
                          store=store, cache=cache, ingest=icfg)
    except faults.InjectedFault as e:
        assert e.site == "feeder.put", e
    else:
        raise AssertionError("feeder kill never crashed the loop")
    faults.install(None)
    assert len(delta.live_entries(root)) == 2, "crashed feed journaled"

    # 3. Recovery: re-drain the whole source; the crashed batch is
    #    re-fed and every batch lands exactly once.
    stats = ingest.run_ingest(root, delta.ColumnsSource(cols), dcfg,
                              store=store, cache=cache, ingest=icfg)
    assert stats.ticks == ticks_total and stats.duplicates == 2, \
        vars(stats)
    assert stats.feeder_depth_hwm >= 1, vars(stats)
    live = delta.live_entries(root)
    hashes = [e["content_hash"] for e in live]
    assert len(live) == ticks_total and len(set(hashes)) == ticks_total
    epochs = [e["epoch"] for e in live]
    assert epochs == sorted(epochs)

    # 4. Byte identity vs a one-shot (unfed, single-dispatch) apply.
    ref = os.path.join(os.path.dirname(ctx["base_root"]),
                       "store-dispatch-ref")
    delta.apply_batch(ref, delta.ColumnsSource(cols),
                      BatchJobConfig(detail_zoom=10, min_detail_zoom=8,
                                     result_delta=2))
    got = _serve_docs(root)["docs"]
    want = _serve_docs(ref)["docs"]
    assert sorted(got) == sorted(want), (
        f"served tile sets diverged: {len(got)} vs {len(want)}")
    mism = [k for k in want if got[k] != want[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    return {"ticks": ticks_total, "absorbed_faults": absorbed,
            "refed_batch": 2, "epochs": epochs,
            "feed_overlap_pct": round(stats.feed_overlap_pct, 1),
            "feeder_depth_hwm": stats.feeder_depth_hwm,
            "tiles": len(got)}


#: host_loss wedge: the wedged worker installs this spec the moment it
#: stops beating, so simulated host 2 is alive and visible up to that
#: point and every later beat is eaten by the ``multihost.heartbeat``
#: fault site — a mid-cascade host death, not a host that never joined.
HOST_LOSS_WEDGE = "seed=29,scale=0,multihost.heartbeat@p2=999"


def phase_host_loss(ctx):
    """Elastic execution under a mid-cascade host death: one simulated
    host completes a shard then stops heartbeating (its beats are eaten
    by the ``multihost.heartbeat`` fault site); the monitor flags it
    stale, its shards are reassigned to the survivors, the job
    completes, and the merged level arrays AND every served tile are
    byte-identical to an unfailed elastic run."""
    faults.install(None)
    tmp = os.path.dirname(ctx["base_root"])
    src = lambda: SyntheticSource(n=ctx["n"], seed=3)  # noqa: E731
    bs = max(1, ctx["n"] // 6)  # 6 batches -> 6 shards over 3 hosts
    obs.enable_metrics(True)
    try:
        obs.get_registry().reset()
        ok = run_job_multihost(
            src(), LevelArraysSink(os.path.join(tmp, "arrays-elastic-ok")),
            CFG, batch_size=bs, on_straggler="reassign",
            elastic_dir=os.path.join(tmp, "elastic-ok"), elastic_hosts=3)
        obs.get_registry().reset()
        lost = run_job_multihost(
            src(), LevelArraysSink(os.path.join(tmp, "arrays-elastic-loss")),
            CFG, batch_size=bs, heartbeat_deadline_s=0.3,
            on_straggler="reassign",
            elastic_dir=os.path.join(tmp, "elastic-loss"), elastic_hosts=3,
            elastic_opts={"wedge_host": 2, "wedge_after": 1,
                          "wedge_spec": HOST_LOSS_WEDGE,
                          "beat_interval_s": 0.05})
        reassigned_metric = obs.ELASTIC_REASSIGNMENTS.value()
    finally:
        faults.install(None)  # the wedge installed its own plane
        obs.enable_metrics(False)
    assert ok["rows"] == lost["rows"], (ok, lost)
    assert lost["reassigned"] > 0, f"no shards were reassigned: {lost}"
    assert reassigned_metric > 0, \
        f"elastic_reassignments_total stayed 0: {lost}"
    a = _levels_bytes(os.path.join(tmp, "arrays-elastic-ok"))
    b = _levels_bytes(os.path.join(tmp, "arrays-elastic-loss"))
    assert sorted(a) == sorted(b), "elastic level-array file sets diverged"
    for name in a:
        assert a[name] == b[name], f"elastic arrays diverged at {name}"
    # Served tiles from the failed run's arrays, byte-for-byte.
    docs = {}
    for which in ("arrays-elastic-ok", "arrays-elastic-loss"):
        store = TileStore(f"arrays:{os.path.join(tmp, which)}")
        app = ServeApp(store, TileCache(max_bytes=64 << 20),
                       render_timeout_s=30.0)
        server, base = serve_in_thread(app)
        try:
            docs[which] = _fetch_all(
                base, _tile_coords(store),
                {"codes": {}, "saw_degraded": False})
        finally:
            server.shutdown()
    want, got = docs["arrays-elastic-ok"], docs["arrays-elastic-loss"]
    assert sorted(want) == sorted(got), (
        f"served tile sets diverged: {len(want)} vs {len(got)}")
    mism = [k for k in want if want[k] != got[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    return {"shards": lost["shards"], "reassigned": lost["reassigned"],
            "reassignments_metric": reassigned_metric,
            "levels": len(a), "tiles": len(want)}


def phase_host_loss_morton(ctx):
    """host_loss under Morton-range elastic shards: the same
    mid-cascade host death, but every shard owns a contiguous
    detail-code range (parallel/partition.py), so failover must
    re-execute ONLY the dead host's tile ranges. Pinned through the
    ``shard_reassigned`` audit events — every reassigned shard index
    must have belonged to the wedged host — on top of the usual bar:
    merged arrays and served tiles byte-identical to an unfailed
    Morton run."""
    faults.install(None)
    tmp = os.path.dirname(ctx["base_root"])
    src = lambda: SyntheticSource(n=ctx["n"], seed=3)  # noqa: E731
    bs = max(1, ctx["n"] // 6)
    events_path = os.path.join(tmp, "morton-loss-events.jsonl")
    obs.enable_metrics(True)
    try:
        obs.get_registry().reset()
        ok = run_job_multihost(
            src(),
            LevelArraysSink(os.path.join(tmp, "arrays-morton-ok")),
            CFG, batch_size=bs, on_straggler="reassign",
            elastic_dir=os.path.join(tmp, "elastic-morton-ok"),
            elastic_hosts=3, elastic_opts={"partition": "morton"})
        obs.get_registry().reset()
        obs.set_event_log(obs.EventLog(events_path))
        lost = run_job_multihost(
            src(),
            LevelArraysSink(os.path.join(tmp, "arrays-morton-loss")),
            CFG, batch_size=bs, heartbeat_deadline_s=0.3,
            on_straggler="reassign",
            elastic_dir=os.path.join(tmp, "elastic-morton-loss"),
            elastic_hosts=3,
            elastic_opts={"wedge_host": 2, "wedge_after": 1,
                          "wedge_spec": HOST_LOSS_WEDGE,
                          "beat_interval_s": 0.05,
                          "partition": "morton"})
    finally:
        faults.install(None)
        log = obs.get_event_log()
        obs.set_event_log(None)
        if log is not None:
            log.close()
        obs.enable_metrics(False)
    assert lost["reassigned"] > 0, f"no shards were reassigned: {lost}"
    events = list(obs.read_events(events_path))
    planned = [e for e in events if e["event"] == "partition_planned"]
    assert planned, "morton elastic run never planned a partition"
    reas = [e for e in events if e["event"] == "shard_reassigned"]
    assert reas, "no shard_reassigned audit events"
    # The locality pin: reassignment touched ONLY the dead host's
    # ranges (shard index i belongs to host i % n_hosts).
    foreign = [e for e in reas if str(e["from_host"]) != "2"]
    assert not foreign, f"non-dead-host ranges re-executed: {foreign}"
    a = _levels_bytes(os.path.join(tmp, "arrays-morton-ok"))
    b = _levels_bytes(os.path.join(tmp, "arrays-morton-loss"))
    assert sorted(a) == sorted(b), "morton level-array file sets diverged"
    for name in a:
        assert a[name] == b[name], f"morton arrays diverged at {name}"
    docs = {}
    for which in ("arrays-morton-ok", "arrays-morton-loss"):
        store = TileStore(f"arrays:{os.path.join(tmp, which)}")
        app = ServeApp(store, TileCache(max_bytes=64 << 20),
                       render_timeout_s=30.0)
        server, base = serve_in_thread(app)
        try:
            docs[which] = _fetch_all(
                base, _tile_coords(store),
                {"codes": {}, "saw_degraded": False})
        finally:
            server.shutdown()
    want, got = docs["arrays-morton-ok"], docs["arrays-morton-loss"]
    assert sorted(want) == sorted(got), "served tile sets diverged"
    mism = [k for k in want if want[k] != got[k]]
    assert not mism, f"{len(mism)} tiles diverged, e.g. {mism[:3]}"
    return {"shards": lost["shards"], "reassigned": lost["reassigned"],
            "reassigned_from_dead_host_only": True,
            "planned_events": len(planned), "ok_shards": ok["shards"],
            "levels": len(a), "tiles": len(want)}


def phase_backend_loss(ctx):
    """Serve-fleet resilience: SIGKILL one backend of a 3-process fleet
    under Zipf load. The router's connection-failure retry must keep
    the client at zero 5xx, the victim's breaker must open
    (``fleet_backend_down``) and re-close through the supervisor
    restart + half-open probe (``fleet_backend_up``), and every tile
    served through the fleet afterwards must be byte-identical to the
    clean single-process run (``base_docs``)."""
    from heatmap_tpu.serve.fleet import FleetSupervisor

    faults.install(None)
    spec = f"delta:{ctx['base_root']}"
    coords = _tile_coords(TileStore(spec))
    tmp = os.path.dirname(ctx["base_root"])
    events_path = os.path.join(tmp, "fleet-events.jsonl")
    ev_log = obs.EventLog(events_path)
    obs.set_event_log(ev_log)
    codes: dict = {}
    lock = threading.Lock()
    stop = threading.Event()
    sup = FleetSupervisor(spec, 3, cache_bytes=64 << 20,
                          render_timeout_s=30.0, probe_interval_s=0.2,
                          restart_base_s=0.1, restart_cap_s=1.0)
    try:
        sup.start()
        server, base = serve_in_thread(sup.router)

        def load_loop(seed):
            rng = random.Random(seed)
            while not stop.is_set():
                # The load_gen 80/20 skew: hot-set traffic plus a tail.
                if rng.random() < 0.8:
                    name, z, x, y = coords[rng.randrange(
                        max(1, len(coords) // 5))]
                else:
                    name, z, x, y = coords[rng.randrange(len(coords))]
                status, _ = _get(
                    f"{base}/tiles/{urllib.parse.quote(name, safe='')}"
                    f"/{z}/{x}/{y}.json")
                with lock:
                    codes[status] = codes.get(status, 0) + 1

        drivers = [threading.Thread(target=load_loop, args=(i,),
                                    daemon=True) for i in range(4)]
        for t in drivers:
            t.start()
        time.sleep(1.0)  # warm traffic across the whole ring
        victim = sorted(sup.router.backends)[0]
        sup.kill_backend(victim)
        # Two-stage wait: right after SIGKILL the breaker has not yet
        # tripped, so /healthz still reports a full ring — polling for
        # eligible==3 straight away would "recover" instantly. First
        # wait for the victim to actually leave the ring, then for the
        # supervisor restart + half-open probe to re-admit it.
        def wait_ring(pred, what, timeout_s):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                status, body = _get(f"{base}/healthz")
                if status == 200:
                    eligible = json.loads(body)["fleet"]["eligible"]
                    if pred(eligible):
                        return
                time.sleep(0.05)
            raise AssertionError(f"victim {victim} never {what}: {codes}")

        wait_ring(lambda e: victim not in e, "left the ring", 30.0)
        wait_ring(lambda e: victim in e and len(e) == 3,
                  "re-admitted", 60.0)
        time.sleep(0.5)  # a little post-recovery traffic
        stop.set()
        for t in drivers:
            t.join(timeout=10.0)
        fives = {s: c for s, c in codes.items() if 500 <= s < 600}
        assert not fives, f"fleet served 5xx during backend loss: {codes}"
        # Byte-equality through the recovered fleet, incl. the victim.
        docs = _fetch_all(base, coords,
                          {"codes": {}, "saw_degraded": False})
        server.shutdown()
        server.server_close()
    finally:
        stop.set()
        sup.stop()
        obs.set_event_log(None)
        ev_log.close()
    base_docs = ctx["base_docs"]
    assert sorted(docs) == sorted(base_docs), (
        f"fleet tile set diverged: {len(docs)} vs {len(base_docs)}")
    mism = [k for k in docs if docs[k] != base_docs[k]]
    assert not mism, f"{len(mism)} fleet tiles diverged, e.g. {mism[:3]}"
    events = [json.loads(line) for line in open(events_path)]
    downs = [e for e in events if e["event"] == "fleet_backend_down"
             and e["backend"] == victim]
    ups = [e for e in events if e["event"] == "fleet_backend_up"
           and e["backend"] == victim]
    assert downs, f"no fleet_backend_down for {victim}: {events}"
    assert ups, f"no fleet_backend_up for {victim}: {events}"
    return {"victim": victim, "codes": {str(k): v for k, v in codes.items()},
            "tiles": len(docs), "down_events": len(downs),
            "up_events": len(ups)}


def phase_synopsis(ctx):
    """Wavelet-synopsis chaos: serve coarse tiles from synopses, tear
    one artifact plus a crashed staging tmp, and require the recovery
    sweep to quarantine both while serving falls back to exact bytes
    for the torn level — other levels keep their synopses, and no
    request ever sees a 500."""
    from heatmap_tpu.delta.recover import sweep
    from heatmap_tpu.io import open_sink
    from heatmap_tpu.synopsis.build import synopsis_path

    faults.install(None)
    root = os.path.join(os.path.dirname(ctx["base_root"]),
                        "store-synopsis")
    bdir = os.path.join(root, "base-000001")
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                         result_delta=2)
    with open_sink(f"arrays-synopsis:{bdir}") as sink:
        run_job(SyntheticSource(ctx["n"], seed=5), sink, cfg)
    with open(os.path.join(root, "CURRENT"), "w") as f:
        json.dump({"schema": "heatmap-tpu.delta_store.v1",
                   "base": "base-000001", "applied_through": 1,
                   "config": None}, f)
    store = TileStore(f"delta:{root}")
    app = ServeApp(store)
    layer = store.layer("default")
    delta_z = layer.result_delta
    syn_zooms = sorted(layer.synopses)
    assert len(syn_zooms) >= 2, f"need >=2 synopsized levels: {syn_zooms}"

    def busy_path(src):
        level = layer.levels[src]
        code = level.codes[int(np.argmax(level.values)):][:1]
        row, col = morton_decode_np(code)
        z = src - delta_z
        shift = delta_z  # source cells per tile axis = 2**delta
        return (f"/tiles/default/{z}/{int(col[0]) >> shift}"
                f"/{int(row[0]) >> shift}.json")

    codes: dict = {}

    def fetch(path):
        res = app.handle("GET", path)
        codes[res[0]] = codes.get(res[0], 0) + 1
        return res

    for src in syn_zooms:
        syn = fetch(busy_path(src) + "?synopsis=1")
        assert syn[0] == 200 and syn.headers is not None, \
            f"z{src} synopsis tile not annotated: {syn[0]}"
        assert fetch(busy_path(src))[0] == 200

    # Tear the middle artifact + leave a crashed staging file behind.
    victim = syn_zooms[len(syn_zooms) // 2]
    with open(synopsis_path(bdir, victim), "wb") as f:
        f.write(b"torn mid-write")
    with open(os.path.join(bdir, "synopsis-z99.npz.tmp"), "wb") as f:
        f.write(b"crashed staging")
    swept = sweep(root)
    reasons = sorted(i["reason"] for i in swept["quarantined"])
    assert reasons == ["orphan_tmp", "torn_synopsis"], reasons
    store.reload()
    layer = store.layer("default")
    assert victim not in layer.synopses, "torn synopsis still indexed"

    # The torn level falls back to exact bytes (no annotation) ...
    fallback = fetch(busy_path(victim) + "?synopsis=1")
    exact = fetch(busy_path(victim))
    assert fallback[0] == 200 and getattr(fallback, "headers", None) is None
    assert fallback[2] == exact[2], "fallback diverged from exact bytes"
    # ... while the surviving levels keep serving synopses.
    survivor = fetch(busy_path(syn_zooms[0]) + "?synopsis=1")
    assert survivor[0] == 200 and survivor.headers is not None
    assert codes.get(500, 0) == 0, f"500s observed: {codes}"
    return {"synopsis_zooms": syn_zooms, "torn_zoom": victim,
            "quarantined": reasons,
            "codes": {str(k): v for k, v in sorted(codes.items())}}


def phase_query(ctx):
    """Range-query chaos: tear one integral-histogram artifact plus a
    crashed staging tmp, and require the recovery sweep to quarantine
    both while /query falls through to the exact level rows for the
    torn zoom — answers identical to the integral path modulo the
    ``path`` marker, sums pinned to an independent brute force, other
    zooms keep their integrals, and no request ever sees a 500."""
    from heatmap_tpu.analytics.integral import integral_path
    from heatmap_tpu.analytics.query import level_cells
    from heatmap_tpu.delta.recover import sweep
    from heatmap_tpu.io import open_sink

    faults.install(None)
    root = os.path.join(os.path.dirname(ctx["base_root"]), "store-query")
    bdir = os.path.join(root, "base-000001")
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                         result_delta=2)
    with open_sink(f"arrays-integral:{bdir}") as sink:
        run_job(SyntheticSource(ctx["n"], seed=5), sink, cfg)
    with open(os.path.join(root, "CURRENT"), "w") as f:
        json.dump({"schema": "heatmap-tpu.delta_store.v1",
                   "base": "base-000001", "applied_through": 1,
                   "config": None}, f)
    store = TileStore(f"delta:{root}")
    app = ServeApp(store)
    layer = store.layer("default")
    int_zooms = sorted(layer.integrals)
    assert len(int_zooms) >= 2, f"need >=2 integral zooms: {int_zooms}"

    codes: dict = {}

    def fetch(path):
        res = app.handle("GET", path)
        codes[res[0]] = codes.get(res[0], 0) + 1
        return res

    def queries(z):
        n = 1 << z
        rects = [(0, 0, n - 1, n - 1)]
        level = layer.levels[z]
        row, col = (int(v[0]) for v in morton_decode_np(
            level.codes[int(np.argmax(level.values)):][:1]))
        rects.append((max(0, row - 40), max(0, col - 40),
                      min(n - 1, row + 40), min(n - 1, col + 40)))
        out = []
        for r0, c0, r1, c1 in rects:
            base = f"/query?layer=default&z={z}&bbox={c0},{r0},{c1},{r1}"
            out += [f"{base}&op=sum", f"{base}&op=topk&k=5",
                    f"{base}&op=quantile&q=0.5"]
        return out

    def answers(z):
        docs = {}
        for path in queries(z):
            res = fetch(path)
            assert res[0] == 200, f"query failed {res[0]}: {path}"
            docs[path] = json.loads(res[2])
        return docs

    before = {z: answers(z) for z in int_zooms}
    for z, docs in before.items():
        assert all(d["path"] == "integral" for d in docs.values()), docs

    # Tear the middle artifact + leave a crashed staging file behind.
    victim = int_zooms[len(int_zooms) // 2]
    with open(integral_path(bdir, victim), "wb") as f:
        f.write(b"torn mid-write")
    with open(os.path.join(bdir, "integral-z99.npz.tmp"), "wb") as f:
        f.write(b"crashed staging")
    swept = sweep(root)
    reasons = sorted(i["reason"] for i in swept["quarantined"])
    assert reasons == ["orphan_tmp", "torn_integral"], reasons
    kinds = sorted(i["kind"] for i in swept["quarantined"])
    assert kinds == ["integral", "integral"], kinds
    store.reload()
    layer = store.layer("default")
    assert victim not in layer.integrals, "torn integral still indexed"

    # The torn zoom falls through to exact rows with identical answers
    # ... while the surviving zooms keep their integral fast path.
    for z in int_zooms:
        want_path = "fallback" if z == victim else "integral"
        for url, doc in answers(z).items():
            assert doc["path"] == want_path, (url, doc)
            was = dict(before[z][url], path=want_path)
            assert doc == was, f"answers diverged after tear: {url}"
            if doc["op"] == "sum":  # independent brute-force pin
                c0, r0, c1, r1 = doc["bbox"]
                _, _, vals = level_cells(layer.levels[z],
                                         (r0, c0, r1, c1))
                assert doc["sum"] == float(vals.sum()), url
    assert codes.get(500, 0) == 0, f"500s observed: {codes}"
    return {"integral_zooms": int_zooms, "torn_zoom": victim,
            "quarantined": reasons,
            "codes": {str(k): v for k, v in sorted(codes.items())}}


def phase_temporal(ctx):
    """Temporal-plane chaos (docs/temporal.md): a bucketed store under
    serve, one bucket torn mid-serve. Warmed temporal tiles must keep
    answering with their last-good bytes (stale-if-error), the
    recovery sweep must quarantine exactly the torn bucket, the
    all-time tiles must stay byte-identical to their pre-tear
    responses (the plain path never reads buckets), and no request
    may see a 5xx."""
    from heatmap_tpu.delta.compact import read_current
    from heatmap_tpu.delta.recover import sweep
    from heatmap_tpu.temporal import buckets as tb
    from heatmap_tpu.temporal import fold as tfold

    faults.install(None)
    root = os.path.join(os.path.dirname(ctx["base_root"]),
                        "store-temporal")
    os.makedirs(root)
    tfold.ensure_config(root, width=100.0, fanout=2, keep=2, tiers=3)
    cfg = BatchJobConfig(detail_zoom=8, min_detail_zoom=2,
                         result_delta=2)
    rng = np.random.default_rng(23)
    for t0 in (1000.0, 1150.0, 1300.0, 1450.0):
        n = 60
        delta.apply_batch(root, delta.ColumnsSource({
            "latitude": rng.uniform(30.0, 50.0, n),
            "longitude": rng.uniform(-120.0, -70.0, n),
            "user_id": ["u%d" % (j % 3) for j in range(n)],
            "timestamp": [str(t0 + j) for j in range(n)],
        }), cfg)
    delta.compact(root, retention=10)

    store = TileStore(f"delta:{root}")
    app = ServeApp(store, TileCache())
    codes: dict = {}

    def fetch(path):
        res = app.handle("GET", path)
        codes[res[0]] = codes.get(res[0], 0) + 1
        return res

    # Warm every z<=2 tile on three temporal cuts plus the plain path.
    before = {}
    for z in (1, 2):
        for x in range(1 << z):
            for y in range(1 << z):
                for q in ("", "?as_of=1200", "?window=150",
                          "?decay=100"):
                    p = f"/tiles/default/{z}/{x}/{y}.json{q}"
                    before[p] = fetch(p)
    warmed = [p for p, r in before.items()
              if r[0] == 200 and "as_of" in p]
    assert warmed, "no as_of tiles warmed — scenario too sparse"

    # Tear the oldest bucket mid-serve; the reload bumps the serving
    # generation so every warmed entry must re-render (and fail into
    # its last-good bytes).
    bdir = os.path.join(root, read_current(root)["base"],
                        tb.BUCKETS_DIRNAME)
    victim = sorted(os.listdir(bdir))[0]
    vdir = os.path.join(bdir, victim)
    level_files = [f for f in os.listdir(vdir) if f.endswith(".npz")]
    with open(os.path.join(vdir, level_files[0]), "wb") as f:
        f.write(b"torn mid-write")
    store.reload()

    stale = 0
    for p, was in before.items():
        if "?" in p and was[0] != 200:
            continue  # cold temporal miss: nothing last-good to keep
        res = fetch(p)
        assert res[0] == was[0] and res[2] == was[2], \
            f"bytes moved after tear: {p} ({was[0]} -> {res[0]})"
        if "?" in p and res[5] == "stale":
            stale += 1
    assert stale > 0, "no stale-if-error serves observed"

    swept = sweep(root)
    reasons = sorted(i["reason"] for i in swept["quarantined"])
    assert reasons == ["torn_bucket"], reasons
    assert not os.path.isdir(vdir), "torn bucket still in place"
    # The all-time path never noticed the quarantine either.
    for p, was in before.items():
        if "?" not in p:
            res = fetch(p)
            assert res[0] == was[0] and res[2] == was[2], p
    assert not any(c >= 500 for c in codes), f"5xx observed: {codes}"
    return {"torn_bucket": victim, "stale_serves": stale,
            "quarantined": reasons,
            "codes": {str(k): v for k, v in sorted(codes.items())}}


def phase_tilefs(ctx):
    """tilefs chaos (heatmap_tpu.tilefs): a converted store serving
    zero-copy through the disk render cache while the fault plane fires
    on both new sites. Requirements: bytes identical to heap (npz)
    serving at every step — clean, with ``tilefs.read`` faults forcing
    per-zoom npz fallbacks mid-reload and ``diskcache.write`` faults
    skipping fills, after a torn disk-cache entry (reads as a miss that
    refills), and after a torn mirror + crashed staging tmp that the
    recovery sweep must quarantine — and no request ever sees a 500."""
    from heatmap_tpu.delta.recover import sweep
    from heatmap_tpu.io import open_sink
    from heatmap_tpu.tilefs import DiskTileCache, sniff_tilefs
    from heatmap_tpu.tilefs.diskcache import DISK_CACHE_TORN

    faults.install(None)
    obs.enable_metrics(True)  # the torn-entry check reads a counter
    root = os.path.join(os.path.dirname(ctx["base_root"]), "store-tilefs")
    bdir = os.path.join(root, "base-000001")
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                         result_delta=2)
    with open_sink(f"arrays-tilefs:{bdir}") as sink:
        run_job(SyntheticSource(ctx["n"], seed=5), sink, cfg)
    with open(os.path.join(root, "CURRENT"), "w") as f:
        json.dump({"schema": "heatmap-tpu.delta_store.v1",
                   "base": "base-000001", "applied_through": 1,
                   "config": None}, f)
    assert sniff_tilefs(bdir), "arrays-tilefs sink left no mirrors"

    # Heap truth: the same base read through the explicit arrays kind
    # (npz only — the bare path would sniff the mirrors right back).
    heap_app = ServeApp(TileStore(f"arrays:{bdir}"), TileCache())
    store = TileStore(root)  # bare path sniffs the tilefs kind
    assert store.stats()["kind"] == "tilefs", store.stats()
    disk_root = os.path.join(root, "diskcache")
    app = ServeApp(store, TileCache(),
                   disk_cache=DiskTileCache(disk_root))

    layer = store.layer("default")
    dz = layer.result_delta
    paths = []
    for d in sorted(layer.detail_zooms):
        z = d - dz
        if z < 0:
            continue
        coarse = np.unique(layer.levels[d].codes >> np.int64(2 * dz))
        rows, cols = morton_decode_np(coarse)
        paths += [f"/tiles/default/{z}/{int(c)}/{int(r)}.{fmt}"
                  for r, c in zip(rows, cols)
                  for fmt in ("json", "png")]
    paths = paths[:48]

    codes: dict = {}

    def identical(note):
        for p in paths:
            a = heap_app.handle("GET", p)
            b = app.handle("GET", p)
            codes[b[0]] = codes.get(b[0], 0) + 1
            assert a[0] == b[0] == 200, (note, p, a[0], b[0])
            assert a[2] == b[2], (note, "bytes diverged", p)

    # 1. Clean pass: mmap'd serving matches heap, disk tier fills.
    identical("clean")
    assert app.disk_cache.stats()["entries"] > 0, "disk tier never filled"

    # 2. Torn disk-cache entry: truncate one published entry, drop the
    #    heap cache so the disk tier is actually consulted — the torn
    #    entry must read as a miss (unlinked + refilled), never bytes.
    victims = [os.path.join(dp, fn) for dp, _dirs, fns
               in os.walk(disk_root) for fn in fns
               if not fn.startswith(".tmp-")]
    with open(victims[0], "r+b") as f:
        f.truncate(7)
    torn0 = DISK_CACHE_TORN.value()
    app.cache.clear()
    identical("after torn disk-cache entry")
    assert DISK_CACHE_TORN.value() > torn0, "torn entry never detected"
    # ... and the refill re-published a whole entry under the same key.
    assert os.path.getsize(victims[0]) > 7, "torn entry never refilled"

    # 3. Fault plane on both new sites: tilefs.read fires during the
    #    reload's per-zoom opens (retries=0 by policy — each faulted
    #    zoom must fall back to its sibling npz level), diskcache.write
    #    fires on the refills (a skipped fill, never an error).
    faults.install_spec(
        "seed=17,scale=0,tilefs.read=3x2,diskcache.write=6x2")
    try:
        store.reload()
        app.cache.clear()
        identical("under fault plane (mixed mmap/npz zooms)")
    finally:
        faults.install(None)
    store.reload()
    identical("recovered (all zooms mapped again)")

    # 4. Torn mirror + crashed staging tmp: the sweep quarantines both,
    #    and the reloaded store serves the torn zoom from npz.
    mirrors = sorted(n for n in os.listdir(bdir)
                     if n.startswith("tilefs-z") and n.endswith(".bin"))
    victim = mirrors[len(mirrors) // 2]
    with open(os.path.join(bdir, victim), "r+b") as f:
        f.write(b"torn mid-write")
    with open(os.path.join(bdir, "tilefs-z99.bin.tmp"), "wb") as f:
        f.write(b"crashed staging")
    swept = sweep(root)
    reasons = sorted(i["reason"] for i in swept["quarantined"])
    assert reasons == ["orphan_tmp", "torn_tilefs"], reasons
    kinds = sorted(i["kind"] for i in swept["quarantined"])
    assert kinds == ["tilefs", "tilefs"], kinds
    store.reload()
    identical("after torn mirror (npz fallback)")

    assert codes.get(500, 0) == 0, f"500s observed: {codes}"
    return {"paths": len(paths), "torn_mirror": victim,
            "quarantined": reasons,
            "disk_cache": app.disk_cache.stats(),
            "codes": {str(k): v for k, v in sorted(codes.items())}}


def phase_incident(ctx):
    """Flight-recorder incident discipline under a seeded fault storm:
    12 injected ``tile.render`` faults inside request-shaped shadow
    spans (head sampling at 0.0) form exactly three storm episodes at
    threshold 4 — the first flushes exactly ONE bundle, the rate limit
    suppresses the other two — and the bundle replays as a valid
    Perfetto trace (tools/trace_analyze.py) holding the request trees
    completed before the flush. Every faulted tree is tail-promoted
    into the collector as if head-sampled, and the request histogram
    carries a promoted trace's id as its /metrics exemplar."""
    from heatmap_tpu.obs import incident as incident_mod
    from heatmap_tpu.obs import recorder as recorder_mod
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.obs.incident import IncidentManager
    from heatmap_tpu.obs.recorder import FlightRecorder

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_analyze

    inc_dir = ctx.get("incident_dir") or os.path.join(
        os.path.dirname(ctx["base_root"]), "incidents")
    n_faults, threshold = 12, 4
    obs.enable_metrics(True)
    collector = tracing.enable_tracing(sample=0.0)
    recorder_mod.install(FlightRecorder(max_spans=256))
    mgr = IncidentManager(inc_dir, run_id="soak",
                          storm_threshold=threshold,
                          storm_window_s=3600.0, min_interval_s=3600.0)
    incident_mod.set_manager(mgr)
    incident_mod.add_state_provider(
        "soak", lambda: {"phase": "incident", "n_faults": n_faults})
    reg = obs.get_registry()
    hist = reg.histogram("soak_request_seconds", buckets=(0.001, 10.0))
    plane = faults.install_spec(f"seed=17,scale=0,tile.render={n_faults}")
    try:
        for i in range(n_faults):
            req = tracing.begin_span("serve.request", {"tile": i})
            render = tracing.begin_span("tile.render")
            try:
                faults.check("tile.render", key=i)
            except faults.InjectedFault:
                pass  # the fault event itself promotes the tree
            tracing.end_span(render)
            hist.observe(0.0005)
            tracing.end_span(req)
        assert plane.injected == n_faults, plane.counts()

        # Exactly one bundle: episodes 2 and 3 hit the rate limit.
        assert len(mgr.flushed) == 1, mgr.flushed
        assert mgr.suppressed == 2, mgr.suppressed
        assert obs.INCIDENTS_TOTAL.value(trigger="fault_storm") == 1
        bundles = [d for d in os.listdir(inc_dir)
                   if not d.startswith(".tmp-")]
        assert bundles == ["soak-0"], bundles

        # The bundle replays as a valid Perfetto trace: the request
        # trees completed before the 4th fault flushed it.
        spans = trace_analyze.load_events(mgr.flushed[0])
        replay = trace_analyze.analyze(spans)
        assert replay["n_spans"] == 2 * (threshold - 1), replay["n_spans"]
        for row in replay["traces"]:
            assert row["root"] == "serve.request" and not row["partial"]
            assert [h["name"] for h in row["critical_path"]] == [
                "serve.request", "tile.render"]
        manifest = json.load(open(os.path.join(mgr.flushed[0],
                                               "manifest.json")))
        assert manifest["trigger"] == "fault_storm"
        state = json.load(open(os.path.join(mgr.flushed[0], "state.json")))
        assert state["soak"]["n_faults"] == n_faults

        # Tail promotion: every faulted (unsampled) tree reached the
        # collector as if head-sampled.
        promoted = {r["trace_id"] for r in collector.spans()}
        assert len(promoted) == n_faults, len(promoted)
        rcd_stats = recorder_mod.get_recorder().stats()
        assert rcd_stats["promoted_traces"] == n_faults

        # Exemplar tie-in: the histogram bucket names a promoted trace.
        prom = reg.render_prometheus()
        [line] = [l for l in prom.splitlines() if l.startswith(
            'soak_request_seconds_bucket{le="0.001"}')]
        exemplar_tid = line.split('trace_id="')[1].split('"')[0]
        assert exemplar_tid in promoted
        return {"bundles": len(bundles), "suppressed": mgr.suppressed,
                "replay_spans": replay["n_spans"],
                "promoted_traces": len(promoted),
                "bundle_bytes": manifest["bytes"],
                "incident_dir": inc_dir}
    finally:
        faults.install(None)
        incident_mod.set_manager(None)
        recorder_mod.install(None)
        tracing.disable_tracing()
        reg.reset()
        obs.enable_metrics(False)


def phase_telemetry(ctx):
    """Telemetry pipeline chaos under a fake clock: a scripted lag
    spike through the real sampler must fire exactly ONE anomaly edge
    and flush exactly one anomaly-triggered incident bundle with the
    surrounding raw-tier history embedded; the telemetry surface
    (``/series`` fleet-merged, ``/dashboard``, ``/healthz``) answers
    every request with zero 5xx while the sampler keeps ticking; and a
    torn telemetry spill snapshot (simulated crash mid-write plus an
    orphaned publish tmp dir) is quarantined on re-arm — never a crash,
    never blocking the next spill."""
    from heatmap_tpu.obs import anomaly as anomaly_mod
    from heatmap_tpu.obs import incident as incident_mod
    from heatmap_tpu.obs import timeseries
    from heatmap_tpu.obs.anomaly import AnomalyEngine, parse_watch_spec
    from heatmap_tpu.obs.incident import IncidentManager
    from heatmap_tpu.obs.timeseries import TelemetrySampler, TimeSeriesStore
    from heatmap_tpu.serve.router import RouterApp

    scratch = os.path.dirname(ctx["base_root"])
    tel_dir = os.path.join(scratch, "telemetry")
    inc_dir = os.path.join(scratch, "incidents-telemetry")
    clock = {"t": 1_000_000.0}

    def now():
        return clock["t"]

    obs.enable_metrics(True)
    reg = obs.get_registry()
    lag = reg.gauge("soak_lag_seconds")
    engine = AnomalyEngine(
        [parse_watch_spec("soak_lag_seconds:z=5,min_count=8")], clock=now)
    anomaly_mod.set_engine(engine)
    store = TimeSeriesStore(spill_dir=tel_dir, clock=now)
    timeseries.install(store)
    sampler = TelemetrySampler(store, 10.0, registry=reg, engine=engine,
                               clock=now, spill_every_ticks=4)
    mgr = IncidentManager(inc_dir, run_id="tel", min_interval_s=3600.0,
                          clock=now)
    incident_mod.set_manager(mgr)
    try:
        # Scripted baseline, then a sustained spike: one rising edge,
        # not one per breaching tick.
        for i in range(30):
            clock["t"] += 10.0
            lag.set(1.0 + (i % 4) * 0.02)
            sampler.sample_once(clock["t"])
        for _ in range(6):
            clock["t"] += 10.0
            lag.set(40.0)
            sampler.sample_once(clock["t"])
        assert engine.status()["edges"] == 1, engine.status()
        bundles = [d for d in os.listdir(inc_dir)
                   if not d.startswith(".tmp-")]
        assert len(bundles) == 1, bundles
        manifest = json.load(open(os.path.join(inc_dir, bundles[0],
                                               "manifest.json")))
        assert manifest["trigger"] == "anomaly", manifest
        tel = json.load(open(os.path.join(inc_dir, bundles[0],
                                          "telemetry.json")))
        pts = tel["series"]["soak_lag_seconds"]["points"]
        assert pts, "bundle must embed the pre-trigger history"
        assert max(p[5] for p in pts) == 40.0
        assert min(p[5] for p in pts) < 2.0  # baseline is in the window

        # Zero 5xx on the telemetry surface while sampling continues —
        # through the fleet router, the strictest path (local parse +
        # fleet merge + dashboard shell).
        router = RouterApp([])
        statuses = set()
        for _ in range(20):
            clock["t"] += 10.0
            sampler.sample_once(clock["t"])
            for path in ("/series?name=soak_lag_seconds&fleet=1",
                         "/dashboard", "/healthz"):
                statuses.add(router.handle("GET", path)[0])
        assert statuses == {200}, statuses
        doc = json.loads(router.handle(
            "GET", "/series?name=soak_lag_seconds")[2])
        assert doc["enabled"] and doc["frames"], doc

        # Torn spill: corrupt the newest snapshot and plant an orphaned
        # publish tmp dir; re-arming quarantines both and restores the
        # newest intact snapshot without raising.
        store.spill()
        snaps = sorted(d for d in os.listdir(tel_dir)
                       if d.startswith("snap-"))
        with open(os.path.join(tel_dir, snaps[-1], "series.json"),
                  "w") as f:
            f.write('{"torn')
        os.makedirs(os.path.join(tel_dir, ".tmp-snap-crash"),
                    exist_ok=True)
        fresh = TimeSeriesStore(spill_dir=tel_dir, clock=now)
        fresh.load_spill()  # must not raise
        qdir = os.path.join(tel_dir, "quarantine")
        assert os.path.isdir(qdir), "torn spill was not quarantined"
        quarantined = len(os.listdir(qdir))
        assert quarantined >= 2, os.listdir(qdir)
        clock["t"] += 10.0
        fresh.observe("soak_lag_seconds", 1.0, ts=clock["t"])
        fresh.spill()  # quarantine never blocks the next spill
        return {"bundles": len(bundles), "edges": 1,
                "statuses": sorted(statuses), "quarantined": quarantined,
                "restored_series": fresh.stats()["series"]}
    finally:
        incident_mod.set_manager(None)
        anomaly_mod.set_engine(None)
        timeseries.install(None)
        obs.enable_metrics(False)


def phase_adaptive(ctx):
    """Brownout-ladder chaos: one overload episode under a fake clock
    and a scripted burn schedule must walk the ladder up 0->1->2->3
    and back down to 0, with the rungs' serving policies observable at
    each plateau (synopsis stamps, the raised ceiling, deterministic
    brownout sheds), zero 500s, identical status/rung traces across a
    repeat run, and — once recovered to rung 0 — bytes and ETags
    identical to a controller-less server."""
    from heatmap_tpu.io import open_sink
    from heatmap_tpu.serve import degrade

    faults.install(None)
    scratch = os.path.dirname(ctx["base_root"])
    root = os.path.join(scratch, "store-adaptive")
    cfg = BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                         result_delta=2)
    with open_sink(f"arrays-synopsis:{root}") as sink:
        run_job(SyntheticSource(ctx["n"], seed=9), sink, cfg)
    store = TileStore(f"arrays:{root}")
    layer = store.layer("default")
    syn_zooms = sorted(layer.synopses)  # sources 7/8/9 under cfg
    delta_z = layer.result_delta

    def busy_path(src):
        level = layer.levels[src]
        code = level.codes[int(np.argmax(level.values)):][:1]
        row, col = morton_decode_np(code)
        z = src - delta_z
        return (f"/tiles/default/{z}/{int(col[0]) >> delta_z}"
                f"/{int(row[0]) >> delta_z}.json")

    # Fixed request mix: every synopsis-backed coarse zoom, one deep
    # zoom with NO natural synopsis (the rung-2 ceiling target), and a
    # spread of neighbors so the rung-3 fractional shed has keys to
    # split. Deterministic by construction.
    deep_src = max(layer.detail_zooms)
    assert deep_src not in layer.synopses
    paths = [busy_path(src) for src in syn_zooms + [deep_src]]
    bx, by = paths[0].split("/")[4:6]
    z0 = int(paths[0].split("/")[3])
    for dx in range(4):
        for dy in range(3):
            x = (int(bx) + dx) % (1 << z0)
            y = (int(by.split(".")[0]) + dy) % (1 << z0)
            paths.append(f"/tiles/default/{z0}/{x}/{y}.json")
    # Burn schedule: hot long enough for three 2s dwells, then cool
    # through three 3s holds — one full staircase per episode.
    schedule = [(float(t), 2.5) for t in range(9)]
    schedule += [(float(t), 0.1) for t in range(9, 22)]

    def episode(run_idx):
        tnow, burn = [0.0], [0.0]
        ctl = degrade.BrownoutController(
            dwell_s=2.0, hold_s=3.0, poll_interval_s=0.0,
            shed_fraction=0.5,
            burn_source=lambda: {"tiles-fast": burn[0]},
            clock=lambda: tnow[0])
        app = ServeApp(store, TileCache(), max_inflight=8, degrade=ctl)
        ev_path = os.path.join(scratch, f"adaptive-{run_idx}.jsonl")
        log = obs.EventLog(ev_path, run_id=f"adaptive-{run_idx}")
        obs.set_event_log(log)
        trace, codes, stamped, sheds = [], {}, 0, 0
        try:
            faults.install(faults.FaultPlane(seed=11))
            for t, b in schedule:
                tnow[0], burn[0] = t, b
                for path in paths:
                    res = app.handle("GET", path)
                    codes[res[0]] = codes.get(res[0], 0) + 1
                    if res[0] == 503:
                        sheds += 1
                        assert json.loads(res[2])["cause"] == "brownout"
                        assert ctl.rung == ctl.max_rung, \
                            f"shed below top rung at t={t}"
                    elif getattr(res, "headers", None) is not None:
                        stamped += 1
                        assert ctl.rung >= 1, f"stamp at rung 0, t={t}"
                    trace.append((t, path, res[0], ctl.rung))
        finally:
            faults.install(None)
            obs.set_event_log(None)
            log.close()
        steps = [(r["rung"], r["direction"], r["cause"])
                 for r in obs.read_events(ev_path)
                 if r["event"] == "degrade_step"]
        return app, trace, codes, steps, stamped, sheds

    app1, trace1, codes1, steps1, stamped1, sheds1 = episode(1)
    _, trace2, codes2, steps2, _, _ = episode(2)

    # One clean staircase, edge-triggered: exactly three ups with the
    # burning objective as cause, three recovery downs, nothing else.
    assert steps1 == [(1, "up", "tiles-fast"), (2, "up", "tiles-fast"),
                      (3, "up", "tiles-fast"), (2, "down", "recovery"),
                      (1, "down", "recovery"),
                      (0, "down", "recovery")], steps1
    # Deterministic ladder: the repeat run reproduces every status and
    # every rung at every tick, not just the final shape.
    assert steps2 == steps1
    assert trace2 == trace1
    assert codes1 == codes2
    assert codes1.get(500, 0) == 0, f"500s observed: {codes1}"
    assert sheds1 > 0, "top rung never shed"
    assert stamped1 > 0, "no synopsis-stamped responses"
    # The stretch rung actually raised the ceiling for the deep zoom.
    deep = paths[len(syn_zooms)]
    stretch_hits = [s for (t, p, s, rung) in trace1
                    if p == deep and rung == 2]
    assert stretch_hits and all(s == 200 for s in stretch_hits)

    # Recovered at rung 0: body AND ETag byte-identical to a server
    # that never had a controller, for every path in the mix.
    bare = ServeApp(store, TileCache())
    assert app1.degrade.rung == 0
    for path in paths:
        a, b = bare.handle("GET", path), app1.handle("GET", path)
        assert tuple(a)[:4] == tuple(b)[:4], path
        assert (getattr(a, "headers", None)
                == getattr(b, "headers", None)), path
    return {"steps": [f"{d}->{r}" for r, d, _ in steps1],
            "requests": sum(codes1.values()),
            "codes": {str(k): v for k, v in sorted(codes1.items())},
            "synopsis_stamped": stamped1, "shed": sheds1}


PHASES = [
    ("baseline", phase_baseline),
    ("chaos_pipeline", phase_chaos_pipeline),
    ("chaos_serve", phase_chaos_serve),
    ("heartbeat", phase_heartbeat),
    ("fault_floor", phase_fault_floor),
    ("ingest_crash", phase_ingest_crash),
    ("writer_loss", phase_writer_loss),
    ("dispatch", phase_dispatch),
    ("host_loss", phase_host_loss),
    ("host_loss_morton", phase_host_loss_morton),
    ("backend_loss", phase_backend_loss),
    ("synopsis", phase_synopsis),
    ("query", phase_query),
    ("temporal", phase_temporal),
    ("tilefs", phase_tilefs),
    ("incident", phase_incident),
    ("telemetry", phase_telemetry),
    ("adaptive", phase_adaptive),
    ("byte_equality", phase_byte_equality),
]


def main():
    ap = argparse.ArgumentParser(
        description="pipeline chaos soak: byte-equality under "
                    "deterministic fault injection")
    ap.add_argument("--n", type=int, default=3000,
                    help="synthetic points for the ingest run")
    ap.add_argument("--chaos", default=DEFAULT_CHAOS,
                    help="fault-plane spec (see docs/robustness.md)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    ap.add_argument("--only", action="append", default=None,
                    help="run only the named phase(s); byte_equality "
                         "needs the earlier ones")
    ap.add_argument("--incident-dir", default=None, metavar="DIR",
                    help="where the incident phase flushes its bundles "
                         "(default: the scratch dir; point it at a "
                         "workspace path so CI can upload bundles as "
                         "artifacts on failure)")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="chaos-soak-")
    ctx = {
        "n": args.n, "chaos": args.chaos,
        "base_root": os.path.join(tmp, "store-base"),
        "chaos_root": os.path.join(tmp, "store-chaos"),
        "base_arrays": os.path.join(tmp, "arrays-base"),
        "chaos_arrays": os.path.join(tmp, "arrays-chaos"),
        "incident_dir": args.incident_dir,
    }
    failed = 0
    try:
        for name, fn in PHASES:
            if args.only and name not in args.only:
                continue
            t0 = time.monotonic()
            try:
                info = fn(ctx)
                print(json.dumps({"phase": name, "ok": True,
                                  "seconds": round(time.monotonic() - t0, 1),
                                  **(info or {})}))
            except Exception as e:
                failed += 1
                traceback.print_exc()
                print(json.dumps({"phase": name, "ok": False,
                                  "seconds": round(time.monotonic() - t0, 1),
                                  "error": f"{type(e).__name__}: {e}"}))
            sys.stdout.flush()
    finally:
        faults.install(None)
        if args.keep:
            print(json.dumps({"scratch": tmp}))
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
