#!/usr/bin/env python
"""Critical-path analysis over Chrome trace-event JSON.

Reads the artifact ``--trace-out`` writes (obs/tracing.py — also any
trace-event file whose ``X`` events carry ``trace_id`` / ``span_id`` /
``parent_id`` in ``args``), rebuilds each trace's span tree, and
attributes SELF time: a span's duration minus its direct children's,
clipped at zero (threaded children can overlap their parent). Two
outputs per file:

- a top-k table of span names ranked by total self time — where the
  process actually spent its wall clock, with parent "umbrella" spans
  deflated to their own bookkeeping cost;
- per trace, the CRITICAL PATH: the root-to-leaf walk that descends
  into the longest child at every level — the chain of spans an
  optimization must shorten for the end-to-end time to move.

Single-threaded trees satisfy sum(self) == root wall exactly (modulo
clock jitter); tests/test_tracing.py pins the 5% envelope. The module
is import-friendly (``load_events`` / ``analyze`` / ``summarize``) so
tools/bench_job.py and tools/bench_delta.py embed the same analysis
into their bench records.

    python tools/trace_analyze.py trace.json [--top 10] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict


def load_events(path: str) -> list[dict]:
    """Span dicts from a trace-event file (``{"traceEvents": [...]}``
    or a bare event list); non-span events (metadata, no span_id) are
    skipped. An incident bundle directory (obs/incident.py) works too:
    its ``trace.json`` is analyzed."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    spans = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if "span_id" not in args:
            continue
        spans.append({
            "name": e.get("name", "?"),
            "ts_us": float(e.get("ts", 0.0)),
            "dur_us": float(e.get("dur", 0.0)),
            "tid": e.get("tid"),
            "trace_id": args.get("trace_id"),
            "span_id": args["span_id"],
            "parent_id": args.get("parent_id"),
            "attrs": {k: v for k, v in args.items()
                      if k not in ("trace_id", "span_id", "parent_id")},
        })
    return spans


def build_traces(spans: list[dict]) -> dict:
    """``{trace_id: {"spans": {id: span}, "children": {id: [span]},
    "roots": [span]}}``. A span whose parent is absent from the file
    (remote parent, dropped span) is treated as a root."""
    traces: dict = {}
    for s in spans:
        t = traces.setdefault(s["trace_id"], {
            "spans": {}, "children": defaultdict(list), "roots": []})
        t["spans"][s["span_id"]] = s
    for s in spans:
        t = traces[s["trace_id"]]
        pid = s["parent_id"]
        if pid is not None and pid in t["spans"]:
            t["children"][pid].append(s)
        else:
            t["roots"].append(s)
    for t in traces.values():
        for kids in t["children"].values():
            kids.sort(key=lambda s: s["ts_us"])
        t["roots"].sort(key=lambda s: s["ts_us"])
    return traces


def self_times(trace: dict) -> dict:
    """{span_id: self_us} — duration minus direct children, >= 0."""
    out = {}
    for sid, s in trace["spans"].items():
        child_us = sum(k["dur_us"] for k in trace["children"].get(sid, ()))
        out[sid] = max(s["dur_us"] - child_us, 0.0)
    return out


def subtree_self_sum(trace: dict, root: dict, selfs: dict) -> float:
    total, stack = 0.0, [root]
    while stack:
        node = stack.pop()
        total += selfs[node["span_id"]]
        stack.extend(trace["children"].get(node["span_id"], ()))
    return total


def critical_path(trace: dict, root: dict) -> list[dict]:
    """Greedy root-to-leaf walk descending into the longest child."""
    path, node = [root], root
    while True:
        kids = trace["children"].get(node["span_id"])
        if not kids:
            return path
        node = max(kids, key=lambda s: s["dur_us"])
        path.append(node)


def analyze(spans: list[dict], top: int = 10) -> dict:
    """Full analysis: per-trace critical paths + the global top-k
    self-time table."""
    traces = build_traces(spans)
    self_by_name: dict = defaultdict(float)
    calls: dict = defaultdict(int)
    trace_rows = []
    for tid, trace in traces.items():
        selfs = self_times(trace)
        for sid, us in selfs.items():
            name = trace["spans"][sid]["name"]
            self_by_name[name] += us
            calls[name] += 1
        for root in trace["roots"]:
            trace_rows.append({
                "trace_id": tid,
                "root": root["name"],
                # A flight-recorder ring can evict a subtree's real
                # parent; the orphan surfaces as a root with a dangling
                # parent_id. Flag it so the sum(self) == wall invariant
                # (only meaningful for complete trees) can skip it.
                "partial": root["parent_id"] is not None,
                "wall_us": round(root["dur_us"], 1),
                "n_spans": len(trace["spans"]),
                "self_sum_us": round(
                    subtree_self_sum(trace, root, selfs), 1),
                "critical_path": [
                    {"name": p["name"],
                     "dur_us": round(p["dur_us"], 1),
                     "self_us": round(selfs[p["span_id"]], 1)}
                    for p in critical_path(trace, root)],
            })
    trace_rows.sort(key=lambda r: -r["wall_us"])
    ranked = sorted(self_by_name, key=lambda n: -self_by_name[n])[:top]
    return {
        "n_spans": len(spans),
        "n_traces": len(traces),
        "traces": trace_rows,
        "top_self": [{"name": n,
                      "self_us": round(self_by_name[n], 1),
                      "calls": calls[n]} for n in ranked],
    }


def summarize(chrome_doc: dict, top: int = 6) -> dict:
    """Compact digest of an in-memory ``to_chrome()`` document for
    embedding in bench records: top self-time names + the slowest
    trace's critical path."""
    spans = []
    for e in chrome_doc.get("traceEvents", []):
        if e.get("ph") != "X" or "span_id" not in (e.get("args") or {}):
            continue
        args = e["args"]
        spans.append({"name": e.get("name", "?"),
                      "ts_us": float(e.get("ts", 0.0)),
                      "dur_us": float(e.get("dur", 0.0)),
                      "tid": e.get("tid"),
                      "trace_id": args.get("trace_id"),
                      "span_id": args["span_id"],
                      "parent_id": args.get("parent_id"),
                      "attrs": {}})
    if not spans:
        return {"n_spans": 0, "n_traces": 0, "top_self": [],
                "critical_path": []}
    full = analyze(spans, top=top)
    slowest = full["traces"][0] if full["traces"] else None
    return {
        "n_spans": full["n_spans"],
        "n_traces": full["n_traces"],
        "top_self": full["top_self"],
        "critical_path": (slowest["critical_path"] if slowest else []),
    }


def format_report(result: dict, max_traces: int = 3) -> str:
    lines = [f"spans: {result['n_spans']}  traces: {result['n_traces']}",
             "", "top self time:",
             f"  {'span':28s} {'calls':>6s} {'self':>10s}"]
    for row in result["top_self"]:
        lines.append(f"  {row['name']:28s} {row['calls']:6d} "
                     f"{row['self_us'] / 1e3:9.2f}ms")
    for t in result["traces"][:max_traces]:
        lines.append("")
        lines.append(f"trace {t['trace_id']}  root={t['root']}  "
                     f"wall={t['wall_us'] / 1e3:.2f}ms  "
                     f"spans={t['n_spans']}  "
                     f"self_sum={t['self_sum_us'] / 1e3:.2f}ms")
        lines.append("  critical path:")
        for i, hop in enumerate(t["critical_path"]):
            lines.append(f"  {'  ' * i}{hop['name']}  "
                         f"dur={hop['dur_us'] / 1e3:.2f}ms  "
                         f"self={hop['self_us'] / 1e3:.2f}ms")
    extra = len(result["traces"]) - max_traces
    if extra > 0:
        lines.append(f"... {extra} more trace(s); --json for all")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="critical-path analysis over --trace-out JSON")
    ap.add_argument("trace", help="Chrome trace-event JSON file, or an "
                    "incident bundle directory (its trace.json)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the self-time table")
    ap.add_argument("--json", action="store_true",
                    help="emit the full analysis as JSON")
    ap.add_argument("--max-traces", type=int, default=3,
                    help="traces printed in table mode")
    args = ap.parse_args()
    spans = load_events(args.trace)
    if not spans:
        print(f"no spans in {args.trace}", file=sys.stderr)
        return 1
    result = analyze(spans, top=args.top)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(format_report(result, max_traces=args.max_traces))
    return 0


if __name__ == "__main__":
    sys.exit(main())
