#!/usr/bin/env python
"""Closed-loop load generator for the tile server: BENCH_serve.json.

Spins up the serving stack IN PROCESS (ServeApp on an ephemeral-port
ThreadingHTTPServer — same code path as ``heatmap_tpu serve``), then
drives it with N closed-loop worker threads over a Zipf-skewed tile
universe sampled from the store itself. Closed loop = each worker
issues its next request only after the previous one returns, so
concurrency is exactly ``--workers`` and the measured RPS is the
server's, not the generator's offered rate.

Phases: a warmup pass touches the working set (cold renders populate
the cache), then the measured window runs against warmed state —
the acceptance gate is hit-rate > 0.95 there. Latency is whole-request
wall time at the client (connect reused via keep-alive).

The record mirrors tools/bench_job.py: one JSON object with the
headline numbers plus the same folded ``run_report`` block
(obs.build_run_report over the shared in-process registry), so serve
benches land in the bench trajectory schema-compatible with job
benches.

    PYTHONPATH=.:$PYTHONPATH python tools/load_gen.py \
        [--store arrays:levels/] [--workers 8] [--duration 10] \
        [--out BENCH_serve.json]

Without --store it generates its own small synthetic artifact through
the real batch pipeline first (requires a jax backend; serving itself
is numpy-only).

``--adaptive`` switches to the brownout bench (BENCH_adaptive.json):
the same closed-loop clients run an overload ramp against a small
admission bound, once with the degradation ladder off and once on,
recording availability and the exact/synopsis/shed fidelity split per
stage (docs/robustness.md, serve/degrade.py).

``--cold-vs-warm`` switches to the tilefs restart A/B (docs/tilefs.md,
heatmap_tpu.tilefs): first-touch sweep latency on a fresh server with
no warm tiers vs a fresh server restarting over a filled disk cache
with a prewarm replay of the hot head, plus the fleet Pss probe
(tools/mem_probe.py) of N mmap'd backends vs N heap backends. Merges
``cold_warm`` / ``fleet_rss`` blocks into BENCH_serve.json next to the
closed-loop record.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

import numpy as np


def synth_store(tmpdir: str, n_points: int, *, sink: str = "arrays",
                config=None) -> str:
    """Run the real batch job on synthetic points into arrays egress.

    The adaptive (brownout) bench passes ``sink="arrays-synopsis"`` and
    a synopsis-bearing config so rung 1 has something to stamp."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from heatmap_tpu.io import open_sink, open_source
    from heatmap_tpu.pipeline import BatchJobConfig, run_job

    path = os.path.join(tmpdir, "levels")
    config = config or BatchJobConfig(detail_zoom=12, min_detail_zoom=5)
    with open_sink(f"{sink}:{path}") as out:
        run_job(open_source(f"synthetic:{n_points}"), out, config)
    return f"arrays:{path}"


def tile_universe(store, max_tiles: int, seed: int = 0) -> list:
    """(layer, z, x, y, fmt) population: every blob-bearing coarse tile
    of the default layer (fallback: first layer), both formats."""
    from heatmap_tpu.tilemath.morton import morton_decode_np

    name = "default" if store.layer("default") else store.layer_names()[0]
    layer = store.layer(name)
    delta = layer.result_delta
    tiles = []
    for d in layer.detail_zooms:
        z = d - delta
        if z < 0:
            continue
        coarse = np.unique(layer.levels[d].codes >> np.int64(2 * delta))
        rows, cols = morton_decode_np(coarse)
        tiles += [(name, z, int(c), int(r), fmt)
                  for r, c in zip(rows, cols)
                  for fmt in ("json", "png")]
    random.Random(seed).shuffle(tiles)
    return tiles[:max_tiles]


class Worker(threading.Thread):
    """One closed-loop client: Zipf-ish sampling over the universe,
    keep-alive connection, per-request wall latency."""

    def __init__(self, host, port, universe, stop_at, seed):
        super().__init__(daemon=True)
        self.host, self.port = host, port
        self.universe = universe
        self.stop_at = stop_at
        self.rng = random.Random(seed)
        self.latencies_ms: list = []
        self.statuses: dict = {}
        self.errors = 0
        # Fidelity accounting for the adaptive (brownout) bench: how
        # many answers were synopsis-stamped, the worst stamped error,
        # and the typed causes behind any 503s.
        self.synopsis = 0
        self.max_err = 0.0
        self.causes: dict = {}

    def _pick(self):
        # 80% of traffic on the first 20% of the (shuffled) universe —
        # the hot-set skew a map viewport produces.
        n = len(self.universe)
        if self.rng.random() < 0.8:
            return self.universe[self.rng.randrange(max(1, n // 5))]
        return self.universe[self.rng.randrange(n)]

    def run(self):
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        while time.monotonic() < self.stop_at:
            layer, z, x, y, fmt = self._pick()
            t0 = time.monotonic()
            try:
                conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
                resp = conn.getresponse()
                body = resp.read()
                status = resp.status
                marker = resp.getheader("X-Heatmap-Synopsis")
            except Exception:
                self.errors += 1
                conn.close()
                conn = http.client.HTTPConnection(self.host, self.port,
                                                  timeout=30)
                continue
            self.latencies_ms.append((time.monotonic() - t0) * 1e3)
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if marker is not None:
                self.synopsis += 1
                try:
                    err = float(marker.split("max_err=")[1].split(";")[0])
                except (IndexError, ValueError):
                    pass
                else:
                    self.max_err = max(self.max_err, err)
            if status == 503:
                try:
                    cause = json.loads(body).get("cause")
                except (ValueError, AttributeError):
                    cause = None
                if cause:
                    self.causes[cause] = self.causes.get(cause, 0) + 1
        conn.close()


def _drive(args) -> int:
    """``--drive URL`` mode: act as a pure load client against an
    already-running server (the fleet bench spawns several of these as
    subprocesses so the *client* is not capped by one GIL). Prints one
    JSON result line on stdout."""
    parsed = urllib.parse.urlsplit(args.drive)
    with open(args.universe_file) as f:
        universe = [tuple(t) for t in json.load(f)]
    stop_at = time.monotonic() + args.duration
    workers = [Worker(parsed.hostname, parsed.port, universe, stop_at,
                      seed=args.seed_base + i)
               for i in range(args.workers)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    statuses: dict = {}
    for w in workers:
        for s, c in w.statuses.items():
            statuses[str(s)] = statuses.get(str(s), 0) + c
    print(json.dumps({
        "latencies_ms": [round(v, 3) for w in workers
                         for v in w.latencies_ms],
        "statuses": statuses,
        "errors": int(sum(w.errors for w in workers)),
    }), flush=True)
    return 0


def _drive_clients(base_url: str, universe, duration: float, *,
                   workers: int, procs: int, tmpdir: str):
    """Fan the Zipf client out over ``procs`` subprocesses; returns
    (sorted latencies ms, statuses, errors)."""
    universe_file = os.path.join(tmpdir, "universe.json")
    with open(universe_file, "w") as f:
        json.dump([list(t) for t in universe], f)
    children = []
    for i in range(procs):
        children.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--drive", base_url, "--universe-file", universe_file,
             "--duration", str(duration), "--workers", str(workers),
             "--seed-base", str(1000 * i)],
            stdout=subprocess.PIPE, text=True))
    latencies: list = []
    statuses: dict = {}
    errors = 0
    for child in children:
        out, _ = child.communicate(timeout=duration + 120)
        result = json.loads(out.strip().splitlines()[-1])
        latencies += result["latencies_ms"]
        errors += result["errors"]
        for s, c in result["statuses"].items():
            statuses[s] = statuses.get(s, 0) + c
    return np.sort(np.asarray(latencies)), statuses, errors


def _lat_summary(lat) -> dict:
    def pct(p):
        return round(float(lat[min(len(lat) - 1, int(p * len(lat)))]), 3) \
            if len(lat) else None

    return {"p50": pct(0.50), "p90": pct(0.90), "p99": pct(0.99),
            "max": round(float(lat[-1]), 3) if len(lat) else None}


def _warm(base_url: str, universe):
    parsed = urllib.parse.urlsplit(base_url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=30)
    for layer, z, x, y, fmt in universe:
        conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
        conn.getresponse().read()
    conn.close()


def _sweep_latencies(host, port, universe):
    """Sequential sweep with per-request wall ms (keep-alive); sorted.
    Unlike the closed-loop Worker this touches every tile exactly once,
    so a fresh server's sweep IS its first-touch (cold) distribution."""
    conn = http.client.HTTPConnection(host, port, timeout=30)
    out = []
    for layer, z, x, y, fmt in universe:
        t0 = time.perf_counter()
        conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
        conn.getresponse().read()
        out.append((time.perf_counter() - t0) * 1e3)
    conn.close()
    return np.sort(np.asarray(out))


def _write_request_log(path: str, universe, hot):
    """Synthesize the ``http_request`` event log the prewarm planner
    replays: one pass over the whole universe, then repeated passes
    over the hot head, so the planner's recency-decayed scores rank the
    head first — the same 80/20 shape the closed-loop Worker drives,
    but deterministic instead of sampled."""
    from heatmap_tpu import obs

    with obs.EventLog(path) as log:
        for pass_set in (universe, hot, hot, hot):
            for layer, z, x, y, fmt in pass_set:
                log.emit("http_request", route="tiles",
                         path=f"/tiles/{layer}/{z}/{x}/{y}.{fmt}",
                         status=200, ms=1.0)


def _cold_warm_bench(args, tmpdir: str) -> dict:
    """``--cold-vs-warm``: the tilefs serving-tier A/B
    (heatmap_tpu.tilefs, docs/tilefs.md) for BENCH_serve.json. Three
    servers over the same mmap'd store:

    - cold: fresh process state, no disk tier, no prewarm — every
      request renders from the pyramid (the post-deploy worst case);
    - prep: sweeps the universe once through a disk cache to fill it,
      then is thrown away (a restart, as far as the tiers can tell);
    - warmed: fresh process state again, same disk cache root, prewarm
      replay of the hot head into the heap cache before the sweep.

    Both measured legs are sequential first-touch sweeps over the SAME
    universe, so warmed-vs-cold isolates exactly what the disk tier +
    prewarm buy across a restart. Also embeds the fleet Pss probe
    (tools/mem_probe.py): N mapped backends vs N heap backends over
    the same store dir — sub-linear fleet memory is the mmap story's
    other half.
    """
    from heatmap_tpu.serve import (ServeApp, TileCache, TileStore,
                                   serve_in_thread)
    from heatmap_tpu.tilefs import DiskTileCache, PrewarmConfig

    # Two views of one artifact: the arrays-tilefs sink writes npz
    # levels AND tilefs mirrors into the same dir, so the heap and
    # mapped legs differ only in how they read it. A caller-supplied
    # --store must be an arrays:DIR whose dir carries tilefs mirrors
    # (tools/tilefs_convert.py adds them in place).
    heap_spec = args.store
    store_dir = heap_spec.split(":", 1)[1]
    mapped_spec = f"tilefs:{store_dir}"

    universe = tile_universe(TileStore(mapped_spec), args.tiles)
    if not universe:
        raise SystemExit("store has no blob-bearing tiles")
    hot = universe[:max(1, len(universe) // 5)]

    def leg(disk_cache=None, prewarm=None):
        app = ServeApp(TileStore(mapped_spec),
                       TileCache(max_bytes=args.cache_bytes),
                       disk_cache=disk_cache, prewarm=prewarm)
        server, _base = serve_in_thread(app)
        host, port = server.server_address[:2]
        summary = app.prewarm_now(source="startup") if prewarm else None
        lat = _sweep_latencies(host, port, universe)
        server.shutdown()
        server.server_close()
        return lat, summary, app

    cold_lat, _, _ = leg()

    disk_root = os.path.join(tmpdir, "diskcache")
    events = os.path.join(tmpdir, "prewarm-events.jsonl")
    _write_request_log(events, universe, hot)
    _prep_lat, _, prep_app = leg(disk_cache=DiskTileCache(disk_root))
    disk_stats = prep_app.disk_cache.stats()

    cfg = PrewarmConfig(events=(events,), top_k=len(hot),
                        budget_s=60.0, budget_bytes=256 << 20)
    warm_lat, warm_summary, _ = leg(disk_cache=DiskTileCache(disk_root),
                                    prewarm=cfg)

    cold, warmed = _lat_summary(cold_lat), _lat_summary(warm_lat)
    speedup = (round(cold["p99"] / warmed["p99"], 2)
               if cold["p99"] and warmed["p99"] else None)
    print(json.dumps({"cold_p99_ms": cold["p99"],
                      "warmed_p99_ms": warmed["p99"],
                      "speedup_p99": speedup}), flush=True)

    import mem_probe  # sibling script; tools/ is sys.path[0] here

    paths = [f"/tiles/{layer}/{z}/{x}/{y}.{fmt}"
             for layer, z, x, y, fmt in universe]
    mapped = mem_probe.measure_fleet_pss(mapped_spec, args.rss_backends,
                                         paths)
    heap = mem_probe.measure_fleet_pss(heap_spec, args.rss_backends,
                                       paths)
    ratio = (round(mapped["total_mb"] / heap["total_mb"], 4)
             if mapped["total_mb"] and heap["total_mb"] else None)
    print(json.dumps({"fleet_rss_ratio": ratio,
                      "mapped_mb": mapped["total_mb"],
                      "heap_mb": heap["total_mb"]}), flush=True)

    return {
        "cold_warm": {
            "store": mapped_spec,
            "tiles": len(universe),
            "hot_tiles": len(hot),
            "cold": {"latency_ms": cold},
            "warmed": {"latency_ms": warmed,
                       "prewarm": warm_summary,
                       "disk_cache": disk_stats},
            "speedup_p99": speedup,
        },
        "fleet_rss": {
            "n": args.rss_backends,
            "mapped": mapped,
            "heap": heap,
            "pss_ratio": ratio,
            "source": mapped["source"],
        },
    }


def _recorder_overhead(host, port, universe, passes: int = 3) -> dict:
    """A/B the flight recorder against the live server: sequential
    full-universe sweeps (keep-alive, warmed cache) with the recorder
    off, then with shadow tracing (sample=0.0) + the ring installed —
    the always-on worst case where EVERY request runs real Span objects
    into the ring but none is head-sampled. Min-of-passes wall time on
    each side; the delta is the recorder's per-request tax. The ring's
    promise is that this stays in the low single digits — the bench
    gate alarms if it grows (obs:recorder_overhead_pct)."""
    from heatmap_tpu.obs import recorder as recorder_mod
    from heatmap_tpu.obs import tracing
    from heatmap_tpu.obs.recorder import FlightRecorder

    def sweep() -> float:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        t0 = time.perf_counter()
        for layer, z, x, y, fmt in universe:
            conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
            conn.getresponse().read()
        dt = time.perf_counter() - t0
        conn.close()
        return dt

    sweep()  # settle after the threaded window
    off_s = min(sweep() for _ in range(passes))
    tracing.enable_tracing(sample=0.0)
    recorder_mod.install(FlightRecorder())
    try:
        sweep()
        on_s = min(sweep() for _ in range(passes))
        stats = recorder_mod.get_recorder().stats()
    finally:
        recorder_mod.install(None)
        tracing.disable_tracing()
    pct = max(0.0, (on_s - off_s) / off_s * 100.0) if off_s else None
    result = {
        "recorder_overhead_pct": round(pct, 2) if pct is not None else None,
        "off_s": round(off_s, 4), "on_s": round(on_s, 4),
        "requests_per_pass": len(universe), "passes": passes,
        "ring_spans": stats["spans"], "ring_dropped": stats["dropped"],
    }
    print(json.dumps({"stage": "recorder_overhead", **result}), flush=True)
    return result


def _telemetry_overhead(host, port, universe, passes: int = 3) -> dict:
    """A/B the telemetry sampler against the live server: sequential
    full-universe sweeps with the sampler off, then armed at a 0.25 s
    interval (40x the production default cadence) with an anomaly
    engine attached — so registry snapshots, ring folds, and detector
    scoring all run while the sweep drives the hot path. The sampler is
    a background thread with zero hot-path hooks, so the promise is
    stronger than the recorder's: the delta should be measurement noise
    (bench_gate folds it as obs:telemetry_overhead_pct with the same
    5% noise floor). Also times ``/series`` queries against the
    freshly sampled history — the dashboard's polling cost."""
    from heatmap_tpu.obs import anomaly as anomaly_mod
    from heatmap_tpu.obs import timeseries
    from heatmap_tpu.obs.anomaly import AnomalyEngine, parse_watch_spec

    def sweep() -> float:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        t0 = time.perf_counter()
        for layer, z, x, y, fmt in universe:
            conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
            conn.getresponse().read()
        dt = time.perf_counter() - t0
        conn.close()
        return dt

    sweep()
    off_s = min(sweep() for _ in range(passes))
    engine = AnomalyEngine([parse_watch_spec("ingest_lag_seconds:z=8")])
    anomaly_mod.set_engine(engine)
    timeseries.arm(0.25, engine=engine)
    try:
        sweep()
        on_s = min(sweep() for _ in range(passes))
        stats = timeseries.get_store().stats()
        conn = http.client.HTTPConnection(host, port, timeout=30)
        q_lat = []
        for _ in range(50):
            t0 = time.perf_counter()
            conn.request("GET", "/series?name=http_requests_total")
            conn.getresponse().read()
            q_lat.append((time.perf_counter() - t0) * 1000.0)
        conn.close()
    finally:
        timeseries.shutdown()
        anomaly_mod.set_engine(None)
    q_lat.sort()
    pct = max(0.0, (on_s - off_s) / off_s * 100.0) if off_s else None
    result = {
        "telemetry_overhead_pct": round(pct, 2) if pct is not None else None,
        "telemetry_off_s": round(off_s, 4),
        "telemetry_on_s": round(on_s, 4),
        "sample_interval_s": 0.25,
        "store_series": stats["series"],
        "store_samples": stats["samples_total"],
        "series_query_ms": {
            "p50": round(q_lat[len(q_lat) // 2], 3),
            "p99": round(q_lat[int(0.99 * (len(q_lat) - 1))], 3),
            "n": len(q_lat),
        },
    }
    print(json.dumps({"stage": "telemetry_overhead", **result}), flush=True)
    return result


def _fleet_bench(args, spec: str, universe, tmpdir: str) -> dict:
    """The N=1/2/4 scaling curve + kill-one-backend availability, all
    through real child serve processes and a threaded router frontend.

    Honest-measurement note: on a single-core host the backends (and
    the client subprocesses) serialize on the same CPU, so the curve
    records whatever this host can actually show — ``host_cores`` is
    in the record so the gate's trend comparisons stay like-for-like.
    """
    from heatmap_tpu.serve import serve_in_thread
    from heatmap_tpu.serve.fleet import FleetSupervisor

    sizes = [int(n) for n in args.fleet.split(",") if n.strip()]
    curve = []
    for n in sizes:
        with FleetSupervisor(spec, n, cache_bytes=args.cache_bytes,
                             probe_interval_s=0.25) as sup:
            sup.start()
            server, base = serve_in_thread(sup.router)
            _warm(base, universe)
            t0 = time.perf_counter()
            lat, statuses, errors = _drive_clients(
                base, universe, args.fleet_duration,
                workers=args.workers, procs=args.drive_procs,
                tmpdir=tmpdir)
            measured_s = time.perf_counter() - t0
            server.shutdown()
            server.server_close()
        row = {"n": n, "requests": int(len(lat)), "errors": errors,
               "statuses": statuses,
               "rps": round(len(lat) / measured_s, 1) if measured_s else None,
               "latency_ms": _lat_summary(lat)}
        curve.append(row)
        print(json.dumps({"fleet_n": n, "rps": row["rps"],
                          "p99_ms": row["latency_ms"]["p99"]}), flush=True)

    # Kill-one availability at the largest N: SIGKILL a backend a third
    # of the way through the window; router failover + supervisor
    # restart should keep 5xx at zero.
    n = max(sizes)
    with FleetSupervisor(spec, n, cache_bytes=args.cache_bytes,
                         probe_interval_s=0.25) as sup:
        sup.start()
        server, base = serve_in_thread(sup.router)
        _warm(base, universe)
        victim = sorted(sup.router.backends)[0]
        killer = threading.Timer(args.fleet_duration / 3,
                                 sup.kill_backend, args=(victim,))
        killer.start()
        lat, statuses, errors = _drive_clients(
            base, universe, args.fleet_duration,
            workers=args.workers, procs=args.drive_procs, tmpdir=tmpdir)
        killer.cancel()
        server.shutdown()
        server.server_close()
    total = int(len(lat)) + errors
    fives = sum(c for s, c in statuses.items() if s.startswith("5"))
    kill_one = {
        "n": n, "victim": victim, "requests": int(len(lat)),
        "errors": errors, "statuses": statuses, "status_5xx": int(fives),
        "availability": round((total - fives - errors) / total, 6)
        if total else None,
        "latency_ms": _lat_summary(lat),
    }
    print(json.dumps({"fleet_kill_one": kill_one["availability"],
                      "status_5xx": fives}), flush=True)
    return {
        "host_cores": os.cpu_count(),
        "workers_per_client": args.workers,
        "client_procs": args.drive_procs,
        "duration_s": args.fleet_duration,
        "curve": curve,
        "kill_one": kill_one,
        "note": "backends are real child processes; on hosts with few "
                "cores the curve is serialized on the CPU and "
                "understates multi-core scaling",
    }


def _adaptive_bench(args, spec: str) -> dict:
    """``--adaptive``: the brownout availability/fidelity curves for
    BENCH_adaptive.json. One overload ramp (worker counts step up into
    saturation against a small admission bound, then back down) run
    twice over the same store: controller off, then controller on.

    The controller's burn source is a per-stage scripted schedule —
    the same fixed-burn discipline as the chaos soak's adaptive phase —
    so the ladder walks the ramp deterministically instead of
    depending on this host's latency noise; the *measured* side
    (latencies, statuses, synopsis stamps, shed causes) is real closed
    -loop traffic. Per stage the record carries availability (served /
    issued), the exact/synopsis/shed fractions, the worst stamped
    synopsis error, and the rung the ladder sat on."""
    from heatmap_tpu.serve import (ServeApp, TileCache, TileStore,
                                   serve_in_thread)
    from heatmap_tpu.serve import degrade

    # (workers, scripted burn): ramp into overload, hold, recover.
    stages = [(2, 0.2), (8, 1.5), (16, 2.5), (16, 3.5),
              (8, 0.2), (2, 0.2)]
    stage_s = args.adaptive_stage_s
    legs: dict = {}
    for leg in ("off", "on"):
        store = TileStore(spec)
        universe = tile_universe(store, args.tiles)
        burn_now = [0.0]
        ctl = None
        if leg == "on":
            # dwell = hold = half a stage: at most two ladder steps per
            # stage, so the ramp reads as a staircase in rung_trace
            # rather than slamming to max_rung in the first hot stage.
            ctl = degrade.BrownoutController(
                dwell_s=stage_s / 2, hold_s=stage_s / 2,
                poll_interval_s=0.05,
                burn_source=lambda: {"overload": burn_now[0]})
        app = ServeApp(store, TileCache(max_bytes=args.cache_bytes),
                       max_inflight=args.adaptive_inflight, degrade=ctl)
        server, base = serve_in_thread(app)
        host, port = server.server_address[:2]
        _warm(base, universe)
        rows = []
        for n_workers, burn in stages:
            burn_now[0] = burn
            stop_at = time.monotonic() + stage_s
            workers = [Worker(host, port, universe, stop_at, seed=i)
                       for i in range(n_workers)]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
            lat = np.sort(np.concatenate(
                [np.asarray(w.latencies_ms) for w in workers]
                or [np.zeros(0)]))
            statuses: dict = {}
            causes: dict = {}
            for w in workers:
                for s, c in w.statuses.items():
                    statuses[str(s)] = statuses.get(str(s), 0) + c
                for k, c in w.causes.items():
                    causes[k] = causes.get(k, 0) + c
            total = int(sum(statuses.values()))
            served = sum(c for s, c in statuses.items()
                         if s.startswith(("2", "304")))
            synopsis = int(sum(w.synopsis for w in workers))
            shed = statuses.get("503", 0)
            row = {
                "workers": n_workers, "burn": burn, "requests": total,
                "statuses": statuses,
                "errors": int(sum(w.errors for w in workers)),
                "availability": round(served / total, 4) if total else None,
                "frac_exact": round(max(0, served - synopsis) / total, 4)
                if total else None,
                "frac_synopsis": round(synopsis / total, 4)
                if total else None,
                "frac_shed": round(shed / total, 4) if total else None,
                "shed_causes": causes,
                "max_stamped_err": round(
                    max((w.max_err for w in workers), default=0.0), 6),
                "latency_ms": _lat_summary(lat),
                **({"rung": ctl.rung} if ctl is not None else {}),
            }
            rows.append(row)
            print(json.dumps({"adaptive": leg, **{k: row[k] for k in (
                "workers", "burn", "availability", "frac_synopsis",
                "frac_shed")}, **({"rung": ctl.rung}
                                  if ctl is not None else {})}),
                flush=True)
        server.shutdown()
        server.server_close()
        # Headline per leg: the overload stages (burn >= 1) are where
        # brownout control earns its keep; light stages always serve.
        hot = [r for (_, b), r in zip(stages, rows) if b >= 1.0]
        issued = sum(r["requests"] for r in hot)
        ok = sum(round(r["availability"] * r["requests"])
                 for r in hot if r["availability"] is not None)
        legs[leg] = {
            "stages": rows,
            "overload_availability": round(ok / issued, 4) if issued else None,
            "overload_p99_ms": max(
                (r["latency_ms"]["p99"] for r in hot
                 if r["latency_ms"]["p99"] is not None), default=None),
            "max_stamped_err": max(r["max_stamped_err"] for r in rows),
            **({"rung_trace": [r["rung"] for r in rows]}
               if leg == "on" else {}),
        }
    return {
        "bench": "adaptive",
        "store": spec,
        "stage_s": stage_s,
        "max_inflight": args.adaptive_inflight,
        "host_cores": os.cpu_count(),
        "stages": [{"workers": w, "burn": b} for w, b in stages],
        "legs": legs,
        "note": "burn is a scripted per-stage schedule (deterministic "
                "ladder), traffic and latencies are real closed-loop "
                "load; availability = served / issued over the "
                "overload stages",
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", default=None,
                    help="serve store spec (default: generate a "
                    "synthetic arrays artifact first)")
    ap.add_argument("--n-points", type=int, default=200_000,
                    help="synthetic points when generating the store")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0,
                    help="measured seconds (after warmup)")
    ap.add_argument("--tiles", type=int, default=512,
                    help="tile universe size (layer/z/x/y/fmt combos)")
    ap.add_argument("--cache-bytes", type=int, default=256 << 20)
    ap.add_argument("--ttl", type=float, default=None)
    ap.add_argument("--out", default="BENCH_serve.json")
    ap.add_argument("--fleet", default=None, metavar="N1,N2,...",
                    help="also bench the serve fleet at these backend "
                    "counts (e.g. 1,2,4) plus a kill-one-backend "
                    "availability run at the largest N")
    ap.add_argument("--fleet-duration", type=float, default=6.0,
                    help="measured seconds per fleet cell")
    ap.add_argument("--drive-procs", type=int, default=2,
                    help="client subprocesses per fleet cell (keeps the "
                    "load generator off a single GIL)")
    ap.add_argument("--adaptive", action="store_true",
                    help="run the brownout bench instead of the serve "
                    "bench: overload ramp with the degradation ladder "
                    "off vs on, availability + fidelity per stage "
                    "(docs/robustness.md)")
    ap.add_argument("--adaptive-out", default="BENCH_adaptive.json")
    ap.add_argument("--adaptive-stage-s", type=float, default=3.0,
                    help="seconds per ramp stage")
    ap.add_argument("--adaptive-inflight", type=int, default=4,
                    help="server admission bound for the ramp (small on "
                    "purpose: the hot stages must actually overload)")
    ap.add_argument("--cold-vs-warm", action="store_true",
                    help="run the tilefs cold-vs-warmed restart A/B + "
                    "fleet Pss probe instead of the closed-loop bench; "
                    "merges cold_warm / fleet_rss blocks into --out "
                    "without clobbering a prior serve record "
                    "(docs/tilefs.md)")
    ap.add_argument("--rss-backends", type=int, default=3,
                    help="backends per fleet Pss leg (--cold-vs-warm)")
    # --drive mode internals (subprocess client; not for direct use).
    ap.add_argument("--drive", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--universe-file", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--seed-base", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.drive:
        return _drive(args)

    if args.cold_vs_warm:
        import shutil

        from heatmap_tpu import obs

        obs.enable_metrics(True)
        cw_tmp = tempfile.mkdtemp(prefix="loadgen-cw-")
        try:
            if args.store is None:
                t0 = time.perf_counter()
                args.store = synth_store(cw_tmp, args.n_points,
                                         sink="arrays-tilefs")
                print(json.dumps({
                    "stage": "synth_store", "spec": args.store,
                    "s": round(time.perf_counter() - t0, 2)}), flush=True)
            blocks = _cold_warm_bench(args, cw_tmp)
        finally:
            shutil.rmtree(cw_tmp, ignore_errors=True)
        # Merge, don't overwrite: the standard serve record (rps/p99/
        # fleet curve) and this A/B share BENCH_serve.json, and the
        # bench gate folds series from both.
        doc: dict = {}
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    loaded = json.load(f)
            except (OSError, ValueError):
                loaded = None
            if isinstance(loaded, dict):
                doc = loaded
        doc.setdefault("bench", "serve")
        doc.update(blocks)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, default=str)
            f.write("\n")
        print(json.dumps({"wrote": args.out}), flush=True)
        return 0

    from heatmap_tpu import obs
    from heatmap_tpu.serve import ServeApp, TileCache, TileStore, serve_in_thread
    from heatmap_tpu.utils.trace import get_tracer

    obs.enable_metrics(True)
    tmpdir = None
    spec = args.store
    if spec is None:
        tmpdir = tempfile.mkdtemp(prefix="loadgen-")
        t0 = time.perf_counter()
        if args.adaptive:
            from heatmap_tpu.pipeline import BatchJobConfig

            # Synopsis-carrying store (same shape as the chaos soak's
            # adaptive phase): sources 7/8/9 synopsized, detail exact.
            spec = synth_store(
                tmpdir, args.n_points, sink="arrays-synopsis",
                config=BatchJobConfig(detail_zoom=10, min_detail_zoom=6,
                                      result_delta=2))
        else:
            spec = synth_store(tmpdir, args.n_points)
        print(json.dumps({"stage": "synth_store", "spec": spec,
                          "s": round(time.perf_counter() - t0, 2)}),
              flush=True)

    if args.adaptive:
        try:
            record = _adaptive_bench(args, spec)
        finally:
            if tmpdir:
                import shutil

                shutil.rmtree(tmpdir, ignore_errors=True)
        with open(args.adaptive_out, "w") as f:
            json.dump(record, f, indent=2, default=str)
            f.write("\n")
        on, off = record["legs"]["on"], record["legs"]["off"]
        print(json.dumps({
            "availability_on": on["overload_availability"],
            "availability_off": off["overload_availability"],
            "p99_ms_on": on["overload_p99_ms"],
            "p99_ms_off": off["overload_p99_ms"],
            "rung_trace": on["rung_trace"],
        }), flush=True)
        print(json.dumps({"wrote": args.adaptive_out}), flush=True)
        return 0

    store = TileStore(spec)
    cache = TileCache(max_bytes=args.cache_bytes, ttl_s=args.ttl)
    app = ServeApp(store, cache)
    server, base = serve_in_thread(app)
    host, port = server.server_address[:2]
    universe = tile_universe(store, args.tiles)
    if not universe:
        print(json.dumps({"error": "store has no blob-bearing tiles",
                          "store": spec}), flush=True)
        return 1

    # Warmup: touch the whole universe once (cold renders fill the
    # cache), then snapshot the counters so the measured window's
    # hit-rate excludes the mandatory first-touch misses.
    conn = http.client.HTTPConnection(host, port, timeout=30)
    t0 = time.perf_counter()
    for layer, z, x, y, fmt in universe:
        conn.request("GET", f"/tiles/{layer}/{z}/{x}/{y}.{fmt}")
        conn.getresponse().read()
    conn.close()
    warm_s = time.perf_counter() - t0

    from heatmap_tpu.serve.cache import CACHE_HITS, CACHE_MISSES

    hits0, misses0 = CACHE_HITS.value(), CACHE_MISSES.value()
    stop_at = time.monotonic() + args.duration
    workers = [Worker(host, port, universe, stop_at, seed=i)
               for i in range(args.workers)]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    measured_s = time.perf_counter() - t0
    obs_overhead = _recorder_overhead(host, port, universe)
    obs_overhead.update(_telemetry_overhead(host, port, universe))
    server.shutdown()

    lat = np.sort(np.concatenate(
        [np.asarray(w.latencies_ms) for w in workers]
        or [np.zeros(0)]))
    statuses: dict = {}
    for w in workers:
        for s, c in w.statuses.items():
            statuses[str(s)] = statuses.get(str(s), 0) + c
    hits = CACHE_HITS.value() - hits0
    misses = CACHE_MISSES.value() - misses0
    total = hits + misses

    fleet = None
    if args.fleet:
        with tempfile.TemporaryDirectory(prefix="loadgen-fleet-") as scratch:
            fleet = _fleet_bench(args, spec, universe, scratch)

    def pct(p):
        return round(float(lat[min(len(lat) - 1, int(p * len(lat)))]), 3) \
            if len(lat) else None

    record = {
        "bench": "serve",
        "store": spec,
        "workers": args.workers,
        "tiles": len(universe),
        "warmup_s": round(warm_s, 2),
        "duration_s": round(measured_s, 2),
        "requests": int(len(lat)),
        "errors": int(sum(w.errors for w in workers)),
        "statuses": statuses,
        "rps": round(len(lat) / measured_s, 1) if measured_s else None,
        "latency_ms": {"p50": pct(0.50), "p90": pct(0.90),
                       "p99": pct(0.99),
                       "max": round(float(lat[-1]), 3) if len(lat) else None},
        "hit_rate": round(hits / total, 4) if total else None,
        "cache": {"entries": len(cache), "bytes": cache.nbytes},
        "obs": obs_overhead,
        **({"fleet": fleet} if fleet else {}),
        # Same folded block bench_job.py embeds: serve benches stay
        # schema-compatible with job benches in the bench trajectory.
        "run_report": obs.build_run_report(tracer=get_tracer(),
                                           registry=obs.get_registry()),
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
        f.write("\n")
    headline = {k: record[k] for k in
                ("rps", "latency_ms", "hit_rate", "requests", "errors")}
    print(json.dumps(headline, default=str), flush=True)
    print(json.dumps({"wrote": args.out}), flush=True)
    if tmpdir:
        import shutil

        shutil.rmtree(tmpdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
