#!/usr/bin/env python
"""On-chip bit-exactness check for the sort-partitioned binning kernel.

The tests in tests/test_partitioned.py run the kernel in interpret mode
(CPU); Mosaic lowering on the real chip differs (layouts, bf16 matmul
accumulation order), so after any kernel change this script must pass on
the TPU before the change counts as verified. Compares the partitioned
raster bit-for-bit against the XLA scatter contract at the headline
window for clustered, adversarial-uniform, and boundary-straddling
inputs, across the swept tunable space.

    PYTHONPATH=. python tools/verify_partitioned_onchip.py
"""

from __future__ import annotations

import json
import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    # On CPU the kernel silently runs in interpret mode — the exact
    # path the interpret-mode tests already cover. Verifying Mosaic
    # lowering requires the real chip; anything else must fail loudly.
    platform = jax.devices()[0].platform
    if platform == "cpu":
        print(json.dumps({"error": "refusing to verify on CPU "
                          "(interpret mode is not Mosaic)",
                          "device": platform}))
        return 2

    from heatmap_tpu.ops import window_from_bounds
    from heatmap_tpu.ops.histogram import bin_rowcol_window
    from heatmap_tpu.ops.partitioned import bin_rowcol_window_partitioned
    from heatmap_tpu.tilemath import mercator

    win = window_from_bounds((44.0, 51.0), (-127.0, -117.0), zoom=15,
                             align_levels=12, pad_multiple=256)
    rng = np.random.default_rng(0)
    n = 1 << 22

    def project(lat, lon):
        r, c, v = mercator.project_points(jnp.asarray(lat), jnp.asarray(lon),
                                          win.zoom, dtype=jnp.float32)
        return r, c, v

    cases = {}
    # Clustered: hot core + sparse fringe (the good-chunk fast path).
    lat = np.concatenate([47.6 + rng.normal(0, 0.02, n // 2),
                          47.6 + rng.normal(0, 0.8, n // 2)]).astype(np.float32)
    lon = np.concatenate([-122.3 + rng.normal(0, 0.03, n // 2),
                          -122.3 + rng.normal(0, 1.2, n // 2)]).astype(np.float32)
    cases["clustered"] = (lat, lon)
    # Adversarial uniform over the whole window: every chunk straddles
    # many blocks -> exercises the lax.cond full-scatter fallback.
    cases["uniform"] = (
        rng.uniform(44.0, 51.0, n).astype(np.float32),
        rng.uniform(-127.0, -117.0, n).astype(np.float32),
    )
    # Out-of-window + single-cell pileup (tail & overflow paths).
    lat = np.full(n, 47.6, np.float32)
    lon = np.full(n, -122.3, np.float32)
    lat[: n // 8] = rng.uniform(-60.0, 85.0, n // 8)
    lon[: n // 8] = rng.uniform(-180.0, 179.9, n // 8)
    cases["pileup"] = (lat, lon)

    combos = [
        {},  # defaults
        {"block_cells": 1 << 12},
        {"block_cells": 1 << 14},
        {"chunk": 512},
        {"chunk": 2048},
        {"bad_frac": 32},
        {"streams": 8},
        {"streams": 32},
        {"streams": 8, "block_cells": 1 << 14},
    ]
    failures = 0
    for name, (lat, lon) in cases.items():
        r, c, v = project(lat, lon)
        expected = np.asarray(bin_rowcol_window(r, c, win, valid=v))
        for kw in combos:
            got = np.asarray(bin_rowcol_window_partitioned(
                r, c, win, valid=v, interpret=False, **kw))
            ok = bool((got == expected).all())
            print(json.dumps({"case": name, "kw": kw, "bit_exact": ok,
                              "total": int(expected.sum())}), flush=True)
            if not ok:
                failures += 1
                bad = np.argwhere(got != expected)
                print(f"  first diffs at {bad[:5].tolist()}", flush=True)
    print(json.dumps({
        "device": jax.devices()[0].platform,
        "failures": failures,
        "verdict": "BIT-EXACT" if failures == 0 else "MISMATCH",
    }), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
